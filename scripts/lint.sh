#!/bin/sh
# graftlint + graftsync + graftaudit wrapper: static analysis over the package.
#
#   scripts/lint.sh                 # AST lint + sync lint + compiled audit
#   scripts/lint.sh path/to/file.py # lint specific paths (audit still runs)
#   scripts/lint.sh --format json   # machine-readable findings (all tools)
#
# Exit codes: 0 clean (modulo baselines), nonzero otherwise.
# Stage 1 (graftlint) is pure-AST source analysis; stage 2 (graftsync)
# checks the concurrency contracts — thread-ownership annotations,
# guarded-by lock discipline, blocking calls under locks, lock-order
# cycles; stage 3 (graftaudit) AOT-lowers the real train/serve/decode
# programs of the sample config on CPU and audits the jaxpr/HLO —
# donation gaps, collective census vs the committed budget, fp32 creep,
# captured constants, replicated params.
# Stage 4 (LINT_ALERTS) validates configs/alerts.yaml against the
# graftscope rule grammar + exported-metric catalogue, when present.
# LINT_SYNC=0 skips stage 2; LINT_AUDIT=0 skips stage 3; LINT_ALERTS=0
# skips stage 4.
set -eu
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m mlx_cuda_distributed_pretraining_tpu.analysis.lint "$@"
if [ "${LINT_SYNC:-1}" != "0" ]; then
    JAX_PLATFORMS=cpu python -m mlx_cuda_distributed_pretraining_tpu.analysis.sync "$@"
fi
# Audit flags don't pass through (lint takes paths, audit takes --config);
# run `python -m mlx_cuda_distributed_pretraining_tpu.analysis.audit` for those.
if [ "${LINT_AUDIT:-1}" != "0" ]; then
    JAX_PLATFORMS=cpu python -m mlx_cuda_distributed_pretraining_tpu.analysis.audit \
        --config configs/model-config-sample.yaml
fi
if [ "${LINT_ALERTS:-1}" != "0" ] && [ -f configs/alerts.yaml ]; then
    JAX_PLATFORMS=cpu python -m mlx_cuda_distributed_pretraining_tpu.obs.alerts \
        --validate configs/alerts.yaml
fi
