#!/bin/sh
# graftlint wrapper: JAX-aware static analysis over the package.
#
#   scripts/lint.sh                 # lint the package against the baseline
#   scripts/lint.sh path/to/file.py # lint specific paths
#   scripts/lint.sh --format json   # machine-readable findings
#
# Exit codes: 0 clean (modulo baseline), 1 new findings, 2 bad paths.
# The linter is pure-AST (never imports the code under analysis), but it
# runs from the package, so pin JAX to CPU in case an import chain wakes
# a backend.
set -eu
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu exec python -m mlx_cuda_distributed_pretraining_tpu.analysis.lint "$@"
