"""Merge per-optimizer compare_optimizers output dirs into one artifact.

The comparison can run one optimizer per invocation (resumable under
flaky schedulers); this stitches the per-run ``optimizer_comparison.json``
/ ``.csv`` files back into the combined artifact layout that a single
multi-optimizer invocation would have produced, and re-renders the PNG.

Usage: python scripts/merge_optcmp_outputs.py OUT_DIR IN_DIR [IN_DIR...]
Each IN_DIR is an --out-dir from a single-optimizer run (its lr_finder_*
subdirs are copied through).
"""

from __future__ import annotations

import csv
import json
import os
import shutil
import sys


def main(out_dir: str, in_dirs: list) -> None:
    os.makedirs(out_dir, exist_ok=True)
    summary = {}
    curves = {}
    for d in in_dirs:
        with open(os.path.join(d, "optimizer_comparison.json")) as f:
            summary.update(json.load(f))
        with open(os.path.join(d, "optimizer_comparison.csv")) as f:
            rows = list(csv.reader(f))
        names = rows[0][1:]
        for j, n in enumerate(names):
            curves[n] = [(int(r[0]), float(r[j + 1])) for r in rows[1:]
                         if r[j + 1] not in ("", "None")]
        for sub in os.listdir(d):
            if sub.startswith("lr_finder_"):
                src = os.path.join(d, sub)
                dst = os.path.join(out_dir, sub)
                # In-place merge (out_dir listed among in_dirs) must not
                # rmtree the source it is about to copy; realpath so a
                # symlinked alias of the same directory is caught too.
                if os.path.realpath(src) == os.path.realpath(dst):
                    continue
                shutil.rmtree(dst, ignore_errors=True)
                shutil.copytree(src, dst)

    with open(os.path.join(out_dir, "optimizer_comparison.json"), "w") as f:
        json.dump(summary, f, indent=2)

    names = list(curves)
    all_steps = sorted({s for c in curves.values() for s, _ in c})
    by = {n: dict(curves[n]) for n in names}
    with open(os.path.join(out_dir, "optimizer_comparison.csv"), "w",
              newline="") as f:
        w = csv.writer(f)
        w.writerow(["step"] + names)
        for s in all_steps:
            w.writerow([s] + [by[n].get(s) for n in names])

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return
    fig, ax = plt.subplots(figsize=(8, 5))
    for n in names:
        steps = [s for s, _ in curves[n]]
        losses = [l for _, l in curves[n]]
        lr = summary.get(n, {}).get("learning_rate")
        label = f"{n} (lr={lr:.1e})" if lr else n
        ax.plot(steps, losses, label=label, linewidth=1.2)
    ax.set_xlabel("step")
    ax.set_ylabel("train loss")
    ax.set_title("Optimizer comparison — per-optimizer tuned LRs")
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(os.path.join(out_dir, "optimizer_comparison.png"), dpi=120)
    plt.close(fig)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2:])
