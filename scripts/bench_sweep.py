"""Full-step knob sweep for a bench case: run `bench.py --one CASE` under
combinations of the bench env knobs and report each as a matrix row.

The kernel-level sweep (scripts/bench_attention.py) picked the flash
defaults; this sweeps knobs in the context of the FULL train step at a
real scale — where the MFU actually lives (VERDICT r3 item 2):

  FLASH_BLOCK_Q / FLASH_BLOCK_KV   flash kernel tiling
  BENCH_CE_CHUNK                   fused-CE rows per chunk
  BENCH_SCAN_LAYERS                lax.scan stack vs unrolled layers
  BENCH_REMAT                      remat policy (none/dots/full/save_attn)
  BENCH_XLA_FLAGS                  named XLA flag set (parallel/xla_flags.py)

``--mfu`` runs the MFU-campaign matrix instead of the per-case combo
list: the remat-policy x scan x flag-set cross product (axes trimmable
via --remat/--scan/--flags), and folds the graftprof overlap/idle
fractions into the summary table so the flag-set effect on exposed
collectives is visible next to tok/s.

Each combo runs in its own subprocess (a hung remote compile can only be
SIGKILLed) and prints a ``BENCHCASE`` line whose case id carries the combo
(e.g. ``400m_flash@SCAN=0``), so scripts/merge_bench_outputs.py folds
sweep points into the same artifact as the main matrix. Ordered
best-guess-first: a window that fits only two combos still answers the
biggest questions. Exit code 0 = every combo produced a row.

    python scripts/bench_sweep.py --case 400m_flash [--steps 10]
        [--timeout 600] [--combo FLASH_BLOCK_Q=512,FLASH_BLOCK_KV=1024]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CASE_MARK = "BENCHCASE "

# Short labels keep the merged case ids readable.
_SHORT = {
    "FLASH_BLOCK_Q": "BQ",
    "FLASH_BLOCK_KV": "BKV",
    "BENCH_CE_CHUNK": "CE",
    "BENCH_SCAN_LAYERS": "SCAN",
    "BENCH_REMAT": "REMAT",
    "BENCH_MEGASTEP": "MEGA",
    "BENCH_XLA_FLAGS": "XLA",
}

# --mfu axes (MFU-campaign sweep). Defaults cover every named remat
# policy (models/llama.py), both layer-stack forms, and both flag sets;
# each axis can be trimmed on the command line.
MFU_REMAT = ["none", "dots", "save_attn", "full"]
MFU_SCAN = ["0", "1"]
MFU_FLAGS = ["none", "latency_hiding"]


def mfu_combos(remat_axis, scan_axis, flags_axis):
    return [
        {"BENCH_REMAT": r, "BENCH_SCAN_LAYERS": s, "BENCH_XLA_FLAGS": f}
        for f in flags_axis for s in scan_axis for r in remat_axis
    ]

# Megastep-first: BENCH_MEGASTEP compiles K steps into one dispatch, so
# the first combo separates tunnel dispatch overhead from chip compute —
# THE open MFU question — and later combos measure their knob on top of
# megastep so tunnel noise can't mask a small kernel-level win.
# The bare megastep points (2m_mega/100m_mega/400m_mega) are first-class
# bench cases; the sweeps here measure the TUNING knobs on top of them.
DEFAULT_COMBOS = {
    "400m_flash": [
        {"BENCH_MEGASTEP": "10", "BENCH_SCAN_LAYERS": "0"},
        {"BENCH_MEGASTEP": "10", "FLASH_BLOCK_Q": "512", "FLASH_BLOCK_KV": "1024"},
        {"BENCH_MEGASTEP": "10", "FLASH_BLOCK_Q": "512", "FLASH_BLOCK_KV": "512"},
        {"BENCH_MEGASTEP": "10", "BENCH_CE_CHUNK": "4096"},
        {"BENCH_MEGASTEP": "10", "BENCH_CE_CHUNK": "1024"},
        {"BENCH_MEGASTEP": "10", "FLASH_BLOCK_Q": "1024", "FLASH_BLOCK_KV": "1024"},
    ],
    "100m_flash": [
        {"BENCH_MEGASTEP": "10", "BENCH_SCAN_LAYERS": "1"},
        {"BENCH_MEGASTEP": "10", "FLASH_BLOCK_Q": "512", "FLASH_BLOCK_KV": "1024"},
        {"BENCH_MEGASTEP": "10", "BENCH_CE_CHUNK": "4096"},
        {"BENCH_MEGASTEP": "10", "BENCH_REMAT": "dots"},
    ],
}


def parse_combo(text):
    combo = {}
    for part in text.split(","):
        k, _, v = part.partition("=")
        combo[k.strip()] = v.strip()
    return combo


def combo_label(combo):
    return ",".join(f"{_SHORT.get(k, k)}={v}" for k, v in sorted(combo.items()))


_child = None


def _on_term(signum, frame):  # noqa: ARG001
    """The harvester's outer `timeout` SIGTERMs only this process; without
    this handler the in-flight bench.py child would be orphaned still
    holding the TPU tunnel (hung remote compiles block in C and need
    SIGKILL), starving every later job in the session."""
    if _child is not None and _child.poll() is None:
        _child.kill()
    sys.exit(143)


def main():
    global _child
    signal.signal(signal.SIGTERM, _on_term)
    ap = argparse.ArgumentParser()
    ap.add_argument("--case", required=True)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--timeout", type=int, default=600)
    ap.add_argument("--combo", action="append", default=[],
                    help="K=V[,K=V...] (repeatable; default: built-in list)")
    ap.add_argument("--mfu", action="store_true",
                    help="sweep the MFU-campaign matrix: remat policy x "
                         "scan x xla flag set")
    ap.add_argument("--remat", default=",".join(MFU_REMAT),
                    help="--mfu remat axis (comma list)")
    ap.add_argument("--scan", default=",".join(MFU_SCAN),
                    help="--mfu scan axis (comma list of 0/1)")
    ap.add_argument("--flags", default=",".join(MFU_FLAGS),
                    help="--mfu flag-set axis (comma list)")
    ap.add_argument("--skip-done", default=None,
                    help="out-file from a previous attempt: combos whose "
                         "case id already has a row there are not re-run, "
                         "so a retried sweep resumes instead of restarting")
    a = ap.parse_args()

    if a.mfu:
        combos = ([parse_combo(c) for c in a.combo]
                  or mfu_combos(a.remat.split(","), a.scan.split(","),
                                a.flags.split(",")))
    else:
        combos = ([parse_combo(c) for c in a.combo]
                  or DEFAULT_COMBOS.get(a.case))
    if not combos:
        sys.exit(f"no default combos for case {a.case!r}; pass --combo")

    already = set()
    if a.skip_done and os.path.exists(a.skip_done):
        with open(a.skip_done) as f:
            for ln in f:
                if ln.startswith(CASE_MARK):
                    try:
                        already.add(json.loads(ln[len(CASE_MARK):])["case"])
                    except (json.JSONDecodeError, KeyError):
                        pass

    failures = 0
    rows = []
    for combo in combos:
        label = combo_label(combo)
        if f"{a.case}@{label}" in already:
            print(f"[sweep] {label}: already captured, skipping",
                  file=sys.stderr)
            continue
        # combo values win over --steps so BENCH_STEPS can itself be swept.
        env = {**os.environ, "BENCH_STEPS": str(a.steps), **combo}
        t0 = time.perf_counter()
        _child = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py"), "--one", a.case],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            out, err = _child.communicate(timeout=a.timeout)
            rc = _child.returncode
        except subprocess.TimeoutExpired:
            _child.kill()
            _child.communicate()
            print(f"[sweep] {label}: TIMEOUT after {a.timeout}s", file=sys.stderr)
            failures += 1
            continue
        finally:
            _child = None
        line = next((ln for ln in out.splitlines()
                     if ln.startswith(CASE_MARK)), None)
        if line is None:
            print(f"[sweep] {label}: no result (rc={rc}) "
                  f"{err[-200:]}", file=sys.stderr)
            failures += 1
            continue
        try:
            row = json.loads(line[len(CASE_MARK):])
        except json.JSONDecodeError:
            print(f"[sweep] {label}: truncated result line", file=sys.stderr)
            failures += 1
            continue
        row["case"] = f"{a.case}@{label}"
        row["sweep_combo"] = combo
        rows.append(row)
        print(CASE_MARK + json.dumps(row), flush=True)
        print(f"[sweep] {label}: tok_s={row.get('tok_s')} mfu={row.get('mfu')}"
              f" ({time.perf_counter() - t0:.0f}s)", file=sys.stderr)
    if rows:
        print_table(rows)
    sys.exit(1 if failures else 0)


def print_table(rows):
    """Aligned sweep summary on stderr. The graftprof fraction columns
    (prof_* from bench.py's in-run profile) appear whenever any row has
    them — overlap_frac next to tok/s is how a flag set proves it moved
    collectives off the critical path, not just the step time."""
    cols = ["case", "tok_s", "mfu"]
    for c in ("prof_compute_frac", "prof_comm_frac", "prof_overlap_frac",
              "prof_idle_frac"):
        if any(c in r for r in rows):
            cols.append(c)
    head = [c.replace("prof_", "") for c in cols]
    table = [head] + [
        ["" if r.get(c) is None else str(r.get(c, "")) for c in cols]
        for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    for row in table:
        print("[sweep] " + "  ".join(v.ljust(w) for v, w in zip(row, widths)),
              file=sys.stderr)


if __name__ == "__main__":
    main()
