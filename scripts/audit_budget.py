#!/usr/bin/env python
"""Regenerate the committed graftaudit collective budgets.

    python scripts/audit_budget.py                 # both default configs
    python scripts/audit_budget.py configs/x.yaml  # just one
    python scripts/audit_budget.py --allow-shrink  # accept comm wins

Lowered on CPU (8 virtual devices) — no accelerator needed. For each
config the script prints the delta against the committed budget
(analysis/budgets/<config>.json) and rewrites it. A SHRINK — the fresh
census below the committed one — is refused without ``--allow-shrink``:
a smaller budget is either a real comm win (great: rerun with the flag
so the audit gate rides at the new floor) or a sign this machine lowered
a different program than CI does (wrong device count, stale tree), and
silently committing the latter would let a later regression hide inside
the stale headroom.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_CONFIGS = (
    "configs/model-config-sample.yaml",
    "configs/model-config-moe-8x40m.yaml",
)


def diff_budget(old, new):
    """(lines, grew, shrank) — human delta between two budget docs."""
    lines, grew, shrank = [], False, False
    ops = (old or {}).get("programs", {}) if old else {}
    nps = new.get("programs", {})
    for prog in sorted(set(ops) | set(nps)):
        o = (ops.get(prog) or {}).get("collectives", {})
        n = (nps.get(prog) or {}).get("collectives", {})
        for op in sorted(set(o) | set(n)):
            ov = o.get(op, {"count": 0, "bytes": 0})
            nv = n.get(op, {"count": 0, "bytes": 0})
            if ov == nv:
                continue
            if (nv["count"], nv["bytes"]) > (ov["count"], ov["bytes"]):
                grew = True
                tag = "GREW"
            else:
                shrank = True
                tag = "shrank"
            lines.append(
                f"  {prog}/{op}: {ov['count']} op(s) / {ov['bytes']} B "
                f"-> {nv['count']} op(s) / {nv['bytes']} B  [{tag}]")
        od = (ops.get(prog) or {}).get("donation")
        nd = (nps.get(prog) or {}).get("donation")
        if od != nd and nd is not None:
            lines.append(f"  {prog}/donation: {od} -> {nd}")
    return lines, grew, shrank


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("configs", nargs="*", default=None)
    ap.add_argument("--allow-shrink", action="store_true",
                    help="accept a budget smaller than the committed one")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--check", action="store_true",
                    help="compare only; exit 1 on ANY delta, write nothing")
    args = ap.parse_args(argv)
    configs = args.configs or [os.path.join(REPO, c) for c in DEFAULT_CONFIGS]

    from mlx_cuda_distributed_pretraining_tpu.analysis import audit

    audit.setup_env(args.devices)

    status = 0
    for config in configs:
        if not os.path.isfile(config):
            print(f"audit_budget: no such config: {config}", file=sys.stderr)
            return 2
        name = audit.config_stem(config)
        path = audit.default_budget_path(name)
        old = audit.load_budget(path)
        programs = audit.build_programs(config)
        doc = audit.build_budget_doc(name, args.devices, programs)
        lines, grew, shrank = diff_budget(old, doc)
        if old is None:
            print(f"{name}: no committed budget yet")
        elif not lines:
            print(f"{name}: budget unchanged")
            continue
        else:
            print(f"{name}: budget delta")
            print("\n".join(lines))
        if args.check:
            status = 1
            continue
        if shrank and not args.allow_shrink:
            print(f"{name}: refusing to shrink the committed budget — "
                  "verify the comm win is real, then rerun with "
                  "--allow-shrink", file=sys.stderr)
            status = 1
            continue
        audit.write_budget(path, doc)
        print(f"{name}: wrote {path}")
    return status


if __name__ == "__main__":
    sys.exit(main())
