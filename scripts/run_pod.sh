#!/usr/bin/env bash
# Multi-host TPU pod launcher: start the SAME SPMD program on every host of
# a pod slice. This replaces the reference's Modal/coordinator-worker cloud
# path (reference: train_a100.py, distributed/worker.py) with the TPU-native
# model — jax.distributed.initialize auto-detects pod topology on each host.
#
# On a Cloud TPU pod slice (run from your workstation):
#   scripts/run_pod.sh <tpu-name> <zone> <config.yaml>
# On each pod host directly (e.g. under a different scheduler), just run:
#   python -m mlx_cuda_distributed_pretraining_tpu.parallel.launch --config <config.yaml>
set -euo pipefail

TPU_NAME="${1:?usage: run_pod.sh <tpu-name> <zone> <config.yaml>}"
ZONE="${2:?usage: run_pod.sh <tpu-name> <zone> <config.yaml>}"
CONFIG="${3:?usage: run_pod.sh <tpu-name> <zone> <config.yaml>}"
# Where the repo lives on each pod host; override with REPO_DIR=... if the
# checkout is not at $HOME/<local-dir-name>.
REPO_DIR="${REPO_DIR:-$(basename "$(pwd)")}"

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd '$REPO_DIR' && python -m mlx_cuda_distributed_pretraining_tpu.parallel.launch --config '$CONFIG'"
