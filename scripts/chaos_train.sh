#!/usr/bin/env bash
# Launch a SUPERVISED training run in the background: the auto-resume
# supervisor (train/supervisor.py) restarts the trainer after any crash
# or preemption, resuming from the newest checkpoint that passes
# manifest verification. Mirrors scripts/run_train.sh (PID file + log),
# but the PID is the supervisor's — kill -TERM it for a clean,
# checkpointed shutdown of the whole tree.
#
# Multi-host mode (HOSTS=N): launches N supervisors on this machine, one
# per simulated host, that coordinate restarts through the shared run
# dir (parallel/elastic.py): per-generation file barrier, fleet restart
# markers, children rendezvousing over jax.distributed on a
# per-generation localhost coordinator port. Chaos injection
# (CHAOS_KILL_AFTER_S=K): after K seconds, SIGKILL one random host's
# TRAINER child (pid read from its heartbeat_p<idx>.json) — the fleet
# must barrier, resume from the newest verified checkpoint, and finish
# with goodput >= ~95% on the ledger. This is the manual form of
# tests/test_elastic_chaos.py.
#
# Usage: scripts/chaos_train.sh <config.yaml> [runs_root] [max_crashes]
# Env:   HOSTS=N              simulated hosts (default 1: single-host mode)
#        COORD_PORT=P         base coordinator port (default 12435)
#        CHAOS_KILL_AFTER_S=K SIGKILL a random host's trainer after K s
#        HOST_DEVICES=D       CPU devices per simulated host (default 2)
set -euo pipefail

CONFIG="${1:?usage: chaos_train.sh <config.yaml> [runs_root] [max_crashes]}"
RUNS_ROOT="${2:-runs}"
MAX_CRASHES="${3:-3}"
HOSTS="${HOSTS:-1}"
COORD_PORT="${COORD_PORT:-12435}"
HOST_DEVICES="${HOST_DEVICES:-2}"
NAME="$(python - "$CONFIG" <<'EOF'
import sys, yaml
print(yaml.safe_load(open(sys.argv[1]))["name"])
EOF
)"

mkdir -p "$RUNS_ROOT"
RUN_DIR="$RUNS_ROOT/$NAME"

if [ "$HOSTS" -le 1 ]; then
  LOG="$RUNS_ROOT/$NAME.supervisor.log"
  nohup python -m mlx_cuda_distributed_pretraining_tpu.train.trainer \
    --config "$CONFIG" --runs-root "$RUNS_ROOT" \
    --auto-resume --max-crashes "$MAX_CRASHES" >"$LOG" 2>&1 &
  PID=$!
  echo "$PID" > "$RUNS_ROOT/$NAME.supervisor.pid"
  echo "supervised training started: pid=$PID config=$CONFIG log=$LOG"
  echo "stop cleanly with: kill -TERM $PID   (forwards to the trainer, which checkpoints and exits)"
  echo "monitor with: python -m mlx_cuda_distributed_pretraining_tpu.obs.monitor $NAME --runs-root $RUNS_ROOT"
  exit 0
fi

# --- multi-host fleet -------------------------------------------------
PIDS=()
for ((i = 0; i < HOSTS; i++)); do
  LOG="$RUNS_ROOT/$NAME.supervisor_p$i.log"
  # Simulated hosts share one machine: force CPU devices so each
  # "host" owns HOST_DEVICES of the global mesh, as real pods would.
  nohup env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=$HOST_DEVICES" \
    python -m mlx_cuda_distributed_pretraining_tpu.train.trainer \
    --config "$CONFIG" --runs-root "$RUNS_ROOT" \
    --auto-resume --max-crashes "$MAX_CRASHES" \
    --coordinator "localhost:$COORD_PORT" \
    --num-processes "$HOSTS" --process-id "$i" >"$LOG" 2>&1 &
  PIDS[$i]=$!
  echo "${PIDS[$i]}" > "$RUNS_ROOT/$NAME.supervisor_p$i.pid"
  echo "host $i supervisor: pid=${PIDS[$i]} log=$LOG"
done

if [ -n "${CHAOS_KILL_AFTER_S:-}" ]; then
  VICTIM=$((RANDOM % HOSTS))
  (
    sleep "$CHAOS_KILL_AFTER_S"
    HB="$RUN_DIR/heartbeat_p$VICTIM.json"
    [ "$VICTIM" -eq 0 ] && HB="$RUN_DIR/heartbeat.json"
    TPID="$(python - "$HB" <<'EOF'
import json, sys
try:
    print(json.load(open(sys.argv[1])).get("pid") or "")
except OSError:
    print("")
EOF
)"
    if [ -n "$TPID" ]; then
      echo "chaos: SIGKILL host $VICTIM trainer pid=$TPID" >&2
      kill -KILL "$TPID" 2>/dev/null || true
    else
      echo "chaos: no heartbeat pid for host $VICTIM yet; skipping kill" >&2
    fi
  ) &
  echo "chaos: will SIGKILL host $VICTIM's trainer after ${CHAOS_KILL_AFTER_S}s"
fi

echo "fleet of $HOSTS supervisors launched (coordinator localhost:$COORD_PORT)"
echo "stop cleanly with: kill -TERM ${PIDS[*]}"

RC=0
for ((i = 0; i < HOSTS; i++)); do
  wait "${PIDS[$i]}" || RC=$?
done

echo "fleet done rc=$RC"
if [ -f "$RUN_DIR/events.jsonl" ]; then
  python - "$RUN_DIR" <<'EOF'
import json, os, sys
run = sys.argv[1]
lost = 0.0
for line in open(os.path.join(run, "events.jsonl")):
    try:
        ev = json.loads(line)
    except ValueError:
        continue
    if ev.get("type") == "restart":
        lost += float(ev.get("lost_s") or 0.0)
print(f"ledger: restart_lost_s={lost:.1f}")
EOF
fi
exit "$RC"
