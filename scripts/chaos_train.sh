#!/usr/bin/env bash
# Launch a SUPERVISED training run in the background: the auto-resume
# supervisor (train/supervisor.py) restarts the trainer after any crash
# or preemption, resuming from the newest checkpoint that passes
# manifest verification. Mirrors scripts/run_train.sh (PID file + log),
# but the PID is the supervisor's — kill -TERM it for a clean,
# checkpointed shutdown of the whole tree.
#
# Usage: scripts/chaos_train.sh <config.yaml> [runs_root] [max_crashes]
set -euo pipefail

CONFIG="${1:?usage: chaos_train.sh <config.yaml> [runs_root] [max_crashes]}"
RUNS_ROOT="${2:-runs}"
MAX_CRASHES="${3:-3}"
NAME="$(python - "$CONFIG" <<'EOF'
import sys, yaml
print(yaml.safe_load(open(sys.argv[1]))["name"])
EOF
)"

mkdir -p "$RUNS_ROOT"
LOG="$RUNS_ROOT/$NAME.supervisor.log"

nohup python -m mlx_cuda_distributed_pretraining_tpu.train.trainer \
  --config "$CONFIG" --runs-root "$RUNS_ROOT" \
  --auto-resume --max-crashes "$MAX_CRASHES" >"$LOG" 2>&1 &
PID=$!
echo "$PID" > "$RUNS_ROOT/$NAME.supervisor.pid"
echo "supervised training started: pid=$PID config=$CONFIG log=$LOG"
echo "stop cleanly with: kill -TERM $PID   (forwards to the trainer, which checkpoints and exits)"
echo "monitor with: python -m mlx_cuda_distributed_pretraining_tpu.obs.monitor $NAME --runs-root $RUNS_ROOT"
