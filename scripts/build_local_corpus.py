#!/usr/bin/env python
"""Harvest a real natural-language corpus from the local machine (no egress).

The bench/judging environment has zero network egress, so FineWeb-style hub
streaming can't supply real text. This builds an honest offline corpus of
English prose from what the image ships:

- documentation files (``*.rst``, ``*.md``, long ``*.txt``) under the
  Python environment and ``/usr/share/doc`` (incl. gzipped changelogs);
- docstrings extracted (via ``ast``) from installed Python packages and
  the standard library.

Output is a shuffled JSONL of ``{"text": ...}`` documents — the same shape
FineWeb prep produces — ready for tools/prepare_dataset.py (split +
tokenizer + config). This is real human-written prose with natural token
statistics, not ``rng.integers`` noise; the provenance is stated in the
produced ``<out-stem>.manifest.json``.

Usage:
    python scripts/build_local_corpus.py --out /tmp/corpus.jsonl \
        [--min-doc-chars 400] [--max-mb 200]
"""

from __future__ import annotations

import argparse
import ast
import glob
import gzip
import io
import json
import os
import random
import re
import sys

DOC_ROOTS = [
    "/opt/venv",
    "/usr/share/doc",
    "/usr/lib/python3.12",
]

_WS = re.compile(r"[ \t]+")
_MANY_NL = re.compile(r"\n{3,}")


def _clean(text: str) -> str:
    text = text.replace("\r\n", "\n").replace("\x00", "")
    text = _WS.sub(" ", text)
    text = _MANY_NL.sub("\n\n", text)
    return text.strip()


def _is_prose(text: str, min_chars: int) -> bool:
    if len(text) < min_chars:
        return False
    # mostly printable ASCII/latin, with a healthy share of letters+spaces
    letters = sum(c.isalpha() or c.isspace() for c in text)
    if letters / len(text) < 0.75:
        return False
    # require real sentences, not symbol tables
    return text.count(". ") + text.count(".\n") >= 3


def iter_doc_files(min_chars: int):
    seen = set()
    patterns = []
    for root in DOC_ROOTS:
        patterns += [
            os.path.join(root, "**", "*.rst"),
            os.path.join(root, "**", "*.md"),
            os.path.join(root, "**", "*.txt"),
            os.path.join(root, "**", "*.gz"),
        ]
    for pat in patterns:
        for path in glob.iglob(pat, recursive=True):
            real = os.path.realpath(path)
            if real in seen or not os.path.isfile(real):
                continue
            seen.add(real)
            try:
                if path.endswith(".gz"):
                    with gzip.open(real, "rt", errors="ignore") as f:
                        raw = f.read(4 << 20)
                else:
                    if os.path.getsize(real) < min_chars:
                        continue
                    with io.open(real, "r", errors="ignore") as f:
                        raw = f.read(4 << 20)
            except (OSError, EOFError):
                continue
            text = _clean(raw)
            if _is_prose(text, min_chars):
                yield text


def iter_docstrings(min_chars: int):
    """Module/class/function docstrings from installed Python source."""
    for root in ("/opt/venv/lib", "/usr/lib/python3.12"):
        for path in glob.iglob(os.path.join(root, "**", "*.py"), recursive=True):
            try:
                with io.open(path, "r", errors="ignore") as f:
                    src = f.read(2 << 20)
                tree = ast.parse(src)
            except (OSError, SyntaxError, ValueError):
                continue
            parts = []
            for node in ast.walk(tree):
                if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    ds = ast.get_docstring(node)
                    if ds and len(ds) > 120:
                        parts.append(ds)
            if not parts:
                continue
            text = _clean("\n\n".join(parts))
            if _is_prose(text, min_chars):
                yield text


def iter_source_files(min_chars: int, exts=(".py",)):
    """Whole source files as documents (real human-written text: code +
    comments + docstrings). Skips vendored/minified/test-fixture noise by
    requiring a minimum size and a sane line length profile."""
    seen = set()
    for root in ("/opt/venv/lib", "/usr/lib/python3.12"):
        for ext in exts:
            for path in glob.iglob(os.path.join(root, "**", f"*{ext}"),
                                   recursive=True):
                real = os.path.realpath(path)
                if real in seen or not os.path.isfile(real):
                    continue
                seen.add(real)
                try:
                    if os.path.getsize(real) < min_chars:
                        continue
                    with io.open(real, "r", errors="ignore") as f:
                        raw = f.read(1 << 20)
                except OSError:
                    continue
                text = raw.replace("\r\n", "\n").replace("\x00", "").strip()
                if len(text) < min_chars:
                    continue
                lines = text.splitlines()
                # minified/generated files have few, enormous lines
                if not lines or sum(len(l) for l in lines) / len(lines) > 200:
                    continue
                yield text


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument("--min-doc-chars", type=int, default=400)
    p.add_argument("--max-mb", type=float, default=200.0)
    p.add_argument("--code-mb", type=float, default=0.0,
                   help="additionally include up to this many MB of whole "
                        "source files (.py) as documents — real text with "
                        "different token statistics than the doc prose")
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args(argv)

    docs = []
    total = 0
    cap = int(a.max_mb * 1e6)
    # With --code-mb, whole .py files already carry their docstrings —
    # running the docstring extractor too would ship every long docstring
    # twice, so the prose side is then doc-files only.
    prose_iters = ((iter_doc_files(a.min_doc_chars),) if a.code_mb > 0 else
                   (iter_doc_files(a.min_doc_chars),
                    iter_docstrings(a.min_doc_chars)))
    for it in prose_iters:
        for text in it:
            docs.append(text)
            total += len(text)
            if total >= cap:
                break
        if total >= cap:
            break

    code_chars = 0
    if a.code_mb > 0:
        code_cap = int(a.code_mb * 1e6)
        for text in iter_source_files(a.min_doc_chars):
            docs.append(text)
            code_chars += len(text)
            if code_chars >= code_cap:
                break
        total += code_chars

    random.Random(a.seed).shuffle(docs)
    os.makedirs(os.path.dirname(os.path.abspath(a.out)) or ".", exist_ok=True)
    with open(a.out, "w") as f:
        for text in docs:
            f.write(json.dumps({"text": text}) + "\n")
    manifest = {
        "documents": len(docs),
        "chars": total,
        "mb": round(total / 1e6, 1),
        "code_mb": round(code_chars / 1e6, 1),
        "sources": "local documentation (*.rst/*.md/*.txt, /usr/share/doc "
                   "gzipped changelogs)"
                   + (" + whole .py source files (docstrings ride along "
                      "in-file)" if code_chars
                      else " + installed-package docstrings"),
        "note": "offline real-prose corpus; zero-egress environment",
    }
    with open(os.path.splitext(a.out)[0] + ".manifest.json", "w") as f:
        json.dump(manifest, f, indent=2)
    print(json.dumps(manifest))
    return 0


if __name__ == "__main__":
    sys.exit(main())
