"""Fold chip-harvester case outputs into one bench.py-format matrix JSON.

The harvester (scripts/chip_harvester.sh) runs each bench case atomically
(`bench.py --one CASE`) across however many tunnel windows the session
gets; each success leaves a ``BENCHCASE {json}`` line in its out-file.
This tool merges those lines — plus any partial matrices from full
``bench.py`` runs or previously-merged artifacts passed via --also — into
the document ``bench.py``'s ``build_doc`` defines (the same shape
``emit()`` prints), so the committed self-captured artifact and the
driver-captured BENCH_rNN.json are directly comparable. Breakdown-job
outputs (scripts/bench_breakdown.py JSON lines, which have no ``case``
key) are preserved under a ``breakdowns`` key so the MFU-attribution data
survives /tmp.

Usage:
    python scripts/merge_bench_outputs.py --chiprun /tmp/chiprun/out \
        --also /tmp/bench_r4_stdout.json --out BENCH_SELF_r4.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import build_doc, harvester_case_rows


def rows_from_one_files(out_dir):
    """Case rows from `bench.py --one` outputs (parse policy shared with
    bench.py's emit-time fold — bench.harvester_case_rows). ``device`` is
    hoisted to the doc level (matching run_case); a ``preempted`` flag is
    KEPT on the row — it marks a SIGTERM-truncated measurement, and the
    harvester retries those, so a surviving flag means no clean capture
    happened."""
    rows, device = harvester_case_rows(out_dir), None
    for r in rows.values():
        device = r.pop("device", None) or device
    return rows, device


def breakdowns_from_out_files(out_dir):
    """bench_breakdown.py outputs: plain JSON lines, no case key. Outputs
    are append-mode across retries; duplicate lines collapse via the
    'component' key when present."""
    found = {}
    for path in sorted(glob.glob(os.path.join(out_dir, "breakdown_*.out"))):
        name = os.path.basename(path)[: -len(".out")]
        by_key, extras = {}, []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                key = obj.get("component") or (
                    "summary:" + str(obj["scale"]) if "scale" in obj else None)
                if key is not None:
                    by_key[key] = obj  # later attempt wins
                else:
                    extras.append(obj)
        lines = list(by_key.values()) + extras
        if lines:
            found[name] = lines
    return found


def parse_doc(path):
    """A bench.py stdout capture (one JSON line, possibly surrounded by
    log noise) or a previously-merged pretty-printed artifact."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return {}
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chiprun", default="/tmp/chiprun/out")
    ap.add_argument("--also", nargs="*", default=[],
                    help="bench.py stdout JSONs / previous merged artifacts "
                         "(harvester rows win on conflict: captured later)")
    ap.add_argument("--out", required=True)
    a = ap.parse_args()

    rows, device, breakdowns, vocab = {}, None, {}, None

    def fold(case, r):
        # Clean-beats-preempted applies across EVERY source pair (--also
        # docs can be previously-merged artifacts that keep preempted
        # flags): a SIGTERM-truncated row never displaces a clean one.
        prev = rows.get(case)
        if (prev is not None and not prev.get("preempted")
                and r.get("preempted")):
            return
        rows[case] = r

    for path in a.also:
        if not os.path.exists(path):
            continue
        doc = parse_doc(path)
        for r in doc.get("matrix", []):
            if "case" in r and "skipped" not in r and "error" not in r:
                fold(r["case"], r)
        device = doc.get("device") or device
        breakdowns.update(doc.get("breakdowns", {}))
    if os.path.isdir(a.chiprun):
        more, dev = rows_from_one_files(a.chiprun)
        for case, r in more.items():
            fold(case, r)
        device = dev or device
        breakdowns.update(breakdowns_from_out_files(a.chiprun))

    matrix = sorted(rows.values(), key=lambda r: r["case"])
    vocab = next((r["vocab"] for r in matrix if r.get("vocab")), 32768)
    doc = build_doc(matrix, device, vocab,
                    "merged (scripts/chip_harvester.sh atomic cases across "
                    "tunnel windows)")
    if breakdowns:
        doc["breakdowns"] = breakdowns
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"{len(matrix)} cases, {len(breakdowns)} breakdowns -> {a.out}")


if __name__ == "__main__":
    main()
