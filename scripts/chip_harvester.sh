#!/bin/bash
# Chip-window harvester: convert an unreliable TPU tunnel into a complete
# benchmark matrix.
#
# The axon tunnel dies and recovers on its own timescale (observed r2-r4:
# windows as short as ~13 min between multi-hour outages, and a downed
# tunnel HANGS the client in a C call rather than erroring). A monolithic
# bench run loses everything past the first death, so this loop owns the
# chip for the whole session instead:
#
#   - probe before EVERY job (bench.py --probe under a hard timeout);
#   - each job is atomic + idempotent with a done-marker, so a window that
#     fits only one case still makes permanent progress;
#   - jobs run under `timeout -k` (SIGKILL backstop: a mid-job tunnel death
#     blocks in C where SIGTERM never fires);
#   - the long real-text training job is resumable: segments run under a
#     bounded timeout and continue from the latest interval checkpoint
#     (trainer resume.checkpoint=latest), so it needs no contiguous window;
#   - a job that fails MAX_FAIL times is quarantined (logged, skipped) so
#     one OOM/miscompiled case cannot eat every window.
#
# Results land in $BASE/out/*.out as BENCHCASE/JSON lines;
# scripts/merge_bench_outputs.py folds them into a bench.py-format matrix.
#
# Usage: scripts/chip_harvester.sh [job-list-file]   (default: built-in list)
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
BASE=${CHIPRUN_BASE:-/tmp/chiprun}
RUN=/tmp/realrun/runs/llama-40m-realtext-tpu
MAX_FAIL=${CHIPRUN_MAX_FAIL:-2}
mkdir -p "$BASE/out" "$BASE/done" "$BASE/fail"
LOG=$BASE/log
cd "$REPO"

# Cross-round hygiene: /tmp survives between rounds, and bench.py's
# emit-time fold reads $BASE/out — rows measured by a PREVIOUS round's
# code must never be published as this round's results. The driver
# appends the round number to PROGRESS.jsonl; when it moved on, archive
# the old round's out-files and reset per-round job state.
ROUND=$(grep -o '"round": *[0-9]*' "$REPO/PROGRESS.jsonl" 2>/dev/null \
        | tail -1 | grep -o '[0-9]*$')
if [ -n "$ROUND" ]; then
  PREV=$(cat "$BASE/round" 2>/dev/null)
  if [ -n "$PREV" ] && [ "$PREV" != "$ROUND" ]; then
    mkdir -p "$BASE/stale_r$PREV"
    mv "$BASE"/out/* "$BASE/stale_r$PREV/" 2>/dev/null
    rm -f "$BASE"/done/* "$BASE"/fail/*
    echo "$(date -u +"%F %T") archived round-$PREV out-files" >> "$LOG"
  fi
  echo "$ROUND" > "$BASE/round"
fi

# Priority order = VERDICT r3 asks: complete the scale matrix first, then
# the MFU attribution breakdowns, then the on-chip real-text training run,
# then decode/longctx/1b rows, then comparison variants.
#
# r5 ordering: chip-rate (mega) proof first — one_2m_mega is the single
# most valuable missing datum and fits a sub-10-minute window; the scanned
# one_400m_mega lands in the first 3 so a {400m,650m,1b} row arrives early.
# Trainer cases sit behind the cheap matrix rows (they cost a big compile).
JOBS=(
  "one_2m_mega 400"
  "one_100m_mega 500"
  "one_400m_mega 700"
  "one_40m_flash 420"
  "one_400m_flash 700"
  "one_1b_adafactor 1000"
  "breakdown_400m 1000"
  "one_650m_flash 800"
  "breakdown_100m 700"
  "one_decode_100m 450"
  "one_decode_100m_16k_int8 560"
  "one_decode_100m_16k_w8 600"
  "one_decode_100m_16k_w4 600"
  "one_trainer_spd8 700"
  "train40m 1600"
  "infbench40m 700"
  "infbench2m 600"
  "sweep_400m 4400"
  "sweep_100m 2200"
  "one_trainer 700"
  "one_400m_bs32 900"
  "one_1b_lion 1000"
  "one_40m_flash_s8k 500"
  "one_100m_muon 450"
  "one_100m_bs64_remat 450"
  "one_1b_flash 1000"
  "one_2m_simple 330"
  "one_40m_simple 400"
  "one_40m_flash_bs16 400"
)
[ $# -ge 1 ] && mapfile -t JOBS < "$1"

stamp() { date -u +"%F %T"; }

probe() { timeout -k 10 80 python bench.py --probe >/dev/null 2>&1; }

nfail() { if [ -f "$BASE/fail/$1" ]; then wc -l < "$BASE/fail/$1"; else echo 0; fi; }

run_one() { # [-strict] id timeout cmd...
  # Default success: a BENCHCASE result line that is NOT a SIGTERM-
  # truncated measurement (the Trainer consumes timeout's SIGTERM and
  # still prints a line with "preempted": true — partial data, retry in a
  # better window). With -strict (multi-row jobs like sweeps): rc==0 only,
  # so a partial run retries — its captured rows survive via append-mode.
  local strict=0
  [ "$1" = "-strict" ] && { strict=1; shift; }
  local id=$1 t=$2; shift 2
  echo "$(stamp) START $id (timeout ${t}s strict=$strict)" >> "$LOG"
  local rows_before
  # No `|| echo 0` here: grep -c prints "0" AND exits 1 on a zero-row file,
  # so `|| echo 0` would yield "0\n0" and break the -gt comparison below.
  rows_before=$(grep -c '^BENCHCASE ' "$BASE/out/$id.out" 2>/dev/null)
  rows_before=${rows_before:-0}
  # Append across retries: a partial first attempt (e.g. 5 of 6 breakdown
  # lines before a tunnel death) is captured data, not garbage.
  timeout -k 15 "$t" "$@" >> "$BASE/out/$id.out" 2>> "$BASE/out/$id.err"
  local rc=$?
  local ok=0
  if [ "$strict" = 1 ]; then
    [ $rc -eq 0 ] && ok=1
    # An incomplete attempt that still captured NEW rows is progress
    # (--skip-done resumes where it left off) — don't count it toward
    # quarantine, mirroring train40m's new-checkpoint rule.
    if [ "$ok" = 0 ]; then
      local rows_after
      rows_after=$(grep -c '^BENCHCASE ' "$BASE/out/$id.out" 2>/dev/null)
      rows_after=${rows_after:-0}
      if [ "$rows_after" -gt "$rows_before" ]; then
        echo "$(stamp) PROGRESS $id rc=$rc ($rows_before -> $rows_after rows)" >> "$LOG"
        return 1
      fi
    fi
  else
    local last
    last=$(grep '^BENCHCASE ' "$BASE/out/$id.out" 2>/dev/null | tail -1)
    if { [ -n "$last" ] && ! printf '%s' "$last" | grep -q '"preempted": true'; } \
        || { [ -z "$last" ] && [ $rc -eq 0 ]; }; then
      ok=1
    fi
  fi
  if [ "$ok" = 1 ]; then
    touch "$BASE/done/$id"; echo "$(stamp) DONE $id rc=$rc" >> "$LOG"; return 0
  fi
  # Only count a failure against the job if the tunnel is still up: a
  # mid-job tunnel death says nothing about the job, and quarantining it
  # for that would defeat the whole design.
  if probe; then
    echo x >> "$BASE/fail/$id"
    echo "$(stamp) FAIL $id rc=$rc $(tail -c 200 "$BASE/out/$id.err" | tr '\n' ' ')" >> "$LOG"
  else
    echo "$(stamp) TUNNEL-DEATH during $id rc=$rc (not counted)" >> "$LOG"
  fi
  return 1
}

model_final() { ls "$1"/checkpoints/step_final_model.safetensors >/dev/null 2>&1; }

train40m_done() { model_final "$RUN"; }

run_infbench() { # id timeout run_name prompts
  local id=$1 t=$2 run=$3 prompts=$4
  run_one "$id" "$t" python -m \
    mlx_cuda_distributed_pretraining_tpu.tools.benchmark_inference \
    --run "$run" --runs-root /tmp/realrun/runs \
    --prompts "$prompts" --n-prompts 4 \
    --max-tokens 128 --modes plain,spec,spec-t0.8
}

train40m() { # timeout
  local t=${1:-1600}
  if train40m_done; then touch "$BASE/done/train40m"; return 0; fi
  local cfg=/tmp/realrun/run40m.yaml
  ls "$RUN"/checkpoints/step_*_model.safetensors >/dev/null 2>&1 \
    && cfg=/tmp/realrun/run40m_resume.yaml
  local seg="$BASE/out/train40m.seg$(date +%s).out"
  local before
  before=$(ls "$RUN"/checkpoints/ 2>/dev/null | md5sum)
  echo "$(stamp) START train40m segment cfg=$cfg (timeout ${t}s)" >> "$LOG"
  timeout -k 15 "$t" python train.py --config "$cfg" \
    --runs-root /tmp/realrun/runs > "$seg" 2>&1
  local rc=$?
  if train40m_done; then
    touch "$BASE/done/train40m"; echo "$(stamp) DONE train40m rc=$rc" >> "$LOG"
  else
    # Progress = a NEW checkpoint landed this segment (resume banners and
    # old checkpoints don't count). A no-progress segment with the tunnel
    # still up counts toward quarantine; a tunnel death counts for nothing.
    if [ "$(ls "$RUN"/checkpoints/ 2>/dev/null | md5sum)" = "$before" ] && probe; then
      echo x >> "$BASE/fail/train40m"
      echo "$(stamp) FAIL train40m rc=$rc no new checkpoint, tunnel up" >> "$LOG"
    else
      echo "$(stamp) SEGMENT train40m rc=$rc ($(ls "$RUN"/checkpoints/ 2>/dev/null | tail -1))" >> "$LOG"
    fi
  fi
}

echo "$(stamp) harvester up, ${#JOBS[@]} jobs" >> "$LOG"
while :; do
  all_done=1
  for spec in "${JOBS[@]}"; do
    [ -z "${spec// /}" ] && continue  # blank job-list lines are not jobs
    id=${spec%% *}; t=${spec##* }
    [ -f "$BASE/done/$id" ] && continue
    [ "$(nfail "$id")" -ge "$MAX_FAIL" ] && continue
    all_done=0
    if ! probe; then
      echo "$(stamp) tunnel down (probe before $id)" >> "$LOG"
      sleep 40
      break  # rescan from the top next window: priority order preserved
    fi
    case $id in
      train40m) train40m "$t" ;;
      infbench40m)
        # On-chip decode/speculative benchmark over the REAL trained 40m
        # model (VERDICT r4 #7): only meaningful once train40m finished.
        if train40m_done; then
          run_infbench "$id" "$t" llama-40m-realtext-tpu \
            /tmp/realrun/data2/val.jsonl
        elif [ "$(nfail train40m)" -ge "$MAX_FAIL" ]; then
          # train40m quarantined -> this job can never become runnable;
          # quarantine it too so the loop keeps its termination guarantee.
          echo x >> "$BASE/fail/$id"
          echo "$(stamp) FAIL $id (train40m quarantined)" >> "$LOG"
        else
          echo "$(stamp) WAIT infbench40m (train40m not done)" >> "$LOG"
        fi ;;
      infbench2m)
        # Fallback speculative-decode target: a 2m real-text model trained
        # CPU-side this session — decouples the on-chip speculative row
        # from train40m getting a long-enough window.
        if model_final /tmp/realrun/runs/llama-2m-realtext-r5; then
          run_infbench "$id" "$t" llama-2m-realtext-r5 \
            /tmp/realrun/data/val.jsonl
        elif [ ! -f /tmp/realrun/run2m_r5.yaml ] || \
             [ -n "$(find /tmp/realrun/run2m_r5.yaml -mmin +300 2>/dev/null)" ]; then
          # The CPU training was staged when its config was written; if
          # the config never appeared, or 5h pass with no final model,
          # it is not coming (a process check would be a transient
          # snapshot — a crash-and-relaunch gap must not permanently
          # quarantine the job). NOTE: the find must not be the only
          # gate — `find missing-file` prints nothing, which previously
          # read as "young config" and made this WAIT forever when the
          # yaml was never written at all.
          echo x >> "$BASE/fail/$id"
          echo "$(stamp) FAIL $id (2m config/model absent past deadline)" >> "$LOG"
        else
          echo "$(stamp) WAIT infbench2m (2m training in progress)" >> "$LOG"
        fi ;;
      breakdown_*) run_one "$id" "$t" python scripts/bench_breakdown.py --scale "${id#breakdown_}" ;;
      sweep_*) run_one -strict "$id" "$t" python scripts/bench_sweep.py \
                 --case "${id#sweep_}_flash" --timeout 600 \
                 --skip-done "$BASE/out/$id.out" ;;
      one_*) run_one "$id" "$t" python bench.py --one "${id#one_}" ;;
      *) echo "$(stamp) UNKNOWN job $id" >> "$LOG"; echo x >> "$BASE/fail/$id" ;;
    esac
  done
  if [ "$all_done" -eq 1 ]; then echo "$(stamp) ALL DONE" >> "$LOG"; break; fi
  sleep "${CHIPRUN_SLEEP:-20}"
done
