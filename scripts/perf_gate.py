#!/usr/bin/env python3
"""perf_gate: compare the newest BENCH_*.json against bench_baseline.json.

The bench harness emits a stdout-contract doc per run (bench.py
``build_doc``: ``{metric, value, matrix: [rows]}``) that the driver
archives as ``BENCH_<tag>.json`` at the repo root. This gate reads the
newest such doc and compares every case/metric pinned in the committed
``bench_baseline.json`` with a noise tolerance:

- throughput metrics (``tok_s``, ``mfu``) compare RELATIVELY: a case
  regresses when ``now < base * (1 - tolerance)``;
- the graftprof fraction columns (``prof_*_frac``) compare ABSOLUTELY
  (relative deltas blow up near 0.0): regression when the delta in the
  bad direction exceeds ``tolerance`` outright.

Higher is better for tok_s / mfu / prof_compute_frac /
prof_overlap_frac; lower is better for prof_comm_frac / prof_idle_frac.

Exit codes: 0 clean (improvements print a refresh-baseline hint),
1 regression, 2 infrastructure (no bench doc / no baseline / nothing
comparable) — bench.py's ``_perf_gate`` treats 2 like the audit gate
treats a crash: logged, never gating. Rows whose values are null
(device-unreachable skip rows) are skipped, not failed.

The committed baseline is PER-BACKEND (schema v2): numbers measured on
a CPU host must never gate a TPU run and vice versa, so
``bench_baseline.json`` keys its pinned cases by the backend family
("cpu" / "tpu" / "gpu", derived from the bench doc's ``device`` stamp)
and the gate compares only the section matching the doc under test. A
doc whose backend has no committed section exits 2 (infrastructure, not
regression) with a ``--write-baseline`` hint. Legacy v1 baselines
(top-level ``cases``) are read as if their cases belonged to the
current doc's backend.

Serving decode rows (``decode_tok_s`` / ``prefill_tok_s`` — including
the weight-only int8/int4 ``decode_*_w8``/``_w4`` arms) are gateable
metrics alongside the training ones, so a quantized-serving perf
regression fails the gate like a train-step one.

``--write-baseline`` regenerates the CURRENT backend's section from the
newest doc's complete rows, preserving the other backends' sections and
the configured tolerance.

Stdlib only; run as ``python scripts/perf_gate.py`` from anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "bench_baseline.json")

# metric -> +1 (higher is better) / -1 (lower is better)
DIRECTIONS = {
    "tok_s": +1,
    "mfu": +1,
    "decode_tok_s": +1,
    "prefill_tok_s": +1,
    "prof_compute_frac": +1,
    "prof_overlap_frac": +1,
    "prof_comm_frac": -1,
    "prof_idle_frac": -1,
}
# Fractions gate on absolute deltas; everything else relatively.
ABSOLUTE = tuple(m for m in DIRECTIONS if m.endswith("_frac"))
BASELINE_METRICS = tuple(DIRECTIONS)


def find_newest_bench(root: str) -> Optional[str]:
    """Newest parseable BENCH_*.json carrying a matrix."""
    best: Tuple[float, Optional[str]] = (-1.0, None)
    for path in glob.glob(os.path.join(root, "BENCH_*.json")):
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc.get("matrix"), list):
                continue
            mt = os.path.getmtime(path)
        except (OSError, json.JSONDecodeError, ValueError):
            continue
        if mt > best[0]:
            best = (mt, path)
    return best[1]


def doc_backend(doc: Dict[str, Any]) -> str:
    """Backend family ("cpu" | "tpu" | "gpu") of a bench doc's device
    stamp, e.g. "TFRT_CPU_0" -> cpu, "TPU v5e" -> tpu."""
    device = str(doc.get("device") or "").lower()
    if "tpu" in device:
        return "tpu"
    if any(k in device for k in ("gpu", "cuda", "nvidia", "rocm")):
        return "gpu"
    return "cpu"


def _rows_by_case(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """First complete (any gateable metric numeric, not preempted) row
    per case — same clean-row preference as bench.py's headline pick.
    Decode/serve rows carry ``decode_tok_s`` instead of ``tok_s``, so
    completeness means ANY direction-pinned metric measured."""
    out: Dict[str, Dict[str, Any]] = {}
    for row in doc.get("matrix") or []:
        case = row.get("case")
        if not case or case in out:
            continue
        if not any(isinstance(row.get(m), (int, float)) for m in DIRECTIONS):
            continue
        if row.get("preempted"):
            continue
        out[str(case)] = row
    return out


def backend_section(baseline: Dict[str, Any], backend: str
                    ) -> Optional[Dict[str, Any]]:
    """The {source, cases} section gating ``backend``, or None.

    v2 looks it up under ``backends``; a v1 baseline (top-level
    ``cases``) is treated as the current backend's section."""
    backends = baseline.get("backends")
    if isinstance(backends, dict):
        sec = backends.get(backend)
        return sec if isinstance(sec, dict) else None
    if isinstance(baseline.get("cases"), dict):  # legacy v1
        return {"source": baseline.get("source"),
                "cases": baseline["cases"]}
    return None


def compare(doc: Dict[str, Any], baseline: Dict[str, Any],
            tolerance: Optional[float] = None
            ) -> Tuple[List[str], List[str], List[str]]:
    """(lines, regressions, improvements) over every pinned metric of
    the section matching the doc's backend."""
    tol = float(baseline.get("tolerance", 0.15)
                if tolerance is None else tolerance)
    rows = _rows_by_case(doc)
    section = backend_section(baseline, doc_backend(doc)) or {}
    lines: List[str] = []
    regressions: List[str] = []
    improvements: List[str] = []
    for case, pinned in sorted((section.get("cases") or {}).items()):
        row = rows.get(case)
        if row is None:
            lines.append(f"perf_gate: case={case} SKIP (no complete row "
                         f"in this bench doc)")
            continue
        for metric, base in sorted(pinned.items()):
            if metric not in DIRECTIONS \
                    or not isinstance(base, (int, float)):
                continue
            now = row.get(metric)
            if not isinstance(now, (int, float)):
                lines.append(f"perf_gate: case={case} metric={metric} "
                             f"SKIP (not measured this run)")
                continue
            sign = DIRECTIONS[metric]
            if metric in ABSOLUTE:
                delta = (now - base) * sign
                bad = delta < -tol
                good = delta > tol
                shown = f"delta={(now - base) * sign:+.4f} (abs)"
            else:
                if base == 0:
                    continue
                rel = (now - base) / abs(base) * sign
                bad = rel < -tol
                good = rel > tol
                shown = f"delta={rel * 100:+.1f}%"
            tag = "REGRESSION" if bad else ("IMPROVED" if good else "ok")
            line = (f"perf_gate: case={case} metric={metric} "
                    f"base={base} now={now} {shown} "
                    f"tolerance={tol} {tag}")
            lines.append(line)
            if bad:
                regressions.append(line)
            elif good:
                improvements.append(line)
    return lines, regressions, improvements


def write_baseline(doc: Dict[str, Any], path: str, tolerance: float,
                   source: str) -> int:
    """Pin every complete row's gateable metrics under the doc's
    backend, preserving other backends' committed sections; returns
    cases pinned."""
    cases: Dict[str, Dict[str, float]] = {}
    for case, row in sorted(_rows_by_case(doc).items()):
        pinned = {m: row[m] for m in BASELINE_METRICS
                  if isinstance(row.get(m), (int, float))}
        if pinned:
            cases[case] = pinned
    backends: Dict[str, Any] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            prev = json.load(f)
        if isinstance(prev.get("backends"), dict):
            backends = dict(prev["backends"])
        elif isinstance(prev.get("cases"), dict):  # migrate v1 in place
            backends = {doc_backend({"device": prev.get("device")}):
                        {"source": prev.get("source"),
                         "cases": prev["cases"]}}
    except (OSError, json.JSONDecodeError, ValueError):
        pass
    backends[doc_backend(doc)] = {"source": os.path.basename(source),
                                  "cases": cases}
    out = {"version": 2, "tool": "perf_gate", "tolerance": tolerance,
           "backends": backends}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return len(cases)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/perf_gate.py",
        description="gate the newest BENCH_*.json against "
                    "bench_baseline.json with a noise tolerance")
    ap.add_argument("--bench", default=None,
                    help="bench doc to check (default: newest "
                         "BENCH_*.json at the repo root)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override the baseline's committed tolerance")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the newest doc's "
                         "complete rows and exit 0")
    args = ap.parse_args(argv)

    bench_path = args.bench or find_newest_bench(REPO)
    if bench_path is None or not os.path.isfile(bench_path):
        print("perf_gate: no BENCH_*.json doc found — run bench.py first",
              file=sys.stderr)
        return 2
    try:
        with open(bench_path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perf_gate: unreadable bench doc {bench_path}: {e}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        try:
            with open(args.baseline, "r", encoding="utf-8") as f:
                prev_tol = float(json.load(f).get("tolerance", 0.15))
        except (OSError, json.JSONDecodeError, ValueError):
            prev_tol = 0.15
        tol = prev_tol if args.tolerance is None else args.tolerance
        n = write_baseline(doc, args.baseline, tol, bench_path)
        print(f"perf_gate: baseline refreshed from "
              f"{os.path.basename(bench_path)} ({n} cases) -> "
              f"{args.baseline}")
        return 0

    try:
        with open(args.baseline, "r", encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perf_gate: no baseline at {args.baseline} ({e}); "
              f"create one with --write-baseline", file=sys.stderr)
        return 2

    backend = doc_backend(doc)
    if backend_section(baseline, backend) is None:
        print(f"perf_gate: baseline has no section for backend "
              f"`{backend}` — create one with --write-baseline",
              file=sys.stderr)
        return 2

    lines, regressions, improvements = compare(doc, baseline,
                                               args.tolerance)
    print(f"perf_gate: doc={os.path.basename(bench_path)} "
          f"backend={backend} "
          f"baseline={os.path.basename(args.baseline)}")
    for line in lines:
        print(line)
    if regressions:
        print(f"perf_gate: {len(regressions)} regression(s) beyond "
              f"tolerance — investigate before merging "
              f"(BENCH_PERF=0 skips the bench-side gate)")
        return 1
    if improvements:
        print(f"perf_gate: {len(improvements)} metric(s) improved beyond "
              f"tolerance — refresh the baseline to lock the gain in: "
              f"python scripts/perf_gate.py --write-baseline")
    if not any("ok" in l or "REGRESSION" in l or "IMPROVED" in l
               for l in lines):
        print("perf_gate: nothing comparable (all rows skipped)")
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
