#!/usr/bin/env python
"""Merge chrome-trace dumps into per-request span trees and reports.

Input: one or more chrome trace-event JSON files as produced by the
tracing ring buffers (obs/trace.py) — the router's ``GET /trace``, each
replica's ``GET /trace``, and the trainer's ``trace_step<N>.json`` /
``trace.json`` exports. Spans from different processes share a
wall-clock timeline and are joined by the ``trace_id`` each span
carries in its args (minted by the router, propagated via the
``X-Trace-Id`` header), so a single request's ``route`` span on the
router nests the ``queue_wait`` / ``prefill_chunk`` / ``decode`` spans
recorded on whichever replica served it:

    python scripts/trace_report.py router_trace.json \
        replica0_trace.json replica1_trace.json --top 3

Prints, in ``key=value`` form:
  * an accounting line — how many requests completed, how many
    ``route`` spans never matched a replica-side ``request`` span
    (anything non-zero there means a replica dropped its ring or died),
    and how many requests were disaggregated handoffs (a prefill
    replica's and a decode replica's ``request`` spans joined under one
    trace id, with the ``kv_transfer`` push between them);
  * per-component TTFT breakdown percentiles (queue_wait, prefill,
    decode, route overhead) across all completed requests;
  * the top-k slowest requests, each with its indented span tree;
  * trainer step-time attribution — per-phase totals from the goodput
    ledger's span mirrors (data_wait / h2d_wait / dispatch / ckpt_save
    / eval / compile) next to the MFU the ``step_window`` instants
    reported — when a trainer trace file is among the inputs;
  * with ``--run-dir <run>``: the run's own trace exports join the
    inputs automatically, and when the run holds a jax.profiler dump
    (``<run>/profile/``) the graftprof op-level attribution
    (obs/profile_report.py: compute/comm/host/idle fractions, overlap,
    top-k ops) is appended — ledger-, span-, and op-level views of the
    same step window from one command.

Stdlib-only: runs on dumped JSON anywhere, no repo install needed (the
graftprof fold imports the in-repo package via a repo-root fallback and
degrades to a note if unavailable).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

# Trainer phase span names (obs/trace.py complete() mirrors of the
# goodput ledger components, minus the "_s" suffix).
TRAIN_PHASES = ("compile", "data_wait", "h2d_wait", "dispatch",
                "ckpt_save", "eval")
# Request-path component span names emitted by serve/engine.py +
# serve/router.py (+ the prefill->decode KV push from infer/server.py
# in a disaggregated fleet).
REQUEST_COMPONENTS = ("queue_wait", "prefill_chunk", "decode",
                      "kv_transfer")
# Wall-clock slack (µs) tolerated when nesting spans from different
# processes: their timelines share one wall anchor but not one clock.
EPS_US = 500.0


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):  # bare event-array form is also legal
        doc = {"traceEvents": doc, "metadata": {}}
    return doc


def service_of(doc: Dict[str, Any], fallback: str) -> str:
    svc = (doc.get("metadata") or {}).get("service")
    if svc:
        return str(svc)
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            return str((ev.get("args") or {}).get("name", fallback))
    return fallback


def collect(paths: List[str]):
    """Flatten files into (spans, instants, per-file stats)."""
    spans: List[Dict[str, Any]] = []
    instants: List[Dict[str, Any]] = []
    stats: List[Dict[str, Any]] = []
    for path in paths:
        doc = load_trace(path)
        svc = service_of(doc, path)
        meta = doc.get("metadata") or {}
        stats.append({"file": path, "service": svc,
                      "dropped": int(meta.get("dropped", 0)),
                      "events": len(doc.get("traceEvents", []))})
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "X":
                spans.append({"name": ev.get("name", "?"),
                              "ts": float(ev.get("ts", 0.0)),
                              "dur": float(ev.get("dur", 0.0)),
                              "service": svc,
                              "args": ev.get("args") or {}})
            elif ev.get("ph") == "i":
                instants.append({"name": ev.get("name", "?"),
                                 "ts": float(ev.get("ts", 0.0)),
                                 "service": svc,
                                 "args": ev.get("args") or {}})
    return spans, instants, stats


def by_trace_id(events: List[Dict[str, Any]]) -> Dict[str, list]:
    groups: Dict[str, list] = {}
    for ev in events:
        tid = ev["args"].get("trace_id")
        if tid:
            groups.setdefault(str(tid), []).append(ev)
    return groups


def build_tree(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Nest one request's spans by time containment (stack walk over
    spans sorted by start, longest-first on ties). Returns roots; each
    node gains a ``children`` list."""
    order = sorted(spans, key=lambda s: (s["ts"], -s["dur"]))
    roots: List[Dict[str, Any]] = []
    stack: List[Dict[str, Any]] = []
    for s in order:
        s = dict(s, children=[])
        while stack and stack[-1]["ts"] + stack[-1]["dur"] + EPS_US < \
                s["ts"] + s["dur"]:
            stack.pop()
        if stack:
            stack[-1]["children"].append(s)
        else:
            roots.append(s)
        stack.append(s)
    return roots


def render_tree(node: Dict[str, Any], t0: float, depth: int = 0) -> List[str]:
    extra = " ".join(
        f"{k}={v}" for k, v in sorted(node["args"].items())
        if k != "trace_id" and isinstance(v, (int, float, str)))
    line = ("  " * (depth + 1)
            + f"span={node['name']} service={node['service']} "
            + f"start_ms={round((node['ts'] - t0) / 1e3, 2)} "
            + f"dur_ms={round(node['dur'] / 1e3, 2)}"
            + (f" {extra}" if extra else ""))
    out = [line]
    for c in node["children"]:
        out.extend(render_tree(c, t0, depth + 1))
    return out


def pct(vals: List[float], p: float, digits: int = 2) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    return round(vals[min(len(vals) - 1, int(p * len(vals)))], digits)


def _fmt(v) -> str:
    return "unknown" if v is None else str(v)


def request_report(spans, top: int) -> List[str]:
    groups = by_trace_id(spans)
    # A request is "complete" when a replica recorded its terminal
    # `request` span; `route` spans with no matching request span mean
    # the replica side was lost (ring overwrite, crash, still running).
    # A disaggregated handoff records TWO request spans under one trace
    # id — the prefill replica's prefill-only pass, then the decode
    # replica's full request — so the terminal span is the LATEST-ending
    # one; the earlier ones are the handoff legs, joined in the same
    # tree with the `kv_transfer` push between them.
    complete: Dict[str, Dict[str, Any]] = {}
    routed_only = 0
    handoffs = 0
    kv_pushes = 0
    for tid, evs in groups.items():
        req = [e for e in evs if e["name"] == "request"]
        route = [e for e in evs if e["name"] == "route"]
        if req:
            complete[tid] = {
                "evs": evs,
                "req": max(req, key=lambda e: e["ts"] + e["dur"]),
                "route": route[0] if route else None}
            if len(req) > 1:
                handoffs += 1
            if any(e["name"] == "kv_transfer" for e in evs):
                kv_pushes += 1
        elif route:
            routed_only += 1
    lines = [f"requests_complete={len(complete)} "
             f"route_unmatched={routed_only} "
             f"handoffs={handoffs} kv_transfers={kv_pushes} "
             f"trace_ids_seen={len(groups)}"]

    comp_ms: Dict[str, List[float]] = {}
    totals: List[tuple] = []
    for tid, g in complete.items():
        per = {}
        for e in g["evs"]:
            if e["name"] in REQUEST_COMPONENTS:
                key = ("prefill" if e["name"] == "prefill_chunk"
                       else e["name"])
                per[key] = per.get(key, 0.0) + e["dur"] / 1e3
        if g["route"] is not None:
            # Router-side time not booked on the replica: network,
            # header shuffling, stream piping.
            per["route_overhead"] = max(
                0.0, (g["route"]["dur"] - g["req"]["dur"]) / 1e3)
        for k, v in per.items():
            comp_ms.setdefault(k, []).append(v)
        ttft = per.get("queue_wait", 0.0) + per.get("prefill", 0.0)
        comp_ms.setdefault("ttft", []).append(ttft)
        totals.append((g["req"]["dur"] / 1e3, tid, g))
    for name in ("ttft", "queue_wait", "prefill", "kv_transfer", "decode",
                 "route_overhead"):
        vals = comp_ms.get(name, [])
        if not vals:
            continue
        lines.append(f"component={name} count={len(vals)} "
                     f"p50_ms={_fmt(pct(vals, 0.50))} "
                     f"p95_ms={_fmt(pct(vals, 0.95))} "
                     f"max_ms={_fmt(round(max(vals), 2))}")

    totals.sort(reverse=True)
    for rank, (dur_ms, tid, g) in enumerate(totals[:max(top, 0)], 1):
        root_evs = g["evs"]
        lines.append(f"slow_rank={rank} trace_id={tid} "
                     f"total_ms={round(dur_ms, 2)} "
                     f"replica={g['req']['service']}")
        t0 = min(e["ts"] for e in root_evs)
        for root in build_tree(root_evs):
            lines.extend(render_tree(root, t0))
    return lines


def trainer_report(spans, instants) -> List[str]:
    # Group by service: a multi-host run exports one trace per host
    # (heartbeat_p<idx> naming on the run dir side), and summing phase
    # time across hosts would double-book wall clock that elapsed in
    # parallel. Single-host traces produce one group and no service= key.
    svc_spans: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        if s["name"] in TRAIN_PHASES:
            svc_spans.setdefault(s["service"], []).append(s)
    if not svc_spans:
        return []
    multi = len(svc_spans) > 1
    lines: List[str] = []
    for svc in sorted(svc_spans):
        phase_s: Dict[str, float] = {}
        t_min, t_max = None, None
        for s in svc_spans[svc]:
            phase_s[s["name"]] = phase_s.get(s["name"], 0.0) + s["dur"] / 1e6
            lo, hi = s["ts"], s["ts"] + s["dur"]
            t_min = lo if t_min is None else min(t_min, lo)
            t_max = hi if t_max is None else max(t_max, hi)
        wall = (t_max - t_min) / 1e6 if t_max is not None else 0.0
        wins = [i for i in instants if i["name"] == "step_window"
                and (not multi or i["service"] == svc)]
        mfus = [float(i["args"]["mfu"]) for i in wins
                if isinstance(i["args"].get("mfu"), (int, float))]
        booked = sum(phase_s.values())
        tag = f"service={svc} " if multi else ""
        lines.append(
            f"trainer_attribution=1 {tag}"
            f"windows={len(wins)} "
            f"mfu_mean={_fmt(round(sum(mfus) / len(mfus), 4) if mfus else None)} "
            f"booked_s={round(booked, 3)} "
            f"span_wall_s={round(wall, 3)}")
        for name in TRAIN_PHASES:
            if name not in phase_s:
                continue
            lines.append(
                f"phase={name} {tag}total_s={round(phase_s[name], 3)} "
                f"share={round(phase_s[name] / booked, 4) if booked else 0.0}")
    return lines


def graftprof_report(run_dir: str) -> List[str]:
    """graftprof fold: when the run dir holds a jax.profiler dump
    (``<run_dir>/profile/plugins/profile/...``), append the op-level
    attribution (obs/profile_report.py) under the span-level one, so a
    single command shows ledger-, span-, and op-level views of the same
    step window. Quiet when there is no dump; degrades to a note when
    the package is not importable (this script runs uninstalled — the
    repo-root fallback covers in-tree use)."""
    try:
        try:
            from mlx_cuda_distributed_pretraining_tpu.obs import (
                profile_report)
        except ImportError:
            sys.path.insert(0, os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            from mlx_cuda_distributed_pretraining_tpu.obs import (
                profile_report)
    except ImportError:
        return ["graftprof=0 reason=package_unavailable"]
    try:
        rep = profile_report.generate_report(run_dir)
    except Exception as e:  # noqa: BLE001 - fold is best-effort
        return [f"graftprof=0 reason=error detail={type(e).__name__}"]
    if rep is None:
        return []
    return profile_report.format_report(rep)


def run_dir_traces(run_dir: str) -> List[str]:
    """Span-trace exports a trainer run dir is known to hold."""
    out: List[str] = []
    for pat in ("trace.json", "trace_p*.json", "trace_step*.json"):
        out.extend(sorted(glob.glob(os.path.join(run_dir, pat))))
    return out


def report(paths: List[str], top: int = 5,
           run_dir: Optional[str] = None) -> List[str]:
    spans, instants, stats = collect(paths)
    lines = []
    for st in stats:
        lines.append(f"trace_file={st['file']} service={st['service']} "
                     f"events={st['events']} dropped={st['dropped']}")
    lines.extend(request_report(spans, top))
    lines.extend(trainer_report(spans, instants))
    if run_dir:
        lines.extend(graftprof_report(run_dir))
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("traces", nargs="*",
                   help="chrome trace JSON files (/trace dumps, trainer "
                        "trace_step*.json)")
    p.add_argument("--run-dir", default=None,
                   help="trainer run dir: its trace.json/trace_step*.json "
                        "exports join the inputs, and a jax.profiler dump "
                        "under <run-dir>/profile gets the graftprof "
                        "op-level attribution appended")
    p.add_argument("--top", type=int, default=5,
                   help="how many slowest requests to print as span trees")
    a = p.parse_args(argv)
    traces = list(a.traces)
    if a.run_dir:
        traces.extend(t for t in run_dir_traces(a.run_dir)
                      if t not in traces)
    if not traces and not a.run_dir:
        p.error("give trace files and/or --run-dir")
    for line in report(traces, top=a.top, run_dir=a.run_dir):
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
