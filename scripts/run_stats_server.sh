#!/usr/bin/env bash
# Start the WebSocket stats hub for multi-worker runs (reference:
# stats_server.py). Workers publish when logging.metrics.stats_url is set.
set -euo pipefail
HOST="${1:-127.0.0.1}"
PORT="${2:-8765}"
PERSIST="${3:-stats.json}"
HTTP_PORT="${4:-8080}"   # live dashboard page; 0 disables
exec python -m mlx_cuda_distributed_pretraining_tpu.obs.stats_server \
  --host "$HOST" --port "$PORT" --persist "$PERSIST" --http-port "$HTTP_PORT"
