#!/usr/bin/env bash
# Run the SERVING-plane chaos drill (graftchaos): bench.py's serve_chaos
# case stands up an in-process disaggregated fleet (1 prefill + 1 decode
# replica behind the fleet router), floods it, then arms the
# fault-injection registry (serve/faults.py) mid-flood:
#
#   kv_transfer.corrupt   — one KV payload bit-flipped on the wire
#                           (decode replica must refuse + quarantine,
#                           router falls back to local prefill)
#   kv_transfer.drop      — one KV push swallowed (same fallback)
#   scrape.timeout        — decode-replica /metrics scrapes time out
#                           (poller must NOT mark the replica dead)
#   http.connect_refused  — decode replica hard-down for a window (the
#                           router's circuit breaker must open, traffic
#                           degrades to the surviving pool, breaker
#                           closes after recovery)
#
# PASS bars (all deterministic; the drill re-runs bit-identically):
#   - every flooded request resolves 200/429/504 — none hang, none 5xx
#   - greedy token parity: the same probe prompt decodes to the same
#     text before and after the chaos window
#   - the decode-replica breaker OPENED during the kill window and
#     RECOVERED (closed) after it
#   - decode TTFT p99 stays within 3x the clean-window p99 (+0.5s)
#
# Usage: scripts/chaos_serve.sh [out.json]
#   Exit 0 iff the drill ran and bar_met=true; the case row (bars,
#   per-outcome counts, fault-fire counts) lands in out.json (default
#   /tmp/chaos_serve.json) and is summarized on stdout.
#
# This is the manual form of tests/test_serve_chaos.py (slow marker).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-/tmp/chaos_serve.json}"
LOG="${OUT%.json}.log"

echo "chaos_serve: running serve_chaos drill (log: $LOG)"
RC=0
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  python bench.py --one serve_chaos >"$LOG" 2>&1 || RC=$?
if [ "$RC" -ne 0 ]; then
  echo "chaos_serve: FAIL — bench.py exited rc=$RC; tail of log:" >&2
  tail -20 "$LOG" >&2
  exit "$RC"
fi

python - "$LOG" "$OUT" <<'EOF'
import json
import sys

MARK = "BENCHCASE "
row = None
for line in open(sys.argv[1]):
    if line.startswith(MARK):
        row = json.loads(line[len(MARK):])
if row is None:
    sys.exit("chaos_serve: FAIL — no case row in log")
json.dump(row, open(sys.argv[2], "w"), indent=2, sort_keys=True)
bars = {k: row.get(k) for k in (
    "no_hung_requests", "all_clean_status", "token_parity",
    "breaker_opened", "breaker_recovered", "ttft_within_bound")}
print(f"chaos_serve: outcomes={row.get('outcomes')}")
print(f"chaos_serve: fault_fires={row.get('fault_fires')}")
for k, v in bars.items():
    print(f"chaos_serve:   {'PASS' if v else 'FAIL'}  {k}")
if not row.get("bar_met"):
    sys.exit("chaos_serve: FAIL — bar_met=false")
print(f"chaos_serve: PASS (row: {sys.argv[2]})")
EOF
