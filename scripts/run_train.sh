#!/usr/bin/env bash
# Launch a training run in the background with PID file + monitor, the way
# the reference's scripts/run_*.sh wrappers do (reference:
# scripts/run_hybrid_distributed.sh starts training + a status poll loop).
#
# Usage: scripts/run_train.sh <config.yaml> [runs_root]
set -euo pipefail

CONFIG="${1:?usage: run_train.sh <config.yaml> [runs_root]}"
RUNS_ROOT="${2:-runs}"
NAME="$(python - "$CONFIG" <<'EOF'
import sys, yaml
print(yaml.safe_load(open(sys.argv[1]))["name"])
EOF
)"

mkdir -p "$RUNS_ROOT"
LOG="$RUNS_ROOT/$NAME.launch.log"

nohup python -m mlx_cuda_distributed_pretraining_tpu.train.trainer \
  --config "$CONFIG" --runs-root "$RUNS_ROOT" >"$LOG" 2>&1 &
PID=$!
echo "$PID" > "$RUNS_ROOT/$NAME.pid"
echo "training started: pid=$PID config=$CONFIG log=$LOG"
echo "monitor with: python -m mlx_cuda_distributed_pretraining_tpu.obs.monitor $NAME --runs-root $RUNS_ROOT"
