#!/usr/bin/env python
"""Concurrent HTTP load generator for the inference server.

Drives N worker threads against ``POST /generate`` (infer/server.py) and
prints one JSON summary line: request counts by status (200 / 429 / 504 /
other), end-to-end latency percentiles, TTFT and per-token decode
latency percentiles (p50/p95/p99 — the numbers that separate a paged
pool from a slotted one under mixed-length traffic), client-side token
throughput, and the server's /metrics snapshot after the run.
Stdlib-only, so it runs anywhere the repo does:

    python scripts/load_gen.py --url http://127.0.0.1:8400 \
        --concurrency 8 --requests 64 --max-tokens 32

Point it at a ``--engine locked`` server and then a ``--engine batch``
one to see continuous batching under identical offered load (the
serve_batch bench case does the same comparison in-process).

Shared-prefix workload (``--shared-prefix-tokens N --prefix-groups G``):
every request's prompt starts with one of G fixed ~N-token prefixes
(the byte-fallback tokenizer is ~1 token/char), modelling templated
traffic — system prompts, few-shot headers. Against a prefix-caching
server the summary splits TTFT p50/p95 by cache hit vs miss (the server
reports ``prefix_cached_tokens`` per request) and adds the aggregate
``cache_hit_rate``; against the router (serve/router.py) each group is
consistently hashed to one replica, so hits land where the blocks live.

Mixed flood (``--mix prefill-heavy:decode-heavy``): interleaves traffic
classes with opposite resource profiles — ``prefill-heavy`` sends a long
unique prompt and asks for a few tokens (compute-bound, the disaggregated
fleet's prefill-pool diet), ``decode-heavy`` a short prompt with a long
generation (bandwidth-bound; its TTFT is what prefill interference
destroys on a homogeneous replica). Class weights repeat via ``*N``
(``prefill-heavy*2:decode-heavy``); shapes via ``--mix-*`` flags. The
summary gains per-class TTFT and TPOT (per-output-token decode latency)
p50/p95/p99 — the ``serve_fleet`` bench case reads exactly these to
score a prefill/decode fleet against a homogeneous baseline.

Per-request tracing (``--trace-out FILE``): writes one CSV row per
request with the server-minted trace id and the server-side TTFT
breakdown (queue_ms / prefill_ms / decode_ms) that the batch engine
attaches to every response. Join the ``trace_id`` column against the
chrome traces dumped by the router's and replicas' ``/trace``
endpoints (scripts/trace_report.py does the merge) to see where each
slow request actually spent its time.
"""

from __future__ import annotations

import argparse
import csv
import json
import threading
import time
import urllib.error
import urllib.request

TRACE_FIELDS = ("trace_id", "status", "latency_s", "ttft_ms", "queue_ms",
                "prefill_ms", "decode_ms", "tokens", "prompt_tokens",
                "cached_tokens", "cls")

# --mix class shapes: (prompt tokens, generated tokens). ~1 token/char
# under the byte-fallback tokenizer; prompts are unique per request (the
# request id leads) so prefill work is real, not a prefix-cache hit.
MIX_SHAPES = {
    "prefill-heavy": (512, 8),
    "decode-heavy": (16, 128),
}


def parse_mix(spec: str) -> list:
    """``a:b*2:c`` -> ["a", "b", "b", "c"] (the round-robin schedule)."""
    classes = []
    for part in spec.split(":"):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition("*")
        classes.extend([name] * max(1, int(weight or 1)))
    if not classes:
        raise ValueError(f"empty --mix spec {spec!r}")
    return classes


def class_prompt(cls: str, i: int, tokens: int) -> str:
    """Unique ~``tokens``-token prompt for request ``i`` of class
    ``cls``: the id comes FIRST so no two prompts share a KV block —
    prefill cost is genuine, not amortized by the prefix cache."""
    stem = f"[{cls} {i}] measure the fleet under mixed load; "
    reps = -(-tokens // len(stem))
    return (stem * reps)[:tokens]


def _one_request(url: str, body: dict, timeout: float) -> dict:
    data = json.dumps(body).encode()
    req = urllib.request.Request(url.rstrip("/") + "/generate", data=data,
                                 headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
            # The batch engine reports server-side TTFT; per-token decode
            # latency is the post-first-token time spread over the rest.
            ttft = out.get("ttft_ms")
            return {"status": resp.status, "latency_s": time.monotonic() - t0,
                    "tokens": int(out.get("tokens", 0)),
                    "ttft_s": ttft / 1e3 if ttft is not None else None,
                    "prompt_tokens": float(out.get("prompt_tokens", 0.0)),
                    "cached_tokens": float(
                        out.get("prefix_cached_tokens", 0.0)),
                    "trace_id": out.get("trace_id"),
                    "queue_ms": out.get("queue_ms"),
                    "prefill_ms": out.get("prefill_ms"),
                    "decode_ms": out.get("decode_ms")}
    except urllib.error.HTTPError as e:
        return {"status": e.code, "latency_s": time.monotonic() - t0,
                "tokens": 0, "ttft_s": None, "prompt_tokens": 0.0,
                "cached_tokens": 0.0, "trace_id": None, "queue_ms": None,
                "prefill_ms": None, "decode_ms": None}
    except Exception as e:  # noqa: BLE001 - count it, keep loading
        return {"status": f"error:{type(e).__name__}",
                "latency_s": time.monotonic() - t0, "tokens": 0,
                "ttft_s": None, "prompt_tokens": 0.0, "cached_tokens": 0.0,
                "trace_id": None, "queue_ms": None, "prefill_ms": None,
                "decode_ms": None}


def group_prefix(group: int, tokens: int) -> str:
    """Deterministic ~``tokens``-token shared prefix for one group (the
    byte-fallback tokenizer maps ~1 token per char)."""
    stem = f"[group {group}] shared context block; "
    reps = -(-tokens // len(stem))
    return (stem * reps)[:tokens]


def run_load(url: str, concurrency: int, requests: int, prompt: str,
             max_tokens: int, temperature: float, deadline_s: float | None,
             timeout: float, shared_prefix_tokens: int = 0,
             prefix_groups: int = 1, trace_out: str | None = None,
             mix: str | None = None,
             mix_shapes: dict | None = None,
             alerts_url: str | None = None) -> dict:
    results: list = []
    lock = threading.Lock()
    counter = iter(range(requests))
    schedule = parse_mix(mix) if mix else None
    shapes = {**MIX_SHAPES, **(mix_shapes or {})}

    def worker():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            cls = None
            if schedule is not None:
                cls = schedule[i % len(schedule)]
                if cls not in shapes:
                    raise ValueError(f"unknown --mix class {cls!r} "
                                     f"(known: {sorted(shapes)})")
                p_toks, g_toks = shapes[cls]
                body = {"prompt": class_prompt(cls, i, p_toks),
                        "max_tokens": g_toks,
                        "temperature": temperature, "seed": i}
            else:
                head = (group_prefix(i % max(prefix_groups, 1),
                                     shared_prefix_tokens)
                        if shared_prefix_tokens > 0 else "")
                body = {"prompt": f"{head}{prompt} [{i}]",
                        "max_tokens": max_tokens,
                        "temperature": temperature, "seed": i}
            if deadline_s is not None:
                body["deadline_s"] = deadline_s
            r = _one_request(url, body, timeout)
            r["cls"] = cls
            with lock:
                results.append(r)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    by_status: dict = {}
    for r in results:
        by_status[str(r["status"])] = by_status.get(str(r["status"]), 0) + 1
    # Chaos-drill rollup: every request must land in exactly one bucket
    # (ok + refused + expired + error == requests — nothing hung). 429
    # and 504 are the CLEAN degradation outcomes; "error" is anything
    # else (5xx, connection failures, client timeouts).
    outcomes = {"ok": 0, "429": 0, "504": 0, "error": 0}
    for r in results:
        s = r["status"]
        key = ("ok" if s == 200 else str(s) if s in (429, 504) else "error")
        outcomes[key] += 1
    ok = [r for r in results if r["status"] == 200]
    lats = sorted(r["latency_s"] for r in ok)
    ttfts = sorted(r["ttft_s"] for r in ok if r["ttft_s"] is not None)
    # Per-token decode latency per request: everything after the first
    # token, normalized by the tokens it produced. Falls back to
    # whole-request normalization when the server (locked engine) does
    # not report TTFT.
    per_tok = sorted(
        ((r["latency_s"] - r["ttft_s"]) / max(r["tokens"] - 1, 1)
         if r["ttft_s"] is not None
         else r["latency_s"] / max(r["tokens"], 1))
        for r in ok if r["tokens"] > 0)

    def pct(vals, p: float, digits: int = 3) -> float | None:
        if not vals:
            return None
        return round(vals[min(len(vals) - 1, int(p * len(vals)))], digits)

    toks = sum(r["tokens"] for r in results)
    summary = {
        "url": url, "concurrency": concurrency, "requests": requests,
        "max_tokens": max_tokens, "wall_s": round(wall, 2),
        "by_status": by_status,
        "outcomes": outcomes,
        "completed": len(results),
        "ok": by_status.get("200", 0),
        "latency_p50_s": pct(lats, 0.50), "latency_p90_s": pct(lats, 0.90),
        "latency_p95_s": pct(lats, 0.95), "latency_p99_s": pct(lats, 0.99),
        "latency_max_s": round(lats[-1], 3) if lats else None,
        "ttft_p50_s": pct(ttfts, 0.50), "ttft_p95_s": pct(ttfts, 0.95),
        "ttft_p99_s": pct(ttfts, 0.99),
        "tok_latency_p50_s": pct(per_tok, 0.50, 5),
        "tok_latency_p95_s": pct(per_tok, 0.95, 5),
        "tok_latency_p99_s": pct(per_tok, 0.99, 5),
        "client_tok_s": round(toks / wall, 1) if wall > 0 else None,
    }
    if shared_prefix_tokens > 0:
        # Hit = the server adopted cached prefix blocks for the request.
        hit_t = sorted(r["ttft_s"] for r in ok
                       if r["ttft_s"] is not None and r["cached_tokens"] > 0)
        miss_t = sorted(r["ttft_s"] for r in ok
                        if r["ttft_s"] is not None
                        and r["cached_tokens"] == 0)
        offered = sum(r["prompt_tokens"] for r in ok)
        cached = sum(r["cached_tokens"] for r in ok)
        summary.update({
            "shared_prefix_tokens": shared_prefix_tokens,
            "prefix_groups": prefix_groups,
            "cache_hits": len(hit_t), "cache_misses": len(miss_t),
            "cache_hit_rate": (round(cached / offered, 4) if offered else 0.0),
            "ttft_hit_p50_s": pct(hit_t, 0.50),
            "ttft_hit_p95_s": pct(hit_t, 0.95),
            "ttft_miss_p50_s": pct(miss_t, 0.50),
            "ttft_miss_p95_s": pct(miss_t, 0.95),
        })
    if schedule is not None:
        # Per-class TTFT/TPOT tails: decode-heavy TTFT p99 is THE number
        # disaggregation exists to protect (prefill interference lands
        # there first); prefill-heavy TTFT tracks prompt-pass throughput.
        def tpot(r) -> float | None:
            if r["tokens"] <= 0:
                return None
            if r["ttft_s"] is not None:
                return (r["latency_s"] - r["ttft_s"]) / max(r["tokens"] - 1,
                                                            1)
            return r["latency_s"] / max(r["tokens"], 1)

        per_class = {}
        for cls in dict.fromkeys(schedule):
            rs = [r for r in results if r["cls"] == cls]
            ok_c = [r for r in rs if r["status"] == 200]
            t = sorted(r["ttft_s"] for r in ok_c if r["ttft_s"] is not None)
            d = sorted(v for v in (tpot(r) for r in ok_c) if v is not None)
            p_toks, g_toks = shapes[cls]
            per_class[cls] = {
                "requests": len(rs), "ok": len(ok_c),
                "prompt_tokens": p_toks, "gen_tokens": g_toks,
                "ttft_p50_s": pct(t, 0.50), "ttft_p95_s": pct(t, 0.95),
                "ttft_p99_s": pct(t, 0.99),
                "tpot_p50_s": pct(d, 0.50, 5), "tpot_p95_s": pct(d, 0.95, 5),
                "tpot_p99_s": pct(d, 0.99, 5),
            }
        summary["mix"] = per_class
    if trace_out:
        # One row per request, in completion order. ttft_ms mirrors the
        # server value; queue/prefill/decode are the server's own
        # monotonic-stamp breakdown, so the columns sum to ~latency
        # minus network + client overhead.
        with open(trace_out, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=TRACE_FIELDS,
                               extrasaction="ignore")
            w.writeheader()
            for r in results:
                row = dict(r)
                row["latency_s"] = round(r["latency_s"], 4)
                row["ttft_ms"] = (round(r["ttft_s"] * 1e3, 2)
                                  if r["ttft_s"] is not None else "")
                for k in ("trace_id", "queue_ms", "prefill_ms", "decode_ms",
                          "cls"):
                    if row.get(k) is None:
                        row[k] = ""
                w.writerow(row)
        summary["trace_out"] = trace_out
        summary["traced_requests"] = sum(
            1 for r in results if r.get("trace_id"))
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                    timeout=10) as resp:
            summary["server_metrics"] = json.loads(resp.read())
    except Exception:  # noqa: BLE001 - summary is still useful without it
        pass
    # graftscope rollup next to the outcome counts: which SLO rules were
    # firing when the run ended. Same tolerance as server_metrics — no
    # collector (or no /alerts route on the target), no keys.
    try:
        with urllib.request.urlopen(
                (alerts_url or url).rstrip("/") + "/alerts",
                timeout=10) as resp:
            doc = json.loads(resp.read())
        firing = sorted(str(al.get("rule", "?"))
                        for al in doc.get("alerts", [])
                        if isinstance(al, dict)
                        and al.get("state") == "firing")
        summary["alerts_firing"] = len(firing)
        summary["alerts_firing_rules"] = firing
    except Exception:  # noqa: BLE001 - alerts are optional evidence
        pass
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default="http://127.0.0.1:8400")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt", default="The quick brown fox")
    p.add_argument("--max-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request deadline passed to the batch engine")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="client-side HTTP timeout per request")
    p.add_argument("--shared-prefix-tokens", type=int, default=0,
                   help="prepend a ~N-token group-shared prefix to every "
                        "prompt (0 = off); TTFT is then split by prefix-"
                        "cache hit vs miss")
    p.add_argument("--prefix-groups", type=int, default=1,
                   help="number of distinct shared prefixes the requests "
                        "rotate through")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="write a per-request CSV (trace_id + server-side "
                        "queue/prefill/decode breakdown) to FILE")
    p.add_argument("--mix", default=None, metavar="SPEC",
                   help="mixed flood: colon-separated traffic classes "
                        "round-robined across requests, e.g. "
                        "'prefill-heavy:decode-heavy' (weights via *N); "
                        "overrides --prompt/--max-tokens and reports "
                        "per-class TTFT/TPOT p50/p95/p99")
    p.add_argument("--mix-prefill-prompt", type=int, default=512,
                   help="prefill-heavy class: ~prompt tokens per request")
    p.add_argument("--mix-prefill-gen", type=int, default=8,
                   help="prefill-heavy class: generated tokens per request")
    p.add_argument("--mix-decode-prompt", type=int, default=16,
                   help="decode-heavy class: ~prompt tokens per request")
    p.add_argument("--mix-decode-gen", type=int, default=128,
                   help="decode-heavy class: generated tokens per request")
    p.add_argument("--alerts-url", default=None,
                   help="graftscope collector base URL for the end-of-run "
                        "firing-alert count (default: --url, which only "
                        "answers when the target itself serves /alerts)")
    a = p.parse_args(argv)
    summary = run_load(a.url, a.concurrency, a.requests, a.prompt,
                       a.max_tokens, a.temperature, a.deadline_s, a.timeout,
                       shared_prefix_tokens=a.shared_prefix_tokens,
                       prefix_groups=a.prefix_groups, trace_out=a.trace_out,
                       mix=a.mix, mix_shapes={
                           "prefill-heavy": (a.mix_prefill_prompt,
                                             a.mix_prefill_gen),
                           "decode-heavy": (a.mix_decode_prompt,
                                            a.mix_decode_gen)},
                       alerts_url=a.alerts_url)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
