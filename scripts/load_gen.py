#!/usr/bin/env python
"""Concurrent HTTP load generator for the inference server.

Drives N worker threads against ``POST /generate`` (infer/server.py) and
prints one JSON summary line: request counts by status (200 / 429 / 504 /
other), latency percentiles, client-side token throughput, and the
server's /metrics snapshot after the run. Stdlib-only, so it runs
anywhere the repo does:

    python scripts/load_gen.py --url http://127.0.0.1:8400 \
        --concurrency 8 --requests 64 --max-tokens 32

Point it at a ``--engine locked`` server and then a ``--engine batch``
one to see continuous batching under identical offered load (the
serve_batch bench case does the same comparison in-process).
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.error
import urllib.request


def _one_request(url: str, body: dict, timeout: float) -> dict:
    data = json.dumps(body).encode()
    req = urllib.request.Request(url.rstrip("/") + "/generate", data=data,
                                 headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
            return {"status": resp.status, "latency_s": time.monotonic() - t0,
                    "tokens": int(out.get("tokens", 0))}
    except urllib.error.HTTPError as e:
        return {"status": e.code, "latency_s": time.monotonic() - t0,
                "tokens": 0}
    except Exception as e:  # noqa: BLE001 - count it, keep loading
        return {"status": f"error:{type(e).__name__}",
                "latency_s": time.monotonic() - t0, "tokens": 0}


def run_load(url: str, concurrency: int, requests: int, prompt: str,
             max_tokens: int, temperature: float, deadline_s: float | None,
             timeout: float) -> dict:
    results: list = []
    lock = threading.Lock()
    counter = iter(range(requests))

    def worker():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            body = {"prompt": f"{prompt} [{i}]", "max_tokens": max_tokens,
                    "temperature": temperature, "seed": i}
            if deadline_s is not None:
                body["deadline_s"] = deadline_s
            r = _one_request(url, body, timeout)
            with lock:
                results.append(r)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    by_status: dict = {}
    for r in results:
        by_status[str(r["status"])] = by_status.get(str(r["status"]), 0) + 1
    lats = sorted(r["latency_s"] for r in results if r["status"] == 200)

    def pct(p: float) -> float | None:
        if not lats:
            return None
        return round(lats[min(len(lats) - 1, int(p * len(lats)))], 3)

    toks = sum(r["tokens"] for r in results)
    summary = {
        "url": url, "concurrency": concurrency, "requests": requests,
        "max_tokens": max_tokens, "wall_s": round(wall, 2),
        "by_status": by_status,
        "ok": by_status.get("200", 0),
        "latency_p50_s": pct(0.50), "latency_p90_s": pct(0.90),
        "latency_max_s": round(lats[-1], 3) if lats else None,
        "client_tok_s": round(toks / wall, 1) if wall > 0 else None,
    }
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                    timeout=10) as resp:
            summary["server_metrics"] = json.loads(resp.read())
    except Exception:  # noqa: BLE001 - summary is still useful without it
        pass
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default="http://127.0.0.1:8400")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt", default="The quick brown fox")
    p.add_argument("--max-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request deadline passed to the batch engine")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="client-side HTTP timeout per request")
    a = p.parse_args(argv)
    summary = run_load(a.url, a.concurrency, a.requests, a.prompt,
                       a.max_tokens, a.temperature, a.deadline_s, a.timeout)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
