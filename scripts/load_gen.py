#!/usr/bin/env python
"""Concurrent HTTP load generator for the inference server.

Drives N worker threads against ``POST /generate`` (infer/server.py) and
prints one JSON summary line: request counts by status (200 / 429 / 504 /
other), end-to-end latency percentiles, TTFT and per-token decode
latency percentiles (p50/p95/p99 — the numbers that separate a paged
pool from a slotted one under mixed-length traffic), client-side token
throughput, and the server's /metrics snapshot after the run.
Stdlib-only, so it runs anywhere the repo does:

    python scripts/load_gen.py --url http://127.0.0.1:8400 \
        --concurrency 8 --requests 64 --max-tokens 32

Point it at a ``--engine locked`` server and then a ``--engine batch``
one to see continuous batching under identical offered load (the
serve_batch bench case does the same comparison in-process).
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.error
import urllib.request


def _one_request(url: str, body: dict, timeout: float) -> dict:
    data = json.dumps(body).encode()
    req = urllib.request.Request(url.rstrip("/") + "/generate", data=data,
                                 headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
            # The batch engine reports server-side TTFT; per-token decode
            # latency is the post-first-token time spread over the rest.
            ttft = out.get("ttft_ms")
            return {"status": resp.status, "latency_s": time.monotonic() - t0,
                    "tokens": int(out.get("tokens", 0)),
                    "ttft_s": ttft / 1e3 if ttft is not None else None}
    except urllib.error.HTTPError as e:
        return {"status": e.code, "latency_s": time.monotonic() - t0,
                "tokens": 0, "ttft_s": None}
    except Exception as e:  # noqa: BLE001 - count it, keep loading
        return {"status": f"error:{type(e).__name__}",
                "latency_s": time.monotonic() - t0, "tokens": 0,
                "ttft_s": None}


def run_load(url: str, concurrency: int, requests: int, prompt: str,
             max_tokens: int, temperature: float, deadline_s: float | None,
             timeout: float) -> dict:
    results: list = []
    lock = threading.Lock()
    counter = iter(range(requests))

    def worker():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            body = {"prompt": f"{prompt} [{i}]", "max_tokens": max_tokens,
                    "temperature": temperature, "seed": i}
            if deadline_s is not None:
                body["deadline_s"] = deadline_s
            r = _one_request(url, body, timeout)
            with lock:
                results.append(r)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    by_status: dict = {}
    for r in results:
        by_status[str(r["status"])] = by_status.get(str(r["status"]), 0) + 1
    ok = [r for r in results if r["status"] == 200]
    lats = sorted(r["latency_s"] for r in ok)
    ttfts = sorted(r["ttft_s"] for r in ok if r["ttft_s"] is not None)
    # Per-token decode latency per request: everything after the first
    # token, normalized by the tokens it produced. Falls back to
    # whole-request normalization when the server (locked engine) does
    # not report TTFT.
    per_tok = sorted(
        ((r["latency_s"] - r["ttft_s"]) / max(r["tokens"] - 1, 1)
         if r["ttft_s"] is not None
         else r["latency_s"] / max(r["tokens"], 1))
        for r in ok if r["tokens"] > 0)

    def pct(vals, p: float, digits: int = 3) -> float | None:
        if not vals:
            return None
        return round(vals[min(len(vals) - 1, int(p * len(vals)))], digits)

    toks = sum(r["tokens"] for r in results)
    summary = {
        "url": url, "concurrency": concurrency, "requests": requests,
        "max_tokens": max_tokens, "wall_s": round(wall, 2),
        "by_status": by_status,
        "ok": by_status.get("200", 0),
        "latency_p50_s": pct(lats, 0.50), "latency_p90_s": pct(lats, 0.90),
        "latency_p95_s": pct(lats, 0.95), "latency_p99_s": pct(lats, 0.99),
        "latency_max_s": round(lats[-1], 3) if lats else None,
        "ttft_p50_s": pct(ttfts, 0.50), "ttft_p95_s": pct(ttfts, 0.95),
        "ttft_p99_s": pct(ttfts, 0.99),
        "tok_latency_p50_s": pct(per_tok, 0.50, 5),
        "tok_latency_p95_s": pct(per_tok, 0.95, 5),
        "tok_latency_p99_s": pct(per_tok, 0.99, 5),
        "client_tok_s": round(toks / wall, 1) if wall > 0 else None,
    }
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                    timeout=10) as resp:
            summary["server_metrics"] = json.loads(resp.read())
    except Exception:  # noqa: BLE001 - summary is still useful without it
        pass
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default="http://127.0.0.1:8400")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt", default="The quick brown fox")
    p.add_argument("--max-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request deadline passed to the batch engine")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="client-side HTTP timeout per request")
    a = p.parse_args(argv)
    summary = run_load(a.url, a.concurrency, a.requests, a.prompt,
                       a.max_tokens, a.temperature, a.deadline_s, a.timeout)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
