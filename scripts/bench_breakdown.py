"""Component-level time breakdown for a bench scale (default 100m).

Answers VERDICT r2's "where does the other 70% go": measures, at the same
shape bench.py uses, the cost of
  - loss forward only,
  - forward+backward (value_and_grad),
  - the full optimizer step,
  - the attention stack alone (L x flash fwd; one-layer fwd+bwd),
  - the CE head alone (fused and unfused),
so fwd / bwd / optimizer / attention / CE shares can be read directly.

Each section times ``steps`` iterations in ONE ``lax.scan`` dispatch, so
the numbers are pure chip compute — compare against bench.py rows taken
with ``BENCH_MEGASTEP`` set (the default per-step bench rows additionally
pay one tunnel RTT per step). ``BREAKDOWN_CHAIN=dispatch`` restores
per-call chaining. Prints JSON lines; run on the TPU:

    python scripts/bench_breakdown.py [--scale 100m] [--steps 10]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import SCALES, flops_per_token, mfu_or_unknown


def chain_time(fn, state, steps, donate=False):
    """fn: state -> state (jitted). Times ``steps`` iterations in ONE
    dispatch (lax.scan), so per-dispatch tunnel RTT (~70-200ms each) is
    paid once instead of per iteration — per-call chaining inflated every
    section's absolute ms and hid the true component shares.

    ``donate`` must be True ONLY when ``state`` is a fresh tree owned by
    this section (the full-step sections: params + Adam moments would
    otherwise be held twice and OOM at scales the bench megastep fits)
    and False for sections whose input (module-level params/q0/h0) is
    reused by later sections — donating those would delete their buffers.
    BREAKDOWN_CHAIN=dispatch restores the old per-call chaining."""
    if os.environ.get("BREAKDOWN_CHAIN") == "dispatch":
        out = fn(state)
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        t0 = time.perf_counter()
        cur = out
        for _ in range(steps):
            cur = fn(cur)
        jax.device_get(jax.tree_util.tree_leaves(cur)[0].ravel()[:1])
        return (time.perf_counter() - t0) / steps

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def scanned(s):
        return jax.lax.scan(lambda c, _: (fn(c), None), s, None,
                            length=steps)[0]

    out = scanned(state)  # compile + warm
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    cur = scanned(out)
    jax.device_get(jax.tree_util.tree_leaves(cur)[0].ravel()[:1])
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", default="100m", choices=list(SCALES))
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--vocab", type=int, default=32768)
    a = ap.parse_args()

    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import flash_attention
    from mlx_cuda_distributed_pretraining_tpu.ops.fused_ce import fused_cross_entropy
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
    from mlx_cuda_distributed_pretraining_tpu.train.train_step import (
        init_train_state,
        make_train_step,
    )

    sc = SCALES[a.scale]
    B, S, remat = sc["batch"], sc["seq"], sc["remat"]
    args = llama.LlamaArgs(vocab_size=a.vocab, max_position_embeddings=S,
                           attention_type="flash", **sc["shape"])
    params = llama.init_params(jax.random.PRNGKey(0), args)
    n_params = llama.num_params(params)
    L, H, Dh = args.num_layers, args.num_heads, args.head_dim
    D = args.hidden_size

    rng = np.random.default_rng(0)
    x = rng.integers(1, a.vocab - 4, size=(B, S + 1)).astype(np.int32)
    batch = {"inputs": jnp.asarray(x[:, :-1]), "targets": jnp.asarray(x[:, 1:]),
             "mask": jnp.ones((B, S), jnp.float32)}

    results = {}

    def report(name, sec):
        results[name] = sec * 1e3
        print(json.dumps({"component": name, "ms": round(sec * 1e3, 2)}), flush=True)

    # full optimizer step (fused CE). The jitted step donates its state
    # buffers, so every timed section gets a FRESH params/state tree —
    # reusing a donated tree raises 'Array has been deleted' on device.
    opt = build_optimizer(TrainingConfig(
        hyperparameters={"learning_rate": 1e-3}, scheduler={"type": "cosine"},
        optimization={"optimizer": "adamw"}), 1000)

    def fresh_params():
        return llama.init_params(jax.random.PRNGKey(0), args)

    def loss_fused(p, b):
        return llama.loss_fn(p, b, args, compute_dtype=jnp.bfloat16,
                             remat=remat, ce_chunk=2048)

    def loss_unfused(p, b):
        return llama.loss_fn(p, b, args, compute_dtype=jnp.bfloat16,
                             remat=remat, ce_chunk=0)

    step, _ = make_train_step(loss_fused, opt)
    report("full_step_fused_ce",
           chain_time(lambda s: step(s, batch)[0],
                      init_train_state(fresh_params(), opt), a.steps,
                      donate=True))

    step_u, _ = make_train_step(loss_unfused, opt)
    report("full_step_unfused_ce",
           chain_time(lambda s: step_u(s, batch)[0],
                      init_train_state(fresh_params(), opt), a.steps,
                      donate=True))

    # non-donating sections below reuse the module-level params (never
    # donated: both full-step sections built their own trees)

    # forward-only loss (chained by feeding loss into a dummy param perturbation)
    @jax.jit
    def fwd_only(p):
        loss, _ = loss_fused(p, batch)
        return jax.tree_util.tree_map(lambda a: a + 0 * loss.astype(a.dtype), p)

    report("forward_loss", chain_time(fwd_only, params, a.steps))

    # forward+backward (no optimizer)
    @jax.jit
    def fwd_bwd(p):
        g = jax.grad(lambda q: loss_fused(q, batch)[0])(p)
        return jax.tree_util.tree_map(lambda a, b: a + 0 * b.astype(a.dtype), p, g)

    report("forward_backward", chain_time(fwd_bwd, params, a.steps))

    # attention stack alone: L flash calls fwd / fwd+bwd
    q0 = jnp.asarray(rng.normal(size=(B, S, H, Dh)).astype(np.float32), jnp.bfloat16)

    @jax.jit
    def attn_stack(q):
        for _ in range(L):
            q = flash_attention(q, q, q)
        return q

    report("attention_stack_fwd", chain_time(attn_stack, q0, a.steps))

    @jax.jit
    def attn_stack_bwd(q):
        # one layer under grad (key: attention_one_layer_fwd_bwd — multiply
        # by L for the stack share)
        g = jax.grad(lambda z: flash_attention(z, z, z).astype(jnp.float32).sum())(q)
        return q + 0 * g

    report("attention_one_layer_fwd_bwd", chain_time(attn_stack_bwd, q0, a.steps))

    # CE head alone
    h0 = jnp.asarray(rng.normal(size=(B, S, D)).astype(np.float32), jnp.bfloat16)
    w = params["tok_embeddings"]["weight"].astype(jnp.bfloat16)

    @jax.jit
    def ce_fused(h):
        nll = fused_cross_entropy(h, w, batch["targets"], batch["mask"], chunk=2048)
        return h + 0 * nll.astype(h.dtype)

    report("ce_head_fused_fwd", chain_time(ce_fused, h0, a.steps))

    @jax.jit
    def ce_fused_bwd(h):
        g = jax.grad(lambda z: fused_cross_entropy(
            z, w, batch["targets"], batch["mask"], chunk=2048))(h)
        return h + 0 * g

    report("ce_head_fused_fwd_bwd", chain_time(ce_fused_bwd, h0, a.steps))

    ft = flops_per_token(n_params, L, S, H * Dh)
    step_s = results["full_step_fused_ce"] / 1e3
    tok_s = B * S / step_s
    print(json.dumps({
        "scale": a.scale, "batch": B, "seq": S, "vocab": a.vocab,
        "params_m": round(n_params / 1e6, 1),
        "tok_s": round(tok_s, 0),
        "mfu": mfu_or_unknown(ft, tok_s),
        "breakdown_ms": {k: round(v, 2) for k, v in results.items()},
    }), flush=True)


if __name__ == "__main__":
    main()
