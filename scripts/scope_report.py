#!/usr/bin/env python
"""Offline graftscope report: alert timeline + series sparklines.

Input: a run directory that a graftscope collector (obs/scope.py) wrote
into — ``events.jsonl`` (alert/bundle events, rotation-aware via
obs/events.py) and the ``scope_tsdb/`` per-series store
(obs/tsdb.py). Prints, in ``key=value`` form:

  * an accounting line — rounds the collector completed, series
    retained, alert transitions and bundles captured;
  * the alert timeline — every pending/firing/resolved transition in
    order with the rule name and the offending value;
  * per-rule firing totals (how long each rule spent firing, how many
    distinct episodes);
  * sparklines for the headline series (``--series`` to pick your own):
    scrape health, per-instance TTFT p99, router error increase, loss.

Stdlib-only on dumped files; the in-repo package import has a repo-root
fallback so the script runs uninstalled from a checkout:

    python scripts/scope_report.py runs/myrun --series train_loss
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

try:
    from mlx_cuda_distributed_pretraining_tpu.obs import events as _events
    from mlx_cuda_distributed_pretraining_tpu.obs import tsdb as _tsdb
except ImportError:  # uninstalled checkout
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from mlx_cuda_distributed_pretraining_tpu.obs import events as _events
    from mlx_cuda_distributed_pretraining_tpu.obs import tsdb as _tsdb

# Series worth a sparkline in every report, when present. Anything
# else is reachable with --series.
DEFAULT_SERIES = (
    "graftscope_scrape_up",
    "serve_ttft_ms_p99",
    "ttft_ms_p99",
    "train_loss",
    "train_grad_norm",
    "train_mfu",
)


def load_alert_events(run_dir: str) -> List[Dict[str, Any]]:
    """alert/bundle events from the run's events.jsonl (+ rotated
    predecessor), in append order."""
    path = _events.events_path(run_dir)
    out: List[Dict[str, Any]] = []
    for ev in _events.iter_events(path):
        if ev.get("type") in ("alert", "bundle"):
            out.append(ev)
    return out


def timeline_lines(evs: List[Dict[str, Any]]) -> List[str]:
    lines = []
    for ev in evs:
        if ev.get("type") == "bundle":
            lines.append(f"t={ev.get('t')} bundle rule={ev.get('rule')} "
                         f"dir={ev.get('dir')}")
            continue
        val = ev.get("value")
        vs = f" value={val}" if val is not None else ""
        lines.append(f"t={ev.get('t')} alert rule={ev.get('rule')} "
                     f"{ev.get('from_state')}->{ev.get('to_state')}{vs}")
    return lines


def firing_totals(evs: List[Dict[str, Any]]) -> List[str]:
    """Per-rule firing episodes and total seconds spent firing.

    An episode still firing at the end of the log counts with an open
    interval (duration measured to the last event timestamp seen)."""
    open_at: Dict[str, float] = {}
    episodes: Dict[str, int] = {}
    total_s: Dict[str, float] = {}
    last_t = 0.0
    for ev in evs:
        t = float(ev.get("t", 0.0) or 0.0)
        last_t = max(last_t, t)
        if ev.get("type") != "alert":
            continue
        rule = str(ev.get("rule", "?"))
        if ev.get("to_state") == "firing":
            open_at[rule] = t
            episodes[rule] = episodes.get(rule, 0) + 1
        elif ev.get("from_state") == "firing":
            t0 = open_at.pop(rule, None)
            if t0 is not None:
                total_s[rule] = total_s.get(rule, 0.0) + (t - t0)
    for rule, t0 in open_at.items():
        total_s[rule] = total_s.get(rule, 0.0) + (last_t - t0)
    lines = []
    for rule in sorted(episodes):
        still = " still_firing=1" if rule in open_at else ""
        lines.append(f"rule={rule} episodes={episodes[rule]} "
                     f"firing_s={total_s.get(rule, 0.0):.0f}{still}")
    return lines


def series_lines(db: "_tsdb.TSDB", names: List[str],
                 width: int = 40) -> List[str]:
    """One sparkline per retained (name, labels) series matching any of
    ``names``; min/max/last annotate the glyphs."""
    lines = []
    for key in db.keys():
        name, labels = _tsdb.parse_series_key(key)
        if name not in names:
            continue
        pts = db.query(name, labels)
        if not pts:
            continue
        vals = [v for _, v in pts]
        spark = _tsdb.sparkline(vals, width=width)
        lines.append(f"series={key} n={len(vals)} min={min(vals):.4g} "
                     f"max={max(vals):.4g} last={vals[-1]:.4g} |{spark}|")
    return lines


def bundles_summary(run_dir: str) -> List[str]:
    bdir = os.path.join(run_dir, "bundles")
    if not os.path.isdir(bdir):
        return []
    lines = []
    for name in sorted(os.listdir(bdir)):
        path = os.path.join(bdir, name)
        if not os.path.isdir(path):
            continue
        members = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        rule = name
        meta = os.path.join(path, "alert.json")
        if os.path.isfile(meta):
            try:
                with open(meta) as fh:
                    rule = json.load(fh).get("alert", {}).get("rule", name)
            except (OSError, ValueError):
                pass
        lines.append(f"bundle={name} rule={rule} members={len(members)}"
                     + (f"({','.join(members)})" if members else ""))
    return lines


def report(run_dir: str, series: Optional[List[str]] = None,
           width: int = 40) -> List[str]:
    evs = load_alert_events(run_dir)
    tsdb_dir = os.path.join(run_dir, "scope_tsdb")
    db = _tsdb.TSDB(dir=tsdb_dir if os.path.isdir(tsdb_dir) else None)
    n_alerts = sum(1 for e in evs if e.get("type") == "alert")
    n_bundles = sum(1 for e in evs if e.get("type") == "bundle")
    rounds = 0
    for key in db.keys():
        name, labels = _tsdb.parse_series_key(key)
        if name == "graftscope_rounds_total":
            pts = db.query(name, labels)
            if pts:
                rounds = max(rounds, int(pts[-1][1]))
    lines = [f"run_dir={run_dir} rounds={rounds} series={len(db.keys())} "
             f"alert_transitions={n_alerts} bundles={n_bundles}"]
    lines.extend(timeline_lines(evs))
    lines.extend(firing_totals(evs))
    lines.extend(series_lines(db, list(series or DEFAULT_SERIES),
                              width=width))
    lines.extend(bundles_summary(run_dir))
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("run_dir", help="run dir a graftscope collector wrote "
                                   "(events.jsonl + scope_tsdb/)")
    p.add_argument("--series", action="append", default=None,
                   help="metric name to sparkline (repeatable; default: "
                        "the headline set)")
    p.add_argument("--width", type=int, default=40,
                   help="sparkline width in characters")
    a = p.parse_args(argv)
    if not os.path.isdir(a.run_dir):
        p.error(f"not a directory: {a.run_dir}")
    for line in report(a.run_dir, series=a.series, width=a.width):
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
