"""Attention kernel microbenchmark on the real TPU chip.

Compares the Pallas flash kernel (fwd+bwd) against XLA's fused attention
(reference_attention: einsum + softmax, fully materialized scores) across
sequence lengths, and sweeps (block_q, block_kv). The VERDICT r1 done-bar:
flash >= XLA at seq 2048/4096/8192 and seq 16k running without OOM.

Methodology: the axon tunnel makes ``block_until_ready`` a no-op and adds
~70 ms dispatch latency per call, so each measurement jits an on-device
``lax.fori_loop`` that chains N attention calls (output feeds the next
query, so nothing is DCE'd), syncs via a 1-element ``device_get``, and
reports (T(n_hi) - T(n_lo)) / (n_hi - n_lo) to cancel the fixed overhead.

Usage (on TPU):  python scripts/bench_attention.py [--sweep]
Writes results to stdout as JSON lines.
"""

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timed_loop(step, q, k, v, n_lo=5, n_hi=25):
    """step: (q, k, v) -> array shaped like q. Returns seconds per call."""

    @partial(jax.jit, static_argnums=(3,))
    def loop(q, k, v, iters):
        return jax.lax.fori_loop(0, iters, lambda i, qq: step(qq, k, v), q)

    def run(iters):
        out = loop(q, k, v, iters)
        jax.device_get(out[(0,) * (out.ndim - 1) + (slice(0, 1),)])

    run(n_lo)  # compile both shapes
    run(n_hi)
    t0 = time.perf_counter()
    run(n_lo)
    t_lo = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(n_hi)
    t_hi = time.perf_counter() - t0
    return max((t_hi - t_lo) / (n_hi - n_lo), 1e-9)


def attn_flops(B, H, Sq, Skv, D, causal=True):
    # QK^T + PV, 2 matmuls of 2*S*S*D MACs each; causal halves the work.
    f = 4.0 * B * H * Sq * Skv * D
    return f / 2 if causal else f


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", action="store_true", help="sweep block sizes")
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--head-dim", type=int, default=64)
    parser.add_argument("--heads", type=int, default=16)
    a = parser.parse_args()

    from mlx_cuda_distributed_pretraining_tpu.ops import masks as M
    from mlx_cuda_distributed_pretraining_tpu.ops.attention import reference_attention
    from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import flash_attention

    dtype = jnp.dtype(a.dtype)
    H, D = a.heads, a.head_dim
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "dtype": str(dtype), "H": H, "D": D}))

    def make_inputs(B, S, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), 3)
        shape = (B, S, H, D)
        return tuple(jax.random.normal(k, shape, dtype) for k in ks)

    def run_case(name, fn, q, k, v):
        B, S = q.shape[0], q.shape[1]

        def fwd_step(qq, kk, vv):
            return fn(qq, kk, vv)

        def bwd_step(qq, kk, vv):
            # grad wrt q has q's shape: chain it as the next query
            return jax.grad(lambda x: jnp.sum(fn(x, kk, vv).astype(jnp.float32)))(qq)

        t_f = timed_loop(fwd_step, q, k, v)
        t_b = timed_loop(bwd_step, q, k, v)
        fl = attn_flops(B, H, S, S, D)
        return {
            "name": name, "B": B, "S": S,
            "fwd_ms": round(t_f * 1e3, 3), "bwd_ms": round(t_b * 1e3, 3),
            "fwd_tflops": round(fl / t_f / 1e12, 2),
            # bwd step includes the fwd recompute + dQ/dK/dV (~3.5x fwd FLOPs)
            "bwd_tflops": round(3.5 * fl / t_b / 1e12, 2),
        }

    if a.sweep:
        # Large bkv included deliberately: KV for one head at seq 2048 is
        # only 512 KB bf16 — VMEM-resident KV (bkv == S) collapses the
        # streamed inner grid dim entirely, trading in-tile causal masking
        # work for ~8x fewer grid steps and no KV re-reads.
        for B, S in [(16, 2048), (8, 4096), (4, 8192)]:
            q, k, v = make_inputs(B, S)
            for bq in (128, 256, 512, 1024):
                for bkv in (256, 512, 1024, 2048, 4096):
                    if bkv > S or bq > S:
                        continue
                    r = run_case(
                        f"flash_bq{bq}_bkv{bkv}",
                        lambda q, k, v, bq=bq, bkv=bkv: flash_attention(
                            q, k, v, block_q=bq, block_kv=bkv),
                        q, k, v)
                    print(json.dumps(r), flush=True)
        return

    # tokens-per-batch held ~constant so memory stays bounded
    cases = [(32, 1024), (16, 2048), (8, 4096), (4, 8192), (2, 16384), (1, 32768)]
    for B, S in cases:
        q, k, v = make_inputs(B, S)
        r = run_case("flash", flash_attention, q, k, v)
        print(json.dumps(r), flush=True)
        if S <= 4096:  # XLA full-score attention OOMs/fails to compile beyond
            try:
                r = run_case("xla_fused", lambda q, k, v: reference_attention(
                    q, k, v, mask_mod=M.causal()), q, k, v)
                print(json.dumps(r), flush=True)
            except Exception as e:  # noqa: BLE001
                print(json.dumps({"name": "xla_fused", "B": B, "S": S,
                                  "error": str(e)[:160]}), flush=True)


if __name__ == "__main__":
    main()
