#!/usr/bin/env bash
# Optimizer comparison sweep on one config (replaces the reference's
# optimizer_comparison.png with reproducible CSV/JSON numbers).
set -euo pipefail
CONFIG="${1:?usage: run_compare_optimizers.sh <config.yaml> [iters]}"
ITERS="${2:-}"
ARGS=(--config "$CONFIG")
[ -n "$ITERS" ] && ARGS+=(--iters "$ITERS")
exec python -m mlx_cuda_distributed_pretraining_tpu.tools.compare_optimizers "${ARGS[@]}"
