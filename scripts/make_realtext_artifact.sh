#!/bin/bash
# Package a real-text training run into a committed artifact directory:
# loss curve + metrics CSV (from log.txt via obs/plotting.py), ppl + cloze
# eval scores (tools/evaluate.py), config, and corpus manifest.
#
# Usage: scripts/make_realtext_artifact.sh <run_dir> <out_dir> \
#            [val_jsonl] [corpus_manifest]
set -euo pipefail
RUN=${1:?run dir}
OUT=${2:?out dir}
VAL=${3:-/tmp/realrun/data/val.jsonl}
MANIFEST=${4:-/tmp/realrun/corpus.manifest.json}
REPO="$(cd "$(dirname "$0")/.." && pwd)"
# Default to CPU: the session env forces JAX_PLATFORMS=axon via a
# sitecustomize that PYTHONPATH="$REPO" displaces, which would otherwise
# leave jax pointing at an unregisterable backend. Export
# ARTIFACT_JAX_PLATFORM=tpu to eval on the chip.
PY=(env PYTHONPATH="$REPO" JAX_PLATFORMS="${ARTIFACT_JAX_PLATFORM:-cpu}" python)

mkdir -p "$OUT"
cp "$RUN/config.yaml" "$RUN/log.txt" "$OUT/"
[ -f "$MANIFEST" ] && cp "$MANIFEST" "$OUT/corpus.manifest.json"

"${PY[@]}" -m mlx_cuda_distributed_pretraining_tpu.obs.plotting "$RUN" \
  --out "$OUT/loss_curve.png"
[ -f "$RUN/metrics.csv" ] && cp "$RUN/metrics.csv" "$OUT/" || true

NAME=$(basename "$RUN")
ROOT=$(dirname "$RUN")
"${PY[@]}" -m mlx_cuda_distributed_pretraining_tpu.tools.evaluate \
  --run "$NAME" --runs-root "$ROOT" --task ppl --data "$VAL" \
  --seq-len 512 --batch-size 4 > "$OUT/eval_ppl.json"
"${PY[@]}" -m mlx_cuda_distributed_pretraining_tpu.tools.make_cloze_eval \
  "$VAL" --out "$OUT/cloze.jsonl" --n 400
"${PY[@]}" -m mlx_cuda_distributed_pretraining_tpu.tools.evaluate \
  --run "$NAME" --runs-root "$ROOT" --task mc --data "$OUT/cloze.jsonl" \
  > "$OUT/eval_cloze.json"
cat "$OUT"/eval_*.json
echo "artifact at $OUT"
