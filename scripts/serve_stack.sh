#!/usr/bin/env bash
# Launch a local serving stack: N batch-engine replicas (infer/server.py,
# prefix caching on) plus the prefix-affinity router front door
# (serve/router.py), then smoke-test one STREAMED request through the
# router and print its SSE events. PIDs land next to the logs so the
# stack can be torn down with `kill $(cat "$OUT"/*.pid)`.
#
# Usage: scripts/serve_stack.sh <run-name> [replicas] [runs_root] [base_port]
#
#   scripts/serve_stack.sh myrun 2
#   python scripts/load_gen.py --url http://127.0.0.1:8500 \
#       --shared-prefix-tokens 64 --prefix-groups 4
set -euo pipefail

RUN="${1:?usage: serve_stack.sh <run-name> [replicas] [runs_root] [base_port]}"
N="${2:-2}"
RUNS_ROOT="${3:-runs}"
BASE_PORT="${4:-8451}"
ROUTER_PORT="${5:-8500}"
OUT="$RUNS_ROOT/$RUN.serve-stack"
mkdir -p "$OUT"

URLS=""
for i in $(seq 0 $((N - 1))); do
  PORT=$((BASE_PORT + i))
  LOG="$OUT/replica-$i.log"
  nohup python -m mlx_cuda_distributed_pretraining_tpu.infer.server \
    --run "$RUN" --runs-root "$RUNS_ROOT" --engine batch \
    --port "$PORT" >"$LOG" 2>&1 &
  echo $! > "$OUT/replica-$i.pid"
  URLS="$URLS${URLS:+,}http://127.0.0.1:$PORT"
  echo "replica $i: pid=$(cat "$OUT/replica-$i.pid") port=$PORT log=$LOG"
done

# Wait for every replica to answer /healthz (first request pays the jit
# compile, so give them time).
for i in $(seq 0 $((N - 1))); do
  PORT=$((BASE_PORT + i))
  for _ in $(seq 1 120); do
    curl -sf "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1 && break
    sleep 1
  done
done

nohup python -m mlx_cuda_distributed_pretraining_tpu.serve.router \
  --replicas "$URLS" --port "$ROUTER_PORT" >"$OUT/router.log" 2>&1 &
echo $! > "$OUT/router.pid"
echo "router: pid=$(cat "$OUT/router.pid") port=$ROUTER_PORT replicas=$URLS"
for _ in $(seq 1 30); do
  curl -sf "http://127.0.0.1:$ROUTER_PORT/healthz" >/dev/null 2>&1 && break
  sleep 1
done

echo "smoke: one streamed request through the router"
curl -sN "http://127.0.0.1:$ROUTER_PORT/generate" \
  -H 'Content-Type: application/json' \
  -d '{"prompt": "the quick brown fox", "max_tokens": 8, "stream": true}'
echo
echo "stack up. tear down with: kill \$(cat $OUT/*.pid)"
