#!/usr/bin/env bash
# Launch a local serving stack: N batch-engine replicas (infer/server.py,
# prefix caching on) plus the prefix-affinity router front door
# (serve/router.py), then smoke-test one STREAMED request through the
# router and print its SSE events. PIDs land next to the logs so the
# stack can be torn down with `kill $(cat "$OUT"/*.pid)`.
#
# Usage: scripts/serve_stack.sh <run-name> [replicas] [runs_root] [base_port]
#        scripts/serve_stack.sh --fleet <run-name> [P] [M] [runs_root] [base_port]
#
#   scripts/serve_stack.sh myrun 2
#   python scripts/load_gen.py --url http://127.0.0.1:8500 \
#       --shared-prefix-tokens 64 --prefix-groups 4
#
# --fleet launches a DISAGGREGATED fleet instead: P prefill replicas +
# M decode replicas (each registering a heartbeat under the fleet
# membership dir) behind the fleet router (serve/fleet.py), which hands
# long prompts to the prefill pool, ships the KV chain to the chosen
# decode replica, and dispatches the request there:
#
#   scripts/serve_stack.sh --fleet myrun 1 1
#   python scripts/load_gen.py --url http://127.0.0.1:8500 \
#       --mix prefill-heavy:decode-heavy
set -euo pipefail

FLEET=0
if [ "${1:-}" = "--fleet" ]; then
  FLEET=1
  shift
fi
RUN="${1:?usage: serve_stack.sh [--fleet] <run-name> [replicas...] [runs_root] [base_port]}"

start_replica() { # index port role fleet_dir -> background server
  local i="$1" port="$2" role="$3" fleet_dir="$4"
  local log="$OUT/replica-$i.log"
  local extra=()
  if [ -n "$fleet_dir" ]; then
    extra=(--role "$role" --fleet-dir "$fleet_dir" --fleet-index "$i")
  fi
  nohup python -m mlx_cuda_distributed_pretraining_tpu.infer.server \
    --run "$RUN" --runs-root "$RUNS_ROOT" --engine batch \
    --port "$port" "${extra[@]}" >"$log" 2>&1 &
  echo $! > "$OUT/replica-$i.pid"
  echo "replica $i: role=$role pid=$(cat "$OUT/replica-$i.pid") port=$port log=$log"
}

wait_health() { # port [tries]
  local port="$1" tries="${2:-120}"
  for _ in $(seq 1 "$tries"); do
    curl -sf "http://127.0.0.1:$port/healthz" >/dev/null 2>&1 && return 0
    sleep 1
  done
  return 1
}

if [ "$FLEET" = "1" ]; then
  P="${2:-1}"                 # prefill replicas
  M="${3:-1}"                 # decode replicas
  RUNS_ROOT="${4:-runs}"
  BASE_PORT="${5:-8451}"
  ROUTER_PORT="${6:-8500}"
  OUT="$RUNS_ROOT/$RUN.serve-stack"
  FLEET_DIR="$OUT/fleet"
  mkdir -p "$OUT" "$FLEET_DIR"

  PRE_URLS=""; DEC_URLS=""
  for i in $(seq 0 $((P + M - 1))); do
    PORT=$((BASE_PORT + i))
    if [ "$i" -lt "$P" ]; then ROLE=prefill; else ROLE=decode; fi
    start_replica "$i" "$PORT" "$ROLE" "$FLEET_DIR"
    if [ "$ROLE" = prefill ]; then
      PRE_URLS="$PRE_URLS${PRE_URLS:+,}http://127.0.0.1:$PORT"
    else
      DEC_URLS="$DEC_URLS${DEC_URLS:+,}http://127.0.0.1:$PORT"
    fi
  done
  for i in $(seq 0 $((P + M - 1))); do
    wait_health $((BASE_PORT + i))
  done

  nohup python -m mlx_cuda_distributed_pretraining_tpu.serve.fleet \
    --prefill "$PRE_URLS" --decode "$DEC_URLS" --fleet-dir "$FLEET_DIR" \
    --port "$ROUTER_PORT" >"$OUT/router.log" 2>&1 &
  echo $! > "$OUT/router.pid"
  echo "fleet router: pid=$(cat "$OUT/router.pid") port=$ROUTER_PORT" \
       "prefill=$PRE_URLS decode=$DEC_URLS"
  wait_health "$ROUTER_PORT" 30

  echo "smoke: one streamed request through the fleet (long prompt -> handoff)"
  PROMPT=$(printf 'fleet smoke prompt %.0s' $(seq 1 8))
  curl -sN "http://127.0.0.1:$ROUTER_PORT/generate" \
    -H 'Content-Type: application/json' \
    -d "{\"prompt\": \"$PROMPT\", \"max_tokens\": 8, \"stream\": true}"
  echo
  echo "stack up. tear down with: kill \$(cat $OUT/*.pid)"
  exit 0
fi

N="${2:-2}"
RUNS_ROOT="${3:-runs}"
BASE_PORT="${4:-8451}"
ROUTER_PORT="${5:-8500}"
OUT="$RUNS_ROOT/$RUN.serve-stack"
mkdir -p "$OUT"

URLS=""
for i in $(seq 0 $((N - 1))); do
  PORT=$((BASE_PORT + i))
  start_replica "$i" "$PORT" any ""
  URLS="$URLS${URLS:+,}http://127.0.0.1:$PORT"
done

# Wait for every replica to answer /healthz (first request pays the jit
# compile, so give them time).
for i in $(seq 0 $((N - 1))); do
  wait_health $((BASE_PORT + i))
done

nohup python -m mlx_cuda_distributed_pretraining_tpu.serve.router \
  --replicas "$URLS" --port "$ROUTER_PORT" >"$OUT/router.log" 2>&1 &
echo $! > "$OUT/router.pid"
echo "router: pid=$(cat "$OUT/router.pid") port=$ROUTER_PORT replicas=$URLS"
wait_health "$ROUTER_PORT" 30

echo "smoke: one streamed request through the router"
curl -sN "http://127.0.0.1:$ROUTER_PORT/generate" \
  -H 'Content-Type: application/json' \
  -d '{"prompt": "the quick brown fox", "max_tokens": 8, "stream": true}'
echo
echo "stack up. tear down with: kill \$(cat $OUT/*.pid)"
