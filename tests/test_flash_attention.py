"""Pallas flash/flex kernel parity vs the einsum reference (SURVEY.md §4
item a): per mask type, forward and gradients, GQA/MQA, fp32.

Runs the real kernel code in Pallas interpret mode on CPU; the identical
code compiles to Mosaic on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.ops import masks as M
from mlx_cuda_distributed_pretraining_tpu.ops.attention import reference_attention
from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import flash_attention
from mlx_cuda_distributed_pretraining_tpu.ops.flex_attention import (
    alibi_score_fn,
    flex_attention,
    soft_cap_score_fn,
)

B, S, D = 2, 256, 32
BLOCK = 64


def _qkv(hq=4, hkv=4, seed=0, s=S):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, s, hq, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, s, hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, s, hkv, D)).astype(np.float32))
    return q, k, v


MASKS = {
    "causal": M.causal(),
    "sliding_window": M.sliding_window(96),
    "prefix_lm": M.prefix_lm(80),
    "full": None,
}


@pytest.mark.parametrize("mask_type", list(MASKS))
def test_forward_parity(mask_type):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, mask_type=mask_type, window_size=96,
                          prefix_len=80, block_q=BLOCK, block_kv=BLOCK)
    ref = reference_attention(q, k, v, mask_mod=MASKS[mask_type])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("hq,hkv", [(4, 2), (4, 1)])
def test_forward_parity_gqa_mqa(hq, hkv):
    q, k, v = _qkv(hq, hkv)
    out = flash_attention(q, k, v, block_q=BLOCK, block_kv=BLOCK)
    ref = reference_attention(q, k, v, mask_mod=M.causal())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("mask_type", ["causal", "sliding_window", "full"])
def test_gradient_parity(mask_type):
    q, k, v = _qkv()

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask_type=mask_type, window_size=96,
                            block_q=BLOCK, block_kv=BLOCK)
        return jnp.sum(o * jnp.cos(o))  # nontrivial cotangent

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, mask_mod=MASKS[mask_type])
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3,
                                   err_msg=f"d{name} mismatch for {mask_type}")


def test_gradient_parity_gqa():
    q, k, v = _qkv(4, 2)

    def loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v) ** 2)

        return inner

    gf = jax.grad(loss(lambda q, k, v: flash_attention(q, k, v, block_q=BLOCK, block_kv=BLOCK)),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: reference_attention(q, k, v, mask_mod=M.causal())),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3)


def test_flex_alibi_parity():
    q, k, v = _qkv()
    out = flex_attention(q, k, v, mask_mod=M.causal(), score_mod=alibi_score_fn(4),
                         block_q=BLOCK, block_kv=BLOCK)

    slopes = M.alibi_slopes(4)

    def ref_score(s, qi, ki):
        # s [B, Hkv, G, Sq, Skv] with Hkv=4, G=1
        bias = jnp.abs(qi - ki)[None, None, None]
        return s - jnp.asarray(slopes, jnp.float32)[None, :, None, None, None] * bias

    ref = reference_attention(q, k, v, mask_mod=M.causal(), score_mod=ref_score)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def _soft_cap_ref(q, k, v, cap=5.0):
    def ref_score(s, qi, ki):
        return cap * jnp.tanh(s / cap)

    return reference_attention(q, k, v, mask_mod=M.causal(), score_mod=ref_score)


def test_flex_soft_cap_forward_parity():
    q, k, v = _qkv()
    capped = flex_attention(q, k, v, mask_mod=M.causal(), score_mod=soft_cap_score_fn(5.0),
                            block_q=BLOCK, block_kv=BLOCK)
    ref = _soft_cap_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(capped), np.asarray(ref), atol=2e-5, rtol=2e-5)
    plain = flex_attention(q, k, v, mask_mod=M.causal(), block_q=BLOCK, block_kv=BLOCK)
    assert not np.allclose(np.asarray(capped), np.asarray(plain))


def test_flex_soft_cap_gradient_parity():
    """Non-additive score mod: backward must chain through the tanh
    Jacobian (regression for the missing sech^2 factor)."""
    q, k, v = _qkv()

    def loss_flex(q, k, v):
        o = flex_attention(q, k, v, mask_mod=M.causal(), score_mod=soft_cap_score_fn(5.0),
                           block_q=BLOCK, block_kv=BLOCK)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        return jnp.sum(_soft_cap_ref(q, k, v) * jnp.cos(_soft_cap_ref(q, k, v)))

    gf = jax.grad(loss_flex, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3,
                                   err_msg=f"d{name} mismatch for soft_cap")


def test_fallback_preserves_mask_and_score():
    """Odd sequence length must NOT silently drop the mask/score program."""

    def mod(q, k):
        return (q >= k) & ((k % 7) != 0)

    q, k, v = _qkv(s=100)  # 100 % 64 != 0 -> fallback path
    out = flex_attention(q, k, v, mask_mod=mod, score_mod=soft_cap_score_fn(5.0),
                         block_q=BLOCK, block_kv=BLOCK)

    def ref_score(s, qi, ki):
        return 5.0 * jnp.tanh(s / 5.0)

    ref = reference_attention(q, k, v, mask_mod=mod, score_mod=ref_score)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # ALiBi through the fallback as well (head-dependent slope)
    out_a = flex_attention(q, k, v, mask_mod=M.causal(),
                           score_mod=__import__(
                               "mlx_cuda_distributed_pretraining_tpu.ops.flex_attention",
                               fromlist=["alibi_score_fn"]).alibi_score_fn(4),
                           block_q=BLOCK, block_kv=BLOCK)
    slopes = M.alibi_slopes(4)

    def ref_alibi(s, qi, ki):
        bias = jnp.abs(qi - ki)[None, None, None]
        return s - jnp.asarray(slopes, jnp.float32)[None, :, None, None, None] * bias

    ref_a = reference_attention(q, k, v, mask_mod=M.causal(), score_mod=ref_alibi)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(ref_a), atol=2e-5, rtol=2e-5)


def test_flex_custom_mask_exact():
    """An arbitrary untagged mask mod (causal AND not-multiple-of-7 col) is
    applied exactly, not block-sampled."""

    def mod(q, k):
        return (q >= k) & ((k % 7) != 0)

    q, k, v = _qkv()
    out = flex_attention(q, k, v, mask_mod=mod, block_q=BLOCK, block_kv=BLOCK)
    ref = reference_attention(q, k, v, mask_mod=mod)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    q, k, v = _qkv()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, block_q=BLOCK, block_kv=BLOCK)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v, mask_mod=M.causal())
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref), atol=2e-2, rtol=2e-2)


def test_odd_sizes_fallback():
    """Non-tile-divisible sequence falls back to the reference path."""
    q, k, v = _qkv(s=100)
    out = flash_attention(q, k, v, block_q=BLOCK, block_kv=BLOCK)
    ref = reference_attention(q, k, v, mask_mod=M.causal())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_model_level_flash_matches_simple():
    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs

    base = LlamaArgs(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
                     max_position_embeddings=256)
    flash = LlamaArgs(**{**base.__dict__, "attention_type": "flash"})
    params = llama.init_params(jax.random.PRNGKey(0), base)
    tokens = jnp.asarray(np.random.default_rng(0).integers(1, 60, size=(2, 128)), jnp.int32)
    l_simple, _ = llama.forward(params, tokens, base)
    l_flash, _ = llama.forward(params, tokens, flash)
    np.testing.assert_allclose(np.asarray(l_simple), np.asarray(l_flash), atol=1e-3, rtol=1e-3)


def test_interior_tile_fast_path_matches():
    """canonical_mask=True (interior tiles skip in-tile masking) produces
    identical outputs to the always-masked path for every canonical mask."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.ops import masks as M
    from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import flash_fwd

    B, H, S, D = 1, 2, 512, 32
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, H, S, D), jnp.float32)
    v = jax.random.normal(kv, (B, H, S, D), jnp.float32)
    cases = [
        ("causal", M.causal(), {}),
        ("sliding_window", M.sliding_window(96), {"window": 96}),
        ("prefix_lm", M.prefix_lm(130), {"prefix_len": 130}),
    ]
    for mask_type, mask_fn, kw in cases:
        o0, l0 = flash_fwd(q, k, v, mask_fn=mask_fn, mask_type=mask_type,
                           block_q=128, block_kv=128, canonical_mask=False, **kw)
        o1, l1 = flash_fwd(q, k, v, mask_fn=mask_fn, mask_type=mask_type,
                           block_q=128, block_kv=128, canonical_mask=True, **kw)
        np.testing.assert_allclose(np.asarray(o0), np.asarray(o1), atol=1e-6,
                                   err_msg=mask_type)
        np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), atol=1e-6,
                                   err_msg=mask_type)


def test_band_mask_multiblock_matches_reference():
    """Band masks (sliding-window ring chunks) with negative/partial edges
    across MULTIPLE kv blocks — exercises empty tile ranges whose index
    maps must stay in [0, n_blocks-1] (OOB DMA regression guard)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.ops import masks as M
    from mlx_cuda_distributed_pretraining_tpu.ops.attention import reference_attention
    from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import flash_fwd

    B, H, S, D = 1, 2, 512, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, H, S, D), jnp.float32)
    v = jax.random.normal(kv, (B, H, S, D), jnp.float32)
    for t in (-384, -100, 64, 700):  # deep-negative edge, partial, beyond-S
        o, lse = flash_fwd(q, k, v, mask_type="band", window=t,
                           mask_fn=M.band(t), canonical_mask=True,
                           block_q=128, block_kv=128, scale=D ** -0.5)
        # reference with the same band mask; rows with no valid key carry
        # weight ~0 in lse -- compare only rows that have any valid key.
        ref = reference_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), mask_mod=M.band(t),
        ).transpose(0, 2, 1, 3)
        rows = np.arange(S)
        valid = rows < (S - 1 + t)  # row - col < t has a solution c <= S-1
        if valid.any():
            np.testing.assert_allclose(np.asarray(o)[:, :, valid],
                                       np.asarray(ref)[:, :, valid],
                                       atol=1e-5, err_msg=f"t={t}")
        # fully-masked rows must report lse ~ NEG_INF (zero merge weight)
        if (~valid).any():
            assert np.all(np.asarray(lse)[:, :, 0][:, :, ~valid] < -1e29)
