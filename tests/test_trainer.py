"""End-to-end training tests: loss decreases, checkpoints round-trip,
resume continues, log protocol parses (SURVEY.md §4 items c, e)."""

import json
import os

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import Config
from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer, load_trained


def _write_jsonl(path, texts):
    with open(path, "w") as f:
        for t in texts:
            f.write(json.dumps({"text": t}) + "\n")


def _tiny_config(tmp_path, name="tiny", iters=30, **extra):
    train = tmp_path / "train.jsonl"
    val = tmp_path / "val.jsonl"
    corpus = ["the quick brown fox jumps over the lazy dog " * 4] * 40
    _write_jsonl(train, corpus)
    _write_jsonl(val, corpus[:10])
    d = {
        "name": name,
        "overwrite": True,
        "data": {
            "input_file": str(train),
            "validation_file": str(val),
            "preprocessing": {"max_context_size": 64},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2, "iters": iters},
            "scheduler": {"type": "cosine", "min_lr_ratio": 0.1},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "log_dir": "logs",
            "checkpoint_dir": "checkpoints",
            "steps": {"logging_interval": 5, "checkpoint_interval": 15, "validation_interval": 10},
        },
        "system": {"seed": 0, "device": "cpu"},
    }
    for k, v in extra.items():
        node = d
        parts = k.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return Config.from_dict(d)


def test_train_loss_decreases_and_logs(tmp_path):
    cfg = _tiny_config(tmp_path)
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    result = tr.train()
    assert result["steps"] == 30
    # loss must drop substantially on this trivially learnable corpus
    log = open(os.path.join(tr.run_dir, "log.txt")).read()
    first_loss = None
    for line in log.splitlines():
        if line.startswith("Step") and "loss=" in line and "validation" not in line:
            loss = float(line.split("loss=")[1].split(" |")[0])
            if first_loss is None:
                first_loss = loss
    assert first_loss is not None
    assert result["final_loss"] < first_loss * 0.7

    # log protocol parses the reference way (utils/plotting.py:27-47)
    steps = []
    for line in log.splitlines():
        if line.startswith("Step") and "validation:" not in line and "loss=" in line:
            steps.append(int(line.split()[1][:-1]))
            assert "toks=" in line
    assert steps and steps[-1] == 30
    assert "validation: val_loss=" in log

    # run dir layout (reference: core/training.py:169-195)
    assert os.path.isfile(os.path.join(tr.run_dir, "config.yaml"))
    assert os.path.isfile(os.path.join(tr.run_dir, "metadata.json"))
    assert os.path.isdir(os.path.join(tr.run_dir, "tokenizer"))
    ckpts = os.listdir(os.path.join(tr.run_dir, "checkpoints"))
    assert "step_final_model.safetensors" in ckpts
    assert "step_15_state.json" in ckpts


@pytest.mark.slow
def test_resume_continues(tmp_path):
    cfg = _tiny_config(tmp_path, name="resumable", iters=15)
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()

    cfg2 = _tiny_config(tmp_path, name="resumable", iters=25)
    cfg2_dict = cfg2.to_dict()
    cfg2_dict["overwrite"] = False
    cfg2_dict["resume"] = {"checkpoint": "15"}
    cfg2 = Config.from_dict(cfg2_dict)
    tr2 = Trainer(cfg2, runs_root=str(tmp_path / "runs"), quiet=True)
    assert tr2.start_step == 15
    result = tr2.train()
    assert result["steps"] == 25

    # resumed params differ from a fresh init (training continued)
    log = open(os.path.join(tr2.run_dir, "log.txt")).read()
    assert "Resumed from checkpoint 15" in log


@pytest.mark.slow
def test_resume_reset_optimizer(tmp_path):
    cfg = _tiny_config(tmp_path, name="reset", iters=10)
    Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True).train()
    d = cfg.to_dict()
    d["overwrite"] = False
    d["resume"] = {"checkpoint": "final", "reset_optimizer": True, "reset_training_state": True}
    d["training"]["hyperparameters"]["iters"] = 5
    tr = Trainer(Config.from_dict(d), runs_root=str(tmp_path / "runs"), quiet=True)
    assert tr.start_step == 0
    tr.train()


def test_load_trained_and_generate(tmp_path):
    cfg = _tiny_config(tmp_path, name="gen", iters=25)
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()
    params, args, tok, _ = load_trained("gen", runs_root=str(tmp_path / "runs"))
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import generate_text

    text = generate_text(params, args, tok, "the quick brown", max_new_tokens=8)
    assert isinstance(text, str)


@pytest.mark.slow
def test_grad_accumulation_equivalence(tmp_path):
    """accum=2 with bs=4 must match accum=1 with bs=4 on the same data
    (same total batch, scan-accumulated grads averaged)."""
    cfg_a = _tiny_config(tmp_path, name="acc1", iters=3)
    tr_a = Trainer(cfg_a, runs_root=str(tmp_path / "runs"), quiet=True)
    cfg_b = _tiny_config(
        tmp_path, name="acc2", iters=3,
        **{"training.hyperparameters.gradient_accumulation_steps": 2},
    )
    tr_b = Trainer(cfg_b, runs_root=str(tmp_path / "runs"), quiet=True)
    tr_a.train()
    tr_b.train()
    pa = tr_a.state["params"]["layers"][0]["attention"]["wq"]["weight"]
    pb = tr_b.state["params"]["layers"][0]["attention"]["wq"]["weight"]
    np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), atol=2e-4)


def test_early_stopping(tmp_path):
    cfg = _tiny_config(
        tmp_path, name="es", iters=40,
        **{
            "training.early_stopping": {"enabled": True, "patience": 1, "min_delta": 10.0},
            "logging.steps": {"logging_interval": 5, "checkpoint_interval": 0, "validation_interval": 5},
        },
    )
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    result = tr.train()
    # min_delta=10 means "never improves" -> stops after patience*interval
    assert result["steps"] < 40


def test_mixed_precision_and_remat_run(tmp_path):
    cfg = _tiny_config(
        tmp_path, name="bf16", iters=5,
        **{"system.mixed_precision": True, "system.gradient_checkpointing": True},
    )
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    result = tr.train()
    assert np.isfinite(result["final_loss"])


@pytest.mark.slow
def test_lr_finder(tmp_path):
    cfg = _tiny_config(
        tmp_path, name="lrf", iters=3,
        **{"training.lr_finder": {"enabled": True, "min_lr": 1e-5, "max_lr": 1.0, "num_steps": 15}},
    )
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()
    assert os.path.isfile(os.path.join(tr.run_dir, "lr_finder.csv"))


def test_sigterm_saves_checkpoint_and_exits(tmp_path):
    """Preemption-aware checkpointing: SIGTERM mid-run saves and stops."""
    import signal
    import threading

    cfg = _tiny_config(tmp_path, name="preempt", iters=100000,
                       **{"logging.steps.checkpoint_interval": 100000,
                          "logging.steps.validation_interval": 0})
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    killer = threading.Timer(3.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    killer.start()
    result = tr.train()
    killer.cancel()
    assert result["steps"] < 100000  # stopped early
    log = open(os.path.join(tr.run_dir, "log.txt")).read()
    assert "Preemption signal received" in log
    ckpts = os.listdir(os.path.join(tr.run_dir, "checkpoints"))
    # both the preemption checkpoint and the final save exist
    assert any(c.startswith("step_") and c.endswith("_model.safetensors") for c in ckpts)
    assert "step_final_model.safetensors" in ckpts


def test_profiler_trace_window(tmp_path):
    cfg = _tiny_config(tmp_path, name="prof", iters=6,
                       **{"logging.steps.validation_interval": 0,
                          "logging.profile_start": 2,
                          "logging.profile_stop": 4})
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()
    prof_dir = os.path.join(tr.run_dir, "profile")
    assert os.path.isdir(prof_dir)
    found = []
    for root, _, files in os.walk(prof_dir):
        found.extend(files)
    assert found, "profiler produced no trace files"
    log = open(os.path.join(tr.run_dir, "log.txt")).read()
    assert "profiler: trace started at step 2" in log


def test_lr_finder_for_optimizer_uses_real_update_rule(tmp_path):
    """Per-optimizer sweep (VERDICT r3 #5): the finder runs the actual
    optimizer (built with an exponential LR schedule), so different
    optimizers can get different suggestions from identical params/data."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.train.lr_finder import (
        run_lr_finder_for_optimizer,
    )

    rng = np.random.default_rng(0)
    w_true = rng.standard_normal((8, 1)).astype(np.float32)
    params = {"w": jnp.zeros((8, 1), jnp.float32)}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2), jnp.float32(1.0)

    def batch_iter(i):
        x = rng.standard_normal((16, 8)).astype(np.float32)
        return {"x": jnp.asarray(x), "y": jnp.asarray(x @ w_true)}

    tr_cfg = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3, "weight_decay": 0.0,
                         "gradient_clip": 1.0},
        scheduler={"type": "cosine", "min_lr_ratio": 0.1},
        optimization={"optimizer": "adamw"},
    )
    out = {}
    for opt in ("adamw", "lion", "muon"):
        suggested, lrs, losses = run_lr_finder_for_optimizer(
            params, loss_fn, batch_iter, tr_cfg, opt,
            min_lr=1e-5, max_lr=10.0, num_steps=25,
            out_dir=str(tmp_path / opt))
        assert np.isfinite(suggested) and suggested > 0
        assert len(lrs) == len(losses) > 4
        assert os.path.isfile(os.path.join(str(tmp_path / opt), "lr_finder.csv"))
        out[opt] = suggested
    # The sweep must actually move loss (the real optimizer stepped) ...
    assert losses[2] != losses[0]
    # ... and the suggestions must be optimizer-specific: if the sweep
    # ignored optimizer_name all three would come out identical.
    assert len(set(out.values())) >= 2, out


@pytest.mark.slow
def test_benchmark_inference_tool(tmp_path):
    """tools/benchmark_inference: runs all modes on a trained run, reports
    per-mode tok/s, and certifies speculative outputs identical to plain."""
    import json

    from mlx_cuda_distributed_pretraining_tpu.tools import benchmark_inference

    cfg = _tiny_config(tmp_path, name="infbench", iters=20)
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()

    report = benchmark_inference.main([
        "--run", "infbench", "--runs-root", str(tmp_path / "runs"),
        "--prompts", str(tmp_path / "val.jsonl"),
        "--n-prompts", "2", "--max-tokens", "12", "--prompt-chars", "80",
    ])
    modes = {r["mode"]: r for r in report["results"]}
    assert set(modes) == {"plain", "spec", "wq", "spec+wq"}
    assert all(r["tok_s"] > 0 for r in report["results"])
    assert report["agreement"]["spec_vs_plain_identical"] == "2/2"
    # report is printable JSON
    json.dumps(report)


@pytest.mark.slow
def test_adafactor_checkpoint_resume(tmp_path):
    """Adafactor's factored state (row/col vectors + (1,) placeholders)
    round-trips through save/resume."""
    cfg = _tiny_config(tmp_path, name="af", iters=10,
                       **{"training.optimization.optimizer": "adafactor"})
    Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True).train()
    d = cfg.to_dict()
    d["overwrite"] = False
    d["resume"] = {"checkpoint": "final"}
    d["training"]["hyperparameters"]["iters"] = 15
    tr = Trainer(Config.from_dict(d), runs_root=str(tmp_path / "runs"),
                 quiet=True)
    assert tr.start_step == 10
    result = tr.train()
    assert result["steps"] == 15 and np.isfinite(result["final_loss"])


@pytest.mark.slow
def test_steps_per_dispatch_equivalence(tmp_path):
    """K steps scanned into one dispatch must match K dispatched steps
    exactly (same data order, same schedule counters), with per-step log
    lines and checkpoint/validation steps unchanged — group boundaries
    must align to the interval events (reference has no analog: this
    amortizes host->device dispatch latency, train/train_step.py
    make_multi_step)."""
    cfg_a = _tiny_config(tmp_path, name="spd1", iters=12)
    tr_a = Trainer(cfg_a, runs_root=str(tmp_path / "runs"), quiet=True)
    cfg_b = _tiny_config(
        tmp_path, name="spd4", iters=12,
        **{"system.steps_per_dispatch": 4},
    )
    tr_b = Trainer(cfg_b, runs_root=str(tmp_path / "runs"), quiet=True)
    ra = tr_a.train()
    rb = tr_b.train()
    assert ra["steps"] == rb["steps"] == 12
    pa = tr_a.state["params"]["layers"][0]["attention"]["wq"]["weight"]
    pb = tr_b.state["params"]["layers"][0]["attention"]["wq"]["weight"]
    np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), atol=1e-6)

    # identical per-step log protocol: same Step lines at the same steps,
    # same losses (bitwise-equal data and math up to reduction order)
    def step_lines(run_dir):
        out = {}
        for line in open(os.path.join(run_dir, "log.txt")).read().splitlines():
            if line.startswith("Step") and "loss=" in line and "validation" not in line:
                step = int(line.split()[1].rstrip(":"))
                out[step] = float(line.split("loss=")[1].split(" |")[0])
        return out

    la, lb = step_lines(tr_a.run_dir), step_lines(tr_b.run_dir)
    assert set(la) == set(lb)
    for s in la:
        assert abs(la[s] - lb[s]) < 1e-4, (s, la[s], lb[s])

    # checkpoint set unchanged: interval boundaries never straddled
    ca = sorted(os.listdir(os.path.join(tr_a.run_dir, "checkpoints")))
    cb = sorted(os.listdir(os.path.join(tr_b.run_dir, "checkpoints")))
    assert ca == cb


@pytest.mark.slow
def test_inference_http_server(tmp_path):
    """Train a tiny run, serve it over HTTP (infer/server.py — the
    platform-free analog of the reference's Modal deploy/client apps),
    and round-trip generation + health through the client helper."""
    import urllib.request

    from mlx_cuda_distributed_pretraining_tpu.infer.server import (
        InferenceService,
        request_generate,
        serve,
    )

    cfg = _tiny_config(tmp_path, name="srv", iters=12)
    Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True).train()

    service = InferenceService.from_run("srv", runs_root=str(tmp_path / "runs"))
    httpd = serve(service, port=0)  # free port
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["params_m"] > 0

        out = request_generate(url, "the quick brown", max_tokens=8)
        assert isinstance(out["text"], str)
        assert out["tokens"] >= 1 and "generation_tps" in out

        # sampling params flow through; a bad request is a 400, not a crash
        out2 = request_generate(url, "the", max_tokens=4, temperature=0.8,
                                top_p=0.9, seed=7)
        assert out2["tokens"] >= 1
        import urllib.error
        try:
            body = json.dumps({"nope": 1}).encode()
            req = urllib.request.Request(
                url + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=30)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_finish_reason_eos_at_budget():
    """A generation that hits EOS exactly at the token budget is a 'stop',
    not a 'length' (ADVICE r4): the generator's stopped_on_token flag wins
    over the completion_tokens >= budget heuristic."""
    from mlx_cuda_distributed_pretraining_tpu.infer.server import (
        _to_openai_completion,
    )

    base = {"text": "hello", "tokens": 6, "generation_tps": 1.0,
            "prompt_tokens": 2.0}
    eos_at_budget = _to_openai_completion(
        dict(base, stopped_on_token=1.0), {}, "run", effective_max=6)
    assert eos_at_budget["choices"][0]["finish_reason"] == "stop"
    ran_out = _to_openai_completion(
        dict(base, stopped_on_token=0.0), {}, "run", effective_max=6)
    assert ran_out["choices"][0]["finish_reason"] == "length"


def test_openai_completions_route(tmp_path):
    """/v1/completions maps the native generate result onto the OpenAI
    completions shape (choices/usage/finish_reason, stop-string
    truncation) so OpenAI-client tooling can point at the server."""
    import urllib.request

    from mlx_cuda_distributed_pretraining_tpu.infer.server import (
        InferenceService,
        serve,
    )

    cfg = _tiny_config(tmp_path, name="oai", iters=8)
    Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True).train()
    service = InferenceService.from_run("oai", runs_root=str(tmp_path / "runs"))
    httpd = serve(service, port=0)
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/v1/completions"
        body = json.dumps({"prompt": "the quick", "max_tokens": 6,
                           "stop": [" "]}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["object"] == "text_completion"
        choice = out["choices"][0]
        assert choice["finish_reason"] in ("stop", "length")
        assert " " not in choice["text"]  # stop-string truncation applied
        u = out["usage"]
        assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]
        # usage counts the RETURNED text: stop-truncation may cut it to 0
        assert 0 <= u["completion_tokens"] <= 6
        assert out["id"].startswith("cmpl-")

        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        with urllib.request.urlopen(base + "/v1/models", timeout=30) as r:
            models = json.loads(r.read())
        assert models["object"] == "list"
        entry = models["data"][0]
        assert entry["id"] == "oai"
        # required by the OpenAI SDK's Model pydantic type
        assert isinstance(entry["created"], int) and entry["owned_by"]
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_server_speculative_mode(tmp_path):
    """--spec serving: greedy requests ride prompt-lookup speculation
    (bit-identical text to plain greedy), while requests using sampler
    knobs the acceptance rule can't honor fall back to plain decode."""
    from mlx_cuda_distributed_pretraining_tpu.infer.server import (
        InferenceService,
        request_generate,
        serve,
    )

    cfg = _tiny_config(tmp_path, name="specsrv", iters=10)
    Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True).train()
    plain = InferenceService.from_run("specsrv", runs_root=str(tmp_path / "runs"))
    spec = InferenceService.from_run("specsrv", runs_root=str(tmp_path / "runs"),
                                     speculative=True)
    httpd = serve(spec, port=0)
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        out_spec = request_generate(url, "the quick brown fox", max_tokens=12)
        assert out_spec["speculative"] is True
        assert "verify_calls" in out_spec
        # bit-identical to plain greedy decode on the same run
        out_plain = plain.generate("the quick brown fox", max_tokens=12)
        assert out_spec["text"] == out_plain["text"]
        # sampler knobs force the plain path
        out_tp = request_generate(url, "the", max_tokens=4, top_p=0.9,
                                  temperature=0.8)
        assert out_tp["speculative"] is False
    finally:
        httpd.shutdown()
        httpd.server_close()
