"""Ring attention (sequence/context parallelism) on the virtual 8-CPU mesh:
exact parity with single-device attention, gradients included."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_cuda_distributed_pretraining_tpu.config import SystemConfig
from mlx_cuda_distributed_pretraining_tpu.ops import masks as M
from mlx_cuda_distributed_pretraining_tpu.ops.attention import reference_attention
from mlx_cuda_distributed_pretraining_tpu.ops.ring_attention import make_ring_attention
from mlx_cuda_distributed_pretraining_tpu.parallel import build_mesh


def _mesh(cfg):
    return build_mesh(SystemConfig(seed=0, device="cpu", mesh=cfg))


def _qkv(hq=4, hkv=4, b=2, s=64, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        for h in (hq, hkv, hkv)
    )


def test_ring_matches_reference_causal():
    mesh = _mesh({"sp": 8})
    q, k, v = _qkv()
    ring = make_ring_attention(mesh, mask_mod=M.causal())
    out = jax.jit(ring)(q, k, v)
    ref = reference_attention(q, k, v, mask_mod=M.causal())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_gqa_and_dp_axis():
    mesh = _mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(hq=4, hkv=2)
    ring = make_ring_attention(mesh, mask_mod=M.causal())
    out = jax.jit(ring)(q, k, v)
    ref = reference_attention(q, k, v, mask_mod=M.causal())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_sliding_window():
    mesh = _mesh({"sp": 4})
    q, k, v = _qkv(s=64)
    ring = make_ring_attention(mesh, mask_mod=M.sliding_window(24))
    out = jax.jit(ring)(q, k, v)
    ref = reference_attention(q, k, v, mask_mod=M.sliding_window(24))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_gradients_match():
    mesh = _mesh({"sp": 4})
    q, k, v = _qkv(s=32)
    ring = make_ring_attention(mesh, mask_mod=M.causal())

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, mask_mod=M.causal()) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_ring_model_level_end_to_end():
    """Full model with attention_type='ring' on an sp mesh == simple
    attention single device, and a sharded train step executes."""
    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
    from mlx_cuda_distributed_pretraining_tpu.parallel.context import use_mesh
    from mlx_cuda_distributed_pretraining_tpu.train.train_step import (
        init_train_state,
        make_train_step,
    )

    base = LlamaArgs(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
                     max_position_embeddings=64)
    ring_args = LlamaArgs(**{**base.__dict__, "attention_type": "ring"})
    params = llama.init_params(jax.random.PRNGKey(0), base)
    tokens = jnp.asarray(np.random.default_rng(0).integers(1, 60, (4, 32)), jnp.int32)

    mesh = _mesh({"dp": 2, "sp": 4})
    with use_mesh(mesh):
        logits_ring, _ = jax.jit(
            lambda p, t: llama.forward(p, t, ring_args))(params, tokens)
    logits_ref, _ = llama.forward(params, tokens, base)
    np.testing.assert_allclose(np.asarray(logits_ring), np.asarray(logits_ref),
                               atol=2e-4, rtol=2e-4)

    # full sharded train step with sp axis
    tr_cfg = TrainingConfig(hyperparameters={"learning_rate": 1e-2},
                            optimization={"optimizer": "adamw"})
    opt = build_optimizer(tr_cfg, 10)
    with use_mesh(mesh):
        step, shardings = make_train_step(
            lambda p, b: llama.loss_fn(p, b, ring_args), opt,
            mesh=mesh, params_like=params)
        state = jax.device_put(init_train_state(params, opt), shardings)
        batch = {
            "inputs": tokens,
            "targets": jnp.roll(tokens, -1, axis=1),
            "mask": jnp.ones((4, 32), jnp.float32),
        }
        state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_ring_non_divisible_shard_falls_back_exactly():
    """S_local not tileable by the Pallas blocks (e.g. 24 rows) must route
    to the exact jnp path, not silently truncate (r2 review finding)."""
    mesh = _mesh({"sp": 4})
    q, k, v = _qkv(s=768)  # S_local = 192: fit_block gives 128, 192 % 128 != 0
    ring = make_ring_attention(mesh, mask_mod=M.causal())
    out = jax.jit(ring)(q, k, v)
    ref = reference_attention(q, k, v, mask_mod=M.causal())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_flash_raw_entries_reject_non_divisible():
    import pytest as _pytest

    from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import flash_fwd

    q = jnp.zeros((1, 2, 640, 16), jnp.float32)
    with _pytest.raises(ValueError, match="block-divisible"):
        flash_fwd(q, q, q, block_q=256, block_kv=256)


@pytest.mark.slow
def test_ring_sliding_window_tiled_grads_match():
    """The statically-unrolled tiled sliding-window ring (fwd+bwd custom
    VJP) matches single-device reference gradients, across window sizes
    that hit all three chunk kinds (diagonal / full / band) and the
    early-rotation-stop path (window < S_local)."""
    mesh = _mesh({"sp": 4})
    for window in (8, 24, 40, 64):  # Sl=16: early-stop, band, full+band, all-full
        q, k, v = _qkv(s=64, seed=window)
        ring = make_ring_attention(mesh, mask_mod=M.sliding_window(window))

        def loss_ring(q, k, v):
            return (jax.jit(ring)(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, mask_mod=M.sliding_window(window)) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=1e-4,
                                       err_msg=f"window={window}")


def test_ring_sliding_window_gqa():
    mesh = _mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(hq=4, hkv=2, s=64)
    ring = make_ring_attention(mesh, mask_mod=M.sliding_window(20))
    out = jax.jit(ring)(q, k, v)
    ref = reference_attention(q, k, v, mask_mod=M.sliding_window(20))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_live_hops_formula():
    """The public early-stop bound matches the kernel's static unroll:
    full causal rings visit all sp chunks; a window smaller than the local
    shard stops after ~2 hops regardless of total sequence length."""
    from mlx_cuda_distributed_pretraining_tpu.ops.ring_attention import ring_live_hops

    assert ring_live_hops(4, 64, None) == 4        # full causal: no early stop
    assert ring_live_hops(4, 64, 96) == 3          # dryrun phase D
    assert ring_live_hops(4, 8192, 1024) == 2      # dryrun phase E (32k/sp4)
    assert ring_live_hops(8, 4096, 1024) == 2      # the 32k/sp8 pitch
    assert ring_live_hops(2, 16, 1000) == 2        # clamped to sp
    # Edge: a row's furthest visible key is window-1 back, so distance-2
    # chunks only come alive once window >= seq_local + 2.
    assert ring_live_hops(4, 64, 64) == 2
    assert ring_live_hops(4, 64, 65) == 2
    assert ring_live_hops(4, 64, 66) == 3
