"""Auto-resume supervisor tests.

Fast tier drives the restart loop with stub children (no jax import in
the child): a tiny ``python -c`` script that consults an attempt counter
and a behavior plan ("crash", "ckpt+crash", "ok", "sleep"), writing
hand-rolled but manifest-valid checkpoints when asked. The slow-tier
chaos test runs REAL CPU training under the supervisor and SIGKILLs it
at ≥3 random points, then asserts the run completes with the same
per-step losses as an uninterrupted baseline — checkpoint-resume replay
is exact (data batches are a pure function of step, optimizer state
round-trips float32-exact).
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

import pytest

from mlx_cuda_distributed_pretraining_tpu.train.supervisor import (
    CrashLoopError,
    Supervisor,
    _trainer_cmd_builder,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Stub child: argv = [run_dir, plan]. Reads/bumps an attempt counter,
# then acts out plan[attempt]: "crash" exits 1; "ckpt+crash" first writes
# a checkpoint (real manifest: bytes + crc32) for step=attempt+1; "ok"
# exits 0; "sleep" hangs until signaled. No jax import — fast.
_STUB = r"""
import json, os, sys, time, zlib
run_dir, plan = sys.argv[1], sys.argv[2].split(",")
cnt = os.path.join(run_dir, "attempt")
n = int(open(cnt).read()) if os.path.exists(cnt) else 0
open(cnt, "w").write(str(n + 1))
action = plan[min(n, len(plan) - 1)]
if action == "sleep":
    time.sleep(120)
    sys.exit(1)
if action.startswith("ckpt"):
    ckdir = os.path.join(run_dir, "checkpoints")
    os.makedirs(ckdir, exist_ok=True)
    step = n + 1
    data = ("model-bytes-%d" % step).encode()
    name = "step_%d_model.safetensors" % step
    open(os.path.join(ckdir, name), "wb").write(data)
    manifest = {"format_version": 1, "step": step, "written_at": float(step),
                "artifacts": {name: {"bytes": len(data),
                                     "crc32": zlib.crc32(data)}}}
    with open(os.path.join(ckdir, "step_%d.manifest.json" % step), "w") as f:
        json.dump(manifest, f)
sys.exit(0 if action.endswith("ok") else 1)
"""


def _stub_supervisor(tmp_path, plan, **kw):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)
    resume_tags = []

    def build_cmd(tag):
        resume_tags.append(tag)
        return [sys.executable, "-c", _STUB, run_dir, plan]

    sup = Supervisor(build_cmd, run_dir, backoff_base=0.01, backoff_max=0.05,
                     log=lambda m: None, **kw)
    return sup, resume_tags


def test_restarts_until_success_and_resumes_from_new_checkpoint(tmp_path):
    sup, tags = _stub_supervisor(tmp_path, "crash,ckpt+crash,ok")
    assert sup.run() == 0
    assert sup.restarts == 2
    # launch 1 fresh, launch 2 fresh (no ckpt yet), launch 3 resumes from
    # the step-2 checkpoint attempt 2 wrote before crashing
    assert tags == [None, None, "2"]


def test_crash_loop_gives_up_after_max_crashes(tmp_path):
    sup, tags = _stub_supervisor(tmp_path, "crash", max_crashes_per_step=3)
    with pytest.raises(CrashLoopError, match="3 consecutive crashes"):
        sup.run()
    assert len(tags) == 3  # exactly max_crashes launches, then give up


def test_checkpoint_progress_resets_crash_counter(tmp_path):
    # two no-progress crashes (counter at 2/3), then a crash WITH a new
    # checkpoint (counter resets to 1), another no-progress crash (2/3),
    # then success. Without the progress reset the third crash would be
    # 3/3 and raise CrashLoopError before ever reaching "ok".
    sup, tags = _stub_supervisor(
        tmp_path, "crash,crash,ckpt+crash,crash,ok", max_crashes_per_step=3)
    assert sup.run() == 0
    assert sup.restarts == 4
    assert tags[-1] == "3"


def test_forwarded_sigterm_stops_without_restart(tmp_path):
    sup, tags = _stub_supervisor(tmp_path, "sleep")

    def on_spawn(child):
        # handler is installed before the first launch; deliver the
        # preemption signal to the SUPERVISOR process once the child runs
        threading.Timer(
            0.2, lambda: os.kill(os.getpid(), signal.SIGTERM)).start()

    sup.on_spawn = on_spawn
    rc = sup.run()
    assert rc != 0  # child was terminated by the forwarded signal
    assert sup.restarts == 0
    assert len(tags) == 1


def test_latest_resumable_skips_corrupt_newest(tmp_path):
    run_dir = str(tmp_path / "run")
    ckdir = os.path.join(run_dir, "checkpoints")
    os.makedirs(ckdir)
    import zlib

    for step in (1, 2):
        data = f"model-bytes-{step}".encode()
        name = f"step_{step}_model.safetensors"
        with open(os.path.join(ckdir, name), "wb") as f:
            f.write(data)
        with open(os.path.join(ckdir, f"step_{step}.manifest.json"), "w") as f:
            json.dump({"format_version": 1, "step": step, "written_at": 0.0,
                       "artifacts": {name: {"bytes": len(data),
                                            "crc32": zlib.crc32(data)}}}, f)
    # tear the newest one (as a kill -9 mid-write would)
    with open(os.path.join(ckdir, "step_2_model.safetensors"), "wb") as f:
        f.write(b"xx")
    sup = Supervisor(lambda tag: ["true"], run_dir, log=lambda m: None)
    assert sup.latest_resumable() == "1"
    assert os.path.isdir(os.path.join(ckdir, "quarantine"))


def test_scan_oserror_raises_instead_of_treating_as_fresh(tmp_path, monkeypatch):
    """A transient scan failure must never read as "no checkpoints" — the
    fresh launch it would trigger could discard the run's recovery state.
    After retries, the error propagates."""
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointManager,
    )

    calls = {"n": 0}

    def boom(self):
        calls["n"] += 1
        raise OSError("stale NFS handle")

    monkeypatch.setattr(CheckpointManager, "latest_complete_step", boom)
    sup = Supervisor(lambda tag: ["true"], str(tmp_path / "run"),
                     backoff_base=0.001, backoff_max=0.002, log=lambda m: None)
    with pytest.raises(OSError, match="stale NFS handle"):
        sup.latest_resumable()
    assert calls["n"] == 3  # retried before giving up


def _builder_args(cfg_path, root, name):
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import build_parser

    return build_parser().parse_args(
        ["--config", str(cfg_path), "--runs-root", str(root),
         "--run-name", name])


def test_fresh_restart_never_overwrites_dir_with_checkpoint_data(tmp_path):
    """REGRESSION: a fresh launch (no verified tag) on a run dir whose
    checkpoints dir is non-empty (quarantine forensics, legacy files, a
    step the scan couldn't vouch for) must NOT pass overwrite=true — that
    rmtree's the whole run dir. It launches in resume mode instead."""
    run_dir = tmp_path / "runs" / "r"
    qdir = run_dir / "checkpoints" / "quarantine"
    os.makedirs(qdir)
    (qdir / "step_7.reason.txt").write_text("crc32 mismatch")

    build = _trainer_cmd_builder(
        _builder_args(tmp_path / "c.yaml", tmp_path / "runs", "r"),
        str(run_dir))
    cmd = build(None)
    assert "overwrite=true" not in cmd
    assert "overwrite=false" in cmd
    assert "resume.checkpoint=latest" in cmd


def test_fresh_start_overwrites_only_without_checkpoint_data(tmp_path):
    run_dir = tmp_path / "runs" / "r"
    build = _trainer_cmd_builder(
        _builder_args(tmp_path / "c.yaml", tmp_path / "runs", "r"),
        str(run_dir))
    # run dir doesn't exist at all
    assert "overwrite=true" in build(None)
    # exists but checkpoints dir is empty (crash before first checkpoint)
    os.makedirs(run_dir / "checkpoints")
    assert "overwrite=true" in build(None)
    # a verified tag always wins
    cmd = build("42")
    assert "resume.checkpoint=42" in cmd and "overwrite=false" in cmd


# --- slow tier: real training, real kill -9 --------------------------------

def _child_env():
    from conftest import device_env

    return device_env(1)


def _write_chaos_config(tmp_path, iters):
    import yaml

    train = tmp_path / "train.jsonl"
    with open(train, "w") as f:
        for _ in range(40):
            f.write(json.dumps(
                {"text": "the quick brown fox jumps over the lazy dog " * 4}) + "\n")
    cfg = {
        "name": "placeholder",
        "overwrite": True,
        "data": {
            "input_file": str(train),
            "preprocessing": {"max_context_size": 64},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64,
                           "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2,
                                "iters": iters},
            "scheduler": {"type": "cosine", "min_lr_ratio": 0.1},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "steps": {"logging_interval": 1, "checkpoint_interval": 5,
                      "validation_interval": 0},
        },
        "system": {"seed": 0, "device": "cpu"},
    }
    path = tmp_path / "chaos.yaml"
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    return str(path)


def _step_losses(run_dir):
    out = {}
    with open(os.path.join(run_dir, "log.txt")) as f:
        for line in f.read().splitlines():
            if line.startswith("Step") and "loss=" in line and "validation" not in line:
                step = int(line.split()[1].rstrip(":"))
                out[step] = float(line.split("loss=")[1].split(" |")[0])
    return out


def _manifest_count(ckdir):
    if not os.path.isdir(ckdir):
        return 0
    return sum(1 for n in os.listdir(ckdir) if n.endswith(".manifest.json"))


@pytest.mark.slow
def test_chaos_kill9_training_completes_and_matches_baseline(tmp_path):
    """The ISSUE's acceptance chaos drill: kill -9 a real CPU training
    subprocess at >=3 random points; the supervisor must drive the run to
    completion, and the trajectory must MATCH an uninterrupted baseline —
    same final step, same per-step losses (resume replays exactly)."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import build_parser

    iters = 300
    cfg_path = _write_chaos_config(tmp_path, iters)
    root = str(tmp_path / "runs")
    env = _child_env()

    # -- uninterrupted baseline (same subprocess env as the chaos children,
    # so XLA device count and numerics are identical)
    proc = subprocess.run(
        [sys.executable, "-m", "mlx_cuda_distributed_pretraining_tpu.train.trainer",
         "--config", cfg_path, "--runs-root", root, "--run-name", "base"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    base = _step_losses(os.path.join(root, "base"))
    assert max(base) == iters

    # -- chaos run under the supervisor
    args = build_parser().parse_args(
        ["--config", cfg_path, "--runs-root", root, "--run-name", "chaos",
         "--auto-resume", "--max-crashes", "3",
         "--backoff-base", "0.05", "--backoff-max", "0.2"])
    run_dir = os.path.join(root, "chaos")
    ckdir = os.path.join(run_dir, "checkpoints")
    rng = random.Random(0)
    kills = {"done": 0}

    def on_spawn(child):
        if kills["done"] >= 3:
            return  # let the last incarnation run to completion
        at_spawn = _manifest_count(ckdir)

        def watch():
            # kill -9 shortly after the child commits a NEW checkpoint: a
            # random point inside the next interval, never after the final
            # save (a post-completion kill would test nothing)
            while child.poll() is None:
                if os.path.isfile(os.path.join(ckdir, "step_final.manifest.json")):
                    return
                if _manifest_count(ckdir) > at_spawn:
                    time.sleep(rng.uniform(0.0, 0.05))
                    if child.poll() is None and not os.path.isfile(
                            os.path.join(ckdir, "step_final.manifest.json")):
                        child.kill()
                        kills["done"] += 1
                    return
                time.sleep(0.005)

        threading.Thread(target=watch, daemon=True).start()

    sup = Supervisor(_trainer_cmd_builder(args, run_dir), run_dir,
                     max_crashes_per_step=3, backoff_base=0.05,
                     backoff_max=0.2, env=env, on_spawn=on_spawn,
                     log=lambda m: None)
    rc = sup.run()
    assert rc == 0
    assert kills["done"] >= 3, "chaos drill must kill the child at least 3 times"
    assert sup.restarts >= 3

    chaos = _step_losses(run_dir)
    assert max(chaos) == iters
    # exact replay: every step logged by both runs carries the same loss
    # (the chaos log's replayed steps keep the LAST occurrence, which is
    # the one that fed the surviving trajectory)
    for step in sorted(set(base) & set(chaos)):
        assert abs(base[step] - chaos[step]) < 1e-3, (
            step, base[step], chaos[step])
    assert abs(base[iters] - chaos[iters]) < 1e-3

    # the completed run's final checkpoint is manifested and verified
    from mlx_cuda_distributed_pretraining_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(run_dir)
    assert mgr.latest_complete_step() == "final"
