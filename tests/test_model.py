import pytest

import jax
import jax.numpy as jnp
import numpy as np

from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.ops import masks as M
from mlx_cuda_distributed_pretraining_tpu.ops.attention import reference_attention

ARGS = LlamaArgs(
    vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=32,
)


def test_forward_shapes_and_dtype():
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, cache = llama.forward(params, tokens, ARGS)
    assert logits.shape == (2, 16, 64)
    assert logits.dtype == jnp.float32
    assert cache is None


def test_causality():
    """Changing a future token must not change earlier logits."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, 6].set(42)
    l1, _ = llama.forward(params, t1, ARGS)
    l2, _ = llama.forward(params, t2, ARGS)
    np.testing.assert_allclose(l1[0, :6], l2[0, :6], atol=1e-5)
    assert not np.allclose(l1[0, 6], l2[0, 6])


def test_gqa_matches_repeated_mha():
    """GQA via head groups == explicit KV repetition."""
    rng = jax.random.PRNGKey(1)
    B, S, Hq, Hkv, D = 2, 8, 4, 2, 16
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, S, Hq, D))
    k = jax.random.normal(kk, (B, S, Hkv, D))
    v = jax.random.normal(kv, (B, S, Hkv, D))
    out = reference_attention(q, k, v, mask_mod=M.causal())
    k_rep = jnp.repeat(k, Hq // Hkv, axis=2)
    v_rep = jnp.repeat(v, Hq // Hkv, axis=2)
    out_rep = reference_attention(q, k_rep, v_rep, mask_mod=M.causal())
    np.testing.assert_allclose(out, out_rep, atol=1e-5)


def test_mask_mods():
    m = M.materialize_mask(M.sliding_window(2), 4, 4)
    expected = np.array(
        [[1, 0, 0, 0], [1, 1, 0, 0], [0, 1, 1, 0], [0, 0, 1, 1]], bool
    )
    np.testing.assert_array_equal(np.asarray(m), expected)
    p = M.materialize_mask(M.prefix_lm(2), 4, 4)
    assert p[0, 1] and not p[0, 3] and p[3, 0]


def test_block_mask_map():
    bm = M.block_mask_map(M.causal(), 8, 8, 4, 4)
    assert bm[0, 0] == 1  # diagonal partial
    assert bm[1, 0] == 2  # below diagonal dense
    assert bm[0, 1] == 0  # above diagonal skipped


def test_sliding_window_differs_from_causal():
    """Reference test parity (tests/test_flex_attention.py:64-80)."""
    args_sw = LlamaArgs(**{**ARGS.__dict__, "mask_type": "sliding_window", "window_size": 2})
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tokens = jnp.arange(16, dtype=jnp.int32)[None, :] % 60
    l_causal, _ = llama.forward(params, tokens, ARGS)
    l_sw, _ = llama.forward(params, tokens, args_sw)
    assert not np.allclose(l_causal, l_sw)


def test_alibi_score_mod_changes_output():
    args_alibi = LlamaArgs(**{**ARGS.__dict__, "score_mod_type": "alibi"})
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tokens = jnp.arange(16, dtype=jnp.int32)[None, :] % 60
    base, _ = llama.forward(params, tokens, ARGS)
    ali, _ = llama.forward(params, tokens, args_alibi)
    assert not np.allclose(base, ali)


def test_kv_cache_decode_matches_full_forward():
    """Incremental decode with KV cache == full-sequence forward."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tokens = jnp.array([[5, 9, 2, 7, 1, 3]], jnp.int32)
    full_logits, _ = llama.forward(params, tokens, ARGS)

    cache = llama.init_cache(ARGS, batch_size=1, max_len=16)
    # prefill first 3, then decode one at a time
    logits, cache = llama.forward(params, tokens[:, :3], ARGS, cache=cache, start_pos=0)
    np.testing.assert_allclose(logits[0, -1], full_logits[0, 2], atol=1e-4)
    for i in range(3, 6):
        logits, cache = llama.forward(params, tokens[:, i : i + 1], ARGS, cache=cache, start_pos=i)
        np.testing.assert_allclose(logits[0, -1], full_logits[0, i], atol=1e-4)


def test_loss_decreases_tiny_overfit():
    """Few SGD steps on one batch must reduce loss."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = {
        "inputs": jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]] * 2, jnp.int32),
        "targets": jnp.array([[2, 3, 4, 5, 6, 7, 8, 9]] * 2, jnp.int32),
        "mask": jnp.ones((2, 8), jnp.float32),
    }
    grad_fn = jax.jit(jax.value_and_grad(lambda p: llama.loss_fn(p, batch, ARGS)[0]))
    loss0 = None
    for _ in range(20):
        loss, grads = grad_fn(params)
        loss0 = loss if loss0 is None else loss0
        params = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, params, grads)
    assert float(loss) < float(loss0) * 0.7


@pytest.mark.slow
def test_remat_matches_no_remat():
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = {
        "inputs": jnp.ones((1, 8), jnp.int32),
        "targets": jnp.ones((1, 8), jnp.int32),
        "mask": jnp.ones((1, 8), jnp.float32),
    }
    g1 = jax.grad(lambda p: llama.loss_fn(p, batch, ARGS)[0])(params)
    g2 = jax.grad(lambda p: llama.loss_fn(p, batch, ARGS, remat="full")[0])(params)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g1, g2)


def test_tied_vs_untied_embeddings():
    untied = LlamaArgs(**{**ARGS.__dict__, "tie_word_embeddings": False})
    p = llama.init_params(jax.random.PRNGKey(0), untied)
    assert "output" in p
    logits, _ = llama.forward(p, jnp.ones((1, 4), jnp.int32), untied)
    assert logits.shape == (1, 4, 64)


def _batch_for(args, B=2, S=16, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, args.vocab_size - 1, size=(B, S + 1)).astype(np.int32)
    mask = np.ones((B, S), np.float32)
    mask[-1, S // 2:] = 0.0  # exercise masked positions
    return {
        "inputs": jnp.asarray(x[:, :-1]),
        "targets": jnp.asarray(x[:, 1:]),
        "mask": jnp.asarray(mask),
    }


@pytest.mark.slow
def test_fused_ce_matches_unfused_loss_and_grads():
    """Fused chunked CE (ops/fused_ce.py) is exact: same loss and same
    gradients as the materialized-logits path, including a chunk size that
    does not divide B*S (padding path)."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch_for(ARGS)

    def loss_unfused(p):
        return llama.loss_fn(p, batch, ARGS, ce_chunk=0)[0]

    for chunk in (8, 12, 64):  # 12 does not divide 32 -> padded rows
        def loss_fused(p, c=chunk):
            return llama.loss_fn(p, batch, ARGS, ce_chunk=c)[0]

        l0, g0 = jax.value_and_grad(loss_unfused)(params)
        l1, g1 = jax.value_and_grad(loss_fused)(params)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g0, g1
        )


def test_fused_ce_untied_with_bias_and_logit_scale():
    args = LlamaArgs(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=32,
        tie_word_embeddings=False, logit_scale=0.5,
    )
    params = llama.init_params(jax.random.PRNGKey(1), args)
    params["output"]["bias"] = jnp.asarray(
        np.random.default_rng(0).normal(size=(64,)).astype(np.float32) * 0.1
    )
    batch = _batch_for(args)
    l0, g0 = jax.value_and_grad(lambda p: llama.loss_fn(p, batch, args, ce_chunk=0)[0])(params)
    l1, g1 = jax.value_and_grad(lambda p: llama.loss_fn(p, batch, args, ce_chunk=8)[0])(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g0, g1
    )


def test_fused_ce_auto_chunk_policy():
    from mlx_cuda_distributed_pretraining_tpu.ops.fused_ce import auto_chunk

    assert auto_chunk(2, 16, 64) == 0           # tiny: stays unfused
    assert auto_chunk(16, 2048, 32768) == 2048  # bench shape: fused


def test_fused_ce_bit_identical_bf16():
    """Under bf16 compute the fused and unfused paths still agree: both run
    the projection with fp32 accumulation and add the raw fp32 bias."""
    args = LlamaArgs(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=32,
        tie_word_embeddings=False,
    )
    params = llama.init_params(jax.random.PRNGKey(1), args)
    params["output"]["bias"] = jnp.asarray(
        np.random.default_rng(0).normal(size=(64,)).astype(np.float32)
    )
    batch = _batch_for(args)
    l0 = llama.loss_fn(params, batch, args, compute_dtype=jnp.bfloat16, ce_chunk=0)[0]
    l1 = llama.loss_fn(params, batch, args, compute_dtype=jnp.bfloat16, ce_chunk=8)[0]
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)


@pytest.mark.slow
def test_scan_layers_matches_loop():
    """lax.scan over stacked layers is numerically identical to the
    unrolled Python loop — loss and grads, dense and MoE, with and
    without remat (the scan path exists to cut compile time at 400M-1B,
    not to change math)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.models import llama

    rng = np.random.default_rng(0)

    def batch_for(vocab, bs=2, seq=32):
        x = rng.integers(1, vocab - 4, size=(bs, seq + 1)).astype(np.int32)
        return {
            "inputs": jnp.asarray(x[:, :-1]),
            "targets": jnp.asarray(x[:, 1:]),
            "mask": jnp.ones((bs, seq), jnp.float32),
        }

    dense = llama.LlamaArgs(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=3,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=64)
    moe = llama.LlamaArgs(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, num_kv_heads=2, head_dim=16, max_position_embeddings=64,
        num_local_experts=4, num_experts_per_tok=2, moe_group_size=16)

    for args, remat, ratio in ((dense, None, 1.0), (dense, "full", 1.0),
                               (dense, "dots", 1.0), (moe, None, 1.0),
                               (dense, "full", 0.5)):
        params = llama.init_params(jax.random.PRNGKey(1), args)
        batch = batch_for(args.vocab_size)

        def loss(p, scan):
            return llama.loss_fn(p, batch, args, remat=remat,
                                 remat_ratio=ratio, scan_layers=scan)[0]

        l_loop, g_loop = jax.value_and_grad(lambda p: loss(p, False))(params)
        l_scan, g_scan = jax.value_and_grad(lambda p: loss(p, True))(params)
        np.testing.assert_allclose(float(l_loop), float(l_scan), rtol=2e-6)
        flat_l, _ = jax.tree_util.tree_flatten(g_loop)
        flat_s, _ = jax.tree_util.tree_flatten(g_scan)
        for a, b in zip(flat_l, flat_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=1e-6)


def test_z_loss_fused_unfused_parity():
    """The z-loss term (w * mean(logsumexp^2)) is identical between the
    fused-CE and full-logits paths, increases the loss, and is exactly
    additive on top of the pure CE."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.models import llama

    args = llama.LlamaArgs(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=64)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    rng = np.random.default_rng(0)
    x = rng.integers(1, 120, size=(2, 33)).astype(np.int32)
    b = {"inputs": jnp.asarray(x[:, :-1]), "targets": jnp.asarray(x[:, 1:]),
         "mask": jnp.ones((2, 32), jnp.float32)}

    w = 1e-2
    plain_u = float(llama.loss_fn(params, b, args, ce_chunk=0)[0])
    z_u = float(llama.loss_fn(params, b, args, ce_chunk=0, z_loss_weight=w)[0])
    z_f = float(llama.loss_fn(params, b, args, ce_chunk=16, z_loss_weight=w)[0])
    plain_f = float(llama.loss_fn(params, b, args, ce_chunk=16)[0])

    np.testing.assert_allclose(z_u, z_f, rtol=1e-6)
    np.testing.assert_allclose(plain_u, plain_f, rtol=1e-6)
    assert z_u > plain_u  # logsumexp^2 is positive
    # additivity: the z term doesn't perturb the CE part
    np.testing.assert_allclose(z_u - plain_u, z_f - plain_f, rtol=1e-5)
    # grads flow through the z term
    g = jax.grad(lambda p: llama.loss_fn(p, b, args, ce_chunk=16,
                                         z_loss_weight=w)[0])(params)
    assert all(np.isfinite(np.asarray(leaf)).all()
               for leaf in jax.tree_util.tree_leaves(g))
