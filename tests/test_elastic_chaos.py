"""Host-kill chaos harness (ISSUE 13 acceptance).

A 2-supervisor fleet (2 simulated hosts x 2 CPU devices, fsdp=4 across the
world) takes a SIGKILL on one host's trainer mid-run. The fleet must:

- resume through the generation barrier + restart-marker protocol and run
  to completion (both supervisors exit 0),
- reproduce the uninterrupted 2-process baseline's per-step losses
  bit-identically after the restarted window (which also proves zero
  skipped/replayed documents — the loss sequence pins the exact doc order),
- book the lost wall clock as ``restart`` events with goodput >= 95% read
  off the ledger (components still sum to the window wall time).

Python-level mirror of ``scripts/chaos_train.sh`` / bench ``train_elastic``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest
import yaml

from conftest import device_env

from mlx_cuda_distributed_pretraining_tpu.parallel.elastic import read_membership

BATCH, SEQ, ITERS = 8, 64, 24


def _write_inputs(workdir, vocab=256):
    shard_dir = os.path.join(workdir, "shards")
    os.makedirs(shard_dir)
    n_tokens = (ITERS + 8) * BATCH * (SEQ + 1)
    rng = np.random.default_rng(0)
    arr = rng.integers(1, vocab - 4, size=n_tokens).astype(np.uint16)
    arr.tofile(os.path.join(shard_dir, "shard_00000.bin"))
    with open(os.path.join(shard_dir, "index.json"), "w") as f:
        json.dump({"dtype": "uint16", "shard_tokens": n_tokens,
                   "total_tokens": n_tokens, "files": ["shard_00000.bin"],
                   "vocab_size": vocab, "eos_id": 0}, f)
    return shard_dir


def _write_cfg(workdir, name, shard_dir, cache_dir):
    cfg = {
        "name": name,
        "overwrite": False,
        "data": {"source": "token_shards", "input_file": shard_dir,
                 "preprocessing": {"max_context_size": SEQ},
                 "tokenizer": {"default": "byte"}},
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 64, "intermediate_size": 128,
                           "num_layers": 2, "num_heads": 4},
            "attention": {"num_kv_heads": 4, "head_dim": 16,
                          "max_position_embeddings": SEQ,
                          "attention_type": "simple"},
            "misc": {"vocab_size": 256},
        },
        "training": {
            "hyperparameters": {"batch_size": BATCH, "learning_rate": 1e-3,
                                "iters": ITERS, "gradient_clip": 1.0},
            "scheduler": {"type": "cosine_with_warmup", "warmup_steps": 2},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {"steps": {"logging_interval": 1,
                              "checkpoint_interval": 4,
                              "validation_interval": 0}},
        "system": {"seed": 0, "compute_dtype": "float32",
                   "mesh": {"fsdp": 4},
                   "compilation_cache_dir": cache_dir},
        # hang_timeout_s 0: the fleet watchdog still runs (process_count>1)
        # but only for peer restart markers — no stale-heartbeat false
        # positives during the cold compile, and a tight 0.5s marker poll
        # keeps restart_lost_s in single-digit seconds.
        "supervisor": {"hang_timeout_s": 0.0, "hang_kill_grace_s": 1.0,
                       "barrier_timeout_s": 90.0},
    }
    path = os.path.join(workdir, f"{name}.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    return path


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_fleet(cfg_path, runs_root, workdir, tag):
    port = _free_port()
    procs = []
    for i in range(2):
        log = open(os.path.join(workdir, f"{tag}_sup_p{i}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m",
             "mlx_cuda_distributed_pretraining_tpu.train.trainer",
             "--config", cfg_path, "--runs-root", runs_root,
             "--auto-resume", "--max-crashes", "5", "--backoff-base", "0.1",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(i)],
            env=device_env(2), stdout=log, stderr=subprocess.STDOUT))
    return procs


def _wait_fleet(procs, workdir, tag, deadline_s=420):
    t0 = time.time()
    rcs = []
    for p in procs:
        try:
            rcs.append(p.wait(timeout=max(5.0, deadline_s - (time.time() - t0))))
        except subprocess.TimeoutExpired:
            p.kill()
            rcs.append(-9)
    if rcs != [0, 0]:
        logs = ""
        for i in range(2):
            path = os.path.join(workdir, f"{tag}_sup_p{i}.log")
            with open(path) as f:
                logs += f"\n--- {path} ---\n" + f.read()[-4000:]
        raise AssertionError(f"{tag} fleet rcs={rcs}{logs}")
    return rcs


def _events(run_dir):
    out = []
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def _last_losses(events):
    # Last occurrence wins: the chaos run logs a step twice when the
    # restarted generation replays the window after the checkpoint.
    losses = {}
    for ev in events:
        if ev.get("type") == "step_window":
            losses[int(ev["step"])] = float(ev["loss"])
    return losses


@pytest.mark.slow
def test_host_kill_chaos_resumes_with_loss_parity(tmp_path):
    workdir = str(tmp_path)
    shard_dir = _write_inputs(workdir)
    cache_dir = os.path.join(workdir, "xla_cache")

    # Uninterrupted 2-process baseline (also warms the compile cache).
    base_cfg = _write_cfg(workdir, "chaos-base", shard_dir, cache_dir)
    base_root = os.path.join(workdir, "runs_base")
    _wait_fleet(_launch_fleet(base_cfg, base_root, workdir, "base"),
                workdir, "base")
    base_losses = _last_losses(_events(os.path.join(base_root, "chaos-base")))
    assert sorted(base_losses) == list(range(1, ITERS + 1)), base_losses

    # Chaos fleet: SIGKILL host 1's trainer once it has progressed past the
    # step-4 checkpoint (pid comes from its per-host heartbeat file).
    chaos_cfg = _write_cfg(workdir, "chaos", shard_dir, cache_dir)
    chaos_root = os.path.join(workdir, "runs_chaos")
    run_dir = os.path.join(chaos_root, "chaos")
    procs = _launch_fleet(chaos_cfg, chaos_root, workdir, "chaos")
    killed = False
    hb_path = os.path.join(run_dir, "heartbeat_p1.json")
    t0 = time.time()
    while time.time() - t0 < 420 and any(p.poll() is None for p in procs):
        if not killed and os.path.isfile(hb_path):
            try:
                with open(hb_path) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                hb = {}
            if int(hb.get("step") or 0) >= 5 and hb.get("pid"):
                os.kill(int(hb["pid"]), signal.SIGKILL)
                killed = True
        time.sleep(0.25)
    assert killed, "host 1's trainer never reached step 5 within the deadline"
    _wait_fleet(procs, workdir, "chaos")

    events = _events(run_dir)

    # The fleet restarted as a new generation and recorded who joined it.
    restarts = [ev for ev in events if ev.get("type") == "restart"]
    assert restarts and all(ev.get("generation", 2) >= 2 for ev in restarts)
    membership = read_membership(run_dir)
    assert membership and int(membership["generation"]) >= 2, membership
    assert int(membership["process_count"]) == 2, membership

    # Loss parity: every step the chaos run (re)computed must match the
    # uninterrupted baseline bit-for-bit — same params, same documents.
    chaos_losses = _last_losses(events)
    assert sorted(chaos_losses) == sorted(base_losses), chaos_losses
    for step, want in sorted(base_losses.items()):
        assert chaos_losses[step] == want, (step, chaos_losses[step], want)

    # Ledger goodput: lost wall clock is booked, components still sum to
    # each window's wall time, and goodput = comp/(comp+lost) >= 95%.
    lost = sum(float(ev.get("lost_s") or 0.0) for ev in restarts)
    assert lost > 0.0, restarts
    comp = 0.0
    for ev in events:
        if ev.get("type") != "step_window":
            continue
        gp = ev.get("goodput") or {}
        assert "other_s" in gp and all(
            isinstance(v, (int, float)) and v >= -1e-9 for v in gp.values()), ev
        comp += sum(gp.values())
    assert comp > 0.0
    goodput = comp / (comp + lost)
    assert goodput >= 0.95, (goodput, comp, lost)
