"""graftsync gate + rule behavior + runtime-shim interleaving tests.

Three jobs:
  1. Gate the package: the merged tree must produce ZERO non-baselined
     sync findings, and every baselined finding must carry a real reason
     (mirrors the graftlint gate in test_lint.py).
  2. Pin rule behavior: each concurrency rule fires at exact
     (rule, line) positions in its bad fixture, stays silent on its good
     fixture, and is silenced (but counted) by inline suppression.
  3. Enforce the contracts dynamically: a deterministic two-thread
     interleaving harness drives the engine-owned KV pool and prefix
     cache from a "wrong" thread — the runtime shim must catch the
     direct call, and the call_in_loop-style funnel must pass with
     exact refcounts. Plus a regression pinning the metrics registry's
     lock discipline under interleaved writers.

The fixture files under tests/lint_fixtures/ are analyzed as text,
never imported.
"""
import json
import os
import subprocess
import sys
import threading

import pytest

from mlx_cuda_distributed_pretraining_tpu.analysis import (
    SYNC_SUPPRESS_RE,
    all_sync_rules,
    lint_file,
    load_baseline,
    package_lock_edges,
    package_ownership,
)
from mlx_cuda_distributed_pretraining_tpu.analysis import sync_runtime
from mlx_cuda_distributed_pretraining_tpu.analysis.sync import (
    default_sync_baseline_path,
    run_sync,
)
from mlx_cuda_distributed_pretraining_tpu.analysis.sync_runtime import (
    SyncMonitor,
    SyncViolation,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mlx_cuda_distributed_pretraining_tpu")
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")

EXPECTED_SYNC_RULE_IDS = {
    "sync-owned-attr",
    "sync-guard",
    "sync-blocking-under-lock",
    "sync-lock-order",
}


def _hits(path):
    """(active findings, suppressed findings) for one fixture file."""
    return lint_file(os.path.join(FIXTURES, path),
                     rules=all_sync_rules(), suppress_re=SYNC_SUPPRESS_RE)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- the gate ---------------------------------------------------------------

def test_registry_has_all_sync_rules():
    assert set(all_sync_rules()) == EXPECTED_SYNC_RULE_IDS


def test_package_has_no_new_sync_findings():
    """The CI gate: the merged tree must be clean modulo the baseline."""
    baseline = load_baseline(default_sync_baseline_path())
    result = run_sync([PKG], baseline=baseline)
    assert not result.new, "new graftsync findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.new)


def test_every_sync_baseline_entry_has_a_reason():
    entries = load_baseline(default_sync_baseline_path())
    assert entries, "sync_baseline.json should exist with triaged entries"
    for e in entries:
        reason = (e.get("reason") or "").strip()
        assert reason, f"baseline entry without reason: {e}"
        assert "REPLACE with a one-line justification" not in reason, (
            f"placeholder reason left in baseline: {e['path']}:{e['line']}")


def test_sync_baseline_entries_all_still_match():
    baseline = load_baseline(default_sync_baseline_path())
    result = run_sync([PKG], baseline=baseline)
    assert not result.stale_baseline, (
        "stale sync-baseline entries (fix was made — prune them):\n"
        + "\n".join(f"  {e.get('path')}:{e.get('line')}: [{e.get('rule')}]"
                    for e in result.stale_baseline))


def test_package_ownership_covers_the_engine_domain():
    """The annotations the runtime shim enforces actually exist."""
    owners = package_ownership()
    eng = owners.get("engine-thread")
    assert eng, f"no engine-thread ownership derived: {sorted(owners)}"
    assert "SlotKVPool" in eng["classes"]
    assert "PagedKVPool" in eng["classes"]
    assert "PrefixCache" in eng["classes"]


# -- per-rule fixtures: bad fires at exact lines ----------------------------

@pytest.mark.parametrize("fixture,rule,lines", [
    ("sync_owner_bad.py", "sync-owned-attr", [25, 28]),
    ("sync_guard_bad.py", "sync-guard", [15, 18, 22]),
    ("sync_guard_interproc_bad.py", "sync-guard", [14]),
    ("sync_blocking_bad.py", "sync-blocking-under-lock", [15, 16, 26]),
    ("sync_lock_order_bad.py", "sync-lock-order", [12]),
])
def test_bad_fixture_fires_at_exact_lines(fixture, rule, lines):
    active, _ = _hits(fixture)
    assert _rule_lines(active, rule) == lines, (
        f"{fixture}: expected {rule} at {lines}, got "
        f"{[(f.rule, f.line) for f in active]}")


def test_lock_order_cycle_names_all_three_locks():
    active, _ = _hits("sync_lock_order_bad.py")
    assert len(active) == 1, [(f.rule, f.line) for f in active]
    msg = active[0].message
    for lock in ("<module>.A", "<module>.B", "<module>.C"):
        assert lock in msg, msg


@pytest.mark.parametrize("fixture", [
    "sync_owner_good.py",
    "sync_guard_good.py",
    "sync_guard_interproc_good.py",
    "sync_blocking_good.py",
    "sync_lock_order_good.py",
])
def test_good_fixture_is_clean(fixture):
    active, suppressed = _hits(fixture)
    assert not active, [(f.rule, f.line, f.message) for f in active]
    assert not suppressed, "good fixtures must not rely on suppressions"


@pytest.mark.parametrize("fixture,rule,line", [
    ("sync_owner_suppressed.py", "sync-owned-attr", 14),
    ("sync_guard_suppressed.py", "sync-guard", 18),
    ("sync_blocking_suppressed.py", "sync-blocking-under-lock", 13),
])
def test_suppression_silences_but_counts(fixture, rule, line):
    active, suppressed = _hits(fixture)
    assert not active, [(f.rule, f.line) for f in active]
    assert [(f.rule, f.line) for f in suppressed] == [(rule, line)]


def test_graftlint_suppressions_do_not_silence_sync_rules():
    """The two tools carry separate comment tags: a `# graftlint:
    disable=` comment must not blanket-silence a concurrency finding."""
    assert SYNC_SUPPRESS_RE.search("# graftsync: disable=sync-guard")
    assert not SYNC_SUPPRESS_RE.search("# graftlint: disable=sync-guard")


# -- CLI contract -----------------------------------------------------------

def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m",
         "mlx_cuda_distributed_pretraining_tpu.analysis.sync", *args],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)


def test_cli_exit_zero_on_package():
    proc = _run_cli(PKG)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_exit_one_on_bad_fixture_and_json_shape():
    proc = _run_cli("--format", "json", "--no-baseline",
                    os.path.join(FIXTURES, "sync_guard_bad.py"))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["tool"] == "graftsync"
    assert {f["rule"] for f in doc["new"]} == {"sync-guard"}
    assert sorted(f["line"] for f in doc["new"]) == [15, 18, 22]
    for key in ("baselined", "suppressed", "stale_baseline"):
        assert key in doc


def test_cli_exit_two_on_missing_path():
    proc = _run_cli(os.path.join(FIXTURES, "does_not_exist.py"))
    assert proc.returncode == 2


def test_cli_list_rules_names_every_rule():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in EXPECTED_SYNC_RULE_IDS:
        assert rule_id in proc.stdout


# -- runtime shim: ownership + lock order -----------------------------------

@pytest.fixture
def monitor():
    """A fresh armed monitor; always disarmed afterwards so the shim
    stays a no-op for every other test in the session."""
    mon = sync_runtime.activate(SyncMonitor())
    yield mon
    sync_runtime.deactivate()


def test_shim_is_noop_when_disarmed():
    assert sync_runtime.active() is None
    sync_runtime.bind("engine-thread")        # must not raise or arm
    sync_runtime.check_owner("engine-thread")
    assert sync_runtime.active() is None


def test_check_owner_enforces_binding_thread(monitor):
    monitor.bind("engine-thread")
    monitor.check_owner("engine-thread")      # owner thread: fine
    monitor.check_owner("never-bound")        # unclaimed domain: fine
    caught = []

    def intruder():
        try:
            monitor.check_owner("engine-thread")
        except SyncViolation as e:
            caught.append(e)

    t = threading.Thread(target=intruder, name="intruder")
    t.start()
    t.join(timeout=5.0)
    assert len(caught) == 1
    assert "engine-thread" in str(caught[0])
    assert monitor.violations


def test_lock_order_inversion_raises_not_deadlocks():
    """A monitor seeded with the static edge A->B must refuse B-then-A
    at the acquisition site — on the FIRST inverted interleaving, not
    the unlucky one that actually deadlocks."""
    mon = SyncMonitor(static_order=[("A", "B")])
    a = mon.wrap_lock("A")
    b = mon.wrap_lock("B")
    with a:
        with b:
            pass  # consistent with the static order
    with pytest.raises(SyncViolation, match="lock-order violation"):
        with b:
            with a:
                pass


def test_lock_order_learned_dynamically():
    """Edges observed at run time count too: A-then-B in one thread
    forbids B-then-A later even with no static seed."""
    mon = SyncMonitor()
    a, b = mon.wrap_lock("A"), mon.wrap_lock("B")
    with a:
        with b:
            pass
    with pytest.raises(SyncViolation):
        with b:
            with a:
                pass


def test_activate_seeds_from_static_edges():
    """activate() with no monitor derives its seed graph from the
    package's statically extracted acquisition edges."""
    edges = package_lock_edges()
    try:
        mon = sync_runtime.activate()
        assert sync_runtime.active() is mon
        for src, dst, _path, _line in edges:
            assert dst in mon._graph.get(src, set())
    finally:
        sync_runtime.deactivate()


# -- deterministic two-thread interleaving harness --------------------------

class Interleave:
    """Run two actors' steps in an exact, scripted order.

    Each actor is a REAL thread (thread identity is what the ownership
    shim checks) but only ever runs the single step the driver releases,
    so every schedule is reproducible. Exceptions are captured per
    actor; the driver re-joins both threads before returning."""

    def __init__(self, steps_a, steps_b):
        self._steps = {"a": list(steps_a), "b": list(steps_b)}
        self._go = {"a": threading.Event(), "b": threading.Event()}
        self._done = threading.Event()
        self.errors = {"a": [], "b": []}
        self._threads = {
            name: threading.Thread(target=self._actor, args=(name,),
                                   name=f"interleave-{name}", daemon=True)
            for name in ("a", "b")}

    def _actor(self, name):
        for step in self._steps[name]:
            self._go[name].wait()
            self._go[name].clear()
            try:
                step()
            except Exception as e:  # noqa: BLE001 - delivered to driver
                self.errors[name].append(e)
            self._done.set()

    def run(self, order):
        """``order`` is a string over {'a','b'}: which actor executes its
        next step at each point. Must consume every step exactly once."""
        assert sorted(order) == sorted("a" * len(self._steps["a"])
                                       + "b" * len(self._steps["b"]))
        for t in self._threads.values():
            t.start()
        for name in order:
            self._done.clear()
            self._go[name].set()
            assert self._done.wait(timeout=10.0), f"step of '{name}' hung"
        for t in self._threads.values():
            t.join(timeout=10.0)
        return self


class Funnel:
    """Minimal call_in_loop stand-in: closures enqueued by any thread,
    drained only by the owner actor's steps (exceptions re-raise at the
    submitting call's ``result()``)."""

    def __init__(self):
        self._items = []
        self._lock = threading.Lock()

    def submit(self, fn):
        box = {}
        with self._lock:
            self._items.append((fn, box))
        return box

    def drain(self):
        with self._lock:
            items, self._items = self._items, []
        for fn, box in items:
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001 - delivered to caller
                box["error"] = e


def _result(box):
    if "error" in box:
        raise box["error"]
    return box["result"]


def _paged_pool():
    jax = pytest.importorskip("jax")
    del jax
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
    from mlx_cuda_distributed_pretraining_tpu.serve import PagedKVPool

    args = LlamaArgs(vocab_size=64, hidden_size=16, intermediate_size=32,
                     num_layers=1, num_heads=2, num_kv_heads=1, head_dim=8,
                     max_position_embeddings=128)
    return PagedKVPool(args, num_seqs=2, max_len=128, block_size=32,
                       num_blocks=8, prefix_cache=True)


@pytest.mark.slow
def test_kv_pool_direct_offthread_call_is_caught(monitor):
    """Seeded violation: an 'HTTP handler' actor frees an engine-owned
    slot directly. The shim must raise BEFORE any bookkeeping mutates —
    refcounts stay exactly as the engine left them."""
    pool = _paged_pool()
    ids = list(range(64))  # two full blocks
    state = {}

    def a_alloc():
        sync_runtime.bind("engine-thread")
        state["seq"] = pool.allocate(need_tokens=64, token_ids=ids)
        pool.lengths[state["seq"]] = 64
        pool.register_upto(state["seq"], ids)
        state["blocks"] = [int(pool.tables[state["seq"], i])
                           for i in range(2)]

    def b_free_direct():
        pool.free(state["seq"])  # wrong thread, no funnel

    def a_check():
        assert all(pool._ref[b] == 1 for b in state["blocks"])

    il = Interleave([a_alloc, a_check], [b_free_direct]).run("aba")
    assert not il.errors["a"], il.errors["a"]
    assert len(il.errors["b"]) == 1
    assert isinstance(il.errors["b"][0], SyncViolation)
    assert "engine-thread" in str(il.errors["b"][0])


@pytest.mark.slow
def test_kv_pool_export_adopt_free_refcounts_through_funnel(monitor):
    """Fixed code passes: the same off-thread actor routes every pool
    mutation through the owner funnel; refcounts are exact at every
    interleaving point (export pins +1, free drops the row's ref,
    release retires to the prefix LRU)."""
    pool = _paged_pool()
    ids = list(range(64))
    funnel = Funnel()
    state = {}

    def a_alloc():
        sync_runtime.bind("engine-thread")
        seq = pool.allocate(need_tokens=64, token_ids=ids)
        pool.lengths[seq] = 64
        pool.register_upto(seq, ids)
        state["seq"] = seq
        state["blocks"] = [int(pool.tables[seq, i]) for i in range(2)]

    def b_submit_export():
        state["export_box"] = funnel.submit(
            lambda: pool.export_blocks(ids))

    def a_drain():
        funnel.drain()

    def b_check_export():
        export = _result(state["export_box"])
        state["export"] = export
        assert export.blocks == state["blocks"]
        # live row + export pin
        assert all(pool._ref[b] == 2 for b in export.blocks)
        state["free_box"] = funnel.submit(
            lambda: pool.free(state["seq"]))

    def b_release():
        assert _result(state["free_box"]) is None
        # export pin only, row gone
        assert all(pool._ref[b] == 1 for b in state["export"].blocks)
        state["rel_box"] = funnel.submit(
            lambda: pool.release_export(state["export"]))

    def a_final_check():
        assert "error" not in state["rel_box"]
        # refcount 0 and registered: retired to the prefix LRU, adoptable
        assert all(pool._ref[b] == 0 for b in state["export"].blocks)
        assert pool.prefix.retired_blocks == 2

    il = Interleave(
        [a_alloc, a_drain, a_drain, a_drain, a_final_check],
        [b_submit_export, b_check_export, b_release],
    ).run("abababaa")
    assert not il.errors["a"], il.errors["a"]
    assert not il.errors["b"], il.errors["b"]


@pytest.mark.slow
def test_prefix_cache_register_evict_interleaved(monitor):
    """PrefixCache mutators are engine-owned: direct off-thread register
    raises; the funneled register/evict sequence lands exact counts."""
    from mlx_cuda_distributed_pretraining_tpu.serve.prefix_cache import (
        PrefixCache,
    )

    cache = PrefixCache(block_size=32)
    funnel = Funnel()
    state = {}

    def a_bind():
        sync_runtime.bind("engine-thread")
        assert cache.register(b"k0", 1)
        cache.retire(1)

    def b_direct_register():
        cache.register(b"k1", 2)  # wrong thread

    def b_funneled():
        state["reg"] = funnel.submit(lambda: cache.register(b"k1", 2))
        state["evict"] = funnel.submit(cache.evict_lru)

    def a_drain():
        funnel.drain()

    def a_check():
        assert _result(state["reg"]) is True
        assert _result(state["evict"]) == 1  # LRU end: the k0 block
        assert cache.cached_blocks == 1      # k1 remains
        assert cache.evictions == 1

    il = Interleave([a_bind, a_drain, a_check],
                    [b_direct_register, b_funneled]).run("abbaa")
    assert not il.errors["a"], il.errors["a"]
    assert len(il.errors["b"]) == 1
    assert isinstance(il.errors["b"][0], SyncViolation)


def test_metrics_registry_interleaved_writers_exact_totals():
    """Regression for the metrics lock discipline: counter increments
    and histogram observations from two interleaved threads must land
    exactly — the registry's single lock covers every RMW (bucket
    increments, sums, counts, series creation)."""
    from mlx_cuda_distributed_pretraining_tpu.obs.metrics import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    c = reg.counter("t_total", "test counter")
    h = reg.histogram("t_lat", "test histogram", buckets=(0.5, 1.5))
    n = 200

    def writer():
        for i in range(n):
            c.inc()
            h.observe(i % 2)  # alternates the two finite buckets

    threads = [threading.Thread(target=writer) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert c.value() == 2 * n
    snap = reg.snapshot()["t_lat"]["series"][0]
    assert snap["count"] == 2 * n
    assert snap["buckets"][-1] == ["+Inf", 2 * n]
    # cumulative: n zeros in the 0.5 bucket, everything by 1.5
    assert snap["buckets"][0] == [0.5, n]
    assert snap["buckets"][1] == [1.5, 2 * n]
