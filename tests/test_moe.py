"""MoE block + expert parallelism.

The reference only declares MoE config fields (models/llama.py:40-41);
our models/moe.py implements the real block. These tests check routing
math, gradient flow to every expert, and that the ep-sharded train step
on a virtual mesh matches the single-device loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.models import llama, moe
from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
from mlx_cuda_distributed_pretraining_tpu.config import SystemConfig, TrainingConfig
from mlx_cuda_distributed_pretraining_tpu.parallel import build_mesh
from mlx_cuda_distributed_pretraining_tpu.train.train_step import (
    init_train_state,
    make_train_step,
)

MOE_ARGS = llama.LlamaArgs(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=2, num_kv_heads=2, head_dim=16, max_position_embeddings=64,
    num_local_experts=4, num_experts_per_tok=2,
)


def _batch(bs=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, 120, size=(bs, seq + 1)).astype(np.int32)
    return {
        "inputs": jnp.asarray(x[:, :-1]),
        "targets": jnp.asarray(x[:, 1:]),
        "mask": jnp.ones((bs, seq), jnp.float32),
    }


def test_dispatch_combine_shapes_and_conservation():
    # A perfectly balanced router keeps every token: combine sums to 1.
    B, S, E, K, C = 2, 8, 4, 2, 8
    probs = jnp.full((B, S, E), 1.0 / E)
    dispatch, combine = moe._dispatch_combine(probs, K, C)
    assert dispatch.shape == (B, S, E, C)
    # every token dispatched to exactly K slots
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(2, 3))), K)
    # combine weights renormalized over the K picks
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0, atol=1e-5)


def test_capacity_drops_overflow_tokens():
    # All tokens want expert 0 with capacity 2: only 2 survive per row.
    B, S, E, K, C = 1, 6, 4, 1, 2
    logits = jnp.zeros((B, S, E)).at[..., 0].set(10.0)
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine = moe._dispatch_combine(probs, K, C)
    assert float(dispatch[..., 0, :].sum()) == pytest.approx(2.0)
    # dropped tokens have zero combine weight (residual carries them)
    per_token = np.asarray(combine.sum(axis=(2, 3)))[0]
    assert (per_token[:2] > 0.9).all() and (per_token[2:] < 1e-6).all()


def test_balanced_router_aux_loss_is_one():
    # Uniform probs + uniform assignment -> Switch aux loss == 1.
    probs = jnp.full((2, 8, 4), 0.25)
    idx = jnp.tile(jnp.arange(4), 4).reshape(2, 8)
    assert float(moe.load_balancing_loss(probs, idx, 4)) == pytest.approx(1.0)


@pytest.mark.slow
def test_moe_forward_and_all_experts_get_gradients():
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    batch = _batch()
    loss, grads = jax.value_and_grad(
        lambda p: llama.loss_fn(p, batch, MOE_ARGS)[0]
    )(params)
    assert np.isfinite(float(loss))
    g = grads["layers"][0]["feed_forward"]["experts"]["w_gate"]["weight"]
    per_expert = np.asarray(jnp.abs(g).sum(axis=(1, 2)))
    assert (per_expert > 0).all(), f"dead experts: {per_expert}"
    # router learns too
    rg = grads["layers"][0]["feed_forward"]["router"]["weight"]
    assert float(jnp.abs(rg).sum()) > 0


def test_moe_aux_loss_increases_total_loss():
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    batch = _batch()
    import dataclasses

    no_aux = dataclasses.replace(MOE_ARGS, moe_aux_weight=0.0)
    l_with, _ = llama.loss_fn(params, batch, MOE_ARGS)
    l_without, _ = llama.loss_fn(params, batch, no_aux)
    assert float(l_with) > float(l_without)


def test_router_z_loss_applies_without_aux_weight():
    # z-loss must survive moe_aux_weight=0 (it is scaled independently).
    import dataclasses

    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    batch = _batch()
    base = dataclasses.replace(MOE_ARGS, moe_aux_weight=0.0, router_z_weight=0.0)
    with_z = dataclasses.replace(MOE_ARGS, moe_aux_weight=0.0, router_z_weight=1.0)
    l0, _ = llama.loss_fn(params, batch, base)
    lz, _ = llama.loss_fn(params, batch, with_z)
    assert float(lz) > float(l0)


def test_moe_nondivisible_seq_is_padded_not_regrouped():
    # S=20 with group 8 pads to 24 (3 groups) instead of reverting to one
    # O(S) capacity group; output stays finite and correctly shaped.
    import dataclasses

    args = dataclasses.replace(MOE_ARGS, moe_group_size=8)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    batch = _batch(bs=2, seq=20)
    loss, _ = llama.loss_fn(params, batch, args)
    assert np.isfinite(float(loss))
    logits, _ = llama.forward(params, batch["inputs"], args)
    assert logits.shape == (2, 20, MOE_ARGS.vocab_size)


def test_eval_loss_excludes_router_aux():
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    batch = _batch()
    l_train, _ = llama.loss_fn(params, batch, MOE_ARGS, include_aux=True)
    l_eval, _ = llama.loss_fn(params, batch, MOE_ARGS, include_aux=False)
    assert float(l_train) > float(l_eval)


def test_mlp_bias_with_moe_rejected():
    import dataclasses

    bad = dataclasses.replace(MOE_ARGS, mlp_bias=True)
    with pytest.raises(ValueError, match="mlp_bias"):
        llama.init_params(jax.random.PRNGKey(0), bad)


def test_moe_token_grouping_keeps_capacity_bounded():
    # group_size fixes capacity independent of S: dispatch memory is O(S).
    import dataclasses

    args = dataclasses.replace(MOE_ARGS, moe_group_size=8)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    batch = _batch(bs=2, seq=32)  # 4 groups of 8 per row
    loss, _ = llama.loss_fn(params, batch, args)
    assert np.isfinite(float(loss))
    # per-group capacity stays fixed while whole-sequence capacity grows
    assert moe.expert_capacity(8, 4, 2, 1.25) < moe.expert_capacity(32, 4, 2, 1.25)


@pytest.mark.slow
def test_moe_decode_cache_matches_full_forward():
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    tokens = jnp.asarray(np.random.default_rng(1).integers(1, 120, (1, 8)), jnp.int32)
    full, _ = llama.forward(params, tokens, MOE_ARGS)
    cache = llama.init_cache(MOE_ARGS, 1, 16)
    logits, cache = llama.forward(params, tokens[:, :4], MOE_ARGS, cache=cache, start_pos=0)
    outs = [logits[:, -1]]
    for i in range(4, 8):
        logits, cache = llama.forward(
            params, tokens[:, i : i + 1], MOE_ARGS, cache=cache, start_pos=i
        )
        outs.append(logits[:, -1])
    # decode sees the whole prefix; capacity is per-call so early-token
    # routing can differ slightly from the full pass — compare loosely.
    np.testing.assert_allclose(
        np.asarray(outs[-1]), np.asarray(full[:, -1]), atol=2e-2, rtol=2e-2
    )


@pytest.mark.slow
def test_moe_train_step_on_ep_mesh_matches_single_device():
    if jax.device_count() < 4:
        pytest.skip("needs 4 virtual devices")
    sys_cfg = SystemConfig(seed=0, device="cpu", mesh={"ep": 2, "dp": 2})
    mesh = build_mesh(sys_cfg, devices=jax.devices()[:4])
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    tr = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3},
        scheduler={"type": "cosine"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr, 10)

    def loss_fn(p, b):
        return llama.loss_fn(p, b, MOE_ARGS)

    batch = _batch(bs=8)
    # single-device reference first: the sharded step donates its buffers
    sstep, _ = make_train_step(loss_fn, opt)
    sstate = init_train_state(jax.tree_util.tree_map(jnp.copy, params), opt)
    _, smetrics = sstep(sstate, batch)

    step, shardings = make_train_step(loss_fn, opt, mesh=mesh, params_like=params)
    state = jax.device_put(init_train_state(params, opt), shardings)
    new_state, metrics = step(state, batch)
    sharded_loss = float(metrics["loss"])
    assert sharded_loss == pytest.approx(float(smetrics["loss"]), rel=1e-4)
    # expert weights actually sharded over ep
    w = new_state["params"]["layers"][0]["feed_forward"]["experts"]["w_gate"]["weight"]
    spec = w.sharding.spec
    assert spec and spec[0] == "ep", f"expert dim not ep-sharded: {spec}"


# -- grouped (dropless, sort-based) dispatch ---------------------------------

def test_grouped_matches_einsum_loss_and_grads():
    # At ample capacity (CF = E/K) the einsum oracle drops nothing, so both
    # impls compute the same math modulo fp32 summation order.
    import dataclasses

    args_g = dataclasses.replace(MOE_ARGS, moe_impl="grouped", moe_group_size=16)
    args_e = dataclasses.replace(
        MOE_ARGS, moe_impl="einsum", moe_group_size=16,
        moe_capacity_factor=float(MOE_ARGS.num_local_experts)
        / MOE_ARGS.num_experts_per_tok)
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    batch = _batch()
    lg, gg = jax.value_and_grad(lambda p: llama.loss_fn(p, batch, args_g)[0])(params)
    le, ge = jax.value_and_grad(lambda p: llama.loss_fn(p, batch, args_e)[0])(params)
    assert float(lg) == pytest.approx(float(le), abs=1e-6)
    flat_g = jax.tree_util.tree_leaves_with_path(gg)
    flat_e = jax.tree_util.tree_leaves_with_path(ge)
    for (kg, vg), (ke, ve) in zip(flat_g, flat_e):
        assert kg == ke
        np.testing.assert_allclose(
            np.asarray(vg), np.asarray(ve), atol=1e-6, rtol=1e-4,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(kg)}")


def test_grouped_is_dropless_keeps_overflow_tokens():
    # Starved capacity: the einsum impl drops selections (counted in its
    # routing stats), the sorted grouped path keeps every one.
    import dataclasses

    args_e = dataclasses.replace(
        MOE_ARGS, moe_impl="einsum", moe_group_size=16, moe_capacity_factor=0.25)
    args_g = dataclasses.replace(args_e, moe_impl="grouped")
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    batch = _batch()

    def run(args):
        loss, (_, stats) = llama.loss_fn(params, batch, args, with_moe_stats=True)
        return float(loss), float(stats["moe_dropped"])

    loss_e, dropped_e = run(args_e)
    loss_g, dropped_g = run(args_g)
    assert dropped_e > 0, "starved einsum capacity must drop selections"
    assert dropped_g == 0, "grouped dispatch must be dropless"
    assert np.isfinite(loss_e) and np.isfinite(loss_g)
    # the kept overflow tokens actually change the computed loss
    assert loss_g != pytest.approx(loss_e, abs=1e-7)


def test_gmm_backends_match_ragged_fwd_and_bwd():
    # blocked and (interpret-mode) pallas against the XLA-native
    # ragged_dot reference: forward values and both gradients.
    from mlx_cuda_distributed_pretraining_tpu.ops import grouped_matmul as gm

    bt = 64
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(256, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(4, 32, 48)), jnp.float32)
    sizes = jnp.asarray([64, 0, 128, 64], jnp.int32)  # empty group included

    def loss(x, w, backend):
        y = gm.gmm(x, w, sizes, block_t=bt, backend=backend)
        return (y * y).sum(), y

    (ref_l, ref_y), (ref_dx, ref_dw) = jax.value_and_grad(
        loss, argnums=(0, 1), has_aux=True)(x, w, "ragged")
    for backend in ("blocked", "pallas"):
        (l, y), (dx, dw) = jax.value_and_grad(
            loss, argnums=(0, 1), has_aux=True)(x, w, backend)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref_y),
                                   atol=1e-5, rtol=1e-5, err_msg=backend)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                                   atol=1e-3, rtol=1e-4, err_msg=backend)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(ref_dw),
                                   atol=1e-3, rtol=1e-4, err_msg=backend)


def test_gmm_unknown_backend_rejected():
    from mlx_cuda_distributed_pretraining_tpu.ops import grouped_matmul as gm

    with pytest.raises(ValueError, match="unknown gmm backend"):
        gm.gmm(jnp.zeros((8, 4)), jnp.zeros((2, 4, 4)),
               jnp.asarray([8, 0]), block_t=8, backend="nope")


def test_aux_loss_ignores_group_padding():
    # Regression: aux is computed from real-token router probs before
    # dispatch, so the S=250 -> 256 group padding (and any other group
    # size) must not move it at all.
    import dataclasses

    args = dataclasses.replace(MOE_ARGS, max_position_embeddings=256)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    p = params["layers"][0]["feed_forward"]
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 250, 32)), jnp.float32)
    auxes = [
        float(moe.moe_block(
            p, x, dataclasses.replace(args, moe_impl=impl, moe_group_size=g))[1])
        for impl in ("einsum", "grouped") for g in (256, 125, 250)
    ]
    assert auxes[0] > 0
    for a in auxes[1:]:
        assert a == auxes[0], f"aux moved with group padding: {auxes}"


@pytest.mark.slow
def test_moe_grouped_ep4_matches_single_device():
    # Pure ep mesh, one expert shard per device: the all_to_all sorted
    # exchange must reproduce the single-device grouped loss.
    if jax.device_count() < 4:
        pytest.skip("needs 4 virtual devices")
    sys_cfg = SystemConfig(seed=0, device="cpu", mesh={"ep": 4})
    mesh = build_mesh(sys_cfg, devices=jax.devices()[:4])
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    tr = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3},
        scheduler={"type": "cosine"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr, 10)

    def loss_fn(p, b):
        return llama.loss_fn(p, b, MOE_ARGS)

    batch = _batch(bs=4)
    sstep, _ = make_train_step(loss_fn, opt)
    sstate = init_train_state(jax.tree_util.tree_map(jnp.copy, params), opt)
    _, smetrics = sstep(sstate, batch)

    step, shardings = make_train_step(loss_fn, opt, mesh=mesh, params_like=params)
    state = jax.device_put(init_train_state(params, opt), shardings)
    _, metrics = step(state, batch)
    assert float(metrics["loss"]) == pytest.approx(
        float(smetrics["loss"]), rel=1e-6)


@pytest.mark.slow
def test_shampoo_bank_stats_shard_over_ep():
    """Shampoo's per-expert preconditioner stats [E, m, m] must shard over
    ep with their bank, not replicate (parallel/sharding_rules.py
    match_opt_leaf_spec leading-dim inheritance)."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 virtual devices")
    sys_cfg = SystemConfig(seed=0, device="cpu", mesh={"ep": 2, "dp": 2})
    mesh = build_mesh(sys_cfg, devices=jax.devices()[:4])
    params = llama.init_params(jax.random.PRNGKey(0), MOE_ARGS)
    tr = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3},
        scheduler={"type": "cosine"},
        optimization={"optimizer": "shampoo"},
    )
    opt = build_optimizer(tr, 10)

    def loss_fn(p, b):
        return llama.loss_fn(p, b, MOE_ARGS)

    step, shardings = make_train_step(loss_fn, opt, mesh=mesh, params_like=params)
    state = jax.device_put(init_train_state(params, opt), shardings)
    state, metrics = step(state, _batch(bs=8))
    assert np.isfinite(float(metrics["loss"]))

    flat = jax.tree_util.tree_flatten_with_path(state["opt_state"])[0]
    stats = [(str(k), v) for k, v in flat if "stats_l" in str(k) and v.ndim == 3]
    assert stats, "no bank stats found in shampoo state"
    for k, v in stats:
        assert v.sharding.spec and v.sharding.spec[0] == "ep", f"{k}: {v.sharding.spec}"
