"""Tool-use agent loop (infer/agent.py — the working version of the
reference's dead generate_agent.py)."""

import pytest

from mlx_cuda_distributed_pretraining_tpu.infer.agent import (
    AgentStep,
    default_tools,
    run_agent,
    safe_calc,
    tool_prompt,
)


def test_safe_calc_arithmetic():
    assert safe_calc("2+2*3") == "8"
    assert safe_calc("(10 - 4) / 3") == "2.0"
    assert safe_calc("2**10") == "1024"


def test_safe_calc_rejects_code():
    assert safe_calc("__import__('os')").startswith("error")
    assert safe_calc("open('/etc/passwd')").startswith("error")
    assert safe_calc("x + 1").startswith("error")
    assert safe_calc("1/0").startswith("error")


def test_agent_executes_tool_and_feeds_result_back():
    contexts = []

    def fake_gen(context):
        contexts.append(context)
        if len(contexts) == 1:
            return "Let me compute that. <<calc: 6*7>>"
        return "The answer is 42."

    final, trace = run_agent(fake_gen, "what is 6*7?")
    assert final == "The answer is 42."
    assert trace[0].tool == "calc" and trace[0].result == "42"
    # result was injected into the follow-up context
    assert "<<result: 42>>" in contexts[1]
    # tool docs are in the first context
    assert "calc" in contexts[0] and "what is 6*7?" in contexts[0]


def test_agent_discards_speculation_after_tool_call():
    calls = []

    def fake_gen(context):
        calls.append(context)
        if len(calls) == 1:
            return "<<calc: 1+1>> and then I guess the answer is 7"
        return "It is 2."

    final, trace = run_agent(fake_gen, "1+1?")
    assert final == "It is 2."
    assert "I guess" not in trace[0].text


def test_agent_unknown_tool_reports_error():
    calls = iter(["<<frobnicate: x>>", "ok"])

    def fake_gen(context):
        return next(calls)

    final, trace = run_agent(fake_gen, "hi")
    assert trace[0].result.startswith("error: unknown tool")
    assert final == "ok"


def test_agent_turn_budget():
    def always_tool(context):
        return "<<calc: 1+1>>"

    final, trace = run_agent(always_tool, "loop forever", max_turns=3)
    assert len(trace) == 3
    assert all(s.tool == "calc" for s in trace)


def test_tool_prompt_lists_tools():
    p = tool_prompt(default_tools())
    assert "calc" in p and "wordcount" in p


def test_safe_calc_caps_magnitude_and_exponent():
    assert safe_calc("9**9**9").startswith("error")
    assert safe_calc("10**300 * 10**300").startswith("error")
    assert safe_calc("2**64").startswith("error")
    assert safe_calc("2**10") == "1024"
