"""End-to-end tracing (obs/trace.py and its integrations).

Three layers:
  1. Recorder semantics — bounded ring with drop accounting, disabled
     path allocating nothing, deterministic per-trace-id sampling,
     Chrome trace-event document shape.
  2. Serving fleet — one X-Trace-Id names a request across the
     router->replica hop (real in-process HTTP servers), /trace dumps
     merge into per-request span trees with every completed request
     accounted for, and the response body carries the server-side
     queue/prefill/decode breakdown that load_gen's --trace-out CSV and
     the TTFT histograms are built from.
  3. Trainer — per-phase span sums reconcile with the goodput ledger on
     a short CPU run (the spans carry the ledger's own numbers, so the
     match is by construction, and the test pins that construction).
"""

import importlib.util
import json
import math
import os
import urllib.request

import jax
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import Config, DataConfig
from mlx_cuda_distributed_pretraining_tpu.infer.server import (
    InferenceService,
    serve,
)
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.obs.metrics import (
    quantile_from_buckets,
)
from mlx_cuda_distributed_pretraining_tpu.obs.trace import (
    TRACE_HEADER,
    Tracer,
    merge_chrome_traces,
    new_trace_id,
    sampled,
)
from mlx_cuda_distributed_pretraining_tpu.serve import (
    BatchEngine,
    EngineConfig,
    Router,
    serve_router,
)
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOK = TokenizerManager(DataConfig())
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)


def _load_script(name):
    """Import a scripts/*.py module by path (scripts/ is not a package).
    trace_report and load_gen are stdlib-only, so this stays cheap."""
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- recorder semantics (no device) -------------------------------------------

def test_disabled_tracer_is_allocation_free_and_silent():
    tr = Tracer("t", enabled=False)
    a = tr.span("x")
    b = tr.span("y", trace_id=new_trace_id())
    assert a is b  # the shared null singleton, no Span allocated
    assert a.end() == 0.0
    with tr.span("z"):
        pass
    tr.complete("w", 0.5)
    tr.instant("i")
    assert tr.stats() == {"recorded": 0, "dropped": 0, "buffered": 0}
    assert tr.chrome_trace()["traceEvents"][0]["ph"] == "M"  # metadata only
    assert len(tr.chrome_trace()["traceEvents"]) == 1


def test_ring_overwrites_oldest_and_counts_drops():
    tr = Tracer("t", capacity=4)
    for i in range(10):
        tr.complete(f"s{i}", 0.001)
    st = tr.stats()
    assert st == {"recorded": 10, "dropped": 6, "buffered": 4}
    names = [e["name"] for e in tr.chrome_events() if e["ph"] == "X"]
    assert names == ["s6", "s7", "s8", "s9"]  # newest 4, oldest first
    doc = tr.chrome_trace()
    assert doc["metadata"]["dropped"] == 6
    # drain empties the ring but keeps lifetime counters
    assert len(tr.drain()) == 4
    assert tr.stats() == {"recorded": 10, "dropped": 6, "buffered": 0}


def test_span_records_once_and_complete_places_by_end_mono():
    tr = Tracer("t")
    with tr.span("ctx", step=1):
        pass
    s = tr.span("manual", trace_id="f" * 32)
    s.end(extra=7)
    s.end()  # idempotent: second end records nothing
    tr.complete("booked", 0.25, end_mono=10.0)
    evs = [e for e in tr.chrome_events() if e["ph"] == "X"]
    assert [e["name"] for e in evs] == ["ctx", "manual", "booked"]
    assert evs[0]["args"] == {"step": 1}
    assert evs[1]["args"] == {"extra": 7, "trace_id": "f" * 32}
    booked = evs[2]
    assert booked["dur"] == 250_000  # the identical measured duration
    # placed ending at end_mono: ts = wall(end_mono - dur)
    assert booked["ts"] == tr._wall_us(10.0 - 0.25)
    assert tr.stats()["recorded"] == 3


def test_sampling_is_deterministic_per_trace_id():
    assert sampled("anything", 1.0) and not sampled("anything", 0.0)
    assert sampled("not-hex!", 0.5)  # malformed ids err toward tracing
    ids = [new_trace_id() for _ in range(200)]
    kept = [t for t in ids if sampled(t, 0.5)]
    assert 0 < len(kept) < len(ids)  # a fraction, not all-or-nothing
    # every process holding the same id reaches the same verdict
    for t in ids:
        assert sampled(t, 0.5) == sampled(t, 0.5)
    tr = Tracer("t", sample=0.0)
    assert tr.span("s", trace_id=ids[0]).end() == 0.0
    tr.complete("s", 0.1, trace_id=ids[0])
    assert tr.stats()["recorded"] == 0
    # spans WITHOUT a trace id (trainer phases) are always recorded
    tr.complete("phase", 0.1)
    assert tr.stats()["recorded"] == 1


def test_merge_chrome_traces_concatenates_timelines():
    a, b = Tracer("a"), Tracer("b")
    a.complete("x", 0.01)
    b.complete("y", 0.01)
    merged = merge_chrome_traces([a.chrome_trace(), b.chrome_trace()])
    names = {e["name"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert names == {"x", "y"}
    procs = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M"}
    assert procs == {"a", "b"}


def test_quantile_from_buckets_upper_bound_estimate():
    rows = [[1.0, 5], [5.0, 9], ["+Inf", 10]]
    assert quantile_from_buckets(rows, 10, 0.5) == 1.0
    assert quantile_from_buckets(rows, 10, 0.9) == 5.0
    # observations past the last finite bound report that bound
    assert quantile_from_buckets(rows, 10, 0.99) == 5.0
    assert quantile_from_buckets(rows, 0, 0.5) is None
    assert quantile_from_buckets([], 10, 0.5) is None


# -- serving fleet ------------------------------------------------------------

def _engine(**kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": 128,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg)


def _replica(**kw):
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    service.engine = _engine(**kw).start()
    httpd = serve(service, port=0)
    return service, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _get_json(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_engine_tracing_spans_breakdown_and_ttft_histograms():
    eng = _engine(trace=True).start()
    try:
        out = eng.generate("the quick brown fox", max_tokens=6,
                           temperature=0.0, timeout=300.0)
    finally:
        eng.stop()
    # response carries the minted id + the monotonic-stamp breakdown
    assert len(out["trace_id"]) == 32
    assert out["queue_ms"] >= 0.0
    assert out["prefill_ms"] >= 0.0 and out["decode_ms"] >= 0.0
    assert out["ttft_ms"] == pytest.approx(
        out["queue_ms"] + out["prefill_ms"], abs=0.1)
    # spans cover the request lifecycle, all keyed by the one id
    spans = [e for e in eng.tracer.chrome_events() if e.get("ph") == "X"]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    for name in ("queue_wait", "prefill_chunk", "decode", "request"):
        assert name in by_name, f"missing span {name}"
        assert all(e["args"]["trace_id"] == out["trace_id"]
                   for e in by_name[name])
    # stream_emit instants mark SSE pushes only, so a buffered generate
    # records just the admission marker
    instants = {e["name"] for e in eng.tracer.chrome_events()
                if e.get("ph") == "i"}
    assert "kv_alloc" in instants
    # the terminal request span nests the component spans (one timeline)
    req = by_name["request"][0]
    for name in ("queue_wait", "prefill_chunk", "decode"):
        for e in by_name[name]:
            assert e["ts"] >= req["ts"] - 1000
            assert e["ts"] + e["dur"] <= req["ts"] + req["dur"] + 1000
    # the same components feed the bounded histograms
    snap = eng.metrics_registry.snapshot()
    assert snap["serve_ttft_ms"]["series"][0]["count"] >= 1
    comps = {s["labels"]["component"]
             for s in snap["serve_ttft_component_ms"]["series"]}
    assert {"queue", "prefill", "decode"} <= comps
    assert eng._ttft_quantiles().keys() == {
        "ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
        "ttft_ms_sum", "ttft_ms_count"}


def test_engine_tracing_disabled_records_nothing_on_hot_path():
    eng = _engine().start()  # trace defaults off
    try:
        out = eng.generate("the quick brown fox", max_tokens=4,
                           temperature=0.0, timeout=300.0)
    finally:
        eng.stop()
    assert not eng.cfg.trace
    assert eng.tracer.stats() == {"recorded": 0, "dropped": 0, "buffered": 0}
    # ids and the TTFT breakdown still flow — they cost no span objects
    assert len(out["trace_id"]) == 32
    assert out["queue_ms"] >= 0.0


def test_router_propagates_one_trace_id_and_report_merges(tmp_path):
    sa, ha, ua = _replica(trace=True)
    sb, hb, ub = _replica(trace=True)
    router = Router([ua, ub], poll_interval_s=0.1, retries=2, trace=True)
    rhttpd = serve_router(router, port=0)
    url = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        # flood through the router with load_gen, CSV capture on
        load_gen = _load_script("load_gen")
        csv_path = str(tmp_path / "requests.csv")
        summary = load_gen.run_load(
            url, concurrency=2, requests=5, prompt="the quick brown fox",
            max_tokens=4, temperature=0.0, deadline_s=None, timeout=300.0,
            trace_out=csv_path)
        assert summary["ok"] == 5 and summary["traced_requests"] == 5
        # plus one request with a client-minted id: it must survive the
        # router hop and come back in both body and response header
        mine = new_trace_id()
        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps({"prompt": "trace me", "max_tokens": 4,
                             "temperature": 0.0}).encode(),
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: mine})
        with urllib.request.urlopen(req, timeout=300.0) as resp:
            assert resp.headers.get(TRACE_HEADER) == mine
            out = json.loads(resp.read())
        assert out["trace_id"] == mine

        # CSV: one row per request, trace ids filled, breakdown numeric
        with open(csv_path) as f:
            lines = [ln.strip().split(",") for ln in f if ln.strip()]
        header, rows = lines[0], lines[1:]
        assert header[:2] == ["trace_id", "status"] and len(rows) == 5
        idx = {k: i for i, k in enumerate(header)}
        for row in rows:
            assert len(row[idx["trace_id"]]) == 32
            assert float(row[idx["queue_ms"]]) >= 0.0
            assert float(row[idx["prefill_ms"]]) >= 0.0

        # dump every ring and merge by id
        paths = []
        for name, u in (("router", url), ("r0", ua), ("r1", ub)):
            doc = _get_json(u + "/trace")
            p = tmp_path / f"{name}.json"
            p.write_text(json.dumps(doc))
            paths.append(str(p))
        docs = [json.loads(open(p).read()) for p in paths]
        route_ids = {e["args"]["trace_id"]
                     for e in docs[0]["traceEvents"] if e.get("ph") == "X"}
        request_ids = {e["args"]["trace_id"]
                       for d in docs[1:] for e in d["traceEvents"]
                       if e.get("ph") == "X" and e["name"] == "request"}
        csv_ids = {row[idx["trace_id"]] for row in rows} | {mine}
        # one id names each request on BOTH sides of the hop
        assert csv_ids <= route_ids
        assert csv_ids <= request_ids

        report = _load_script("trace_report").report(paths, top=2)
        acct = next(ln for ln in report
                    if ln.startswith("requests_complete="))
        assert "requests_complete=6" in acct
        assert "route_unmatched=0" in acct  # every request accounted for
        assert any(ln.startswith("component=queue_wait") for ln in report)
        assert any(ln.startswith("component=prefill") for ln in report)
        # the slowest-request tree nests replica spans under the router's
        i_route = next(i for i, ln in enumerate(report)
                       if ln.lstrip().startswith("span=route"))
        assert report[i_route].startswith("  span=route")
        i_req = next(i for i, ln in enumerate(report[i_route:])
                     if ln.lstrip().startswith("span=request")) + i_route
        assert report[i_req].startswith("    span=request")
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        for s, h in ((sa, ha), (sb, hb)):
            s.close()
            h.shutdown()
            h.server_close()


# -- trainer ------------------------------------------------------------------

def _tiny_cfg_dict(tmp_path, name, iters, **extra):
    train = tmp_path / "train.jsonl"
    if not train.exists():
        with open(train, "w") as f:
            for _ in range(40):
                f.write(json.dumps(
                    {"text": "the quick brown fox jumps over the lazy dog "
                             * 4}) + "\n")
    d = {
        "name": name,
        "overwrite": True,
        "data": {
            "input_file": str(train),
            "preprocessing": {"max_context_size": 64},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64,
                           "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2,
                                "iters": iters},
            "scheduler": {"type": "cosine", "min_lr_ratio": 0.1},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "steps": {"logging_interval": 5, "checkpoint_interval": 3,
                      "validation_interval": 0},
        },
        "system": {"seed": 0, "device": "cpu"},
    }
    for k, v in extra.items():
        node = d
        parts = k.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return d


def test_trainer_spans_reconcile_with_goodput_ledger(tmp_path):
    """The tentpole invariant: per-component span sums match the goodput
    ledger's cumulative totals (the spans carry the ledger's own
    durations, so within 5% is conservative)."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    cfg = Config.from_dict(_tiny_cfg_dict(
        tmp_path, "traced", iters=7,
        **{"logging.trace": {"enabled": True, "capacity": 65536}}))
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()
    assert tr.tracer.enabled and tr.tracer.stats()["dropped"] == 0

    per_span = {}
    events = tr.tracer.chrome_events()
    for e in events:
        if e.get("ph") == "X":
            per_span[e["name"]] = per_span.get(e["name"], 0.0) \
                + e["dur"] / 1e6
    # totals() folds only on close_window; time booked after the last
    # window closed (the final checkpoint) still sits in the open window.
    totals = tr.goodput.totals()
    for comp, v in tr.goodput.window_view().items():
        totals[comp] = totals.get(comp, 0.0) + v
    checked = 0
    for comp, booked in totals.items():
        if comp in ("other_s", "restart_lost_s") or booked < 1e-3:
            continue  # no span mirrors the residual; skip sub-ms noise
        name = comp[:-2]
        assert per_span.get(name, 0.0) == pytest.approx(
            booked, rel=0.05), f"{name} spans diverge from ledger {comp}"
        checked += 1
    assert checked >= 2  # at least dispatch + ckpt_save on any CPU run
    assert per_span.get("dispatch", 0.0) > 0.0
    assert per_span.get("ckpt_save", 0.0) > 0.0
    # one step_window instant per closed window, carrying tok/s
    wins = [e for e in events
            if e.get("ph") == "i" and e["name"] == "step_window"]
    assert wins and all("tok_s" in w["args"] for w in wins)

    # the ring was exported to the run dir at exit, loadable as-is
    out = os.path.join(tr.run_dir, "trace.json")
    assert os.path.isfile(out)
    doc = json.load(open(out))
    assert doc["displayTimeUnit"] == "ms"
    assert any(e.get("name") == "dispatch" for e in doc["traceEvents"])
    # and trace_report's attribution section reads it
    report = _load_script("trace_report").report([out])
    assert any(ln.startswith("trainer_attribution=1") for ln in report)
    assert any(ln.startswith("phase=dispatch") for ln in report)
    for ln in report:
        if ln.startswith("phase="):
            assert not math.isnan(float(ln.split("total_s=")[1].split()[0]))
