"""Tensor-parallel serving (GSPMD batch engine) + reshard-on-load.

Everything runs on the conftest-forced 8-device virtual CPU platform:
tp=2 meshes take a 2-device prefix. The bar throughout is token-for-token
greedy identity with the unsharded (mesh=None) engine — sharding is a
layout annotation, never a numerics change.
"""

import threading
import warnings
from types import SimpleNamespace

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
    CheckpointManager,
)
from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import (
    save_safetensors,
)
from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.parallel import (
    build_mesh,
    build_serve_mesh,
    mesh_axis_sizes,
    parse_mesh_spec,
)
from mlx_cuda_distributed_pretraining_tpu.parallel.sharding_rules import (
    param_pspec,
    tree_pspecs,
)
from mlx_cuda_distributed_pretraining_tpu.serve import BatchEngine, EngineConfig
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

TOK = TokenizerManager(DataConfig())
# num_heads=4 and num_kv_heads=2 both divide tp=2: attention shards clean.
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)
MAX_LEN = 128

PROMPTS = ["the quick brown fox", "a b c a b c a", "hello world hello world"]


def _tp2():
    # Exact 2-device prefix: no stranded devices, no warning.
    return build_serve_mesh({"tp": 2}, devices=jax.devices()[:2])


def _engine(mesh=None, **kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": MAX_LEN,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg, mesh=mesh)


def _collect(eng, prompts, max_tokens=24, **gen_kw):
    eng.start()
    outs = [None] * len(prompts)
    try:
        def run(i):
            outs[i] = eng.generate(prompts[i], max_tokens=max_tokens,
                                   timeout=300.0, **gen_kw)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        metrics = eng.metrics()
    finally:
        eng.stop()
    return outs, metrics


# -- mesh construction --------------------------------------------------------

def test_parse_mesh_spec():
    assert parse_mesh_spec("tp=2") == {"tp": 2}
    assert parse_mesh_spec("tp=2, dp=4") == {"tp": 2, "dp": 4}
    assert parse_mesh_spec("") == {}
    with pytest.raises(ValueError, match="axis=N"):
        parse_mesh_spec("tp")
    with pytest.raises(ValueError, match="axis size"):
        parse_mesh_spec("tp=two")


def test_build_serve_mesh_none_on_trivial_specs():
    # None means "run the pre-mesh single-device path": the engine's jit
    # cache keys stay byte-identical to a build without the mesh feature.
    assert build_serve_mesh(None) is None
    assert build_serve_mesh({}) is None
    assert build_serve_mesh({"tp": 1, "dp": 1}) is None
    assert build_serve_mesh("tp=1") is None


def test_build_serve_mesh_rejects_trainer_axes():
    with pytest.raises(ValueError, match="trainer-only"):
        build_serve_mesh({"fsdp": 2})


def test_build_serve_mesh_shapes():
    mesh = _tp2()
    assert dict(mesh.shape) == {"tp": 2} and mesh.size == 2
    both = build_serve_mesh("dp=2,tp=2", devices=jax.devices()[:4])
    # AXIS_ORDER puts dp before tp — same order the trainer mesh uses.
    assert tuple(both.axis_names) == ("dp", "tp")


def test_stranded_devices_warn_loudly():
    with pytest.warns(RuntimeWarning, match="STRANDED"):
        sizes = mesh_axis_sizes(SimpleNamespace(mesh={"tp": 2}), 8)
    assert sizes == {"tp": 2}
    # Exact cover: silent.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert mesh_axis_sizes(SimpleNamespace(mesh={"tp": 2}), 2) == {"tp": 2}
        assert mesh_axis_sizes(SimpleNamespace(mesh={"dp": -1}), 8) == {"dp": 8}


# -- tp engine parity ---------------------------------------------------------

@pytest.mark.parametrize("arm", [
    {},                                          # base paged
    {"kv_quant": True},                          # int8 KV quartet sharded
    {"spec_draft_len": 4, "spec_max_ngram": 3},  # spec-decode on top of tp
], ids=["base", "int8", "spec"])
def test_tp2_greedy_matches_unsharded(arm):
    ref, _ = _collect(_engine(**arm), PROMPTS, temperature=0.0)
    tp, m = _collect(_engine(mesh=_tp2(), **arm), PROMPTS, temperature=0.0)
    assert m["mesh"] == "tp=2"
    for r, t in zip(ref, tp):
        assert t["text"] == r["text"]
        assert t["tokens"] == r["tokens"]
        assert t["finish_reason"] == r["finish_reason"]
    if arm.get("spec_draft_len"):
        assert m["spec_proposed"] >= m["spec_accepted"] >= 0


def test_tp2_prefix_cache_adoption_parity():
    # Sequential requests sharing a long prefix: the second adopts the
    # first one's cached KV blocks, which under tp=2 live sharded over
    # the head axis.
    shared = "the quick brown fox jumps over the lazy dog and then"
    prompts = [shared + " stops", shared + " keeps going"]

    def run(eng):
        eng.start()
        try:
            outs = [eng.generate(p, max_tokens=24, temperature=0.0,
                                 timeout=300.0) for p in prompts]
            return outs, eng.metrics()["prefix_cache_hits"]
        finally:
            eng.stop()

    ref, ref_hits = run(_engine(block_size=16, prefix_min_hit_blocks=1))
    tp, tp_hits = run(_engine(mesh=_tp2(), block_size=16,
                              prefix_min_hit_blocks=1))
    assert tp_hits == ref_hits and tp_hits >= 1
    for r, t in zip(ref, tp):
        assert t["text"] == r["text"]


def test_mesh_metrics_surface():
    eng = _engine(mesh=_tp2())
    m = eng.metrics()
    assert m["mesh"] == "tp=2"
    assert _engine().metrics()["mesh"] == "1dev"


# -- reshard-on-load ----------------------------------------------------------

def test_reshard_on_load_fsdp2_checkpoint_into_tp2(tmp_path):
    # A checkpoint written under a TRAINING mesh (fsdp=2) loads directly
    # into the SERVING sharding (tp=2): no host gather, and no device ever
    # holds a full replica of a sharded matrix.
    devs = jax.devices()
    fsdp_mesh = build_mesh(SimpleNamespace(mesh={"fsdp": 2}), devs[:2])
    placed = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(fsdp_mesh, spec)),
        PARAMS, tree_pspecs(PARAMS, fsdp_mesh))
    flat_host = {k: np.asarray(v) for k, v in flatten_dict(placed).items()}
    path = str(tmp_path / "model.safetensors")
    save_safetensors(path, flat_host)

    tp_mesh = _tp2()
    loaded = CheckpointManager.load_params(path, like=PARAMS, mesh=tp_mesh)
    flat = flatten_dict(loaded)

    # Column-parallel attention weight: one half per device, exactly.
    wq = flat["layers.0.attention.wq.weight"]
    assert wq.sharding.mesh == tp_mesh
    assert wq.sharding.spec == param_pspec(
        "layers.0.attention.wq.weight", wq.shape, tp_mesh)
    shard_bytes = [s.data.nbytes for s in wq.addressable_shards]
    assert len(shard_bytes) == 2
    assert all(b == wq.nbytes // 2 for b in shard_bytes)

    # Per-device buffer accounting across the WHOLE tree: a leaf sharded
    # over tp contributes exactly its host bytes (half per device), a
    # replicated leaf contributes 2x. Full-replica materialization of the
    # sharded leaves would blow this exact budget.
    expected = actual = 0
    for k, v in flat.items():
        sharded = any(ax is not None
                      for ax in param_pspec(k, v.shape, tp_mesh))
        expected += v.nbytes * (1 if sharded else 2)
        actual += sum(s.data.nbytes for s in v.addressable_shards)
    host_total = sum(v.nbytes for v in flat_host.values())
    assert actual == expected
    assert actual < 2 * host_total  # proves something actually sharded

    # And the resharded params serve token-identically.
    ref, _ = _collect(_engine(), PROMPTS[:2], temperature=0.0)
    cfg = EngineConfig(num_slots=2, max_len=MAX_LEN, prefill_chunk=16)
    tp, _ = _collect(BatchEngine(loaded, ARGS, TOK, cfg, mesh=tp_mesh),
                     PROMPTS[:2], temperature=0.0)
    for r, t in zip(ref, tp):
        assert t["text"] == r["text"]
        assert t["tokens"] == r["tokens"]


def test_load_params_mesh_rejects_dtype_mismatch(tmp_path):
    # With a mesh, a dtype cast would re-materialize the full array on the
    # host — load_params must refuse instead of silently gathering.
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointIntegrityError,
    )

    flat = {k: np.asarray(v) for k, v in flatten_dict(PARAMS).items()}
    key = "layers.0.attention.wq.weight"
    flat[key] = flat[key].astype(np.float16)
    path = str(tmp_path / "model.safetensors")
    save_safetensors(path, flat)
    with pytest.raises(CheckpointIntegrityError, match="re-materialize"):
        CheckpointManager.load_params(path, like=PARAMS, mesh=_tp2())


# -- subprocess device forcing (shared conftest helper) -----------------------

@pytest.mark.slow
def test_spawn_with_devices_forces_child_device_count():
    import sys

    from conftest import spawn_with_devices

    src = (
        "import jax\n"
        "from mlx_cuda_distributed_pretraining_tpu.parallel import build_serve_mesh\n"
        "assert jax.device_count() == 2, jax.device_count()\n"
        "mesh = build_serve_mesh('tp=2')\n"
        "print('CHILD_OK', dict(mesh.shape))\n"
    )
    proc = spawn_with_devices([sys.executable, "-c", src], n=2)
    out, _ = proc.communicate(timeout=300)
    assert proc.returncode == 0, out[-2000:]
    assert "CHILD_OK {'tp': 2}" in out
