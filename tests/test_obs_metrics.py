"""Unified telemetry tests: metrics registry, FLOPs/MFU/goodput
accounting, structured event log, Prometheus exposition, and the
supervisor hang watchdog.

Fast tests cover each obs/ primitive in isolation plus one CPU trainer
smoke run asserting the acceptance contract: every window line reports
``mfu=`` and a goodput breakdown summing to window wall time, and the
live ``/metrics`` scrape agrees with the final ``events.jsonl`` tallies.
The slow test stalls a synthetic child and proves the watchdog
SIGTERMs + restarts it with the lost time booked as ``restart_lost_s``.
"""

import json
import os
import re
import socket
import sys
import textwrap
import threading
import time
import urllib.request

import pytest

from mlx_cuda_distributed_pretraining_tpu.obs.events import (
    EventLog,
    append_event,
    events_path,
    heartbeat_path,
    iter_events,
    read_heartbeat,
    replay_into,
    tally,
    write_heartbeat,
)
from mlx_cuda_distributed_pretraining_tpu.obs.flops import (
    GOODPUT_COMPONENTS,
    GoodputLedger,
    flops_per_token,
    mfu,
    model_flops_per_token,
    peak_flops_per_chip,
)
from mlx_cuda_distributed_pretraining_tpu.obs.metrics import MetricsRegistry
from mlx_cuda_distributed_pretraining_tpu.obs.prometheus import (
    MetricsServer,
    render_prometheus,
    start_metrics_server,
)


# -- metrics registry -------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    g.set(3)
    assert g.value() == 3.0
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    s = snap["lat_seconds"]["series"][0]
    assert s["count"] == 3 and s["sum"] == pytest.approx(5.55)
    # cumulative buckets: <=0.1 holds 1, <=1.0 holds 2, +Inf holds 3
    assert s["buckets"] == [[0.1, 1], [1.0, 2], ["+Inf", 3]]


def test_registry_kind_and_sign_errors():
    reg = MetricsRegistry()
    c = reg.counter("c", "")
    with pytest.raises(TypeError):
        c.set(1.0)
    with pytest.raises(ValueError):
        c.inc(-1.0)
    with pytest.raises(TypeError):
        reg.gauge("c", "")  # name already registered as a counter


def test_registry_labels_and_series_bound():
    reg = MetricsRegistry(max_series_per_metric=3)
    c = reg.counter("by_kind_total", "")
    for kind in ("a", "b", "c", "d", "e"):
        c.inc(kind=kind)
    snap = reg.snapshot()
    assert len(snap["by_kind_total"]["series"]) == 3
    assert snap["_dropped_series"] == 2
    # existing series keep accepting increments at the bound
    c.inc(kind="a")
    assert c.value(kind="a") == 2.0


def test_registry_thread_concurrency():
    reg = MetricsRegistry()
    c = reg.counter("hits_total", "")
    g = reg.gauge("level", "")

    def work(n):
        for i in range(500):
            c.inc()
            g.set(i)
            if i % 100 == 0:
                reg.snapshot()

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8 * 500


def test_registry_flat_view():
    reg = MetricsRegistry()
    reg.counter("a_total", "").inc(2)
    reg.counter("b_total", "").inc(1, kind="x")
    reg.histogram("h", "").observe(1.0)
    flat = reg.flat()
    assert flat["a_total"] == 2.0
    assert flat["b_total{kind=x}"] == 1.0
    assert "h" not in flat  # histograms stay out of the scalar view


# -- FLOPs / MFU / goodput --------------------------------------------------

def test_flops_per_token_hand_check():
    # 6N + 6*L*S*d_attn with N=1e6, L=4, S=128, d_attn=64:
    # 6e6 + 6*4*128*64 = 6,000,000 + 196,608
    assert flops_per_token(1_000_000, 4, 128, 64) == 6_196_608.0


def test_model_flops_per_token_uses_heads_times_head_dim():
    class M:
        num_layers = 2
        num_heads = 4
        head_dim = 8

    assert model_flops_per_token(M, 1000, 64) == \
        flops_per_token(1000, 2, 64, 32)


def test_peak_flops_detection_and_env_override(monkeypatch):
    assert peak_flops_per_chip("TPU v5 lite") == 197e12
    assert peak_flops_per_chip("TPU v5p chip") == 459e12
    assert peak_flops_per_chip("NVIDIA H100 80GB") == 989e12
    assert peak_flops_per_chip("cpu") is None
    monkeypatch.setenv("GRAFT_PEAK_FLOPS", "123e12")
    assert peak_flops_per_chip("cpu") == 123e12
    monkeypatch.setenv("GRAFT_PEAK_FLOPS", "not-a-number")
    assert peak_flops_per_chip("cpu") is None


def test_mfu_value_and_unknown():
    # 1000 tok/s * 1e9 FLOPs/tok over 2 chips of 1e12 → 0.5
    assert mfu(1000.0, 1e9, 1e12, 2) == pytest.approx(0.5)
    assert mfu(1000.0, 1e9, None, 2) is None
    assert mfu(1000.0, 1e9, 0.0, 2) is None


def test_goodput_ledger_residual_and_totals():
    led = GoodputLedger()
    led.add("dispatch_s", 3.0)
    led.add("data_wait_s", 1.0)
    led.add("ckpt_save_s", -5.0)  # negative clamps to zero
    with pytest.raises(KeyError):
        led.add("nonsense_s", 1.0)
    win = led.close_window(10.0)
    assert win["dispatch_s"] == 3.0
    assert win["other_s"] == pytest.approx(6.0)
    assert sum(win.values()) == pytest.approx(10.0)
    # window reset; booked time beyond elapsed clamps the residual at 0
    led.add("dispatch_s", 9.0)
    win2 = led.close_window(4.0)
    assert win2["other_s"] == 0.0
    totals = led.totals()
    assert totals["dispatch_s"] == pytest.approx(12.0)
    assert set(GOODPUT_COMPONENTS) < set(totals)


# -- event log --------------------------------------------------------------

def test_events_round_trip_and_torn_line(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.append("run_start", name="t", total_steps=10)
    log.append("step_window", step=5, steps=5, toks=320, loss=2.0,
               goodput={"dispatch_s": 1.0})
    log.close()
    append_event(path, "fault", kind="hang", stalled_s=3.0)
    with open(path, "a") as f:
        f.write('{"v":1,"type":"truncat')  # crash mid-append
    evs = list(iter_events(path))
    assert [e["type"] for e in evs] == ["run_start", "step_window", "fault"]
    assert all(e["v"] == 1 and "t" in e for e in evs)


def test_replay_rebuilds_registry_and_matches_tally(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.append("run_start", name="t")
    log.append("step_window", step=5, steps=5, toks=100,
               goodput={"dispatch_s": 2.0, "other_s": 1.0})
    log.append("step_window", step=10, steps=5, toks=100,
               goodput={"dispatch_s": 3.0})
    log.append("checkpoint_save", step=10, seconds=0.5)
    log.append("eval", loss=2.0, seconds=0.1)
    log.append("fault", kind="hang", stalled_s=9.0)
    log.append("restart", lost_s=12.5, resume="10")
    log.close()

    reg = MetricsRegistry()
    assert replay_into(reg, path) == 7
    assert reg.counter("train_steps_total").value() == 10.0
    assert reg.counter("train_tokens_total").value() == 200.0
    assert reg.counter("checkpoint_saves_total").value() == 1.0
    assert reg.counter("eval_runs_total").value() == 1.0
    assert reg.counter("faults_total").value(kind="hang") == 1.0
    assert reg.counter("restarts_total").value() == 1.0
    gp = reg.counter("goodput_seconds_total")
    assert gp.value(component="dispatch_s") == 5.0
    assert gp.value(component="restart_lost_s") == 12.5

    t = tally(path)
    assert t["steps"] == 10 and t["toks"] == 200
    assert t["checkpoint_saves"] == 1 and t["evals"] == 1
    assert t["faults"] == 1 and t["restarts"] == 1 and t["events"] == 7


def test_replay_missing_file_is_zero(tmp_path):
    assert replay_into(MetricsRegistry(), str(tmp_path / "none.jsonl")) == 0


def test_heartbeat_write_read_atomic(tmp_path):
    hb_path = str(tmp_path / "heartbeat.json")
    write_heartbeat(hb_path, step=42)
    hb = read_heartbeat(hb_path)
    assert hb["step"] == 42 and hb["pid"] == os.getpid()
    assert abs(hb["t"] - time.time()) < 5.0
    assert not os.path.exists(hb_path + ".tmp")
    with open(hb_path, "w") as f:
        f.write("{torn")
    assert read_heartbeat(hb_path) is None
    assert read_heartbeat(str(tmp_path / "absent.json")) is None


# -- prometheus exposition --------------------------------------------------

def test_render_prometheus_format():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests served").inc(3, code="200")
    reg.gauge("depth", "queue depth").set(1.5)
    reg.histogram("lat", "latency", buckets=(0.1, 1.0)).observe(0.5)
    text = render_prometheus(reg.snapshot())
    assert "# HELP req_total requests served" in text
    assert "# TYPE req_total counter" in text
    assert 'req_total{code="200"} 3' in text
    assert "depth 1.5" in text
    assert 'lat_bucket{le="0.1"} 0' in text
    assert 'lat_bucket{le="1"} 1' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_sum 0.5" in text and "lat_count 1" in text
    assert text.rstrip().endswith("telemetry_dropped_series_total 0")


def test_metrics_server_scrape_and_health():
    reg = MetricsRegistry()
    reg.counter("scraped_total", "").inc(9)
    srv = MetricsServer(reg, port=0)  # OS-assigned port
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics", timeout=5).read().decode()
        assert "scraped_total 9" in text
        assert urllib.request.urlopen(f"{base}/healthz", timeout=5).status == 200
        snap = json.loads(
            urllib.request.urlopen(f"{base}/snapshot", timeout=5).read())
        assert snap["scraped_total"]["series"][0]["value"] == 9.0
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        srv.shutdown()


def test_start_metrics_server_survives_port_conflict():
    reg = MetricsRegistry()
    first = start_metrics_server(reg, 0, host="127.0.0.1")
    assert first is not None
    try:
        second = start_metrics_server(reg, first.port, host="127.0.0.1")
        assert second is None  # port taken → None, never an exception
    finally:
        first.shutdown()


# -- trainer integration (CPU smoke) ---------------------------------------

def _write_jsonl(path, texts):
    with open(path, "w") as f:
        for t in texts:
            f.write(json.dumps({"text": t}) + "\n")


def _tiny_config(tmp_path, name="telemetry", iters=15, **extra):
    from mlx_cuda_distributed_pretraining_tpu.config import Config

    train = tmp_path / "train.jsonl"
    val = tmp_path / "val.jsonl"
    corpus = ["the quick brown fox jumps over the lazy dog " * 4] * 40
    _write_jsonl(train, corpus)
    _write_jsonl(val, corpus[:10])
    d = {
        "name": name,
        "overwrite": True,
        "data": {
            "input_file": str(train),
            "validation_file": str(val),
            "preprocessing": {"max_context_size": 64},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64,
                           "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2,
                                "iters": iters},
            "scheduler": {"type": "cosine", "min_lr_ratio": 0.1},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "steps": {"logging_interval": 5, "checkpoint_interval": 15,
                      "validation_interval": 10},
        },
        "system": {"seed": 0, "device": "cpu"},
    }
    for k, v in extra.items():
        node = d
        for p in k.split(".")[:-1]:
            node = node.setdefault(p, {})
        node[k.split(".")[-1]] = v
    return Config.from_dict(d)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse_prom(text):
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, val = line.rsplit(" ", 1)
        out[name] = float(val)
    return out


def test_trainer_telemetry_end_to_end(tmp_path):
    """The acceptance contract: mfu + goodput on every window line (sum
    within 5% of window wall time), Prometheus counters matching the
    events.jsonl tallies, heartbeat + event stream on disk."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    port = _free_port()
    cfg = _tiny_config(tmp_path, iters=15, **{"logging.metrics_port": port})
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    try:
        result = tr.train()
        assert result["steps"] == 15

        # -- log lines: mfu + goodput breakdown on every window ------------
        window_lines = [
            ln for ln in open(os.path.join(tr.run_dir, "log.txt"))
            if ln.startswith("Step") and "loss=" in ln
            and "validation" not in ln]
        assert window_lines
        gp_keys = ("compile_s", "data_wait_s", "h2d_wait_s", "dispatch_s",
                   "ckpt_save_s", "eval_s", "other_s")
        for ln in window_lines:
            assert "mfu=unknown" in ln  # CPU: peak undetectable
            kv = dict(re.findall(r"([\w/]+)=([0-9.eE+-]+|unknown)", ln))
            for k in gp_keys:
                assert k in kv, f"missing {k} in: {ln}"
            toks, tok_s = float(kv["toks"]), float(kv["tok/s"])
            elapsed = toks / tok_s
            booked = sum(float(kv[k]) for k in gp_keys)
            # components + residual sum to window wall time (5% covers
            # the log-line float rounding)
            assert booked == pytest.approx(elapsed, rel=0.05), ln

        # -- live scrape agrees with the durable event log -----------------
        assert tr._metrics_server is not None
        url = f"http://127.0.0.1:{tr._metrics_server.port}/metrics"
        prom = _parse_prom(
            urllib.request.urlopen(url, timeout=5).read().decode())
        t = tally(events_path(tr.run_dir))
        assert prom["train_steps_total"] == t["steps"] == 15
        assert prom["train_tokens_total"] == t["toks"] > 0
        assert prom["checkpoint_saves_total"] == t["checkpoint_saves"] >= 2
        assert prom["eval_runs_total"] == t["evals"] >= 1
        assert prom["train_step"] == 15

        # -- event stream + heartbeat --------------------------------------
        types = [e["type"] for e in iter_events(events_path(tr.run_dir))]
        assert types[0] == "run_start"
        assert types[-1] == "run_end"
        assert "compile" in types and "step_window" in types
        assert "checkpoint_save" in types and "eval" in types
        win = next(e for e in iter_events(events_path(tr.run_dir))
                   if e["type"] == "step_window")
        assert win["mfu"] is None  # CPU
        assert sum(win["goodput"].values()) > 0
        hb = read_heartbeat(heartbeat_path(tr.run_dir))
        assert hb and hb["step"] == 15
    finally:
        if tr._metrics_server is not None:
            tr._metrics_server.shutdown()


def test_trainer_registry_replays_on_construction(tmp_path):
    """A second Trainer on the same run dir rebuilds its counters from
    events.jsonl — Prometheus totals survive process death."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    cfg = _tiny_config(tmp_path, name="replayed", iters=10)
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()
    t = tally(events_path(tr.run_dir))
    assert t["steps"] == 10

    cfg2 = _tiny_config(tmp_path, name="replayed", iters=10,
                        **{"overwrite": False,
                           "resume.checkpoint": "latest"})
    tr2 = Trainer(cfg2, runs_root=str(tmp_path / "runs"), quiet=True)
    assert tr2.metrics.counter("train_steps_total").value() >= 10.0


# -- hang watchdog ----------------------------------------------------------

def test_watchdog_last_progress_floors_stale_heartbeat(tmp_path):
    """A heartbeat left behind by a PREVIOUS child must not count against
    a freshly spawned one."""
    from mlx_cuda_distributed_pretraining_tpu.train.supervisor import Supervisor

    run_dir = str(tmp_path)
    write_heartbeat(heartbeat_path(run_dir), step=3)
    sup = Supervisor(lambda tag: ["true"], run_dir, log=lambda m: None)
    spawn_after = time.time() + 100
    assert sup._last_progress(spawn_after) == spawn_after
    spawn_before = time.time() - 100
    assert sup._last_progress(spawn_before) > spawn_before  # hb is newer


@pytest.mark.slow
def test_watchdog_restarts_hung_child_and_books_lost_time(tmp_path):
    """Synthetic hang: run 1 writes one heartbeat then stalls (trapping
    SIGTERM → exit 0, the nastiest case: a hang must count as a crash
    even on a clean exit code); run 2 completes. The supervisor must
    SIGTERM + restart, log fault/restart events, and the replayed
    registry must carry the lost wall clock as restart_lost_s."""
    from mlx_cuda_distributed_pretraining_tpu.train.supervisor import Supervisor

    run_dir = tmp_path / "run"
    (run_dir / "checkpoints").mkdir(parents=True)
    marker = tmp_path / "attempts.txt"
    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent(f"""
        import json, os, signal, sys, time
        marker = {str(marker)!r}
        n = int(open(marker).read()) if os.path.exists(marker) else 0
        open(marker, "w").write(str(n + 1))
        hb = {str(run_dir / "heartbeat.json")!r}
        tmp = hb + ".tmp"
        with open(tmp, "w") as f:
            json.dump({{"t": time.time(), "step": n, "pid": os.getpid()}}, f)
        os.replace(tmp, hb)
        if n == 0:
            signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
            time.sleep(300)  # hang: heartbeat never advances again
        sys.exit(0)
    """))

    sup = Supervisor(
        lambda tag: [sys.executable, str(child)],
        str(run_dir),
        backoff_base=0.05, backoff_max=0.05,
        hang_timeout_s=1.5, hang_kill_grace_s=5.0,
        log=lambda m: None,
    )
    rc = sup.run()
    assert rc == 0
    assert sup.hangs == 1 and sup.restarts == 1
    assert int(marker.read_text()) == 2

    evs = list(iter_events(events_path(str(run_dir))))
    fault = next(e for e in evs if e["type"] == "fault")
    assert fault["kind"] == "hang" and fault["stalled_s"] > 1.5
    restart = next(e for e in evs if e["type"] == "restart")
    assert restart["lost_s"] > 0
    post = next(e for e in evs if e["type"] == "postmortem")
    assert post["hang"] is True and post["rc"] == 0  # clean-exit hang

    reg = MetricsRegistry()
    replay_into(reg, events_path(str(run_dir)))
    assert reg.counter("faults_total").value(kind="hang") == 1.0
    assert reg.counter("restarts_total").value() == 1.0
    lost = reg.counter("goodput_seconds_total").value(
        component="restart_lost_s")
    assert lost == pytest.approx(restart["lost_s"])


# -- events.jsonl rotation (logging.events.max_bytes) -----------------------

def test_events_rotation_bounds_live_file_and_replay_reads_pair(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.obs.events import (
        rotated_events_path)

    path = str(tmp_path / "events.jsonl")
    cap = 600
    log = EventLog(path, now=lambda: 1000.0, max_bytes=cap)
    for i in range(30):
        log.append("step_window", step=(i + 1) * 5, steps=5, toks=10)
    log.close()
    rotated = rotated_events_path(path)
    assert os.path.exists(rotated)
    # rotation happens between complete lines, so both generations stay
    # under the cap (the live file strictly, the rotated one too)
    assert os.path.getsize(path) <= cap
    assert os.path.getsize(rotated) <= cap
    # readers see a contiguous SUFFIX of history ending at the newest
    # event — older generations age out by design, nothing interleaves
    evs = list(iter_events(path))
    steps = [e["step"] for e in evs]
    assert steps == list(range(steps[0], 151, 5)) and steps[-1] == 150
    assert 2 <= len(evs) < 30
    # a torn tail on the live file is still skipped, not fatal
    with open(path, "a") as f:
        f.write('{"v":1,"type":"torn')
    assert [e["step"] for e in iter_events(path)] == steps
    # replay_into rebuilds from the pair: 5 steps per surviving window
    reg = MetricsRegistry()
    assert replay_into(reg, path) == len(evs)
    assert reg.counter("train_steps_total").value() == 5.0 * len(evs)


def test_events_max_bytes_zero_never_rotates(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.obs.events import (
        rotated_events_path)

    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, max_bytes=0)
    for i in range(50):
        log.append("step_window", step=i, steps=1, toks=1)
    log.close()
    assert not os.path.exists(rotated_events_path(path))
    assert len(list(iter_events(path))) == 50


def test_logging_config_events_max_bytes_key():
    from mlx_cuda_distributed_pretraining_tpu.config import LoggingConfig

    assert LoggingConfig().events_max_bytes == 0
    cfg = LoggingConfig(events={"max_bytes": 1 << 20})
    assert cfg.events_max_bytes == 1 << 20


# -- TTFT histogram exposition pins -----------------------------------------

def test_ttft_prometheus_text_format_pin():
    """The serve_ttft_ms exposition shape external scrapers (graftscope,
    real Prometheus) parse: every LATENCY_MS_BUCKETS le line in order,
    cumulative counts, then _sum and _count. A bucket-boundary or
    formatting change must be a deliberate one."""
    from mlx_cuda_distributed_pretraining_tpu.obs.metrics import (
        LATENCY_MS_BUCKETS)

    reg = MetricsRegistry()
    h = reg.histogram("serve_ttft_ms", "time to first token (ms)",
                      buckets=LATENCY_MS_BUCKETS)
    for v in (3.0, 40.0, 800.0):
        h.observe(v)
    text = render_prometheus(reg.snapshot())
    lines = [ln for ln in text.splitlines()
             if ln.startswith("serve_ttft_ms")]
    want_cum = {1.0: 0, 2.5: 0, 5.0: 1, 10.0: 1, 25.0: 1, 50.0: 2,
                100.0: 2, 250.0: 2, 500.0: 2, 1000.0: 3, 2500.0: 3,
                5000.0: 3, 10000.0: 3, 30000.0: 3}
    expected = ['serve_ttft_ms_bucket{le="%g"} %d' % (le, want_cum[le])
                for le in LATENCY_MS_BUCKETS]
    expected += ['serve_ttft_ms_bucket{le="+Inf"} 3',
                 "serve_ttft_ms_sum 843",
                 "serve_ttft_ms_count 3"]
    assert lines == expected
    assert "# TYPE serve_ttft_ms histogram" in text


def test_engine_json_metrics_include_ttft_sum_and_count():
    """BatchEngine._ttft_quantiles feeds the JSON /metrics surface: the
    quantile keys alone cannot recover a mean, so sum/count ride along
    (graftscope and port-less scrapers compute averages from them)."""
    from types import SimpleNamespace

    from mlx_cuda_distributed_pretraining_tpu.obs.metrics import (
        LATENCY_MS_BUCKETS)
    from mlx_cuda_distributed_pretraining_tpu.serve.engine import (
        BatchEngine)

    reg = MetricsRegistry()
    h = reg.histogram("serve_ttft_ms", "", buckets=LATENCY_MS_BUCKETS)
    for v in (10.0, 20.0, 400.0):
        h.observe(v)
    stub = SimpleNamespace(metrics_registry=reg)
    out = BatchEngine._ttft_quantiles(stub)
    assert set(out) == {"ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
                        "ttft_ms_sum", "ttft_ms_count"}
    assert out["ttft_ms_sum"] == 430.0 and out["ttft_ms_count"] == 3
    # empty histogram: the whole block stays absent (no fake zeros)
    assert BatchEngine._ttft_quantiles(
        SimpleNamespace(metrics_registry=MetricsRegistry())) == {}
