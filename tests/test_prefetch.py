"""Device-prefetch input pipeline tests (data/device_prefetch.py).

Covers the PR's contracts: ordering, depth back-pressure, StopIteration /
error propagation, worker-thread lifecycle, checkpoint position semantics
(consumed, not fetched), prefetch on/off loss parity through the real
trainer, the host-side schedule evaluation, the persistent compilation
cache knob, and the data_wait_frac stats gauge.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import Config, DataConfig
from mlx_cuda_distributed_pretraining_tpu.data import (
    DevicePrefetcher,
    StreamingDataManager,
)
from mlx_cuda_distributed_pretraining_tpu.obs import StatsState
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager


def _write_shard(path, n_docs, prefix="doc"):
    with open(path, "w") as f:
        for i in range(n_docs):
            f.write(json.dumps({"text": f"{prefix} {i} " + "hello world " * 20}) + "\n")


def _streaming_cfg(shards, ctx=64, **extra):
    return DataConfig(
        preprocessing={"max_context_size": ctx},
        tokenizer={"type": "byte"},
        source="jsonl",
        streaming={"shards": shards, "shuffle_buffer": 8, **extra},
    )


class FakeLoader:
    """Deterministic loader: batch contents encode the step. Raises
    StopIteration past ``limit`` (like a finite stream)."""

    def __init__(self, limit=10**9):
        self.limit = limit
        self.fetches = 0

    def generate_batch(self, step):
        self.fetches += 1
        if step >= self.limit:
            raise StopIteration("dry")
        return {
            "inputs": np.full((2, 4), step, np.int32),
            "targets": np.full((2, 4), step + 1, np.int32),
            "mask": np.ones((2, 4), np.float32),
        }

    def state_dict(self):
        return {"val_ptr": 0}

    def load_state_dict(self, state):
        pass


def _drain(pf):
    out = []
    while True:
        try:
            batch, tokens, waits = pf.get()
        except StopIteration:
            return out
        out.append((int(np.asarray(batch["inputs"])[0, 0]), tokens, waits))


def _wait_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# -- unit: ordering / back-pressure / lifecycle ------------------------------

def test_ordering_matches_loader_sequence():
    pf = DevicePrefetcher(FakeLoader(), depth=2, start_step=0, total_steps=6)
    try:
        got = _drain(pf)
    finally:
        pf.stop()
    assert [g[0] for g in got] == [0, 1, 2, 3, 4, 5]
    # token counts are host-counted by the worker (2x4 all-ones mask)
    assert [g[1] for g in got] == [8] * 6
    assert all("data_wait_s" in g[2] and "h2d_wait_s" in g[2] for g in got)


def test_depth_backpressure_bounds_fetches():
    loader = FakeLoader()
    pf = DevicePrefetcher(loader, depth=2, start_step=0, total_steps=100)
    try:
        # Worker fills the queue (depth) plus at most one item in hand,
        # then blocks — it must NOT run ahead of the consumer.
        _wait_until(lambda: loader.fetches >= 3)
        time.sleep(0.1)
        assert loader.fetches <= 3
        pf.get()
        _wait_until(lambda: loader.fetches >= 4)
        time.sleep(0.1)
        assert loader.fetches <= 4  # one consumed -> exactly one refill
    finally:
        pf.stop()


def test_stopiteration_propagates_after_prefix():
    pf = DevicePrefetcher(FakeLoader(limit=3), depth=2, start_step=0, total_steps=100)
    try:
        got = _drain(pf)
    finally:
        pf.stop()
    assert [g[0] for g in got] == [0, 1, 2]
    with pytest.raises(StopIteration):
        pf.get()  # stays exhausted on repeated calls


def test_loader_error_reraised_at_get():
    class Exploding(FakeLoader):
        def generate_batch(self, step):
            if step >= 1:
                raise RuntimeError("producer died")
            return super().generate_batch(step)

    pf = DevicePrefetcher(Exploding(), depth=2, start_step=0, total_steps=10)
    try:
        pf.get()  # step 1 batch is fine
        with pytest.raises(RuntimeError, match="producer died"):
            pf.get()
    finally:
        pf.stop()


def test_stop_joins_worker_thread():
    pf = DevicePrefetcher(FakeLoader(), depth=2, start_step=0, total_steps=1000)
    assert _wait_until(
        lambda: any(t.name == "device-prefetch" for t in threading.enumerate()))
    pf.stop()
    assert pf._thread is None
    assert not any(
        t.name == "device-prefetch" and t.is_alive() for t in threading.enumerate())


def test_sync_mode_matches_async_sequence():
    on = DevicePrefetcher(FakeLoader(), depth=2, start_step=0, total_steps=5)
    off = DevicePrefetcher(FakeLoader(), depth=0, start_step=0, total_steps=5)
    try:
        a, b = _drain(on), _drain(off)
    finally:
        on.stop()
        off.stop()
    assert [x[0] for x in a] == [x[0] for x in b] == [0, 1, 2, 3, 4]
    assert off._thread is None  # sync mode runs no worker at all


def test_group_mode_stacks_and_serves_prefix_on_exhaustion():
    pf = DevicePrefetcher(
        FakeLoader(limit=7), depth=2, start_step=0, total_steps=100,
        group_len_fn=lambda step: 4)
    try:
        g, tokens, _ = pf.get()
        assert np.asarray(g["inputs"]).shape == (4, 2, 4)
        assert np.asarray(g["inputs"])[:, 0, 0].tolist() == [0, 1, 2, 3]
        assert tokens == [8, 8, 8, 8]
        g, tokens, _ = pf.get()  # steps 4-6, then the stream runs dry
        assert np.asarray(g["inputs"])[:, 0, 0].tolist() == [4, 5, 6]
        assert tokens == [8, 8, 8]
        with pytest.raises(StopIteration):
            pf.get()
    finally:
        pf.stop()


# -- checkpoint position: consumed, not fetched ------------------------------

def test_state_dict_reflects_consumed_not_fetched(tmp_path):
    p = str(tmp_path / "s0.jsonl")
    _write_shard(p, 60)
    tok = TokenizerManager(DataConfig(
        preprocessing={"max_context_size": 64}, tokenizer={"type": "byte"}))
    cfg = _streaming_cfg([p])

    # Reference: plain manager, 2 batches consumed.
    ref = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    for i in range(2):
        ref.generate_batch(i)
    ref_state = ref.state_dict()
    ref.stop()

    # Prefetcher with a deep queue: the worker runs AHEAD of consumption,
    # but state_dict must report the consumed position only.
    mgr = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    pf = DevicePrefetcher(mgr, depth=4, start_step=0, total_steps=100)
    try:
        for _ in range(2):
            pf.get()
        _wait_until(lambda: pf._queue.qsize() >= 3)  # queue fetched ahead
        state = pf.state_dict()
    finally:
        pf.stop()
        mgr.stop()
    assert state["docs_consumed"] == ref_state["docs_consumed"]
    assert state.get("source") == ref_state.get("source")
    assert state.get("buf") == ref_state.get("buf")


def test_resume_equivalence_prefetch_on_vs_off(tmp_path):
    """Resume from a mid-stream checkpoint taken under the prefetcher ==
    resume from one taken without it: batches 4-6 match the uninterrupted
    run exactly (extends test_streaming_exact_resume_batch_equality)."""
    shards = []
    for s in range(2):
        p = str(tmp_path / f"s{s}.jsonl")
        _write_shard(p, 40, prefix=f"shard{s}")
        shards.append(p)
    tok = TokenizerManager(DataConfig(
        preprocessing={"max_context_size": 64}, tokenizer={"type": "byte"}))
    cfg = _streaming_cfg(shards)

    ref = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    ref_batches = [ref.generate_batch(i) for i in range(6)]
    ref.stop()

    mgr = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    pf = DevicePrefetcher(mgr, depth=3, start_step=0, total_steps=100)
    try:
        for _ in range(3):
            pf.get()
        state = pf.state_dict()
    finally:
        pf.stop()
        mgr.stop()

    resumed_mgr = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    resumed_mgr.load_state_dict(state)
    pf2 = DevicePrefetcher(resumed_mgr, depth=3, start_step=3, total_steps=100)
    try:
        resumed = [np.asarray(pf2.get()[0]["inputs"]) for _ in range(3)]
    finally:
        pf2.stop()
        resumed_mgr.stop()

    for got, want in zip(resumed, ref_batches[3:]):
        np.testing.assert_array_equal(got, want["inputs"])


# -- trainer integration: loss parity, checkpoints, breakdown ----------------

def _tiny_cfg(tmp_path, name, prefetch_depth, ckpt_interval=0, spd=1):
    train = str(tmp_path / "train.jsonl")
    if not os.path.exists(train):
        _write_shard(train, 80)
    return Config.from_dict({
        "name": name,
        "overwrite": True,
        "data": {
            "source": "jsonl",
            "streaming": {"shards": [train], "shuffle_buffer": 8},
            "preprocessing": {"max_context_size": 32},
            "tokenizer": {"normal_vocab_size": 256},
            "prefetch_depth": prefetch_depth,
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2, "iters": 8},
            "optimization": {"optimizer": "adamw"},
            "scheduler": {"type": "cosine", "min_lr_ratio": 0.1},
        },
        "logging": {
            "steps": {"logging_interval": 2, "checkpoint_interval": ckpt_interval,
                      "validation_interval": 0},
        },
        "system": {"seed": 0, "steps_per_dispatch": spd},
    })


def _loss_series(run_dir):
    losses, fracs = [], []
    with open(os.path.join(run_dir, "log.txt")) as f:
        for line in f:
            if "loss=" in line and "tok/s=" in line:
                losses.append(line.split("loss=")[1].split()[0].rstrip("|"))
                assert "data_wait_frac=" in line, line
                fracs.append(float(
                    line.split("data_wait_frac=")[1].split()[0].rstrip("|")))
    return losses, fracs


@pytest.mark.parametrize("spd", [1, 2])
def test_trainer_loss_parity_prefetch_on_vs_off(tmp_path, spd):
    """Same seed, prefetch on vs off: identical batch sequence, identical
    losses (final loss bitwise), and both runs report data_wait_frac."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    results, series = {}, {}
    for depth in (2, 0):
        cfg = _tiny_cfg(tmp_path, f"parity-d{depth}-k{spd}", depth, spd=spd)
        tr = Trainer(cfg, runs_root=str(tmp_path / f"runs-d{depth}-k{spd}"), quiet=True)
        results[depth] = tr.train()
        series[depth] = _loss_series(tr.run_dir)

    assert results[2]["steps"] == results[0]["steps"] == 8
    assert results[2]["final_loss"] == results[0]["final_loss"]  # bitwise
    losses_on, fracs_on = series[2]
    losses_off, fracs_off = series[0]
    assert losses_on == losses_off and len(losses_on) >= 4
    assert all(0.0 <= fr <= 1.0 for fr in fracs_on + fracs_off)


def test_trainer_checkpoint_position_prefetch_on_vs_off(tmp_path):
    """The mid-run checkpoint saves the CONSUMED loader position: with the
    device queue running ahead, step-4 state must equal the prefetch-off
    run's (batches in the queue don't count — PR 3 resume contract)."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    states = {}
    for depth in (4, 0):
        cfg = _tiny_cfg(tmp_path, f"ckpt-d{depth}", depth, ckpt_interval=4)
        tr = Trainer(cfg, runs_root=str(tmp_path / f"runs-ckpt-d{depth}"), quiet=True)
        tr.train()
        _, _, state_path = tr.checkpoints.paths_for_step(4)
        with open(state_path) as f:
            states[depth] = json.load(f)

    assert states[4]["docs_consumed"] == states[0]["docs_consumed"]
    assert states[4]["step"] == states[0]["step"] == 4


# -- satellites: host-side schedule, compilation cache, stats gauge ----------

def test_schedule_value_matches_device_path():
    import jax.numpy as jnp

    from mlx_cuda_distributed_pretraining_tpu.optim import schedule_value
    from mlx_cuda_distributed_pretraining_tpu.optim.schedules import (
        build_schedule,
        warmup_cosine,
    )

    class TCfg:
        learning_rate = 2e-2

        def __init__(self, sched):
            self.scheduler = sched

    kinds = [
        {"type": "cosine_with_warmup", "warmup_steps": 10, "min_lr_ratio": 0.01},
        {"type": "cosine", "min_lr_ratio": 0.1},
        {"type": "linear", "min_lr_ratio": 0.0},
        {"type": "constant"},
    ]
    for sched in kinds:
        s = build_schedule(TCfg(sched), 100)
        for step in (0, 1, 9, 10, 50, 100):
            host = schedule_value(s, step)
            dev = float(s(jnp.asarray(step)))
            assert host == pytest.approx(dev, rel=1e-5, abs=1e-9), (sched, step)

    # Schedules without the xp keyword fall back to the device path.
    legacy = lambda step: jnp.asarray(3e-4, jnp.float32)
    assert schedule_value(legacy, 7) == pytest.approx(3e-4)
    # warmup boundary is exact in both paths
    w = warmup_cosine(1e-2, 100, 10)
    assert schedule_value(w, 10) == pytest.approx(1e-2, rel=1e-5)


def test_compilation_cache_enabled_and_logged(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    cache_dir = str(tmp_path / "xla-cache")
    cfg = _tiny_cfg(tmp_path, "cache-run", 2)
    cfg.system.compilation_cache_dir = cache_dir
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs-cache"), quiet=True)
    tr.train()
    assert os.path.isdir(cache_dir)
    with open(os.path.join(tr.run_dir, "log.txt")) as f:
        log = f.read()
    assert "compilation cache" in log
    assert "cold" in log or "warm" in log


def test_stats_state_mean_data_wait_frac_gauge():
    st = StatsState()
    st.handle({"type": "metrics", "worker_id": "w0", "step": 5,
               "data": {"loss": 2.0, "tok/s": 100.0, "data_wait_frac": 0.2}})
    st.handle({"type": "metrics", "worker_id": "w1", "step": 5,
               "data": {"loss": 2.0, "tok/s": 100.0, "data_wait_frac": 0.4}})
    agg = st.aggregated()
    assert agg["mean_data_wait_frac"] == pytest.approx(0.3)

    # training-only runs without the field keep the original shape
    st2 = StatsState()
    st2.handle({"type": "metrics", "worker_id": "w0", "step": 1,
                "data": {"loss": 2.0}})
    assert "mean_data_wait_frac" not in st2.aggregated()
