"""Pipeline parallelism (pp axis, parallel/pipeline.py).

The reference has no pipeline parallelism (SURVEY.md §2.4) — this is new
TPU-native capability. Correctness bar: the GPipe schedule must reproduce
the single-device loss and gradients exactly (same math, token-weighted),
and the Trainer must train/checkpoint/resume through the pipeline path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.parallel import pipeline as pl

ARGS = llama.LlamaArgs(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
    num_heads=2, num_kv_heads=2, head_dim=16, max_position_embeddings=64,
)


def _mesh(shape=(2, 2), names=("pp", "dp")):
    if jax.device_count() < int(np.prod(shape)):
        pytest.skip(f"needs {np.prod(shape)} devices")
    devs = mesh_utils.create_device_mesh(shape, devices=jax.devices()[: int(np.prod(shape))])
    return Mesh(devs, names)


def _batch(bs=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, 120, size=(bs, seq + 1)).astype(np.int32)
    return {
        "inputs": jnp.asarray(x[:, :-1]),
        "targets": jnp.asarray(x[:, 1:]),
        "mask": jnp.ones((bs, seq), jnp.float32),
    }


def test_stack_unstack_roundtrip():
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    stacked = pl.stack_layers(params)
    assert stacked["layers"]["attention"]["wq"]["weight"].shape[0] == ARGS.num_layers
    back = pl.unstack_layers(stacked, ARGS.num_layers)
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_opt_state_stack_unstack_roundtrip():
    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer

    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tr = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3},
        scheduler={"type": "cosine"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr, 10)
    stacked_state = opt.init(pl.stack_layers(params))
    unstacked = pl.unstack_opt_state(stacked_state, ARGS.num_layers)
    # unstacked layout mirrors the canonical opt state (list-of-layers)
    canonical = opt.init(params)
    assert jax.tree_util.tree_structure(unstacked) == jax.tree_util.tree_structure(canonical)
    back = pl.stack_opt_state(unstacked, ARGS.num_layers)
    for a, b in zip(
        jax.tree_util.tree_leaves(stacked_state), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_loss_matches_single_device():
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    ref, ref_toks = llama.loss_fn(params, batch, ARGS)
    loss_fn = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=4)
    got, toks = jax.jit(loss_fn)(pl.stack_layers(params), batch)
    assert float(toks) == float(ref_toks)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


@pytest.mark.slow
def test_pipeline_grads_match_single_device():
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    loss_fn = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=2)
    g_ref = jax.grad(lambda p: llama.loss_fn(p, batch, ARGS)[0])(params)
    g_pp = jax.jit(jax.grad(lambda p: loss_fn(p, batch)[0]))(pl.stack_layers(params))
    g_pp = pl.unstack_layers(g_pp, ARGS.num_layers)
    ref_flat = {str(k): v for k, v in jax.tree_util.tree_flatten_with_path(g_ref)[0]}
    for k, v in jax.tree_util.tree_flatten_with_path(g_pp)[0]:
        np.testing.assert_allclose(
            np.asarray(ref_flat[str(k)]), np.asarray(v), atol=3e-5, err_msg=str(k)
        )


@pytest.mark.slow
def test_pipeline_remat_matches():
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    plain = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=2)
    remat = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=2, remat="full")
    stacked = pl.stack_layers(params)
    g1 = jax.jit(jax.grad(lambda p: plain(p, batch)[0]))(stacked)
    g2 = jax.jit(jax.grad(lambda p: remat(p, batch)[0]))(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_pipeline_train_step_runs_and_shards():
    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
    from mlx_cuda_distributed_pretraining_tpu.train.train_step import init_train_state

    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tr = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3},
        scheduler={"type": "cosine"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr, 10)
    step, shardings = pl.make_pipeline_train_step(
        ARGS, opt, mesh, num_microbatches=4, params_like=params
    )
    state = jax.device_put(init_train_state(pl.stack_layers(params), opt), shardings)
    state, metrics = step(state, _batch())
    assert np.isfinite(float(metrics["loss"]))
    assert int(state["step"]) == 1
    spec = state["params"]["layers"]["attention"]["wq"]["weight"].sharding.spec
    assert spec and spec[0] == "pp", f"layer dim not pp-sharded: {spec}"


def test_pipeline_moe_loss_finite():
    import dataclasses

    mesh = _mesh()
    margs = dataclasses.replace(
        ARGS, num_local_experts=4, num_experts_per_tok=2, moe_group_size=8
    )
    params = llama.init_params(jax.random.PRNGKey(0), margs)
    loss_fn = pl.make_pipeline_loss(margs, mesh, num_microbatches=2)
    loss, toks = jax.jit(loss_fn)(pl.stack_layers(params), _batch())
    assert np.isfinite(float(loss))
    # aux excluded for eval
    ev = pl.make_pipeline_loss(margs, mesh, num_microbatches=2, include_aux=False)
    l_eval, _ = jax.jit(ev)(pl.stack_layers(params), _batch())
    assert float(loss) > float(l_eval)


@pytest.mark.slow
def test_trainer_pipeline_end_to_end(tmp_path):
    """Full Trainer drive over a pp mesh: train, checkpoint, resume."""
    import json
    import yaml

    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    if jax.device_count() < 4:
        pytest.skip("needs 4 devices")
    data = tmp_path / "train.jsonl"
    with open(data, "w") as f:
        for i in range(64):
            f.write(json.dumps({"text": "hello world " * (3 + i % 5)}) + "\n")
    cfg = {
        "name": "pp-e2e",
        "overwrite": True,
        "data": {
            "input_file": str(data),
            "validation_file": str(data),
            "preprocessing": {"max_context_size": 32},
            "tokenizer": {"normal_vocab_size": 256,
                          "special_tokens": {"pad": "<pad>", "bos": "<bos>", "eos": "<eos>"}},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 4},
            "attention": {"num_heads": 2, "num_kv_heads": 2, "head_dim": 16,
                          "max_position_embeddings": 32},
        },
        "training": {
            "hyperparameters": {"batch_size": 8, "learning_rate": 1e-3, "iters": 4},
            "scheduler": {"type": "cosine"},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {"steps": {"logging_interval": 2, "checkpoint_interval": 2,
                              "validation_interval": 0}},
        "system": {"seed": 0, "device": "cpu", "mesh": {"pp": 2, "dp": 2},
                   "pipeline_microbatches": 2},
    }
    cfg_path = tmp_path / "cfg.yaml"
    with open(cfg_path, "w") as f:
        yaml.safe_dump(cfg, f)
    t = Trainer(str(cfg_path), runs_root=str(tmp_path / "runs"))
    assert t.pipeline
    t.train()
    ckpt_dir = tmp_path / "runs" / "pp-e2e" / "checkpoints"
    assert (ckpt_dir / "step_final_model.safetensors").exists()

    # checkpoints are saved unstacked: loadable for plain inference
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    params, margs, tok, _ = load_trained("pp-e2e", runs_root=str(tmp_path / "runs"))
    logits, _ = llama.forward(params, jnp.ones((1, 8), jnp.int32), margs)
    assert logits.shape[-1] == tok.vocab_size

    # resume from step 2 on the same pp mesh
    cfg["overwrite"] = False
    cfg["training"]["hyperparameters"]["iters"] = 6
    cfg["resume"] = {"checkpoint": "2"}
    with open(cfg_path, "w") as f:
        yaml.safe_dump(cfg, f)
    t2 = Trainer(str(cfg_path), runs_root=str(tmp_path / "runs"))
    assert t2.start_step == 2
    t2.train()
    assert int(t2.state["step"]) == 6

    # cross-layout resume: the pp checkpoint loads on a plain (no-pp) mesh
    # with optimizer moments intact (saved unstacked).
    cfg["system"] = {"seed": 0, "device": "cpu"}
    with open(cfg_path, "w") as f:
        yaml.safe_dump(cfg, f)
    t3 = Trainer(str(cfg_path), runs_root=str(tmp_path / "runs"))
    assert not t3.pipeline and t3.start_step == 2
    mu_leaves = [
        np.abs(np.asarray(x)).sum()
        for x in jax.tree_util.tree_leaves(t3.state["opt_state"])
    ]
    assert sum(mu_leaves) > 0, "optimizer moments were lost across layouts"


def test_pipeline_fused_ce_matches_unfused():
    """ce_chunk threads through the pipeline head: fused chunked CE on the
    last stage equals the full-logits pipeline loss and the single-device
    reference (incl. a chunk that does not divide the microbatch rows)."""
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    ref, _ = llama.loss_fn(params, batch, ARGS, ce_chunk=0)
    stacked = pl.stack_layers(params)
    for chunk in (8, 24):  # mb rows = (8/4)*16 = 32; 24 pads
        loss_fn = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=4, ce_chunk=chunk)
        got, _ = jax.jit(loss_fn)(stacked, batch)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


def test_pipeline_z_loss_matches_single_device():
    """z_loss plumbs through the pipeline head: pp loss with z equals the
    non-pp loss_fn with the same weight (a pp>1 config must not silently
    drop the regularizer)."""
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    w = 1e-2
    ref, _ = llama.loss_fn(params, batch, ARGS, z_loss_weight=w)
    loss_fn = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=4,
                                    z_loss_weight=w)
    got, _ = jax.jit(loss_fn)(pl.stack_layers(params), batch)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    # and the z term is actually active (differs from the pure-CE loss)
    plain, _ = llama.loss_fn(params, batch, ARGS)
    assert float(got) > float(plain)


# --- zero-waste schedule: interleave, compute-skip, honest accounting -------


def test_interleave_stack_layout_and_roundtrip():
    """stacked[v, j] under interleave=V is global layer v*(L/V)+j (round-robin
    circuits over contiguous chunks), and unstack inverts it exactly."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    V, Lv = 2, ARGS.num_layers // 2
    stacked = pl.stack_layers(params, interleave=V)
    wq = stacked["layers"]["attention"]["wq"]["weight"]
    assert wq.shape[:2] == (V, Lv)
    flat = pl.stack_layers(params)["layers"]["attention"]["wq"]["weight"]
    for v in range(V):
        for j in range(Lv):
            np.testing.assert_array_equal(
                np.asarray(wq[v, j]), np.asarray(flat[v * Lv + j]))
    back = pl.unstack_layers(stacked, ARGS.num_layers, interleave=V)
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_interleave_opt_state_roundtrip():
    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer

    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tr = TrainingConfig(
        hyperparameters={"learning_rate": 1e-3},
        scheduler={"type": "cosine"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr, 10)
    stacked_state = opt.init(pl.stack_layers(params, interleave=2))
    unstacked = pl.unstack_opt_state(stacked_state, ARGS.num_layers, interleave=2)
    assert jax.tree_util.tree_structure(unstacked) == jax.tree_util.tree_structure(
        opt.init(params))
    back = pl.stack_opt_state(unstacked, ARGS.num_layers, interleave=2)
    for a, b in zip(
        jax.tree_util.tree_leaves(stacked_state), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("interleave", [1, 2])
def test_interleave_loss_matches_single_device(interleave):
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    ref, ref_toks = llama.loss_fn(params, batch, ARGS)
    loss_fn = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=4,
                                    interleave=interleave)
    got, toks = jax.jit(loss_fn)(
        pl.stack_layers(params, interleave=interleave), batch)
    assert float(toks) == float(ref_toks)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("interleave,remat,ce_chunk", [
    (2, None, -1),     # plain interleaved schedule
    (2, "full", -1),   # + remat through the virtual-stage slabs
    (2, None, 8),      # + fused chunked CE head on the last stage
    (1, None, 8),      # fused head without interleave (skip-path coverage)
])
def test_interleave_grads_match_single_device(interleave, remat, ce_chunk):
    """Interleaved circular schedule is gradient-exact vs the single-device
    reference, including the remat arm and the fused-CE head."""
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    loss_fn = pl.make_pipeline_loss(
        ARGS, mesh, num_microbatches=4, interleave=interleave,
        remat=remat, ce_chunk=ce_chunk)
    g_ref = jax.grad(lambda p: llama.loss_fn(p, batch, ARGS)[0])(params)
    g_pp = jax.jit(jax.grad(lambda p: loss_fn(p, batch)[0]))(
        pl.stack_layers(params, interleave=interleave))
    g_pp = pl.unstack_layers(g_pp, ARGS.num_layers, interleave=interleave)
    ref_flat = {str(k): v for k, v in jax.tree_util.tree_flatten_with_path(g_ref)[0]}
    for k, v in jax.tree_util.tree_flatten_with_path(g_pp)[0]:
        np.testing.assert_allclose(
            np.asarray(ref_flat[str(k)]), np.asarray(v), atol=3e-5, err_msg=str(k)
        )


@pytest.mark.parametrize("interleave", [1, 2])
def test_compute_skip_bit_identical(interleave):
    """Skipping bubble ticks changes WHAT runs, not the math: the loss with
    compute_skip on is bitwise equal to the all-ticks schedule."""
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    stacked = pl.stack_layers(params, interleave=interleave)
    on = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=4,
                               interleave=interleave, compute_skip=True)
    off = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=4,
                                interleave=interleave, compute_skip=False)
    l_on, t_on = jax.jit(on)(stacked, batch)
    l_off, t_off = jax.jit(off)(stacked, batch)
    assert float(l_on) == float(l_off), "compute-skip changed the loss"
    assert float(t_on) == float(t_off)


@pytest.mark.parametrize("interleave,compute_skip", [
    (1, True), (1, False), (2, True), (2, False),
])
def test_compute_skip_slab_application_count(interleave, compute_skip):
    """The schedule really skips bubble ticks: per-device slab applications
    drop from P*(V*M + P-1) to P*(V*M) with compute_skip on (counted via the
    debug-callback hook inside the cond's work branch)."""
    mesh = _mesh((2, 1))
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    M, P, V = 4, 2, interleave
    n = [0]
    # the hook is bound when make_pipeline_loss builds the schedule
    pl._SLAB_APP_HOOK = lambda: n.__setitem__(0, n[0] + 1)
    try:
        loss_fn = pl.make_pipeline_loss(ARGS, mesh, num_microbatches=M,
                                        interleave=V, compute_skip=compute_skip)
        loss, _ = jax.jit(loss_fn)(pl.stack_layers(params, interleave=V), batch)
        loss.block_until_ready()
        jax.effects_barrier()
    finally:
        pl._SLAB_APP_HOOK = None
    expected = P * (V * M) if compute_skip else P * (V * M + P - 1)
    assert n[0] == expected, f"slab applications {n[0]} != {expected}"


@pytest.mark.slow
@pytest.mark.parametrize("interleave", [1, 2])
def test_pipeline_moe_stats_parity(interleave):
    """MoE routing stats thread through the pipeline loss aux: same grouped
    load / dropped counts as the single-device loss_fn taps."""
    import dataclasses

    from mlx_cuda_distributed_pretraining_tpu.parallel.context import use_mesh

    mesh = _mesh()
    margs = dataclasses.replace(
        ARGS, num_local_experts=4, num_experts_per_tok=2, moe_group_size=8)
    params = llama.init_params(jax.random.PRNGKey(0), margs)
    batch = _batch()
    with use_mesh(None):  # shield from a base mesh left by Trainer tests
        ref_loss, (ref_toks, ref_stats) = llama.loss_fn(
            params, batch, margs, with_moe_stats=True)
        loss_fn = pl.make_pipeline_loss(margs, mesh, num_microbatches=4,
                                        interleave=interleave,
                                        with_moe_stats=True)
        loss, (toks, stats) = jax.jit(loss_fn)(
            pl.stack_layers(params, interleave=interleave), batch)
    assert float(toks) == float(ref_toks)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=3e-4)
    assert sorted(stats) == sorted(ref_stats)
    np.testing.assert_allclose(
        np.asarray(stats["moe_load"]), np.asarray(ref_stats["moe_load"]),
        rtol=1e-5)
    np.testing.assert_allclose(
        float(np.asarray(stats["moe_dropped"]).sum()),
        float(np.asarray(ref_stats["moe_dropped"]).sum()))


def test_bubble_accounting():
    from mlx_cuda_distributed_pretraining_tpu.obs.flops import (
        pipeline_bubble_frac, pipeline_executed_flops_ratio)

    assert pipeline_bubble_frac(4, 8) == pytest.approx(3 / 11)
    assert pipeline_bubble_frac(4, 8, interleave=2) == pytest.approx(3 / 19)
    assert pipeline_bubble_frac(1, 8) == 0.0
    assert pipeline_executed_flops_ratio(4, 8, compute_skip=True) == 1.0
    assert pipeline_executed_flops_ratio(4, 8, compute_skip=False) == pytest.approx(11 / 8)
    assert pipeline_executed_flops_ratio(4, 8, interleave=2, compute_skip=False) == pytest.approx(19 / 16)


@pytest.mark.parametrize("interleave", [1, 2])
def test_load_params_stacked_pp_placement(interleave):
    """An unstacked (fsdp-layout) checkpoint loads straight into the stacked
    pp-sharded placement: correct specs, exact values, and a per-device byte
    budget — no device ever holds a full replica of the stacked tree."""
    import tempfile

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointIntegrityError, CheckpointManager)
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import (
        save_safetensors)
    from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

    mesh = _mesh((2, 2), ("pp", "fsdp"))
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "model.safetensors")
        save_safetensors(
            path, {k: np.asarray(v) for k, v in flatten_dict(params).items()})
        placed = CheckpointManager.load_params_stacked(
            path, mesh, ARGS.num_layers, interleave=interleave)
    want = pl.stack_layers(params, interleave=interleave)
    n_dev = mesh.devices.size
    for k, v in flatten_dict(placed).items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(flatten_dict(want)[k]), err_msg=k)
        spec = v.sharding.spec
        if k.startswith("layers."):
            # layer dim pp-sharded: [V, L/V, ...] circuits lead, else [L, ...]
            assert spec[1 if interleave > 1 else 0] == "pp", (k, spec)
            sharded = int(np.prod([
                mesh.shape[a] for a in jax.tree_util.tree_leaves(tuple(spec))
                if isinstance(a, str)]))
            for s in v.addressable_shards:
                assert s.data.nbytes == v.nbytes // sharded, (k, spec)
            assert sum(s.data.nbytes for s in v.addressable_shards) \
                == v.nbytes * n_dev // sharded


def test_load_params_stacked_rejects_mismatch():
    """A checkpoint whose per-layer dtype does not match the live tree fails
    loudly at load time (not as a runtime donation error mid-step)."""
    import tempfile

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointIntegrityError, CheckpointManager)
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import (
        save_safetensors)
    from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

    mesh = _mesh((2, 2), ("pp", "fsdp"))
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    like = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), pl.stack_layers(params))
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "model.safetensors")
        save_safetensors(
            path, {k: np.asarray(v) for k, v in flatten_dict(params).items()})
        with pytest.raises(CheckpointIntegrityError, match="re-materialize"):
            CheckpointManager.load_params_stacked(
                path, mesh, ARGS.num_layers, like_stacked=like)


@pytest.mark.slow
def test_fsdp_checkpoint_resumes_on_pp_mesh(tmp_path):
    """Train+checkpoint on a dp x fsdp mesh, resume the SAME run on a
    pp x dp mesh with interleave: the stacked params must come up pp-sharded
    (per-device live bytes == leaf/pp, never a full stacked replica) with
    values identical to the saved step. Runs in a subprocess so the fsdp and
    pp trainers each get a clean 4-device runtime."""
    import sys

    from conftest import spawn_with_devices

    worker = tmp_path / "worker.py"
    worker.write_text(PP_RESUME_WORKER)
    proc = spawn_with_devices([sys.executable, str(worker), str(tmp_path)], 4)
    out, _ = proc.communicate(timeout=600)
    assert proc.returncode == 0, out
    assert "PP_RESUME_OK" in out, out


PP_RESUME_WORKER = """
import json
import sys

import numpy as np
import yaml

import jax

from mlx_cuda_distributed_pretraining_tpu.parallel import pipeline as pl
from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

tmp = sys.argv[1]
assert jax.device_count() == 4, jax.devices()

data = tmp + "/train.jsonl"
with open(data, "w") as f:
    for i in range(64):
        f.write(json.dumps({"text": "hello world " * (3 + i % 5)}) + "\\n")

cfg = {
    "name": "xresume",
    "overwrite": True,
    "data": {
        "input_file": data,
        "validation_file": data,
        "preprocessing": {"max_context_size": 32},
        "tokenizer": {"normal_vocab_size": 256,
                      "special_tokens": {"pad": "<pad>", "bos": "<bos>",
                                         "eos": "<eos>"}},
    },
    "model": {
        "architecture": "llama",
        "dimensions": {"hidden_size": 32, "intermediate_size": 64,
                       "num_layers": 4},
        "attention": {"num_heads": 2, "num_kv_heads": 2, "head_dim": 16,
                      "max_position_embeddings": 32},
    },
    "training": {
        "hyperparameters": {"batch_size": 8, "learning_rate": 1e-3, "iters": 2},
        "scheduler": {"type": "cosine"},
        "optimization": {"optimizer": "adamw"},
    },
    "logging": {"steps": {"logging_interval": 2, "checkpoint_interval": 2,
                          "validation_interval": 0}},
    "system": {"seed": 0, "device": "cpu", "mesh": {"dp": 2, "fsdp": 2}},
}
cfg_path = tmp + "/cfg.yaml"
with open(cfg_path, "w") as f:
    yaml.safe_dump(cfg, f)
t1 = Trainer(cfg_path, runs_root=tmp + "/runs")
assert not t1.pipeline
t1.train()
saved = {k: np.asarray(v) for k, v in flatten_dict(t1._host_params()).items()}
del t1

cfg["overwrite"] = False
cfg["training"]["hyperparameters"]["iters"] = 4
cfg["resume"] = {"checkpoint": "2"}
cfg["system"] = {"seed": 0, "device": "cpu", "mesh": {"pp": 2, "dp": 2},
                 "pipeline_microbatches": 2, "pipeline_interleave": 2}
with open(cfg_path, "w") as f:
    yaml.safe_dump(cfg, f)
t2 = Trainer(cfg_path, runs_root=tmp + "/runs")
assert t2.pipeline and t2.pipeline_interleave == 2
assert t2.start_step == 2, t2.start_step

# per-device live-byte budget: every stacked layer leaf is pp-sharded --
# each device holds exactly leaf/pp bytes, no full stacked replica anywhere
pp = 2
layers = flatten_dict(t2.state["params"]["layers"])
assert layers
for k, v in layers.items():
    for s in v.addressable_shards:
        assert s.data.nbytes == v.nbytes // pp, (k, s.data.nbytes, v.nbytes)

# values identical to the step-2 checkpoint (no lossy round trip)
back = flatten_dict(
    pl.unstack_layers(jax.device_get(t2.state["params"]),
                      4, interleave=2))
for k, want in saved.items():
    np.testing.assert_array_equal(np.asarray(back[k]), want, err_msg=k)

# and the resumed pipeline actually trains on
t2.train()
assert int(t2.state["step"]) == 4

print("PP_RESUME_OK", json.dumps({"leaves": len(layers)}))
"""
