"""Observability tests: log parsing/plotting, monitor tailer, stats
server/client round trip (reference capabilities: utils/plotting.py,
monitor_training.py, stats_server.py, stats_client.py)."""

import asyncio
import importlib.util
import json
import os
import threading
import time

import pytest

from mlx_cuda_distributed_pretraining_tpu.obs import (
    LogTailer,
    StatsClient,
    StatsServer,
    StatsState,
    ema,
    find_latest_run,
    parse_log,
    plot_run,
)

LOG = """[2026-01-01 00:00:00] Model: 1,000 parameters (0.00M)
Step 0 validation: val_loss=5.5000
Step 5: loss=4.0000 | ppl=54.5982 | lr=1.000e-02 | tok/s=1000.0 | toks=320
Step 10: loss=3.0000 | ppl=20.0855 | lr=9.000e-03 | grad_norm=0.5000 | tok/s=2000.0 | toks=320
Step 10 validation: val_loss=3.2000
[2026-01-01 00:01:00] Saved checkpoint at step 10
"""


def _write_log(tmp_path, text=LOG, name="r1"):
    run = tmp_path / name
    os.makedirs(run, exist_ok=True)
    with open(run / "log.txt", "w") as f:
        f.write(text)
    return str(run)


def test_parse_log(tmp_path):
    run = _write_log(tmp_path)
    steps, metrics = parse_log(os.path.join(run, "log.txt"))
    assert steps == [5, 10]
    assert metrics["loss"] == [4.0, 3.0]
    assert metrics["grad_norm"] == [None, 0.5]
    assert metrics["_val_steps"] == [0, 10]
    assert metrics["_val_losses"] == [5.5, 3.2]
    assert metrics["val_loss"] == [None, 3.2]


def test_ema_smoothing():
    vals = [10.0, None, 0.0]
    sm = ema(vals, alpha=0.5)
    assert sm[0] == 10.0 and sm[1] is None and sm[2] == 5.0


def test_plot_run_writes_csv_and_png(tmp_path):
    run = _write_log(tmp_path)
    out = plot_run(run)
    csv_path = os.path.join(run, "metrics.csv")
    assert os.path.isfile(csv_path)
    lines = open(csv_path).read().strip().splitlines()
    assert lines[0].startswith("step,")
    assert len(lines) == 3
    if out is not None:  # matplotlib available
        assert os.path.isfile(out)


def test_log_tailer_incremental(tmp_path):
    run = _write_log(tmp_path, text="")
    tailer = LogTailer(os.path.join(run, "log.txt"))
    assert tailer.poll() == 0
    with open(os.path.join(run, "log.txt"), "a") as f:
        f.write("Step 5: loss=4.0000 | ppl=54.5982 | lr=1.000e-02 | tok/s=10.0 | toks=32\n")
    assert tailer.poll() == 1
    assert tailer.latest["loss"] == 4.0
    with open(os.path.join(run, "log.txt"), "a") as f:
        f.write("Step 10 validation: val_loss=3.5000\n")
    tailer.poll()
    assert tailer.val_losses == [3.5]
    assert "val_loss=3.5000@10" in tailer.status_line()


def test_find_latest_run(tmp_path):
    _write_log(tmp_path, name="old")
    time.sleep(0.02)
    new = _write_log(tmp_path, name="new")
    assert find_latest_run(str(tmp_path)) == new


def test_stats_state_aggregation():
    st = StatsState()
    assert st.handle({"type": "register", "worker_id": "w0", "capabilities": {"devices": 4}})
    st.handle({"type": "metrics", "worker_id": "w0", "step": 5,
               "data": {"loss": 2.0, "tok/s": 100.0}})
    st.handle({"type": "metrics", "worker_id": "w1", "step": 7,
               "data": {"loss": 4.0, "tok/s": 300.0}})
    agg = st.aggregated()
    assert agg["num_workers"] == 2
    assert agg["mean_loss"] == 3.0
    assert agg["total_tok_s"] == 400.0
    assert agg["max_step"] == 7
    snap = st.snapshot()
    assert snap["type"] == "initial_state"
    assert len(snap["history"]) == 2


def test_stats_state_history_ring():
    st = StatsState(history_limit=10)
    for i in range(25):
        st.handle({"type": "metrics", "worker_id": "w", "step": i, "data": {"loss": float(i)}})
    assert len(st.history) == 10
    assert st.history[-1]["step"] == 24


@pytest.mark.skipif(importlib.util.find_spec("websockets") is None,
                    reason="websockets unavailable")
def test_stats_server_client_roundtrip(tmp_path):
    """Full wire test: server hub + background client, metrics land in
    state and persistence file."""
    persist = str(tmp_path / "stats.json")
    server = StatsServer(host="127.0.0.1", port=18765, persist_path=persist)

    loop_holder = {}

    def run_server():
        loop = asyncio.new_event_loop()
        loop_holder["loop"] = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(server.serve())

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    time.sleep(0.5)

    client = StatsClient("ws://127.0.0.1:18765", "worker-a",
                         heartbeat_interval=0.5).start()
    client.register({"devices": 8})
    for step in range(3):
        client.log_metrics(step, {"loss": 3.0 - step, "tok/s": 1000.0})
    deadline = time.time() + 5
    while time.time() < deadline and server.state.workers.get("worker-a", {}).get("step") != 2:
        time.sleep(0.05)
    client.close()

    w = server.state.workers.get("worker-a")
    assert w is not None, "client messages never reached the server"
    assert w["step"] == 2
    assert w["metrics"]["loss"] == 1.0
    server.persist()
    with open(persist) as f:
        saved = json.load(f)
    assert saved["workers"]["worker-a"]["metrics"]["tok/s"] == 1000.0

    loop_holder["loop"].call_soon_threadsafe(server.stop)
    t.join(timeout=5)


def test_stats_client_offline_buffering():
    """Messages sent while no server exists are buffered, not lost/crashy."""
    client = StatsClient("ws://127.0.0.1:19999", "w", reconnect_delay=0.1).start()
    for i in range(5):
        client.log_metrics(i, {"loss": 1.0})
    time.sleep(0.5)
    client.close()
    assert len(client._buffer) == 5


def test_dashboard_page_and_http_server():
    """The live dashboard (reference: hybrid_distributed_patch.py's embedded
    Chart.js page) is self-contained HTML served over HTTP."""
    import urllib.request

    from mlx_cuda_distributed_pretraining_tpu.obs.dashboard import (
        DASHBOARD_HTML,
        serve_dashboard,
        write_dashboard,
    )

    # self-contained: no external asset references (offline pods)
    assert "http://" not in DASHBOARD_HTML.replace("ws://", "").replace(
        "http://\" + location.hostname", "")
    for needle in ('id="loss"', 'id="tput"', 'id="workers"', "WebSocket",
                   "--series-1", "prefers-color-scheme: dark", "initial_state"):
        assert needle in DASHBOARD_HTML, needle

    srv = serve_dashboard("127.0.0.1", 0)
    try:
        port = srv.server_address[1]
        html = urllib.request.urlopen(f"http://127.0.0.1:{port}/").read().decode()
        assert 'id="loss"' in html
    finally:
        srv.shutdown()


def test_dashboard_write(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.obs.dashboard import write_dashboard

    p = write_dashboard(str(tmp_path / "sub" / "dashboard.html"))
    assert open(p).read().startswith("<!DOCTYPE html>")


# -- PR 5 telemetry satellites ----------------------------------------------

# A window line in the extended (telemetry) format: mfu + full goodput
# breakdown. CPU runs report mfu=unknown — parsers must treat it as None,
# never crash.
LOG_EXTENDED = LOG + (
    "Step 15: loss=2.5000 | ppl=12.1825 | lr=8.000e-03 | tok/s=1500.0 | "
    "toks=320 | mfu=unknown | data_wait_s=0.0100 | h2d_wait_s=0.0000 | "
    "dispatch_s=0.1000 | compile_s=0.0000 | ckpt_save_s=0.0500 | "
    "eval_s=0.0000 | other_s=0.0500 | data_wait_frac=0.0476\n"
    "Step 20: loss=2.0000 | ppl=7.3891 | lr=7.000e-03 | tok/s=1600.0 | "
    "toks=320 | mfu=0.4210 | data_wait_s=0.0000 | h2d_wait_s=0.0000 | "
    "dispatch_s=0.1500 | compile_s=0.0000 | ckpt_save_s=0.0000 | "
    "eval_s=0.0000 | other_s=0.0500 | data_wait_frac=0.0000\n"
)


def test_parse_log_extended_keys_and_unknown(tmp_path):
    """New telemetry keys parse; mfu=unknown maps to None; pre-telemetry
    lines (no mfu/goodput keys) in the same file stay parseable."""
    run = _write_log(tmp_path, text=LOG_EXTENDED)
    steps, metrics = parse_log(os.path.join(run, "log.txt"))
    assert steps == [5, 10, 15, 20]
    assert metrics["loss"] == [4.0, 3.0, 2.5, 2.0]
    assert metrics["mfu"] == [None, None, None, 0.421]
    assert metrics["ckpt_save_s"] == [None, None, 0.05, 0.0]
    assert metrics["dispatch_s"] == [None, None, 0.1, 0.15]


def test_parse_value_unknown():
    from mlx_cuda_distributed_pretraining_tpu.obs.plotting import parse_value

    assert parse_value("unknown") is None
    assert parse_value("0.5") == 0.5


def test_log_tailer_handles_unknown_mfu(tmp_path):
    run = _write_log(tmp_path, text="")
    tailer = LogTailer(os.path.join(run, "log.txt"))
    with open(os.path.join(run, "log.txt"), "a") as f:
        f.write(LOG_EXTENDED.splitlines()[-2] + "\n")  # the mfu=unknown line
    assert tailer.poll() == 1
    assert "mfu" not in tailer.latest  # unknown dropped, not a crash
    assert tailer.latest["ckpt_save_s"] == 0.05
    with open(os.path.join(run, "log.txt"), "a") as f:
        f.write(LOG_EXTENDED.splitlines()[-1] + "\n")  # numeric mfu
    tailer.poll()
    assert tailer.latest["mfu"] == 0.421
    assert "mfu=0.421" in tailer.status_line()


def test_stats_state_mean_mfu_aggregation():
    st = StatsState()
    st.handle({"type": "metrics", "worker_id": "w0", "step": 5,
               "data": {"loss": 2.0, "tok/s": 100.0, "mfu": 0.4}})
    st.handle({"type": "metrics", "worker_id": "w1", "step": 5,
               "data": {"loss": 2.0, "tok/s": 100.0, "mfu": 0.6}})
    # CPU worker reports mfu=unknown (a string) — excluded from the mean
    st.handle({"type": "metrics", "worker_id": "w2", "step": 5,
               "data": {"loss": 2.0, "tok/s": 10.0, "mfu": "unknown"}})
    agg = st.aggregated()
    assert agg["mean_mfu"] == pytest.approx(0.5)


def test_stats_state_evicts_dead_workers():
    st = StatsState(worker_ttl_s=100.0)
    st.handle({"type": "register", "worker_id": "alive"})
    st.handle({"type": "register", "worker_id": "dead"})
    st.workers["dead"]["last_seen"] = time.time() - 500
    assert st.evict_stale() == 1
    assert set(st.workers) == {"alive"}
    agg = st.aggregated()  # aggregation evicts too
    assert agg["num_workers"] == 1


def test_stats_state_ttl_zero_disables_eviction():
    st = StatsState(worker_ttl_s=0)
    st.handle({"type": "register", "worker_id": "ancient"})
    st.workers["ancient"]["last_seen"] = 0
    assert st.evict_stale() == 0
    assert "ancient" in st.workers


def test_stats_persist_atomic_on_failure(tmp_path):
    """An interrupted persist (crash mid-json.dump) must leave the
    previous good snapshot untouched — tmp+rename, never in-place."""
    persist = str(tmp_path / "stats.json")
    server = StatsServer(persist_path=persist)
    server.state.handle({"type": "metrics", "worker_id": "w0", "step": 1,
                         "data": {"loss": 2.0}})
    server.persist()
    good = open(persist).read()
    assert json.loads(good)["workers"]["w0"]["metrics"]["loss"] == 2.0

    # Poison the state: json.dump raises AFTER the tmp file is opened,
    # exactly the mid-write crash window.
    server.state.workers["w0"]["metrics"]["bad"] = object()
    with pytest.raises(TypeError):
        server.persist()
    assert open(persist).read() == good


def test_dashboard_has_mfu_and_goodput_panels():
    from mlx_cuda_distributed_pretraining_tpu.obs.dashboard import DASHBOARD_HTML

    for needle in ('id="t-mfu"', 'id="goodput"', "drawGoodput", "mean_mfu"):
        assert needle in DASHBOARD_HTML, needle
