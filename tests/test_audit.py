"""graftaudit rule-behavior + plumbing tests.

Each audit rule is pinned against tiny jitted programs with a known
ground truth — a step that forgets to donate its state, a bf16 program
with an fp32 matmul, a captured megabyte constant, a replicated param
the sharding rules expect sharded. Lowering happens on the 8-device
virtual CPU platform the conftest forces; nothing executes.

The full-config gate (audit the sample config end to end, zero new
findings, committed budget matches a fresh census) runs in a subprocess
and is marked slow — scripts/lint.sh and the bench gate run it too.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_cuda_distributed_pretraining_tpu.analysis import audit, audit_rules
from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
    AuditProgram,
    CollectiveCensus,
    DonationGap,
    DtypeUpcast,
    LargeConstantCapture,
    ReplicatedParam,
    parse_hlo_census,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

f32 = jnp.float32
SDS = jax.ShapeDtypeStruct


def _prog(fn, args, donate=(), name="prog", **kw):
    jitted = jax.jit(fn, donate_argnums=donate)
    kw.setdefault("arg_names", tuple(f"arg{i}" for i in range(len(args))))
    return audit._trace_program(name, "testcfg", jitted, args, **kw)


def _by_rule(prog, rule):
    return [f for f in rule.check(prog)]


# -- donation-gap ------------------------------------------------------------

# (256, 256) f32 = 256 KiB — comfortably above the 64 KiB group floor.
BIG = SDS((256, 256), f32)


def _state_step(state, batch):
    return state + batch.sum(), batch.mean()


def test_donation_gap_fires_on_undonated_state():
    prog = _prog(_state_step, (BIG, SDS((32, 32), f32)),
                 arg_names=("state", "batch"))
    findings = _by_rule(prog, DonationGap())
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "donation-gap"
    assert "`state`" in f.message and "256.0 KiB" in f.message
    assert f.path == "<testcfg:prog>"


def test_donation_gap_silent_when_donated():
    prog = _prog(_state_step, (BIG, SDS((32, 32), f32)), donate=(0,),
                 arg_names=("state", "batch"))
    assert _by_rule(prog, DonationGap()) == []
    assert prog.donation_summary() == {
        "donated_bytes": 256 * 256 * 4, "gap_bytes": 0}


def test_donation_gap_ignores_read_only_args():
    # params shape (256, 256) but the output is (32,): no in/out pair,
    # nothing to alias, no finding — read-only args never flag.
    prog = _prog(lambda w, x: (x @ w).sum(axis=1), (BIG, SDS((32, 256), f32)))
    assert _by_rule(prog, DonationGap()) == []


def test_donation_gap_floor_suppresses_small_buffers():
    # (16, 16) f32 = 1 KiB round-trips un-donated, but chasing it is
    # noise: below max(64 KiB, 5% of inputs) the rule stays quiet.
    prog = _prog(_state_step, (SDS((16, 16), f32), SDS((8, 8), f32)))
    assert _by_rule(prog, DonationGap()) == []


def test_donation_gap_donated_inputs_consume_matches_first():
    # Two same-shaped buffers, one output of that shape: the donated one
    # claims the output slot, the undonated one has nothing left to pair
    # with (returning it unchanged would be read-only anyway).
    def step(a, b):
        return a + b
    prog = _prog(step, (BIG, BIG), donate=(0,))
    assert _by_rule(prog, DonationGap()) == []


# -- dtype-upcast ------------------------------------------------------------


def _bf16_body_with_fp32_dot(x, w):
    h = x @ w                                    # bf16 — fine
    return (h.astype(f32) @ w.astype(f32)).sum()  # fp32 — the finding


def test_dtype_upcast_fires_in_bf16_program():
    args = (SDS((64, 64), jnp.bfloat16), SDS((64, 64), jnp.bfloat16))
    prog = _prog(_bf16_body_with_fp32_dot, args, compute_dtype="bfloat16")
    findings = _by_rule(prog, DtypeUpcast())
    assert len(findings) == 1
    f = findings[0]
    assert "fp32 dot_general" in f.message and "(64, 64)" in f.message
    assert f.line > 0  # attributed to real source, not the synthetic path
    assert "test_audit" in f.path


def test_dtype_upcast_inactive_in_fp32_program():
    args = (SDS((64, 64), f32), SDS((64, 64), f32))
    prog = _prog(lambda x, w: (x @ w).sum(), args, compute_dtype="float32")
    assert _by_rule(prog, DtypeUpcast()) == []


def test_dtype_upcast_silent_on_bf16_matmul():
    args = (SDS((64, 64), jnp.bfloat16), SDS((64, 64), jnp.bfloat16))
    prog = _prog(lambda x, w: (x @ w).sum(), args, compute_dtype="bfloat16")
    assert _by_rule(prog, DtypeUpcast()) == []


# -- large-constant-capture --------------------------------------------------


def test_large_constant_capture_fires():
    baked = jnp.asarray(np.ones((256, 256), np.float32))  # 256 KiB
    prog = _prog(lambda x: (x * baked).sum(), (BIG,))
    findings = _by_rule(prog, LargeConstantCapture())
    assert len(findings) == 1
    assert "(256, 256)" in findings[0].message
    assert "256.0 KiB" in findings[0].message


def test_small_constant_capture_silent():
    baked = jnp.asarray(np.ones((16, 16), np.float32))  # 1 KiB
    prog = _prog(lambda x: (x * baked).sum(), (SDS((16, 16), f32),))
    assert _by_rule(prog, LargeConstantCapture()) == []


# -- collective-census -------------------------------------------------------

_HLO = """\
  %ar = f32[128,256]{1,0} all-reduce(f32[128,256]{1,0} %p0), replica_groups={}
  %ag = f32[1024]{0} all-gather-start(f32[128]{0} %p1), dimensions={0}
  %agd = f32[1024]{0} all-gather-done(f32[1024]{0} %ag)
  %tup = (f32[64,64]{1,0}, f32[64,64]{1,0}) all-to-all(f32[64,64] %a, f32[64,64] %b)
  %fus = f32[128,256]{1,0} fusion(f32[128,256]{1,0} %ar), kind=kLoop
"""


def test_parse_hlo_census_counts_and_bytes():
    census = parse_hlo_census(_HLO)
    # -start counted once, -done skipped, operand references (the fusion
    # consuming %ar) never match.
    assert census["all-reduce"] == {"count": 1, "bytes": 128 * 256 * 4}
    assert census["all-gather"] == {"count": 1, "bytes": 1024 * 4}
    assert census["all-to-all"] == {"count": 1, "bytes": 2 * 64 * 64 * 4}


def _census_prog(census, budget):
    prog = AuditProgram(
        name="p", config_name="testcfg", lowered=None, closed_jaxpr=None,
        arg_leaves=[], out_avals=[], budget=budget)
    prog._census = census
    return prog


def test_census_regression_over_budget():
    prog = _census_prog({"all-reduce": {"count": 3, "bytes": 4096}},
                        {"all-reduce": {"count": 2, "bytes": 4096}})
    findings = _by_rule(prog, CollectiveCensus())
    assert len(findings) == 1
    assert "regressed" in findings[0].message


def test_census_within_budget_is_silent():
    prog = _census_prog({"all-reduce": {"count": 2, "bytes": 4096}},
                        {"all-reduce": {"count": 2, "bytes": 4096}})
    assert _by_rule(prog, CollectiveCensus()) == []


def test_census_without_budget_demands_one():
    prog = _census_prog({"all-reduce": {"count": 2, "bytes": 4096}}, None)
    findings = _by_rule(prog, CollectiveCensus())
    assert len(findings) == 1
    assert "no committed budget" in findings[0].message


def test_census_real_lowering_sees_gspmd_collectives():
    # GSPMD inserts the all-reduce during compilation — it exists in no
    # jaxpr, which is exactly why the census parses compiled HLO.
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    fn = jax.jit(lambda x: x.sum(),
                 in_shardings=NamedSharding(mesh, P("dp")),
                 out_shardings=NamedSharding(mesh, P()))
    prog = audit._trace_program("sum", "testcfg", fn, (SDS((64, 8), f32),),
                                arg_names=("x",))
    assert sum(v["count"] for v in prog.census().values()) >= 1


# -- replicated-param --------------------------------------------------------


def _sharded_param_prog(param_spec):
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    fn = jax.jit(
        lambda p, x: x @ p["w"],
        in_shardings=({"w": NamedSharding(mesh, param_spec)},
                      NamedSharding(mesh, P())),
    )
    return audit._trace_program(
        "mm", "testcfg", fn,
        ({"w": SDS((64, 64), f32)}, SDS((8, 64), f32)),
        arg_names=("params", "x"), param_arg_index=0,
        expected_param_specs={"w": str(P("dp", None))})


def test_replicated_param_fires_when_spec_dropped():
    findings = _by_rule(_sharded_param_prog(P()), ReplicatedParam())
    assert len(findings) == 1
    assert "`w` lowered fully replicated" in findings[0].message


def test_replicated_param_silent_when_sharded():
    assert _by_rule(_sharded_param_prog(P("dp", None)),
                    ReplicatedParam()) == []


# -- plumbing: suppression, budgets, baseline hygiene ------------------------


def test_synthetic_findings_skip_inline_suppression(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.analysis.core import Finding

    src = tmp_path / "mod.py"
    src.write_text("x = 1  # graftlint: disable=dtype-upcast\ny = 2\n")
    findings = [
        Finding("dtype-upcast", str(src), 1, 0, "suppressed one"),
        Finding("dtype-upcast", str(src), 2, 0, "active one"),
        Finding("donation-gap", "<testcfg:prog>", 0, 0, "synthetic"),
    ]
    active, suppressed = audit._apply_suppressions(findings)
    assert [f.message for f in suppressed] == ["suppressed one"]
    assert {f.message for f in active} == {"active one", "synthetic"}


def test_budget_doc_roundtrip_and_shrink_gate(tmp_path):
    prog = _census_prog({"all-reduce": {"count": 2, "bytes": 4096}}, None)
    prog.arg_leaves = []
    doc = audit.build_budget_doc("testcfg", 8, [prog])
    path = str(tmp_path / "budgets" / "testcfg.json")
    audit.write_budget(path, doc)
    assert audit.load_budget(path) == doc
    assert audit.budget_shrinks([prog], doc) == []

    # Committed numbers above the observed census: the budget overstates
    # the comm cost and must be refreshed, not silently coasted on.
    fat = json.loads(json.dumps(doc))
    fat["programs"]["p"]["collectives"]["all-reduce"]["count"] = 5
    shrinks = audit.budget_shrinks([prog], fat)
    assert len(shrinks) == 1 and "shrank" in shrinks[0]


def test_committed_budgets_are_well_formed():
    bdir = os.path.join(REPO, "mlx_cuda_distributed_pretraining_tpu",
                        "analysis", "budgets")
    docs = [f for f in os.listdir(bdir) if f.endswith(".json")]
    assert "model-config-sample.json" in docs
    assert "model-config-moe-8x40m.json" in docs
    for name in docs:
        with open(os.path.join(bdir, name)) as f:
            doc = json.load(f)
        assert doc["tool"] == "graftaudit"
        assert doc["config"] == name[:-len(".json")]
        assert doc["programs"], name
        for prog, entry in doc["programs"].items():
            assert set(entry) == {"collectives", "donation"}, (name, prog)
            # The whole donation sweep: every audited program aliases its
            # updated state and leaves NO provable gap.
            assert entry["donation"]["gap_bytes"] == 0, (name, prog)
            for op, v in entry["collectives"].items():
                assert v["count"] > 0 and v["bytes"] >= 0, (name, prog, op)


def test_audit_baseline_entries_carry_reasons():
    path = audit.default_audit_baseline_path()
    if not os.path.isfile(path):
        pytest.skip("no audit baseline committed (tree is clean)")
    with open(path) as f:
        doc = json.load(f)
    for e in doc.get("findings", []):
        reason = (e.get("reason") or "").strip()
        assert reason and "REPLACE" not in reason, (
            f"baseline entry for [{e.get('rule')}] {e.get('path')} has no "
            f"real reason")


def test_cli_rejects_unknown_program_and_missing_config():
    assert audit.main(["--config", "configs/no-such.yaml"]) == 2
    assert audit.main(["--config",
                       os.path.join(REPO, "configs/model-config-sample.yaml"),
                       "--programs", "bogus"]) == 2


def test_cli_list_rules(capsys):
    assert audit.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("donation-gap", "collective-census", "dtype-upcast",
                "large-constant-capture", "replicated-param"):
        assert rid in out


# -- the gate (subprocess, slow) ---------------------------------------------


@pytest.mark.slow
def test_sample_config_audits_clean():
    """The merged tree must audit green: zero new findings and a committed
    budget that matches a fresh lowering, exactly what scripts/lint.sh and
    the bench gate enforce."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-m",
         "mlx_cuda_distributed_pretraining_tpu.analysis.audit",
         "--config", "configs/model-config-sample.yaml", "--format", "json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["tool"] == "graftaudit"
    assert doc["new"] == [] and doc["stale_budget"] == []
    assert len(doc["suppressed"]) >= 3  # the muon Newton-Schulz fp32 dots
