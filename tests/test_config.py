"""Config schema tests: reference YAMLs must load unchanged."""

import textwrap

from mlx_cuda_distributed_pretraining_tpu.config import Config, apply_overrides

SAMPLE_YAML = textwrap.dedent(
    """
    name: "Llama (2M)"
    overwrite: true
    data:
      input_file: "train.jsonl"
      validation_file: "val.jsonl"
      tokenizer_path: null
      preprocessing:
        max_context_size: 1024
        chunk_overlap: 0
      tokenizer:
        normal_vocab_size: 256
        special_tokens:
          pad: "<pad>"
          bos: "<bos>"
          eos: "<eos>"
    model:
      architecture: "llama"
      dimensions:
        hidden_size: 128
        intermediate_size: 256
        num_layers: 4
      attention:
        num_heads: 8
        num_kv_heads: null
        head_dim: null
        max_position_embeddings: null
      normalization:
        rms_norm_eps: 1.0e-5
      rope:
        theta: 10000
        traditional: false
        scaling: null
      misc:
        attention_bias: false
        mlp_bias: false
        tie_word_embeddings: true
    training:
      epochs: 1
      hyperparameters:
        batch_size: 16
        learning_rate: 2.0e-2
        weight_decay: 0.01
      scheduler:
        type: "cosine"
        min_lr_ratio: 0.01
      optimization:
        optimizer: "muon"
    logging:
      log_dir: "logs"
      checkpoint_dir: "checkpoints"
      steps:
        logging_interval: 1
        checkpoint_interval: 10000
        validation_interval: 1000
      metrics:
        log_loss: true
    system:
      seed: 42
      device: "gpu"
      distributed: false
    """
)


def test_reference_yaml_roundtrip(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(SAMPLE_YAML)
    cfg = Config.from_yaml(str(p))
    assert cfg.name == "Llama (2M)"
    assert cfg.overwrite is True
    assert cfg.model.hidden_size == 128
    assert cfg.model.num_heads == 8
    assert cfg.model.num_kv_heads == 8  # null -> num_heads
    assert cfg.model.head_dim == 16
    assert cfg.training.batch_size == 16
    assert cfg.training.learning_rate == 2.0e-2
    assert cfg.training.optimizer_name == "muon"
    assert cfg.training.epochs == 1
    assert cfg.logging.validation_interval == 1000
    assert cfg.system.seed == 42
    assert cfg.data.max_context_size == 1024

    out = tmp_path / "copy.yaml"
    cfg.to_yaml(str(out))
    cfg2 = Config.from_yaml(str(out))
    assert cfg2.model.hidden_size == cfg.model.hidden_size
    assert cfg2.training.optimizer_name == cfg.training.optimizer_name


def test_missing_name_raises(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("data:\n  input_file: x.jsonl\n")
    try:
        Config.from_yaml(str(p))
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_unknown_keys_tolerated():
    cfg = Config.from_dict(
        {"name": "t", "system": {"seed": 1, "device": "tpu", "future_flag": 7}}
    )
    assert cfg.system.seed == 1
    assert getattr(cfg.system, "_extras")["future_flag"] == 7


def test_dotted_overrides():
    d = {"name": "t", "training": {"hyperparameters": {"batch_size": 16}}}
    d2 = apply_overrides(d, {"training.hyperparameters.batch_size": 4, "system.seed": 9})
    cfg = Config.from_dict(d2)
    assert cfg.training.batch_size == 4
    assert cfg.system.seed == 9
    # original untouched
    assert d["training"]["hyperparameters"]["batch_size"] == 16


def test_mesh_config():
    cfg = Config.from_dict({"name": "t", "system": {"seed": 0, "device": "tpu", "mesh": {"dp": -1, "tp": 2}}})
    assert cfg.system.mesh == {"dp": -1, "tp": 2}
    assert cfg.system.compute_dtype == "float32"
    cfg2 = Config.from_dict(
        {"name": "t", "system": {"seed": 0, "device": "tpu", "mixed_precision": True, "precision": "float16"}}
    )
    assert cfg2.system.compute_dtype == "bfloat16"  # fp16 mapped to bf16 on TPU


def test_system_compute_dtype_explicit_key():
    """system.compute_dtype in YAML is honored even though the dataclass
    derives it (it lands in _extras — the bench trainer config relies on
    this)."""
    from mlx_cuda_distributed_pretraining_tpu.config import Config

    cfg = Config.from_dict({
        "name": "t", "system": {"compute_dtype": "bfloat16"},
    })
    assert cfg.system.compute_dtype == "bfloat16"
    cfg2 = Config.from_dict({"name": "t", "system": {}})
    assert cfg2.system.compute_dtype == "float32"
    cfg3 = Config.from_dict({"name": "t", "system": {"mixed_precision": True}})
    assert cfg3.system.compute_dtype == "bfloat16"
    assert cfg3.system.fused_ce_chunk == -1


def test_pipeline_config_validation():
    """Invalid pp/interleave/microbatch combinations fail at config load with
    errors naming the keys, not as reshape tracer errors inside the step."""
    import pytest

    def mk(**sys_extra):
        d = {
            "name": "t",
            "training": {"hyperparameters": {"batch_size": 32}},
            "model": {"dimensions": {"num_layers": 16}},
            "system": {"seed": 0, "device": "cpu", "mesh": {"pp": 4, "dp": 2},
                       "pipeline_microbatches": 8, **sys_extra},
        }
        return Config.from_dict(d)

    cfg = mk(pipeline_interleave=2, pipeline_compute_skip=False)
    assert cfg.system.pipeline_interleave == 2
    assert cfg.system.pipeline_compute_skip is False
    # defaults: interleave 1, compute-skip on
    assert mk().system.pipeline_interleave == 1
    assert mk().system.pipeline_compute_skip is True

    with pytest.raises(ValueError, match="batch_size=30 must be divisible"):
        d = mk().to_dict()
        d["training"]["hyperparameters"]["batch_size"] = 30
        Config.from_dict(d)
    with pytest.raises(ValueError, match=r"num_layers=14 must be divisible"):
        d = mk(pipeline_interleave=2).to_dict()
        d["model"]["dimensions"]["num_layers"] = 14
        Config.from_dict(d)
    with pytest.raises(ValueError, match="pipeline_microbatches >= mesh.pp"):
        d = mk(pipeline_interleave=2).to_dict()
        d["system"]["pipeline_microbatches"] = 2
        d["training"]["hyperparameters"]["batch_size"] = 4
        Config.from_dict(d)
    with pytest.raises(ValueError, match="pipeline_interleave must be >= 1"):
        mk(pipeline_interleave=0)
    # pp=1 (or no mesh): the divisibility rules don't apply
    d = mk().to_dict()
    d["system"]["mesh"] = {"dp": 2}
    d["training"]["hyperparameters"]["batch_size"] = 30
    assert Config.from_dict(d).system.mesh == {"dp": 2}
