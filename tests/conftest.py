"""Test harness: force a pure 8-device virtual CPU platform.

Multi-device-without-a-pod strategy (SURVEY.md §4): DP/TP/SP sharding
correctness is validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``); the real TPU chip is only
touched by bench.py.

The session environment activates the axon TPU plugin via sitecustomize and
forces ``jax_platforms="axon,cpu"`` at the jax-config level, so setting the
``JAX_PLATFORMS`` env var is not enough — tests must also reset the config
and deregister the axon backend factory before any backend initializes,
otherwise every test run dials the (single-client) TPU tunnel.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

# This harness leans on two PRIVATE jax internals. Assert they exist with a
# loud explanation so a jax upgrade that renames them fails HERE with a
# pointer, not deep inside the first test with an AttributeError.
assert hasattr(_xb, "_backend_factories") and hasattr(
    _xb._backend_factories, "pop"), (
    "jax._src.xla_bridge._backend_factories (private dict) is gone — the jax "
    "upgrade renamed it. The test harness pops the 'axon' TPU plugin factory "
    "from it so CPU test runs never dial the single-client TPU tunnel; find "
    "the new factory-registry name and update tests/conftest.py (and the CPU "
    "guard in mlx_cuda_distributed_pretraining_tpu/__init__.py).")
assert hasattr(_xb, "backends_are_initialized"), (
    "jax._src.xla_bridge.backends_are_initialized() is gone — the jax "
    "upgrade renamed it. tests/conftest.py uses it to prove the backend "
    "de-registration below still happens early enough; find the replacement "
    "and update this file.")

assert not _xb.backends_are_initialized(), "jax backends initialized before conftest"
_xb._backend_factories.pop("axon", None)


# --- shared subprocess-spawn helpers ---------------------------------------
# Several suites (test_multiprocess, test_supervisor, test_serve_tp, bench
# children) spawn real Python subprocesses that must see a forced virtual
# CPU device count. The env recipe is identical everywhere; keep it in ONE
# place so "how do child processes get N devices" has a single answer.

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def device_env(n, base=None):
    """Child-process env with ``n`` virtual CPU devices.

    Sets PYTHONPATH to the repo root (which both makes the package importable
    and drops the axon TPU sitecustomize dir from the inherited path), forces
    the CPU backend, and forces the host-platform device count.
    """
    env = dict(os.environ if base is None else base)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={int(n)}"
    return env


def spawn_with_devices(argv, n, **popen_kw):
    """subprocess.Popen(argv) under device_env(n), output captured as text."""
    import subprocess

    kw = dict(stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    kw.update(popen_kw)
    return subprocess.Popen(argv, env=device_env(n), **kw)


# --- serial scheduling for thread-heavy drills ------------------------------
# A few serving tests run several live HTTP servers plus engine/router
# threads inside the test process and assert on stream timing. Under a
# loaded batch (xdist workers, a busy CI box) they flake purely from
# scheduler contention. The ``serial`` marker (pytest.ini) moves them to
# the END of the collection order — they run after the bulk of the suite
# has released its threads — and pins them all to one xdist group so a
# parallel runner never splits them across simultaneously-busy workers.

def pytest_collection_modifyitems(config, items):
    import pytest

    serial = [it for it in items if it.get_closest_marker("serial")]
    if not serial:
        return
    rest = [it for it in items if not it.get_closest_marker("serial")]
    for it in serial:
        it.add_marker(pytest.mark.xdist_group("serial"))
    items[:] = rest + serial
