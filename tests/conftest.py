"""Test harness: force a pure 8-device virtual CPU platform.

Multi-device-without-a-pod strategy (SURVEY.md §4): DP/TP/SP sharding
correctness is validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``); the real TPU chip is only
touched by bench.py.

The session environment activates the axon TPU plugin via sitecustomize and
forces ``jax_platforms="axon,cpu"`` at the jax-config level, so setting the
``JAX_PLATFORMS`` env var is not enough — tests must also reset the config
and deregister the axon backend factory before any backend initializes,
otherwise every test run dials the (single-client) TPU tunnel.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

assert not _xb.backends_are_initialized(), "jax backends initialized before conftest"
_xb._backend_factories.pop("axon", None)
