"""Test harness: force an 8-device virtual CPU platform BEFORE jax imports.

This is the multi-device-without-a-pod strategy from SURVEY.md §4: DP/TP/SP
sharding correctness is validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``); the real TPU chip is only
used by bench.py.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
