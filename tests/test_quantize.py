"""Low-precision end to end: weight-only int8/int4 serving + scaled
low-precision training matmuls.

Serving bar: per-channel weight-only quantization is a LAYOUT change,
never a decode-policy change — greedy w8 serving must be text-identical
to fp on the test model (including on top of the int8 KV cache and the
prefix cache), quantize-on-load must place only quantized slices (per-
device byte accounting, no fp replica), and a live engine must hot-swap
an fp checkpoint INTO its quantized layout. Training bar: the opt-in
int8 matmul path (model.matmul_precision) tracks loss parity with the
bf16 cast within the same order of deviation.
"""

import dataclasses
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
    CheckpointManager,
)
from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import (
    save_safetensors,
)
from mlx_cuda_distributed_pretraining_tpu.config import DataConfig, ModelConfig
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.models.quantize import (
    check_weight_dtype,
    dequantize_leaf,
    pack_int4,
    quantize_leaf,
    quantize_weights,
    quantized_key_shapes,
    unpack_int4,
    weight_dtype_of,
    weight_plane_bytes,
)
from mlx_cuda_distributed_pretraining_tpu.parallel import build_serve_mesh
from mlx_cuda_distributed_pretraining_tpu.parallel.sharding_rules import (
    param_pspec,
)
from mlx_cuda_distributed_pretraining_tpu.serve import BatchEngine, EngineConfig
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOK = TokenizerManager(DataConfig())
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)
MAX_LEN = 128
PROMPTS = ["the quick brown fox", "a b c a b c a"]


# -- quantization core --------------------------------------------------------

def test_check_weight_dtype_normalizes_and_rejects():
    assert check_weight_dtype(None) == "fp"
    assert check_weight_dtype("") == "fp"
    assert check_weight_dtype("FP32") == "fp"
    assert check_weight_dtype("bf16") == "fp"
    assert check_weight_dtype("INT8") == "int8"
    assert check_weight_dtype("int4") == "int4"
    with pytest.raises(ValueError, match="weight_dtype"):
        check_weight_dtype("fp8")


@pytest.mark.parametrize("wd", ["int8", "int4"])
def test_per_channel_round_trip(wd):
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 48)).astype(np.float32))
    leaf = quantize_leaf(w, wd)
    back = dequantize_leaf(leaf)
    # Symmetric per-output-channel grid: worst-case round-trip error is
    # half a quantization step of that channel's own scale.
    step = np.asarray(leaf["weight_s"])
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err.max(axis=0) <= step / 2 + 1e-6).all()


def test_int4_pack_unpack_exact():
    rng = np.random.default_rng(1)
    vals = jnp.asarray(rng.integers(-7, 8, size=(64, 24)).astype(np.int8))
    packed = pack_int4(vals)
    assert packed.shape == (32, 24) and packed.dtype == jnp.int8
    assert (unpack_int4(packed) == vals).all()
    # expert-bank layout round-trips too
    bank = jnp.asarray(rng.integers(-7, 8, size=(3, 16, 8)).astype(np.int8))
    assert (unpack_int4(pack_int4(bank)) == bank).all()
    with pytest.raises(ValueError, match="even contraction"):
        pack_int4(vals[:63])


def test_quantized_key_shapes_and_odd_contraction():
    out = quantized_key_shapes("layers.0.attention.wq.weight", (32, 32),
                               "int4")
    assert out == {"layers.0.attention.wq.weight_q4": (16, 32),
                   "layers.0.attention.wq.weight_s": (32,)}
    # odd contraction dim cannot pack two nibbles per byte: stays fp
    assert quantized_key_shapes("layers.0.attention.wq.weight", (33, 32),
                                "int4") is None
    # non-matmul leaves never quantize
    assert quantized_key_shapes("layers.0.attention_norm.weight", (32,),
                                "int8") is None
    assert quantized_key_shapes("tok_embeddings.weight", (256, 32),
                                "int8") is None


@pytest.mark.parametrize("wd", ["int8", "int4"])
def test_forward_matches_dequantized_oracle(wd):
    # The quantized apply (int storage, scale in the matmul epilogue)
    # must match the fp forward over DEQUANTIZED weights — same grid
    # points, different layout; only float associativity differs.
    pq = quantize_weights(PARAMS, wd)
    assert weight_dtype_of(pq) == wd

    def dequant(tree):
        if isinstance(tree, dict):
            if "weight_q" in tree or "weight_q4" in tree:
                out = {k: v for k, v in tree.items()
                       if k not in ("weight_q", "weight_q4", "weight_s")}
                out["weight"] = dequantize_leaf(tree)
                return out
            return {k: dequant(v) for k, v in tree.items()}
        if isinstance(tree, list):
            return [dequant(v) for v in tree]
        return tree

    toks = jnp.asarray([[5, 9, 3, 7, 2, 8]], jnp.int32)
    out_q, _ = llama.forward(pq, toks, ARGS)
    out_ref, _ = llama.forward(dequant(pq), toks, ARGS)
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_ref),
                               rtol=1e-4, atol=1e-4)


def test_weight_plane_bytes_ratios():
    fp = weight_plane_bytes(PARAMS)
    w8 = weight_plane_bytes(quantize_weights(PARAMS, "int8"))
    w4 = weight_plane_bytes(quantize_weights(PARAMS, "int4"))
    assert fp > w8 > w4


# -- engine parity ------------------------------------------------------------

def _engine(mesh=None, **kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": MAX_LEN,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg, mesh=mesh)


def _collect(eng, prompts, max_tokens=20):
    eng.start()
    outs = [None] * len(prompts)
    try:
        def run(i):
            outs[i] = eng.generate(prompts[i], max_tokens=max_tokens,
                                   temperature=0.0, timeout=300.0)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        metrics = eng.metrics()
    finally:
        eng.stop()
    return outs, metrics


@pytest.mark.parametrize("arm", [
    {"weight_dtype": "int8"},
    {"weight_dtype": "int8", "kv_quant": True},   # w8 on top of int8 KV
    {"weight_dtype": "int4"},
], ids=["w8", "w8_kv8", "w4"])
def test_weight_quant_greedy_matches_fp(arm):
    ref, _ = _collect(_engine(**{k: v for k, v in arm.items()
                                 if k != "weight_dtype"}), PROMPTS)
    q, m = _collect(_engine(**arm), PROMPTS)
    assert m["weight_dtype"] == arm["weight_dtype"]
    assert m["weight_bytes"] < weight_plane_bytes(PARAMS)
    for r, t in zip(ref, q):
        assert t["finish_reason"] == r["finish_reason"]
        if arm["weight_dtype"] == "int8":
            # acceptance bar: w8 greedy is token-exact vs fp
            assert t["text"] == r["text"]
            assert t["tokens"] == r["tokens"]


def test_weight_quant_prefix_cache_adoption_parity():
    shared = "the quick brown fox jumps over the lazy dog and then"
    prompts = [shared + " stops", shared + " keeps going"]

    def run(eng):
        eng.start()
        try:
            outs = [eng.generate(p, max_tokens=16, temperature=0.0,
                                 timeout=300.0) for p in prompts]
            return outs, eng.metrics()["prefix_cache_hits"]
        finally:
            eng.stop()

    ref, ref_hits = run(_engine(block_size=16, prefix_min_hit_blocks=1))
    q, q_hits = run(_engine(block_size=16, prefix_min_hit_blocks=1,
                            weight_dtype="int8"))
    assert q_hits == ref_hits and q_hits >= 1
    for r, t in zip(ref, q):
        assert t["text"] == r["text"]
        assert t["prefix_cached_tokens"] == r["prefix_cached_tokens"]


def test_engine_hot_swap_fp_checkpoint_into_quantized_replica(tmp_path):
    # A live w8 replica receives an fp checkpoint (the trainer's output):
    # swap_params must quantize it INTO the serving layout, bump the
    # version, and keep greedy output identical (same weights in).
    flat = {k: np.asarray(v) for k, v in flatten_dict(PARAMS).items()}
    path = str(tmp_path / "model.safetensors")
    save_safetensors(path, flat)

    eng = _engine(weight_dtype="int8")
    eng.start()
    try:
        base = eng.generate(PROMPTS[0], max_tokens=16, temperature=0.0,
                            timeout=300.0)
        loaded = CheckpointManager.load_params(path, like=PARAMS)
        version = eng.swap_params(loaded)
        assert version == 1
        post = eng.generate(PROMPTS[0], max_tokens=16, temperature=0.0,
                            timeout=300.0)
        m = eng.metrics()
        assert m["params_version"] == 1
        assert m["weight_dtype"] == "int8"
        assert weight_dtype_of(eng.params) == "int8"
        assert post["text"] == base["text"]
        assert post["tokens"] == base["tokens"]
    finally:
        eng.stop()


# -- quantize-on-load ---------------------------------------------------------

def test_load_params_quantize_matches_host_quantization(tmp_path):
    flat = {k: np.asarray(v) for k, v in flatten_dict(PARAMS).items()}
    path = str(tmp_path / "model.safetensors")
    save_safetensors(path, flat)
    loaded = CheckpointManager.load_params(path, like=PARAMS,
                                           weight_dtype="int8")
    want = flatten_dict(quantize_weights(PARAMS, "int8"))
    got = flatten_dict(loaded)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_array_equal(np.asarray(want[k]),
                                      np.asarray(got[k]), err_msg=k)


def test_reshard_on_load_int4_per_device_byte_budget(tmp_path):
    # fp safetensors stays canonical; each tp=2 device quantizes only its
    # own slice. Whole-tree per-device byte accounting must come out
    # EXACTLY at the quantized layout's cost (sharded leaves place once
    # across the mesh, replicated ones once per device — no fp replica of
    # any quantized weight anywhere), with the quantized plane itself
    # under a quarter of its fp bytes.
    flat_host = {k: np.asarray(v) for k, v in flatten_dict(PARAMS).items()}
    path = str(tmp_path / "model.safetensors")
    save_safetensors(path, flat_host)

    mesh = build_serve_mesh({"tp": 2}, devices=jax.devices()[:2])
    loaded = CheckpointManager.load_params(path, like=PARAMS, mesh=mesh,
                                           weight_dtype="int4")
    assert weight_dtype_of(loaded) == "int4"
    flat = flatten_dict(loaded)

    expected = actual = 0
    for k, v in flat.items():
        sharded = any(ax is not None for ax in param_pspec(k, v.shape, mesh))
        expected += v.nbytes * (1 if sharded else 2)
        actual += sum(s.data.nbytes for s in v.addressable_shards)
    assert actual == expected

    # per-device slices reproduce the host-side full quantization exactly
    want = flatten_dict(quantize_weights(PARAMS, "int4"))
    assert set(want) == set(flat)
    for k in want:
        np.testing.assert_array_equal(np.asarray(want[k]),
                                      np.asarray(flat[k]), err_msg=k)

    # quantized plane (ints + scales) lands below fp/4
    q_bytes = sum(v.nbytes for k, v in flat.items()
                  if k.endswith(("weight_q4", "weight_s")))
    fp_bytes = sum(v.nbytes for k, v in flatten_dict(PARAMS).items()
                   if quantized_key_shapes(k, v.shape, "int4"))
    assert 0 < q_bytes < fp_bytes / 4

    # and it serves: greedy output matches the host-quantized engine
    cfg = EngineConfig(num_slots=2, max_len=MAX_LEN, prefill_chunk=16)
    q, _ = _collect(BatchEngine(loaded, ARGS, TOK, cfg, mesh=mesh),
                    PROMPTS[:1])
    host_q, _ = _collect(_engine(weight_dtype="int4"), PROMPTS[:1])
    assert q[0]["text"] == host_q[0]["text"]


# -- training matmul precision ------------------------------------------------

def test_model_config_matmul_precision_validation():
    assert ModelConfig(matmul_precision="INT8").matmul_precision == "int8"
    assert ModelConfig(matmul_precision="fp32").matmul_precision is None
    assert ModelConfig().matmul_precision is None
    with pytest.raises(ValueError, match="matmul_precision"):
        ModelConfig(matmul_precision="fp8")
    mc = ModelConfig(matmul_precision="bf16")
    assert LlamaArgs.from_config(mc, 256).matmul_precision == "bf16"


def test_matmul_precision_loss_parity_vs_bf16():
    # int8 fake-quant forward must track the fp loss within the same
    # order of deviation as the bf16 operand cast — the "is low precision
    # safe to turn on" gate.
    args = dataclasses.replace(ARGS, attention_type="flash")
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0,
                              ARGS.vocab_size)

    def loss_fn(p, a):
        logits, _ = llama.forward(p, toks, a)
        lse = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(lse[:, :-1],
                                             toks[:, 1:, None], -1))

    losses = {}
    for prec in (None, "bf16", "int8"):
        a = dataclasses.replace(args, matmul_precision=prec)
        losses[prec] = float(loss_fn(PARAMS, a))
        g = jax.grad(loss_fn)(PARAMS, a)
        assert all(bool(jnp.all(jnp.isfinite(x)))
                   for x in jax.tree_util.tree_leaves(g))
    base = abs(losses[None]) + 1e-12
    dev_bf16 = abs(losses["bf16"] - losses[None]) / base
    dev_int8 = abs(losses["int8"] - losses[None]) / base
    assert dev_int8 < 1e-4
    assert dev_int8 <= max(10.0 * dev_bf16, 1e-5)


def test_gmm_int8_precision_fwd_bwd():
    from mlx_cuda_distributed_pretraining_tpu.ops import grouped_matmul as gm

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(256, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(4, 64, 96)), jnp.float32) * 0.1
    gs = jnp.array([64, 128, 0, 64], jnp.int32)
    y_fp = gm.gmm(x, w, gs, block_t=64)
    y_q = gm.gmm(x, w, gs, block_t=64, precision="int8")
    rel = float(jnp.linalg.norm(y_q - y_fp) / jnp.linalg.norm(y_fp))
    assert 0 < rel < 0.02  # quantized but close

    def loss(prec):
        def f(x, w):
            return jnp.sum(gm.gmm(x, w, gs, block_t=64, precision=prec) ** 2)
        return f

    gx_fp, gw_fp = jax.grad(loss(None), argnums=(0, 1))(x, w)
    gx_q, gw_q = jax.grad(loss("int8"), argnums=(0, 1))(x, w)
    for a, b in ((gx_q, gx_fp), (gw_q, gw_fp)):
        rel = float(jnp.linalg.norm(a - b) / jnp.linalg.norm(b))
        assert rel < 0.02


def test_flash_attention_precision_modes():
    from mlx_cuda_distributed_pretraining_tpu.ops.flash_attention import (
        check_matmul_precision,
        flash_attention,
    )

    assert check_matmul_precision(None) is None
    assert check_matmul_precision("FP32") is None
    assert check_matmul_precision("int8") == "int8"
    with pytest.raises(ValueError, match="precision"):
        check_matmul_precision("fp8")

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 128, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 128, 4, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 128, 4, 16)), jnp.float32)
    o = flash_attention(q, k, v, mask_type="causal")
    o8 = flash_attention(q, k, v, mask_type="causal", precision="int8")
    rel = float(jnp.linalg.norm(o8 - o) / jnp.linalg.norm(o))
    assert 0 < rel < 0.05
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, mask_type="causal", precision="int8") ** 2))(q)
    assert bool(jnp.all(jnp.isfinite(g)))


# -- bandwidth decode model ---------------------------------------------------

def test_weight_bytes_per_token_and_roofline():
    from mlx_cuda_distributed_pretraining_tpu.obs.flops import (
        decode_roofline_tok_s,
        weight_bytes_per_token,
    )

    mc = ModelConfig(dimensions={"hidden_size": 256, "intermediate_size": 512,
                                 "num_layers": 4},
                     attention={"num_heads": 8, "num_kv_heads": 8,
                                "head_dim": 32})
    n = 3_000_000  # matmul-plane dominated at these dims
    fp = weight_bytes_per_token(mc, n, "fp")
    w8 = weight_bytes_per_token(mc, n, "int8")
    w4 = weight_bytes_per_token(mc, n, "int4")
    assert fp > w8 > w4
    with pytest.raises(ValueError, match="weight_dtype"):
        weight_bytes_per_token(mc, n, "fp8")
    assert decode_roofline_tok_s(w8, None) is None
    assert decode_roofline_tok_s(w8, 1e12) == pytest.approx(1e12 / w8)
    # the int8 roofline clears the 1.5x decode acceptance bar analytically
    assert decode_roofline_tok_s(w8, 1e12) > 1.5 * decode_roofline_tok_s(
        fp, 1e12)


# -- graftaudit rule ----------------------------------------------------------

def _rule_prog(fn, paths, *avals):
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        ArgLeaf,
        AuditProgram,
    )

    traced = jax.jit(fn).trace(*avals)
    leaves = []
    for i, (p, a) in enumerate(zip(paths, jax.tree_util.tree_leaves(avals))):
        leaves.append(ArgLeaf(index=i, name=p, path=p, shape=tuple(a.shape),
                              dtype=str(a.dtype),
                              nbytes=a.size * a.dtype.itemsize,
                              donated=False))
    return AuditProgram(name="t", config_name="t", lowered=traced.lower(),
                        closed_jaxpr=traced.jaxpr, arg_leaves=leaves,
                        out_avals=list(traced.jaxpr.out_avals))


def test_dequant_materialization_rule():
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        DequantMaterialization,
    )

    rule = DequantMaterialization()
    W = jax.ShapeDtypeStruct((512, 512), jnp.int8)
    S = jax.ShapeDtypeStruct((512,), jnp.float32)
    X = jax.ShapeDtypeStruct((4, 512), jnp.float32)
    paths = ["a.weight_q", "a.weight_s", "x"]

    # fused epilogue: convert feeds exactly one dot, scale after — clean
    good = lambda wq, s, x: (x @ wq.astype(jnp.float32)) * s
    assert list(rule.check(_rule_prog(good, paths, W, S, X))) == []

    # dequant-then-scale BEFORE the dot: fp copy feeds a mul — flagged
    bad = lambda wq, s, x: x @ (wq.astype(jnp.float32) * s)
    found = list(rule.check(_rule_prog(bad, paths, W, S, X)))
    assert len(found) == 1 and "a.weight_q" in found[0].message

    # fp copy escaping as a program output — flagged
    esc = lambda wq, s, x: ((x @ wq.astype(jnp.float32)) * s,
                            wq.astype(jnp.float32))
    assert len(list(rule.check(_rule_prog(esc, paths, W, S, X)))) == 1

    # one fp copy reused by two matmuls — flagged
    def reuse(wq, s, x):
        w = wq.astype(jnp.float32)
        return x @ w + (x * 2.0) @ w
    assert len(list(rule.check(_rule_prog(reuse, paths, W, S, X)))) == 1

    # int4 unpack chain (shifts -> convert -> single dot) — clean
    def int4(wq4, s, x):
        low = (wq4 << 4) >> 4
        high = wq4 >> 4
        w = jnp.stack([low, high], axis=1).reshape(1024, 512)
        return (x @ w.astype(jnp.float32)) * s
    W4 = jax.ShapeDtypeStruct((512, 512), jnp.int8)
    X4 = jax.ShapeDtypeStruct((4, 1024), jnp.float32)
    assert list(rule.check(_rule_prog(
        int4, ["a.weight_q4", "a.weight_s", "x"], W4, S, X4))) == []


@pytest.mark.slow
def test_audit_serve_decode_quantized_programs_clean():
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit import (
        build_programs,
    )
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        DequantMaterialization,
    )

    progs = build_programs(
        os.path.join(REPO, "configs", "model-config-sample.yaml"),
        wanted=("serve_decode_w8", "serve_decode_w4"))
    assert [p.name for p in progs] == ["serve_decode_w8", "serve_decode_w4"]
    rule = DequantMaterialization()
    for prog in progs:
        assert any(leaf.path.endswith(("weight_q", "weight_q4"))
                   for leaf in prog.arg_leaves), "params not quantized"
        assert list(rule.check(prog)) == []
