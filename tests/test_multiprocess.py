"""Real multi-PROCESS (not just multi-device) tests: two jax.distributed
CPU processes train on an fsdp mesh, save a checkpoint of process-sharded
state, and resume (VERDICT r1 weak #3: the old save crashed on arrays not
fully addressable from process 0).

Each test spawns two subprocesses running ``_WORKER`` below with a
coordinator rendezvous on localhost; each process exposes 2 CPU devices, so
the global mesh is fsdp=4 across 2 processes and every parameter shard
spans both processes.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    # Real rendezvous (parallel/elastic.py): also enables gloo CPU
    # collectives — without them every computation over a process-spanning
    # sharding fails on the CPU backend.
    from mlx_cuda_distributed_pretraining_tpu.parallel.elastic import rendezvous
    rendezvous(
        coordinator_address={coord!r},
        num_processes=2,
        process_id=int(sys.argv[1]),
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    import yaml

    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    workdir = {workdir!r}
    os.chdir(workdir)  # relative data paths resolve against cwd
    cfg_path = os.path.join(workdir, "cfg.yaml")
    phase = sys.argv[2]
    if phase == "resume":
        cfg = yaml.safe_load(open(cfg_path))
        cfg["training"]["hyperparameters"]["iters"] = 4
        cfg["resume"] = {{"checkpoint": "2"}}
        cfg_path = os.path.join(workdir, "cfg_resume.yaml")
        if int(sys.argv[1]) == 0:
            yaml.dump(cfg, open(cfg_path, "w"))
        import jax.experimental.multihost_utils as mh
        mh.sync_global_devices("cfg_written")

    config = Config.from_yaml(cfg_path)
    t = Trainer(config, runs_root=os.path.join(workdir, "runs"), quiet=True)
    assert jax.process_count() == 2, jax.process_count()
    assert t.mesh is not None and t.mesh.shape["fsdp"] == 4, t.mesh
    # fsdp-sharded params must span both processes
    leaves = jax.tree_util.tree_leaves(t.state["params"])
    assert any(not l.is_fully_addressable for l in leaves), "expected process-sharded params"
    t.train()
    if phase == "resume" and jax.process_index() == 0:
        log = open(os.path.join(workdir, "runs", config.name, "log.txt")).read()
        assert "Resumed from checkpoint 2" in log, log[-2000:]
    print(f"WORKER_OK p{{jax.process_index()}} {{phase}}")
    """
)

CFG = """
name: "mp-fsdp"
overwrite: true
data:
  input_file: "corpus.jsonl"
  preprocessing: {max_context_size: 32}
  tokenizer: {default: "byte"}
model:
  architecture: "llama"
  dimensions: {hidden_size: 32, intermediate_size: 64, num_layers: 2, num_heads: 2}
  attention: {num_kv_heads: 2, max_position_embeddings: 32}
training:
  hyperparameters: {batch_size: 4, learning_rate: 1e-3, iters: 2}
  optimization: {optimizer: "adamw"}
logging:
  steps: {logging_interval: 1, checkpoint_interval: 2, validation_interval: 0}
system:
  seed: 7
  device: "cpu"
  mesh: {fsdp: 4}
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn(worker_src, pid, phase):
    from conftest import spawn_with_devices

    return spawn_with_devices(
        [sys.executable, "-c", worker_src, str(pid), phase], n=2)


def _run_phase(workdir, phase):
    coord = f"localhost:{_free_port()}"
    src = _WORKER.format(repo=REPO, coord=coord, workdir=str(workdir))
    procs = [_spawn(src, pid, phase) for pid in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"
        assert "WORKER_OK" in out
    return outs


@pytest.mark.slow
def test_two_process_fsdp_train_save_resume(tmp_path):
    import json as _json

    with open(tmp_path / "corpus.jsonl", "w") as f:
        for i in range(200):
            f.write(_json.dumps({"text": f"doc {i} " + "hello world " * 8}) + "\n")
    with open(tmp_path / "cfg.yaml", "w") as f:
        f.write(CFG)

    _run_phase(tmp_path, "train")
    ckpt = tmp_path / "runs" / "mp-fsdp" / "checkpoints" / "step_2_model.safetensors"
    assert ckpt.exists(), "process-0 checkpoint of process-sharded state missing"

    _run_phase(tmp_path, "resume")
    final = tmp_path / "runs" / "mp-fsdp" / "checkpoints" / "step_final_model.safetensors"
    assert final.exists()
