"""Outbound-call policy (serve/policy.py) + fault injection
(serve/faults.py) for the serving plane — graftchaos.

Pure-policy tests pin the primitives (Deadline arithmetic, deterministic
backoff, token-bucket retry budget, the circuit-breaker state machine)
with no device and no sockets. The HTTP tests run a stub replica (or the
real tiny-model replicas, test_serve.py-style) and drive failures
through the ONE fault-injection choke point instead of monkeypatching:
pre-first-byte stream retry, deadline-header propagation, retry-budget
exhaustion, and the KV-corrupt -> quarantine -> local-prefill-fallback
degradation rung."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.infer.server import (
    InferenceService,
    request_generate,
    request_stream,
    serve,
)
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.serve import (
    AdmissionRefusedError,
    BatchEngine,
    BreakerOpenError,
    CallPolicy,
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceeded,
    EngineConfig,
    FleetRouter,
    PolicyConfig,
    Request,
    Router,
    Scheduler,
    SlotKVPool,
    faults,
    serve_router,
)
from mlx_cuda_distributed_pretraining_tpu.serve.policy import (
    CircuitBreaker,
    TokenBucket,
    backoff_s,
)
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

TOK = TokenizerManager(DataConfig())
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)
MAX_LEN = 128


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.reset()


@pytest.fixture
def _clean_fault_registry():
    """Registry hygiene for the thread-heavy drills, both directions: the
    autouse fixture only resets AFTER a test, so a rule leaked by an
    earlier test that died before its teardown (or armed in a still-draining
    background thread) could tear this test's first stream. Reset before
    AND after so these drills always start from a silent registry."""
    faults.reset()
    yield
    faults.reset()


def _engine(**kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": MAX_LEN,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg)


def _replica(**kw):
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    service.engine = _engine(**kw).start()
    httpd = serve(service, port=0)
    return service, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


# -- deadline arithmetic (no device, no sockets) ------------------------------

def test_deadline_header_roundtrip_and_clamp():
    dl = Deadline.after(1.0)
    assert 0.0 < dl.remaining_s() <= 1.0
    hv = float(dl.header_value())
    assert 0.0 < hv <= 1000.0
    # round trip: the next hop's parsed budget never exceeds what was sent
    hop2 = Deadline.from_header({DEADLINE_HEADER: dl.header_value()})
    assert hop2 is not None and hop2.remaining_ms() <= hv
    # clamp bounds the socket timeout by the remaining budget
    assert dl.clamp(30.0) <= 1.0
    assert dl.clamp(0.05) == 0.05
    # absent / malformed headers mean "no deadline", never an error
    assert Deadline.from_header({}) is None
    assert Deadline.from_header(None) is None
    assert Deadline.from_header({DEADLINE_HEADER: "soon-ish"}) is None
    gone = Deadline.after(0.0)
    assert gone.expired() and gone.header_value() == "0"
    with pytest.raises(DeadlineExceeded):
        gone.clamp(5.0)
    # the exception taxonomy every HTTP 504 mapping relies on
    assert issubclass(DeadlineExceeded, TimeoutError)
    assert issubclass(AdmissionRefusedError, DeadlineExceeded)
    assert issubclass(BreakerOpenError, ConnectionError)


def test_backoff_deterministic_jittered_and_capped():
    assert backoff_s(1, key="t1") == backoff_s(1, key="t1")  # replayable
    assert backoff_s(1, key="t1") != backoff_s(1, key="t2")  # decorrelated
    assert backoff_s(1, key="t1") != backoff_s(2, key="t1")
    for attempt in range(1, 12):
        raw = min(2.0, 0.05 * 2.0 ** (attempt - 1))
        v = backoff_s(attempt, base=0.05, cap=2.0, key="x")
        assert 0.5 * raw <= v < raw  # jitter window, growth capped


def test_token_bucket_spend_and_refill():
    tb = TokenBucket(capacity=2.0, refill_per_s=20.0)
    assert tb.try_take() and tb.try_take()
    assert not tb.try_take()  # spent
    time.sleep(0.11)
    assert tb.try_take()  # refilled (bounded by capacity)
    frozen = TokenBucket(capacity=1.0, refill_per_s=0.0)
    assert frozen.try_take()
    assert not frozen.try_take() and frozen.tokens() == 0.0


def test_circuit_breaker_state_machine():
    br = CircuitBreaker(threshold=2, open_for_s=0.08)
    assert br.state == "closed" and br.allow()
    br.record(False)
    assert br.state == "closed"  # below threshold
    br.record(False)
    assert br.state == "open" and not br.allow()
    time.sleep(0.1)
    assert br.allow()  # hold-off elapsed: the single half-open probe
    assert br.state == "half_open"
    assert not br.allow()  # second caller refused while the probe is out
    br.record(False)  # probe failed
    assert br.state == "open"
    time.sleep(0.1)
    assert br.allow() and br.state == "half_open"
    br.record(True)  # probe answered
    assert br.state == "closed" and br.allow()
    br.record(False)  # success reset the failure streak
    assert br.state == "closed"
    assert br.state_code() == 0


# -- fault registry (no sockets) ----------------------------------------------

def test_faults_triggers_match_times_and_reset():
    # disarmed = pure passthrough, nothing counted
    assert faults.take("http.connect_refused", "x") is None
    assert faults.counts() == {}
    rule = faults.inject("http.connect_refused", nth=2, match="target")
    assert faults.take("http.connect_refused", "elsewhere") is None
    assert rule.calls == 0  # non-matching labels are not even counted
    assert faults.take("http.connect_refused", "target/a") is None
    assert faults.take("http.connect_refused", "target/b") is rule  # nth=2
    assert faults.take("http.connect_refused", "target/c") is None
    assert (rule.calls, rule.fires) == (3, 1)
    assert faults.counts() == {"http.connect_refused": 1}
    faults.reset()
    assert faults.counts() == {}
    every = faults.inject("scrape.timeout", every=2, times=2)
    hits = [faults.take("scrape.timeout") is not None for _ in range(8)]
    assert hits == [False, True, False, True, False, False, False, False]
    assert every.fires == 2  # times cap held
    with pytest.raises(ValueError):
        faults.inject("no.such.point")
    with pytest.raises(ValueError):
        faults.inject("arena.exhaust", nth=1, every=2)  # one trigger only


def test_faults_seeded_rate_replays_exactly():
    def pattern(seed):
        faults.reset()
        faults.inject("kv_transfer.drop", rate=0.5, seed=seed)
        return [faults.take("kv_transfer.drop") is not None
                for _ in range(16)]

    first = pattern(9)
    assert first == pattern(9)  # same seed: bit-identical replay
    assert any(first) and not all(first)
    assert first != pattern(10)  # different seed: different drill


def test_faults_active_context_disarms_only_its_rule():
    keep = faults.inject("arena.exhaust", every=1)
    with faults.active("engine.swap_fail") as rule:
        assert faults.take("engine.swap_fail") is rule
    assert faults.take("engine.swap_fail") is None  # context disarmed it
    assert faults.take("arena.exhaust") is keep  # the other rule survives


# -- stub replica (records what each dispatch arrived with) -------------------

def _stub_replica():
    state = {"deadlines": [], "hits": 0}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):
            pass

        def _reply(self, payload):
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._reply({"queue_depth": 0, "batch_occupancy": 0})

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", "0")))
            state["hits"] += 1
            raw = self.headers.get(DEADLINE_HEADER)
            state["deadlines"].append(None if raw is None else float(raw))
            self._reply({"text": "stub", "tokens": 1})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="stub-replica").start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_policy_call_stamps_strictly_decreasing_deadline():
    httpd, state, url = _stub_replica()
    try:
        pol = CallPolicy()
        dl = Deadline.after(3.0)
        pol.call(url + "/generate", data=b"{}", deadline=dl, method="POST")
        time.sleep(0.01)
        pol.call(url + "/generate", data=b"{}", deadline=dl, method="POST")
        v1, v2 = state["deadlines"]
        assert 0.0 < v2 < v1 <= 3000.0  # each hop forwards LESS budget
        # a spent budget is refused locally: the wire is never touched
        hits = state["hits"]
        with pytest.raises(DeadlineExceeded):
            pol.call(url + "/generate", data=b"{}",
                     deadline=Deadline.after(0.0), method="POST")
        assert state["hits"] == hits
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_router_propagates_deadline_header_to_replica():
    httpd, state, url = _stub_replica()
    router = Router([url], poll_interval_s=30.0)
    rhttpd = serve_router(router, port=0)
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        def post(body, headers=None):
            req = urllib.request.Request(
                rurl + "/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         **(headers or {})})
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                return resp.status

        # client header -> router -> replica: strictly shrinking budget
        assert post({"prompt": "x", "max_tokens": 1},
                    headers={DEADLINE_HEADER: "2000"}) == 200
        assert 0.0 < state["deadlines"][-1] < 2000.0
        # a body deadline_s starts the clock at the router hop
        assert post({"prompt": "x", "max_tokens": 1,
                     "deadline_s": 5.0}) == 200
        assert 0.0 < state["deadlines"][-1] <= 5000.0
        # an exhausted upstream budget answers 504 without a dispatch
        hits = state["hits"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            post({"prompt": "x", "max_tokens": 1},
                 headers={DEADLINE_HEADER: "0"})
        assert exc.value.code == 504
        assert state["hits"] == hits

        # The policy gauges are scrapeable as Prometheus text from the
        # router itself; the bare /metrics JSON shape is untouched.
        with urllib.request.urlopen(rurl + "/metrics?format=prom",
                                    timeout=10.0) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert "# TYPE serve_breaker_state gauge" in text
        assert "serve_retry_budget_tokens" in text
        assert "serve_router_requests_total" in text
        with urllib.request.urlopen(rurl + "/metrics",
                                    timeout=10.0) as resp:
            assert json.loads(resp.read())["role"] == "router"
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        httpd.shutdown()
        httpd.server_close()


def test_policy_retry_budget_exhaustion_under_injected_refusal():
    httpd, state, url = _stub_replica()
    try:
        pol = CallPolicy(PolicyConfig(
            max_attempts=10, base_backoff_s=0.0, max_backoff_s=0.0,
            retry_budget=2.0, retry_refill_per_s=0.0,
            breaker_threshold=100))
        rule = faults.inject("http.connect_refused", every=1, match=url)
        with pytest.raises(urllib.error.URLError):
            pol.call(url + "/generate", data=b"{}", timeout=5.0,
                     method="POST")
        # 1 initial try + exactly the 2 budgeted replays, then surface —
        # max_attempts=10 did NOT mean 10 connection attempts.
        assert rule.fires == 3
        assert pol.tokens(url) == 0.0
        assert state["hits"] == 0
        # budget empty: the next call gets its single unbudgeted attempt
        with pytest.raises(urllib.error.URLError):
            pol.call(url + "/generate", data=b"{}", timeout=5.0,
                     method="POST")
        assert rule.fires == 4
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_policy_breaker_opens_and_refuses_locally():
    httpd, state, url = _stub_replica()
    try:
        pol = CallPolicy(PolicyConfig(breaker_threshold=1,
                                      breaker_open_s=60.0, max_attempts=1))
        with faults.active("http.connect_refused", every=1, match=url):
            with pytest.raises(urllib.error.URLError):
                pol.call(url + "/generate", data=b"{}", timeout=5.0,
                         method="POST")
            assert pol.breaker_state(url) == "open"
            # circuit open: refused locally, no socket, no fault fire
            with pytest.raises(BreakerOpenError):
                pol.call(url + "/generate", data=b"{}", timeout=5.0,
                         method="POST")
        assert state["hits"] == 0
        # an HTTP error status is a LIVE destination: breaker stays shut
        pol2 = CallPolicy(PolicyConfig(breaker_threshold=1, max_attempts=1))
        with pytest.raises(urllib.error.HTTPError):
            pol2.call(url + "/nope", data=b"{}", timeout=5.0, method="PUT")
        assert pol2.breaker_state(url) == "closed"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_faults_choke_point_slow_read_and_truncate():
    httpd, state, url = _stub_replica()
    try:
        with faults.active("http.slow_read", delay_s=0.15, match="/metrics"):
            t0 = time.monotonic()
            with faults.urlopen(
                    urllib.request.Request(url + "/metrics"),
                    timeout=5.0) as resp:
                body = resp.read()
            assert time.monotonic() - t0 >= 0.15
            assert json.loads(body)["queue_depth"] == 0  # content intact
        with faults.active("http.truncate_body", truncate_bytes=2,
                           match="/metrics"):
            with faults.urlopen(
                    urllib.request.Request(url + "/metrics"),
                    timeout=5.0) as resp:
                assert len(resp.read(2)) == 2  # budget served
                with pytest.raises(ConnectionResetError):
                    resp.read(1)  # then the connection "tears"
        with faults.active("http.connect_refused", match="/metrics") as r:
            with pytest.raises(urllib.error.URLError):
                faults.urlopen(urllib.request.Request(url + "/metrics"),
                               timeout=5.0)
            assert r.fires == 1
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- admission control (scheduler, no device) ---------------------------------

def test_admission_refuses_unmeetable_deadline_only_after_warmup():
    pool = SlotKVPool(ARGS, num_slots=1, max_len=MAX_LEN)
    # a COLD scheduler admits even an already-lapsed deadline — the
    # classic eviction path handles it (pre-graftchaos behavior).
    cold = Scheduler(max_queue=8)
    cold.submit(Request([1], max_tokens=2, deadline_s=1e-6))
    assert cold.refused == 0

    sched = Scheduler(max_queue=8)
    for _ in range(Scheduler.EWMA_WARMUP):
        r = Request([1], max_tokens=2)
        sched.submit(r)
        sched.admit(pool)
        time.sleep(0.005)
        sched.finish(pool, r, "stop")
    assert sched._ewma_n >= Scheduler.EWMA_WARMUP
    assert sched._ewma_service_s > 0.0
    # occupy the slot and queue one request so the wait estimate is real
    blocker = Request([1], max_tokens=2)
    sched.submit(blocker)
    sched.admit(pool)
    sched.submit(Request([1], max_tokens=2))
    with pytest.raises(AdmissionRefusedError):
        sched.submit(Request([1], max_tokens=2, deadline_s=1e-6))
    assert sched.refused == 1
    assert sched.counters()["refused"] == 1
    # a roomy deadline still admits at the same queue depth
    sched.submit(Request([1], max_tokens=2, deadline_s=60.0))
    assert sched.queue_depth() == 2


# -- engine wait derivation (tiny model) --------------------------------------

def test_generate_wait_derives_from_default_deadline(monkeypatch):
    # Spy on the waiter: the caller-side park must be deadline + grace
    # (the old behavior was a fixed 600s regardless of the deadline).
    waits = []
    orig_wait = Request.wait

    def spy(self, timeout=None):
        waits.append(timeout)
        return orig_wait(self, timeout)

    monkeypatch.setattr(Request, "wait", spy)
    eng = _engine(default_deadline_s=60.0).start()
    try:
        eng.generate("config default", max_tokens=2)
        assert waits[-1] == 60.0 + BatchEngine.WAIT_GRACE_S
        eng.generate("explicit deadline wins", max_tokens=2, deadline_s=5.0)
        assert waits[-1] == 5.0 + BatchEngine.WAIT_GRACE_S
        eng.generate("explicit timeout wins", max_tokens=2, deadline_s=5.0,
                     timeout=42.0)
        assert waits[-1] == 42.0
    finally:
        eng.stop()


# -- router stream retry through the choke point (tiny model) -----------------

@pytest.mark.serial
def test_router_stream_retries_before_first_byte_on_truncation(
        _clean_fault_registry):
    sa, ha, ua = _replica()
    sb, hb, ub = _replica()
    router = Router([ua, ub], poll_interval_s=30.0, retries=2)
    rhttpd = serve_router(router, port=0)
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        # The FIRST /generate dispatch (whichever replica wins the plan)
        # tears before its first body byte; the router must replay on the
        # other candidate and the client must see one clean stream.
        rule = faults.inject("http.truncate_body", nth=1,
                             truncate_bytes=0, match="/generate")
        events = list(request_stream(rurl, "stream survives a torn hop",
                                     max_tokens=5, timeout=120.0))
        assert rule.fires == 1
        assert events[-1].get("done") is True
        deltas = "".join(e.get("text", "") for e in events[:-1])
        assert deltas == events[-1]["text"]
        assert router._mc_retries.value() >= 1
        dead = sum(router._mc_requests.value(replica=rid,
                                             outcome="dead_prestream")
                   for rid in router.replicas)
        assert dead == 1
        # disarmed: the identical stream replays bit-for-bit (greedy)
        again = list(request_stream(rurl, "stream survives a torn hop",
                                    max_tokens=5, timeout=120.0))
        assert again[-1]["text"] == events[-1]["text"]
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        for s, h in ((sa, ha), (sb, hb)):
            s.close()
            h.shutdown()
            h.server_close()


# -- KV corrupt/drop -> quarantine -> local-prefill fallback (tiny model) -----

@pytest.mark.serial
def test_kv_corrupt_quarantined_then_local_prefill_fallback(
        _clean_fault_registry):
    pre_s, pre_h, pre_url = _replica(prefix_cache=True, block_size=16,
                                     role="prefill")
    dec_s, dec_h, dec_url = _replica(prefix_cache=True, block_size=16,
                                     role="decode")
    router = FleetRouter([pre_url], [dec_url], poll_interval_s=30.0,
                         handoff_min_prompt_bytes=32)
    rhttpd = serve_router(router, port=0)
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    prompt = "the chained keys must refuse a payload torn in flight"
    try:
        # Corrupt the one KV push: the decode replica must refuse the
        # payload (verify_keys), quarantine the claimed chain, count the
        # failure — and still serve the request via local prefill.
        rule = faults.inject("kv_transfer.corrupt", nth=1)
        out = request_generate(rurl, prompt, timeout=300.0, max_tokens=8,
                               temperature=0.0, seed=0)
        assert rule.fires == 1
        assert out["tokens"] == 8
        assert dec_s.engine._mc_kv_fail.value(reason="corrupt") >= 1
        assert dec_s.engine.metrics()["completed"] == 1
        faults.reset()
        # Token parity: the same prompt served CLEAN (handoff lands this
        # time) decodes to the same greedy text — the degraded path was
        # slower, never wrong, and the quarantined chain did not poison
        # the cache.
        clean = request_generate(rurl, prompt, timeout=300.0, max_tokens=8,
                                 temperature=0.0, seed=0)
        assert clean["text"] == out["text"]
        # Dropped push: the prefill side reports ok, the decode replica
        # never sees the chain — a plain cache miss, same fallback.
        drop = faults.inject("kv_transfer.drop", nth=1)
        prompt2 = prompt + " and a silently swallowed push is a miss"
        out2 = request_generate(rurl, prompt2, timeout=300.0, max_tokens=8,
                                temperature=0.0, seed=0)
        assert drop.fires == 1 and out2["tokens"] == 8
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        for s, h in ((pre_s, pre_h), (dec_s, dec_h)):
            s.close()
            h.shutdown()
            h.server_close()
