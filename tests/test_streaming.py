"""Streaming data pipeline tests (reference capability: fineweb_stream*.py)."""

import json
import os

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import Config, DataConfig
from mlx_cuda_distributed_pretraining_tpu.data import (
    DataManager,
    DiskSpaceManager,
    StreamingDataManager,
    build_data_manager,
)
from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
    iter_jsonl_shards,
    iter_synthetic,
    sharded,
    shuffled,
)
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager


def _write_shard(path, n_docs, prefix="doc"):
    with open(path, "w") as f:
        for i in range(n_docs):
            f.write(json.dumps({"text": f"{prefix} {i} " + "hello world " * 20}) + "\n")


def _tokenizer(tmp_path, ctx=64):
    dc = DataConfig(preprocessing={"max_context_size": ctx}, tokenizer={"type": "byte"})
    return TokenizerManager(dc)


def _streaming_cfg(tmp_path, shards, ctx=64, **extra):
    return DataConfig(
        preprocessing={"max_context_size": ctx},
        tokenizer={"type": "byte"},
        source="jsonl",
        streaming={"shards": shards, "shuffle_buffer": 8, **extra},
    )


def test_iter_jsonl_shards_norepeat(tmp_path):
    p = str(tmp_path / "s0.jsonl")
    _write_shard(p, 5)
    docs = list(iter_jsonl_shards([p], repeat=False))
    assert len(docs) == 5
    assert docs[0].startswith("doc 0")


def test_sharded_disjoint():
    items = list(range(10))
    a = list(sharded(iter(items), 0, 2))
    b = list(sharded(iter(items), 1, 2))
    assert a == [0, 2, 4, 6, 8] and b == [1, 3, 5, 7, 9]


def test_shuffled_is_permutation():
    items = [str(i) for i in range(100)]
    out = list(shuffled(iter(items), buffer_size=16, seed=0))
    assert sorted(out, key=int) == items and out != items


def test_streaming_batches_static_shape(tmp_path):
    p = str(tmp_path / "s0.jsonl")
    _write_shard(p, 40)
    tok = _tokenizer(tmp_path)
    cfg = _streaming_cfg(tmp_path, [p])
    mgr = StreamingDataManager(cfg, tok, batch_size=4, seq_len=32)
    try:
        for step in range(5):
            b = mgr.generate_batch(step)
            assert b["inputs"].shape == (4, 32)
            assert b["targets"].shape == (4, 32)
            assert b["mask"].shape == (4, 32)
            assert b["inputs"].dtype == np.int32
    finally:
        mgr.stop()


def test_streaming_finite_stream_raises(tmp_path):
    p = str(tmp_path / "s0.jsonl")
    _write_shard(p, 2)
    tok = _tokenizer(tmp_path)
    cfg = _streaming_cfg(tmp_path, [p], repeat=False)
    mgr = StreamingDataManager(cfg, tok, batch_size=4, seq_len=4096)
    with pytest.raises(StopIteration):
        for _ in range(100):
            mgr.generate_batch(0)
    mgr.stop()


def test_streaming_resume_skips_consumed(tmp_path):
    p = str(tmp_path / "s0.jsonl")
    _write_shard(p, 50)
    tok = _tokenizer(tmp_path)
    cfg = _streaming_cfg(tmp_path, [p])
    mgr = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    mgr.generate_batch(0)
    state = mgr.state_dict()
    mgr.stop()
    assert state["docs_consumed"] > 0

    mgr2 = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    mgr2.load_state_dict(state)
    b = mgr2.generate_batch(0)
    assert b["inputs"].shape == (2, 32)
    mgr2.stop()


def test_synthetic_source_deterministic():
    a = [next_doc for _, next_doc in zip(range(5), iter_synthetic(seed=3))]
    b = [next_doc for _, next_doc in zip(range(5), iter_synthetic(seed=3))]
    assert a == b


def test_disk_space_manager_lru_cleanup(tmp_path):
    cache = str(tmp_path / "cache")
    mgr = DiskSpaceManager(cache, max_gb=2e-6)  # ~2 KB cap
    for i in range(6):
        with open(os.path.join(cache, f"f{i}.bin"), "wb") as f:
            f.write(b"x" * 1024)
        os.utime(os.path.join(cache, f"f{i}.bin"), (i + 1, i + 1))
    assert mgr.usage_bytes() == 6 * 1024
    removed = mgr.cleanup()
    assert removed >= 4
    assert mgr.usage_bytes() <= mgr.max_bytes
    # Oldest files went first.
    assert not os.path.exists(os.path.join(cache, "f0.bin"))
    assert os.path.exists(os.path.join(cache, "f5.bin"))


def test_build_data_manager_dispatch(tmp_path):
    train = str(tmp_path / "train.jsonl")
    _write_shard(train, 10)
    # In-memory path
    dc = DataConfig(input_file=train, preprocessing={"max_context_size": 32},
                    tokenizer={"type": "byte"})
    tok = TokenizerManager(dc)
    m1 = build_data_manager(dc, tok, batch_size=2, seq_len=32)
    assert isinstance(m1, DataManager)
    # Streaming path
    dc2 = _streaming_cfg(tmp_path, [train])
    m2 = build_data_manager(dc2, tok, batch_size=2, seq_len=32)
    assert isinstance(m2, StreamingDataManager)
    m2.stop()


def test_trainer_with_streaming_source(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    train = str(tmp_path / "train.jsonl")
    _write_shard(train, 60)
    cfg = Config.from_dict({
        "name": "stream-tiny",
        "overwrite": True,
        "data": {
            "source": "jsonl",
            "streaming": {"shards": [train], "shuffle_buffer": 8},
            "preprocessing": {"max_context_size": 32},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2, "iters": 8},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "steps": {"logging_interval": 4, "checkpoint_interval": 0, "validation_interval": 0},
        },
        "system": {"seed": 0},
    })
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    result = tr.train()
    assert result["steps"] == 8
    assert np.isfinite(result["final_loss"])


def _write_tar_shard(path, n_docs, prefix="tardoc", as_json=False):
    import io
    import tarfile

    with tarfile.open(path, "w") as tf:
        for i in range(n_docs):
            if as_json:
                payload = json.dumps({"text": f"{prefix} {i} " + "json body " * 10}).encode()
                name = f"{i:06d}.json"
            else:
                payload = (f"{prefix} {i} " + "tar body " * 10).encode()
                name = f"{i:06d}.txt"
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))


def test_webdataset_tar_shard_streaming(tmp_path):
    """WebDataset-style .tar shards stream like JSONL shards (reference:
    fineweb_stream.py:18-57)."""
    p_txt = str(tmp_path / "s0.tar")
    p_json = str(tmp_path / "s1.tar")
    _write_tar_shard(p_txt, 30, as_json=False)
    _write_tar_shard(p_json, 30, as_json=True)
    tok = _tokenizer(tmp_path)
    cfg = _streaming_cfg(tmp_path, [p_txt, p_json])
    mgr = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    b = mgr.generate_batch(0)
    assert b["inputs"].shape == (2, 32)
    assert b["inputs"].dtype == np.int32
    mgr.stop()

    from mlx_cuda_distributed_pretraining_tpu.data.streaming import load_shard_docs

    docs = load_shard_docs(p_txt)
    assert len(docs) == 30 and docs[0].startswith("tardoc 0")
    docs = load_shard_docs(p_json)
    assert len(docs) == 30 and "json body" in docs[0]


def test_streaming_exact_resume_batch_equality(tmp_path):
    """Batch N+1 after resume == batch N+1 without resume, exactly, for
    local shard sources (VERDICT r1 item 7)."""
    shards = []
    for s in range(3):
        p = str(tmp_path / f"s{s}.jsonl")
        _write_shard(p, 40, prefix=f"shard{s}")
        shards.append(p)
    tok = _tokenizer(tmp_path)
    cfg = _streaming_cfg(tmp_path, shards)

    # uninterrupted run: collect 6 batches
    ref = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    ref_batches = [ref.generate_batch(i) for i in range(6)]
    ref.stop()

    # interrupted run: 3 batches, checkpoint, resume, 3 more
    a = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    for i in range(3):
        a.generate_batch(i)
    state = a.state_dict()
    a.stop()
    assert "source" in state  # exact path, not skip-replay

    b = StreamingDataManager(cfg, tok, batch_size=2, seq_len=32)
    b.load_state_dict(state)
    resumed = [b.generate_batch(i) for i in range(3)]
    b.stop()

    for got, want in zip(resumed, ref_batches[3:]):
        np.testing.assert_array_equal(got["inputs"], want["inputs"])
        np.testing.assert_array_equal(got["targets"], want["targets"])


def test_seekable_source_deterministic_and_sharded(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import SeekableShuffledSource

    shards = []
    for s in range(2):
        p = str(tmp_path / f"s{s}.jsonl")
        _write_shard(p, 10, prefix=f"sh{s}")
        shards.append(p)

    def take(src, n):
        out = []
        for doc in src:
            out.append(doc)
            if len(out) == n:
                break
        return out

    a = take(SeekableShuffledSource(shards, seed=7), 15)
    b = take(SeekableShuffledSource(shards, seed=7), 15)
    assert a == b  # deterministic
    c = take(SeekableShuffledSource(shards, seed=8), 15)
    assert a != c  # seed-dependent

    # two hosts partition one epoch (2 shards x 10 docs) exactly
    full_epoch = take(SeekableShuffledSource(shards, seed=7), 20)
    h0 = take(SeekableShuffledSource(shards, seed=7, process_index=0, process_count=2), 10)
    h1 = take(SeekableShuffledSource(shards, seed=7, process_index=1, process_count=2), 10)
    assert not (set(h0) & set(h1))
    assert sorted(h0 + h1) == sorted(full_epoch)


class _FakeHubDS:
    """Mock hub IterableDataset implementing the datasets state API."""

    def __init__(self, n=5000):
        self.pos = 0
        self.n = n

    def __iter__(self):
        while self.pos < self.n:
            i = self.pos
            self.pos += 1
            yield {"text": f"hubdoc {i} " + "lorem ipsum " * (5 + i % 13)}

    def state_dict(self):
        return {"pos": self.pos}

    def load_state_dict(self, state):
        self.pos = int(state["pos"])


def _hf_cfg(ds_factory, ctx=64):
    return DataConfig(
        preprocessing={"max_context_size": ctx},
        tokenizer={"type": "byte"},
        source="hf_stream",
        streaming={"ds_factory": ds_factory, "shuffle_buffer": 1},
    )


def test_hf_stream_exact_resume_batch_equality(tmp_path):
    """hf_stream resumes exactly via the datasets-native state API
    (VERDICT r2 item 7): batch N+1 after resume == batch N+1 without
    resume, with no skip-replay of consumed documents."""
    tok = _tokenizer(tmp_path)

    ref = StreamingDataManager(_hf_cfg(_FakeHubDS), tok, batch_size=2, seq_len=32)
    ref_batches = [ref.generate_batch(i) for i in range(6)]
    ref.stop()

    a = StreamingDataManager(_hf_cfg(_FakeHubDS), tok, batch_size=2, seq_len=32)
    for i in range(3):
        a.generate_batch(i)
    state = a.state_dict()
    a.stop()
    assert "hf" in state  # exact path, not skip-replay
    assert state["hf"]["pos"] > 0

    # The resumed source starts a FRESH fake hub stream: if the state were
    # ignored it would replay from document 0 and batches would differ.
    b = StreamingDataManager(_hf_cfg(_FakeHubDS), tok, batch_size=2, seq_len=32)
    b.load_state_dict(state)
    resumed = [b.generate_batch(i) for i in range(3)]
    b.stop()

    for got, want in zip(resumed, ref_batches[3:]):
        np.testing.assert_array_equal(got["inputs"], want["inputs"])
        np.testing.assert_array_equal(got["targets"], want["targets"])


def test_hf_stream_skip_replay_fallback(tmp_path):
    """A source without the state API still resumes via skip-replay."""

    class _Plain:
        def __init__(self, n=5000):
            self.n = n

        def __iter__(self):
            for i in range(self.n):
                yield {"text": f"plaindoc {i} " + "alpha beta " * (5 + i % 7)}

    tok = _tokenizer(tmp_path)
    ref = StreamingDataManager(_hf_cfg(_Plain), tok, batch_size=2, seq_len=32)
    ref_batches = [ref.generate_batch(i) for i in range(6)]
    ref.stop()

    a = StreamingDataManager(_hf_cfg(_Plain), tok, batch_size=2, seq_len=32)
    for i in range(3):
        a.generate_batch(i)
    state = a.state_dict()
    a.stop()
    assert "hf" not in state and state["docs_consumed"] > 0

    b = StreamingDataManager(_hf_cfg(_Plain), tok, batch_size=2, seq_len=32)
    b.load_state_dict(state)
    got = b.generate_batch(0)
    b.stop()
    # Skip-replay drops the partial packer buffer, so alignment is
    # document-level, not bit-exact — but consumed documents must never be
    # replayed: the resumed batch differs from the run's first batches and
    # its text contains only docs at/after the checkpoint's position.
    for early in ref_batches[:3]:
        assert not np.array_equal(got["inputs"], early["inputs"])
    text = tok.detokenize([t for t in got["inputs"][0].tolist() if t >= 0])
    import re

    doc_ids = [int(m) for m in re.findall(r"plaindoc (\d+)", text)]
    assert doc_ids and min(doc_ids) >= state["docs_consumed"] - 1


def test_cross_source_resume_does_not_splice_foreign_buffer(tmp_path):
    """Resuming an hf_stream checkpoint into a local-shard run must not
    restore the foreign packer buffer — the shard run starts clean."""
    tok = _tokenizer(tmp_path)
    a = StreamingDataManager(_hf_cfg(_FakeHubDS), tok, batch_size=2, seq_len=32)
    for i in range(2):
        a.generate_batch(i)
    hf_state = a.state_dict()
    a.stop()
    assert "hf" in hf_state

    p = str(tmp_path / "s0.jsonl")
    _write_shard(p, 40)
    fresh = StreamingDataManager(_streaming_cfg(tmp_path, [p]), tok,
                                 batch_size=2, seq_len=32)
    want = fresh.generate_batch(0)
    fresh.stop()

    resumed = StreamingDataManager(_streaming_cfg(tmp_path, [p]), tok,
                                   batch_size=2, seq_len=32)
    resumed.load_state_dict(hf_state)
    got = resumed.generate_batch(0)
    resumed.stop()
    np.testing.assert_array_equal(got["inputs"], want["inputs"])
