from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.tokenizer import ByteTokenizer, TokenizerManager


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello world")
    assert tok.decode(ids) == "hello world"
    assert tok.vocab_size == 259
    assert tok.pad_id == 256 and tok.bos_id == 257 and tok.eos_id == 258


def test_tokenize_doc_wraps_and_truncates():
    mgr = TokenizerManager(DataConfig(preprocessing={"max_context_size": 8}))
    ids = mgr.tokenize_doc("abcdefghijklmnop")
    assert ids[0] == mgr.bos_id and ids[-1] == mgr.eos_id
    assert len(ids) == 10  # 8 + BOS + EOS


def test_run_dir_roundtrip(tmp_path):
    mgr = TokenizerManager(DataConfig(), run_dir=str(tmp_path))
    mgr2 = TokenizerManager.from_run_dir(str(tmp_path))
    assert mgr2.vocab_size == mgr.vocab_size
    assert mgr2.detokenize(mgr2.tokenize("xyz")) == "xyz"
