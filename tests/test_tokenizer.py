from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.tokenizer import ByteTokenizer, TokenizerManager


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello world")
    assert tok.decode(ids) == "hello world"
    assert tok.vocab_size == 259
    assert tok.pad_id == 256 and tok.bos_id == 257 and tok.eos_id == 258


def test_tokenize_doc_wraps_and_truncates():
    mgr = TokenizerManager(DataConfig(preprocessing={"max_context_size": 8}))
    ids = mgr.tokenize_doc("abcdefghijklmnop")
    assert ids[0] == mgr.bos_id and ids[-1] == mgr.eos_id
    assert len(ids) == 10  # 8 + BOS + EOS


def test_run_dir_roundtrip(tmp_path):
    mgr = TokenizerManager(DataConfig(), run_dir=str(tmp_path))
    mgr2 = TokenizerManager.from_run_dir(str(tmp_path))
    assert mgr2.vocab_size == mgr.vocab_size
    assert mgr2.detokenize(mgr2.tokenize("xyz")) == "xyz"


# --- adversarial edges (VERDICT r3 next #7) --------------------------------

def test_byte_tokenizer_multibyte_unicode_roundtrip():
    """UTF-8 multi-byte sequences (2..4 bytes) survive encode/decode
    exactly — every byte is < 256, so nothing is dropped."""
    tok = ByteTokenizer()
    text = "héllo жизнь 数学 🎉🧪"
    ids = tok.encode(text)
    assert max(ids) < 256 and len(ids) == len(text.encode("utf-8"))
    assert tok.decode(ids) == text


def test_byte_tokenizer_truncated_multibyte_replaces_not_raises():
    """Truncating a doc mid-UTF-8-sequence must decode with replacement
    characters, never raise (tokenize_doc truncates at a byte count that
    can split a codepoint)."""
    mgr = TokenizerManager(DataConfig(preprocessing={"max_context_size": 5}))
    ids = mgr.tokenize_doc("ab🎉cd")  # 🎉 is 4 bytes; cut lands inside it
    assert ids[0] == mgr.bos_id and ids[-1] == mgr.eos_id
    assert len(ids) == 7  # 5 payload bytes + BOS/EOS
    out = mgr.detokenize(ids)  # must not raise
    assert out.startswith("ab")


def test_byte_tokenizer_small_vocab_drops_high_bytes():
    """normal_vocab_size < 256: bytes outside the table are dropped on
    encode, and decode of arbitrary ids never raises."""
    tok = ByteTokenizer(normal_vocab_size=128)
    ids = tok.encode("abc é")  # é is 2 bytes >= 128
    assert ids == [ord(c) for c in "abc "]
    assert tok.decode([0, 127, 128, 1000, -3]) == "\x00\x7f"  # out-of-range skipped
    assert tok.vocab_size == 131


def test_special_token_ids_stable_across_run_dir_roundtrip(tmp_path):
    """Custom specials in a non-default order keep their EXACT ids after
    save_to_run_dir -> from_run_dir (ids are assigned by dict order; a
    reorder would silently remap BOS/EOS in resumed runs)."""
    cfg = DataConfig(tokenizer={
        "normal_vocab_size": 200,
        "special_tokens": {"eos": "<e>", "bos": "<b>", "pad": "<p>"},
    })
    mgr = TokenizerManager(cfg, run_dir=str(tmp_path))
    assert (mgr.eos_id, mgr.bos_id, mgr.pad_id) == (200, 201, 202)
    mgr2 = TokenizerManager.from_run_dir(str(tmp_path))
    assert (mgr2.eos_id, mgr2.bos_id, mgr2.pad_id) == (200, 201, 202)
    assert mgr2.vocab_size == 203


def test_hf_tokenizer_specials_collision_and_unicode(tmp_path):
    """HF tokenizer.json path: literal special-token text in user input
    maps to the special id (added tokens match raw text), and decode()
    strips it — adversarial input cannot smuggle an EOS through a
    detokenize round-trip. Unicode survives byte-level BPE."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<pad>", "<bos>", "<eos>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(["hello world häßlich 🎉"] * 8, trainer)
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    tok.save(str(tok_dir / "tokenizer.json"))

    mgr = TokenizerManager(DataConfig(tokenizer_path=str(tok_dir)))
    assert mgr.external_path is not None
    # unicode round-trip through byte-level BPE
    assert mgr.detokenize(mgr.tokenize("häßlich 🎉")) == "häßlich 🎉"
    # literal "<eos>" in input text becomes the special id ...
    ids = mgr.tokenize("abc<eos>def")
    assert mgr.eos_id in ids
    # ... and detokenize strips it rather than re-emitting the marker
    assert "<eos>" not in mgr.detokenize(ids)


def test_tokenize_doc_empty_and_exact_boundary():
    mgr = TokenizerManager(DataConfig(preprocessing={"max_context_size": 4}))
    assert mgr.tokenize_doc("") == [mgr.bos_id, mgr.eos_id]
    ids = mgr.tokenize_doc("abcd")  # exactly max_context_size bytes
    assert len(ids) == 6 and ids[1:-1] == [ord(c) for c in "abcd"]
