"""Fast-tier elastic tests: rendezvous retry semantics, generation
bookkeeping (membership, barrier, restart markers), exact N->M data
remapping, and reshard-on-load of optimizer state onto an in-process
mesh. The multi-process halves (real jax.distributed fleets, SIGKILL
chaos) live in the slow tier (test_cross_mesh_resume.py,
test_elastic_chaos.py)."""

import json
import threading

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.parallel.elastic import (
    ELASTIC_GENERATION_ENV,
    BarrierTimeoutError,
    RendezvousError,
    fleet_restart_requested,
    generation_barrier,
    latest_generation,
    read_membership,
    record_membership,
    rendezvous,
    request_fleet_restart,
)

import mlx_cuda_distributed_pretraining_tpu.parallel.elastic as elastic_mod

# Captured before the autouse no-op fixture below replaces the attribute,
# so the helper's own tests can still exercise the real implementation.
_REAL_ENABLE_CPU_COLLECTIVES = elastic_mod._enable_cpu_collectives

# -- rendezvous ------------------------------------------------------------


@pytest.fixture(autouse=True)
def _no_gloo_flip(monkeypatch):
    # rendezvous() flips the CPU backend's collectives impl to gloo when
    # joining a real multi-process world; inside this single-process pytest
    # runtime a gloo backend (no distributed client) would fail every later
    # backend creation, so the stub-driven tests must never flip it.
    monkeypatch.setattr(
        elastic_mod, "_enable_cpu_collectives", lambda log: None)


def test_enable_cpu_collectives_flips_default_to_gloo(monkeypatch):
    import jax

    calls = []
    monkeypatch.delenv("JAX_CPU_COLLECTIVES_IMPLEMENTATION", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(jax.config, "_read", lambda name: "none")
    monkeypatch.setattr(jax.config, "update",
                        lambda *a: calls.append(a))
    _REAL_ENABLE_CPU_COLLECTIVES(lambda m: None)
    assert calls == [("jax_cpu_collectives_implementation", "gloo")]


def test_enable_cpu_collectives_respects_user_choice(monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(jax.config, "update", lambda *a: calls.append(a))
    # explicit env var wins
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "mpi")
    _REAL_ENABLE_CPU_COLLECTIVES(lambda m: None)
    assert not calls
    # non-cpu platform: never touched
    monkeypatch.delenv("JAX_CPU_COLLECTIVES_IMPLEMENTATION", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    _REAL_ENABLE_CPU_COLLECTIVES(lambda m: None)
    assert not calls
    # explicit non-default config value: kept
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(jax.config, "_read", lambda name: "mpi")
    _REAL_ENABLE_CPU_COLLECTIVES(lambda m: None)
    assert not calls


def test_rendezvous_explicit_retries_then_raises():
    calls = []
    logs = []

    def stub(**kw):
        calls.append(kw)
        raise RuntimeError("connection refused")

    with pytest.raises(RendezvousError) as ei:
        rendezvous("badhost:1", 2, 0, timeout_s=0.3, attempt_timeout_s=1.0,
                   backoff_base=0.05, backoff_max=0.1,
                   log=logs.append, _initialize=stub)
    assert len(calls) >= 2, "explicit coordinator must be retried"
    assert "badhost:1" in str(ei.value)
    assert "connection refused" in str(ei.value)
    failed = [m for m in logs if "failed" in m]
    assert len(failed) >= 2
    # every attempt was handed a bounded per-attempt timeout
    assert all("initialization_timeout" in kw for kw in calls)


def test_rendezvous_success_after_retry():
    calls = []

    def stub(**kw):
        calls.append(kw)
        if len(calls) == 1:
            raise TimeoutError("coordinator not up yet")

    logs = []
    assert rendezvous("h:9", 2, 1, timeout_s=5.0, backoff_base=0.01,
                      log=logs.append, _initialize=stub) is True
    assert len(calls) == 2
    assert calls[1]["coordinator_address"] == "h:9"
    assert calls[1]["num_processes"] == 2
    assert calls[1]["process_id"] == 1
    assert any("rendezvous ok" in m for m in logs)


def test_rendezvous_auto_failure_is_logged_not_raised(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    logs = []

    def stub(**kw):
        raise RuntimeError("no TPU metadata server")

    assert rendezvous(log=logs.append, _initialize=stub) is False
    assert any("no TPU metadata server" in m for m in logs), logs


def test_rendezvous_stub_without_timeout_kwarg():
    # Older-jax compatibility: a stub rejecting initialization_timeout
    # gets the plain call instead of an eternal TypeError loop.
    calls = []

    def stub(coordinator_address, num_processes, process_id):
        calls.append((coordinator_address, num_processes, process_id))

    assert rendezvous("h:1", 2, 0, _initialize=stub, log=lambda m: None)
    assert calls == [("h:1", 2, 0)]


# -- generations -----------------------------------------------------------


def test_membership_single_process(tmp_path):
    run = str(tmp_path)
    assert latest_generation(run) == 0
    rec = record_membership(run, process_index=0, process_count=1)
    assert rec["generation"] == 1
    assert latest_generation(run) == 1
    on_disk = read_membership(run)
    assert on_disk["generation"] == 1
    assert on_disk["process_count"] == 1
    assert [m["process_index"] for m in on_disk["members"]] == [0]
    # next incarnation bumps
    rec2 = record_membership(run, process_index=0, process_count=1)
    assert rec2["generation"] == 2


def test_membership_generation_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv(ELASTIC_GENERATION_ENV, "7")
    rec = record_membership(str(tmp_path), process_index=0, process_count=1)
    assert rec["generation"] == 7
    assert latest_generation(str(tmp_path)) == 7


def test_latest_generation_sees_restart_markers(tmp_path):
    run = str(tmp_path)
    request_fleet_restart(run, 5, 1, "rc=1")
    assert latest_generation(run) == 5


def test_generation_barrier_two_arrivals(tmp_path):
    run = str(tmp_path)
    done = []
    t = threading.Thread(target=lambda: (
        generation_barrier(run, 3, 0, 2, timeout_s=10.0), done.append(0)))
    t.start()
    generation_barrier(run, 3, 1, 2, timeout_s=10.0)
    t.join(timeout=10.0)
    assert done == [0]


def test_generation_barrier_timeout_names_missing(tmp_path):
    with pytest.raises(BarrierTimeoutError) as ei:
        generation_barrier(str(tmp_path), 4, 0, 2, timeout_s=0.4, poll_s=0.05)
    assert "[1]" in str(ei.value)
    assert "generation 4" in str(ei.value)


def test_restart_marker_first_writer_wins(tmp_path):
    run = str(tmp_path)
    assert fleet_restart_requested(run, 2) is None
    request_fleet_restart(run, 2, 1, "rc=-9")
    request_fleet_restart(run, 2, 0, "hang")  # later request: no-op
    marker = fleet_restart_requested(run, 2)
    assert marker["process_index"] == 1
    assert marker["reason"] == "rc=-9"
    # markers are per-generation
    assert fleet_restart_requested(run, 3) is None


# -- exact N -> M data remapping -------------------------------------------


def _mk_shards(tmp_path, n_docs=30, n_shards=2):
    per = n_docs // n_shards
    paths, k = [], 0
    for s in range(n_shards):
        p = tmp_path / f"shard_{s}.jsonl"
        with open(p, "w") as f:
            for _ in range(per):
                f.write(json.dumps({"text": f"doc-{k}"}) + "\n")
                k += 1
        paths.append(str(p))
    return paths


def _world(shards, count, seed=3, repeat=True):
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
        SeekableShuffledSource,
    )

    return [SeekableShuffledSource(shards, seed=seed, repeat=repeat,
                                   process_index=i, process_count=count)
            for i in range(count)]


def _consume(src, n):
    it = iter(src)
    return [next(it) for _ in range(n)]


@pytest.mark.parametrize("new_count", [1, 3])
def test_remap_world2_exact_complement(tmp_path, new_count):
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
        remap_seekable_states,
    )

    shards = _mk_shards(tmp_path)
    old = _world(shards, 2)
    consumed = _consume(old[0], 6) + _consume(old[1], 9)
    assert len(set(consumed)) == 15, "old world must be disjoint"
    states = [s.state_dict() for s in old]

    remainder = []
    for j in range(new_count):
        src = _world(shards, new_count, repeat=False)[j]
        src.load_state_dict(remap_seekable_states(states, j, new_count))
        part = list(iter(src))
        assert not (set(part) & set(remainder)), "new hosts must be disjoint"
        remainder.extend(part)

    every = {f"doc-{i}" for i in range(30)}
    assert not (set(consumed) & set(remainder)), "replayed documents"
    assert set(consumed) | set(remainder) == every, "skipped documents"
    assert len(consumed) + len(remainder) == 30


def test_remap_chained_2_to_3_to_2(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
        remap_seekable_states,
    )

    shards = _mk_shards(tmp_path)
    old = _world(shards, 2)
    consumed = _consume(old[0], 6) + _consume(old[1], 9)
    states2 = [s.state_dict() for s in old]

    mid = _world(shards, 3)
    for j, s in enumerate(mid):
        s.load_state_dict(remap_seekable_states(states2, j, 3))
    consumed += [d for s in mid for d in _consume(s, 2)]
    states3 = [s.state_dict() for s in mid]

    remainder = []
    for j in range(2):
        src = _world(shards, 2, repeat=False)[j]
        src.load_state_dict(remap_seekable_states(states3, j, 2))
        remainder.extend(iter(src))

    every = {f"doc-{i}" for i in range(30)}
    assert len(consumed) == len(set(consumed)) == 21
    assert not (set(consumed) & set(remainder))
    assert set(consumed) | set(remainder) == every
    assert len(consumed) + len(remainder) == 30


def test_remap_same_world_is_identity(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
        remap_seekable_states,
    )

    shards = _mk_shards(tmp_path)
    old = _world(shards, 2)
    _consume(old[0], 4), _consume(old[1], 5)
    states = [s.state_dict() for s in old]
    assert remap_seekable_states(states, 1, 2) == states[1]


def test_source_load_refuses_world_mismatch(tmp_path):
    shards = _mk_shards(tmp_path)
    state = _world(shards, 2)[0].state_dict()
    with pytest.raises(ValueError, match="remap_seekable_states"):
        _world(shards, 3)[0].load_state_dict(state)
    with pytest.raises(ValueError, match="host mismatch"):
        _world(shards, 2)[1].load_state_dict(state)


def test_remap_data_states_partitions_buffers():
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
        remap_data_states,
    )

    def src_state(i):
        return {"epoch": 0, "shard_ptr": 0, "doc_ptr": 0, "emitted": 4 + i,
                "taken": 4 + i, "process_count": 2, "process_index": i}

    states = [
        {"docs_consumed": 10, "buf": [1, 2], "source": src_state(0),
         "process_count": 2, "process_index": 0},
        {"docs_consumed": 12, "buf": [3], "source": src_state(1),
         "process_count": 2, "process_index": 1},
    ]
    out = remap_data_states(states, 0, 1)
    assert out["buf"] == [1, 2, 3]
    assert out["docs_consumed"] == 22
    assert out["process_count"] == 1 and out["process_index"] == 0
    assert out["source"]["process_count"] == 1
    assert out["source"]["tables"][-1]["world"] == 2
    assert out["source"]["tables"][-1]["positions"] == [4, 5]


def test_remap_data_states_refusals():
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import (
        remap_data_states,
    )

    base = {"docs_consumed": 1, "buf": [], "process_count": 2}
    with pytest.raises(ValueError, match="predates world stamping"):
        remap_data_states([{"docs_consumed": 1}, {"docs_consumed": 2}], 0, 1)
    with pytest.raises(ValueError, match="'hf'"):
        remap_data_states(
            [dict(base, process_index=0, hf={}),
             dict(base, process_index=1, hf={})], 0, 1)
    with pytest.raises(ValueError, match="'source'"):
        remap_data_states(
            [dict(base, process_index=0), dict(base, process_index=1)], 0, 1)
    with pytest.raises(ValueError, match="one complete world"):
        remap_data_states(
            [dict(base, process_index=0, source={}),
             dict(base, process_index=0, source={})], 0, 1)
    with pytest.raises(ValueError, match="disagree"):
        remap_data_states(
            [dict(base, process_index=0, source={})], 0, 1)


# -- reshard-on-load of optimizer state ------------------------------------


def test_load_opt_state_resharded_per_device_slices(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointManager,
        CheckpointIntegrityError,
    )

    mesh = Mesh(np.array(jax.devices()[:2]), ("fsdp",))
    params = {"w": np.arange(32, dtype=np.float32).reshape(8, 4)}
    opt_state = {
        "mu": {"w": np.full((8, 4), 2.0, dtype=np.float32)},
        "nu": {"w": np.full((8, 4), 3.0, dtype=np.float32)},
        "count": 11,
    }
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, params, opt_state=opt_state, training_state={"step": 1})

    sh = NamedSharding(mesh, P("fsdp", None))
    shardings = {"mu": {"w": sh}, "nu": {"w": sh}, "count": None}
    live = {
        "mu": {"w": jax.device_put(np.zeros((8, 4), np.float32), sh)},
        "nu": {"w": jax.device_put(np.zeros((8, 4), np.float32), sh)},
        "count": 0,
    }
    out = mgr.load_opt_state_resharded(1, live, shardings)
    assert out is not None
    np.testing.assert_array_equal(np.asarray(out["mu"]["w"]),
                                  opt_state["mu"]["w"])
    np.testing.assert_array_equal(np.asarray(out["nu"]["w"]),
                                  opt_state["nu"]["w"])
    assert int(out["count"]) == 11
    # landed in the requested sharding: each device holds a (4, 4) slice
    for leaf in (out["mu"]["w"], out["nu"]["w"]):
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim)
        assert sorted(s.data.shape for s in leaf.addressable_shards) \
            == [(4, 4), (4, 4)]

    # dtype/shape drift must refuse, not silently re-materialize
    bad_live = {
        "mu": {"w": jax.device_put(np.zeros((4, 8), np.float32), sh)},
        "nu": live["nu"], "count": 0,
    }
    with pytest.raises(CheckpointIntegrityError, match="re-materialize"):
        mgr.load_opt_state_resharded(1, bad_live, shardings)


def test_load_opt_state_resharded_stacks_layers(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointManager,
    )

    mesh = Mesh(np.array(jax.devices()[:2]), ("fsdp",))
    layer = lambda i: np.full((4, 2), float(i + 1), dtype=np.float32)  # noqa: E731
    opt_state = {"mu": {"layers": {"0": {"w": layer(0)}, "1": {"w": layer(1)}}}}
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, {"w": np.zeros(2, np.float32)}, opt_state=opt_state,
             training_state={"step": 2})

    sh = NamedSharding(mesh, P(None, "fsdp", None))
    stacked = jax.device_put(np.zeros((2, 4, 2), np.float32), sh)
    out = mgr.load_opt_state_resharded(
        2, {"mu": {"layers": {"w": stacked}}},
        {"mu": {"layers": {"w": sh}}}, num_layers=2, interleave=1)
    got = np.asarray(out["mu"]["layers"]["w"])
    np.testing.assert_array_equal(got, np.stack([layer(0), layer(1)]))
    assert out["mu"]["layers"]["w"].sharding.is_equivalent_to(sh, 3)


def test_load_opt_state_resharded_missing_file(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
        CheckpointManager,
        CheckpointIntegrityError,
    )

    mgr = CheckpointManager(str(tmp_path))
    assert mgr.load_opt_state_resharded(9, {"x": 0}, {"x": None}) is None
    with pytest.raises(CheckpointIntegrityError, match="MISSING"):
        mgr.load_opt_state_resharded(9, {"x": 0}, {"x": None}, strict=True)


# -- config plumbing -------------------------------------------------------


def test_system_distributed_config():
    from mlx_cuda_distributed_pretraining_tpu.config import (
        SupervisorConfig,
        SystemConfig,
    )

    legacy = SystemConfig(distributed=False)
    assert legacy.distributed_coordinator is None
    assert legacy.distributed_num_processes is None
    assert legacy.distributed_rendezvous_timeout_s == 120.0

    sc = SystemConfig(distributed={"coordinator_address": "h:12345",
                                   "num_processes": 4,
                                   "rendezvous_timeout_s": 60})
    assert sc.distributed_coordinator == "h:12345"
    assert sc.distributed_num_processes == 4
    assert sc.distributed_rendezvous_timeout_s == 60.0

    assert SupervisorConfig().barrier_timeout_s == 300.0


def test_sample_config_parses_distributed():
    import os

    from mlx_cuda_distributed_pretraining_tpu.config import Config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = Config.from_yaml(
        os.path.join(repo, "configs", "model-config-sample.yaml"))
    assert cfg.system.distributed_coordinator is None
    assert cfg.system.distributed_rendezvous_timeout_s == 120.0
    assert cfg.supervisor.barrier_timeout_s == 300.0


# -- supervisor glue -------------------------------------------------------


def test_supervisor_cmd_builder_per_generation_port():
    import argparse

    from mlx_cuda_distributed_pretraining_tpu.train.supervisor import (
        _trainer_cmd_builder,
        _wants_generation,
    )

    args = argparse.Namespace(
        config="c.yaml", runs_root="runs", set=[], iters=None,
        batch_size=None, learning_rate=None, run_name=None,
        coordinator="localhost:4000", num_processes=2, process_id=1,
        rendezvous_timeout_s=30.0)
    build = _trainer_cmd_builder(args, "/nonexistent-run-dir")
    assert _wants_generation(build)
    assert not _wants_generation(lambda tag: [])

    cmd1 = build(None, 1)
    cmd3 = build("12", 3)
    assert "localhost:4000" in cmd1
    assert "localhost:4002" in cmd3
    assert cmd3[cmd3.index("--num-processes") + 1] == "2"
    assert cmd3[cmd3.index("--process-id") + 1] == "1"
    assert "resume.checkpoint=12" in " ".join(cmd3)
