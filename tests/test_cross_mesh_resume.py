"""Cross-mesh optimizer-state resume (ISSUE 13 satellite).

A checkpoint written on one mesh must come back on a *different* mesh via
per-device slices only — no host gather, no full replica on any device —
and the resumed run's per-step losses must track an uninterrupted baseline.

Both directions run inside one 4-device subprocess (conftest
``spawn_with_devices``):

- scale-DOWN: fsdp4 checkpoint @3 -> pp2 x fsdp2 pipeline trainer to 6
- scale-UP:   fsdp2 checkpoint @3 -> fsdp4 trainer to 6

Every run uses the same total ``iters`` (the cosine schedule is a function
of the step AND the horizon, so a shorter first leg would train with
different learning rates and diverge from any baseline by step 2). The
uninterrupted first legs run straight to step 6 with a mid-run checkpoint
at 3 and double as the parity baselines.
"""

import sys

import pytest

from conftest import spawn_with_devices


@pytest.mark.slow
def test_cross_mesh_resume_scale_down_and_up(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(CROSS_MESH_WORKER)
    proc = spawn_with_devices([sys.executable, str(worker), str(tmp_path)], 4)
    out, _ = proc.communicate(timeout=600)
    assert proc.returncode == 0, out
    assert "CROSS_MESH_OK" in out, out


CROSS_MESH_WORKER = """
import json
import sys

import numpy as np
import yaml

import jax

import mlx_cuda_distributed_pretraining_tpu.checkpoint.manager as mgr_mod
from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

tmp = sys.argv[1]
assert jax.device_count() == 4, jax.devices()

data = tmp + "/train.jsonl"
with open(data, "w") as f:
    for i in range(64):
        f.write(json.dumps({"text": "hello world " * (3 + i % 5)}) + "\\n")

ITERS = 6


def cfg_for(name, mesh, extra_system=None):
    system = {"seed": 0, "device": "cpu", "mesh": dict(mesh)}
    if extra_system:
        system.update(extra_system)
    return {
        "name": name,
        "overwrite": True,
        "data": {
            "input_file": data,
            "validation_file": data,
            "preprocessing": {"max_context_size": 32},
            "tokenizer": {"normal_vocab_size": 256,
                          "special_tokens": {"pad": "<pad>", "bos": "<bos>",
                                             "eos": "<eos>"}},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64,
                           "num_layers": 4},
            "attention": {"num_heads": 2, "num_kv_heads": 2, "head_dim": 16,
                          "max_position_embeddings": 32},
        },
        "training": {
            "hyperparameters": {"batch_size": 8, "learning_rate": 1e-3,
                                "iters": ITERS},
            "scheduler": {"type": "cosine"},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {"steps": {"logging_interval": 1,
                              "checkpoint_interval": 3,
                              "validation_interval": 0}},
        "system": system,
    }


def write_cfg(cfg):
    path = tmp + "/" + cfg["name"] + ".yaml"
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    return path


def step_losses(run_dir, last=True):
    # The resumed run appends to the first leg's events.jsonl, so steps
    # past the checkpoint appear twice: first=baseline leg, last=resumed.
    out = {}
    with open(run_dir + "/events.jsonl") as f:
        for line in f:
            ev = json.loads(line)
            if ev.get("type") == "step_window":
                step = int(ev["step"])
                if last or step not in out:
                    out[step] = float(ev["loss"])
    return out


def no_gather_trainer(cfg_path):
    # _resume() runs inside Trainer.__init__; the spy proves the whole
    # resume path never host-gathers a tree (per-device slices only).
    calls = {"n": 0}
    orig = mgr_mod._to_numpy_tree

    def spy(tree):
        calls["n"] += 1
        return orig(tree)

    mgr_mod._to_numpy_tree = spy
    try:
        t = Trainer(cfg_path, runs_root=tmp + "/runs")
    finally:
        mgr_mod._to_numpy_tree = orig
    assert calls["n"] == 0, f"resume host-gathered {calls['n']} trees"
    return t


def device_live_budget(state, ndev, slack=1.5):
    # Per-device live bytes across params+opt_state stay within a sharded
    # budget: no device holds anything close to a full replica of the state.
    total = 0
    per_dev = {}
    for leaf in jax.tree_util.tree_leaves(state):
        if not isinstance(leaf, jax.Array):
            continue
        total += leaf.nbytes
        for s in leaf.addressable_shards:
            per_dev[s.device] = per_dev.get(s.device, 0) + s.data.nbytes
    assert len(per_dev) == ndev, per_dev
    budget = total / ndev * slack
    for d, nbytes in per_dev.items():
        assert nbytes <= budget, (str(d), nbytes, budget, total)


def assert_parity(got, baseline, steps):
    # Observed bit-identical across fsdp4 / fsdp2 / pp2xfsdp2 on the CPU
    # backend; the tight tolerance only shields against fusion-order
    # jitter, not against wrong data or wrong params (those miss by >0.01).
    for s in steps:
        assert abs(got[s] - baseline[s]) <= 1e-4, (s, got[s], baseline[s])


# ---- scale-DOWN: uninterrupted fsdp4 to 6 (ckpt@3), resume pp2 x fsdp2 ---
down = cfg_for("down", {"fsdp": 4})
down_path = write_cfg(down)
t1 = Trainer(down_path, runs_root=tmp + "/runs")
t1.train()
run_down = t1.run_dir
base_losses = step_losses(run_down)  # uninterrupted fsdp4 baseline
assert sorted(base_losses) == [1, 2, 3, 4, 5, 6], base_losses
del t1

down["overwrite"] = False
down["resume"] = {"checkpoint": "3"}
down["system"] = {"seed": 0, "device": "cpu", "mesh": {"pp": 2, "fsdp": 2},
                  "pipeline_microbatches": 2}
with open(down_path, "w") as f:
    yaml.safe_dump(down, f)
t2 = no_gather_trainer(down_path)
assert t2.pipeline
assert t2.start_step == 3, t2.start_step

# Stacked layer leaves are pp-sharded (fsdp may shard inner dims further):
# each device holds at most leaf/pp bytes, never a full stacked replica.
pp = 2
layers = flatten_dict(t2.state["params"]["layers"])
assert layers
for k, v in layers.items():
    for s in v.addressable_shards:
        assert s.data.nbytes <= v.nbytes // pp, (k, s.data.nbytes, v.nbytes)
device_live_budget({"params": t2.state["params"],
                    "opt_state": t2.state["opt_state"]}, 4)

t2.train()
assert int(t2.state["step"]) == 6
down_losses = step_losses(run_down, last=True)
# the resumed leg really recomputed 4-6 (steps logged twice in events)
assert step_losses(run_dir=run_down, last=False) == base_losses
assert_parity(down_losses, base_losses, (4, 5, 6))
del t2

# ---- scale-UP: uninterrupted fsdp2 to 6 (ckpt@3), resume fsdp4 -----------
up = cfg_for("up", {"fsdp": 2})
up_path = write_cfg(up)
t3 = Trainer(up_path, runs_root=tmp + "/runs")
assert t3.mesh is not None and dict(t3.mesh.shape) == {"fsdp": 2}
t3.train()
run_up = t3.run_dir
up_base = step_losses(run_up)
# mesh-shape independence: the fsdp2 run tracks the fsdp4 baseline too
assert_parity(up_base, base_losses, (1, 2, 3, 4, 5, 6))
del t3

up["overwrite"] = False
up["resume"] = {"checkpoint": "3"}
up["system"] = {"seed": 0, "device": "cpu", "mesh": {"fsdp": 4}}
with open(up_path, "w") as f:
    yaml.safe_dump(up, f)
t4 = no_gather_trainer(up_path)
assert dict(t4.mesh.shape) == {"fsdp": 4}
assert t4.start_step == 3, t4.start_step
device_live_budget({"params": t4.state["params"],
                    "opt_state": t4.state["opt_state"]}, 4)

t4.train()
assert int(t4.state["step"]) == 6
up_losses = step_losses(run_up, last=True)
assert_parity(up_losses, base_losses, (4, 5, 6))

print("CROSS_MESH_OK", json.dumps(
    {"base": {str(k): v for k, v in sorted(base_losses.items())},
     "down": {str(k): v for k, v in sorted(down_losses.items())},
     "up": {str(k): v for k, v in sorted(up_losses.items())}}))
"""
