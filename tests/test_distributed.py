"""Multi-device tests on the virtual 8-CPU mesh (SURVEY.md §4 item d):
DP gradient psum correctness, TP/FSDP sharding, ZeRO-1 state sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import SystemConfig, TrainingConfig
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
from mlx_cuda_distributed_pretraining_tpu.parallel import build_mesh
from mlx_cuda_distributed_pretraining_tpu.parallel.mesh import mesh_axis_sizes
from mlx_cuda_distributed_pretraining_tpu.train.train_step import (
    init_train_state,
    make_train_step,
)

ARGS = LlamaArgs(
    vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=64,
)


def _setup(mesh_cfg, zero=0, seed=0):
    sys_cfg = SystemConfig(seed=seed, device="cpu", mesh=mesh_cfg,
                           zero_optimization_level=zero)
    mesh = build_mesh(sys_cfg)
    tr_cfg = TrainingConfig(
        hyperparameters={"learning_rate": 1e-2, "gradient_clip": 1.0},
        scheduler={"type": "constant"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr_cfg, 100)
    params = llama.init_params(jax.random.PRNGKey(seed), ARGS)

    def loss_fn(params, batch):
        return llama.loss_fn(params, batch, ARGS)

    step, shardings = make_train_step(loss_fn, opt, mesh=mesh, zero_level=zero, params_like=params)
    state = jax.device_put(init_train_state(params, opt), shardings)
    return mesh, step, state, shardings


def _batch(bs=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, 60, size=(bs, seq + 1)).astype(np.int32)
    return {
        "inputs": jnp.asarray(x[:, :-1]),
        "targets": jnp.asarray(x[:, 1:]),
        "mask": jnp.ones((bs, seq), jnp.float32),
    }


def test_mesh_axis_sizes():
    sizes = mesh_axis_sizes(SystemConfig(seed=0, device="cpu", mesh={"dp": -1, "tp": 2}), 8)
    assert sizes["dp"] == 4 and sizes["tp"] == 2
    # explicit sub-device mesh is allowed (prefix of devices)
    assert mesh_axis_sizes(SystemConfig(seed=0, device="cpu", mesh={"dp": 3}), 8)["dp"] == 3
    with pytest.raises(ValueError):
        mesh_axis_sizes(SystemConfig(seed=0, device="cpu", mesh={"dp": 16}), 8)
    with pytest.raises(ValueError):  # -1 with non-divisible fixed axis
        mesh_axis_sizes(SystemConfig(seed=0, device="cpu", mesh={"dp": -1, "tp": 3}), 8)


@pytest.mark.slow
def test_dp_matches_single_device():
    """8-way DP step == single-device step on the same global batch."""
    batch = _batch()
    mesh, step, state, _ = _setup({"dp": 8})
    new_state, metrics = step(state, batch)

    # single-device
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tr_cfg = TrainingConfig(
        hyperparameters={"learning_rate": 1e-2, "gradient_clip": 1.0},
        scheduler={"type": "constant"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr_cfg, 100)
    sstep, _ = make_train_step(lambda p, b: llama.loss_fn(p, b, ARGS), opt)
    sstate = init_train_state(params, opt)
    ref_state, ref_metrics = sstep(sstate, batch)

    np.testing.assert_allclose(float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5)
    a = np.asarray(new_state["params"]["layers"][0]["attention"]["wq"]["weight"])
    b = np.asarray(ref_state["params"]["layers"][0]["attention"]["wq"]["weight"])
    np.testing.assert_allclose(a, b, atol=1e-5)


@pytest.mark.parametrize("mesh_cfg", [{"dp": 2, "tp": 4}, {"dp": 2, "fsdp": 2, "tp": 2}])
@pytest.mark.slow
def test_tp_fsdp_matches_single_device(mesh_cfg):
    batch = _batch()
    mesh, step, state, shardings = _setup(mesh_cfg)
    new_state, metrics = step(state, batch)

    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    tr_cfg = TrainingConfig(
        hyperparameters={"learning_rate": 1e-2, "gradient_clip": 1.0},
        scheduler={"type": "constant"},
        optimization={"optimizer": "adamw"},
    )
    opt = build_optimizer(tr_cfg, 100)
    sstep, _ = make_train_step(lambda p, b: llama.loss_fn(p, b, ARGS), opt)
    ref_state, ref_metrics = sstep(init_train_state(params, opt), batch)
    np.testing.assert_allclose(float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-4)

    # TP actually shards: wq weight [32, 32] over tp on dim 1
    wq_shard = new_state["params"]["layers"][0]["attention"]["wq"]["weight"].sharding
    tp = mesh.shape["tp"]
    assert wq_shard.shard_shape((32, 32))[1] == 32 // tp


def test_zero1_shards_optimizer_state():
    mesh, step, state, shardings = _setup({"dp": 8}, zero=1)
    new_state, _ = step(state, _batch())
    # adam mu for the embedding [64, 32]: param replicated (dp only mesh),
    # but optimizer state sharded over dp on dim 0
    mu = None
    # chain state: [clip:{}, adam:{mu,nu}, wd:{}, schedule:{count}] -> find mu
    for s in new_state["opt_state"]:
        if isinstance(s, dict) and "mu" in s:
            mu = s["mu"]["tok_embeddings"]["weight"]
    assert mu is not None
    assert mu.sharding.shard_shape((64, 32))[0] == 64 // 8
    # params stay replicated
    emb = new_state["params"]["tok_embeddings"]["weight"]
    assert emb.sharding.shard_shape((64, 32)) == (64, 32)


def test_sharding_no_shape_collision():
    """wq [D, H*Dh] and wo [H*Dh, D] have the same shape when H*Dh == D;
    their optimizer state must still get the matching (not transposed)
    spec — regression for suffix-vs-shape matching."""
    args = LlamaArgs(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=1,
        num_heads=4, num_kv_heads=4, head_dim=8, max_position_embeddings=64,
    )  # H*Dh = 32 = D
    sys_cfg = SystemConfig(seed=0, device="cpu", mesh={"fsdp": 2, "tp": 4})
    mesh = build_mesh(sys_cfg)
    tr_cfg = TrainingConfig(hyperparameters={"learning_rate": 1e-2},
                            optimization={"optimizer": "adamw"})
    opt = build_optimizer(tr_cfg, 10)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    _, shardings = make_train_step(
        lambda p, b: llama.loss_fn(p, b, args), opt, mesh=mesh, params_like=params)

    def find_mu(tree):
        for s in tree:
            if isinstance(s, dict) and "mu" in s:
                return s["mu"]

    mu = find_mu(shardings["opt_state"])
    wq_param = shardings["params"]["layers"][0]["attention"]["wq"]["weight"]
    wo_param = shardings["params"]["layers"][0]["attention"]["wo"]["weight"]
    wq_mu = mu["layers"][0]["attention"]["wq"]["weight"]
    wo_mu = mu["layers"][0]["attention"]["wo"]["weight"]
    assert wq_mu.spec == wq_param.spec
    assert wo_mu.spec == wo_param.spec
    assert wq_param.spec != wo_param.spec  # transposed rules really differ


@pytest.mark.slow
def test_sp_fused_ce_matches_dense():
    """Sequence-sharded fused CE (ops/fused_ce.py::fused_cross_entropy_sp,
    auto-routed by llama.loss_fn on sp meshes with tp == 1): loss AND
    grads match the single-device unfused reference on a dp x sp mesh —
    the shard_map path just distributes the row chunks."""
    import dataclasses

    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.config import SystemConfig
    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.parallel import build_mesh
    from mlx_cuda_distributed_pretraining_tpu.parallel.context import set_mesh

    mesh = build_mesh(SystemConfig(seed=0, device="cpu",
                                   mesh={"dp": 2, "sp": 4}))
    args = llama.LlamaArgs(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, num_kv_heads=2, head_dim=16,
        max_position_embeddings=256, attention_type="ring")
    params = llama.init_params(jax.random.PRNGKey(0), args)
    rng = np.random.default_rng(0)
    x = rng.integers(1, 120, size=(4, 257)).astype(np.int32)
    b = {"inputs": jnp.asarray(x[:, :-1]), "targets": jnp.asarray(x[:, 1:]),
         "mask": jnp.ones((4, 256), jnp.float32)}

    set_mesh(None)
    dargs = dataclasses.replace(args, attention_type="simple")
    dense, dg = jax.value_and_grad(
        lambda p: llama.loss_fn(p, b, dargs, ce_chunk=0)[0])(params)

    set_mesh(mesh)
    try:
        loss, g = jax.jit(jax.value_and_grad(
            lambda p: llama.loss_fn(p, b, args, ce_chunk=64)[0]))(params)
        assert abs(float(loss) - float(dense)) < 1e-4
        mx = max(float(jnp.max(jnp.abs(a - b2))) for a, b2 in
                 zip(jax.tree_util.tree_leaves(dg),
                     jax.tree_util.tree_leaves(g)))
        assert mx < 1e-6, mx
    finally:
        set_mesh(None)


@pytest.mark.slow
def test_multi_step_sharded_matches_single_dispatch():
    """K scanned steps in ONE dispatch (make_multi_step) on a dp+tp mesh
    == K individual dispatched steps with the same batches (the trainer's
    system.steps_per_dispatch path; amortizes host->device latency)."""
    from mlx_cuda_distributed_pretraining_tpu.train.train_step import make_multi_step
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer as _bo

    mesh_cfg = {"dp": 4, "tp": 2}
    mesh, step, state, shardings = _setup(mesh_cfg)
    sys_cfg = SystemConfig(seed=0, device="cpu", mesh=mesh_cfg)
    tr_cfg = TrainingConfig(
        hyperparameters={"learning_rate": 1e-2, "gradient_clip": 1.0},
        scheduler={"type": "constant"},
        optimization={"optimizer": "adamw"},
    )
    opt = _bo(tr_cfg, 100)
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)

    def loss_fn(p, b):
        return llama.loss_fn(p, b, ARGS)

    multi, _ = make_multi_step(loss_fn, opt, mesh=mesh, params_like=params)

    batches = [_batch(seed=s) for s in range(3)]

    # reference: 3 individual dispatches
    ref_state = state
    ref_losses = []
    for b in batches:
        ref_state, m = step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    # one dispatch of the scanned triple
    state2 = jax.device_put(
        init_train_state(llama.init_params(jax.random.PRNGKey(0), ARGS), opt),
        shardings)
    stacked = {k: jnp.stack([b[k] for b in batches]) for k in batches[0]}
    state2, mm = multi(state2, stacked)

    np.testing.assert_allclose(
        np.asarray(mm["loss"]), np.asarray(ref_losses), atol=1e-5)
    pa = ref_state["params"]["layers"][0]["attention"]["wq"]["weight"]
    pb = state2["params"]["layers"][0]["attention"]["wq"]["weight"]
    np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), atol=1e-5)
