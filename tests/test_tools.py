"""Tools tests: tokenizer training, HF export, run inspection, data prep
(reference capabilities: tools/train-tokenizer.py, tools/convert-to-mlx-lm.py,
tools/visualize_model.py, tools/model_cli.py, prepare_data_a100.py,
examine.py, find_data.py)."""

import json
import os

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import Config
from mlx_cuda_distributed_pretraining_tpu.tools import (
    convert_to_hf,
    inspect_data,
    prepare_data,
    train_tokenizer,
    visualize_model,
)


def _write_jsonl(path, texts):
    with open(path, "w") as f:
        for t in texts:
            f.write(json.dumps({"text": t}) + "\n")


def _train_tiny_run(tmp, name, iters=10, model_extra=None, val_interval=5):
    """Build + train the shared tiny run used by the tools fixtures."""
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    train = tmp / "train.jsonl"
    _write_jsonl(train, ["the quick brown fox jumps over the lazy dog " * 3] * 30)
    model = {
        "architecture": "llama",
        "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 2},
        "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        "misc": {"tie_word_embeddings": False},
    }
    model.update(model_extra or {})
    cfg = Config.from_dict({
        "name": name,
        "overwrite": True,
        "data": {
            "input_file": str(train),
            "validation_file": str(train),
            "preprocessing": {"max_context_size": 48},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": model,
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2, "iters": iters},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "steps": {"logging_interval": 5, "checkpoint_interval": 0,
                      "validation_interval": val_interval},
        },
        "system": {"seed": 0},
    })
    tr = Trainer(cfg, runs_root=str(tmp / "runs"), quiet=True)
    tr.train()
    return tr.run_dir


@pytest.fixture(scope="module")
def trained_run(tmp_path_factory):
    """One tiny trained run shared by export/inspect/CLI tests."""
    return _train_tiny_run(tmp_path_factory.mktemp("toolrun"), "tooltest")


def test_train_tokenizer(tmp_path):
    corpus = tmp_path / "c.jsonl"
    _write_jsonl(corpus, ["hello world, this is a corpus of words"] * 50)
    out = train_tokenizer.train_tokenizer([str(corpus)], str(tmp_path / "tok"), vocab_size=300)
    assert os.path.isfile(out)
    from tokenizers import Tokenizer

    tok = Tokenizer.from_file(out)
    ids = tok.encode("hello world", add_special_tokens=False).ids
    assert len(ids) > 0
    assert tok.token_to_id("<pad>") is not None
    assert tok.decode(ids).replace(" ", "") == "helloworld".replace(" ", "")


def test_tokenizer_roundtrip_into_manager(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
    from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

    corpus = tmp_path / "c.jsonl"
    _write_jsonl(corpus, ["some words to learn merges from"] * 40)
    out_dir = tmp_path / "tok"
    train_tokenizer.train_tokenizer([str(corpus)], str(out_dir), vocab_size=280)
    mgr = TokenizerManager(DataConfig(tokenizer_path=str(out_dir)))
    ids = mgr.tokenize("some words")
    assert mgr.detokenize(ids).strip() == "some words"
    assert mgr.pad_id != mgr.eos_id


def test_convert_to_hf(trained_run, tmp_path):
    out = convert_to_hf.convert_run(trained_run, str(tmp_path / "export"))
    assert os.path.isfile(os.path.join(out, "model.safetensors"))
    with open(os.path.join(out, "config.json")) as f:
        cfg = json.load(f)
    assert cfg["architectures"] == ["LlamaForCausalLM"]
    assert cfg["hidden_size"] == 32
    assert cfg["num_key_value_heads"] == 2

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import load_safetensors

    tensors, meta = load_safetensors(os.path.join(out, "model.safetensors"))
    assert "model.embed_tokens.weight" in tensors
    assert "lm_head.weight" in tensors  # untied in this run
    # HF layout is [out, in]: q_proj out dim = num_heads*head_dim = 32
    q = tensors["model.layers.0.self_attn.q_proj.weight"]
    assert q.shape == (32, 32)
    emb = tensors["model.embed_tokens.weight"]
    assert emb.shape[0] == cfg["vocab_size"]


def test_hf_export_logits_match(trained_run, tmp_path):
    """The exported HF state dict must describe the same function: check a
    manual forward with HF-layout weights equals our model's logits."""
    import jax.numpy as jnp

    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    params, args, tok, _ = load_trained(trained_run)
    sd = convert_to_hf.hf_state_dict(params, args.tie_word_embeddings)

    x = np.array([[1, 5, 9, 7]], dtype=np.int32)
    ours, _ = llama.forward(params, jnp.asarray(x), args)

    # Rebuild our param tree from the HF dict (transpose back) and re-run.
    rebuilt = {
        "tok_embeddings": {"weight": jnp.asarray(sd["model.embed_tokens.weight"])},
        "norm": {"weight": jnp.asarray(sd["model.norm.weight"])},
        "layers": [],
    }
    for i in range(args.num_layers):
        pre = f"model.layers.{i}"
        rebuilt["layers"].append({
            "attention_norm": {"weight": jnp.asarray(sd[f"{pre}.input_layernorm.weight"])},
            "ffn_norm": {"weight": jnp.asarray(sd[f"{pre}.post_attention_layernorm.weight"])},
            "attention": {
                "wq": {"weight": jnp.asarray(sd[f"{pre}.self_attn.q_proj.weight"].T)},
                "wk": {"weight": jnp.asarray(sd[f"{pre}.self_attn.k_proj.weight"].T)},
                "wv": {"weight": jnp.asarray(sd[f"{pre}.self_attn.v_proj.weight"].T)},
                "wo": {"weight": jnp.asarray(sd[f"{pre}.self_attn.o_proj.weight"].T)},
            },
            "feed_forward": {
                "w_gate": {"weight": jnp.asarray(sd[f"{pre}.mlp.gate_proj.weight"].T)},
                "w_up": {"weight": jnp.asarray(sd[f"{pre}.mlp.up_proj.weight"].T)},
                "w_down": {"weight": jnp.asarray(sd[f"{pre}.mlp.down_proj.weight"].T)},
            },
        })
    if "lm_head.weight" in sd:
        rebuilt["output"] = {"weight": jnp.asarray(sd["lm_head.weight"].T)}
    theirs, _ = llama.forward(rebuilt, jnp.asarray(x), args)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs), rtol=1e-5, atol=1e-5)


def test_visualize_model(trained_run, capsys):
    s = visualize_model.run_summary(trained_run)
    assert s["architecture"] == "llama"
    assert s["last_step"] == 10
    assert s["final_val_loss"] is not None
    visualize_model.print_summary(s)
    out = capsys.readouterr().out
    assert "tooltest" in out

    runs_root = os.path.dirname(trained_run)
    assert "tooltest" in visualize_model.list_runs(runs_root)


def test_model_cli(trained_run, capsys):
    from mlx_cuda_distributed_pretraining_tpu.tools.model_cli import ModelCLI

    cli = ModelCLI(runs_root=os.path.dirname(trained_run))
    cli.cmd_list()
    assert "tooltest" in capsys.readouterr().out
    cli.dispatch("load tooltest")
    cli.max_tokens = 8
    text = cli.cmd_generate("the quick")
    assert isinstance(text, str)
    assert cli.dispatch("quit") is False


def test_prepare_data(tmp_path):
    src = tmp_path / "src.jsonl"
    _write_jsonl(src, [f"document number {i}" for i in range(200)])
    train_p, val_p = prepare_data.prepare_split(
        str(src), str(tmp_path / "out"), val_fraction=0.1, seed=0)
    n_train = sum(1 for _ in open(train_p))
    n_val = sum(1 for _ in open(val_p))
    assert n_train + n_val == 200
    assert 5 <= n_val <= 40  # ~10%
    good, bad = prepare_data.validate_jsonl(train_p)
    assert good == n_train and bad == 0


def test_validate_jsonl_catches_bad(tmp_path):
    p = tmp_path / "bad.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"text": "ok"}) + "\n")
        f.write("not json\n")
        f.write(json.dumps({"notext": 1}) + "\n")
    good, bad = prepare_data.validate_jsonl(str(p))
    assert good == 1 and bad == 2


def test_inspect_data(tmp_path):
    p = tmp_path / "c.jsonl"
    _write_jsonl(p, ["abc", "defgh"])
    stats = inspect_data.examine_file(str(p), count_tokens=True)
    assert stats["docs"] == 2
    assert stats["chars"] == 8
    assert stats["byte_tokens"] == 8 + 4  # bytes + BOS/EOS per doc
    files = inspect_data.find_data_files(str(tmp_path), min_bytes=1)
    assert any(f["path"].endswith("c.jsonl") for f in files)


@pytest.mark.slow
def test_compare_optimizers(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.tools import compare_optimizers

    train = tmp_path / "train.jsonl"
    _write_jsonl(train, ["the quick brown fox jumps over the lazy dog " * 3] * 30)
    base = {
        "name": "cmp",
        "data": {
            "input_file": str(train),
            "preprocessing": {"max_context_size": 32},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 1},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2, "iters": 6},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {"steps": {"logging_interval": 2, "checkpoint_interval": 0,
                              "validation_interval": 0}},
        "system": {"seed": 0},
    }
    results = compare_optimizers.compare(
        base, ["adamw", "muon"], str(tmp_path / "runs"), iters=6)
    assert set(results) == {"adamw", "muon"}
    for r in results.values():
        assert np.isfinite(r["final_loss"])
        assert len(r["steps"]) == 3
    csv_path = compare_optimizers.write_outputs(results, str(tmp_path / "out"))
    header = open(csv_path).readline().strip().split(",")
    assert header == ["step", "adamw", "muon"]


@pytest.mark.slow
def test_hf_export_loads_in_transformers_with_matching_logits(trained_run, tmp_path):
    """The strongest parity check: the exported directory loads with real
    ``transformers.LlamaForCausalLM`` (torch CPU) and produces the same
    logits as our JAX forward (reference flow: README.md:101-125 feeds the
    exported model to the mlx-lm/lm-eval ecosystem)."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    out = str(tmp_path / "hf_export")
    convert_to_hf.convert_run(trained_run, out)

    model = transformers.LlamaForCausalLM.from_pretrained(out)
    model.eval()

    params, args, tok, _ = load_trained(trained_run)
    x = np.array([[1, 5, 9, 7, 3, 11]], dtype=np.int32)
    ours, _ = llama.forward(params, jnp.asarray(x), args)
    with torch.no_grad():
        theirs = model(torch.from_numpy(x.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4, atol=2e-4)


def test_import_from_hf_roundtrip(trained_run, tmp_path):
    """export → import returns the identical pytree (tools/import_from_hf
    is the inverse of convert_to_hf; reference parity: models/llama.py
    :414-477 tolerant HF weight loading)."""
    import jax

    from mlx_cuda_distributed_pretraining_tpu.tools import import_from_hf
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    out = str(tmp_path / "hf_export")
    convert_to_hf.convert_run(trained_run, out)
    params2, args2 = import_from_hf.import_hf_dir(out)

    params, args, _, _ = load_trained(trained_run)
    assert args2.num_layers == args.num_layers
    assert args2.num_kv_heads == args.num_kv_heads
    a = {k: v for k, v in
         jax.tree_util.tree_flatten_with_path(params)[0]}
    b = {k: v for k, v in
         jax.tree_util.tree_flatten_with_path(params2)[0]}
    assert set(map(str, a)) == set(map(str, b))
    for k in a:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]), rtol=1e-6,
                                   err_msg=str(k))


def test_import_from_hf_cli(trained_run, tmp_path, capsys):
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import CheckpointManager
    from mlx_cuda_distributed_pretraining_tpu.tools import import_from_hf
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    out = str(tmp_path / "hf_export")
    convert_to_hf.convert_run(trained_run, out)
    ckpt_dir = str(tmp_path / "imported")
    import_from_hf.main(["--hf-dir", out, "--out", ckpt_dir])
    assert "imported" in capsys.readouterr().out
    params, _, _, _ = load_trained(trained_run)
    loaded = CheckpointManager.load_params(
        os.path.join(ckpt_dir, "step_final_model.safetensors"), like=params)
    for a, b in zip(*(map(lambda t: __import__("jax").tree_util.tree_leaves(t),
                          (params, loaded)))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.fixture(scope="module")
def moe_run(tmp_path_factory):
    """Tiny trained MoE run for Mixtral-format export tests.
    capacity_factor = num experts => capacity == all tokens: no drops, so
    routing matches Mixtral's (no-capacity) semantics."""
    return _train_tiny_run(
        tmp_path_factory.mktemp("moerun"), "moetool", iters=6, val_interval=0,
        model_extra={"moe": {"num_local_experts": 4, "num_experts_per_tok": 2,
                             "capacity_factor": 4.0, "aux_loss_weight": 0.01}},
    )


def test_moe_export_mixtral_layout(moe_run, tmp_path):
    out = convert_to_hf.convert_run(moe_run, str(tmp_path / "mx"))
    with open(os.path.join(out, "config.json")) as f:
        cfg = json.load(f)
    assert cfg["architectures"] == ["MixtralForCausalLM"]
    assert cfg["num_local_experts"] == 4 and cfg["num_experts_per_tok"] == 2
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import load_safetensors

    tensors, _ = load_safetensors(os.path.join(out, "model.safetensors"))
    assert "model.layers.0.block_sparse_moe.gate.weight" in tensors
    assert "model.layers.0.block_sparse_moe.experts.3.w2.weight" in tensors


def test_moe_export_loads_in_transformers_mixtral_with_matching_logits(moe_run, tmp_path):
    """Our MoE block must BE Mixtral's function when capacity drops nothing:
    softmax→top-k→renormalize equals Mixtral's softmax-over-selected."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    out = convert_to_hf.convert_run(moe_run, str(tmp_path / "mx"))
    model = transformers.MixtralForCausalLM.from_pretrained(out)
    model.eval()

    params, args, tok, _ = load_trained(moe_run)
    x = np.array([[1, 5, 9, 7, 3, 11]], dtype=np.int32)
    ours, _ = llama.forward(params, jnp.asarray(x), args)
    with torch.no_grad():
        theirs = model(torch.from_numpy(x.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4, atol=2e-4)


def test_moe_import_from_hf_roundtrip(moe_run, tmp_path):
    import jax

    from mlx_cuda_distributed_pretraining_tpu.tools import import_from_hf
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import load_trained

    out = convert_to_hf.convert_run(moe_run, str(tmp_path / "mx"))
    params2, args2 = import_from_hf.import_hf_dir(out)
    params, args, _, _ = load_trained(moe_run)
    assert args2.num_local_experts == 4 and args2.num_experts_per_tok == 2
    a = {str(k): v for k, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    b = {str(k): v for k, v in jax.tree_util.tree_flatten_with_path(params2)[0]}
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]), rtol=1e-6,
                                   err_msg=k)


def test_prepare_dataset_one_command(tmp_path):
    """Dataset onboarding in one command (reference:
    prepare_tinystories_data.py flow): 'story'-keyed JSONL -> train/val
    JSONL + trained tokenizer + runnable config."""
    import json

    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.tools.prepare_dataset import (
        prepare_dataset,
    )

    src = tmp_path / "stories.jsonl"
    with open(src, "w") as f:
        for i in range(120):
            f.write(json.dumps({"story": f"Once upon a time number {i}. "
                                          "The cat sat on the mat. " * 4}) + "\n")

    out = str(tmp_path / "prepared")
    manifest = prepare_dataset(str(src), out, vocab_size=300,
                               val_fraction=0.1, seed=0, context_size=128)
    assert manifest["text_key"] == "story"

    n_train = sum(1 for _ in open(manifest["train"]))
    n_val = sum(1 for _ in open(manifest["val"]))
    assert n_train + n_val == 120 and n_val > 0
    # every produced line is {"text": ...} regardless of the source key
    first = json.loads(open(manifest["train"]).readline())
    assert "text" in first and "Once upon a time" in first["text"]

    assert os.path.isfile(os.path.join(manifest["tokenizer"], "tokenizer.json"))

    cfg = Config.from_yaml(manifest["config"])
    assert cfg.data.input_file == manifest["train"]
    assert cfg.data.tokenizer_path == manifest["tokenizer"]
    from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

    tok = TokenizerManager(cfg.data)
    ids = tok.tokenize_doc("Once upon a time")
    assert len(ids) > 0


def test_prepare_dataset_token_shards(tmp_path):
    """--token-shards onboarding: splits are tokenized into binary shards
    (reference: download_and_process_llm_data.py:1-85 ends in processed
    tokens) and the emitted config trains from them directly, with the
    validation tail landing on held-out docs."""
    import json

    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.data.streaming import build_data_manager
    from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager
    from mlx_cuda_distributed_pretraining_tpu.tools.prepare_dataset import (
        prepare_dataset,
    )

    src = tmp_path / "docs.jsonl"
    with open(src, "w") as f:
        for i in range(200):
            f.write(json.dumps({"text": f"Document number {i}. "
                                        "A quick brown fox jumps. " * 6}) + "\n")

    out = str(tmp_path / "prepared")
    manifest = prepare_dataset(str(src), out, vocab_size=300, val_fraction=0.1,
                               seed=0, context_size=64, token_shards=True)
    shards = manifest["shards"]
    assert shards and os.path.isfile(os.path.join(shards["shard_dir"], "index.json"))
    with open(os.path.join(shards["shard_dir"], "index.json")) as f:
        index = json.load(f)
    assert index["total_tokens"] == shards["total_tokens"] > 0
    # val tail exists and matches the split fraction direction
    assert 0.0 < shards["val_fraction"] < 0.5

    cfg = Config.from_yaml(manifest["config"])
    assert cfg.data.source == "token_shards"
    tok = TokenizerManager(cfg.data)
    dm = build_data_manager(cfg, tok, batch_size=4, seq_len=64)
    b = dm.generate_batch(0)
    assert b["inputs"].shape == (4, 64)
    assert dm.has_validation_data
    vb = next(iter(dm.iter_validation()))
    assert vb["inputs"].shape[1] == 64
    # shard tokens decode back to the corpus vocabulary, not noise
    flat = np.asarray(b["inputs"]).ravel()[:50].tolist()
    text = tok.detokenize([t for t in flat if t > 0])
    assert "fox" in text or "Document" in text


@pytest.mark.slow
def test_evaluate_ppl_and_mc(tmp_path):
    """Offline eval tool (reference README.md:110-125 shows an external
    lm-eval ARC-Easy run): ppl over a text file is finite and near-uniform
    for a random model; MC scoring parses index/letter/HF-ARC answer keys
    and returns sane accuracies; MC argmax agrees with a direct
    full-forward logprob computation."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
    from mlx_cuda_distributed_pretraining_tpu.tokenizer import ByteTokenizer
    from mlx_cuda_distributed_pretraining_tpu.tools.evaluate import (
        _mc_records,
        _norm_answer,
        evaluate_mc,
        evaluate_ppl,
    )

    args = LlamaArgs(vocab_size=300, hidden_size=32, intermediate_size=64,
                     num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
                     max_position_embeddings=256)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    tok = ByteTokenizer()

    # answer-key normalization
    assert _norm_answer(2, 4) == 2
    assert _norm_answer("C", 4) == 2
    assert _norm_answer("1", 4) == 1

    # record parsing: plain, letter-keyed, HF-ARC dict
    data = tmp_path / "mc.jsonl"
    with open(data, "w") as f:
        f.write(json.dumps({"question": "2 plus 2 is", "choices": ["four", "five"],
                            "answer": 0}) + "\n")
        f.write(json.dumps({"question": "the sky is", "choices": ["blue", "red"],
                            "answer": "A"}) + "\n")
        f.write(json.dumps({"question": "water is", "answerKey": "B",
                            "choices": {"text": ["dry", "wet"], "label": ["A", "B"]}}) + "\n")
    recs = list(_mc_records(str(data)))
    assert len(recs) == 3 and recs[2][2] == 1

    r = evaluate_mc(params, args, tok, str(data))
    assert r["n"] == 3 and 0.0 <= r["acc"] <= 1.0 and 0.0 <= r["acc_norm"] <= 1.0

    # MC argmax agrees with direct per-choice scoring for the first record
    q, choices, _ = recs[0]
    ctx = tok.encode(q)
    direct = []
    for ch in choices:
        ch_ids = tok.encode(" " + ch.strip())
        ids = np.asarray([ctx + ch_ids], np.int32)
        logits, _ = llama.forward(params, jnp.asarray(ids[:, :-1]), args)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        gold = np.take_along_axis(np.asarray(lp), ids[:, 1:][..., None], axis=-1)[0, :, 0]
        direct.append(float(gold[-len(ch_ids):].sum()))
    # recompute the tool's unnormalized scores via a 1-record file
    one = tmp_path / "one.jsonl"
    with open(one, "w") as f:
        f.write(json.dumps({"question": q, "choices": choices,
                            "answer": int(np.argmax(direct))}) + "\n")
    r1 = evaluate_mc(params, args, tok, str(one))
    assert r1["acc"] == 1.0  # tool's argmax matches the direct computation

    # perplexity: finite, positive, near-uniform for an untrained model
    txt = tmp_path / "text.jsonl"
    with open(txt, "w") as f:
        for i in range(40):
            f.write(json.dumps({"text": "the quick brown fox jumps. " * 40}) + "\n")
    rp = evaluate_ppl(params, args, tok, str(txt), seq_len=64, batch_size=2)
    assert rp["tokens"] > 0 and 1.0 < rp["ppl"] < 10 * args.vocab_size


def test_make_cloze_eval(tmp_path):
    """Offline cloze-eval generator: records are evaluate.py-compatible,
    deterministic under seed, and the gold is recoverable from choices."""
    import json

    from mlx_cuda_distributed_pretraining_tpu.tools.evaluate import _mc_records
    from mlx_cuda_distributed_pretraining_tpu.tools.make_cloze_eval import build_cloze

    src = tmp_path / "corpus.jsonl"
    base = ("apple banana cherry dragonfruit elderberry fig grape honeydew "
            "kiwi lemon mango nectarine orange papaya quince raspberry").split()
    words = [f"{w}{sfx}" for w in base for sfx in ("", "tree", "seed", "leaf")]
    with open(src, "w") as f:
        for i in range(600):
            sent = " ".join(words[(i * 7 + j) % len(words)] for j in range(10))
            f.write(json.dumps({"text": sent.capitalize() + "."}) + "\n")

    recs = build_cloze(str(src), n=50, n_choices=4, seed=3)
    assert len(recs) == 50
    for r in recs:
        assert set(r) == {"question", "choices", "answer"}
        assert len(r["choices"]) == 4
        assert 0 <= r["answer"] < 4
        assert len(r["question"].split()) >= 6
    # deterministic
    assert build_cloze(str(src), n=50, n_choices=4, seed=3) == recs
    assert build_cloze(str(src), n=50, n_choices=4, seed=4) != recs

    # evaluate.py parses them
    out = tmp_path / "cloze.jsonl"
    with open(out, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    parsed = list(_mc_records(str(out)))
    assert len(parsed) == 50


def test_merge_optcmp_outputs(tmp_path):
    """scripts/merge_optcmp_outputs.py stitches per-optimizer --out-dir
    runs back into the combined artifact layout (summary JSON merged,
    curves re-aligned on the step axis, lr_finder dirs copied)."""
    import csv
    import importlib.util
    import json
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "merge_optcmp", os.path.join(repo, "scripts", "merge_optcmp_outputs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    def write_dir(name, steps, losses, lr):
        d = tmp_path / name
        d.mkdir()
        with open(d / "optimizer_comparison.json", "w") as f:
            json.dump({name: {"final_loss": losses[-1], "final_val_loss": None,
                              "learning_rate": lr, "wall_s": 1.0,
                              "mean_tok_s": 10.0}}, f)
        with open(d / "optimizer_comparison.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["step", name])
            w.writerows(zip(steps, losses))
        (d / f"lr_finder_{name}").mkdir()
        (d / f"lr_finder_{name}" / "lr_finder.csv").write_text("lr,loss\n")
        return str(d)

    a = write_dir("alpha", [10, 20, 30], [3.0, 2.5, 2.0], 1e-3)
    b = write_dir("beta", [10, 30], [3.1, 2.1], 2e-3)  # sparser steps
    out = str(tmp_path / "merged")
    mod.main(out, [a, b])

    with open(os.path.join(out, "optimizer_comparison.json")) as f:
        summary = json.load(f)
    assert set(summary) == {"alpha", "beta"}
    assert summary["beta"]["learning_rate"] == 2e-3
    with open(os.path.join(out, "optimizer_comparison.csv")) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["step", "alpha", "beta"]
    by_step = {int(r[0]): r[1:] for r in rows[1:]}
    assert by_step[20] == ["2.5", ""] or by_step[20] == ["2.5", "None"] or \
        by_step[20][1] in ("", "None")  # beta has no step 20
    assert os.path.isdir(os.path.join(out, "lr_finder_alpha"))
    assert os.path.isdir(os.path.join(out, "lr_finder_beta"))
