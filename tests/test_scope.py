"""graftscope — fleet-wide SLO control plane (obs/scope.py, obs/tsdb.py,
obs/alerts.py).

Pure-unit tests pin the TSDB encoding (delta-of-delta varints, torn-tail
truncation, retention compaction, counter-reset-aware increase, bucket
quantiles), the rule grammar validator, and every rule kind's evaluator
against a hand-built store. The collector tests run real MetricsServer
targets and drive failures through the graftchaos choke point
(scrape.timeout) to prove a sick target never wedges a round. The chaos
drill replays a scripted error-ratio outage on a logical clock and
asserts the whole alert lifecycle — pending inside the burn windows,
firing after the for_s hold-down, a debug bundle naming every member,
resolved after the fault window — is **bit-identical** across two runs.
"""

import json
import math
import os
import socket
import urllib.request

import pytest

from mlx_cuda_distributed_pretraining_tpu.obs import tsdb as tsdb_mod
from mlx_cuda_distributed_pretraining_tpu.obs.alerts import (
    AlertState,
    RuleEngine,
    RuleError,
    validate_rules,
)
from mlx_cuda_distributed_pretraining_tpu.obs.events import iter_events
from mlx_cuda_distributed_pretraining_tpu.obs.metrics import MetricsRegistry
from mlx_cuda_distributed_pretraining_tpu.obs.prometheus import MetricsServer
from mlx_cuda_distributed_pretraining_tpu.obs.scope import (
    Collector,
    ScopeConfig,
    parse_json_metrics,
    parse_prom_text,
)
from mlx_cuda_distributed_pretraining_tpu.obs.tsdb import (
    TSDB,
    decode_records,
    encode_record,
    parse_series_key,
    series_key,
    sparkline,
)
from mlx_cuda_distributed_pretraining_tpu.serve import faults


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- tsdb: encoding ---------------------------------------------------------

def test_tsdb_record_round_trip_including_float_escape():
    samples = [(1000, 2.5), (2000, 2.5), (3500, -1.0),
               (3600, 0.1234567), (10_000, 1e12)]
    buf = bytearray()
    prev_t, prev_delta, prev_v = 0, 0, 0.0
    for t_ms, v in samples:
        rec = encode_record(t_ms, prev_t, prev_delta, v, prev_v)
        buf.extend(rec)
        prev_delta = t_ms - prev_t
        prev_t, prev_v = t_ms, v
    out = decode_records(bytes(buf))
    assert [(t, round(v, 9)) for t, v in out] == \
        [(t, round(v, 9)) for t, v in samples]


def test_tsdb_append_query_and_persistence(tmp_path):
    d = str(tmp_path / "tsdb")
    db = TSDB(d)
    for i in range(10):
        db.append("loss", {"instance": "t0"}, 100.0 + i, 3.0 - i * 0.1)
    pts = db.query("loss", {"instance": "t0"})
    assert len(pts) == 10
    assert pts[0] == (100.0, 3.0)
    assert abs(pts[-1][1] - 2.1) < 1e-9
    # windowed query
    win = db.query("loss", {"instance": "t0"}, 103.0, 105.0)
    assert [t for t, _ in win] == [103.0, 104.0, 105.0]
    # a second TSDB over the same dir sees the same data (reload path)
    db2 = TSDB(d)
    assert db2.query("loss", {"instance": "t0"}) == pts
    # non-monotonic appends are dropped, the series stays sane
    db2.append("loss", {"instance": "t0"}, 50.0, 9.9)
    assert db2.query("loss", {"instance": "t0"}) == pts


def test_tsdb_torn_tail_truncated_then_appendable(tmp_path):
    d = str(tmp_path / "tsdb")
    db = TSDB(d)
    for i in range(5):
        db.append("x", None, 10.0 + i, float(i))
    path = db._series[series_key("x", None)].path
    with open(path, "ab") as fh:
        fh.write(b"\x83\x41")  # half a record: crash mid-append
    db2 = TSDB(d)
    assert [v for _, v in db2.query("x")] == [0.0, 1.0, 2.0, 3.0, 4.0]
    # the torn bytes were truncated away, so new appends stay decodable
    db2.append("x", None, 20.0, 9.0)
    db3 = TSDB(d)
    assert db3.query("x")[-1] == (20.0, 9.0)


def test_tsdb_retention_compaction(tmp_path):
    db = TSDB(str(tmp_path / "tsdb"), max_points=16)
    for i in range(100):
        db.append("c", None, float(i), float(i))
    pts = db.query("c")
    assert len(pts) <= 32  # compaction triggers at 2x max_points
    assert pts[-1] == (99.0, 99.0)
    # the retained window survives a reload with the newest points intact
    db2 = TSDB(str(tmp_path / "tsdb"), max_points=16)
    assert db2.query("c")[-1] == (99.0, 99.0)
    assert len(db2.query("c")) == len(pts)


def test_tsdb_increase_rate_and_counter_reset():
    db = TSDB()
    for t, v in [(0, 0.0), (10, 5.0), (20, 12.0), (30, 3.0), (40, 8.0)]:
        db.append("req_total", {"i": "a"}, float(t), v)
    # 0->5->12 is +12; the reset to 3 contributes its new value; 3->8 is +5
    assert db.increase("req_total", {"i": "a"}, 0.0, 40.0) == 12.0 + 3.0 + 5.0
    assert db.rate("req_total", {"i": "a"}, 0.0, 40.0) == 20.0 / 40.0
    db.append("req_total", {"i": "b"}, 0.0, 0.0)
    db.append("req_total", {"i": "b"}, 40.0, 10.0)
    assert db.sum_increase("req_total", {}, 0.0, 40.0) == 30.0


def test_tsdb_quantile_from_bucket_series():
    db = TSDB()
    # 100 observations in [0, t]: 50 under 10ms, 90 under 100ms, all under +Inf
    for t, b10, b100, inf in [(0, 0, 0, 0), (60, 50, 90, 100)]:
        db.append("lat_ms_bucket", {"le": "10"}, float(t), float(b10))
        db.append("lat_ms_bucket", {"le": "100"}, float(t), float(b100))
        db.append("lat_ms_bucket", {"le": "+Inf"}, float(t), float(inf))
    p50 = db.quantile("lat_ms", {}, 0.5, 0.0, 60.0)
    p99 = db.quantile("lat_ms", {}, 0.99, 0.0, 60.0)
    assert p50 is not None and p50 <= 10.0
    assert p99 is not None and p99 >= 100.0


def test_series_key_round_trip_and_sparkline():
    key = series_key("m", {"b": "2", "a": "1"})
    assert key == 'm{a=1,b=2}'
    assert parse_series_key(key) == ("m", {"a": "1", "b": "2"})
    s = sparkline([0, 1, 2, 3], width=4)
    assert len(s) == 4 and s[0] == "▁" and s[-1] == "█"
    assert sparkline([], width=4) == ""


# -- alerts: validation -----------------------------------------------------

def _rule(**kw):
    base = {"name": "r", "kind": "threshold", "metric": "train_loss",
            "value": 1.0}
    base.update(kw)
    return {"alerts": {"rules": [base]}}


def test_validate_rules_catches_typos():
    assert validate_rules(_rule()) == []
    assert any("unknown kind" in e
               for e in validate_rules(_rule(kind="treshold")))
    assert any("unknown metric" in e
               for e in validate_rules(_rule(metric="serve_ttft_msec")))
    # custom_metric: true is the escape hatch for out-of-tree exporters
    assert validate_rules(_rule(metric="my_metric",
                                custom_metric=True)) == []
    assert any("unknown action" in e
               for e in validate_rules(_rule(actions=["pager"])))
    assert any("for_s" in e for e in validate_rules(_rule(for_s=-5)))
    assert any("op must be" in e for e in validate_rules(_rule(op="eq")))


def test_validate_rules_burn_window_ordering_and_objective():
    doc = {"alerts": {"rules": [{
        "name": "b", "kind": "error_burn_rate",
        "metric": "serve_router_requests_total",
        "bad_label": "outcome", "bad_values": ["error"],
        "objective": 0.99, "fast_window_s": 300, "slow_window_s": 60}]}}
    assert any("must be < slow_window_s" in e for e in validate_rules(doc))
    doc["alerts"]["rules"][0].update(fast_window_s=60, slow_window_s=300,
                                     objective=1.5)
    assert any("objective" in e for e in validate_rules(doc))


def test_validate_rules_duplicates_and_engine_refuses_invalid():
    doc = {"alerts": {"rules": [
        {"name": "same", "kind": "threshold", "metric": "train_loss",
         "value": 1.0},
        {"name": "same", "kind": "threshold", "metric": "train_loss",
         "value": 2.0}]}}
    assert any("duplicate" in e for e in validate_rules(doc))
    with pytest.raises(RuleError):
        RuleEngine([{"name": "x", "kind": "nope"}], TSDB())


def test_validate_alerts_yaml_cli_and_shipped_config(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.obs.alerts import main
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shipped = os.path.join(repo, "configs", "alerts.yaml")
    assert main(["--validate", shipped]) == 0
    bad = tmp_path / "bad.yaml"
    bad.write_text("alerts:\n  rules:\n    - name: x\n      kind: nope\n")
    assert main(["--validate", str(bad)]) == 1


# -- alerts: state machine --------------------------------------------------

def test_alert_state_for_s_hold_down():
    st = AlertState({"name": "r", "kind": "threshold", "for_s": 20})
    assert [t["to"] for t in st.step(True, 5.0, 100.0)] == ["pending"]
    assert st.step(True, 5.0, 110.0) == []          # still inside for_s
    trs = st.step(True, 6.0, 120.0)
    assert [t["to"] for t in trs] == ["firing"] and st.fire_count == 1
    assert st.step(True, 6.0, 130.0) == []          # stays firing quietly
    trs = st.step(False, 0.0, 140.0)
    assert [(t["from"], t["to"]) for t in trs] == [("firing", "resolved")]
    # a blip that clears inside the hold-down never fires
    st.step(True, 5.0, 150.0)
    trs = st.step(False, 0.0, 160.0)
    assert [(t["from"], t["to"]) for t in trs] == [("pending", "inactive")]
    assert st.fire_count == 1


def test_alert_state_immediate_fire_without_for_s():
    st = AlertState({"name": "r", "kind": "threshold"})
    assert [t["to"] for t in st.step(True, 1.0, 10.0)] == ["firing"]


# -- alerts: rule kinds against a hand-built store --------------------------

def _engine(rules, db):
    return RuleEngine(rules, db)


def test_threshold_rule_worst_series_wins():
    db = TSDB()
    db.append("train_grad_norm", {"instance": "p0"}, 100.0, 2.0)
    db.append("train_grad_norm", {"instance": "p1"}, 100.0, 150.0)
    eng = _engine([{"name": "gn", "kind": "threshold",
                    "metric": "train_grad_norm", "op": "gt",
                    "value": 100.0}], db)
    trs = eng.evaluate(100.0)
    assert [t["to"] for t in trs] == ["firing"]
    assert trs[0]["value"] == 150.0


def test_zscore_rule_fires_on_loss_spike():
    db = TSDB()
    for i in range(20):
        db.append("train_loss", {"instance": "p0"}, float(i), 2.0)
    db.append("train_loss", {"instance": "p0"}, 20.0, 2.0001)
    eng = _engine([{"name": "spike", "kind": "zscore",
                    "metric": "train_loss", "z": 4.0, "window_s": 600}], db)
    assert eng.evaluate(20.0) == []  # tiny wiggle: no alert
    db.append("train_loss", {"instance": "p0"}, 21.0, 9.0)
    trs = eng.evaluate(21.0)
    assert [t["to"] for t in trs] == ["firing"]


def test_nonfinite_rule_gauge_and_sentinel_counter():
    db = TSDB()
    db.append("train_loss", None, 10.0, float("nan"))
    eng = _engine([{"name": "nan", "kind": "nonfinite",
                    "metric": "train_loss"}], db)
    trs = eng.evaluate(10.0)
    assert [t["to"] for t in trs] == ["firing"]
    assert math.isnan(trs[0]["value"])
    db2 = TSDB()
    db2.append("train_nonfinite_total", None, 0.0, 0.0)
    db2.append("train_nonfinite_total", None, 50.0, 2.0)
    eng2 = _engine([{"name": "nf", "kind": "nonfinite",
                     "metric": "train_nonfinite_total"}], db2)
    assert [t["to"] for t in eng2.evaluate(50.0)] == ["firing"]


def test_flap_rule_counts_breaker_transitions():
    db = TSDB()
    vals = [0, 2, 0, 2, 0, 2]  # closed<->open, 5 flips
    for i, v in enumerate(vals):
        db.append("serve_breaker_state", {"dest": "r0"}, float(i * 10), v)
    eng = _engine([{"name": "flap", "kind": "flap",
                    "metric": "serve_breaker_state", "window_s": 300,
                    "threshold": 4}], db)
    assert [t["to"] for t in eng.evaluate(50.0)] == ["firing"]


def test_goodput_floor_rule():
    db = TSDB()
    for t, disp, other in [(0, 0.0, 0.0), (300, 50.0, 70.0)]:
        db.append("goodput_seconds_total", {"component": "dispatch"},
                  float(t), disp)
        db.append("goodput_seconds_total", {"component": "data_wait_s"},
                  float(t), other)
    eng = _engine([{"name": "gp", "kind": "goodput_floor",
                    "metric": "goodput_seconds_total", "floor": 0.6,
                    "good_components": ["dispatch"], "window_s": 300}], db)
    trs = eng.evaluate(300.0)
    assert [t["to"] for t in trs] == ["firing"]
    assert abs(trs[0]["value"] - 50.0 / 120.0) < 1e-6


def test_baseline_drop_rule_reads_committed_baseline(tmp_path):
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 2, "backends": {"cpu": {"cases": {
            "100m_flash": {"mfu": 0.30}}}}}))
    rule = {"name": "mfu", "kind": "baseline_drop", "metric": "train_mfu",
            "baseline_file": str(baseline), "backend": "cpu",
            "case": "100m_flash", "baseline_key": "mfu",
            "max_drop_frac": 0.5, "window_s": 300, "min_points": 3}
    db = TSDB()
    for i, v in enumerate([0.25, 0.26, 0.24]):
        db.append("train_mfu", None, float(i * 10), v)
    assert _engine([dict(rule)], db).evaluate(30.0) == []  # above the floor
    db2 = TSDB()
    for i, v in enumerate([0.10, 0.12, 0.11]):
        db2.append("train_mfu", None, float(i * 10), v)
    trs = _engine([dict(rule)], db2).evaluate(30.0)
    assert [t["to"] for t in trs] == ["firing"]


def test_latency_burn_rule_over_threshold_share():
    db = TSDB()
    # 10 requests in the window, only 2 under the 100ms objective bucket
    # (the mid-window sample keeps the fast window's increase non-empty)
    for t, b100, inf, count in [(0, 0, 0, 0), (40, 1, 5, 5),
                                (60, 2, 10, 10)]:
        db.append("serve_ttft_ms_bucket", {"le": "100"}, float(t),
                  float(b100))
        db.append("serve_ttft_ms_bucket", {"le": "+Inf"}, float(t),
                  float(inf))
        db.append("serve_ttft_ms_count", {}, float(t), float(count))
    eng = _engine([{"name": "lat", "kind": "latency_burn_rate",
                    "metric": "serve_ttft_ms", "threshold_ms": 100,
                    "objective": 0.5, "fast_window_s": 30,
                    "slow_window_s": 60}], db)
    trs = eng.evaluate(60.0)
    assert [t["to"] for t in trs] == ["firing"]
    assert trs[0]["value"] == pytest.approx((0.8) / 0.5)


def test_rule_evaluator_bug_reads_as_no_data():
    db = TSDB()
    db.append("train_loss", None, 0.0, 1.0)
    # value: None would crash the threshold evaluator's float() — the
    # engine must swallow it (no-data), not take down the collector.
    eng = RuleEngine([{"name": "ok", "kind": "threshold",
                       "metric": "train_loss", "value": 10.0}], db)
    eng.states[0].rule["value"] = None
    assert eng.evaluate(0.0) == []
    assert eng.states[0].state == "inactive"


# -- scrape parsing ---------------------------------------------------------

def test_parse_prom_text_and_json_metrics():
    text = ("# HELP x y\n# TYPE x counter\n"
            'x{a="1",b="two"} 3\n'
            "plain 1.5\n"
            "bad_value nan_is_fine nope\n")
    samples = parse_prom_text(text)
    assert ("x", {"a": "1", "b": "two"}, 3.0) in samples
    assert ("plain", {}, 1.5) in samples
    assert len(samples) == 2
    js = parse_json_metrics({"queue_depth": 3, "tok/s": 12.5,
                             "engine": "batch", "live": True})
    assert ("queue_depth", {}, 3.0) in js
    assert ("tok_s", {}, 12.5) in js  # key normalized, strings/bools skipped
    assert len(js) == 2


# -- collector: scraping through the policy ---------------------------------

def test_collector_scrapes_target_with_instance_label(tmp_path):
    reg = MetricsRegistry()
    reg.counter("serve_requests_total", "").inc(7, outcome="ok")
    srv = MetricsServer(reg, port=0)
    try:
        cfg = ScopeConfig(targets=[
            {"name": "router0", "url": f"http://127.0.0.1:{srv.port}",
             "role": "router"}],
            run_dir=str(tmp_path / "run"), rules=[])
        c = Collector(cfg, now_fn=lambda: 1000.0)
        res = c.collect_once(now=1000.0)
        assert res["targets"] == 1 and res["up"] == 1
        pts = c.db.query("serve_requests_total",
                         {"outcome": "ok", "instance": "router0"})
        assert pts == [(1000.0, 7.0)]
        assert c.registry.gauge("graftscope_scrape_up").value(
            instance="router0") == 1.0
    finally:
        srv.shutdown()


def test_collector_discovers_fleet_members_and_skips_stale(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.serve.fleet import (
        register_replica)
    fleet_dir = str(tmp_path / "fleet")
    p0 = register_replica(fleet_dir, "http://127.0.0.1:1/", role="decode",
                          index=0)
    register_replica(fleet_dir, "http://127.0.0.1:2", role="prefill",
                     index=1)
    cfg = ScopeConfig(targets=["http://127.0.0.1:3"], fleet_dir=fleet_dir,
                      rules=[])
    c = Collector(cfg)
    names = [t["name"] for t in c.targets()]
    assert names == sorted(names)
    assert "decode0" in names and "prefill1" in names
    # a member whose heartbeat went stale drops out of the scrape set
    rec = json.load(open(p0))
    rec["t"] -= 3600.0
    json.dump(rec, open(p0, "w"))
    names = [t["name"] for t in c.targets()]
    assert "decode0" not in names and "prefill1" in names


def test_sick_target_never_wedges_the_round(tmp_path):
    """One live target + one armed with scrape.timeout + one dead port:
    the round completes, the live target's samples land, the sick ones
    read up=0, and repeated connect-refusals open the breaker (the next
    rounds fail fast locally instead of dialing a corpse)."""
    reg = MetricsRegistry()
    reg.gauge("serve_queue_depth", "").set(4)
    live = MetricsServer(reg, port=0)
    sick = MetricsServer(MetricsRegistry(), port=0)
    dead_url = f"http://127.0.0.1:{_free_port()}"
    try:
        faults.inject("scrape.timeout", every=1, match=f":{sick.port}/")
        cfg = ScopeConfig(targets=[
            {"name": "live0", "url": f"http://127.0.0.1:{live.port}"},
            {"name": "sick0", "url": f"http://127.0.0.1:{sick.port}"},
            {"name": "dead0", "url": dead_url}],
            rules=[], scrape_timeout_s=2.0)
        c = Collector(cfg)
        for i in range(6):
            res = c.collect_once(now=1000.0 + i)
            assert res["up"] == 1  # the round always completes
        up = c.registry.gauge("graftscope_scrape_up")
        assert up.value(instance="live0") == 1.0
        assert up.value(instance="sick0") == 0.0
        assert up.value(instance="dead0") == 0.0
        assert c.db.query("serve_queue_depth", {"instance": "live0"})
        # 6 consecutive connect-refusals exceed breaker_threshold=5
        assert c.policy.breaker_state(dead_url) == "open"
        errs = c.registry.counter("graftscope_scrape_errors_total")
        assert errs.value(instance="sick0") == 6.0
        assert errs.value(instance="dead0") == 6.0
    finally:
        live.shutdown()
        sick.shutdown()


# -- the deterministic chaos drill ------------------------------------------

BURN_RULE = {
    "name": "router-error-burn", "kind": "error_burn_rate",
    "metric": "serve_router_requests_total",
    "bad_label": "outcome", "bad_values": ["error"],
    "objective": 0.9, "fast_window_s": 30, "slow_window_s": 60,
    "for_s": 15, "actions": ["bundle"],
}


def _drill(run_dir, serve_port_holder=None):
    """One scripted outage on a logical clock: 3 clean rounds, 4 rounds
    of errors, 5 clean recovery rounds, 10 s apart. A second target is
    kept permanently sick through the graftchaos scrape.timeout point.
    Returns (timeline, alerts_doc, bundle_listing)."""
    faults.reset()
    reg = MetricsRegistry()
    req = reg.counter("serve_router_requests_total", "")
    router = MetricsServer(reg, port=0)
    ghost = MetricsServer(MetricsRegistry(), port=0)
    clock = {"t": 1000.0}
    try:
        faults.inject("scrape.timeout", every=1, match=f":{ghost.port}/")
        cfg = ScopeConfig(targets=[
            {"name": "router0", "url": f"http://127.0.0.1:{router.port}",
             "role": "router"},
            {"name": "ghost0", "url": f"http://127.0.0.1:{ghost.port}",
             "role": "decode"}],
            run_dir=str(run_dir), rules=[dict(BURN_RULE)],
            port=0 if serve_port_holder is not None else None)
        c = Collector(cfg, now_fn=lambda: clock["t"])
        if serve_port_holder is not None:
            serve_port_holder.append(c)
        script = ["ok"] * 3 + ["error"] * 4 + ["ok"] * 5
        for outcome in script:
            req.inc(10, outcome=outcome)
            c.collect_once(now=clock["t"])
            clock["t"] += 10.0
        timeline = c.alerts()["timeline"]
        alerts_doc = c.alerts()["alerts"]
        bdir = os.path.join(str(run_dir), "bundles")
        listing = []
        for root, dirs, files in os.walk(bdir):
            rel = os.path.relpath(root, bdir)
            for f in sorted(files):
                listing.append(os.path.join(rel, f))
            dirs.sort()
        if serve_port_holder is None:
            c.stop()
        return timeline, alerts_doc, sorted(listing)
    finally:
        router.shutdown()
        ghost.shutdown()


def test_chaos_drill_alert_lifecycle_and_bundle(tmp_path):
    holder = []
    timeline, alerts_doc, listing = _drill(tmp_path / "run", holder)
    c = holder[0]
    try:
        # pending inside the burn windows, firing after for_s, resolved
        # after the fault window drains out of both windows
        trans = [(t["from"], t["to"], t["t"]) for t in timeline]
        assert [x[:2] for x in trans] == [
            ("inactive", "pending"), ("pending", "firing"),
            ("firing", "resolved")]
        t_pending, t_firing, t_resolved = (x[2] for x in trans)
        assert t_firing - t_pending >= BURN_RULE["for_s"]
        assert t_resolved > t_firing
        # the bundle captured at fire time names every member; the live
        # router contributed its snapshots, the sick ghost a bare dir
        bdir = os.path.join(str(tmp_path / "run"), "bundles",
                            "router-error-burn_%d" % int(t_firing))
        meta = json.load(open(os.path.join(bdir, "alert.json")))
        assert meta["alert"]["rule"] == "router-error-burn"
        assert meta["members"] == ["ghost0", "router0"]
        assert os.path.isfile(os.path.join(bdir, "router0", "metrics.txt"))
        assert os.path.isfile(os.path.join(bdir, "router0",
                                           "snapshot.json"))
        assert os.path.isdir(os.path.join(bdir, "ghost0"))
        assert os.path.isfile(os.path.join(bdir, "events_tail.jsonl"))
        # alert events landed in events.jsonl with logical timestamps
        evs = [e for e in iter_events(
            os.path.join(str(tmp_path / "run"), "events.jsonl"))
            if e.get("type") == "alert"]
        assert len(evs) == 3
        assert all(float(e["t"]).is_integer() for e in evs)
        # the firing gauge and GET /alerts agree with the final state
        assert c.registry.gauge("graftscope_alerts_firing").value(
            rule="router-error-burn") == 0.0
        url = f"http://127.0.0.1:{c.server.port}/alerts"
        doc = json.loads(urllib.request.urlopen(url, timeout=5).read())
        assert doc["alerts"][0]["state"] == "inactive"
        assert doc["alerts"][0]["fire_count"] == 1
        assert len(doc["timeline"]) == 3
    finally:
        c.stop()


def test_chaos_drill_is_bit_identical_across_runs(tmp_path):
    t1, a1, l1 = _drill(tmp_path / "run_a")
    t2, a2, l2 = _drill(tmp_path / "run_b")
    assert json.dumps(t1, sort_keys=True) == json.dumps(t2, sort_keys=True)
    assert json.dumps(a1, sort_keys=True) == json.dumps(a2, sort_keys=True)
    assert l1 == l2 and l1  # same bundles, and there are bundles
    ev_a = open(tmp_path / "run_a" / "events.jsonl", "rb").read()
    ev_b = open(tmp_path / "run_b" / "events.jsonl", "rb").read()
    assert ev_a == ev_b  # byte-for-byte: logical clock all the way down


# -- scope_report ------------------------------------------------------------

def test_scope_report_renders_timeline_and_sparklines(tmp_path, capsys):
    import scope_report  # via the scripts/ path hook below

    _drill(tmp_path / "run")
    rc = scope_report.main([str(tmp_path / "run"),
                            "--series", "serve_router_requests_total"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "alert_transitions=3" in out
    assert "rule=router-error-burn episodes=1" in out
    assert "inactive->pending" in out and "firing->resolved" in out
    assert "bundle=router-error-burn_" in out and "members=2" in out
    assert "series=serve_router_requests_total{" in out


def _import_scripts_path():
    import sys
    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)


_import_scripts_path()


# -- config plumbing ---------------------------------------------------------

def test_scope_config_from_yaml_block(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("scope:\n  interval_s: 2.5\n  port: 0\n"
                 "  targets: [\"http://127.0.0.1:9\"]\n"
                 "  max_points: 64\n")
    cfg = ScopeConfig.from_yaml(str(p))
    assert cfg.interval_s == 2.5 and cfg.max_points == 64
    assert cfg.targets == ["http://127.0.0.1:9"]


def test_shipped_sample_configs_carry_scope_blocks():
    import yaml
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for fname in ("configs/serve-sample.yaml",
                  "configs/model-config-sample.yaml"):
        with open(os.path.join(repo, fname)) as fh:
            doc = yaml.safe_load(fh)
        assert "scope" in doc, fname
        cfg = ScopeConfig.from_dict(doc["scope"])
        assert cfg.interval_s > 0 and cfg.alerts_path
