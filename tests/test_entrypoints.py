"""Shipped configs load; graft entry points run on the CPU mesh."""

import pytest

import glob
import os

from mlx_cuda_distributed_pretraining_tpu.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_all_shipped_configs_load():
    """Every shipped preset (incl. configs/models/ and configs/optimizers/)
    loads, resolves model args, and builds its optimizer."""
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer

    paths = glob.glob(os.path.join(REPO, "configs", "**", "*.yaml"), recursive=True)
    assert len(paths) >= 25
    for p in paths:
        cfg = Config.from_yaml(p)
        assert cfg.name
        if "tokenizer-config" in p:
            continue  # tokenizer-training preset: no model/training sections
        assert cfg.model.hidden_size > 0
        assert cfg.training.batch_size > 0
        args = LlamaArgs.from_config(cfg.model, vocab_size=259)
        assert args.hidden_size == cfg.model.hidden_size
        assert build_optimizer(cfg.training, 100) is not None


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None


@pytest.mark.slow
def test_bench_subprocess_harness_end_to_end(tmp_path):
    """Drive the real bench.py parent -> probe -> --one child machinery on
    CPU with the CI-only tiny case: the stdout contract line must appear
    with a populated matrix and a real device string."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "",          # disable the axon sitecustomize
        "JAX_PLATFORMS": "cpu",
        "BENCH_CASES": "tiny",
        "BENCH_STEPS": "2",
        "BENCH_VOCAB": "512",
        "BENCH_BUDGET_S": "240",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    contract = json.loads(proc.stdout.strip().splitlines()[-1])
    assert contract["unit"] == "tok/s"
    assert "CPU" in contract["device"].upper()
    [case] = [r for r in contract["matrix"] if r.get("case") == "tiny_simple"]
    assert case["tok_s"] > 0 and case["final_loss"] > 0
