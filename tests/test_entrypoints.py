"""Shipped configs load; graft entry points run on the CPU mesh."""

import glob
import os

from mlx_cuda_distributed_pretraining_tpu.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_all_shipped_configs_load():
    """Every shipped preset (incl. configs/models/ and configs/optimizers/)
    loads, resolves model args, and builds its optimizer."""
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer

    paths = glob.glob(os.path.join(REPO, "configs", "**", "*.yaml"), recursive=True)
    assert len(paths) >= 25
    for p in paths:
        cfg = Config.from_yaml(p)
        assert cfg.name
        if "tokenizer-config" in p:
            continue  # tokenizer-training preset: no model/training sections
        assert cfg.model.hidden_size > 0
        assert cfg.training.batch_size > 0
        args = LlamaArgs.from_config(cfg.model, vocab_size=259)
        assert args.hidden_size == cfg.model.hidden_size
        assert build_optimizer(cfg.training, 100) is not None


def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None
