"""Shipped configs load; graft entry points run on the CPU mesh."""

import pytest

import glob
import os

from mlx_cuda_distributed_pretraining_tpu.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_all_shipped_configs_load():
    """Every shipped preset (incl. configs/models/ and configs/optimizers/)
    loads, resolves model args, and builds its optimizer."""
    from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer

    paths = glob.glob(os.path.join(REPO, "configs", "**", "*.yaml"), recursive=True)
    assert len(paths) >= 25
    for p in paths:
        if os.path.basename(p).startswith("serve-"):
            # serving preset: EngineConfig schema, not a training Config
            from mlx_cuda_distributed_pretraining_tpu.serve import EngineConfig

            scfg = EngineConfig.from_yaml(p)
            assert scfg.num_slots > 0 and scfg.max_len > 1
            continue
        if os.path.basename(p) == "alerts.yaml":
            # graftscope alert rules: their own schema, own validator
            from mlx_cuda_distributed_pretraining_tpu.obs.alerts import load_rules

            assert len(load_rules(p)) > 0
            continue
        cfg = Config.from_yaml(p)
        assert cfg.name
        if "tokenizer-config" in p:
            continue  # tokenizer-training preset: no model/training sections
        assert cfg.model.hidden_size > 0
        assert cfg.training.batch_size > 0
        args = LlamaArgs.from_config(cfg.model, vocab_size=259)
        assert args.hidden_size == cfg.model.hidden_size
        assert build_optimizer(cfg.training, 100) is not None


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_bench_emit_folds_harvester_rows(tmp_path):
    """emit() folds chip-harvester out-file rows into the contract doc —
    the driver's end-of-round bench must report session-harvested rows
    even when the tunnel dies during its own run (r2-r4 failure mode).
    Same-vocab filter, skipped-placeholder replacement, clean-beats-
    preempted, per-row device provenance, and the off-switch all hold."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "out"
    out.mkdir()
    rows = [
        {"case": "2m_mega", "tok_s": 5e5, "vocab": 512, "megastep": 20,
         "device": "FakeTPU:0"},
        # preempted first, clean later in the same file: clean must win
        {"case": "40m_flash", "tok_s": 1.0, "vocab": 512, "preempted": True,
         "device": "FakeTPU:0"},
        {"case": "40m_flash", "tok_s": 2e5, "vocab": 512,
         "device": "FakeTPU:0"},
        # wrong vocab: must be filtered out
        {"case": "100m_flash", "tok_s": 3e5, "vocab": 32768,
         "device": "FakeTPU:0"},
        # legacy row with no vocab key (pre-r5 decode format): accepted
        {"case": "decode_100m", "decode_tok_s": 1e4, "device": "FakeTPU:0"},
        # only a preempted (truncated) capture exists: never folded
        {"case": "650m_flash", "tok_s": 9.0, "vocab": 512,
         "preempted": True, "device": "FakeTPU:0"},
    ]
    with open(out / "mixed.out", "w") as f:
        for r in rows:
            f.write("BENCHCASE " + json.dumps(r) + "\n")

    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
        "BENCH_CASES": "tiny", "BENCH_STEPS": "2", "BENCH_VOCAB": "512",
        "BENCH_BUDGET_S": "240", "CHIPRUN_OUT": str(out),
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    by_case = {r["case"]: r for r in doc["matrix"]}
    assert doc["harvester_rows_merged"] == 3
    assert "100m_flash" not in by_case  # vocab filter
    assert by_case["decode_100m"]["source"] == "harvester"  # legacy no-vocab
    assert "650m_flash" not in by_case  # preempted-only capture: not folded
    assert by_case["40m_flash"]["tok_s"] == 2e5  # clean beat preempted
    assert by_case["2m_mega"]["source"] == "harvester"
    assert by_case["2m_mega"]["device"] == "FakeTPU:0"  # per-row provenance
    # headline prefers the folded chip-rate row; doc device is the live one
    assert doc["value"] == 5e5 and "CPU" in doc["device"].upper()

    env["BENCH_MERGE_CHIPRUN"] = "0"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(tmp_path),
    )
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "harvester_rows_merged" not in doc
    assert all(r.get("source") != "harvester" for r in doc["matrix"])


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None


@pytest.mark.slow
def test_bench_subprocess_harness_end_to_end(tmp_path):
    """Drive the real bench.py parent -> probe -> --one child machinery on
    CPU with the CI-only tiny case: the stdout contract line must appear
    with a populated matrix and a real device string."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "",          # disable the axon sitecustomize
        "JAX_PLATFORMS": "cpu",
        "BENCH_CASES": "tiny",
        "BENCH_STEPS": "2",
        "BENCH_VOCAB": "512",
        "BENCH_BUDGET_S": "240",
        "CHIPRUN_OUT": str(tmp_path / "no_chiprun"),  # isolate from /tmp
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    contract = json.loads(proc.stdout.strip().splitlines()[-1])
    assert contract["unit"] == "tok/s"
    assert "CPU" in contract["device"].upper()
    [case] = [r for r in contract["matrix"] if r.get("case") == "tiny_simple"]
    assert case["tok_s"] > 0 and case["final_loss"] > 0
