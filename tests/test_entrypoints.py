"""Shipped configs load; graft entry points run on the CPU mesh."""

import glob
import os

from mlx_cuda_distributed_pretraining_tpu.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_all_shipped_configs_load():
    paths = glob.glob(os.path.join(REPO, "configs", "*.yaml"))
    assert len(paths) >= 6
    for p in paths:
        cfg = Config.from_yaml(p)
        assert cfg.name
        assert cfg.model.hidden_size > 0
        assert cfg.training.batch_size > 0


def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn).lower(*args).compile()
    assert out is not None
