"""The serving-plane chaos drill as a test (graftchaos, slow tier).

Runs scripts/chaos_serve.sh, which drives bench.py's ``serve_chaos``
case: an in-process 1 prefill + 1 decode fleet behind the fleet router,
flooded while the fault registry tears KV pushes (corrupt + drop),
times out metrics scrapes, and hard-kills the decode replica for a
window. The script exits 0 only when every bar held: no hung requests,
every outcome a clean 200/429/504, greedy token parity across the chaos
window, the circuit breaker opened AND recovered, and TTFT stayed
bounded. The drill is deterministic (seeded faults, greedy decode), so
a failure here is a regression, not flake."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_chaos_serve_drill_meets_every_bar(tmp_path):
    out_json = str(tmp_path / "chaos_serve.json")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "chaos_serve.sh"), out_json],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (
        f"chaos drill failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-4000:]}")
    row = json.loads(open(out_json).read())
    assert row["bar_met"] is True
    # The drill actually exercised every armed fault point.
    fires = row["fault_fires"]
    assert fires.get("kv_transfer.corrupt", 0) >= 1
    assert fires.get("kv_transfer.drop", 0) >= 1
    assert fires.get("http.connect_refused", 0) >= 1
    # Every flooded request resolved with a clean status.
    outcomes = row["outcomes"]
    assert outcomes["error"] == 0
    assert outcomes["ok"] > 0
    sys.stdout.write(proc.stdout[-1500:])
