"""Deterministic fault-injection tests for the checkpoint durability layer.

Every fault the ISSUE's acceptance list names — truncated model file,
missing optimizer file, torn manifest, ENOSPC during background write,
corrupt metadata.json — is injected at a named point
(checkpoint/faults.py) and the invariant pinned: resume selects the
newest VERIFIED checkpoint, never a torn one, quarantining the wreckage.
"""

import json
import os

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.checkpoint import (
    CheckpointIntegrityError,
    CheckpointManager,
    faults,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.reset()


PARAMS = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
OPT = {"m": np.ones((3, 4), np.float32), "count": 7}


def _mgr(tmp_path, name="run", **kw):
    run = CheckpointManager.setup_run_directory(str(tmp_path), name)
    notes = []
    mgr = CheckpointManager(run, notify=notes.append, **kw)
    mgr._notes = notes
    return mgr


def _save(mgr, step):
    mgr.save(step, {"w": PARAMS["w"] + float(step)}, OPT, {"step": step})


# -- manifest basics ---------------------------------------------------------

def test_manifest_written_last_and_verifies(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 10)
    mpath = mgr.manifest_path(10)
    assert os.path.isfile(mpath)
    with open(mpath) as f:
        manifest = json.load(f)
    assert set(manifest["artifacts"]) == {
        "step_10_model.safetensors", "step_10_optimizer.safetensors",
        "step_10_state.json"}
    for info in manifest["artifacts"].values():
        assert info["bytes"] > 0 and isinstance(info["crc32"], int)
    ok, reason = mgr.verify(10)
    assert ok, reason
    assert mgr.latest_complete_step() == "10"


def test_unmanifested_step_never_selected(tmp_path):
    """A crash between artifact writes leaves no manifest — that step must
    be invisible to resume even though its model file exists."""
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    with faults.active("manifest", "drop"):
        _save(mgr, 2)  # all artifacts land, manifest vanishes
    model2, _, _ = mgr.paths_for_step(2)
    assert os.path.isfile(model2)
    assert mgr.latest_complete_step() == "1"
    # latest_step (unverified) would have picked the torn step
    assert mgr.latest_step() == "2"


# -- injected write faults ---------------------------------------------------

def test_enospc_on_blocking_model_write_raises_and_leaves_no_manifest(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    rule = faults.inject("model", "enospc", match="step_2")
    with pytest.raises(OSError):
        _save(mgr, 2)
    assert rule.hits == 1
    assert not os.path.isfile(mgr.manifest_path(2))
    assert mgr.latest_complete_step() == "1"


def test_enospc_during_background_write_surfaces_and_resume_falls_back(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    faults.inject("model", "enospc", match="step_2")
    mgr.save(2, PARAMS, OPT, {"step": 2}, blocking=False)
    with pytest.raises(RuntimeError, match="background checkpoint write failed"):
        mgr.wait()
    assert mgr.latest_complete_step() == "1"


def test_truncated_model_write_quarantined_on_resume(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    with faults.active("model", "truncate", match="step_2", truncate_bytes=16):
        _save(mgr, 2)
    ok, reason = mgr.verify(2)
    assert not ok and "size mismatch" in reason
    assert mgr.latest_complete_step() == "1"
    qdir = os.path.join(mgr.checkpoint_dir, "quarantine")
    assert "step_2_model.safetensors" in os.listdir(qdir)
    assert any("quarantined checkpoint step 2" in n for n in mgr._notes)


def test_dropped_optimizer_write_detected(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    with faults.active("optimizer", "drop", match="step_2"):
        _save(mgr, 2)
    ok, reason = mgr.verify(2)
    assert not ok and "missing artifact" in reason
    assert mgr.latest_complete_step() == "1"


def test_torn_manifest_quarantined(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    with faults.active("manifest", "truncate", match="step_2", truncate_bytes=40):
        _save(mgr, 2)
    ok, reason = mgr.verify(2)
    assert not ok and "torn manifest" in reason
    assert mgr.latest_complete_step() == "1"
    qdir = os.path.join(mgr.checkpoint_dir, "quarantine")
    assert "step_2.manifest.json" in os.listdir(qdir)


def test_bitrot_after_write_detected_by_crc(tmp_path):
    """Corruption that keeps the size (flipped bytes, not truncation) is
    caught by the CRC pass."""
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    _save(mgr, 2)
    model2, _, _ = mgr.paths_for_step(2)
    size = os.path.getsize(model2)
    with open(model2, "r+b") as f:
        f.seek(size - 8)
        f.write(b"\xff" * 8)
    ok, reason = mgr.verify(2)
    assert not ok and "crc32 mismatch" in reason
    assert mgr.latest_complete_step() == "1"


def test_fallback_walks_multiple_corrupt_steps(tmp_path):
    mgr = _mgr(tmp_path)
    for s in (1, 2, 3, 4):
        _save(mgr, s)
    for s in (2, 3, 4):
        model, _, _ = mgr.paths_for_step(s)
        with open(model, "r+b") as f:
            f.truncate(10)
    assert mgr.latest_complete_step() == "1"
    qdir = os.path.join(mgr.checkpoint_dir, "quarantine")
    names = os.listdir(qdir)
    for s in (2, 3, 4):
        assert f"step_{s}_model.safetensors" in names


def test_legacy_unmanifested_checkpoints_still_resumable(tmp_path):
    """Runs from before the manifest era (no manifests at all) fall back
    to the unverified latest_step so old checkpoints stay loadable."""
    mgr = _mgr(tmp_path)
    for s in (1, 2):
        _save(mgr, s)
    for s in (1, 2):
        os.unlink(mgr.manifest_path(s))
    assert mgr.latest_complete_step() == "2"
    assert any("predate integrity manifests" in n for n in mgr._notes)


def test_mixed_era_falls_back_to_legacy_after_quarantine(tmp_path):
    """REGRESSION: old pre-manifest checkpoints alongside newer manifested
    ones — when every manifested candidate fails verification, resume must
    fall back to the newest loadable legacy step, not report nothing
    (which would let a supervisor fresh-start wipe the dir)."""
    mgr = _mgr(tmp_path)
    for s in (1, 2):
        _save(mgr, s)
    os.unlink(mgr.manifest_path(1))  # step 1 is now "legacy"
    model2, _, _ = mgr.paths_for_step(2)
    with open(model2, "r+b") as f:
        f.truncate(10)  # the only manifested step is corrupt
    assert mgr.latest_complete_step() == "1"
    assert any("resuming unverified pre-manifest step 1" in n
               for n in mgr._notes)
    # the corrupt manifested step was still quarantined
    qdir = os.path.join(mgr.checkpoint_dir, "quarantine")
    assert "step_2.manifest.json" in os.listdir(qdir)


def test_read_only_scan_skips_without_quarantining(tmp_path):
    """latest_complete_step(quarantine=False): eval/serving consumers must
    not move files out from under a concurrently training process."""
    mgr = _mgr(tmp_path)
    for s in (1, 2):
        _save(mgr, s)
    model2, _, _ = mgr.paths_for_step(2)
    with open(model2, "r+b") as f:
        f.truncate(10)
    assert mgr.latest_complete_step(quarantine=False) == "1"
    # nothing moved: the corrupt step's files are all still in place
    assert os.path.isfile(model2)
    assert os.path.isfile(mgr.manifest_path(2))
    assert not os.path.isdir(os.path.join(mgr.checkpoint_dir, "quarantine"))
    assert any("read-only scan" in n for n in mgr._notes)
    # and the failed candidate (still on disk, since nothing was moved) is
    # never offered as the legacy fallback: with step 1 de-manifested, the
    # fallback must pick legacy step 1, not corrupt-but-newer step 2
    os.unlink(mgr.manifest_path(1))
    assert mgr.latest_complete_step(quarantine=False) == "1"


def test_sidecar_fault_injection_point(tmp_path):
    """The per-host data sidecar is covered: it is folded into the step
    manifest and a torn sidecar fails verification."""
    mgr = _mgr(tmp_path)
    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import _atomic_json

    sidecar = os.path.join(mgr.checkpoint_dir, "step_5_data_p0.json")
    os.makedirs(mgr.checkpoint_dir, exist_ok=True)
    _atomic_json(sidecar, {"val_ptr": 123, "position": 456})
    _save(mgr, 5)
    with open(mgr.manifest_path(5)) as f:
        assert "step_5_data_p0.json" in json.load(f)["artifacts"]
    with open(sidecar, "r+b") as f:
        f.truncate(4)
    ok, reason = mgr.verify(5)
    assert not ok and "step_5_data_p0.json" in reason

    # and the sidecar write itself is an injectable point
    with faults.active("sidecar", "enospc"):
        with pytest.raises(OSError):
            _atomic_json(sidecar, {"val_ptr": 1})


# -- optimizer-state degradation (silent-reset satellite) --------------------

def test_missing_optimizer_warns_and_strict_raises(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    _, opt_path, _ = mgr.paths_for_step(1)
    os.unlink(opt_path)

    _, opt_state, _ = mgr.load(1, like_params=PARAMS, like_opt_state=OPT)
    assert opt_state is None
    assert any("MISSING" in n for n in mgr._notes)

    with pytest.raises(CheckpointIntegrityError, match="MISSING"):
        mgr.load(1, like_params=PARAMS, like_opt_state=OPT, strict=True)


def test_unreadable_optimizer_warns_and_strict_raises(tmp_path):
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    _, opt_path, _ = mgr.paths_for_step(1)
    with open(opt_path, "wb") as f:
        f.write(b"garbage that is not safetensors")

    _, opt_state, _ = mgr.load(1, like_params=PARAMS, like_opt_state=OPT)
    assert opt_state is None
    assert any("UNREADABLE" in n for n in mgr._notes)

    with pytest.raises(CheckpointIntegrityError, match="UNREADABLE"):
        mgr.load(1, like_params=PARAMS, like_opt_state=OPT, strict=True)


def test_partial_optimizer_state_warns_and_strict_raises(tmp_path):
    """An optimizer file missing expected leaves (e.g. optimizer changed
    between save and resume) is a loud partial reset, not a silent one."""
    mgr = _mgr(tmp_path)
    _save(mgr, 1)
    bigger_like = dict(OPT, extra=np.zeros((2,), np.float32))
    _, opt_state, _ = mgr.load(1, like_params=PARAMS, like_opt_state=bigger_like)
    assert opt_state is not None  # partial state still rebuilt...
    assert any("lacks" in n for n in mgr._notes)  # ...but loudly
    with pytest.raises(CheckpointIntegrityError, match="lacks"):
        mgr.load(1, like_params=PARAMS, like_opt_state=bigger_like, strict=True)


# -- retention GC ------------------------------------------------------------

def test_retention_gc_keep_last_and_keep_every(tmp_path):
    mgr = _mgr(tmp_path, keep_last=2, keep_every=10)
    for s in (5, 10, 15, 20, 25):
        _save(mgr, s)
    kept = {t for t in mgr.manifested_steps()}
    # last two (20, 25) plus keep_every multiples (10, 20); 5 and 15 pruned
    assert kept == {"10", "20", "25"}
    assert not os.path.exists(mgr.paths_for_step(5)[0])
    ok, _ = mgr.verify(10)
    assert ok


def test_retention_gc_never_deletes_final_or_protected(tmp_path):
    mgr = _mgr(tmp_path, keep_last=1)
    mgr.protect_steps.add("1")  # the resume source
    for s in (1, 2, 3):
        _save(mgr, s)
    mgr.save("final", PARAMS, OPT, {"step": 3})
    kept = set(mgr.manifested_steps())
    assert "final" in kept and "1" in kept and "3" in kept
    assert "2" not in kept


def test_retention_disabled_by_default(tmp_path):
    mgr = _mgr(tmp_path)
    for s in (1, 2, 3, 4):
        _save(mgr, s)
    assert set(mgr.manifested_steps()) == {"1", "2", "3", "4"}


def test_retention_gc_prunes_ledger_entries(tmp_path):
    """REGRESSION: GC'd steps must leave the metadata.json ledger too —
    entries pointing at deleted files read as phantom checkpoints."""
    mgr = _mgr(tmp_path, keep_last=2)
    for s in (1, 2, 3, 4):
        _save(mgr, s)
    with open(os.path.join(mgr.run_dir, "metadata.json")) as f:
        ledger = json.load(f)
    steps = [e["step"] for e in ledger["checkpoints"]]
    assert steps == [3, 4]
    for e in ledger["checkpoints"]:
        assert os.path.isfile(e["path"])


# -- corrupt metadata.json (ledger satellite) --------------------------------

def test_corrupt_ledger_preserved_and_rebuilt_from_scan(tmp_path):
    mgr = _mgr(tmp_path)
    for s in (1, 2):
        _save(mgr, s)
    meta = os.path.join(mgr.run_dir, "metadata.json")
    with open(meta, "w") as f:
        f.write('{"checkpoints": [tru')  # torn mid-write

    _save(mgr, 3)  # next append must NOT reset the ledger
    with open(meta) as f:
        ledger = json.load(f)
    steps = [e["step"] for e in ledger["checkpoints"]]
    assert steps == [1, 2, 3]
    assert all(e.get("rebuilt") for e in ledger["checkpoints"][:2])
    assert os.path.isfile(meta + ".corrupt")
    assert any("rebuilding the ledger" in n for n in mgr._notes)


# -- trainer-level end-to-end ------------------------------------------------

def _tiny_cfg_dict(tmp_path, name, iters, **extra):
    import json as _json

    train = tmp_path / "train.jsonl"
    if not train.exists():
        with open(train, "w") as f:
            for _ in range(40):
                f.write(_json.dumps(
                    {"text": "the quick brown fox jumps over the lazy dog " * 4}) + "\n")
    d = {
        "name": name,
        "overwrite": True,
        "data": {
            "input_file": str(train),
            "preprocessing": {"max_context_size": 64},
            "tokenizer": {"normal_vocab_size": 256},
        },
        "model": {
            "architecture": "llama",
            "dimensions": {"hidden_size": 32, "intermediate_size": 64, "num_layers": 2},
            "attention": {"num_heads": 4, "num_kv_heads": 2, "head_dim": 8},
        },
        "training": {
            "hyperparameters": {"batch_size": 4, "learning_rate": 1e-2, "iters": iters},
            "scheduler": {"type": "cosine", "min_lr_ratio": 0.1},
            "optimization": {"optimizer": "adamw"},
        },
        "logging": {
            "steps": {"logging_interval": 5, "checkpoint_interval": 3,
                      "validation_interval": 0},
        },
        "system": {"seed": 0, "device": "cpu"},
    }
    for k, v in extra.items():
        node = d
        parts = k.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return d


def test_trainer_resume_falls_back_to_older_verified(tmp_path):
    """End-to-end: corrupt the two newest checkpoints of a real run; a
    resume.checkpoint=latest trainer quarantines both and resumes from the
    newest step that verifies."""
    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    runs = str(tmp_path / "runs")
    cfg = Config.from_dict(_tiny_cfg_dict(tmp_path, "fallback", iters=9))
    tr = Trainer(cfg, runs_root=runs, quiet=True)
    tr.train()  # checkpoints at 3, 6, 9 + final

    mgr = tr.checkpoints
    for tag in ("final", "9"):
        model, _, _ = mgr.paths_for_step(tag)
        with open(model, "r+b") as f:
            f.truncate(32)

    d = _tiny_cfg_dict(tmp_path, "fallback", iters=9)
    d["overwrite"] = False
    d["resume"] = {"checkpoint": "latest"}
    tr2 = Trainer(Config.from_dict(d), runs_root=runs, quiet=True)
    assert tr2.start_step == 6
    qdir = os.path.join(tr2.checkpoints.checkpoint_dir, "quarantine")
    names = os.listdir(qdir)
    assert "step_final_model.safetensors" in names
    assert "step_9_model.safetensors" in names
    log = open(os.path.join(tr2.run_dir, "log.txt")).read()
    assert "quarantined checkpoint step final" in log
    assert "Resumed from checkpoint 6" in log


def test_trainer_strict_resume_raises_without_verified_checkpoint(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    runs = str(tmp_path / "runs")
    cfg = Config.from_dict(_tiny_cfg_dict(tmp_path, "strictrun", iters=3))
    tr = Trainer(cfg, runs_root=runs, quiet=True)
    tr.train()
    # wipe every checkpoint: nothing resumable remains
    import shutil

    shutil.rmtree(tr.checkpoints.checkpoint_dir)
    os.makedirs(tr.checkpoints.checkpoint_dir)

    d = _tiny_cfg_dict(tmp_path, "strictrun", iters=3)
    d["overwrite"] = False
    d["resume"] = {"checkpoint": "latest", "strict": True}
    with pytest.raises(CheckpointIntegrityError, match="no\\s+verified"):
        Trainer(Config.from_dict(d), runs_root=runs, quiet=True)


def test_trainer_nonstrict_resume_starts_fresh_without_checkpoint(tmp_path):
    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    runs = str(tmp_path / "runs")
    cfg = Config.from_dict(_tiny_cfg_dict(tmp_path, "freshrun", iters=3))
    tr = Trainer(cfg, runs_root=runs, quiet=True)
    tr.train()
    import shutil

    shutil.rmtree(tr.checkpoints.checkpoint_dir)
    os.makedirs(tr.checkpoints.checkpoint_dir)

    d = _tiny_cfg_dict(tmp_path, "freshrun", iters=3)
    d["overwrite"] = False
    d["resume"] = {"checkpoint": "latest"}
    tr2 = Trainer(Config.from_dict(d), runs_root=runs, quiet=True)
    assert tr2.start_step == 0
    log = open(os.path.join(tr2.run_dir, "log.txt")).read()
    assert "no resumable checkpoint found" in log


def test_trainer_explicit_legacy_tag_loads_unverified_not_quarantined(tmp_path):
    """REGRESSION: resume.checkpoint=<tag> naming a healthy pre-manifest
    checkpoint in a MIXED-era dir (other steps do have manifests) must
    load that step unverified — not quarantine the user's known-good
    checkpoint and silently resume a different step."""
    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    runs = str(tmp_path / "runs")
    cfg = Config.from_dict(_tiny_cfg_dict(tmp_path, "legacytag", iters=9))
    tr = Trainer(cfg, runs_root=runs, quiet=True)
    tr.train()  # checkpoints at 3, 6, 9 + final, all manifested

    mgr = tr.checkpoints
    os.unlink(mgr.manifest_path(6))  # step 6 becomes "pre-manifest"

    d = _tiny_cfg_dict(tmp_path, "legacytag", iters=9)
    d["overwrite"] = False
    d["resume"] = {"checkpoint": "6"}
    tr2 = Trainer(Config.from_dict(d), runs_root=runs, quiet=True)
    assert tr2.start_step == 6
    model6, _, _ = tr2.checkpoints.paths_for_step(6)
    assert os.path.isfile(model6)  # still in place, not quarantined
    assert not os.path.isdir(
        os.path.join(tr2.checkpoints.checkpoint_dir, "quarantine"))
    log = open(os.path.join(tr2.run_dir, "log.txt")).read()
    assert "no integrity manifest" in log


def test_load_trained_read_only_never_quarantines(tmp_path):
    """REGRESSION: load_trained (eval/serving) runs a read-only scan — a
    corrupt newest checkpoint is skipped, not moved, so a concurrent
    trainer's resume/GC view of the dir is undisturbed."""
    from mlx_cuda_distributed_pretraining_tpu.config import Config
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import (
        Trainer,
        load_trained,
    )

    runs = str(tmp_path / "runs")
    cfg = Config.from_dict(_tiny_cfg_dict(tmp_path, "servable", iters=9))
    tr = Trainer(cfg, runs_root=runs, quiet=True)
    tr.train()

    mgr = tr.checkpoints
    model_final, _, _ = mgr.paths_for_step("final")
    with open(model_final, "r+b") as f:
        f.truncate(32)

    params, args, tok, _ = load_trained(tr.run_dir, runs_root=runs)
    assert params is not None
    # the torn final checkpoint was skipped in place, not quarantined
    assert os.path.isfile(model_final)
    assert not os.path.isdir(os.path.join(mgr.checkpoint_dir, "quarantine"))
