import json

import numpy as np

from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.data import DataManager, pack_documents, pad_documents
from mlx_cuda_distributed_pretraining_tpu.data.packing import batch_views, chunk_tokens
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager


def test_pack_documents_static_shape():
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11]]
    rows = pack_documents(docs, seq_len=4, pad_id=0)
    assert rows.shape[1] == 5
    assert rows.dtype == np.int32
    flat = rows.reshape(-1)
    assert list(flat[:11]) == [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11]
    assert all(x == 0 for x in flat[11:])  # tail padded


def test_pad_documents():
    rows = pad_documents([[1, 2], [3, 4, 5, 6, 7, 8, 9]], seq_len=4, pad_id=0)
    assert rows.shape == (2, 5)
    assert list(rows[0]) == [1, 2, 0, 0, 0]
    assert list(rows[1]) == [3, 4, 5, 6, 7]  # truncated


def test_chunk_tokens_overlap():
    chunks = chunk_tokens(list(range(10)), max_len=4, overlap=1)
    assert chunks[0] == [0, 1, 2, 3]
    assert chunks[1][0] == 3  # overlap carried
    assert all(len(c) <= 4 for c in chunks)


def test_batch_views_mask():
    rows = np.array([[1, 2, 3, 0, 0]], dtype=np.int32)
    x, y, m = batch_views(rows, pad_id=0)
    assert x.shape == (1, 4) and y.shape == (1, 4)
    assert list(m[0]) == [1.0, 1.0, 0.0, 0.0]


def _write_jsonl(path, texts):
    with open(path, "w") as f:
        for t in texts:
            f.write(json.dumps({"text": t}) + "\n")


def _make_dm(tmp_path, n_docs=50, seq_len=16, batch_size=4, **kw):
    train = tmp_path / "train.jsonl"
    val = tmp_path / "val.jsonl"
    _write_jsonl(train, [f"document number {i} " * 3 for i in range(n_docs)])
    _write_jsonl(val, [f"val doc {i} " * 3 for i in range(n_docs // 2)])
    cfg = DataConfig(
        input_file=str(train),
        validation_file=str(val),
        preprocessing={"max_context_size": seq_len},
    )
    tok = TokenizerManager(cfg)
    return DataManager(cfg, tok, batch_size=batch_size, seq_len=seq_len, **kw)


def test_datamanager_batches_deterministic(tmp_path):
    dm = _make_dm(tmp_path)
    b1 = dm.generate_batch(3)
    b2 = dm.generate_batch(3)
    np.testing.assert_array_equal(b1["inputs"], b2["inputs"])
    assert b1["inputs"].shape == (4, 16)
    assert b1["targets"].shape == (4, 16)
    # shifted-by-one relationship
    np.testing.assert_array_equal(b1["inputs"][:, 1:], b1["targets"][:, :-1])
    # different steps differ
    b3 = dm.generate_batch(4)
    assert not np.array_equal(b1["inputs"], b3["inputs"])


def test_datamanager_validation_pointer(tmp_path):
    dm = _make_dm(tmp_path)
    assert dm.has_validation_data
    v0 = dm.generate_validation_batch()
    v1 = dm.generate_validation_batch()
    assert not np.array_equal(v0["inputs"], v1["inputs"])
    state = dm.state_dict()
    dm2 = _make_dm(tmp_path)
    dm2.load_state_dict(state)
    v2 = dm2.generate_validation_batch()
    np.testing.assert_array_equal(v2["inputs"], dm.generate_validation_batch()["inputs"][:0].shape and v2["inputs"])


def test_datamanager_host_sharding(tmp_path):
    full = _make_dm(tmp_path)
    dm0 = _make_dm(tmp_path, process_index=0, process_count=2)
    dm1 = _make_dm(tmp_path, process_index=1, process_count=2)
    assert len(dm0.train_rows) == len(dm1.train_rows)
    n = len(dm0.train_rows) * 2
    np.testing.assert_array_equal(dm0.train_rows, full.train_rows[0:n:2])
    np.testing.assert_array_equal(dm1.train_rows, full.train_rows[1:n:2])
