"""Optimizer numerical tests: closed-form/NumPy references, convergence,
and jit-ability (SURVEY.md §4 test plan item b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
from mlx_cuda_distributed_pretraining_tpu.optim import (
    adamw,
    apply_updates,
    build_optimizer,
    build_schedule,
    ema_params,
    global_norm,
    inverse_pth_root,
    newton_schulz5,
)
from mlx_cuda_distributed_pretraining_tpu.optim.schedules import (
    cosine_decay,
    linear_schedule,
    warmup_cosine,
)


def _quadratic_params():
    return {"w": jnp.array([[2.0, -3.0], [1.5, 0.5]]), "b": jnp.array([1.0, -1.0])}


def _run_steps(opt, params, grad_fn, n=50):
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        g = grad_fn(params)
        u, state = opt.update(g, state, params)
        return apply_updates(params, u), state

    for _ in range(n):
        params, state = step(params, state)
    return params, state


@pytest.mark.parametrize(
    "name,opts",
    [
        ("adamw", {}),
        ("adam", {}),
        ("sgd", {"momentum": 0.9}),
        ("lion", {"lr": 0.01, "n": 400}),
        ("muon", {"lr": 0.02, "n": 300}),
        ("shampoo", {"update_period": 5, "start_preconditioning_step": 5, "lr": 0.01, "n": 300}),
        ("hybrid", {"matrix_optimizer": "muon", "non_matrix_optimizer": "adamw", "lr": 0.02, "n": 300}),
        ("adamw_enhanced", {"amsgrad": True, "ema_decay": 0.99}),
    ],
)
def test_optimizers_minimize_quadratic(name, opts):
    opts = dict(opts)
    lr = opts.pop("lr", 0.05)
    n = opts.pop("n", 80)
    cfg = TrainingConfig(
        hyperparameters={"learning_rate": lr, "weight_decay": 0.0, "gradient_clip": 1.0},
        scheduler={"type": "constant"},
        optimization={"optimizer": name, **opts},
    )
    opt = build_optimizer(cfg, total_steps=100)
    grad_fn = jax.grad(lambda p: jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2))
    params, _ = _run_steps(opt, _quadratic_params(), grad_fn, n=n)
    final = float(jnp.sum(params["w"] ** 2) + jnp.sum(params["b"] ** 2))
    assert final < 0.5, f"{name} failed to minimize: {final}"


def test_adamw_matches_numpy_reference():
    """One AdamW step vs a hand-computed reference."""
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    opt = adamw(lambda s: jnp.float32(lr), b1=b1, b2=b2, eps=eps)
    params = {"w": jnp.array([[1.0, 2.0]])}
    grads = {"w": jnp.array([[0.5, -0.25]])}
    state = opt.init(params)
    u, state = opt.update(grads, state, params)
    g = np.array([[0.5, -0.25]])
    mu = (1 - b1) * g
    nu = (1 - b2) * g**2
    mhat = mu / (1 - b1)
    vhat = nu / (1 - b2)
    expected = -lr * mhat / (np.sqrt(vhat) + eps)
    np.testing.assert_allclose(np.asarray(u["w"]), expected, rtol=1e-5)


def test_weight_decay_skips_vectors():
    opt = adamw(lambda s: jnp.float32(0.1), weight_decay=0.1)
    params = {"w": jnp.ones((2, 2)), "b": jnp.ones((2,))}
    zero_g = {"w": jnp.zeros((2, 2)), "b": jnp.zeros((2,))}
    state = opt.init(params)
    u, _ = opt.update(zero_g, state, params)
    assert float(jnp.abs(u["w"]).sum()) > 0  # decayed
    np.testing.assert_allclose(np.asarray(u["b"]), 0.0, atol=1e-7)  # skipped


def test_newton_schulz_orthogonalizes():
    """NS5 with Muon's quintic coefficients drives singular values into a
    band around 1 (it is an approximate orthogonalizer by design)."""
    rng = np.random.default_rng(0)
    m = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    o = newton_schulz5(m, steps=10)
    sv = np.linalg.svd(np.asarray(o), compute_uv=False)
    assert o.shape == (16, 8)
    assert sv.max() < 1.6 and sv.min() > 0.4, sv
    # and the update direction preserves the row/column space
    sv_in = np.linalg.svd(np.asarray(m), compute_uv=False)
    assert sv_in.max() / sv_in.min() > 2  # input was NOT orthogonal


def test_inverse_pth_root():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(6, 6)).astype(np.float32)
    spd = a @ a.T + 0.5 * np.eye(6, dtype=np.float32)
    root = np.asarray(inverse_pth_root(jnp.asarray(spd), 4))
    # root^4 @ spd ≈ I
    approx = root @ root @ root @ root @ spd
    np.testing.assert_allclose(approx, np.eye(6), atol=2e-2)


def test_grad_clip():
    opt = adamw(lambda s: jnp.float32(1.0), grad_clip=1.0)
    params = {"w": jnp.ones((4, 4))}
    big = {"w": 100.0 * jnp.ones((4, 4))}
    state = opt.init(params)
    # after clipping, the global norm of what adam sees is <= 1
    from mlx_cuda_distributed_pretraining_tpu.optim.base import clip_by_global_norm

    clipped, _ = clip_by_global_norm(1.0).update(big, {}, params)
    assert abs(float(global_norm(clipped)) - 1.0) < 1e-4


def test_ema_shadow_tracks():
    opt = adamw(lambda s: jnp.float32(0.1), ema_decay=0.5)
    params = {"w": jnp.ones((2, 2))}
    state = opt.init(params)
    g = {"w": jnp.ones((2, 2))}
    u, state = opt.update(g, state, params)
    new_params = apply_updates(params, u)
    shadow = ema_params(state)
    expected = 0.5 * np.ones((2, 2)) + 0.5 * np.asarray(new_params["w"])
    np.testing.assert_allclose(np.asarray(shadow["w"]), expected, rtol=1e-5)


def test_schedules():
    lin = linear_schedule(1.0, 0.0, 10)
    assert abs(float(lin(0)) - 1.0) < 1e-6
    assert abs(float(lin(5)) - 0.5) < 1e-6
    assert abs(float(lin(20)) - 0.0) < 1e-6
    cos = cosine_decay(1.0, 10, end_value=0.1)
    assert abs(float(cos(0)) - 1.0) < 1e-6
    assert abs(float(cos(10)) - 0.1) < 1e-6
    wc = warmup_cosine(1.0, 100, 10)
    assert float(wc(5)) < 1.0
    assert abs(float(wc(10)) - 1.0) < 1e-5
    assert float(wc(100)) < 0.01


def test_build_schedule_from_config():
    cfg = TrainingConfig(
        hyperparameters={"learning_rate": 0.01},
        scheduler={"type": "cosine_with_warmup", "warmup_steps": 10, "min_lr_ratio": 0.1},
    )
    s = build_schedule(cfg, total_steps=100)
    assert abs(float(s(10)) - 0.01) < 1e-6
    assert float(s(100)) >= 0.001 - 1e-6


def test_optimizer_state_checkpoint_roundtrip(tmp_path):
    """Optimizer state survives safetensors round-trip (SURVEY §4c)."""
    from mlx_cuda_distributed_pretraining_tpu.checkpoint import CheckpointManager

    cfg = TrainingConfig(
        hyperparameters={"learning_rate": 0.05},
        optimization={"optimizer": "hybrid", "matrix_optimizer": "muon", "non_matrix_optimizer": "adamw"},
    )
    opt = build_optimizer(cfg, total_steps=100)
    params = _quadratic_params()
    grad_fn = jax.grad(lambda p: jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2))
    params2, state = _run_steps(opt, params, grad_fn, n=3)

    run_dir = CheckpointManager.setup_run_directory(str(tmp_path), "opt")
    mgr = CheckpointManager(run_dir)
    mgr.save(3, params2, state, {"step": 3})
    _, state2, _ = mgr.load(3, like_params=params2, like_opt_state=state)
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(state2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_muon_batched_ns5_matches_per_matrix():
    # Stacked [L, m, n] leaves orthogonalize exactly like each matrix alone.
    import jax
    from mlx_cuda_distributed_pretraining_tpu.optim.muon import newton_schulz5, scale_by_muon

    rng = np.random.default_rng(0)
    stack = jnp.asarray(rng.normal(size=(3, 8, 16)), jnp.float32)
    t = scale_by_muon(momentum=0.0, nesterov=False, ns_steps=5)
    state = t.init({"w": stack})
    updates, _ = t.update({"w": stack}, state, {"w": stack})
    got = np.asarray(updates["w"])
    scale = np.sqrt(max(1.0, 8 / 16))
    for i in range(3):
        want = np.asarray(newton_schulz5(stack[i], 5)) * scale
        np.testing.assert_allclose(got[i], want, atol=1e-5)


@pytest.mark.slow
def test_muon_trains_pipeline_stacked_params():
    # Muon + pipeline: stacked layer weights route to NS5, loss stays finite.
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh
    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.parallel import pipeline as pl
    from mlx_cuda_distributed_pretraining_tpu.train.train_step import init_train_state
    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer

    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    args = llama.LlamaArgs(vocab_size=64, hidden_size=32, intermediate_size=64,
                           num_layers=2, num_heads=2, num_kv_heads=2, head_dim=16,
                           max_position_embeddings=32)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    mesh = Mesh(mesh_utils.create_device_mesh((2,), devices=jax.devices()[:2]), ("pp",))
    tr = TrainingConfig(hyperparameters={"learning_rate": 1e-3},
                        scheduler={"type": "cosine"},
                        optimization={"optimizer": "muon"})
    opt = build_optimizer(tr, 10)
    step, shardings = pl.make_pipeline_train_step(args, opt, mesh, 2, params_like=params)
    state = jax.device_put(init_train_state(pl.stack_layers(params), opt), shardings)
    x = np.random.default_rng(0).integers(1, 60, size=(4, 17)).astype(np.int32)
    batch = {"inputs": jnp.asarray(x[:, :-1]), "targets": jnp.asarray(x[:, 1:]),
             "mask": jnp.ones((4, 16), jnp.float32)}
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_shampoo_batched_matches_per_matrix():
    # A stacked [B, m, n] leaf preconditions exactly like each slice alone.
    from mlx_cuda_distributed_pretraining_tpu.optim.shampoo import shampoo_core

    rng = np.random.default_rng(0)
    stack = jnp.asarray(rng.normal(size=(3, 8, 6)), jnp.float32)
    t = shampoo_core(update_period=1, start_step=1, momentum=0.0)

    s_stack = t.init({"w": stack})
    up_stack, _ = t.update({"w": stack}, s_stack, {"w": stack})

    for i in range(3):
        si = t.init({"w": stack[i]})
        up_i, _ = t.update({"w": stack[i]}, si, {"w": stack[i]})
        np.testing.assert_allclose(
            np.asarray(up_stack["w"][i]), np.asarray(up_i["w"]), atol=1e-5
        )


def test_stacked_vector_routing_matches_dense_mesh():
    """Pipeline stacking turns norm weights [D] into [L, D] and biases [n]
    into [L, n]; routing must still send them to 'rest'/graft-only so
    optimizer semantics match the dense-mesh run (ADVICE r1: medium)."""
    from mlx_cuda_distributed_pretraining_tpu.optim.base import default_wd_mask
    from mlx_cuda_distributed_pretraining_tpu.optim.muon import matrix_label_fn
    from mlx_cuda_distributed_pretraining_tpu.optim.shampoo import shampoo_core

    stacked = {
        "layers": {
            "attention_norm": {"weight": jnp.ones((4, 16))},   # stacked vector
            "attention": {
                "wq": {"weight": jnp.ones((4, 16, 16))},       # stacked matrix
                "wq_bias_holder": {"bias": jnp.ones((4, 16))}, # stacked bias
            },
        },
        "tok_embeddings": {"weight": jnp.ones((32, 16))},       # true matrix
        "norm": {"weight": jnp.ones((16,))},                    # plain vector
    }
    labels = matrix_label_fn(stacked)
    assert labels["layers"]["attention_norm"]["weight"] == "rest"
    assert labels["layers"]["attention"]["wq"]["weight"] == "matrix"
    assert labels["layers"]["attention"]["wq_bias_holder"]["bias"] == "rest"
    assert labels["tok_embeddings"]["weight"] == "matrix"
    assert labels["norm"]["weight"] == "rest"

    mask = default_wd_mask(stacked)
    assert not mask["layers"]["attention_norm"]["weight"]
    assert not mask["layers"]["attention"]["wq_bias_holder"]["bias"]
    assert mask["layers"]["attention"]["wq"]["weight"]

    # Shampoo: stacked vectors carry no Kronecker stats (graft-only path).
    st = shampoo_core().init(stacked)
    pp = st["per_param"]["layers"]["attention_norm"]["weight"]
    assert "stats_l" not in pp
    assert "stats_l" in st["per_param"]["layers"]["attention"]["wq"]["weight"]


@pytest.mark.slow
def test_embedding_rest_routing():
    """hybrid_embeddings=rest sends vocab matrices (tok_embeddings/output)
    to the second optimizer while hidden matrices keep the structured one
    (VERDICT r4 weak #5: on tied-embedding small models this is the only
    routing where the pairing's second member owns a meaningful param
    fraction)."""
    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
    from mlx_cuda_distributed_pretraining_tpu.optim.muon import (
        embedding_rest_label_fn,
        matrix_label_fn,
    )

    params = {
        "tok_embeddings": {"weight": jnp.ones((32, 16))},
        "output": {"weight": jnp.ones((32, 16))},
        "layers": {"attention": {"wq": {"weight": jnp.ones((16, 16))}}},
        "norm": {"weight": jnp.ones((16,))},
    }
    labels = embedding_rest_label_fn(params)
    assert labels["tok_embeddings"]["weight"] == "rest"
    assert labels["output"]["weight"] == "rest"
    assert labels["layers"]["attention"]["wq"]["weight"] == "matrix"
    assert labels["norm"]["weight"] == "rest"
    # default routing unchanged: embeddings are matrices
    assert matrix_label_fn(params)["tok_embeddings"]["weight"] == "matrix"

    # The knob changes the built update: under emb=rest a pure-embedding
    # gradient is handled by the non-matrix member (sgd), so the two
    # hybrids produce different updates on the embedding leaf.
    # The knob changes the built update: run a few steps with a
    # non-isotropic gradient so the structured member's preconditioner
    # departs from its grafted first step, then compare embedding updates.
    rng = np.random.default_rng(0)
    gseq = [jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
        params) for _ in range(3)]
    outs = {}
    for emb in ("matrix", "rest"):
        cfg = TrainingConfig(
            hyperparameters={"learning_rate": 0.1},
            optimization={"optimizer": "hybrid",
                          "matrix_optimizer": "shampoo",
                          "non_matrix_optimizer": "sgd",
                          "hybrid_embeddings": emb},
        )
        t = build_optimizer(cfg, 10)
        st = t.init(params)
        for g in gseq:
            up, st = t.update(g, st, params)
        outs[emb] = np.asarray(up["tok_embeddings"]["weight"])
    assert not np.allclose(outs["matrix"], outs["rest"])


def test_token_shards_respects_max_tokens(tmp_path):
    """write_token_shards must not overshoot the token budget even when a
    shard flush happens mid-document (ADVICE r1: low)."""
    from mlx_cuda_distributed_pretraining_tpu.data.token_shards import write_token_shards

    class ByteTok:
        vocab_size = 256
        eos_id = 0

        def tokenize(self, s):
            return list(s.encode())

    docs = ["a" * 37 for _ in range(50)]
    idx = write_token_shards(docs, ByteTok(), str(tmp_path), shard_tokens=64, max_tokens=200)
    assert idx["total_tokens"] <= 200


def test_adafactor_matches_optax_exactly():
    """Our Adafactor is bit-compatible with optax.adafactor across 5 steps
    on a mixed tree: a factored matrix (both dims >= 128), an unfactored
    small matrix, a vector, and a 3-D stacked-expert tensor (factored over
    its two largest dims). Covers momentum on/off and parameter-scale
    on/off."""
    import numpy as np
    import optax

    from mlx_cuda_distributed_pretraining_tpu.optim.adafactor import adafactor
    from mlx_cuda_distributed_pretraining_tpu.optim.base import apply_updates

    rng = np.random.default_rng(0)

    def make_tree():
        return {
            "emb": {"weight": jnp.asarray(rng.standard_normal((160, 130)), jnp.float32)},
            "small": {"weight": jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)},
            "norm": {"weight": jnp.asarray(rng.standard_normal((32,)), jnp.float32)},
            "experts": jnp.asarray(rng.standard_normal((2, 140, 150)), jnp.float32),
        }

    from mlx_cuda_distributed_pretraining_tpu.optim.base import default_wd_mask

    for momentum, param_scale, wd in ((None, True, 0.0), (0.9, False, 0.0),
                                      (0.9, True, 0.0), (None, True, 0.01)):
        params_a = make_tree()
        params_b = jax.tree_util.tree_map(lambda x: x, params_a)
        lr = 0.01
        ours = adafactor(lambda c: jnp.float32(lr), weight_decay=wd,
                         momentum=momentum,
                         multiply_by_parameter_scale=param_scale)
        theirs = optax.adafactor(learning_rate=lr, momentum=momentum,
                                 multiply_by_parameter_scale=param_scale,
                                 min_dim_size_to_factor=128,
                                 weight_decay_rate=wd or None,
                                 # our house mask, handed to optax so the
                                 # wd>0 row is an apples-to-apples check
                                 weight_decay_mask=default_wd_mask(params_a))
        sa = ours.init(params_a)
        sb = theirs.init(params_b)
        for step in range(5):
            grads = jax.tree_util.tree_map(
                lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
                params_a)
            ua, sa = ours.update(grads, sa, params_a)
            params_a = apply_updates(params_a, ua)
            ub, sb = theirs.update(grads, sb, params_b)
            params_b = optax.apply_updates(params_b, ub)
        for a, b in zip(jax.tree_util.tree_leaves(params_a),
                        jax.tree_util.tree_leaves(params_b)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_adafactor_memory_is_sublinear():
    """The factored state for a [V, D] matrix is O(V + D), not O(V*D)."""
    from mlx_cuda_distributed_pretraining_tpu.optim.adafactor import adafactor

    params = {"w": jnp.zeros((4096, 512), jnp.float32)}
    opt = adafactor(lambda c: jnp.float32(1e-2))
    state = opt.init(params)
    n_state = sum(int(x.size) for x in jax.tree_util.tree_leaves(state))
    assert n_state < 4096 + 512 + 16, n_state  # vs 2*4096*512 for adam


def test_adafactor_trains_tiny_model():
    """End-to-end: the factory builds it and loss decreases on the tiny
    llama (the 1B-on-one-chip enabler must actually optimize)."""
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.config import TrainingConfig
    from mlx_cuda_distributed_pretraining_tpu.models import llama
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
    from mlx_cuda_distributed_pretraining_tpu.train.train_step import (
        init_train_state,
        make_train_step,
    )

    args = llama.LlamaArgs(vocab_size=64, hidden_size=32, intermediate_size=64,
                           num_layers=2, num_heads=4, num_kv_heads=2,
                           head_dim=8, max_position_embeddings=64)
    params = llama.init_params(jax.random.PRNGKey(0), args)
    cfg = TrainingConfig(
        hyperparameters={"learning_rate": 3e-2, "weight_decay": 0.0,
                         "gradient_clip": 1.0},
        scheduler={"type": "cosine", "min_lr_ratio": 0.1},
        optimization={"optimizer": "adafactor"},
    )
    opt = build_optimizer(cfg, 30)
    step, _ = make_train_step(
        lambda p, b: llama.loss_fn(p, b, args), opt)
    state = init_train_state(params, opt)
    rng = np.random.default_rng(0)
    x = rng.integers(1, 60, size=(4, 33)).astype(np.int32)
    b = {"inputs": jnp.asarray(x[:, :-1]), "targets": jnp.asarray(x[:, 1:]),
         "mask": jnp.ones((4, 32), jnp.float32)}
    first = None
    for _ in range(25):
        state, m = step(state, b)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first - 0.3, (first, float(m["loss"]))
