"""Continuous-batching engine (serve/): pool, scheduler, engine and the
HTTP front end. Everything runs CPU-side on the tiny test shape."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.infer.generate import generate_text
from mlx_cuda_distributed_pretraining_tpu.infer.server import (
    InferenceService,
    serve,
)
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.serve import (
    BatchEngine,
    EngineConfig,
    PagedKVPool,
    QueueFullError,
    Request,
    Scheduler,
    SlotKVPool,
)
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

TOK = TokenizerManager(DataConfig())
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)

# One pool max_len for the whole module: with the tiny shape this matches
# the locked path's bucketed cache length, so identity tests compare the
# same attend shapes.
MAX_LEN = 128


def _engine(**kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": MAX_LEN,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg)


# -- kv pool ------------------------------------------------------------------

def test_pool_allocate_free_reset():
    pool = SlotKVPool(ARGS, num_slots=3, max_len=MAX_LEN)
    assert pool.capacity == MAX_LEN - 1  # last position is reserved
    slots = [pool.allocate() for _ in range(3)]
    assert sorted(slots) == [0, 1, 2]
    assert pool.allocate() is None  # full pool: no slot, no exception
    assert pool.num_used == 3 and pool.occupancy() == 1.0
    pool.lengths[slots[0]] = 7
    pool.free(slots[0])
    with pytest.raises(ValueError):
        pool.free(slots[0])  # double free
    with pytest.raises(ValueError):
        pool.free(99)  # out of range
    s = pool.allocate()
    assert s == slots[0] and pool.lengths[s] == 0  # reuse resets length
    pool.reset()
    assert pool.num_free == 3 and pool.lengths == [0, 0, 0]
    # int8 pool builds the quantized quartet per layer
    qpool = SlotKVPool(ARGS, num_slots=2, max_len=MAX_LEN, quantize=True)
    assert "k_q" in qpool.cache[0] and "k" not in qpool.cache[0]


# -- scheduler (no device) ----------------------------------------------------

def test_scheduler_admit_evict_under_full_pool():
    pool = SlotKVPool(ARGS, num_slots=2, max_len=MAX_LEN)
    sched = Scheduler(max_queue=3)
    reqs = [Request([1, 2, 3], max_tokens=4) for _ in range(3)]
    for r in reqs:
        sched.submit(r)
    admitted = sched.admit(pool)
    assert [r.id for r in admitted] == [reqs[0].id, reqs[1].id]  # FIFO
    assert sched.queue_depth() == 1 and pool.num_free == 0
    assert all(r.state == "prefill" for r in admitted)
    # finishing one frees its slot; the queued request takes it next admit
    sched.finish(pool, admitted[0], "stop")
    assert pool.num_free == 1
    assert [r.id for r in sched.admit(pool)] == [reqs[2].id]
    assert sched.admitted == 3 and sched.completed == 1


def test_scheduler_queue_full_and_deadline_eviction():
    pool = SlotKVPool(ARGS, num_slots=1, max_len=MAX_LEN)
    sched = Scheduler(max_queue=2)
    running = Request([1], max_tokens=4, deadline_s=0.01)
    sched.submit(running)
    sched.admit(pool)
    queued = Request([1], max_tokens=4, deadline_s=0.01)
    sched.submit(queued)
    with pytest.raises(QueueFullError):
        sched.submit(Request([1], max_tokens=4))
        sched.submit(Request([1], max_tokens=4))
    # both the running and the queued request expire; the slot is freed
    evicted = sched.expire(pool, now=time.monotonic() + 1.0)
    assert {r.id for r in evicted} == {running.id, queued.id}
    assert all(r.finish_reason == "deadline" and r.error for r in evicted)
    assert pool.num_free == 1 and sched.evicted == 2


# -- engine -------------------------------------------------------------------

def test_batch1_greedy_token_identity_with_generate_text():
    prompt = "the quick brown fox"
    locked_text, stats = generate_text(
        PARAMS, ARGS, TOK, prompt, max_new_tokens=16, temperature=0.0,
        return_stats=True)
    eng = _engine().start()
    try:
        out = eng.generate(prompt, max_tokens=16, temperature=0.0,
                           timeout=300.0)
    finally:
        eng.stop()
    assert out["text"] == locked_text
    assert out["generation_tokens"] == stats["generation_tokens"]
    assert out["stopped_on_token"] == stats["stopped_on_token"]
    assert out["prompt_tokens"] == stats["prompt_tokens"]


def test_engine_concurrent_more_requests_than_slots():
    eng = _engine().start()
    outs = [None] * 5
    try:
        def run(i):
            outs[i] = eng.generate(f"prompt {i}", max_tokens=6,
                                   temperature=0.5, seed=i, timeout=300.0)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        m = eng.metrics()
    finally:
        eng.stop()
    assert all(o is not None and o["tokens"] == 6 for o in outs)
    # sampled requests with distinct seeds should not all collapse to one
    # output (each slot runs its own rng chain)
    assert m["admitted"] == 5 and m["completed"] == 5
    assert m["batch_occupancy"] == 0 and m["queue_depth"] == 0


def test_engine_deadline_eviction_reported():
    eng = _engine(num_slots=1).start()
    try:
        with pytest.raises(TimeoutError, match="deadline"):
            eng.generate("slow request", max_tokens=64, deadline_s=1e-4,
                         timeout=300.0)
        assert eng.metrics()["evicted"] == 1
    finally:
        eng.stop()


def test_engine_rejects_oversized_prompt():
    eng = _engine()
    with pytest.raises(ValueError):
        eng._submit_ids(list(range(MAX_LEN + 5)), max_tokens=4,
                        temperature=0.0, seed=0)


# -- HTTP front end -----------------------------------------------------------

def _post(url, body, timeout=300.0):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_server_batch_engine_429_past_max_queue_depth():
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    # Engine NOT started: submissions stack up in the admission queue so
    # the over-depth rejection is deterministic.
    service.engine = _engine(max_queue=2)
    httpd = serve(service, port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        # fill the queue without the engine draining it
        for i in range(2):
            service.engine.submit(f"fill {i}", max_tokens=4)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(url, {"prompt": "overflow", "max_tokens": 4}, timeout=60.0)
        assert exc.value.code == 429
        assert service.engine.metrics()["rejected"] == 1
        # start the engine: the queued fills drain and new requests serve
        service.engine.start()
        status, out = _post(url, {"prompt": "after drain", "max_tokens": 4})
        assert status == 200 and out["engine"] == "batch"
        assert out["finish_reason"] in ("stop", "length")
        # health/metrics surfaces the engine
        with urllib.request.urlopen(url + "/healthz", timeout=30) as resp:
            h = json.loads(resp.read())
        assert h["engine"] == "batch" and "serve" in h
        with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
            m = json.loads(resp.read())
        assert m["num_slots"] == 2
    finally:
        httpd.shutdown()
        httpd.server_close()
        service.close()


# -- paged pool ---------------------------------------------------------------

def test_paged_pool_block_alloc_free_reuse_invariants():
    pool = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, block_size=32,
                       num_blocks=6)
    assert pool.max_blocks == 4 and pool.capacity == MAX_LEN - 1
    # arena holds num_blocks + 1 buffers: block 0 is the reserved junk block
    assert pool.cache[0]["k"].shape[0] == 7
    assert pool.blocks_for(0) == 0 and pool.blocks_for(1) == 1
    assert pool.blocks_for(32) == 1 and pool.blocks_for(33) == 2
    s0 = pool.allocate(40)  # 2 blocks
    assert s0 is not None and pool.blocks_in_use == 2
    assert sorted(set(pool.tables[s0][:2])) != [0]  # mapped, non-junk
    assert all(b == 0 for b in pool.tables[s0][2:])  # tail unmapped -> junk
    # on-demand growth maps exactly the missing blocks
    assert pool.ensure_capacity(s0, 65)  # 3 blocks
    assert pool.blocks_in_use == 3
    assert pool.ensure_capacity(s0, 65)  # idempotent
    assert pool.blocks_in_use == 3
    s1 = pool.allocate(96)  # 3 blocks -> arena full (6/6)
    assert s1 is not None and pool.free_blocks == 0
    # exhaustion: growth refused with NO state change
    assert not pool.ensure_capacity(s0, 100)
    assert pool.blocks_in_use == 6
    # beyond the table extent is always refused
    assert not pool.ensure_capacity(s0, MAX_LEN + 1)
    pool.free(s0)
    assert pool.free_blocks == 3 and all(b == 0 for b in pool.tables[s0])
    with pytest.raises(ValueError):
        pool.free(s0)  # double free
    # freed blocks are reusable; allocation still honours the arena bound
    assert pool.allocate(MAX_LEN) is None  # 4 blocks > 3 free
    s2 = pool.allocate(96)
    assert s2 == s0 and pool.lengths[s2] == 0
    # watermark saw the full-arena moment; fragmentation counts slack
    assert pool.read_watermark() == 0
    assert pool.read_watermark() == 0  # reset to current free level
    pool.lengths[s1] = 65  # 3 blocks mapped, 96 positions, 65 live
    pool.lengths[s2] = 96
    frag = pool.fragmentation()
    assert 0.0 < frag < 1.0 and abs(frag - (1 - 161 / 192)) < 1e-9
    pool.reset()
    assert pool.free_blocks == 6 and pool.num_free == 2
    # int8 arena builds the quantized quartet per layer
    qpool = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, quantize=True)
    assert "k_q" in qpool.cache[0] and "k" not in qpool.cache[0]
    with pytest.raises(ValueError):
        PagedKVPool(ARGS, num_seqs=1, max_len=MAX_LEN, block_size=24)
    with pytest.raises(ValueError):
        PagedKVPool(ARGS, num_seqs=1, max_len=100, block_size=32)


def test_paged_admission_gated_on_free_blocks():
    # 3 blocks of 32: two 40-token prompts (2 blocks each) cannot both be
    # admitted even though batch rows are free.
    pool = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, block_size=32,
                       num_blocks=3)
    sched = Scheduler(max_queue=4)
    r0 = Request(list(range(40)), max_tokens=4)
    r1 = Request(list(range(40)), max_tokens=4)
    sched.submit(r0)
    sched.submit(r1)
    admitted = sched.admit(pool)
    assert [r.id for r in admitted] == [r0.id]  # head admitted, FIFO kept
    assert sched.queue_depth() == 1 and pool.num_free == 1
    # finishing the head releases its blocks; the waiter admits next round
    sched.finish(pool, r0, "stop")
    assert [r.id for r in sched.admit(pool)] == [r1.id]


def test_engine_429_when_blocks_exhausted_backs_up_queue():
    # Arena sized so ONE request's prompt occupies every block: the second
    # waits in the queue and the third submission overflows -> 429 path.
    eng = _engine(num_blocks=2, block_size=32, max_queue=1)
    ids = list(range(50))  # 2 blocks
    eng._submit_ids(ids, max_tokens=4, temperature=0.0, seed=0)
    eng.scheduler.admit(eng.pool)
    assert eng.pool.free_blocks == 0
    eng._submit_ids(ids, max_tokens=4, temperature=0.0, seed=0)
    assert eng.scheduler.admit(eng.pool) == []  # blocks exhausted: waits
    with pytest.raises(QueueFullError):
        eng._submit_ids(ids, max_tokens=4, temperature=0.0, seed=0)
    assert eng.metrics()["rejected"] == 1


# -- paged engine parity ------------------------------------------------------

def _collect(eng, prompts, max_tokens=40, **gen_kw):
    eng.start()
    outs = [None] * len(prompts)
    try:
        def run(i):
            outs[i] = eng.generate(prompts[i], max_tokens=max_tokens,
                                   timeout=300.0, **gen_kw)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        metrics = eng.metrics()
    finally:
        eng.stop()
    return outs, metrics


PARITY_PROMPTS = ["the quick brown fox", "pack my box with", "a b c a b c a",
                  "hello world hello world hello", "zzz"]


def test_paged_vs_slotted_greedy_parity():
    # Token-for-token identity under concurrency: mixed-length prompts,
    # generations long enough to cross several block boundaries.
    slotted, _ = _collect(_engine(kv_backend="slotted", num_slots=3),
                          PARITY_PROMPTS, temperature=0.0)
    paged, _ = _collect(_engine(kv_backend="paged", num_slots=3,
                                block_size=16), PARITY_PROMPTS,
                        temperature=0.0)
    for s, p in zip(slotted, paged):
        assert p["text"] == s["text"]
        assert p["tokens"] == s["tokens"]
        assert p["finish_reason"] == s["finish_reason"]


def test_paged_int8_roundtrip_parity_with_slotted_int8():
    slotted, _ = _collect(_engine(kv_backend="slotted", kv_quant=True),
                          PARITY_PROMPTS[:2], temperature=0.0)
    eng = _engine(kv_backend="paged", kv_quant=True)
    assert "k_q" in eng.pool.cache[0]
    paged, _ = _collect(eng, PARITY_PROMPTS[:2], temperature=0.0)
    for s, p in zip(slotted, paged):
        assert p["text"] == s["text"]


def test_batched_spec_matches_single_stream_spec_greedy():
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import (
        generate_speculative,
    )

    # Repetitive prompts so prompt-lookup actually lands acceptances.
    prompts = ["a b c a b c a b", "the cat and the cat and the"]
    singles = []
    for p in prompts:
        ids = [TOK.bos_id] + TOK.tokenize(p)
        toks, stats = generate_speculative(
            PARAMS, ARGS, ids, max_tokens=32, draft_len=4, max_ngram=3,
            stop_tokens=[TOK.eos_id], temperature=0.0)
        singles.append(TOK.detokenize(toks))
    outs, m = _collect(_engine(spec_draft_len=4, spec_max_ngram=3),
                       prompts, max_tokens=32, temperature=0.0)
    for single, out in zip(singles, outs):
        assert out["text"] == single
    assert m["spec_proposed"] > 0
    assert 0 < m["spec_accepted"] <= m["spec_proposed"]
    assert m["spec_acceptance_rate"] > 0.0


def test_batched_spec_sampled_still_terminates_and_counts():
    outs, m = _collect(_engine(spec_draft_len=3), PARITY_PROMPTS[:3],
                       max_tokens=8, temperature=0.7)
    assert all(o is not None and 0 < o["tokens"] <= 8 for o in outs)
    assert m["spec_proposed"] >= m["spec_accepted"] >= 0


def test_paged_preemption_recompute_keeps_greedy_output():
    # Arena deliberately too small for both sequences at full length
    # (2 rows x up to 3 blocks needed, 4 blocks total): the younger
    # request must be preempted and recomputed, with identical output.
    reference, _ = _collect(_engine(num_slots=2), PARITY_PROMPTS[:2],
                            max_tokens=60, temperature=0.0)
    tight, m = _collect(_engine(num_slots=2, num_blocks=4, block_size=32),
                        PARITY_PROMPTS[:2], max_tokens=60, temperature=0.0)
    for ref, out in zip(reference, tight):
        assert out["text"] == ref["text"]
        assert out["tokens"] == ref["tokens"]
    assert m["preempted"] >= 1
    assert m["kv_blocks_used"] == 0 and m["kv_blocks_free"] == 4


def test_moe_model_batch_engine_greedy_matches_generate_text():
    # The batch engine's step shares moe_block with training: a MoE
    # checkpoint must greedy-decode under --engine batch token-for-token
    # with the single-stream locked path (grouped dispatch is dropless and
    # deterministic, so decode-time routing is capacity-independent).
    import dataclasses

    margs = dataclasses.replace(
        ARGS, num_local_experts=4, num_experts_per_tok=2,
        moe_aux_weight=0.01, router_z_weight=0.001)
    mparams = llama.init_params(jax.random.PRNGKey(1), margs)
    prompts = PARITY_PROMPTS[:3]
    singles = [
        generate_text(mparams, margs, TOK, p, max_new_tokens=16,
                      temperature=0.0)
        for p in prompts
    ]
    cfg = EngineConfig(num_slots=3, max_len=MAX_LEN, prefill_chunk=16)
    eng = BatchEngine(mparams, margs, TOK, cfg)
    outs, _ = _collect(eng, prompts, max_tokens=16, temperature=0.0)
    for ref, out in zip(singles, outs):
        assert out["text"] == ref
        assert out["finish_reason"] in ("length", "stop")


def test_server_locked_path_unchanged_and_reshaping_knobs_fall_back():
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    service.engine = _engine().start()
    try:
        # top_p reshapes logits -> served by the locked path even with the
        # engine attached (the batched step samples by temperature only)
        out = service.generate("abc", max_tokens=4, temperature=0.8,
                               top_p=0.9)
        assert "engine" not in out and "speculative" in out
        out2 = service.generate("abc", max_tokens=4)
        assert out2["engine"] == "batch"
    finally:
        service.close()
    # without an engine, health keeps the pre-engine shape
    plain = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    assert "engine" not in plain.health()
    assert plain.metrics() == {"engine": "locked", "role": "any",
                               "draining": False}
