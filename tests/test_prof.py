"""graftprof tests: trace parsing, step-time attribution, the CLI, the
profiler helper, per-host exposition, and the perf gate.

The golden fixture is a hand-built Chrome trace (two annotated steps,
an overlapping collective+matmul pair, an infeed slice, and a torn
tail) whose attribution is known exactly — per ISSUE 14 it pins the
parser's numbers, not just their sum. The slow test captures a real
2-step ``jax.profiler`` window on CPU and asserts the report parses it
with fractions summing to ~1.
"""

import gzip
import importlib.util
import json
import os

import pytest

from mlx_cuda_distributed_pretraining_tpu.obs.profile_report import (
    PROF_FIELDS,
    attribute,
    base_op_name,
    classify_op,
    find_trace_files,
    format_report,
    generate_report,
    load_trace_events,
    prof_fields,
    write_summary,
)
from mlx_cuda_distributed_pretraining_tpu.obs.profiler import ProfileCapture
from mlx_cuda_distributed_pretraining_tpu.obs.prometheus import (
    render_prometheus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    path = os.path.join(REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- fixture --------------------------------------------------------------
# Timeline (µs), one device. Step 1 = [1000, 2000), step 2 = [2000, 3000):
#   dot.1                 [1000, 1400)  compute/matmul
#   flash fusion          [1400, 1600)  compute/flash
#   all-gather-start.1    [1200, 1500)  comm, FULLY under compute
#   infeed.1              [1900, 1950)  host
#   dot.2                 [2000, 2400)  compute/matmul
#   reduce-scatter.2      [2300, 2800)  comm, 100µs under compute
# Exact attribution:
#   step 1: compute .6  comm_exposed 0.0  host .05  idle .35
#           comm_total .3  overlap 300/300 = 1.0
#   step 2: compute .4  comm_exposed .4   host .0   idle .2
#           comm_total .5  overlap 100/500 = 0.2
#   aggregate (equal durations): compute .5  comm .2  host .025
#           idle .275  comm_total .4  overlap 400/800 = 0.5

def _op(name, ts, dur, tid=2):
    return {"ph": "X", "name": name.lstrip("%"), "ts": ts, "dur": dur,
            "pid": 7, "tid": tid, "args": {"hlo_op": name}}


def _fixture_events():
    return [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "name": "train", "ts": 1000, "dur": 1000,
         "pid": 7, "tid": 9, "args": {"step_num": "1"}},
        {"ph": "X", "name": "train", "ts": 2000, "dur": 1000,
         "pid": 7, "tid": 9, "args": {"step_num": "2"}},
        _op("%dot.1", 1000, 400),
        _op("%fusion.flash_attention.3", 1400, 200),
        _op("%all-gather-start.1", 1200, 300, tid=3),
        _op("%infeed.1", 1900, 50),
        _op("%dot.2", 2000, 400),
        _op("%reduce-scatter.2", 2300, 500, tid=3),
    ]


def _write_trace(path, events, torn=False):
    text = json.dumps({"displayTimeUnit": "ns",
                       "traceEvents": events})
    if torn:
        # Cut inside the final event object: the salvage reader must
        # keep every complete event and flag the file torn.
        cut = text.rfind('{"ph"')
        assert cut > 0
        text = text[:cut + 25]
    data = text.encode()
    if path.endswith(".gz"):
        data = gzip.compress(data)
    with open(path, "wb") as f:
        f.write(data)
    return path


def _make_dump(root, torn=False, fname="host.trace.json.gz"):
    """Lay out <root>/plugins/profile/<session>/<fname> like jax does."""
    sess = os.path.join(root, "plugins", "profile", "2026_08_05_00_00_00")
    os.makedirs(sess, exist_ok=True)
    events = _fixture_events()
    if torn:
        events = events + [_op("%sacrificial-op.9", 2950, 40)]
    return _write_trace(os.path.join(sess, fname), events, torn=torn)


GOLD_STEP1 = dict(compute_frac=0.6, comm_frac=0.0, host_frac=0.05,
                  idle_frac=0.35, comm_total_frac=0.3, overlap_frac=1.0)
GOLD_STEP2 = dict(compute_frac=0.4, comm_frac=0.4, host_frac=0.0,
                  idle_frac=0.2, comm_total_frac=0.5, overlap_frac=0.2)
GOLD_AGG = dict(compute_frac=0.5, comm_frac=0.2, host_frac=0.025,
                idle_frac=0.275, comm_total_frac=0.4, overlap_frac=0.5)


def _check(golden, actual):
    for k, v in golden.items():
        assert actual[k] == pytest.approx(v, abs=1e-9), (k, actual)


# -- classification -------------------------------------------------------

def test_base_op_name_and_classify():
    assert base_op_name("%all-gather-start.12") == "all-gather-start"
    assert base_op_name("%dot.3.1") == "dot"
    assert classify_op("%all-gather-start.1") == ("comm", "all-gather")
    assert classify_op("all-gather-done.1") == ("comm", "all-gather")
    assert classify_op("%reduce-scatter.5") == ("comm", "reduce-scatter")
    assert classify_op("%all-reduce.2") == ("comm", "all-reduce")
    assert classify_op("%collective-permute-start.1") == (
        "comm", "collective-permute")
    assert classify_op("%dot.7") == ("compute", "matmul")
    assert classify_op("%convolution.1") == ("compute", "matmul")
    assert classify_op("%fusion.flash_attention.2") == ("compute", "flash")
    assert classify_op("%gmm.1") == ("compute", "gmm")
    assert classify_op("%infeed.1") == ("host", "host")
    assert classify_op("%fusion.99") == ("compute", "other")


# -- golden attribution ---------------------------------------------------

def test_golden_attribution_exact(tmp_path):
    _make_dump(str(tmp_path))
    report = generate_report(str(tmp_path))
    assert report is not None
    assert report["torn"] is False
    assert report["n_devices"] == 1
    assert [s["step"] for s in report["steps"]] == [1, 2]
    _check(GOLD_STEP1, report["steps"][0])
    _check(GOLD_STEP2, report["steps"][1])
    _check(GOLD_AGG, report["aggregate"])
    # Seconds columns pin the same numbers in absolute form.
    s1 = report["steps"][0]
    assert s1["compute_s"] == pytest.approx(600e-6)
    assert s1["comm_s"] == pytest.approx(300e-6)
    assert s1["overlap_s"] == pytest.approx(300e-6)
    assert s1["host_s"] == pytest.approx(50e-6)
    assert s1["compute_by_family"] == {
        "flash": pytest.approx(200e-6), "matmul": pytest.approx(400e-6)}
    assert s1["comm_by_kind"] == {"all-gather": pytest.approx(300e-6)}
    assert report["steps"][1]["comm_by_kind"] == {
        "reduce-scatter": pytest.approx(500e-6)}


def test_fractions_sum_to_one(tmp_path):
    _make_dump(str(tmp_path))
    report = generate_report(str(tmp_path))
    for scope in report["steps"] + [report["aggregate"]]:
        total = (scope["compute_frac"] + scope["comm_frac"]
                 + scope["host_frac"] + scope["idle_frac"])
        assert total == pytest.approx(1.0, abs=0.02)


def test_op_table_and_families(tmp_path):
    _make_dump(str(tmp_path))
    report = generate_report(str(tmp_path), analytic={
        "tokens_per_step": 1000.0,
        "matmul_flops_per_token": 6e6,
        "attn_flops_per_token": 1e6,
        "collective_bytes_per_step": {"reduce-scatter": 4096.0},
    })
    ops = {o["op"]: o for o in report["ops"]}
    assert ops["dot"]["count"] == 2
    assert ops["dot"]["total_s"] == pytest.approx(800e-6)
    # dot occupies 800µs of the 2000µs covered by step windows.
    assert ops["dot"]["frac"] == pytest.approx(0.4)
    assert ops["reduce-scatter"]["category"] == "comm"
    fams = report["families"]
    # achieved = flops_per_step * n_steps / family_seconds
    assert fams["compute"]["matmul"]["achieved_flops_per_s"] == \
        pytest.approx(6e6 * 1000 * 2 / 800e-6)
    assert fams["compute"]["flash"]["achieved_flops_per_s"] == \
        pytest.approx(1e6 * 1000 * 2 / 200e-6)
    assert fams["comm"]["reduce-scatter"]["achieved_bytes_per_s"] == \
        pytest.approx(4096.0 * 2 / 500e-6)
    # all-gather has no pinned bytes: time-only row, no rate invented.
    assert "achieved_bytes_per_s" not in fams["comm"]["all-gather"]


def test_torn_tail_tolerated(tmp_path):
    _make_dump(str(tmp_path), torn=True)
    report = generate_report(str(tmp_path))
    assert report["torn"] is True
    # Every complete event survives; the truncated sacrificial op does
    # not — attribution equals the untorn goldens exactly.
    _check(GOLD_STEP1, report["steps"][0])
    _check(GOLD_STEP2, report["steps"][1])
    _check(GOLD_AGG, report["aggregate"])


def test_load_trace_events_plain_json(tmp_path):
    p = _write_trace(str(tmp_path / "t.trace.json"), _fixture_events())
    events, torn = load_trace_events(p)
    assert not torn and len(events) == len(_fixture_events())


def test_truncated_gzip_does_not_raise(tmp_path):
    full = gzip.compress(json.dumps(
        {"traceEvents": _fixture_events()}).encode())
    p = str(tmp_path / "t.trace.json.gz")
    with open(p, "wb") as f:
        f.write(full[:len(full) - 8])  # lose the gzip trailer + tail
    events, torn = load_trace_events(p)  # must not raise
    assert isinstance(events, list)


def test_no_steps_synthesizes_one_window(tmp_path):
    events = [e for e in _fixture_events()
              if "step_num" not in (e.get("args") or {})]
    p = _write_trace(str(tmp_path / "t.trace.json"), events)
    report = attribute([p])
    assert [s["step"] for s in report["steps"]] == [0]
    agg = report["aggregate"]
    total = (agg["compute_frac"] + agg["comm_frac"]
             + agg["host_frac"] + agg["idle_frac"])
    assert total == pytest.approx(1.0, abs=1e-9)


def test_find_trace_files_variants(tmp_path):
    trace = _make_dump(str(tmp_path / "profile"))
    # run dir (contains profile/), dump dir, session dir, direct file
    assert find_trace_files(str(tmp_path)) == [trace]
    assert find_trace_files(str(tmp_path / "profile")) == [trace]
    assert find_trace_files(os.path.dirname(trace)) == [trace]
    assert find_trace_files(trace) == [trace]
    assert find_trace_files(str(tmp_path / "missing")) == []


def test_multi_host_files_average(tmp_path):
    # Same fixture from two "hosts" (same pids!): device identity is
    # (file, pid), so fractions average to the single-host goldens
    # instead of double-counting one lane.
    _make_dump(str(tmp_path), fname="host0.trace.json.gz")
    _make_dump(str(tmp_path), fname="host1.trace.json.gz")
    report = generate_report(str(tmp_path))
    assert report["n_devices"] == 2
    _check(GOLD_AGG, report["aggregate"])


def test_prof_fields_and_format(tmp_path):
    _make_dump(str(tmp_path))
    report = generate_report(str(tmp_path))
    fields = prof_fields(report)
    assert set(fields) == set(PROF_FIELDS)
    assert fields["prof_compute_frac"] == pytest.approx(0.5)
    assert fields["prof_overlap_frac"] == pytest.approx(0.5)
    lines = format_report(report)
    assert lines[0].startswith("graftprof=1")
    assert any(l.startswith("aggregate=1") for l in lines)
    assert any(l.startswith("op=dot") for l in lines)
    out = write_summary(report, str(tmp_path / "prof_summary.json"))
    with open(out) as f:
        assert json.load(f)["aggregate"]["n_steps"] == 2


# -- CLI ------------------------------------------------------------------

def _make_run_dir(tmp_path):
    run = tmp_path / "run"
    _make_dump(str(run / "profile"))
    with open(run / "events.jsonl", "w") as f:
        f.write(json.dumps({"v": 1, "type": "run_start", "t": 1.0,
                            "name": "model-config-sample",
                            "n_params": 1000, "flops_per_token": 7000.0,
                            "peak_flops": None, "n_chips": 1}) + "\n")
        f.write(json.dumps({"v": 1, "type": "step_window", "t": 2.0,
                            "step": 10, "steps": 10, "toks": 10000,
                            "loss": 1.0, "tok_s": 5.0,
                            "mfu": None}) + "\n")
    return run


def test_cli_prints_table_and_writes_summary(tmp_path, capsys):
    from mlx_cuda_distributed_pretraining_tpu.analysis import prof

    run = _make_run_dir(tmp_path)
    assert prof.main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "aggregate=1" in out
    assert "overlap_frac=0.5" in out
    summary = run / "prof_summary.json"
    assert summary.is_file()
    with open(summary) as f:
        doc = json.load(f)
    _check(GOLD_AGG, doc["aggregate"])
    # Analytic join recovered from the run dir's own events.jsonl:
    # 6N = 6000, attention residual = 1000, 1000 tokens/step.
    an = doc["analytic"]
    assert an["matmul_flops_per_token"] == pytest.approx(6000.0)
    assert an["attn_flops_per_token"] == pytest.approx(1000.0)
    assert an["tokens_per_step"] == pytest.approx(1000.0)


def test_cli_budget_join(tmp_path, capsys):
    from mlx_cuda_distributed_pretraining_tpu.analysis import prof

    run = _make_run_dir(tmp_path)
    budget = tmp_path / "budget.json"
    with open(budget, "w") as f:
        json.dump({"programs": {"train_step": {"collectives": {
            "all-gather": {"bytes": 8192, "count": 2}}}}}, f)
    assert prof.main([str(run), "--budgets", str(budget)]) == 0
    with open(run / "prof_summary.json") as f:
        doc = json.load(f)
    ag = doc["families"]["comm"]["all-gather"]
    assert ag["achieved_bytes_per_s"] == pytest.approx(8192 * 2 / 300e-6)


def test_cli_no_trace_exits_2(tmp_path, capsys):
    from mlx_cuda_distributed_pretraining_tpu.analysis import prof

    empty = tmp_path / "empty"
    empty.mkdir()
    assert prof.main([str(empty)]) == 2
    assert "no profiler trace" in capsys.readouterr().err


# -- profiler helper ------------------------------------------------------

def test_profile_capture_idempotent(tmp_path, monkeypatch):
    import jax.profiler

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    logs = []
    cap = ProfileCapture(str(tmp_path / "dump"), log=logs.append,
                         summary_path=str(tmp_path / "s.json"))
    assert cap.start(5) is True
    assert cap.active
    assert cap.start(6) is False          # second start: no-op
    assert [c[0] for c in calls] == ["start"]
    assert cap.stop(7) is None            # empty dump -> no report
    assert not cap.active
    assert cap.stop(8) is None            # second stop: no-op
    assert [c[0] for c in calls] == ["start", "stop"]
    assert any("trace started at step 5" in l for l in logs)
    assert any("trace written to" in l for l in logs)


def test_profile_capture_reports_on_stop(tmp_path, monkeypatch):
    import jax.profiler

    dump = tmp_path / "dump"

    def fake_stop():
        _make_dump(str(dump))  # "the profiler" writes its files on stop

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)
    synced = []
    cap = ProfileCapture(
        str(dump), sync=lambda: synced.append(1),
        analytic_fn=lambda: {"tokens_per_step": 1000.0,
                             "matmul_flops_per_token": 6e6},
        summary_path=str(tmp_path / "prof_summary.json"))
    assert cap.start() is True
    report = cap.stop(42)
    assert synced == [1]
    _check(GOLD_AGG, report["aggregate"])
    assert cap.last_report is report
    assert (tmp_path / "prof_summary.json").is_file()


def test_profile_capture_report_disabled(tmp_path, monkeypatch):
    import jax.profiler

    dump = tmp_path / "dump"
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: _make_dump(str(dump)))
    cap = ProfileCapture(str(dump), report=False)
    cap.start()
    assert cap.stop() is None             # attribution switched off


def test_profile_capture_start_failure_is_soft(tmp_path, monkeypatch):
    import jax.profiler

    def boom(d):
        raise RuntimeError("profiler busy")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    logs = []
    cap = ProfileCapture(str(tmp_path / "d"), log=logs.append)
    assert cap.start() is False
    assert not cap.active
    assert any("unavailable" in l for l in logs)


# -- per-host exposition --------------------------------------------------

def test_render_prometheus_process_index_stamp():
    snap = {"train_step": {"kind": "gauge", "help": "s",
                           "series": [{"labels": {}, "value": 7}]}}
    text = render_prometheus(snap, process_index=3)
    assert "process_index 3" in text
    assert "# TYPE process_index gauge" in text
    assert "process_index" not in render_prometheus(snap)


# -- trace_report fold ----------------------------------------------------

def test_trace_report_folds_graftprof(tmp_path, capsys):
    mod = _load_script("trace_report")
    run = _make_run_dir(tmp_path)
    lines = mod.graftprof_report(str(run))
    assert lines and lines[0].startswith("graftprof=1")
    assert any(l.startswith("aggregate=1") for l in lines)
    # No dump -> quiet, not an error.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert mod.graftprof_report(str(empty)) == []
    # --run-dir end to end through main().
    assert mod.main([ "--run-dir", str(run)]) == 0
    assert "graftprof=1" in capsys.readouterr().out


# -- perf gate ------------------------------------------------------------

def _gate_doc(rows):
    return {"metric": "x", "value": 1, "matrix": rows}


def test_perf_gate_ok_and_regression(tmp_path, capsys):
    gate = _load_script("perf_gate")
    baseline = {"version": 1, "tolerance": 0.1, "cases": {
        "2m_flash": {"tok_s": 1000.0, "mfu": 0.10,
                     "prof_idle_frac": 0.20}}}
    base_path = tmp_path / "bench_baseline.json"
    with open(base_path, "w") as f:
        json.dump(baseline, f)

    ok_doc = tmp_path / "BENCH_ok.json"
    with open(ok_doc, "w") as f:
        json.dump(_gate_doc([{"case": "2m_flash", "tok_s": 980.0,
                              "mfu": 0.095, "prof_idle_frac": 0.25}]), f)
    rc = gate.main(["--bench", str(ok_doc), "--baseline", str(base_path)])
    assert rc == 0

    bad_doc = tmp_path / "BENCH_bad.json"
    with open(bad_doc, "w") as f:
        json.dump(_gate_doc([{"case": "2m_flash", "tok_s": 500.0,
                              "mfu": 0.04, "prof_idle_frac": 0.45}]), f)
    rc = gate.main(["--bench", str(bad_doc), "--baseline", str(base_path)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    # tok_s and mfu regress relatively; the idle fraction regresses
    # absolutely (0.45 vs 0.20 > 0.1 abs tolerance).
    assert out.count("REGRESSION") >= 3


def test_perf_gate_improvement_hint_and_skips(tmp_path, capsys):
    gate = _load_script("perf_gate")
    base_path = tmp_path / "bench_baseline.json"
    with open(base_path, "w") as f:
        json.dump({"version": 1, "tolerance": 0.1, "cases": {
            "2m_flash": {"tok_s": 1000.0},
            "100m_flash": {"tok_s": 5000.0, "mfu": 0.3}}}, f)
    doc = tmp_path / "BENCH_x.json"
    with open(doc, "w") as f:
        # 2m improved beyond tolerance; 100m row incomplete (tok_s null
        # = device-unreachable skip row) -> skipped, never a failure.
        json.dump(_gate_doc([
            {"case": "2m_flash", "tok_s": 1300.0},
            {"case": "100m_flash", "tok_s": None, "mfu": None},
        ]), f)
    rc = gate.main(["--bench", str(doc), "--baseline", str(base_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "refresh the baseline" in out
    assert "case=100m_flash SKIP" in out


def test_perf_gate_missing_inputs_exit_2(tmp_path, capsys):
    gate = _load_script("perf_gate")
    doc = tmp_path / "BENCH_y.json"
    with open(doc, "w") as f:
        json.dump(_gate_doc([{"case": "a", "tok_s": 1.0}]), f)
    rc = gate.main(["--bench", str(doc),
                    "--baseline", str(tmp_path / "nope.json")])
    assert rc == 2
    rc = gate.main(["--bench", str(tmp_path / "missing.json"),
                    "--baseline", str(tmp_path / "nope.json")])
    assert rc == 2


def test_perf_gate_write_baseline_roundtrip(tmp_path):
    gate = _load_script("perf_gate")
    doc = tmp_path / "BENCH_z.json"
    with open(doc, "w") as f:
        json.dump(_gate_doc([
            {"case": "2m_flash", "tok_s": 1200.0, "mfu": 0.06,
             "prof_compute_frac": 0.7, "prof_idle_frac": 0.1,
             "final_loss": 3.0},
            {"case": "skipme", "tok_s": None},
        ]), f)
    base_path = tmp_path / "bench_baseline.json"
    rc = gate.main(["--bench", str(doc), "--baseline", str(base_path),
                    "--write-baseline"])
    assert rc == 0
    with open(base_path) as f:
        base = json.load(f)
    # Schema v2: cases pinned under the doc's backend section (the doc
    # carries no device stamp, so it lands under "cpu").
    assert base["version"] == 2
    assert base["backends"]["cpu"]["cases"] == {"2m_flash": {
        "tok_s": 1200.0, "mfu": 0.06,
        "prof_compute_frac": 0.7, "prof_idle_frac": 0.1}}
    # And the fresh baseline gates its own doc clean.
    assert gate.main(["--bench", str(doc),
                      "--baseline", str(base_path)]) == 0


def test_committed_baseline_is_valid():
    gate = _load_script("perf_gate")
    with open(os.path.join(REPO, "bench_baseline.json")) as f:
        base = json.load(f)
    assert base["version"] == 2 and base["backends"]
    for backend, section in base["backends"].items():
        assert backend in ("cpu", "tpu", "gpu")
        assert section["cases"]
        for case, pinned in section["cases"].items():
            for metric in pinned:
                assert metric in gate.DIRECTIONS, (backend, case, metric)


# -- trainer auto-report (slow) -------------------------------------------

@pytest.mark.slow
def test_trainer_profile_window_auto_report(tmp_path):
    """A profile window ends -> the trainer runs attribution itself:
    graftprof log line, prof_summary.json, prof gauges on /metrics
    snapshots, and prof_* fields on subsequent step_window events."""
    from tests.test_trainer import _tiny_config  # reuse the tiny corpus
    from mlx_cuda_distributed_pretraining_tpu.train.trainer import Trainer

    cfg = _tiny_config(tmp_path, name="profrep", iters=8,
                       **{"logging.steps.validation_interval": 0,
                          "logging.profile_start": 2,
                          "logging.profile_stop": 4})
    tr = Trainer(cfg, runs_root=str(tmp_path / "runs"), quiet=True)
    tr.train()
    log = open(os.path.join(tr.run_dir, "log.txt")).read()
    assert "graftprof: steps=" in log
    summary = os.path.join(tr.run_dir, "prof_summary.json")
    assert os.path.isfile(summary)
    with open(summary) as f:
        agg = json.load(f)["aggregate"]
    total = (agg["compute_frac"] + agg["comm_frac"]
             + agg["host_frac"] + agg["idle_frac"])
    assert total == pytest.approx(1.0, abs=0.02)
    snap = tr.metrics.snapshot()
    for name in PROF_FIELDS:
        assert name in snap, name
    events = [json.loads(l) for l in
              open(os.path.join(tr.run_dir, "events.jsonl"))]
    assert any(e["type"] == "profile_report" for e in events)
    windows = [e for e in events if e["type"] == "step_window"]
    assert any("prof_compute_frac" in e for e in windows)


# -- real capture (slow) --------------------------------------------------

@pytest.mark.slow
def test_real_two_step_profile_window(tmp_path):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x @ x + jnp.sum(x)

    x = jnp.ones((256, 256))
    step(x).block_until_ready()  # compile outside the window

    cap = ProfileCapture(str(tmp_path / "dump"),
                         summary_path=str(tmp_path / "prof_summary.json"))
    assert cap.start() is True
    for i in range(2):
        with jax.profiler.StepTraceAnnotation("train", step_num=i):
            x = step(x)
    x.block_until_ready()
    report = cap.stop()
    assert report is not None
    agg = report["aggregate"]
    total = (agg["compute_frac"] + agg["comm_frac"]
             + agg["host_frac"] + agg["idle_frac"])
    assert total == pytest.approx(1.0, abs=0.02)
    assert agg["compute_frac"] > 0
    assert (tmp_path / "prof_summary.json").is_file()
