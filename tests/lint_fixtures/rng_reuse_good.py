"""GOOD fixture: rng-reuse — split/fold_in between consumers."""
import jax


def split_between(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    return a + b


def split_in_loop(key, xs):
    out = []
    for x in xs:
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, (2,)) + x)
    return out


def branch_single_consume(key, flag):
    if flag:
        return jax.random.normal(key, (2,))
    else:
        return jax.random.uniform(key, (2,))  # other branch: not a reuse


def disjoint_rows(key, k):
    keys = jax.random.split(key, k + 1)
    head = jax.vmap(lambda kk: jax.random.normal(kk, ()))(keys[:k])
    tail = jax.random.normal(keys[k], ())  # different rows of the split
    return head, tail
