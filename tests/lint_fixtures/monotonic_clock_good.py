"""GOOD fixture: monotonic-clock — durations come from the monotonic
clock; time.time() appears only as a calendar timestamp (never
subtracted from another wall reading)."""
import time


def timed_work(job, log):
    t0 = time.monotonic()
    stamp = time.time()  # wall timestamp for the log line, fine
    log(stamp)
    job()
    return time.monotonic() - t0


def rebound_name(job):
    t = time.time()
    t = time.monotonic()  # also bound from a non-wall read: rule disarms
    job()
    return time.monotonic() - t
