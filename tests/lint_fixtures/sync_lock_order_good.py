"""Fixture: consistent acquisition order (good) — every path takes the
locks in the A < B < C order, including through a local helper."""

import threading

A = threading.Lock()
B = threading.Lock()
C = threading.Lock()


def _with_c():
    with C:
        pass


def ab():
    with A:
        with B:
            pass


def bc():
    with B:
        _with_c()


def ac():
    with A:
        _with_c()
