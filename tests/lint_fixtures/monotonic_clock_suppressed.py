"""SUPPRESSED fixture: monotonic-clock acknowledged inline (the elapsed
value is deliberately in calendar time, NTP steps and all)."""
import time


def wall_elapsed(job):
    t0 = time.time()
    job()
    return time.time() - t0  # graftlint: disable=monotonic-clock
