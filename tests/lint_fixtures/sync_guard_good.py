"""Fixture: every guarded access holds the lock (good)."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # graftsync: guarded-by=self._lock

    def inc(self):
        with self._lock:
            self.count += 1

    def value(self):
        with self._lock:
            return self.count


def bump(c):
    with c._lock:
        c.count += 1
