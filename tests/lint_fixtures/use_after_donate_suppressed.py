"""SUPPRESSED fixture: use-after-donate acknowledged inline (e.g. the
backend is known to ignore donation on CPU)."""
import jax


def f(s):
    return s


fj = jax.jit(f, donate_argnums=(0,))


def checked(s0):
    out = fj(s0)
    y = s0 * 2  # graftlint: disable=use-after-donate
    return out + y
