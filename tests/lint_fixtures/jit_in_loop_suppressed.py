"""SUPPRESSED fixture: jit-in-loop acknowledged inline (a deliberate
per-shape wrapper in a bounded sweep)."""
import jax


def sweep(fns, x):
    for f in fns:
        g = jax.jit(f)  # graftlint: disable=jit-in-loop
        x = g(x)
    return x
