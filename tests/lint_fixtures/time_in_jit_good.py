"""GOOD fixture: time-in-jit — timing wraps the dispatch; in-trace
output goes through jax.debug.print."""
import time

import jax


@jax.jit
def step(x):
    jax.debug.print("x = {}", x)  # supported in-trace output
    return x * 2


def timed_step(x):
    t0 = time.perf_counter()
    y = step(x)
    jax.block_until_ready(y)
    return y, time.perf_counter() - t0
