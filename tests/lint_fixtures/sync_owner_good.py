"""Fixture: owned-attribute mutations funneled through call_in_loop
(good) — both the lambda and the named-closure form are exempt."""


class Engine:
    def __init__(self):
        self.params = {}  # graftsync: owner=engine-thread
        self.iterations = 0  # graftsync: owner=engine-thread
        self._tasks = []

    def call_in_loop(self, fn):
        self._tasks.append(fn)

    def _loop(self):  # graftsync: owner=engine-thread
        self._step()

    def _step(self):
        self.iterations += 1

    def swap_params(self, new):
        self.call_in_loop(lambda: setattr(self, "params", new))

    def reset(self):
        def _do():
            self.iterations = 0
        self.call_in_loop(_do)
