"""BAD fixture: recompile-hazard. Never imported — analyzed as text."""
import jax
from functools import partial


@jax.jit
def branch_on_traced(x, n):
    if n > 0:  # line 8: Python branch on traced param n
        return x + 1
    return x - 1


@partial(jax.jit, static_argnums=(2,))
def loop_on_traced(x, n, m):
    for _ in range(n):  # line 15: range() over traced n (m IS static)
        x = x + 1
    return x


def plain(x, cfg):
    return x


plain_j = jax.jit(plain, static_argnums=(1,))
out = plain_j(1, [1, 2])  # line 25: non-hashable list at static position 1
