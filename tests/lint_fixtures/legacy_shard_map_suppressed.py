"""SUPPRESSED fixture: legacy-shard-map-import acknowledged inline (a
version probe that must see the legacy path directly)."""
from jax.experimental.shard_map import shard_map  # graftlint: disable=legacy-shard-map-import


def run(f, mesh, x):
    return shard_map(f, mesh=mesh)(x)
