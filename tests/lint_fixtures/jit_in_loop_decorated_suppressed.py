"""SUPPRESSED fixture: the jit-in-loop finding lands on the DECORATOR
line, but the acknowledgement sits on the ``def`` line below it — one
decorated statement, so the suppression must cover the whole span."""
import functools

import jax


def rebuild_per_config(configs, x):
    outs = []
    for cfg in configs:
        @functools.partial(jax.jit, static_argnums=(1,))  # line 12
        def step(v, scale):  # graftlint: disable=jit-in-loop
            return v * scale

        outs.append(step(x, cfg))
    return outs
