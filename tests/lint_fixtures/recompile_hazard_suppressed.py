"""SUPPRESSED fixture: recompile-hazard acknowledged inline."""
import jax


@jax.jit
def branch_on_traced(x, n):
    if n > 0:  # graftlint: disable=recompile-hazard
        return x + 1
    return x - 1
