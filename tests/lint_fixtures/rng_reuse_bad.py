"""BAD fixture: rng-reuse."""
import jax


def double_consume(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))  # line 7: key consumed twice
    return a + b


def loop_reuse(key, xs):
    out = []
    for x in xs:
        out.append(jax.random.normal(key, (2,)) + x)  # line 14: per-iter reuse
    return out
