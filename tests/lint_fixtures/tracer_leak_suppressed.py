"""SUPPRESSED fixture: tracer-leak acknowledged inline (e.g. a debug
counter the author accepts is trace-time-only)."""
import jax


class Model:
    @jax.jit
    def fwd(self, x):
        self.trace_count = 1  # graftlint: disable=tracer-leak
        return x * 2
