"""SUPPRESSED fixture: time-in-jit acknowledged inline (a trace-time
banner the author wants exactly once per compile)."""
import jax


@jax.jit
def step(x):
    print("tracing step")  # graftlint: disable=time-in-jit
    return x * 2
