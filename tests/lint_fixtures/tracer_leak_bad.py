"""BAD fixture: tracer-leak."""
import jax

_CAPTURED = None


class Model:
    @jax.jit
    def fwd(self, x):
        self.cache = x * 2  # line 10: traced value escapes onto self
        return x


@jax.jit
def stash(x):
    global _CAPTURED
    _CAPTURED = x  # line 17: traced value escapes to a global
    return x
