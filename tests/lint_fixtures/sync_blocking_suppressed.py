"""Fixture: blocking-under-lock acknowledged in place (a one-time
build step that deliberately serializes behind the lock)."""

import subprocess
import threading

_lock = threading.Lock()


def build_once():
    with _lock:
        # first caller builds; later callers wait for the artifact
        subprocess.run(["true"])  # graftsync: disable=sync-blocking-under-lock
