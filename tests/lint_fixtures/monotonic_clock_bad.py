"""BAD fixture: monotonic-clock."""
import time


def timed_work(job):
    t0 = time.time()
    job()
    return time.time() - t0  # line 8: wall-clock duration


def wall_pair(job):
    start = time.time()
    job()
    end = time.time()
    return end - start  # line 15: both operands are wall readings
