"""BAD fixture: jit-in-loop."""
import jax


def run(fns, x):
    for f in fns:
        g = jax.jit(f)  # line 7: fresh wrapper (and cache entry) per iter
        x = g(x)
    return x
