"""BAD fixture: host-sync-in-hot-loop."""
import jax
import numpy as np


@jax.jit
def step(s, b):
    return s + b, s * 2


def train(s, batches):
    tot = 0.0
    for b in batches:
        s, m = step(s, b)
        tot += float(m)  # line 15: unconditional sync every step
    return tot


def materialize(s, batches):
    rows = []
    for b in batches:
        s, m = step(s, b)
        rows.append(np.asarray(m))  # line 23: device->host copy per step
    return rows


def log_lr_per_step(s, batches, schedule):
    import jax.numpy as jnp

    lr = 0.0
    for i, b in enumerate(batches):
        s, m = step(s, b)
        lr = float(schedule(jnp.asarray(i)))  # line 33: retrace + device
        # scalar sync per step — evaluate schedules host-side instead
        # (optim.schedules.schedule_value)
    return s, lr
