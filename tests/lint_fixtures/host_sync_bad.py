"""BAD fixture: host-sync-in-hot-loop."""
import jax
import numpy as np


@jax.jit
def step(s, b):
    return s + b, s * 2


def train(s, batches):
    tot = 0.0
    for b in batches:
        s, m = step(s, b)
        tot += float(m)  # line 15: unconditional sync every step
    return tot


def materialize(s, batches):
    rows = []
    for b in batches:
        s, m = step(s, b)
        rows.append(np.asarray(m))  # line 23: device->host copy per step
    return rows
