"""SUPPRESSED fixture: host-sync-in-hot-loop acknowledged inline (the
per-token decode-yield shape, where the sync IS the API)."""
import jax


@jax.jit
def step(s, b):
    return s + b, s * 2


def decode(s, batches):
    for b in batches:
        s, m = step(s, b)
        yield float(m)  # graftlint: disable=host-sync-in-hot-loop
