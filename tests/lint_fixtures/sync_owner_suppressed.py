"""Fixture: off-owner mutation acknowledged in place — the suppression
silences the finding but the runner still counts it."""


class Engine:
    def __init__(self):
        self.params = {}  # graftsync: owner=engine-thread

    def _loop(self):  # graftsync: owner=engine-thread
        pass

    def swap_params(self, new):
        # loop not running yet in this phase; caller owns the object
        self.params = new  # graftsync: disable=sync-owned-attr
