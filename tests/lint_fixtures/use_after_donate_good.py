"""GOOD fixture: use-after-donate — the name is rebound from the result."""
import jax


def f(s):
    return s


fj = jax.jit(f, donate_argnums=(0,))


def rebind(s0):
    s0 = fj(s0)
    return s0 * 2  # the rebound name is the live output buffer


def rebind_in_loop(s0, batches):
    for b in batches:
        s0 = fj(s0)  # rebound every iteration: the donated chain pattern
    return s0
