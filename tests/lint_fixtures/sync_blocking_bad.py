"""Fixture: blocking calls while holding a lock (bad) — a sleep and a
queue get directly in the critical section, and one reached through a
local helper."""

import queue
import threading
import time

_lock = threading.Lock()
_q = queue.Queue()


def drain():
    with _lock:
        time.sleep(0.1)  # BAD
        item = _q.get()  # BAD
    return item


def _fetch():
    return _q.get()


def indirect():
    with _lock:
        return _fetch()  # BAD: reaches _q.get with the lock held
