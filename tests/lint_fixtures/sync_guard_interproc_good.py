"""Fixture: interprocedural guarded access (good) — the helper itself is
lock-free but every call site (two hops up) holds the lock."""

import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # graftsync: guarded-by=self._lock

    def _append(self, x):
        self.items.append(x)

    def _add_twice(self, x):
        self._append(x)
        self._append(x)

    def locked_add(self, x):
        with self._lock:
            self._append(x)

    def locked_bulk(self, xs):
        with self._lock:
            for x in xs:
                self._add_twice(x)
