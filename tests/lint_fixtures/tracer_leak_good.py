"""GOOD fixture: tracer-leak — state returned, not stashed."""
import jax


class Model:
    @staticmethod
    @jax.jit
    def fwd(cache, x):
        new_cache = x * 2  # local name: fine
        return new_cache, x

    def drive(self, x):
        self.cache, y = self.fwd(getattr(self, "cache", x), x)  # outside jit
        return y
