"""BAD fixture: use-after-donate."""
import jax


def f(s):
    return s


fj = jax.jit(f, donate_argnums=(0,))


def straight_line(s0):
    out = fj(s0)
    y = s0 * 2  # line 14: s0's buffer was donated to fj
    return out + y


def in_loop(s0, batches):
    outs = []
    for b in batches:
        outs.append(fj(s0))  # line 21: s0 donated again every iteration
    return outs
