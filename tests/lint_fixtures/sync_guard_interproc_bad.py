"""Fixture: interprocedural guarded access (bad) — the helper touches
the guarded list without the lock, and one of its call sites doesn't
hold it either, so the helper's access fires."""

import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # graftsync: guarded-by=self._lock

    def _append(self, x):
        self.items.append(x)  # BAD: unlocked_add calls this bare

    def locked_add(self, x):
        with self._lock:
            self._append(x)

    def unlocked_add(self, x):
        self._append(x)
