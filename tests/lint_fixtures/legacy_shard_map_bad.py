"""BAD fixture: legacy-shard-map-import."""
import jax.experimental.shard_map  # line 2: deprecated module path
from jax.experimental.shard_map import shard_map  # line 3: same, from-form
from jax.experimental import shard_map as smap  # line 4: module via parent


def run(f, mesh, x):
    return shard_map(f, mesh=mesh)(x), smap, jax
