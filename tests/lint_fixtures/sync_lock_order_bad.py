"""Fixture: three-lock acquisition cycle (bad) — A<B, B<C, C<A can
deadlock three threads; the acquisition graph has a cycle."""

import threading

A = threading.Lock()
B = threading.Lock()
C = threading.Lock()


def ab():
    with A:
        with B:
            pass


def bc():
    with B:
        with C:
            pass


def ca():
    with C:
        with A:
            pass
