"""Fixture: thread-owned attribute mutated off the owner thread (bad).

``swap_params`` runs on whatever thread calls it, but ``params`` and
``iterations`` are owned by the engine thread — the mutation must go
through ``call_in_loop``.
"""


class Engine:
    def __init__(self):
        self.params = {}  # graftsync: owner=engine-thread
        self.iterations = 0  # graftsync: owner=engine-thread
        self._tasks = []

    def call_in_loop(self, fn):
        self._tasks.append(fn)

    def _loop(self):  # graftsync: owner=engine-thread
        self._step()

    def _step(self):
        self.iterations += 1  # fine: reachable from the owner entry

    def swap_params(self, new):
        self.params = new  # BAD: caller-thread write to an owned attr

    def reset(self):
        self.iterations = 0  # BAD: not reachable from _loop
