"""Fixture: unguarded access acknowledged in place (a single aligned
read the author deems racy-but-benign)."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # graftsync: guarded-by=self._lock

    def inc(self):
        with self._lock:
            self.count += 1

    def peek(self):
        # monotonic advisory read; staleness is fine for display
        return self.count  # graftsync: disable=sync-guard
