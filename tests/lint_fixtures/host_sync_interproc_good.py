"""GOOD fixture: interprocedural host-sync stays quiet when the helper
call is interval-gated, the helper gates its own sync, or the callee is
a generator (calling one does not run its body)."""
import jax


@jax.jit
def step(s, b):
    return s + b, s * 2


def log_metrics(m, rows):
    rows.append(float(m))  # reached only behind the interval gate below


def sample_stream(s):
    yield float(s)  # generator body: not executed by the bare call


class Trainer:
    def _publish(self, m, i):
        if i % 10 == 0:
            self.last = m.item()  # gated inside the helper

    def train(self, s, batches):
        rows = []
        for i, b in enumerate(batches):
            s, m = step(s, b)
            if i % 10 == 0:
                log_metrics(m, rows)  # gated call: helper sync is gated too
            self._publish(m, i)
            sample_stream(m)
        return s, rows
