"""BAD fixture: host-sync-in-hot-loop, interprocedural — the sync hides
inside a module-local helper the loop calls unconditionally."""
import jax


@jax.jit
def step(s, b):
    return s + b, s * 2


def log_metrics(m, rows):
    rows.append(float(m))  # line 12: sync, reached per iteration via helper


class Trainer:
    def _publish(self, m):
        self.last = m.item()  # line 17: sync via self.* helper call

    def train(self, s, batches):
        rows = []
        for b in batches:
            s, m = step(s, b)
            log_metrics(m, rows)
            self._publish(m)
        return s, rows
