"""GOOD fixture: recompile-hazard — static marking / lax control flow."""
import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnums=(1,))
def branch_on_static(x, n):
    if n > 0:  # n is static: branch is resolved at trace time
        return x + 1
    return x - 1


@partial(jax.jit, static_argnames=("m",))
def loop_on_static(x, m):
    for _ in range(m):  # m is static by name
        x = x + 1
    return x


@jax.jit
def branch_on_device(x, n):
    return jnp.where(n > 0, x + 1, x - 1)  # device select, no retrace


def plain(x, cfg):
    return x


plain_j = jax.jit(plain, static_argnums=(1,))
out = plain_j(1, (1, 2))  # hashable tuple for the static arg
