"""Fixture: guarded attribute accessed without its lock (bad) — once via
``self`` inside the class, once via an outside reference (the required
lock name follows the access base: ``c.count`` needs ``with c._lock``).
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # graftsync: guarded-by=self._lock

    def inc(self):
        self.count += 1  # BAD: read-modify-write outside the lock

    def value(self):
        return self.count  # BAD: unguarded read


def bump(c):
    c.count += 1  # BAD: outside reference, no lock
