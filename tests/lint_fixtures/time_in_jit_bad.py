"""BAD fixture: time-in-jit."""
import time

import jax


@jax.jit
def step(x):
    t0 = time.perf_counter()  # line 9: trace-time constant, not a timing
    y = x * 2
    print("value:", y)  # line 11: runs once at trace time, never again
    return y, time.time() - t0  # line 12: another trace-time read
