"""BAD fixture: jit applied as a DECORATOR inside a loop — the decorator
expression runs per iteration, building a fresh wrapper each time."""
import functools

import jax


def rebuild_per_config(configs, x):
    outs = []
    for cfg in configs:
        @functools.partial(jax.jit, static_argnums=(1,))  # line 11
        def step(v, scale):
            return v * scale

        outs.append(step(x, cfg))
    return outs
