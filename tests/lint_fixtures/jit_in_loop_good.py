"""GOOD fixture: jit-in-loop — the jit is hoisted out of the loop."""
import jax


def run(f, xs):
    g = jax.jit(f)  # wrapped once
    for x in xs:
        x = g(x)
    return x
