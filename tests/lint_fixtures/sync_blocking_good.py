"""Fixture: blocking work hoisted out of the critical section (good) —
the lock only covers the in-memory bookkeeping."""

import queue
import threading
import time

_lock = threading.Lock()
_q = queue.Queue()
_items = []


def drain():
    item = _q.get()
    time.sleep(0.1)
    with _lock:
        _items.append(item)
    return item
