"""SUPPRESSED fixture: rng-reuse acknowledged inline (e.g. a deliberate
common-random-numbers experiment)."""
import jax


def crn_pair(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.normal(key, (2,))  # graftlint: disable=rng-reuse
    return a, b
