"""GOOD fixture: host-sync-in-hot-loop — gated or hoisted syncs."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(s, b):
    return s + b, s * 2


def train_gated(s, batches, log_int=10):
    last = None
    for i, b in enumerate(batches):
        s, m = step(s, b)
        if i % log_int == 0:  # interval-gated: the allowed logging shape
            last = float(m)
    return last


def train_accumulated(s, batches):
    tot = jnp.zeros(())
    for b in batches:
        s, m = step(s, b)
        tot = tot + m  # accumulates on device, no per-step sync
    return float(tot)  # one sync, after the loop


def data_loop(batches):
    # np.asarray in a loop with NO jit dispatch is host-side data prep
    return [np.asarray(b) for b in batches] + [np.asarray(b + 1) for b in batches]


def log_lr_host_side(s, batches, schedule_value, schedule):
    lr = 0.0
    for i, b in enumerate(batches):
        s, m = step(s, b)
        lr = schedule_value(schedule, i)  # host-side numpy evaluation:
        # no retrace, no device scalar round-trip
    return s, lr
