"""GOOD fixture: interprocedural time-in-jit stays quiet — the helper
with the clock read is only called OUTSIDE the jitted function, and
in-trace output goes through jax.debug.print."""
import time

import jax


def _stamp():
    return time.time()  # only reached from un-jitted code


@jax.jit
def step(x):
    jax.debug.print("x = {}", x)
    return x * 2


def run(x):
    t0 = _stamp()
    y = step(x)
    return y, _stamp() - t0
