"""BAD fixture: time-in-jit, interprocedural — the wall-clock read and
the print live in helpers the jitted body calls at trace time."""
import time

import jax


def _stamp(x):
    t = time.time()  # line 9: trace-time constant via helper
    return x, t


def _banner(x):
    print("step", x)  # line 14: trace-time I/O via helper
    return x


@jax.jit
def step(x):
    x, t = _stamp(x)
    return _banner(x * 2), t
