"""GOOD fixture: legacy-shard-map-import — the shim import plus nearby
jax.experimental names the rule must not confuse with shard_map."""
from jax.experimental import mesh_utils

from mlx_cuda_distributed_pretraining_tpu.parallel.compat import shard_map


def run(f, mesh, x):
    return shard_map(f, mesh=mesh)(x), mesh_utils
