"""MFU-campaign invariants: every lever is a pure perf knob.

The 2x MFU campaign moves step time through remat policies, scan-over-
layers, the manual overlap schedule (parallel/overlap.py), named XLA
flag sets (parallel/xla_flags.py), and the fused optimizer update
(optim/fused.py). None of them may move the math: these tests pin loss
parity (bitwise where the schedule is deterministic), optimizer-state
equality, flag-set resolution, and the sync-collectives audit rule.
"""

import types

import pytest

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np

from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs

ARGS = LlamaArgs(
    vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=32,
)

REMAT_POLICIES = (None, "none", "dots", "save_attn", "full")


def _batch(bs=2, seq=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, vocab - 4, size=(bs, seq + 1)).astype(np.int32)
    return {
        "inputs": jnp.asarray(x[:, :-1]),
        "targets": jnp.asarray(x[:, 1:]),
        "mask": jnp.ones((bs, seq), jnp.float32),
    }


# -- remat policies / scan ----------------------------------------------------


def test_remat_policy_loss_parity():
    """Every named remat policy recomputes the SAME ops on the same
    inputs: loss is bitwise identical across none/dots/save_attn/full."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    losses = {p: float(llama.loss_fn(params, batch, ARGS, remat=p)[0])
              for p in REMAT_POLICIES}
    base = losses[None]
    assert all(v == base for v in losses.values()), losses


def test_remat_policy_grad_parity():
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()

    def grads(pol):
        return jax.grad(
            lambda p: llama.loss_fn(p, batch, ARGS, remat=pol)[0])(params)

    g0 = jtu.tree_leaves(grads(None))
    for pol in ("dots", "save_attn", "full"):
        for a, b in zip(g0, jtu.tree_leaves(grads(pol))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-5)


def test_scan_layers_loss_bitwise():
    """The scanned layer stack is the same math in a different control
    structure — loss must match the unrolled loop bit for bit."""
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch()
    l_loop = float(llama.loss_fn(params, batch, ARGS, scan_layers=False)[0])
    l_scan = float(llama.loss_fn(params, batch, ARGS, scan_layers=True)[0])
    assert l_loop == l_scan


def test_remat_policy_config_validation():
    from mlx_cuda_distributed_pretraining_tpu.config import ModelConfig

    assert ModelConfig(remat_policy="save_attn").remat_policy == "save_attn"
    with pytest.raises(ValueError):
        ModelConfig(remat_policy="bogus")


# -- fused optimizer ----------------------------------------------------------


def _tiny_params(seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "layers": {"0": {"attention": {"wq": {
            "weight": jax.random.normal(k1, (8, 4), jnp.float32)}}}},
        "norm": {"weight": jax.random.normal(k2, (8,), jnp.float32)},
        "embed": {"weight": jax.random.normal(k3, (16, 8), jnp.float32)},
    }


def _run_steps(opt, params, steps=5, seed=1):
    from mlx_cuda_distributed_pretraining_tpu.optim import apply_updates
    from mlx_cuda_distributed_pretraining_tpu.optim.fused import fused_apply_of

    state = opt.init(params)
    fused = fused_apply_of(opt)
    keys = jax.random.split(jax.random.PRNGKey(seed), steps)
    for k in keys:
        leaves, treedef = jtu.tree_flatten(params)
        gks = jax.random.split(k, len(leaves))
        grads = jtu.tree_unflatten(treedef, [
            jax.random.normal(gk, l.shape, l.dtype)
            for gk, l in zip(gks, leaves)])
        if fused is not None:
            params, state = fused(grads, state, params)
        else:
            updates, state = opt.update(grads, state, params)
            params = apply_updates(params, updates)
    return params, state


@pytest.mark.parametrize("kw", [
    dict(weight_decay=0.0, grad_clip=None),
    dict(weight_decay=0.1, grad_clip=None),
    dict(weight_decay=0.1, grad_clip=1.0),
    dict(weight_decay=0.1, grad_clip=1.0, amsgrad=True),
])
def test_fused_adamw_matches_chain(kw):
    """The single-pass fused update (optim/fused.py) is BITWISE equal to
    the clip->adam->wd->schedule chain — params, every opt_state leaf,
    and the state tree structure — after K steps."""
    from mlx_cuda_distributed_pretraining_tpu.optim import adamw, fused_adamw

    sched = lambda c: 1e-2 * (1.0 + 0.1 * c)  # noqa: E731
    p_ref, s_ref = _run_steps(adamw(sched, **kw), _tiny_params())
    p_fus, s_fus = _run_steps(fused_adamw(sched, **kw), _tiny_params())
    assert jtu.tree_structure(s_ref) == jtu.tree_structure(s_fus)
    for a, b in zip(jtu.tree_leaves((p_ref, s_ref)),
                    jtu.tree_leaves((p_fus, s_fus))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_factory_builds_fused_by_default():
    from mlx_cuda_distributed_pretraining_tpu.optim import build_optimizer
    from mlx_cuda_distributed_pretraining_tpu.optim.fused import (
        FusedTransform,
        fused_apply_of,
    )

    def cfg(**opt):
        return types.SimpleNamespace(
            optimizer_name="adamw", weight_decay=0.01, gradient_clip=1.0,
            hyperparameters={}, optimization=opt)

    sched = lambda c: 1e-3  # noqa: E731
    assert isinstance(build_optimizer(cfg(), 10, schedule=sched),
                      FusedTransform)
    chain = build_optimizer(cfg(fused=False), 10, schedule=sched)
    assert fused_apply_of(chain) is None
    # EMA consumes the updates tree: must keep the chain.
    ema = types.SimpleNamespace(
        optimizer_name="adamw_enhanced", weight_decay=0.01,
        gradient_clip=1.0, hyperparameters={},
        optimization={"ema_decay": 0.999})
    assert fused_apply_of(build_optimizer(ema, 10, schedule=sched)) is None


# -- xla flag sets ------------------------------------------------------------


def test_flag_sets_resolve_per_backend():
    from mlx_cuda_distributed_pretraining_tpu.parallel import xla_flags

    assert xla_flags.flags_for("latency_hiding", "tpu")
    assert xla_flags.flags_for("latency_hiding", "gpu")
    assert xla_flags.flags_for("latency_hiding", "cpu") == []
    assert xla_flags.flags_for("none", "tpu") == []
    assert xla_flags.flags_for(None, "tpu") == []
    with pytest.raises(ValueError):
        xla_flags.flags_for("latency_hidng", "tpu")  # typo must be loud


def test_missing_flags_reads_env():
    from mlx_cuda_distributed_pretraining_tpu.parallel import xla_flags

    flags = xla_flags.flags_for("latency_hiding", "tpu")
    assert xla_flags.missing_flags("latency_hiding", "tpu",
                                   env={"XLA_FLAGS": ""}) == flags
    applied = {"XLA_FLAGS": " ".join(flags)}
    assert xla_flags.missing_flags("latency_hiding", "tpu",
                                   env=applied) == []
    partial = {"XLA_FLAGS": flags[0]}
    assert xla_flags.missing_flags("latency_hiding", "tpu",
                                   env=partial) == flags[1:]


def test_apply_flag_set_stamp_on_cpu():
    """On a CPU host the set resolves empty: the stamp still names the
    set (row attribution) and reports applied without touching env."""
    import os

    from mlx_cuda_distributed_pretraining_tpu.parallel import xla_flags

    before = os.environ.get("XLA_FLAGS")
    stamp = xla_flags.apply_flag_set("latency_hiding", backend="cpu")
    assert stamp["xla_flag_set"] == "latency_hiding"
    assert stamp["xla_backend"] == "cpu"
    assert stamp["xla_flags"] == []
    assert stamp["xla_flags_applied"] is True
    assert os.environ.get("XLA_FLAGS") == before


# -- sync-collectives audit rule ---------------------------------------------


class _FakeCompiled:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


_SYNC_HLO = """\
  %ag = f32[16,32]{1,0} all-gather(f32[8,32]{1,0} %p0), dimensions={0}
  %ar = f32[8]{0} all-reduce(f32[8]{0} %x), to_apply=%add
  %ags = f32[16]{0} all-gather-start(f32[8]{0} %p1), dimensions={0}
  %agd = f32[16]{0} all-gather-done(f32[16]{0} %ags)
"""


def _fake_program(requested, backend, hlo=_SYNC_HLO):
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        AuditProgram,
    )

    prog = AuditProgram(
        name="train_step", config_name="fake", lowered=None,
        closed_jaxpr=None, arg_leaves=[], out_avals=[],
        requested_flag_set=requested, flag_backend=backend)
    prog._compiled = _FakeCompiled(hlo)
    return prog


def test_sync_collective_census_counts_only_sync_forms():
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        sync_collective_census,
    )

    census = sync_collective_census(_SYNC_HLO)
    assert census == {"all-gather": 1, "all-reduce": 1}


def test_sync_collectives_rule_fires_for_tpu_request(monkeypatch):
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        SyncCollectives,
    )

    monkeypatch.setenv("XLA_FLAGS", "")
    findings = list(SyncCollectives().check(
        _fake_program("latency_hiding", "tpu")))
    assert len(findings) == 1
    msg = findings[0].message
    assert "synchronous" in msg and "latency_hiding" in msg
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" in msg


def test_sync_collectives_rule_silent_when_inapplicable(monkeypatch):
    from mlx_cuda_distributed_pretraining_tpu.analysis.audit_rules import (
        SyncCollectives,
    )

    monkeypatch.setenv("XLA_FLAGS", "")
    # CPU backend: the set resolves to (), sync is the only spelling.
    assert not list(SyncCollectives().check(
        _fake_program("latency_hiding", "cpu")))
    # No flag set requested.
    assert not list(SyncCollectives().check(_fake_program(None, "tpu")))
    # Flag set "none": nothing was promised.
    assert not list(SyncCollectives().check(_fake_program("none", "tpu")))
    # Async-only HLO: the scheduler did its job.
    async_only = "  %ags = f32[16]{0} all-gather-start(f32[8]{0} %p1)\n"
    assert not list(SyncCollectives().check(
        _fake_program("latency_hiding", "tpu", hlo=async_only)))


# -- overlap schedule ---------------------------------------------------------


def _fsdp_mesh(n=2):
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devs = mesh_utils.create_device_mesh((1, n), devices=jax.devices()[:n])
    return Mesh(devs, ("dp", "fsdp"))


def test_can_overlap_gating():
    from mlx_cuda_distributed_pretraining_tpu.parallel import overlap

    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    layers = params["layers"]
    mesh = _fsdp_mesh(2)
    assert overlap.can_overlap(mesh, layers, 4)
    assert not overlap.can_overlap(None, layers, 4)       # no mesh
    assert not overlap.can_overlap(mesh, layers, 3)       # batch % devices
    assert not overlap.can_overlap(mesh, [], 4)           # no layers
    from jax.sharding import Mesh
    dp_only = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "fsdp"))
    assert not overlap.can_overlap(dp_only, layers, 4)    # fsdp axis == 1


def test_bucket_layout_covers_all_sharded_leaves():
    from mlx_cuda_distributed_pretraining_tpu.parallel import overlap

    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    layer = params["layers"][0]
    mesh = _fsdp_mesh(2)
    dims = jtu.tree_leaves(overlap.layer_gather_dims(layer, mesh),
                           is_leaf=lambda x: x is None)
    leaves = jtu.tree_leaves(layer)
    assert len(dims) == len(leaves)
    buckets = overlap.bucket_layout(leaves, dims, 2,
                                    bucket_bytes=16 * 1024)
    # Every fsdp-sharded leaf lands in exactly one bucket; unsharded
    # leaves (norm vectors) ride along outside the gather.
    sharded = [i for i, d in enumerate(dims) if d is not None]
    covered = sorted(i for b in buckets for i, _, _ in b.entries)
    assert covered == sharded and sharded
    for b in buckets:
        assert b.shard_elems > 0


@pytest.mark.slow
def test_overlap_loss_parity_fsdp2():
    """The double-buffered gather schedule is bitwise-transparent: with
    the batch explicitly sharded over (dp, fsdp) the overlap loss equals
    the plain loss exactly (an unsharded batch differs in the last ulp —
    GSPMD re-partitions the CE reduction)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mlx_cuda_distributed_pretraining_tpu.parallel.context import use_mesh

    mesh = _fsdp_mesh(2)
    params = llama.init_params(jax.random.PRNGKey(0), ARGS)
    batch = _batch(bs=4)
    sharded = jax.device_put(
        batch, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    with use_mesh(mesh):
        l_base = float(llama.loss_fn(params, sharded, ARGS)[0])
        l_ov = float(llama.loss_fn(params, sharded, ARGS, overlap=True)[0])
    assert l_base == l_ov
