"""Chip-window harvester + merge tooling (scripts/chip_harvester.sh,
scripts/merge_bench_outputs.py): the machinery that converts short TPU
tunnel windows into a complete benchmark matrix. CPU-driven end to end —
the same chain the session runs against the real chip."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MERGE = os.path.join(REPO, "scripts", "merge_bench_outputs.py")
HARVESTER = os.path.join(REPO, "scripts", "chip_harvester.sh")


def _cpu_env(**extra):
    env = dict(os.environ)
    env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu", **extra)
    return env


def test_merge_bench_outputs(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    # --one results: a clean row, then a preempted duplicate that must NOT
    # displace it, plus a truncated line that must be skipped
    (out / "one_a.out").write_text(
        'BENCHCASE {"case": "2m_flash", "tok_s": 1000.0, "vocab": 64, '
        '"mfu": 0.11, "device": "TPU test"}\n'
        'BENCHCASE {"case": "trainer", "tok_s": 50.0, "preempted": true}\n'
        'BENCHCASE {"case": "trainer", "tok_s": 900.0}\n'
        'BENCHCASE {"case": "trainer", "tok_s": 10.0, "preempted": true}\n'
        "BENCHCASE {\"case\": \"torn\n")
    # breakdown output: component lines + summary, with a retried duplicate
    (out / "breakdown_x.out").write_text(
        '{"component": "fwd", "ms": 5.0}\n'
        '{"component": "fwd", "ms": 4.0}\n'
        '{"scale": "x", "tok_s": 123.0}\n')
    # a previous partial matrix doc (--also)
    also = tmp_path / "prev.json"
    also.write_text(json.dumps({
        "device": "TPU prev",
        "matrix": [{"case": "decode_2m", "decode_tok_s": 7.0},
                   {"case": "2m_flash", "tok_s": 1.0},  # loses to --one row
                   {"case": "skipped_one", "skipped": "budget"}],
    }))
    merged = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, MERGE, "--chiprun", str(out), "--also", str(also),
         "--out", str(merged)],
        capture_output=True, text=True, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    doc = json.loads(merged.read_text())
    rows = {m["case"]: m for m in doc["matrix"]}
    assert rows["2m_flash"]["tok_s"] == 1000.0  # harvester row wins
    assert rows["trainer"]["tok_s"] == 900.0  # clean row beats preempted
    assert "preempted" not in rows["trainer"]
    assert "skipped_one" not in rows
    assert doc["device"] == "TPU test"  # row device hoisted, doc-level kept as fallback
    assert doc["value"] == 1000.0 and doc["vs_baseline"] is not None
    bd = doc["breakdowns"]["breakdown_x"]
    by = {b.get("component") or "summary": b for b in bd}
    assert by["fwd"]["ms"] == 4.0  # later attempt wins
    assert by["summary"]["tok_s"] == 123.0
    # re-merge of the merged doc is stable (pretty-printed input path)
    merged2 = tmp_path / "merged2.json"
    r2 = subprocess.run(
        [sys.executable, MERGE, "--chiprun", str(tmp_path / "none"),
         "--also", str(merged), "--out", str(merged2)],
        capture_output=True, text=True, env=_cpu_env())
    assert r2.returncode == 0, r2.stderr
    doc2 = json.loads(merged2.read_text())
    assert {m["case"] for m in doc2["matrix"]} == set(rows)


@pytest.mark.skipif(os.name != "posix", reason="bash required")
@pytest.mark.slow
def test_harvester_chain(tmp_path):
    """The full loop on CPU: probe -> run a tiny case -> done-marker ->
    ALL DONE exit; a second run is a no-op thanks to the marker."""
    jobs = tmp_path / "jobs"
    jobs.write_text("one_tiny_simple 240\n\n")  # blank line must be ignored
    base = tmp_path / "chiprun"
    env = _cpu_env(CHIPRUN_BASE=str(base), BENCH_VOCAB="512",
                   BENCH_STEPS="3", CHIPRUN_SLEEP="1")
    r = subprocess.run(["bash", HARVESTER, str(jobs)], cwd=REPO,
                       capture_output=True, text=True, env=env, timeout=360)
    assert r.returncode == 0, r.stderr
    log = (base / "log").read_text()
    assert "DONE one_tiny_simple" in log and "ALL DONE" in log
    out_text = (base / "out" / "one_tiny_simple.out").read_text()
    assert "BENCHCASE" in out_text
    assert (base / "done" / "one_tiny_simple").exists()

    # second invocation: marker short-circuits, no re-run
    r2 = subprocess.run(["bash", HARVESTER, str(jobs)], cwd=REPO,
                        capture_output=True, text=True, env=env, timeout=60)
    assert r2.returncode == 0
    assert (base / "log").read_text().count("START one_tiny_simple") == 1

    merged = tmp_path / "m.json"
    rm = subprocess.run(
        [sys.executable, MERGE, "--chiprun", str(base / "out"),
         "--out", str(merged)],
        capture_output=True, text=True, env=_cpu_env())
    assert rm.returncode == 0, rm.stderr
    doc = json.loads(merged.read_text())
    assert doc["matrix"][0]["case"] == "tiny_simple"
