"""Native C++ data-plane parity tests: the ctypes packer must produce
byte-identical rows to the Python tokenize→chunk→pack pipeline."""

import numpy as np
import pytest

from mlx_cuda_distributed_pretraining_tpu import native
from mlx_cuda_distributed_pretraining_tpu.data.packing import chunk_tokens, pack_documents
from mlx_cuda_distributed_pretraining_tpu.tokenizer import ByteTokenizer

pytestmark = pytest.mark.skipif(not native.available(), reason="no C++ toolchain")


def _python_rows(texts, tok, seq_len, overlap=0, max_doc_tokens=10**9):
    docs = []
    for t in texts:
        ids = [tok.bos_id] + tok.encode(t)[:max_doc_tokens] + [tok.eos_id]
        docs.extend(chunk_tokens(ids, seq_len + 1, overlap))
    return pack_documents(docs, seq_len, tok.pad_id)


@pytest.mark.parametrize("overlap", [0, 3])
def test_native_matches_python(overlap):
    tok = ByteTokenizer()
    texts = ["hello world", "a" * 500, "", "unicode éè☃ text", "short"]
    seq_len = 64
    expect = _python_rows(texts, tok, seq_len, overlap)
    got = native.byte_pack_docs(
        texts, normal_vocab=256, bos=tok.bos_id, eos=tok.eos_id,
        pad=tok.pad_id, row_len=seq_len + 1, overlap=overlap)
    np.testing.assert_array_equal(got, expect)


def test_native_byte_filter_small_vocab():
    tok = ByteTokenizer(normal_vocab_size=128)
    texts = ["ascii only", "café ☃"]  # multi-byte chars filtered out
    expect = _python_rows(texts, tok, 32)
    got = native.byte_pack_docs(
        texts, normal_vocab=128, bos=tok.bos_id, eos=tok.eos_id,
        pad=tok.pad_id, row_len=33)
    np.testing.assert_array_equal(got, expect)


def test_native_truncation():
    tok = ByteTokenizer()
    texts = ["x" * 1000]
    expect = _python_rows(texts, tok, 16, max_doc_tokens=100)
    got = native.byte_pack_docs(
        texts, normal_vocab=256, bos=tok.bos_id, eos=tok.eos_id,
        pad=tok.pad_id, row_len=17, max_doc_tokens=100)
    np.testing.assert_array_equal(got, expect)


def test_native_empty_inputs():
    tok = ByteTokenizer()
    got = native.byte_pack_docs(
        [], normal_vocab=256, bos=tok.bos_id, eos=tok.eos_id,
        pad=tok.pad_id, row_len=17)
    assert got.shape == (0, 17)


def test_datamanager_uses_native(tmp_path):
    """The in-memory loader's native fast path yields identical training rows."""
    import json

    from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
    from mlx_cuda_distributed_pretraining_tpu.data import DataManager
    from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

    p = tmp_path / "train.jsonl"
    with open(p, "w") as f:
        for i in range(20):
            f.write(json.dumps({"text": f"document {i} " + "lorem ipsum " * 30}) + "\n")
    dc = DataConfig(input_file=str(p), preprocessing={"max_context_size": 48})
    tok = TokenizerManager(dc)
    mgr = DataManager(dc, tok, batch_size=2, seq_len=48)

    texts = [json.loads(l)["text"] for l in open(p)]
    expect = _python_rows(texts, tok.tokenizer, 48)
    np.testing.assert_array_equal(mgr.train_rows, expect)
