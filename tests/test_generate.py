import pytest

import jax
import jax.numpy as jnp
import numpy as np

from mlx_cuda_distributed_pretraining_tpu.infer.generate import beam_search, generate_lite
from mlx_cuda_distributed_pretraining_tpu.infer.samplers import (
    make_logits_processors,
    make_sampler,
    min_p_sampler,
    repetition_penalty_processor,
    top_p_sampler,
)
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs

ARGS = LlamaArgs(
    vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)


@pytest.mark.slow
def test_greedy_matches_argmax_full_forward():
    prompt = [1, 5, 9, 3]
    toks, stats = generate_lite(PARAMS, ARGS, prompt, max_tokens=5)
    # manually roll forward with full recompute
    seq = list(prompt)
    for _ in range(5):
        logits, _ = llama.forward(PARAMS, jnp.asarray([seq], jnp.int32), ARGS)
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert toks == seq[len(prompt):]
    assert stats["generation_tokens"] == 5.0
    assert stats["mean_logprob"] <= 0.0


def test_stop_tokens():
    prompt = [1, 2, 3]
    full, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=8)
    stop_at = full[2]
    toks, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=8, stop_tokens=[stop_at])
    assert stop_at not in toks
    assert len(toks) <= 8


def test_samplers_shapes_and_determinism():
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (2, 64))
    for sampler in [make_sampler(0.0), make_sampler(0.8), top_p_sampler(1.0, 0.9), min_p_sampler(1.0, 0.1)]:
        out = sampler(key, logits)
        assert out.shape == (2,)
        assert out.dtype in (jnp.int32, jnp.int64)
    # greedy deterministic
    g = make_sampler(0.0)
    np.testing.assert_array_equal(np.asarray(g(key, logits)), np.asarray(jnp.argmax(logits, -1)))
    # make_sampler caches by args (identity -> zero decode recompiles)
    assert make_sampler(0.7, 0.9) is make_sampler(0.7, 0.9)


def test_top_p_restricts_support():
    key = jax.random.PRNGKey(1)
    # one dominant token
    logits = jnp.full((1, 10), -10.0).at[0, 3].set(10.0)
    s = top_p_sampler(1.0, 0.5)
    for i in range(5):
        assert int(s(jax.random.fold_in(key, i), logits)[0]) == 3


def test_repetition_penalty():
    proc = repetition_penalty_processor(2.0)
    history = jnp.array([[5, 7, -1, -1]], jnp.int32)
    logits = jnp.ones((1, 10))
    out = proc(history, logits)
    assert float(out[0, 5]) == 0.5 and float(out[0, 7]) == 0.5
    assert float(out[0, 0]) == 1.0
    assert make_logits_processors(1.5) == make_logits_processors(1.5)


def test_beam_search_beats_greedy_logprob():
    prompt = [1, 5, 9, 3]
    seq, score = beam_search(PARAMS, ARGS, prompt, num_beams=4, max_tokens=6, eos_id=None)
    assert len(seq) == 6
    assert np.isfinite(score)
    # beam-1 equals greedy
    seq1, _ = beam_search(PARAMS, ARGS, prompt, num_beams=1, max_tokens=6, eos_id=None)
    greedy_toks, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=6)
    assert seq1 == greedy_toks


def test_long_prompt_prefill_chunking():
    prompt = list(np.random.default_rng(0).integers(1, 60, size=100))
    toks, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=4, prefill_step_size=32)
    toks2, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=4, prefill_step_size=512)
    assert toks == toks2


def test_kv_quant_cache_matches_fp32_closely():
    # int8 per-(position, head) symmetric quantization: greedy decode should
    # agree with the fp32 cache on a random-init model.
    prompt = [1, 5, 9, 3]
    toks_fp, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=8)
    toks_q, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=8, kv_quant=True)
    # identical tokens expected at this scale; allow <=1 divergence tail
    agree = sum(a == b for a, b in zip(toks_fp, toks_q))
    assert agree >= len(toks_fp) - 1, (toks_fp, toks_q)


def test_kv_quant_cache_buffers_are_int8():
    cache = llama.init_cache(ARGS, 1, max_len=32, quantize=True)
    assert cache[0]["k_q"].dtype == jnp.int8
    assert cache[0]["v_q"].dtype == jnp.int8
    assert cache[0]["k_s"].shape == (1, 32, ARGS.num_kv_heads, 1)
    # int8 buffers + scales are ~4x smaller than fp32 K/V
    q_bytes = cache[0]["k_q"].nbytes + cache[0]["k_s"].nbytes
    full = llama.init_cache(ARGS, 1, max_len=32)
    assert q_bytes < full[0]["k"].nbytes / 2


def test_kv_quant_decode_logits_close_to_full_forward():
    tokens = np.random.default_rng(0).integers(1, 60, size=(1, 12)).astype(np.int32)
    full_logits, _ = llama.forward(PARAMS, jnp.asarray(tokens), ARGS)
    cache = llama.init_cache(ARGS, 1, max_len=16, quantize=True)
    logits, cache = llama.forward(PARAMS, jnp.asarray(tokens[:, :8]), ARGS,
                                  cache=cache, start_pos=0)
    for i in range(8, 12):
        logits, cache = llama.forward(PARAMS, jnp.asarray(tokens[:, i:i + 1]), ARGS,
                                      cache=cache, start_pos=i)
    np.testing.assert_allclose(
        np.asarray(logits[0, -1]), np.asarray(full_logits[0, -1]), atol=0.05, rtol=0.05
    )


@pytest.mark.slow
def test_decode_across_attend_bucket_boundary_matches_full_forward():
    """Decode attends over a power-of-two bucket of the cache; crossing a
    bucket boundary (pos 256) must not change outputs (VERDICT r1 weak #4)."""
    args = LlamaArgs(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=512,
    )
    params = llama.init_params(jax.random.PRNGKey(1), args)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 64, size=250).tolist()
    n_new = 10  # decode positions 250..259 cross the 256-slot bucket
    toks, _ = generate_lite(params, args, prompt, max_tokens=n_new)
    seq = list(prompt)
    for _ in range(n_new):
        logits, _ = llama.forward(params, jnp.asarray([seq], jnp.int32), args)
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert toks == seq[len(prompt):]


def test_attend_bucket_helper():
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import _attend_bucket

    assert _attend_bucket(1, 4096) == 256
    assert _attend_bucket(256, 4096) == 256
    assert _attend_bucket(257, 4096) == 512
    assert _attend_bucket(5000, 8192) == 8192
    assert _attend_bucket(5000, 6000) == 6000  # clamped to cache


@pytest.mark.slow
def test_moe_decode_matches_full_forward():
    """Cached single-token decode through MoE blocks must equal full-forward
    greedy — expert capacity at S=1 must not silently drop the token."""
    moe_args = LlamaArgs(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position_embeddings=64,
        num_local_experts=4, num_experts_per_tok=2)
    params = llama.init_params(jax.random.PRNGKey(0), moe_args)
    prompt = [1, 5, 9, 3, 7]
    toks, _ = generate_lite(params, moe_args, prompt, max_tokens=6)

    cur = list(prompt)
    for _ in range(6):
        logits, _, _ = llama.forward(params, jnp.asarray([cur]), moe_args,
                                     return_aux=True)
        cur.append(int(jnp.argmax(logits[0, -1])))
    assert toks == cur[len(prompt):]


@pytest.mark.slow
def test_speculative_matches_greedy_exactly():
    """Prompt-lookup speculative decoding is bit-identical to plain greedy
    decode — the draft only proposes; every emitted token is the model's
    own argmax. Covered across: a repetitive prompt (drafts accept), a
    non-repetitive prompt (drafts mostly reject), and several draft_len /
    ngram settings."""
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import (
        generate_speculative,
    )

    prompts = [
        [1, 5, 9, 3, 1, 5, 9, 3, 1, 5, 9, 3],   # strongly repetitive
        [7, 2, 61, 40, 13, 28, 55, 4],           # no structure
        [3, 3, 3, 3],                            # degenerate repeat
    ]
    for prompt in prompts:
        ref, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=40)
        # Ground-truth per-token logprobs via full recompute: a saturated
        # tiny model can emit identical TOKENS through a corrupted KV
        # cache (e.g. a position off-by-one), but not identical logprobs.
        seq = list(prompt) + ref
        logits, _ = llama.forward(PARAMS, jnp.asarray([seq], jnp.int32), ARGS)
        lsm = jax.nn.log_softmax(logits[0], axis=-1)
        ref_lps = [float(lsm[len(prompt) - 1 + i, t]) for i, t in enumerate(ref)]
        ref_mean = float(np.mean(ref_lps))
        for k, n in ((8, 3), (4, 2), (1, 1)):
            out, stats = generate_speculative(
                PARAMS, ARGS, prompt, max_tokens=40, draft_len=k, max_ngram=n)
            assert out == ref, (prompt, k, n, out, ref)
            assert stats["verify_calls"] >= 1
            # Mean logprob must match the full-recompute ground truth to
            # float noise: a corrupted cache (e.g. a position off-by-one)
            # shifts it by ~1e-4 even when argmax tokens stay identical.
            assert abs(stats["mean_logprob"] - ref_mean) < 1e-5, \
                (k, n, stats["mean_logprob"], ref_mean)


def test_speculative_stop_tokens_and_stats():
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import (
        generate_speculative,
    )

    prompt = [1, 5, 9, 3, 1, 5, 9, 3]
    ref, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=40)
    stop = ref[5]  # stop at a token we know will be produced
    ref_stopped, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=40,
                                   stop_tokens=[stop])
    out, stats = generate_speculative(PARAMS, ARGS, prompt, max_tokens=40,
                                      draft_len=6, stop_tokens=[stop])
    assert out == ref_stopped
    # On this model's (repetitive) continuation, drafting must actually
    # pay: strictly more than one token per device step on average.
    out2, stats2 = generate_speculative(PARAMS, ARGS, prompt, max_tokens=40,
                                        draft_len=8)
    assert stats2["tokens_per_call"] > 1.5, stats2
    assert stats2["verify_calls"] < 40 / 1.5


def test_int8_weight_quant_decode():
    """Weight-only int8 quantization: per-channel error bound holds, the
    quantized model decodes through the full KV-cache path (composing
    with int8 KV), and its per-position logprobs stay close to the fp
    model's."""
    from mlx_cuda_distributed_pretraining_tpu.models.llama import (
        quantize_params_int8,
    )

    qparams = quantize_params_int8(PARAMS)
    # per-channel symmetric error bound: |w - q*s| <= s/2 elementwise
    layer = PARAMS["layers"][0]["attention"]["wq"]["weight"]
    qlayer = qparams["layers"][0]["attention"]["wq"]
    deq = qlayer["weight_q"].astype(jnp.float32) * qlayer["weight_s"]
    err = np.abs(np.asarray(layer) - np.asarray(deq))
    bound = np.asarray(qlayer["weight_s"])[None, :] / 2 + 1e-7
    assert (err <= bound).all()
    assert qlayer["weight_q"].dtype == jnp.int8

    prompt = [1, 5, 9, 3, 7, 2]
    ref, ref_stats = generate_lite(PARAMS, ARGS, prompt, max_tokens=16)
    out, stats = generate_lite(qparams, ARGS, prompt, max_tokens=16,
                               kv_quant=True)
    assert len(out) == 16  # decodes end-to-end
    # logit quality: mean logprob within a coarse band of the fp model
    assert abs(stats["mean_logprob"] - ref_stats["mean_logprob"]) < 0.3


def test_int8_weight_quant_full_forward_close():
    from mlx_cuda_distributed_pretraining_tpu.models.llama import (
        quantize_params_int8,
    )

    qparams = quantize_params_int8(PARAMS)
    toks = jnp.asarray([[1, 5, 9, 3, 7, 2, 11, 4]], jnp.int32)
    ref, _ = llama.forward(PARAMS, toks, ARGS)
    got, _ = llama.forward(qparams, toks, ARGS)
    # int8 per-channel on a tiny random model: logits track closely
    denom = float(jnp.abs(ref).mean()) + 1e-6
    rel = float(jnp.abs(ref - got).mean()) / denom
    assert rel < 0.05, rel


def test_spec_accept_preserves_distribution():
    """The speculative-sampling acceptance step is distribution-exact:
    over many keys, emit(draft if accept else alt) ~ p, for drafts the
    model likes AND drafts it hates."""
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import (
        _spec_accept_one,
    )

    probs = jnp.asarray([0.4, 0.25, 0.15, 0.1, 0.05, 0.03, 0.015, 0.005],
                        jnp.float32)
    n = 60000
    for draft in (0, 5, 7):  # high-, low-, lowest-probability proposals
        keys = jax.random.split(jax.random.PRNGKey(draft), n)
        accept, alts = jax.vmap(
            lambda k: _spec_accept_one(k, probs, jnp.int32(draft)))(keys)
        emitted = jnp.where(accept, draft, alts)
        freq = np.bincount(np.asarray(emitted), minlength=8) / n
        l1 = float(np.abs(freq - np.asarray(probs)).sum())
        assert l1 < 0.02, (draft, l1, freq)
        # acceptance rate is p(draft) itself
        acc_rate = float(np.mean(np.asarray(accept)))
        assert abs(acc_rate - float(probs[draft])) < 0.02


@pytest.mark.slow
def test_speculative_sampling_runs_and_reproduces():
    """temperature > 0 speculation: seeded-reproducible, full stats, and
    the temperature=0 path stays bit-identical to greedy."""
    from mlx_cuda_distributed_pretraining_tpu.infer.generate import (
        generate_speculative,
    )

    prompt = [1, 5, 9, 3, 1, 5, 9, 3]
    a1, s1 = generate_speculative(PARAMS, ARGS, prompt, max_tokens=24,
                                  temperature=0.9, seed=7)
    a2, _ = generate_speculative(PARAMS, ARGS, prompt, max_tokens=24,
                                 temperature=0.9, seed=7)
    assert a1 == a2 and len(a1) == 24
    assert s1["verify_calls"] >= 1 and np.isfinite(s1["mean_logprob"])
    b, _ = generate_speculative(PARAMS, ARGS, prompt, max_tokens=24,
                                temperature=0.9, seed=8)
    # different seed may legitimately coincide, but not across the board
    c, _ = generate_speculative(PARAMS, ARGS, prompt, max_tokens=24,
                                temperature=2.0, seed=9)
    assert (b != a1) or (c != a1)
    # greedy path untouched
    ref, _ = generate_lite(PARAMS, ARGS, prompt, max_tokens=24)
    g, _ = generate_speculative(PARAMS, ARGS, prompt, max_tokens=24)
    assert g == ref
