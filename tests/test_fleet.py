"""Disaggregated serving fleet (serve/fleet.py + serve/kv_transfer.py).

KV handoff correctness is anchored on greedy parity: a decode engine
that adopted a prefill engine's transferred blocks must emit exactly the
tokens a standalone engine emits for the same prompt (the final prompt
token is always recomputed receiver-side, so the sampler's logits — and
thus seeded sampling — are independent of who ran the prefill). Fleet
lifecycle (autoscale, drain, canary swap) runs against stub HTTP
replicas so policy is tested without devices; the end-to-end handoff
runs real in-process servers and joins both replicas' trace dumps under
one trace id."""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import (
    CheckpointManager,
)
from mlx_cuda_distributed_pretraining_tpu.checkpoint.safetensors_io import (
    save_safetensors,
)
from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.infer.server import (
    InferenceService,
    serve,
)
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.parallel import (
    build_mesh,
    build_serve_mesh,
)
from mlx_cuda_distributed_pretraining_tpu.parallel.elastic import (
    _atomic_write_json,
    _read_json,
)
from mlx_cuda_distributed_pretraining_tpu.parallel.sharding_rules import (
    tree_pspecs,
)
from mlx_cuda_distributed_pretraining_tpu.serve import (
    BatchEngine,
    EngineConfig,
    FleetConfig,
    FleetController,
    FleetRouter,
    KVTransferPayload,
    PagedKVPool,
)
from mlx_cuda_distributed_pretraining_tpu.serve.fleet import (
    fleet_generation,
    read_fleet,
    register_replica,
    start_heartbeat,
)
from mlx_cuda_distributed_pretraining_tpu.serve.kv_transfer import (
    build_payload,
)
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict

TOK = TokenizerManager(DataConfig())
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)
MAX_LEN = 128
SHARED = "the quick brown fox jumps over the lazy dog again and "


def _engine(**kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": MAX_LEN,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg, mesh=kw.pop("mesh", None))


def _pool(**kw):
    return PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN,
                       **{"block_size": 32, "num_blocks": 8,
                          "prefix_cache": True, **kw})


def _fill_and_register(pool, seq, ids):
    pool.lengths[seq] = len(ids)
    pool.ensure_capacity(seq, len(ids))
    pool.register_upto(seq, ids)


def _stamp(pool, seed=0):
    """Give the arena distinctive per-position bytes so a transfer test
    proves data actually moved (zeros would vacuously compare equal)."""
    import jax.numpy as jnp

    cache = []
    for li, layer in enumerate(pool.cache):
        stamped = {}
        for ni, (name, arr) in enumerate(sorted(layer.items())):
            vals = (np.arange(np.prod(arr.shape), dtype=np.float64)
                    + 13 * li + 7 * ni + seed) % 31
            stamped[name] = jnp.asarray(
                vals.reshape(arr.shape).astype(np.dtype(arr.dtype)))
        cache.append(stamped)
    pool.cache = cache


# -- wire format --------------------------------------------------------------

@pytest.mark.parametrize("quantize", [False, True],
                         ids=["fp", "int8"])
def test_payload_roundtrip_and_integrity_gate(quantize):
    pool = _pool(quantize=quantize)
    ids = list(range(70))  # 2 full blocks + tail
    s = pool.allocate(len(ids), token_ids=ids)
    _fill_and_register(pool, s, ids)
    _stamp(pool)
    export = pool.export_blocks(ids)
    payload = build_payload(export, ids, pool.block_size, pool.quantize)
    pool.release_export(export)
    assert payload.num_blocks == 2
    assert payload.quantized == quantize
    assert len(payload.token_ids) == 64  # only the covered full blocks

    back = KVTransferPayload.from_bytes(payload.to_bytes())
    assert back.keys == payload.keys
    assert back.token_ids == payload.token_ids
    assert back.block_size == payload.block_size
    assert back.nbytes() == payload.nbytes() > 0
    for blk_a, blk_b in zip(payload.blocks, back.blocks):
        for la, lb in zip(blk_a, blk_b):
            assert sorted(la) == sorted(lb)
            for name in la:
                np.testing.assert_array_equal(np.asarray(la[name]),
                                              np.asarray(lb[name]))

    # Integrity gate: token ids that do not hash to the claimed chain
    # are refused before any block could land.
    evil = KVTransferPayload(
        token_ids=[9] + payload.token_ids[1:],
        block_size=payload.block_size, quantized=payload.quantized,
        keys=list(payload.keys), blocks=payload.blocks)
    with pytest.raises(ValueError, match="do not match"):
        KVTransferPayload.from_bytes(evil.to_bytes())
    # Truncated payloads are refused too.
    with pytest.raises(Exception):
        KVTransferPayload.from_bytes(payload.to_bytes()[:-3])


# -- pool export/adopt bookkeeping -------------------------------------------

def test_pool_export_pins_and_double_release_raises():
    pool = _pool()
    ids = list(range(70))
    s = pool.allocate(len(ids), token_ids=ids)
    _fill_and_register(pool, s, ids)
    e1 = pool.export_blocks(ids)
    e2 = pool.export_blocks(ids)  # overlapping export: pins nest
    assert e1.blocks == e2.blocks and len(e1.blocks) == 2
    assert all(pool._ref[b] >= 3 for b in e1.blocks)  # seq + 2 exports
    pool.free(s)
    # Pinned blocks survive the owner's free (refcount held by exports).
    assert pool.prefix.lookup(e1.keys[0]) is not None
    pool.release_export(e1)
    with pytest.raises(ValueError, match="already released"):
        pool.release_export(e1)
    pool.release_export(e2)
    assert all(pool._ref[b] == 0 for b in e2.blocks)
    assert pool.prefix.retired_blocks == 2  # back on the LRU, adoptable
    # Short prompt: nothing published -> empty export, trivially safe.
    e3 = pool.export_blocks(list(range(10)))
    assert e3.keys == [] and e3.blocks == []
    pool.release_export(e3)


def test_pool_adopt_roundtrip_reuse_and_layout_gate():
    src, dst = _pool(), _pool()
    ids = list(range(70))
    s = src.allocate(len(ids), token_ids=ids)
    _fill_and_register(src, s, ids)
    _stamp(src)
    export = src.export_blocks(ids)
    payload = build_payload(export, ids, src.block_size, False)
    src.release_export(export)

    stats = dst.adopt_blocks(payload.keys, payload.blocks)
    assert stats == {"adopted": 2, "reused": 0, "skipped": 0}
    # The bytes landed under the right content addresses.
    for i, key in enumerate(payload.keys):
        b = dst.prefix.lookup(key)
        assert b is not None
        for li, layer in enumerate(payload.blocks[i]):
            for name, arr in layer.items():
                np.testing.assert_array_equal(
                    np.asarray(dst.cache[li][name][b]), np.asarray(arr))
    # Idempotent: the same chain transfers at most once.
    again = dst.adopt_blocks(payload.keys, payload.blocks)
    assert again == {"adopted": 0, "reused": 2, "skipped": 0}
    # The adopted chain is a plain prefix hit for admission.
    s2 = dst.allocate(len(ids), token_ids=ids)
    assert dst.lengths[s2] == 64
    dst.free(s2)

    # Layout gate: a payload whose tensor names do not match the arena
    # (e.g. fp blocks into an int8 arena) is refused before mutation.
    qdst = _pool(quantize=True)
    with pytest.raises(ValueError, match="mismatch|names"):
        qdst.adopt_blocks(payload.keys, payload.blocks)
    assert qdst.blocks_in_use == 0


def test_pool_adopt_after_evict_reinstalls():
    src = _pool()
    ids = list(range(70))
    s = src.allocate(len(ids), token_ids=ids)
    _fill_and_register(src, s, ids)
    export = src.export_blocks(ids)
    payload = build_payload(export, ids, src.block_size, False)
    src.release_export(export)

    dst = _pool(num_blocks=3)  # tiny arena: adoption then pressure
    assert dst.adopt_blocks(payload.keys, payload.blocks)["adopted"] == 2
    # Unrelated traffic needs every block -> the adopted chain evicts.
    other = list(range(1000, 1070))
    s1 = dst.allocate(len(other), token_ids=other)
    assert s1 is not None and dst.prefix.evictions >= 1
    assert dst.prefix.lookup(payload.keys[1]) is None
    dst.free(s1)
    # A re-transfer simply re-installs the evicted chain (or its tail).
    stats = dst.adopt_blocks(payload.keys, payload.blocks)
    assert stats["adopted"] >= 1 and stats["skipped"] == 0
    s2 = dst.allocate(len(ids), token_ids=ids)
    assert dst.lengths[s2] == 64


def test_pool_adopt_arena_full_keeps_chain_prefix():
    src = _pool(num_blocks=8, block_size=16)
    ids = list(range(100))  # 6 full 16-token blocks
    s = src.allocate(len(ids), token_ids=ids)
    _fill_and_register(src, s, ids)
    export = src.export_blocks(ids)
    payload = build_payload(export, ids, 16, False)
    src.release_export(export)
    assert payload.num_blocks == 6

    dst = _pool(num_blocks=4, block_size=16)
    stats = dst.adopt_blocks(payload.keys, payload.blocks)
    # Arena smaller than the chain: a contiguous PREFIX lands, the rest
    # is skipped (a chain with holes would never match).
    assert stats["adopted"] == 4 and stats["skipped"] == 2
    assert all(dst.prefix.lookup(k) is not None for k in payload.keys[:4])
    assert all(dst.prefix.lookup(k) is None for k in payload.keys[4:])


# -- engine-level handoff -----------------------------------------------------

def test_engine_kv_handoff_greedy_parity():
    prompt = SHARED + SHARED + "handoff"
    base_eng = _engine(prefix_cache=True, block_size=16)
    base_eng.start()
    try:
        base = base_eng.generate(prompt, max_tokens=16, temperature=0.0,
                                 timeout=300.0)
    finally:
        base_eng.stop()

    pre = _engine(prefix_cache=True, block_size=16, role="prefill").start()
    dec = _engine(prefix_cache=True, block_size=16, role="decode").start()
    try:
        req = pre.submit(prompt, max_tokens=1, prefill_only=True)
        assert req.wait(timeout=300.0)
        assert req.finish_reason == "prefill"
        assert req.result["tokens"] == 0  # prefill-only: nothing sampled
        payload = pre.export_kv(req.prompt_ids)
        assert payload.num_blocks >= 2
        stats = dec.adopt_kv(payload)
        assert stats["adopted"] == payload.num_blocks

        out = dec.generate(prompt, max_tokens=16, temperature=0.0,
                           timeout=300.0)
        assert out["text"] == base["text"]  # greedy parity across the wire
        assert out["tokens"] == base["tokens"]
        assert out["prefix_cached_tokens"] >= 16  # adopted, not recomputed
        assert dec.metrics()["prefix_cache_hits"] >= 1
        assert pre.metrics()["role"] == "prefill"
        # Mismatched geometry is refused at the engine door.
        wrong = KVTransferPayload(
            token_ids=payload.token_ids, block_size=payload.block_size * 2,
            quantized=payload.quantized, keys=payload.keys,
            blocks=payload.blocks)
        with pytest.raises(ValueError, match="block_size"):
            dec.adopt_kv(wrong)
    finally:
        pre.stop()
        dec.stop()


def test_engine_swap_params_mid_request_greedy_identity():
    # Satellite: an fsdp2-sharded checkpoint hot-swaps into a LIVE tp2
    # decode engine with a greedy request straddling the cutover; the
    # weights are value-identical, so the token stream must be too.
    devs = jax.devices()
    fsdp_mesh = build_mesh(SimpleNamespace(mesh={"fsdp": 2}), devs[:2])
    placed = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(
            leaf, NamedSharding(fsdp_mesh, spec)),
        PARAMS, tree_pspecs(PARAMS, fsdp_mesh))
    flat_host = {k: np.asarray(v) for k, v in flatten_dict(placed).items()}

    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/model.safetensors"
        save_safetensors(path, flat_host)
        tp_mesh = build_serve_mesh({"tp": 2}, devices=devs[:2])
        eng = _engine(mesh=tp_mesh, role="decode")
        eng.start()
        try:
            prompt = SHARED + "swap me"
            base = eng.generate(prompt, max_tokens=20, temperature=0.0,
                                timeout=300.0)
            loaded = CheckpointManager.load_params(path, like=PARAMS,
                                                   mesh=tp_mesh)
            req = eng.submit(prompt + " again", max_tokens=20,
                             temperature=0.0)
            deadline = time.monotonic() + 120.0
            while not req.tokens and time.monotonic() < deadline:
                time.sleep(0.005)  # let the request into decode
            version = eng.swap_params(loaded)  # cutover mid-generation
            assert version == 1
            assert req.wait(timeout=300.0) and req.error is None
            # The straddling request finished cleanly on the new weights.
            assert req.result["tokens"] == 20

            post = eng.generate(prompt, max_tokens=20, temperature=0.0,
                                timeout=300.0)
            assert post["text"] == base["text"]  # bit-identical pre/post
            assert eng.metrics()["params_version"] == 1
        finally:
            eng.stop()


# -- fleet membership ---------------------------------------------------------

def test_membership_heartbeat_and_staleness(tmp_path):
    fdir = str(tmp_path / "fleet")
    assert fleet_generation(fdir) == 0
    stop = start_heartbeat(fdir, "http://127.0.0.1:9001", role="prefill",
                           index=0, interval_s=0.05)
    register_replica(fdir, "http://127.0.0.1:9002", role="decode", index=1)
    try:
        view = read_fleet(fdir, stale_after_s=5.0)
        assert view["generation"] == 1
        assert [m["role"] for m in view["members"]] == ["prefill", "decode"]
        assert all(m["alive"] for m in view["members"])

        # Age member 1's stamp far into the past: it reads dead, while
        # the heartbeat keeps member 0 alive through the same window.
        path = str(tmp_path / "fleet" / "members" / "gen_1_p1.json")
        rec = _read_json(path)
        rec["t"] = time.time() - 3600.0
        _atomic_write_json(path, rec)
        time.sleep(0.15)  # >= two heartbeat intervals
        view = read_fleet(fdir, stale_after_s=1.0)
        alive = {m["index"]: m["alive"] for m in view["members"]}
        assert alive == {0: True, 1: False}

        # A new generation makes the old epoch invisible, not just dead.
        register_replica(fdir, "http://127.0.0.1:9003", role="decode",
                         index=0, generation=2)
        view = read_fleet(fdir, stale_after_s=5.0)
        assert view["generation"] == 2 and len(view["members"]) == 1
    finally:
        stop.set()


# -- stub replicas: lifecycle policy without devices --------------------------

class _StubReplica:
    """Minimal HTTP replica: /metrics from a mutable dict, /admin/*
    mutate it, swap bumps params_version (or fails on demand)."""

    def __init__(self, role="decode"):
        self.state = {"queue_depth": 0, "batch_occupancy": 0, "role": role,
                      "draining": False, "params_version": 0,
                      "kv_blocks_free": 64, "kv_num_blocks": 64,
                      "kv_free_watermark": 64}
        self.fail_swap = False
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.rstrip("/") in ("", "/healthz"):
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(200, stub.state)

            def do_POST(self):
                path = self.path.rstrip("/")
                self.rfile.read(
                    int(self.headers.get("Content-Length", "0") or 0))
                if path == "/admin/drain":
                    stub.state["draining"] = True
                    self._reply(200, {"draining": True})
                elif path == "/admin/undrain":
                    stub.state["draining"] = False
                    self._reply(200, {"draining": False})
                elif path == "/admin/swap_weights":
                    if stub.fail_swap:
                        self._reply(500, {"error": "bad checkpoint"})
                        return
                    stub.state["params_version"] += 1
                    self._reply(200, {
                        "swapped": True,
                        "params_version": stub.state["params_version"]})
                else:
                    self._reply(404, {"error": path})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_controller_autoscale_spawn_and_drain():
    d0, d1 = _StubReplica(), _StubReplica()
    router = FleetRouter([], [d0.url])
    spawned, stopped = [], []
    cfg = FleetConfig(scale_up_queue_depth=8, scale_down_idle_ticks=2,
                      min_replicas_per_pool=1, max_replicas_per_pool=2,
                      drain_timeout_s=5.0)
    ctl = FleetController(router, cfg,
                          spawn_fn=lambda role: (spawned.append(role)
                                                 or d1.url),
                          stop_fn=stopped.append)
    try:
        router.poll_once()
        assert ctl.autoscale_tick() == []  # healthy: no action

        d0.state["queue_depth"] = 20  # sustained queueing
        router.poll_once()
        actions = ctl.autoscale_tick()
        assert spawned == ["decode"] and len(router.replicas) == 2
        assert any(a.startswith("spawn decode") for a in actions)
        # At the pool cap: more pressure does not spawn again.
        router.poll_once()
        assert ctl.autoscale_tick() == []

        d0.state["queue_depth"] = 0  # idle again
        router.poll_once()
        assert ctl.autoscale_tick() == []  # tick 1 of 2: patience
        actions = ctl.autoscale_tick()    # tick 2: drain the newest
        assert any(a.startswith("drain decode r1") for a in actions)
        assert stopped == [d1.url]
        assert len(router.replicas) == 1
        assert d1.state["draining"] is True  # told to stop admitting
    finally:
        d0.close()
        d1.close()
        router.stop()


def test_controller_rolling_swap_canary_promotes_each_replica():
    d0, d1 = _StubReplica(), _StubReplica()
    p0 = _StubReplica(role="prefill")
    router = FleetRouter([p0.url], [d0.url, d1.url], canary_fraction=0.5)
    ctl = FleetController(router, FleetConfig())
    router.poll_once()

    # Simulated traffic: deliveries tick every replica's ok counter while
    # the canary window is open (the router normally does this in _pipe).
    stop_traffic = threading.Event()

    def traffic():
        while not stop_traffic.wait(0.01):
            for r in router.replicas.values():
                if r.canary:
                    r.ok_count += 1

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    try:
        out = ctl.rolling_swap(model_path="new.safetensors",
                               canary_requests=3, canary_timeout_s=10.0)
        assert out["failed"] == []
        assert [s["replica"] for s in out["swapped"]] == ["r1", "r2", "r0"]
        assert all(s["canary_ok"] >= 3 for s in out["swapped"])
        assert d0.state["params_version"] == 1
        assert d1.state["params_version"] == 1
        assert p0.state["params_version"] == 1
        assert not any(r.canary for r in router.replicas.values())

        # A swap failure halts the rollout before later replicas touch
        # the bad checkpoint.
        d0.fail_swap = True
        out = ctl.rolling_swap(model_path="worse.safetensors",
                               canary_requests=1, canary_timeout_s=5.0)
        assert [f["replica"] for f in out["failed"]] == ["r1"]
        assert out["swapped"] == []
        assert d1.state["params_version"] == 1  # untouched by the halt
    finally:
        stop_traffic.set()
        t.join(timeout=2.0)
        for s in (d0, d1, p0):
            s.close()
        router.stop()


def test_controller_sync_membership_adopts_and_reaps(tmp_path):
    fdir = str(tmp_path / "fleet")
    d0 = _StubReplica()
    fresh = _StubReplica(role="prefill")
    router = FleetRouter([], [d0.url])
    ctl = FleetController(router, FleetConfig(heartbeat_stale_s=1.0),
                          fleet_dir=fdir)
    try:
        router.poll_once()
        # d0 registered long ago and stopped beating; `fresh` is new.
        register_replica(fdir, d0.url, role="decode", index=0)
        path = str(tmp_path / "fleet" / "members" / "gen_1_p0.json")
        rec = _read_json(path)
        rec["t"] = time.time() - 60.0
        _atomic_write_json(path, rec)
        register_replica(fdir, fresh.url, role="prefill", index=1)

        actions = ctl.tick()
        assert any(a.startswith("adopt") for a in actions)
        assert any(a.startswith("reap") for a in actions)
        by_url = {r.url: r for r in router.replicas.values()}
        assert by_url[fresh.url].role == "prefill"
        assert by_url[d0.url].up is False
        assert by_url[d0.url].last_error == "heartbeat stale"
    finally:
        d0.close()
        fresh.close()
        router.stop()


def test_canary_gate_deterministic_fraction():
    router = FleetRouter(["http://p0"], ["http://d0", "http://d1"],
                         canary_fraction=0.25)
    try:
        router.set_canary("r2", True)
        cands = [router.replicas["r1"], router.replicas["r2"]]
        picks = {}
        for i in range(400):
            tid = f"trace-{i}"
            gated = router._gate_canary(cands, tid)
            assert gated == router._gate_canary(cands, tid)  # deterministic
            picks[tid] = gated[0].canary if gated[0].canary else False
            if not picks[tid]:
                # Ungated requests never see the canary at all.
                assert all(not r.canary for r in gated)
        frac = sum(picks.values()) / len(picks)
        assert 0.15 < frac < 0.35  # ~canary_fraction of traffic
        # Whole pool canary: gating would be an outage, so it is off.
        router.set_canary("r1", True)
        assert router._gate_canary(cands, "any") == cands
    finally:
        router.stop()


# -- end-to-end: HTTP handoff joined under one trace id -----------------------

def _fleet_replica(role):
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    service.engine = _engine(prefix_cache=True, block_size=16, role=role,
                             trace=True).start()
    httpd = serve(service, port=0)
    return service, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_fleet_http_handoff_trace_join_and_drain(tmp_path):
    pre_s, pre_h, pre_url = _fleet_replica("prefill")
    dec_s, dec_h, dec_url = _fleet_replica("decode")
    router = FleetRouter([pre_url], [dec_url], poll_interval_s=0.1,
                         handoff_min_prompt_bytes=32, trace=True)
    from mlx_cuda_distributed_pretraining_tpu.serve.router import (
        serve_router,
    )
    rhttpd = serve_router(router, port=0)
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        prompt = SHARED + SHARED + "fleet e2e"
        req = urllib.request.Request(
            rurl + "/generate",
            data=json.dumps({"prompt": prompt, "max_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300.0) as resp:
            assert resp.status == 200
            out = json.loads(resp.read())
        # The decode replica served it off the transferred chain.
        assert out["tokens"] == 8
        assert out["prefix_cached_tokens"] >= 16
        assert dec_s.engine.metrics()["completed"] == 1
        assert pre_s.engine.metrics()["completed"] == 1  # the prefill leg
        assert router._mc_handoffs.value(outcome="ok") == 1

        # Both replicas' spans + the router's join under ONE trace id,
        # with the kv_transfer span bridging the two request trees.
        files = []
        for name, doc in (("router", router.tracer.chrome_trace()),
                          ("pre", pre_s.engine.tracer.chrome_trace()),
                          ("dec", dec_s.engine.tracer.chrome_trace())):
            path = str(tmp_path / f"{name}.json")
            with open(path, "w") as fh:
                json.dump(doc, fh)
            files.append(path)
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "trace_report", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts", "trace_report.py"))
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        lines = tr.report(files, top=1)
        acct = next(ln for ln in lines if "requests_complete=" in ln)
        assert "requests_complete=1" in acct
        assert "handoffs=1" in acct and "kv_transfers=1" in acct
        assert any(ln.startswith("component=kv_transfer") for ln in lines)
        tree = [ln for ln in lines if "span=kv_transfer" in ln]
        assert tree and "service=serve" in tree[0]

        # Drain the decode replica: it 503s new work, the router sees
        # `draining` on the next poll and unpublishes it.
        urllib.request.urlopen(urllib.request.Request(
            dec_url + "/admin/drain", data=b"{}", method="POST"),
            timeout=10.0)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                dec_url + "/generate",
                data=json.dumps({"prompt": "x", "max_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"}),
                timeout=10.0)
        assert exc.value.code == 503
        router.poll_once()
        rid = next(r.id for r in router.replicas.values()
                   if r.url == dec_url)
        assert router.replicas[rid].state == "draining"
        assert router.replicas[rid] not in router.candidates(None,
                                                             role="decode")
        urllib.request.urlopen(urllib.request.Request(
            dec_url + "/admin/undrain", data=b"{}", method="POST"),
            timeout=10.0)
        router.poll_once()
        assert router.replicas[rid].state == "active"
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        for s, h in ((pre_s, pre_h), (dec_s, dec_h)):
            s.close()
            h.shutdown()
            h.server_close()


import urllib.error  # noqa: E402  (used in the e2e drain assertions)
