"""graftlint gate + rule-behavior tests.

Pure-AST: no jax import, no device work — these run fast on CPU and are
NOT marked slow. The fixtures under tests/lint_fixtures/ are analyzed as
text, never imported.

Two jobs:
  1. Gate the package: the merged tree must produce ZERO non-baselined
     findings, and every baselined finding must carry a real reason.
  2. Pin rule behavior: each rule fires at exact (rule, line) positions
     in its bad fixture, stays silent on its good fixture, and is
     silenced (but counted) by inline suppression.
"""
import json
import os
import subprocess
import sys

import pytest

from mlx_cuda_distributed_pretraining_tpu.analysis import (
    all_rules,
    default_baseline_path,
    lint_file,
    load_baseline,
    run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mlx_cuda_distributed_pretraining_tpu")
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")

EXPECTED_RULE_IDS = {
    "recompile-hazard",
    "rng-reuse",
    "host-sync-in-hot-loop",
    "use-after-donate",
    "tracer-leak",
    "jit-in-loop",
    "time-in-jit",
    "legacy-shard-map-import",
    "monotonic-clock",
}


def _hits(path):
    """(active findings, suppressed findings) for one fixture file."""
    return lint_file(os.path.join(FIXTURES, path))


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- the gate ---------------------------------------------------------------

def test_registry_has_all_rules():
    ids = set(all_rules())
    assert EXPECTED_RULE_IDS <= ids, f"missing rules: {EXPECTED_RULE_IDS - ids}"


def test_package_has_no_new_findings():
    """The CI gate: the merged tree must be clean modulo the baseline."""
    result = run_lint([PKG], baseline=load_baseline(None))
    assert not result.new, "new graftlint findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.new)


def test_every_baseline_entry_has_a_reason():
    entries = load_baseline(None)
    assert entries, "baseline.json should exist and carry the triaged entries"
    for e in entries:
        reason = (e.get("reason") or "").strip()
        assert reason, f"baseline entry without reason: {e}"
        assert "REPLACE with a one-line justification" not in reason, (
            f"placeholder reason left in baseline: {e['path']}:{e['line']}")


def test_baseline_entries_all_still_match():
    """A baseline entry whose finding was fixed should be pruned, not kept."""
    result = run_lint([PKG], baseline=load_baseline(None))
    assert not result.stale_baseline, (
        "stale baseline entries (fix was made — prune them):\n" + "\n".join(
            f"  {e.get('path')}:{e.get('line')}: [{e.get('rule')}]"
            for e in result.stale_baseline))


# -- per-rule fixtures: bad fires at exact lines ----------------------------

@pytest.mark.parametrize("fixture,rule,lines", [
    ("recompile_hazard_bad.py", "recompile-hazard", [8, 15, 25]),
    ("rng_reuse_bad.py", "rng-reuse", [7, 14]),
    ("host_sync_bad.py", "host-sync-in-hot-loop", [15, 23, 33]),
    ("use_after_donate_bad.py", "use-after-donate", [14, 21]),
    ("tracer_leak_bad.py", "tracer-leak", [10, 17]),
    ("jit_in_loop_bad.py", "jit-in-loop", [7]),
    ("jit_in_loop_decorated_bad.py", "jit-in-loop", [11]),
    ("time_in_jit_bad.py", "time-in-jit", [9, 11, 12]),
    ("host_sync_interproc_bad.py", "host-sync-in-hot-loop", [12, 17]),
    ("time_in_jit_interproc_bad.py", "time-in-jit", [9, 14]),
    ("legacy_shard_map_bad.py", "legacy-shard-map-import", [2, 3, 4]),
    ("monotonic_clock_bad.py", "monotonic-clock", [8, 15]),
])
def test_bad_fixture_fires_at_exact_lines(fixture, rule, lines):
    active, _ = _hits(fixture)
    assert _rule_lines(active, rule) == lines, (
        f"{fixture}: expected {rule} at {lines}, got "
        f"{[(f.rule, f.line) for f in active]}")


@pytest.mark.parametrize("fixture", [
    "recompile_hazard_good.py",
    "rng_reuse_good.py",
    "host_sync_good.py",
    "use_after_donate_good.py",
    "tracer_leak_good.py",
    "jit_in_loop_good.py",
    "time_in_jit_good.py",
    "host_sync_interproc_good.py",
    "time_in_jit_interproc_good.py",
    "legacy_shard_map_good.py",
    "monotonic_clock_good.py",
])
def test_good_fixture_is_clean(fixture):
    active, suppressed = _hits(fixture)
    assert not active, [(f.rule, f.line, f.message) for f in active]
    assert not suppressed, "good fixtures must not rely on suppressions"


@pytest.mark.parametrize("fixture,rule,line", [
    ("recompile_hazard_suppressed.py", "recompile-hazard", 7),
    ("rng_reuse_suppressed.py", "rng-reuse", 8),
    ("host_sync_suppressed.py", "host-sync-in-hot-loop", 14),
    ("use_after_donate_suppressed.py", "use-after-donate", 15),
    ("tracer_leak_suppressed.py", "tracer-leak", 9),
    ("jit_in_loop_suppressed.py", "jit-in-loop", 8),
    ("jit_in_loop_decorated_suppressed.py", "jit-in-loop", 12),
    ("time_in_jit_suppressed.py", "time-in-jit", 8),
    ("legacy_shard_map_suppressed.py", "legacy-shard-map-import", 3),
    ("monotonic_clock_suppressed.py", "monotonic-clock", 9),
])
def test_suppression_silences_but_counts(fixture, rule, line):
    active, suppressed = _hits(fixture)
    assert not active, [(f.rule, f.line) for f in active]
    assert [(f.rule, f.line) for f in suppressed] == [(rule, line)]


# -- baseline mechanics -----------------------------------------------------

def test_baseline_absorbs_then_budget_exhausts(tmp_path):
    """One baseline entry absorbs exactly one matching finding."""
    bad = os.path.join(FIXTURES, "jit_in_loop_bad.py")
    active, _ = _hits("jit_in_loop_bad.py")
    entry = {**active[0].to_dict(), "reason": "test entry"}

    absorbed = run_lint([bad], baseline=[entry])
    assert not absorbed.new and len(absorbed.baselined) == 1

    # Same entry against a clean file: reported stale, but never failing.
    clean = os.path.join(FIXTURES, "jit_in_loop_good.py")
    stale = run_lint([clean], baseline=[entry])
    assert not stale.new and len(stale.stale_baseline) == 1


def test_baseline_match_ignores_line_numbers():
    """Moving a grandfathered finding (unrelated edits above it) must not
    break the gate: matching is on (rule, path, message), not line."""
    bad = os.path.join(FIXTURES, "jit_in_loop_bad.py")
    active, _ = _hits("jit_in_loop_bad.py")
    entry = {**active[0].to_dict(), "reason": "test entry", "line": 99999}
    result = run_lint([bad], baseline=[entry])
    assert not result.new and len(result.baselined) == 1


# -- CLI contract -----------------------------------------------------------

def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "mlx_cuda_distributed_pretraining_tpu.analysis.lint",
         *args],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)


def test_cli_exit_zero_on_package():
    proc = _run_cli(PKG)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_exit_one_on_bad_fixture_and_json_shape():
    proc = _run_cli("--format", "json", "--no-baseline",
                    os.path.join(FIXTURES, "host_sync_bad.py"))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["tool"] == "graftlint"
    assert {f["rule"] for f in doc["new"]} == {"host-sync-in-hot-loop"}
    assert sorted(f["line"] for f in doc["new"]) == [15, 23, 33]
    for key in ("baselined", "suppressed", "stale_baseline"):
        assert key in doc


def test_cli_exit_two_on_missing_path():
    proc = _run_cli(os.path.join(FIXTURES, "does_not_exist.py"))
    assert proc.returncode == 2


def test_cli_list_rules_names_every_rule():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in EXPECTED_RULE_IDS:
        assert rule_id in proc.stdout
