"""Automatic prefix caching (serve/prefix_cache.py + paged pool
adoption) and the multi-replica router (serve/router.py).

Parity oracle: ``prefix_cache=off`` is bit-for-bit the pre-cache engine,
so every cache arm asserts token-identical greedy output against it —
adoption, partial-tail recompute, eviction-then-refill, refcounted free,
int8 KV and the spec-decode arm. Router tests run real replica servers
in-process (infer/server.py on port 0)."""

import json
import math
import time
import urllib.error
import urllib.request

import jax
import pytest

from mlx_cuda_distributed_pretraining_tpu.config import DataConfig
from mlx_cuda_distributed_pretraining_tpu.infer.server import (
    InferenceService,
    request_stream,
    serve,
)
from mlx_cuda_distributed_pretraining_tpu.models import llama
from mlx_cuda_distributed_pretraining_tpu.models.llama import LlamaArgs
from mlx_cuda_distributed_pretraining_tpu.serve import (
    BatchEngine,
    EngineConfig,
    PagedKVPool,
    PrefixCache,
    Request,
    Router,
    Scheduler,
    serve_router,
)
from mlx_cuda_distributed_pretraining_tpu.serve.prefix_cache import chain_keys
from mlx_cuda_distributed_pretraining_tpu.tokenizer import TokenizerManager

TOK = TokenizerManager(DataConfig())
ARGS = LlamaArgs(
    vocab_size=TOK.vocab_size, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    max_position_embeddings=128,
)
PARAMS = llama.init_params(jax.random.PRNGKey(0), ARGS)
MAX_LEN = 128


def _engine(**kw):
    cfg = EngineConfig(**{"num_slots": 2, "max_len": MAX_LEN,
                          "prefill_chunk": 16, **kw})
    return BatchEngine(PARAMS, ARGS, TOK, cfg)


def _gen_seq(eng, prompts, max_tokens=24, **kw):
    """Sequential generation (deterministic admission order, so the
    second identical prompt always sees the first one's cached blocks)."""
    eng.start()
    try:
        return [eng.generate(p, max_tokens=max_tokens, temperature=0.0,
                             timeout=300.0, **kw) for p in prompts], \
               eng.metrics()
    finally:
        eng.stop()


# -- prefix cache bookkeeping (no device) -------------------------------------

def test_chain_keys_chain_and_partial_tail():
    ids = list(range(70))
    keys = chain_keys(ids, 32)
    assert len(keys) == 2  # 70 tokens = 2 full blocks + partial tail
    # chained: block 1's key depends on block 0's
    assert chain_keys(ids[:64], 32) == keys
    assert chain_keys([9] + ids[1:], 32)[0] != keys[0]
    # resumable: start_block + parent_key continues the same chain
    assert chain_keys(ids, 32, parent_key=keys[0], start_block=1) == [keys[1]]


def test_prefix_cache_match_register_retire_evict():
    pc = PrefixCache(block_size=4)
    ids = list(range(13))  # 3 full blocks + 1 tail token
    keys = chain_keys(ids, 4)
    assert pc.match(ids) == ([], None)  # cold: nothing cached
    for k, b in zip(keys, (7, 8, 9)):
        assert pc.register(k, b)
    assert not pc.register(keys[0], 55)  # first writer wins
    blocks, last = pc.match(ids)
    assert blocks == [7, 8, 9] and last == keys[2]
    # never the final token: a 12-token prompt adopts only 2 blocks
    assert pc.match(ids[:12])[0] == [7, 8]
    assert pc.match(ids, max_blocks=1)[0] == [7]
    # divergent tail stops the walk at the shared prefix
    assert pc.match(ids[:8] + [99, 99, 99, 99, 0])[0] == [7, 8]
    # retire -> adoptable from the LRU; evict pops oldest and unpublishes
    for b in (7, 8, 9):
        assert pc.retire(b)
    assert pc.retired_blocks == 3
    pc.revive(7)
    assert pc.evict_lru() == 8  # oldest retired (7 was revived)
    assert pc.match(ids)[0] == [7]  # chain broken at the evicted block
    assert pc.evictions == 1
    assert not pc.retire(55)  # unregistered -> plain free list


def test_prefix_cache_counters_never_nan():
    pc = PrefixCache(block_size=4, min_hit_blocks=2)
    assert pc.hit_rate() == 0.0  # fresh: no division by zero
    assert all(math.isfinite(v) for v in pc.stats().values())
    # below min_hit_blocks the match reports nothing
    pc.register(chain_keys(list(range(8)), 4)[0], 3)
    assert pc.match(list(range(9))) == ([], None)
    pc.note_lookup(10, 0)
    pc.note_lookup(10, 8)
    assert pc.hits == 1 and pc.misses == 1
    assert pc.hit_rate() == pytest.approx(8 / 20)


# -- paged pool adoption (no device math, real pool) --------------------------

def _fill_and_register(pool, seq, ids):
    pool.lengths[seq] = len(ids)
    pool.ensure_capacity(seq, len(ids))
    pool.register_upto(seq, ids)


def test_pool_adopts_cached_chain_zero_copy():
    pool = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, block_size=32,
                       num_blocks=8, prefix_cache=True)
    ids = list(range(70))  # 3 blocks (2 full + tail)
    s0 = pool.allocate(len(ids), token_ids=ids)
    assert pool.lengths[s0] == 0 and pool.prefix.misses == 1
    _fill_and_register(pool, s0, ids)
    shared = [int(b) for b in pool.tables[s0][:2]]
    # a second identical prompt adopts the two FULL blocks zero-copy
    s1 = pool.allocate(len(ids), token_ids=ids)
    assert pool.lengths[s1] == 64  # prefill resumes after the adopted KV
    assert [int(b) for b in pool.tables[s1][:2]] == shared
    assert int(pool.tables[s1][2]) not in shared  # fresh tail block
    assert pool.prefix.hits == 1 and pool.prefix.hit_tokens == 64
    # refcounted free: first free keeps the shared blocks live ...
    pool.free(s0)
    assert pool._ref[shared[0]] == 1 and pool.prefix.retired_blocks == 0
    # ... second free retires them to the LRU (still adoptable, counted free)
    pool.free(s1)
    assert pool.prefix.retired_blocks == 2
    assert pool.free_blocks == 8 and pool.blocks_in_use == 0
    s2 = pool.allocate(len(ids), token_ids=ids)
    assert pool.lengths[s2] == 64  # revived straight off the LRU


def test_pool_eviction_unpublishes_and_refuses_without_mutation():
    pool = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, block_size=32,
                       num_blocks=3, prefix_cache=True)
    ids = list(range(70))
    s0 = pool.allocate(len(ids), token_ids=ids)
    _fill_and_register(pool, s0, ids)
    pool.free(s0)  # 2 registered blocks on the LRU + 1 plain free
    assert pool.free_blocks == 3 and pool.prefix.retired_blocks == 2
    # a non-matching 3-block prompt must evict the cached chain
    other = list(range(1000, 1070))
    s1 = pool.allocate(len(other), token_ids=other)
    assert s1 is not None and pool.prefix.evictions >= 1
    pool.free(s1)
    # the evicted chain no longer matches: allocation is a miss again
    s2 = pool.allocate(len(ids), token_ids=ids)
    assert pool.lengths[s2] == 0
    pool.free(s2)
    # refusal gate: adopting retired blocks consumes LRU supply, so a
    # request needing adopted + more fresh than remain must refuse cleanly
    small = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, block_size=32,
                        num_blocks=3, prefix_cache=True)
    s = small.allocate(len(ids), token_ids=ids)
    _fill_and_register(small, s, ids)
    small.free(s)
    used_before = small.blocks_in_use
    # 4 blocks needed: 2 adopted (from LRU) + 2 fresh, but only 1 other
    # block exists -> refuse with no state change
    assert small.allocate(MAX_LEN - 1, token_ids=list(range(127))) is None
    assert small.blocks_in_use == used_before
    assert small.prefix.retired_blocks == 2


def test_pool_growth_preserves_registered_keys():
    pool = PagedKVPool(ARGS, num_seqs=1, max_len=MAX_LEN, block_size=32,
                       num_blocks=4, prefix_cache=True)
    ids = list(range(40))
    s0 = pool.allocate(len(ids), token_ids=ids)
    _fill_and_register(pool, s0, ids)
    key0 = pool.prefix.key_of(int(pool.tables[s0][0]))
    assert key0 is not None
    # decode growth maps more blocks; earlier published keys survive
    assert pool.ensure_capacity(s0, 100)
    assert pool.prefix.key_of(int(pool.tables[s0][0])) == key0
    # and the longer sequence registers as a continuation of the chain
    longer = ids + list(range(40, 96))
    pool.lengths[s0] = 96
    pool.register_upto(s0, longer)
    assert pool.prefix.cached_blocks == 3
    pool.free(s0)
    s1 = pool.allocate(len(longer) + 1, token_ids=longer + [7])
    assert pool.lengths[s1] == 96  # whole generated chain adoptable


# -- engine parity: prefix on == prefix off -----------------------------------

SHARED = "the quick brown fox jumps over the lazy dog again and "
PREFIX_PROMPTS = [SHARED + "one", SHARED + "one", SHARED + "two wide",
                  SHARED + "one"]


def test_prefix_on_off_greedy_parity_and_hit_accounting():
    off, _ = _gen_seq(_engine(prefix_cache=False), PREFIX_PROMPTS)
    on, m = _gen_seq(_engine(prefix_cache=True, block_size=16),
                     PREFIX_PROMPTS)
    for a, b in zip(off, on):
        assert b["text"] == a["text"]
        assert b["tokens"] == a["tokens"]
        assert b["finish_reason"] == a["finish_reason"]
    # repeats adopted the shared prefix (warm hits), firsts missed
    assert m["prefix_cache"] is True
    assert m["prefix_cache_hits"] >= 2
    assert m["prefix_cache_hit_rate"] > 0.0
    assert on[1]["prefix_cached_tokens"] > 0
    assert off[1].get("prefix_cached_tokens", 0.0) == 0.0
    # partial tail: prompt 3 shares blocks with 1 but diverges at the tail
    assert on[2]["prefix_cached_tokens"] < float(
        len(TOK.tokenize(PREFIX_PROMPTS[2])))


def test_prefix_parity_int8_kv():
    off, _ = _gen_seq(_engine(prefix_cache=False, kv_quant=True),
                      PREFIX_PROMPTS[:2], max_tokens=16)
    on, m = _gen_seq(_engine(prefix_cache=True, kv_quant=True,
                             block_size=16), PREFIX_PROMPTS[:2],
                     max_tokens=16)
    assert [o["text"] for o in on] == [o["text"] for o in off]
    assert m["prefix_cache_hits"] >= 1


def test_prefix_parity_spec_decode():
    off, _ = _gen_seq(_engine(prefix_cache=False, spec_draft_len=4),
                      PREFIX_PROMPTS[:2], max_tokens=24)
    on, m = _gen_seq(_engine(prefix_cache=True, spec_draft_len=4,
                             block_size=16), PREFIX_PROMPTS[:2],
                     max_tokens=24)
    assert [o["text"] for o in on] == [o["text"] for o in off]
    assert m["prefix_cache_hits"] >= 1 and m["spec_proposed"] > 0


def test_prefix_parity_eviction_then_refill():
    # Arena so small that caching the first prompt's blocks must be
    # evicted by the second; the third (repeat of the first) refills.
    prompts = [SHARED + "one", "zq " * 30, SHARED + "one"]
    off, _ = _gen_seq(_engine(prefix_cache=False, num_slots=1,
                              num_blocks=4, block_size=32), prompts,
                      max_tokens=12)
    on, m = _gen_seq(_engine(prefix_cache=True, num_slots=1,
                             num_blocks=4, block_size=32), prompts,
                     max_tokens=12)
    assert [o["text"] for o in on] == [o["text"] for o in off]
    assert m["prefix_cache_evictions"] >= 1


# -- satellite: expire on a preempted request ---------------------------------

def test_expired_preempted_request_releases_shared_blocks_once():
    pool = PagedKVPool(ARGS, num_seqs=2, max_len=MAX_LEN, block_size=32,
                       num_blocks=8, prefix_cache=True)
    sched = Scheduler(max_queue=4)
    ids = list(range(70))
    r0 = Request(ids, max_tokens=4)
    r1 = Request(ids, max_tokens=4, deadline_s=30.0)
    sched.submit(r0)
    sched.admit(pool)
    _fill_and_register(pool, r0.slot, ids)
    sched.finish(pool, r0, "stop")
    sched.submit(r1)
    sched.admit(pool)  # r1 adopts r0's retired chain
    assert r1.prefilled == 64 and r1.cached_tokens == 64
    shared = int(pool.tables[r1.slot][0])
    assert pool._ref[shared] == 1
    used = pool.blocks_in_use
    # preemption releases the blocks (shared ones retire, ref 1 -> 0)...
    sched.preempt(pool, r1)
    assert r1.slot is None and pool.blocks_in_use < used
    assert pool._ref[shared] == 0
    # ...and the deadline lapsing in the queue must NOT free them again
    evicted = sched.expire(pool, now=time.monotonic() + 60.0)
    assert evicted == [r1]
    assert r1.finish_reason == "deadline" and r1.error  # -> HTTP 504
    assert pool._ref[shared] == 0 and pool.blocks_in_use == 0
    assert pool.free_blocks == 8
    # the retired chain survives the eviction and is still adoptable
    s = pool.allocate(len(ids), token_ids=ids)
    assert pool.lengths[s] == 64


# -- satellite: metrics well-defined before any traffic -----------------------

def test_fresh_engine_metrics_no_traffic_no_nan():
    eng = _engine(spec_draft_len=4)  # never started, zero traffic
    m = eng.metrics()
    assert m["spec_acceptance_rate"] == 0.0  # no division by zero
    assert m["prefix_cache"] is True
    for k in ("prefix_cache_hits", "prefix_cache_misses",
              "prefix_cache_evictions", "prefix_cache_hit_rate"):
        assert m[k] == 0
    for v in m.values():
        if isinstance(v, float):
            assert math.isfinite(v)
    # gauges/counters exist in the registry snapshot pre-traffic too
    snap = eng.metrics_registry.snapshot()
    assert "serve_prefix_cache_hit_rate" in snap
    assert "serve_spec_acceptance_rate" in snap
    # slotted backend reports no prefix cache but stays NaN-free
    m2 = _engine(kv_backend="slotted").metrics()
    assert m2["prefix_cache"] is False


# -- router -------------------------------------------------------------------

def _post(url, body, timeout=300.0):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _replica(**kw):
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    service.engine = _engine(**kw).start()
    httpd = serve(service, port=0)
    return service, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_router_ring_is_deterministic_and_affine():
    r = Router(["http://a", "http://b", "http://c"])
    key = r.routing_key({"prompt": SHARED + "xyz"})
    assert key == r.routing_key({"prompt": SHARED + "different tail"})
    assert key is not None
    picks = {r._ring.lookup(key) for _ in range(8)}
    assert len(picks) == 1  # stable
    skey = r.routing_key({"prompt": "anything", "session": "s1"})
    assert skey == r.routing_key({"prompt": "else", "session": "s1"})
    assert skey != r.routing_key({"prompt": "else", "session": "s2"})


def test_router_two_replicas_streams_and_survives_death():
    sa, ha, ua = _replica()
    sb, hb, ub = _replica()
    router = Router([ua, ub], poll_interval_s=0.1, retries=2)
    rhttpd = serve_router(router, port=0)
    url = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        status, out = _post(url, {"prompt": SHARED + "route me",
                                  "max_tokens": 6})
        assert status == 200 and out["engine"] == "batch"
        # session affinity: every request of one session lands on ONE
        # replica (completed counters move on exactly one engine)
        base = [sa.engine.metrics()["completed"],
                sb.engine.metrics()["completed"]]
        for i in range(3):
            _post(url, {"prompt": f"turn {i}", "max_tokens": 4,
                        "session": "conv-1"})
        moved = [sa.engine.metrics()["completed"] - base[0],
                 sb.engine.metrics()["completed"] - base[1]]
        assert sorted(moved) == [0, 3]
        # streaming through the router: token events then the summary
        events = list(request_stream(url, SHARED + "stream it",
                                     max_tokens=5))
        assert events[-1].get("done") is True
        deltas = "".join(e.get("text", "") for e in events[:-1])
        assert deltas == events[-1]["text"]
        assert len(events) - 1 == events[-1]["tokens"]
        # kill one replica mid-service: requests keep completing
        dead = sa if moved[0] else sb
        dead.close()
        (ha if dead is sa else hb).shutdown()
        (ha if dead is sa else hb).server_close()
        for i in range(3):
            status, out = _post(url, {"prompt": f"turn {i}", "max_tokens": 4,
                                      "session": "conv-1"})
            assert status == 200
        assert router.health()["replicas_up"] == 1
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        for s, h in ((sa, ha), (sb, hb)):
            try:
                s.close()
                h.shutdown()
                h.server_close()
            except Exception:  # noqa: BLE001 - one pair already closed
                pass


def test_router_backpressure_propagates_429_with_retry_after():
    service = InferenceService(PARAMS, ARGS, TOK, run_name="tiny")
    service.engine = _engine(max_queue=1)  # engine NOT started
    httpd = serve(service, port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    router = Router([url], poll_interval_s=30.0)
    rhttpd = serve_router(router, port=0)
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        service.engine.submit("fill", max_tokens=4)  # queue now full
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(rurl, {"prompt": "overflow", "max_tokens": 4}, timeout=60.0)
        assert exc.value.code == 429
        assert int(exc.value.headers["Retry-After"]) >= 1
    finally:
        rhttpd.shutdown()
        rhttpd.server_close()
        router.stop()
        httpd.shutdown()
        httpd.server_close()
        service.close()


def test_replica_sse_stream_matches_buffered_result():
    service, httpd, url = _replica(prefix_cache=True, block_size=16)
    try:
        _, buffered = _post(url, {"prompt": SHARED + "sse", "max_tokens": 6,
                                  "seed": 0})
        events = list(request_stream(url, SHARED + "sse", max_tokens=6,
                                     seed=0))
        final = events[-1]
        assert final.get("done") is True
        assert final["text"] == buffered["text"]
        assert final["prefix_cached_tokens"] >= 0.0
        assert all("token" in e for e in events[:-1])
    finally:
        httpd.shutdown()
        httpd.server_close()
        service.close()


def test_router_stale_scrape_vs_connection_refused():
    # A hung /metrics (connect succeeds, response never comes) must NOT
    # mark the replica down — stats go stale and routing continues on
    # the last-known load; only `stale_down_after` consecutive slow
    # scrapes declare it down. A refused connection is down immediately.
    import socket

    hung = socket.socket()
    hung.bind(("127.0.0.1", 0))
    hung.listen(8)  # backlog completes the TCP handshake; never accept
    hung_url = f"http://127.0.0.1:{hung.getsockname()[1]}"
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_url = f"http://127.0.0.1:{probe.getsockname()[1]}"
    probe.close()  # nothing listens here any more -> refused

    router = Router([hung_url, dead_url], poll_interval_s=30.0,
                    scrape_timeout_s=0.2, stale_down_after=3)
    try:
        hung_r = next(r for r in router.replicas.values()
                      if r.url == hung_url)
        dead_r = next(r for r in router.replicas.values()
                      if r.url == dead_url)
        router.poll_once()
        assert dead_r.up is False          # refused -> down at once
        assert dead_r.stale is False
        assert hung_r.up is True           # slow -> stale, still routable
        assert hung_r.stale is True
        assert hung_r.state == "stale"
        assert "stale" in hung_r.last_error
        assert hung_r in router.candidates(None)
        router.poll_once()
        assert hung_r.up is True           # 2 of 3: still tolerated
        router.poll_once()
        assert hung_r.up is False          # 3rd consecutive: give up
        assert hung_r not in router.candidates(None)
    finally:
        router.stop()
        hung.close()
