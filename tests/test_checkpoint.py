import json
import os

import ml_dtypes
import numpy as np

from mlx_cuda_distributed_pretraining_tpu.checkpoint import (
    CheckpointManager,
    load_safetensors,
    save_safetensors,
)
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict, unflatten_dict


def test_safetensors_roundtrip(tmp_path):
    path = str(tmp_path / "t.safetensors")
    tensors = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b.c": np.ones((2, 2), dtype=ml_dtypes.bfloat16),
        "d": np.array([1, 2, 3], dtype=np.int64),
    }
    save_safetensors(path, tensors, metadata={"format": "pt"})
    loaded, meta = load_safetensors(path)
    assert meta["format"] == "pt"
    assert set(loaded) == set(tensors)
    for k in tensors:
        assert loaded[k].dtype == tensors[k].dtype
        np.testing.assert_array_equal(np.asarray(loaded[k], np.float64), np.asarray(tensors[k], np.float64))


def test_safetensors_matches_external_reader(tmp_path):
    """Cross-check our writer against the `safetensors` pip package if present."""
    try:
        from safetensors.numpy import load_file
    except ImportError:
        return
    path = str(tmp_path / "t.safetensors")
    tensors = {"w": np.random.randn(4, 5).astype(np.float32)}
    save_safetensors(path, tensors)
    ext = load_file(path)
    np.testing.assert_array_equal(ext["w"], tensors["w"])


def test_flatten_unflatten():
    tree = {"layers": [{"w": 1, "b": 2}, {"w": 3}], "head": {"w": 4}}
    flat = flatten_dict(tree)
    assert flat["layers.0.w"] == 1 and flat["head.w"] == 4
    nested = unflatten_dict(flat)
    assert nested["layers"]["0"]["b"] == 2


def test_checkpoint_roundtrip(tmp_path):
    run_dir = CheckpointManager.setup_run_directory(str(tmp_path), "run1")
    mgr = CheckpointManager(run_dir)
    params = {"emb": np.random.randn(8, 4).astype(np.float32), "layers": [{"w": np.ones((4, 4), np.float32)}]}
    opt_state = {"mu": {"emb": np.zeros((8, 4), np.float32)}, "count": np.int32(5)}
    mgr.save(100, params, opt_state, {"step": 100, "total_tokens": 12345})

    p2, o2, ts = mgr.load(100, like_params=params, like_opt_state=opt_state)
    np.testing.assert_array_equal(p2["emb"], params["emb"])
    np.testing.assert_array_equal(p2["layers"][0]["w"], params["layers"][0]["w"])
    assert ts["total_tokens"] == 12345
    assert int(o2["count"]) == 5

    # metadata ledger appended
    with open(os.path.join(run_dir, "metadata.json")) as f:
        ledger = json.load(f)
    assert ledger["checkpoints"][0]["step"] == 100
    assert mgr.latest_step() == "100"


def test_overwrite_guard(tmp_path):
    CheckpointManager.setup_run_directory(str(tmp_path), "r")
    try:
        CheckpointManager.setup_run_directory(str(tmp_path), "r", overwrite=False)
        assert False
    except ValueError:
        pass
    CheckpointManager.setup_run_directory(str(tmp_path), "r", overwrite=True)


def test_async_save_matches_blocking(tmp_path):
    """Async interval saves write the same triplet as blocking saves, in
    FIFO order, and wait() drains them; a blocking save after async ones
    preserves ledger order."""
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import CheckpointManager

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    opt = {"m": np.ones((3, 4), np.float32), "count": 7}

    for step in (10, 20):
        mgr.save(step, {"w": params["w"] + step}, opt,
                 {"step": step, "total_tokens": step * 5}, blocking=False)
    mgr.save("final", {"w": params["w"] + 99}, opt, {"step": 30})  # blocking
    mgr.wait()

    for step, off in ((10, 10), (20, 20), ("final", 99)):
        loaded, lopt, tstate = mgr.load(step, like_params=params, like_opt_state=opt)
        np.testing.assert_array_equal(loaded["w"], params["w"] + off)
        assert lopt["count"] == 7
    with open(os.path.join(run, "metadata.json")) as f:
        ledger = json.load(f)
    assert [e["step"] for e in ledger["checkpoints"]] == [10, 20, "final"]


def test_async_save_error_surfaces(tmp_path):
    """A failed background write raises on the next save/wait instead of
    being silently dropped."""
    import numpy as np
    import pytest

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import CheckpointManager

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.ones((2, 2), np.float32)}
    mgr.save(1, params, blocking=False)
    mgr.wait()
    # make the checkpoint dir unwritable-by-rename: replace it with a file
    import shutil

    shutil.rmtree(os.path.join(run, "checkpoints"))
    with open(os.path.join(run, "checkpoints"), "w") as f:
        f.write("not a dir")
    mgr.save(2, params, blocking=False)
    with pytest.raises(RuntimeError, match="background checkpoint write failed"):
        mgr.wait()
