import json
import os

import ml_dtypes
import numpy as np

from mlx_cuda_distributed_pretraining_tpu.checkpoint import (
    CheckpointManager,
    load_safetensors,
    save_safetensors,
)
from mlx_cuda_distributed_pretraining_tpu.utils.tree import flatten_dict, unflatten_dict


def test_safetensors_roundtrip(tmp_path):
    path = str(tmp_path / "t.safetensors")
    tensors = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b.c": np.ones((2, 2), dtype=ml_dtypes.bfloat16),
        "d": np.array([1, 2, 3], dtype=np.int64),
    }
    save_safetensors(path, tensors, metadata={"format": "pt"})
    loaded, meta = load_safetensors(path)
    assert meta["format"] == "pt"
    assert set(loaded) == set(tensors)
    for k in tensors:
        assert loaded[k].dtype == tensors[k].dtype
        np.testing.assert_array_equal(np.asarray(loaded[k], np.float64), np.asarray(tensors[k], np.float64))


def test_safetensors_matches_external_reader(tmp_path):
    """Cross-check our writer against the `safetensors` pip package if present."""
    try:
        from safetensors.numpy import load_file
    except ImportError:
        return
    path = str(tmp_path / "t.safetensors")
    tensors = {"w": np.random.randn(4, 5).astype(np.float32)}
    save_safetensors(path, tensors)
    ext = load_file(path)
    np.testing.assert_array_equal(ext["w"], tensors["w"])


def test_flatten_unflatten():
    tree = {"layers": [{"w": 1, "b": 2}, {"w": 3}], "head": {"w": 4}}
    flat = flatten_dict(tree)
    assert flat["layers.0.w"] == 1 and flat["head.w"] == 4
    nested = unflatten_dict(flat)
    assert nested["layers"]["0"]["b"] == 2


def test_checkpoint_roundtrip(tmp_path):
    run_dir = CheckpointManager.setup_run_directory(str(tmp_path), "run1")
    mgr = CheckpointManager(run_dir)
    params = {"emb": np.random.randn(8, 4).astype(np.float32), "layers": [{"w": np.ones((4, 4), np.float32)}]}
    opt_state = {"mu": {"emb": np.zeros((8, 4), np.float32)}, "count": np.int32(5)}
    mgr.save(100, params, opt_state, {"step": 100, "total_tokens": 12345})

    p2, o2, ts = mgr.load(100, like_params=params, like_opt_state=opt_state)
    np.testing.assert_array_equal(p2["emb"], params["emb"])
    np.testing.assert_array_equal(p2["layers"][0]["w"], params["layers"][0]["w"])
    assert ts["total_tokens"] == 12345
    assert int(o2["count"]) == 5

    # metadata ledger appended
    with open(os.path.join(run_dir, "metadata.json")) as f:
        ledger = json.load(f)
    assert ledger["checkpoints"][0]["step"] == 100
    assert mgr.latest_step() == "100"


def test_overwrite_guard(tmp_path):
    CheckpointManager.setup_run_directory(str(tmp_path), "r")
    try:
        CheckpointManager.setup_run_directory(str(tmp_path), "r", overwrite=False)
        assert False
    except ValueError:
        pass
    CheckpointManager.setup_run_directory(str(tmp_path), "r", overwrite=True)


def test_async_save_matches_blocking(tmp_path):
    """Async interval saves write the same triplet as blocking saves, in
    FIFO order, and wait() drains them; a blocking save after async ones
    preserves ledger order."""
    import numpy as np

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import CheckpointManager

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    opt = {"m": np.ones((3, 4), np.float32), "count": 7}

    for step in (10, 20):
        mgr.save(step, {"w": params["w"] + step}, opt,
                 {"step": step, "total_tokens": step * 5}, blocking=False)
    mgr.save("final", {"w": params["w"] + 99}, opt, {"step": 30})  # blocking
    mgr.wait()

    for step, off in ((10, 10), (20, 20), ("final", 99)):
        loaded, lopt, tstate = mgr.load(step, like_params=params, like_opt_state=opt)
        np.testing.assert_array_equal(loaded["w"], params["w"] + off)
        assert lopt["count"] == 7
    with open(os.path.join(run, "metadata.json")) as f:
        ledger = json.load(f)
    assert [e["step"] for e in ledger["checkpoints"]] == [10, 20, "final"]


def test_async_save_error_surfaces(tmp_path):
    """A failed background write raises on the next save/wait instead of
    being silently dropped."""
    import numpy as np
    import pytest

    from mlx_cuda_distributed_pretraining_tpu.checkpoint.manager import CheckpointManager

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.ones((2, 2), np.float32)}
    mgr.save(1, params, blocking=False)
    mgr.wait()
    # make the checkpoint dir unwritable-by-rename: replace it with a file
    import shutil

    shutil.rmtree(os.path.join(run, "checkpoints"))
    with open(os.path.join(run, "checkpoints"), "w") as f:
        f.write("not a dir")
    mgr.save(2, params, blocking=False)
    with pytest.raises(RuntimeError, match="background checkpoint write failed"):
        mgr.wait()


def test_blocking_save_writes_before_raising_stale_error(tmp_path):
    """A failed BACKGROUND write must not abort a later blocking save (the
    final/preemption checkpoint): the blocking write lands on disk first,
    then the stale error surfaces (ADVICE r3)."""
    import shutil

    import pytest

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.ones((2, 2), np.float32)}
    mgr.save(1, params, blocking=False)
    mgr.wait()
    # sabotage the dir so the NEXT background write fails ...
    shutil.rmtree(os.path.join(run, "checkpoints"))
    with open(os.path.join(run, "checkpoints"), "w") as f:
        f.write("not a dir")
    mgr.save(2, params, blocking=False)
    import time

    for _ in range(100):  # let the writer consume and fail
        if mgr._write_error is not None:
            break
        time.sleep(0.05)
    # ... then repair it and take the blocking "preemption" save
    os.remove(os.path.join(run, "checkpoints"))
    os.makedirs(os.path.join(run, "checkpoints"))
    with pytest.raises(RuntimeError, match="was written"):
        mgr.save(3, params, blocking=True)
    model_path, _, _ = mgr.paths_for_step(3)
    assert os.path.exists(model_path)
    loaded, _ = load_safetensors(model_path)
    np.testing.assert_array_equal(loaded["w"], params["w"])


# --- async-writer failure paths (fault-injected) ---------------------------

def test_stale_background_write_error_type_and_semantics(tmp_path):
    """The stale error is its own type (StaleBackgroundWriteError), and its
    contract holds: the blocking save that surfaced it DID land, manifest
    included, so an exit path catching exactly this type loses nothing."""
    import pytest

    from mlx_cuda_distributed_pretraining_tpu.checkpoint import (
        StaleBackgroundWriteError,
        faults,
    )

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.ones((2, 2), np.float32)}
    try:
        faults.inject("model", "enospc", match="step_2")
        mgr.save(2, params, blocking=False)
        if mgr._writer is not None:
            mgr._queue.join()  # let the background write consume and fail
        with pytest.raises(StaleBackgroundWriteError) as exc:
            mgr.save("final", params, blocking=True)
        assert issubclass(StaleBackgroundWriteError, RuntimeError)
        assert "ENOSPC" in str(exc.value) or "No space" in str(exc.value)
        # the final save is complete and verified despite the raise
        ok, reason = mgr.verify("final")
        assert ok, reason
        assert mgr.latest_complete_step() == "final"
        # the stale error is consumed: a later wait() is clean
        mgr.wait()
    finally:
        faults.reset()


def test_async_backpressure_blocks_at_two_in_flight(tmp_path):
    """queue maxsize=1 bounds live host snapshots at two: with one write
    blocked in the writer thread and one payload queued, a third save()
    must block on put() until the writer drains — that back-pressure is
    the memory bound for multi-GB checkpoints."""
    import threading
    import time

    from mlx_cuda_distributed_pretraining_tpu.checkpoint import faults

    run = str(tmp_path / "run")
    os.makedirs(os.path.join(run, "checkpoints"))
    mgr = CheckpointManager(run)
    params = {"w": np.ones((2, 2), np.float32)}
    gate = threading.Event()
    try:
        faults.inject("model", "block", match="step_1", event=gate)
        mgr.save(1, params, blocking=False)   # writer thread parks on gate
        mgr.save(2, params, blocking=False)   # fills the queue slot
        third_done = threading.Event()

        def third():
            mgr.save(3, params, blocking=False)
            third_done.set()

        t = threading.Thread(target=third)
        t.start()
        time.sleep(0.3)
        assert not third_done.is_set(), "third save should block on put()"
        gate.set()
        t.join(timeout=30)
        assert third_done.is_set()
        mgr.wait()
    finally:
        faults.reset()
        gate.set()
    # FIFO drain: all three landed, in order, each fully manifested
    with open(os.path.join(run, "metadata.json")) as f:
        ledger = json.load(f)
    assert [e["step"] for e in ledger["checkpoints"]] == [1, 2, 3]
    for step in (1, 2, 3):
        ok, reason = mgr.verify(step)
        assert ok, (step, reason)


# --- safetensors adversarial edges (VERDICT r3 next #7) --------------------

def _roundtrip(tmp_path, tensors, name="x.safetensors", metadata=None):
    path = str(tmp_path / name)
    save_safetensors(path, tensors, metadata=metadata)
    return path, load_safetensors(path)


def test_safetensors_all_dtypes_roundtrip(tmp_path):
    """Every dtype in the codec table survives bit-exactly."""
    rng = np.random.default_rng(0)
    tensors = {
        "f64": rng.standard_normal((3, 2)).astype(np.float64),
        "f32": rng.standard_normal((2, 3)).astype(np.float32),
        "f16": rng.standard_normal((4,)).astype(np.float16),
        "bf16": rng.standard_normal((5,)).astype(ml_dtypes.bfloat16),
        "f8_e4m3": rng.standard_normal((6,)).astype(ml_dtypes.float8_e4m3fn),
        "f8_e5m2": rng.standard_normal((6,)).astype(ml_dtypes.float8_e5m2),
        "i64": np.array([-(2**62), 2**62], dtype=np.int64),
        "i32": np.array([-(2**31), 2**31 - 1], dtype=np.int32),
        "i16": np.array([-(2**15), 2**15 - 1], dtype=np.int16),
        "i8": np.array([-128, 127], dtype=np.int8),
        "u8": np.array([0, 255], dtype=np.uint8),
        "u16": np.array([0, 2**16 - 1], dtype=np.uint16),
        "u32": np.array([0, 2**32 - 1], dtype=np.uint32),
        "u64": np.array([0, 2**64 - 1], dtype=np.uint64),
        "bool": np.array([True, False, True]),
    }
    _, (loaded, _) = _roundtrip(tmp_path, tensors)
    assert set(loaded) == set(tensors)
    for k, v in tensors.items():
        assert loaded[k].dtype == v.dtype, k
        assert loaded[k].tobytes() == np.ascontiguousarray(v).tobytes(), k


def test_safetensors_zero_size_and_scalar(tmp_path):
    """Zero-element tensors (any position of the 0 dim) and 0-d scalars."""
    tensors = {
        "empty1d": np.zeros((0,), np.float32),
        "empty_mid": np.zeros((3, 0, 2), np.float32),
        "scalar": np.array(3.5, dtype=np.float32),
        "normal": np.ones((2,), np.float32),
    }
    _, (loaded, _) = _roundtrip(tmp_path, tensors)
    assert loaded["empty1d"].shape == (0,)
    assert loaded["empty_mid"].shape == (3, 0, 2)
    assert loaded["scalar"].shape == () and float(loaded["scalar"]) == 3.5


def test_safetensors_noncontiguous_and_bigendian_input(tmp_path):
    """Transposed views and big-endian arrays are normalized on write."""
    base = np.arange(12, dtype=np.float32).reshape(3, 4)
    be = np.arange(6, dtype=">f4").reshape(2, 3)  # big-endian
    tensors = {"t": base.T, "sliced": base[:, 1::2], "be": be.astype(np.float32)}
    _, (loaded, _) = _roundtrip(tmp_path, tensors)
    np.testing.assert_array_equal(loaded["t"], base.T)
    np.testing.assert_array_equal(loaded["sliced"], base[:, 1::2])
    np.testing.assert_array_equal(loaded["be"], be.astype(np.float32))


def test_safetensors_unicode_metadata_and_names(tmp_path):
    tensors = {"层.0.权重": np.ones((2,), np.float32)}
    _, (loaded, meta) = _roundtrip(
        tmp_path, tensors, metadata={"描述": "模型", "emoji": "🧪"})
    assert "层.0.权重" in loaded
    assert meta["描述"] == "模型" and meta["emoji"] == "🧪"


def test_safetensors_truncated_file_raises(tmp_path):
    """A truncated body must raise, not return silently-wrong tensors."""
    import pytest

    path = str(tmp_path / "t.safetensors")
    save_safetensors(path, {"w": np.arange(1000, dtype=np.float32)})
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) - 100])
    with pytest.raises(Exception):
        load_safetensors(path)


def test_safetensors_cross_package_both_directions(tmp_path):
    """Ours -> safetensors-pip reader AND safetensors-pip writer -> ours,
    over the adversarial dtype/shape set the pip package supports."""
    try:
        from safetensors.numpy import load_file, save_file
    except ImportError:
        return
    rng = np.random.default_rng(1)
    tensors = {
        "f32": rng.standard_normal((4, 5)).astype(np.float32),
        "f16": rng.standard_normal((3,)).astype(np.float16),
        "i8": np.array([-128, 127], np.int8),
        "u64": np.array([2**64 - 1], np.uint64),
        "bool": np.array([True, False]),
        "empty": np.zeros((0, 7), np.float32),
        "scalar": np.array(1.25, np.float32),
    }
    ours = str(tmp_path / "ours.safetensors")
    save_safetensors(ours, tensors)
    ext = load_file(ours)
    assert set(ext) == set(tensors)
    for k in tensors:
        np.testing.assert_array_equal(ext[k], tensors[k])

    theirs = str(tmp_path / "theirs.safetensors")
    save_file(tensors, theirs)
    loaded, _ = load_safetensors(theirs)
    assert set(loaded) == set(tensors)
    for k in tensors:
        assert loaded[k].dtype == tensors[k].dtype
        np.testing.assert_array_equal(loaded[k], tensors[k])
