#!/usr/bin/env python
"""Root CLI shim: ``python generate.py --run <name> --prompt "..."``."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mlx_cuda_distributed_pretraining_tpu.infer.cli import main

if __name__ == "__main__":
    main()
