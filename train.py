#!/usr/bin/env python
"""Root CLI shim: ``python train.py --config configs/model-config-sample.yaml``
(reference keeps the same entry point at its repo root)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mlx_cuda_distributed_pretraining_tpu.train.trainer import main

if __name__ == "__main__":
    main()
