"""Flex attention: programmable masks/score mods on the flash kernel.

The reference's FlexAttention applies ``score_mod``/``mask_mod`` via
quadruple-nested Python loops over (batch, head, q, kv) (reference:
models/attention/flex_attention.py:220-275 — O(B·H·S²) Python calls), and
builds block masks by sampling block midpoints (:90-138). Here:

- mods are **traceable functions of index lattices** traced directly into
  the Pallas flash kernel (ops/flash_attention.py) — same tiling, online
  softmax and custom VJP as the named fast paths;
- named mask types (causal / sliding_window / prefix_lm) get exact
  block-sparsity plans; arbitrary mask mods run the full tile grid with the
  mask applied in-tile (always exact, never sampled).

Kernel-style score mods have signature ``(scores, q_idx, kv_idx, head) ->
scores``; builders below cover ALiBi and tanh soft-capping.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional

import jax.numpy as jnp

from . import masks as M
from .flash_attention import flash_attention

KernelScoreMod = Callable  # (scores[bq,bkv], row, col, head) -> scores


@lru_cache(maxsize=None)
def alibi_score_fn(num_heads: int) -> KernelScoreMod:
    def fn(s, row, col, head):
        # slope_h = 2^(-8(h+1)/H) computed arithmetically — Pallas kernels
        # cannot capture constant arrays, and this matches M.alibi_slopes.
        slope = jnp.exp2(-8.0 * (jnp.asarray(head, jnp.float32) + 1.0) / num_heads)
        return s - slope * jnp.abs(row - col).astype(jnp.float32)

    fn._d_score = None  # additive: d(mod)/ds == 1
    return fn


@lru_cache(maxsize=None)
def soft_cap_score_fn(cap: float) -> KernelScoreMod:
    def fn(s, row, col, head):
        return cap * jnp.tanh(s / cap)

    def d_score(s, row, col, head):
        t = jnp.tanh(s / cap)
        return 1.0 - t * t

    fn._d_score = d_score  # non-additive: backward needs the Jacobian
    return fn


def kernel_score_mod(kind: Optional[str], num_heads: int, soft_cap: float) -> Optional[KernelScoreMod]:
    """Single dispatch point for config-named score mods (used by
    models/llama.py's flex path)."""
    if kind == "alibi":
        return alibi_score_fn(num_heads)
    if kind == "soft_cap":
        return soft_cap_score_fn(float(soft_cap))
    return None


def _plan_for(mask_mod) -> tuple:
    """Exact block-sparsity plan: named builders (ops/masks.py) carry a
    ``_plan`` tag; arbitrary mods run the full tile grid (exact, in-tile
    masking)."""
    if mask_mod is None:
        return ("full", 0, 0)
    return getattr(mask_mod, "_plan", ("full", 0, 0))


def flex_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask_mod: Optional[Callable] = None,
    score_mod: Optional[KernelScoreMod] = None,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_kv: int = 1024,
) -> jnp.ndarray:
    """[B, S, H, D] layout. ``mask_mod(q_idx, kv_idx) -> bool`` (True =
    attend); ``score_mod(scores, q_idx, kv_idx, head)``."""
    mask_type, window, prefix = _plan_for(mask_mod)
    return flash_attention(
        q, k, v,
        mask_type=mask_type, window_size=window, prefix_len=prefix,
        scale=scale, block_q=block_q, block_kv=block_kv,
        mask_fn=mask_mod, score_fn=score_mod,
    )
