"""Grouped expert matmuls (``gmm``) for dropless MoE dispatch.

A grouped GEMM multiplies a token-sorted activation matrix ``x [T, N_in]``
against stacked per-expert weights ``w [E, N_in, N_out]``: rows
``[offset_e, offset_{e+1})`` of ``x`` hit expert ``e``'s weight. This is the
MegaBlocks formulation (Gale et al., 2022): routing becomes a sort + two
gathers and the expert FFN becomes three grouped GEMMs, so no token is ever
dropped and no dispatch one-hots are materialized.

Two backends behind one differentiable entry point:

- ``pallas`` — a tiled TPU kernel. Row tiles of ``block_t`` map onto expert
  weight blocks through a scalar-prefetch ``tile → expert`` table, so the
  MXU only ever touches the experts that actually received tokens. Backward
  is a custom VJP: dX is a gmm against transposed weights, dW is a
  per-group accumulation kernel (``tgmm``) that revisits each expert's
  output block across that expert's row tiles. Runs under Pallas interpret
  mode off-TPU, so tier-1 CPU tests exercise the same kernel code.
- ``blocked`` — the kernel's tiling expressed as plain XLA ops: reshape the
  tile-aligned buffer to ``[n_tiles, block_t, K]``, gather each tile's
  expert weight through the same ``tile_experts`` table, one batched
  matmul. Differentiates itself (dW is XLA's scatter-add through the
  gather). Default off-TPU: interpret-mode Pallas is an emulator, and
  ``jax.lax.ragged_dot`` lowers to a serial row walk on CPU (~10x slower
  than the equivalent dense matmul, measured) — the batched form keeps the
  padded-buffer overhead (~T_buf/T) as the only cost over dense.
- ``ragged`` — ``jax.lax.ragged_dot``, which XLA lowers natively on every
  backend and differentiates itself; the reference semantics the other
  two backends are tested against.

Contract shared by both backends (the dispatcher in models/moe.py
guarantees it): ``group_sizes`` must each be a multiple of ``block_t`` so a
row tile never straddles two experts, and rows inside a group beyond the
real token count are zero padding. Rows past ``sum(group_sizes)`` are
compute-garbage tiles the caller must never read back.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - pltpu imports fine on CPU jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "DEFAULT_BLOCK_T",
    "gmm",
    "pick_block_t",
    "round_up",
    "tile_experts",
]

# Row-tile height and output-column tile width. 128 matches the MXU systolic
# array; off-TPU the values only shape the dispatch padding.
DEFAULT_BLOCK_T = int(os.environ.get("GMM_BLOCK_T", 128))
DEFAULT_BLOCK_N = int(os.environ.get("GMM_BLOCK_N", 128))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def default_backend() -> str:
    """``pallas`` on TPU, ``blocked`` elsewhere; ``GMM_BACKEND`` overrides
    (tests force ``pallas`` to run the kernel under interpret mode)."""
    env = os.environ.get("GMM_BACKEND", "").strip()
    if env:
        return env
    return "pallas" if jax.default_backend() == "tpu" else "blocked"


def round_up(value: int, multiple: int) -> int:
    return ((value + multiple - 1) // multiple) * multiple


def pick_block_t(rows: int, num_experts: int = 0) -> int:
    """Largest power-of-two tile ≤ DEFAULT_BLOCK_T that does not dwarf the
    row count — decode steps route a handful of tokens and would otherwise
    pay E·(128−1) rows of padding per microbatch.

    With ``num_experts`` the tile also shrinks while the worst-case
    per-expert alignment padding (``E·(bt−1)`` rows) exceeds half the real
    rows: production token counts (rows ≫ E·256) keep the MXU-matched
    default, while decode-sized dispatches trade tile width for a
    near-dense buffer. The threshold is deliberately loose — each halving
    also doubles the tile count, and the blocked backend pays one expert
    weight gather per tile, so small tiles cost more than the padding
    they save.
    """
    bt = 8
    while bt < DEFAULT_BLOCK_T and bt < rows:
        bt *= 2
    if num_experts > 0:
        while bt > 8 and num_experts * (bt - 1) > rows // 2:
            bt //= 2
    return bt


def tile_experts(group_sizes: jnp.ndarray, n_tiles: int, block_t: int) -> jnp.ndarray:
    """int32 ``[n_tiles]`` owning expert of each row tile.

    Expert ``e`` covers rows ``[ends[e-1], ends[e])``; a tile starting at
    ``s`` belongs to the first expert whose end exceeds ``s``. Tiles past
    the last group (static padding tail) clamp to the final expert — they
    multiply zero rows and their output is never read.
    """
    ends = jnp.cumsum(group_sizes.astype(jnp.int32))
    starts = jnp.arange(n_tiles, dtype=jnp.int32) * block_t
    te = jnp.searchsorted(ends, starts, side="right")
    return jnp.minimum(te, group_sizes.shape[0] - 1).astype(jnp.int32)


def _compiler_params(semantics):
    if pltpu is None or _interpret():
        return None
    return pltpu.CompilerParams(dimension_semantics=semantics)


# -- forward kernel ----------------------------------------------------------
def _gmm_kernel(te_ref, x_ref, w_ref, o_ref):
    del te_ref  # only consumed by the index maps
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _gmm_pallas(x, w, group_sizes, block_t, block_n):
    T, K = x.shape
    E, _, N = w.shape
    bn = min(block_n, N)
    if T % block_t or N % bn:
        raise ValueError(
            f"gmm pallas backend needs T ({T}) % block_t ({block_t}) == 0 and "
            f"N ({N}) % block_n ({bn}) == 0; the moe dispatcher pads for this")
    n_t, n_n = T // block_t, N // bn
    te = tile_experts(group_sizes, n_t, block_t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_t, n_n),
        in_specs=[
            pl.BlockSpec((block_t, K), lambda t, n, te: (t, 0)),
            pl.BlockSpec((1, K, bn), lambda t, n, te: (te[t], 0, n)),
        ],
        out_specs=pl.BlockSpec((block_t, bn), lambda t, n, te: (t, n)),
    )
    return pl.pallas_call(
        _gmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, N), x.dtype),
        compiler_params=_compiler_params(("parallel", "parallel")),
        interpret=_interpret(),
    )(te, x, w)


# -- backward dW kernel (tgmm) -----------------------------------------------
def _tgmm_kernel(te_ref, x_ref, dy_ref, dw_ref):
    # Grid is (n_n, n_t) with t fastest, so revisits of one expert's output
    # block are consecutive — initialize on the first tile of each group,
    # accumulate on the rest (the Pallas output-revisit rule).
    t = pl.program_id(1)
    prev = te_ref[jnp.maximum(t - 1, 0)]
    first = jnp.logical_or(t == 0, te_ref[t] != prev)
    part = jax.lax.dot_general(
        x_ref[...], dy_ref[...],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[None].astype(dw_ref.dtype)

    @pl.when(first)
    def _init():
        dw_ref[...] = part

    @pl.when(jnp.logical_not(first))
    def _accumulate():
        dw_ref[...] = dw_ref[...] + part


def _tgmm_pallas(x, dy, group_sizes, n_experts, block_t, block_n):
    """dW ``[E, K, N]`` = per-group ``x_rows.T @ dy_rows``."""
    T, K = x.shape
    _, N = dy.shape
    bn = min(block_n, N)
    n_t, n_n = T // block_t, N // bn
    te = tile_experts(group_sizes, n_t, block_t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_n, n_t),
        in_specs=[
            pl.BlockSpec((block_t, K), lambda n, t, te: (t, 0)),
            pl.BlockSpec((block_t, bn), lambda n, t, te: (t, n)),
        ],
        out_specs=pl.BlockSpec((1, K, bn), lambda n, t, te: (te[t], 0, n)),
    )
    dw = pl.pallas_call(
        _tgmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_experts, K, N), x.dtype),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=_interpret(),
    )(te, x, dy)
    # Experts that received no tiles were never written; also covers the
    # clamped tail tiles double-writing the last expert with zero rows.
    return jnp.where((group_sizes > 0)[:, None, None], dw, 0)


# -- int8 forward (amax/scale tracked), fp backward --------------------------
def _quantize_rows_int8(x):
    """Per-row symmetric int8 over the contraction dim: [T, K] ->
    (int8 [T, K], fp32 scales [T, 1])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    s = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _quantize_cols_int8(w):
    """Per-(expert, out-column) symmetric int8 over the contraction dim:
    [E, K, N] -> (int8 [E, K, N], fp32 scales [E, 1, N])."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1, keepdims=True)
    s = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _gmm_int8_impl(x, w, group_sizes, block_t):
    """Real int8×int8→int32 grouped GEMM in the blocked formulation: both
    operands are amax/scale-quantized per contraction row/column, the
    tile-batched ``dot_general`` contracts in integers, and the scales
    multiply back on the [T, N] result (rank-1 per tile: row scales ×
    that tile's expert column scales)."""
    T, K = x.shape
    if T % block_t:
        raise ValueError(
            f"gmm int8 path needs T ({T}) % block_t ({block_t}) == 0; "
            "the moe dispatcher pads for this")
    n_t = T // block_t
    te = tile_experts(group_sizes.astype(jnp.int32), n_t, block_t)
    xq, sx = _quantize_rows_int8(x)
    wq, sw = _quantize_cols_int8(w)
    yt = jnp.einsum("tbk,tkn->tbn", xq.reshape(n_t, block_t, K), wq[te],
                    preferred_element_type=jnp.int32)
    y = yt.astype(jnp.float32) * sx.reshape(n_t, block_t, 1) * sw[te]
    return y.reshape(T, w.shape[2]).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm_int8(x, w, group_sizes, block_t):
    return _gmm_int8_impl(x, w, group_sizes, block_t)


def _gmm_int8_fwd(x, w, group_sizes, block_t):
    return _gmm_int8_impl(x, w, group_sizes, block_t), (x, w, group_sizes)


def _gmm_int8_bwd(block_t, residuals, dy):
    # Straight-through: gradients flow as if the forward were the fp
    # grouped GEMM (the quantization error is treated as noise), keeping
    # the backward in full precision like the flash-attention int8 path.
    x, w, group_sizes = residuals
    T, K = x.shape
    n_t = T // block_t
    te = tile_experts(group_sizes.astype(jnp.int32), n_t, block_t)
    dx = gmm(dy, w.transpose(0, 2, 1), group_sizes, block_t=block_t,
             backend="blocked")
    part = jnp.einsum("tbk,tbn->tkn", x.reshape(n_t, block_t, K),
                      dy.reshape(n_t, block_t, -1),
                      preferred_element_type=jnp.float32)
    dw = jnp.zeros(w.shape, jnp.float32).at[te].add(part)
    dw = jnp.where((group_sizes > 0)[:, None, None], dw, 0)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


_gmm_int8.defvjp(_gmm_int8_fwd, _gmm_int8_bwd)


# -- differentiable entry point ----------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _gmm_pallas_diff(x, w, group_sizes, block_t, block_n):
    return _gmm_pallas(x, w, group_sizes, block_t, block_n)


def _gmm_fwd(x, w, group_sizes, block_t, block_n):
    return _gmm_pallas(x, w, group_sizes, block_t, block_n), (x, w, group_sizes)


def _gmm_bwd(block_t, block_n, residuals, dy):
    x, w, group_sizes = residuals
    dx = _gmm_pallas(dy, w.transpose(0, 2, 1), group_sizes, block_t, block_n)
    dw = _tgmm_pallas(x, dy, group_sizes, w.shape[0], block_t, block_n)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


_gmm_pallas_diff.defvjp(_gmm_fwd, _gmm_bwd)


def gmm(
    x: jnp.ndarray,
    w: jnp.ndarray,
    group_sizes: jnp.ndarray,
    *,
    block_t: int = DEFAULT_BLOCK_T,
    block_n: int = DEFAULT_BLOCK_N,
    backend: Optional[str] = None,
    precision: Optional[str] = None,
) -> jnp.ndarray:
    """``x [T, N_in]`` × ``w [E, N_in, N_out]`` → ``[T, N_out]`` where row
    block ``e`` of ``x`` (per ``group_sizes``, block_t-aligned) multiplies
    ``w[e]``. Differentiable in ``x`` and ``w`` on both backends.

    ``precision`` (model.matmul_precision): "int8" runs the forward as a
    real int8×int8→int32 grouped contraction with amax/scale tracking
    (per activation row, per expert output column) and a full-precision
    backward; "bf16" casts the operands. None/"fp32" is the fp path."""
    from .flash_attention import check_matmul_precision

    precision = check_matmul_precision(precision)
    if precision == "int8":
        return _gmm_int8(x, w, group_sizes, block_t)
    if precision == "bf16":
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    backend = backend or default_backend()
    if backend == "ragged":
        # XLA-native ragged dot: differentiates itself (dX transpose rule +
        # grouped dW) and tolerates unaligned groups.
        return jax.lax.ragged_dot(x, w, group_sizes.astype(jnp.int32))
    if backend == "blocked":
        T, K = x.shape
        if T % block_t:
            raise ValueError(
                f"gmm blocked backend needs T ({T}) % block_t ({block_t})"
                " == 0; the moe dispatcher pads for this")
        n_t = T // block_t
        te = tile_experts(group_sizes.astype(jnp.int32), n_t, block_t)
        xt = x.reshape(n_t, block_t, K)
        # One weight gather + one batched matmul; XLA's transpose rules
        # give dX (batched matmul vs w[te].T) and dW (scatter-add of the
        # per-tile outer products back through the gather) for free.
        yt = jnp.einsum("tbk,tkn->tbn", xt, w[te],
                        preferred_element_type=jnp.float32)
        return yt.reshape(T, w.shape[2]).astype(x.dtype)
    if backend != "pallas":
        raise ValueError(
            f"unknown gmm backend {backend!r} (pallas|blocked|ragged)")
    if pltpu is None:  # pragma: no cover - pltpu ships with this jaxlib
        raise RuntimeError("gmm pallas backend needs jax.experimental.pallas.tpu")
    return _gmm_pallas_diff(x, w, group_sizes.astype(jnp.int32), block_t, block_n)
