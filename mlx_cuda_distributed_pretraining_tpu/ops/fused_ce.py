"""Chunked (fused) cross-entropy over the vocabulary projection.

The naive LM loss materializes fp32 logits ``[B, S, V]`` — at a bench-scale
shape (B16 x S2048 x V32768) that is 4.3 GB of HBM written by the forward,
read by the softmax, and re-touched by the backward: the single largest
memory consumer in the whole step, and pure bandwidth (the reference pays
the same cost: core/training.py compute_loss materializes full logits).

This is the standard TPU trick instead: fold the output projection INTO the
loss and compute it in row chunks under ``jax.checkpoint`` inside a
``lax.scan``:

- forward: for each chunk of N rows, one ``[N, D] @ [D, V]`` MXU matmul
  (bf16 operands, fp32 accumulation) -> logsumexp + gold-logit gather ->
  scalar partial sum. Peak logits memory is ``chunk x V`` fp32 (a few
  hundred MB at most) instead of ``B*S x V``.
- backward: ``jax.checkpoint`` recomputes each chunk's logits, so the
  softmax Jacobian never exists whole either; the scan accumulates dW
  across chunks and emits per-chunk dX. FLOPs are identical to the naive
  path + one extra forward matmul per chunk (the remat), traded for ~3x
  less HBM traffic at the projection.

Exactness: identical math to ``logsumexp(logits) - logits[target]`` in fp32
(same reduction, same dtype), verified against the unfused path by
tests/test_model.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def fused_cross_entropy(
    hidden: jnp.ndarray,
    w_vd: jnp.ndarray,
    targets: jnp.ndarray,
    mask: jnp.ndarray,
    bias_v: Optional[jnp.ndarray] = None,
    logit_scale: Optional[float] = None,
    chunk: int = 2048,
    with_z: bool = False,
):
    """Masked NLL sum without materializing full logits.

    hidden  [B, S, D]  final hidden states (compute dtype, e.g. bf16)
    w_vd    [V, D]     output embedding (same dtype as hidden for the MXU)
    targets [B, S]     int32
    mask    [B, S]     0/1
    bias_v  [V]        optional output-projection bias
    Returns the fp32 scalar sum of masked token NLLs (caller divides by
    the token count); with ``with_z`` returns ``(nll_sum, z_sum)`` where
    z_sum is the masked sum of logsumexp(logits)^2 — the z-loss
    regularizer's numerator (PaLM-style logit-drift control), computed
    from the same per-chunk logsumexp at zero extra memory.
    """
    B, S, D = hidden.shape
    N = B * S
    x = hidden.reshape(N, D)
    t = targets.reshape(N).astype(jnp.int32)
    m = mask.reshape(N).astype(jnp.float32)

    chunk = max(min(chunk, N), 1)
    n_chunks = -(-N // chunk)
    pad = n_chunks * chunk - N
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        t = jnp.pad(t, (0, pad))
        m = jnp.pad(m, (0, pad))
    xs = x.reshape(n_chunks, chunk, D)
    ts = t.reshape(n_chunks, chunk)
    ms = m.reshape(n_chunks, chunk)

    def body(acc, inp):
        xc, tc, mc = inp
        logits = jax.lax.dot_general(
            xc, w_vd, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if bias_v is not None:
            logits = logits + bias_v.astype(jnp.float32)
        if logit_scale:
            logits = logits * logit_scale
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        nll_c = jnp.sum((logz - gold) * mc)
        if with_z:  # trace-time constant: pure-CE callers keep one carry
            nll_acc, z_acc = acc
            return (nll_acc + nll_c, z_acc + jnp.sum(jnp.square(logz) * mc)), None
        return acc + nll_c, None

    init = ((jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
            if with_z else jnp.zeros((), jnp.float32))
    acc, _ = jax.lax.scan(jax.checkpoint(body), init, (xs, ts, ms))
    return acc  # (nll_sum, z_sum) when with_z, else the nll_sum scalar


def auto_chunk(batch: int, seq: int, vocab: int) -> int:
    """Chunk-size policy for ``fused_ce_chunk: -1`` (auto).

    Fused CE pays one extra projection matmul per chunk (the remat); it wins
    when the full logits tensor is HBM-significant. Threshold: enable when
    ``B*S*V`` fp32 exceeds 256 MB, with 2048-row chunks (a 2048 x 32k fp32
    chunk is 256 MB peak — comfortably resident)."""
    if batch * seq * vocab * 4 < 256 * 1024 * 1024:
        return 0
    return 2048


def fused_cross_entropy_sp(
    hidden: jnp.ndarray,
    w_vd: jnp.ndarray,
    targets: jnp.ndarray,
    mask: jnp.ndarray,
    mesh,
    bias_v: Optional[jnp.ndarray] = None,
    logit_scale: Optional[float] = None,
    chunk: int = 2048,
    with_z: bool = False,
):
    """Sequence-sharded fused CE for sp (context-parallel) meshes.

    The flat-row reshape in :func:`fused_cross_entropy` has no valid GSPMD
    sharding when the sequence dim is sharded, which previously forced sp
    runs back to full [B, S, V] logits — the exact memory hog fused CE
    exists to avoid, and sp runs are where S is LONGEST. This variant
    drops to ``shard_map``: every device runs the chunked fused CE on its
    own local [B_local, S_local] block (chunking over local rows), and one
    ``psum`` reduces the masked NLL sums. Requires the vocab projection
    replicated — i.e. ``tp == 1`` (with tp, the projection is
    vocab-sharded and GSPMD's own vocab-parallel handling of the unfused
    path applies instead).

    Exactness: identical math to the single-device path — the row chunks
    are just distributed; the psum is the same fp32 sum re-associated per
    device (tests assert loss AND grad parity on a dp x sp mesh).
    """
    from jax.sharding import PartitionSpec as P

    def size(a):
        return mesh.shape.get(a, 1)

    assert size("tp") == 1, (
        "fused_cross_entropy_sp needs a replicated vocab projection "
        "(tp == 1); with tp the unfused path is already vocab-parallel")
    data_axes = tuple(a for a in ("dp", "fsdp", "ep") if size(a) > 1)
    b_axes = data_axes if data_axes else None
    seq_axis = "sp" if size("sp") > 1 else None

    in_specs = [P(b_axes, seq_axis, None), P(None, None),
                P(b_axes, seq_axis), P(b_axes, seq_axis)]
    args = [hidden, w_vd, targets, mask]
    if bias_v is not None:
        in_specs.append(P(None))
        args.append(bias_v)

    def local(h, w, t, m, *rest):
        b = rest[0] if rest else None
        nll, z = fused_cross_entropy(h, w, t, m, bias_v=b,
                                     logit_scale=logit_scale, chunk=chunk,
                                     with_z=True)
        return jax.lax.psum((nll, z), tuple(mesh.axis_names))

    # Current API straight off jax when present; the compat shim only
    # backfills the deprecated experimental path (ROADMAP: trainer-side
    # collectives off the shim).
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from ..parallel.compat import shard_map as sm

    fn = sm(local, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=(P(), P()), check_vma=False)
    nll_sum, z_sum = fn(*args)
    if with_z:
        return nll_sum, z_sum
    return nll_sum
