from .attention import reference_attention
from . import masks

__all__ = ["reference_attention", "masks"]
