from .attention import reference_attention
from .donation import donate_argnums
from . import masks

__all__ = ["reference_attention", "donate_argnums", "masks"]
