"""Reference (einsum) attention — the correctness baseline.

Equivalent capability to the reference's SimpleAttention / full-matrix
"flash" (reference: models/attention/simple_attention.py,
flash_attention.py:134-151) but fully vectorized and traceable: GQA handled
by reshaping to head groups (no materialized repeat), fp32 softmax, mask and
score mods applied on index lattices.

Layout convention throughout the framework: ``q [B, Sq, Hq, D]``,
``k/v [B, Skv, Hkv, D]`` with Hq a multiple of Hkv.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .masks import NEG_INF, MaskMod, ScoreMod, materialize_mask


def reference_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask_mod: Optional[MaskMod] = None,
    score_mod: Optional[ScoreMod] = None,
    scale: Optional[float] = None,
    q_offset: int = 0,
    explicit_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Multi-head attention with GQA and traceable mask/score mods.

    ``explicit_mask`` ([Sq, Skv] or broadcastable bool, True = attend) is an
    alternative to ``mask_mod`` for precomputed masks (e.g. padding).
    """
    B, Sq, Hq, D = q.shape
    _, Skv, Hkv, _ = k.shape
    if Hq % Hkv != 0:
        raise ValueError(f"query heads {Hq} not a multiple of kv heads {Hkv}")
    G = Hq // Hkv
    scale = (D ** -0.5) if scale is None else scale

    # [B, Hkv, G, Sq, D] x [B, Hkv, Skv, D] -> [B, Hkv, G, Sq, Skv]
    qg = q.transpose(0, 2, 1, 3).reshape(B, Hkv, G, Sq, D)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kh) * scale
    scores = scores.astype(jnp.float32)

    if score_mod is not None:
        q_idx = jnp.arange(Sq, dtype=jnp.int32)[:, None] + q_offset
        k_idx = jnp.arange(Skv, dtype=jnp.int32)[None, :]
        scores = score_mod(scores, q_idx, k_idx)

    m = explicit_mask
    if mask_mod is not None:
        mm = materialize_mask(mask_mod, Sq, Skv, q_offset)
        m = mm if m is None else (m & mm)
    if m is not None:
        scores = jnp.where(m, scores, NEG_INF)

    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    probs = probs.astype(v.dtype)

    out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, vh)
    return out.reshape(B, Hq, Sq, D).transpose(0, 2, 1, 3)
