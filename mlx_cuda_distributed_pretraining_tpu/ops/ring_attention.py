"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has no sequence/context parallelism at all (SURVEY.md §2.4 —
longest context 4096, sliding-window masks only). This implements
blockwise ring attention (Liu et al.): the sequence dim is sharded over the
``sp`` mesh axis; each device keeps its Q shard and rotates KV shards
around the ring with ``jax.lax.ppermute`` over ICI, accumulating an online
softmax. The KV transfer overlaps with compute under XLA's async
collective scheduling.

Perf-grade paths (causal AND sliding-window — the training cases): each
rotation chunk runs the
**tiled Pallas flash kernels** (ops/flash_attention.py flash_fwd /
flash_bwd_*), so per-chip attention memory is O(block_q x block_kv), not
O(S_local²), and scores ride the MXU. Chunk-level block sparsity comes
free from the ring structure: the diagonal chunk uses the causal kernel,
fully-visible chunks use the full-mask kernel, invisible chunks are
``lax.cond``-skipped entirely. The whole op is one ``jax.custom_vjp``:
forward saves (o, global lse) per flash-attention-2; backward re-runs the
tiled kernels per chunk with the global statistics and rotates dK/dV
accumulators around the ring alongside K/V, landing them back on their
owner after sp hops.

Sliding-window rings additionally stop rotating once the window is
exhausted (_ring_attention_flash_sw) — a 1024-token window on a 32k
sequence over sp=8 does 1-2 KV hops instead of 8.

Arbitrary mask mods fall back to a pure-jnp chunk path (exact, memory
O(S_local²)) — custom masks are an inference/research surface; causal and
sliding-window are the hot ones.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel.compat import axis_size as _axis_size
from .masks import NEG_INF, MaskMod


def ring_live_hops(sp: int, seq_local: int, window: Optional[int]) -> int:
    """Number of live KV rotation chunks for a ring of size ``sp``.

    This is the kernel's own static unroll bound: a full causal ring
    visits all ``sp`` chunks, while a sliding window of ``window`` tokens
    only has visible elements at rotation distances ``i*seq_local <
    window + seq_local - 1`` — so a 1024-window over a 32k sequence on
    sp=8 does 2 hops, not 8. Exposed so callers (dryrun, tests) can
    certify the early stop from outside the kernel."""
    if window is None:
        return sp
    return min(sp, (window + seq_local - 2) // seq_local + 1)


def _ring_perm(sp: int):
    return [(j, (j + 1) % sp) for j in range(sp)]


def _merge_chunk(m, num, den, o_c, lse_c):
    """Online-softmax merge of one chunk's (o, lse) into the running
    (max, numerator, denominator). lse_c: [B, Hq, Sl] (invisible chunks
    carry NEG_INF rows => weight exp(NEG_INF - m_new) == 0)."""
    m_new = jnp.maximum(m, lse_c)
    w_old = jnp.exp(m - m_new)
    w_new = jnp.exp(lse_c - m_new)
    num = num * w_old[..., None] + o_c.astype(jnp.float32) * w_new[..., None]
    den = den * w_old + w_new
    return m_new, num, den


def _gqa_reduce(d_h, B, Hkv, G, Sl, D):
    """Per-query-head dK/dV [B, Hq, Sl, D] -> per-kv-head [B, Sl, Hkv, D]."""
    return d_h.reshape(B, Hkv, G, Sl, D).sum(axis=2).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Flash-kernel causal path
# ---------------------------------------------------------------------------
def _ring_attention_flash(q, k, v, axis_name: str, scale: float,
                          block_q: int, block_kv: int):
    """Causal ring attention with Pallas-tiled chunk math. Runs INSIDE
    shard_map; q/k/v are local shards [B, S_local, H, D]."""
    from . import masks as M
    from .flash_attention import flash_bwd_dkv, flash_bwd_dq, flash_fwd

    _causal_mask = M.causal()

    B, Sl, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    sp = _axis_size(axis_name)
    kw = dict(block_q=block_q, block_kv=block_kv, scale=scale)

    @jax.custom_vjp
    def attn(q, k, v):
        o, _ = _fwd(q, k, v)
        return o

    def _chunk_fwd(qt, kt, vt, src, my):
        """(o_c, lse_c) for one rotation chunk; lse_c rows of invisible
        chunks are NEG_INF so the merge treats them as weight zero."""

        def causal_case(ops):
            # diag chunk: causality is local (q_global-k_global = r-c)
            return flash_fwd(*ops, mask_type="causal", mask_fn=_causal_mask, **kw)

        def offdiag_case(ops):
            def full_case(ops):
                return flash_fwd(*ops, mask_type="full", mask_fn=None, **kw)

            def skip_case(ops):
                qt = ops[0]
                return (jnp.zeros_like(qt),
                        jnp.full((B, Hq, 1, Sl), NEG_INF, jnp.float32))

            return jax.lax.cond(src < my, full_case, skip_case, ops)

        return jax.lax.cond(src == my, causal_case, offdiag_case, (qt, kt, vt))

    def _fwd(q, k, v):
        # axis_index must be taken fresh in BOTH fwd and bwd: a custom_vjp
        # bwd runs in its own trace, so a closed-over traced index leaks.
        my = jax.lax.axis_index(axis_name)
        qt = q.transpose(0, 2, 1, 3)  # [B, Hq, Sl, D]

        def step(carry, i):
            k_cur, v_cur, m, num, den = carry
            src = (my - i) % sp
            o_c, lse_c = _chunk_fwd(qt, k_cur.transpose(0, 2, 1, 3),
                                    v_cur.transpose(0, 2, 1, 3), src, my)
            m, num, den = _merge_chunk(m, num, den, o_c, lse_c[:, :, 0])
            k_nxt = jax.lax.ppermute(k_cur, axis_name, _ring_perm(sp))
            v_nxt = jax.lax.ppermute(v_cur, axis_name, _ring_perm(sp))
            return (k_nxt, v_nxt, m, num, den), None

        m0 = jnp.full((B, Hq, Sl), NEG_INF, jnp.float32)
        num0 = jnp.zeros((B, Hq, Sl, D), jnp.float32)
        den0 = jnp.zeros((B, Hq, Sl), jnp.float32)
        (k_last, v_last, m, num, den), _ = jax.lax.scan(
            step, (k, v, m0, num0, den0), jnp.arange(sp, dtype=jnp.int32))
        den_safe = jnp.maximum(den, 1e-30)
        ot = (num / den_safe[..., None]).astype(q.dtype)   # [B, Hq, Sl, D]
        lse_g = (m + jnp.log(den_safe))[:, :, None, :]     # [B, Hq, 1, Sl]
        o = ot.transpose(0, 2, 1, 3)
        return o, (q, k, v, o, lse_g)

    def _bwd(res, g):
        q, k, v, o, lse_g = res
        my = jax.lax.axis_index(axis_name)
        qt = q.transpose(0, 2, 1, 3)
        gt = g.transpose(0, 2, 1, 3)
        delta = jnp.sum(gt.astype(jnp.float32) *
                        o.transpose(0, 2, 1, 3).astype(jnp.float32),
                        axis=-1)[:, :, None, :]            # [B, Hq, 1, Sl]

        def chunk_bwd(kt, vt, src, my):
            def causal_case(_):
                dq_c = flash_bwd_dq(qt, kt, vt, gt, lse_g, delta,
                                    mask_type="causal", mask_fn=_causal_mask, **kw)
                dk_h, dv_h = flash_bwd_dkv(qt, kt, vt, gt, lse_g, delta,
                                           mask_type="causal", mask_fn=_causal_mask, **kw)
                return dq_c, dk_h, dv_h

            def offdiag(_):
                def full_case(_):
                    dq_c = flash_bwd_dq(qt, kt, vt, gt, lse_g, delta,
                                        mask_type="full", mask_fn=None, **kw)
                    dk_h, dv_h = flash_bwd_dkv(qt, kt, vt, gt, lse_g, delta,
                                               mask_type="full", mask_fn=None, **kw)
                    return dq_c, dk_h, dv_h

                def skip(_):
                    return (jnp.zeros_like(qt),
                            jnp.zeros((B, Hq, Sl, D), kt.dtype),
                            jnp.zeros((B, Hq, Sl, D), vt.dtype))

                return jax.lax.cond(src < my, full_case, skip, None)

            return jax.lax.cond(src == my, causal_case, offdiag, None)

        def step(carry, i):
            k_cur, v_cur, dk_cur, dv_cur, dq = carry
            src = (my - i) % sp
            dq_c, dk_h, dv_h = chunk_bwd(k_cur.transpose(0, 2, 1, 3),
                                         v_cur.transpose(0, 2, 1, 3), src, my)
            dq = dq + dq_c.astype(jnp.float32)
            # per-query-head -> per-kv-head, back to [B, Sl, Hkv, D]
            dk_cur = dk_cur + _gqa_reduce(dk_h, B, Hkv, G, Sl, D).astype(jnp.float32)
            dv_cur = dv_cur + _gqa_reduce(dv_h, B, Hkv, G, Sl, D).astype(jnp.float32)
            # dK/dV accumulators ride the ring WITH their K/V chunk: after
            # sp hops they are back on the owning device.
            perm = _ring_perm(sp)
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
            dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
            return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq), None

        dq0 = jnp.zeros((B, Hq, Sl, D), jnp.float32)
        dkv0 = jnp.zeros((B, Sl, Hkv, D), jnp.float32)
        (_, _, dk, dv, dqt), _ = jax.lax.scan(
            step, (k, v, dkv0, dkv0, dq0), jnp.arange(sp, dtype=jnp.int32))
        dq = dqt.transpose(0, 2, 1, 3).astype(q.dtype)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype)

    attn.defvjp(_fwd, _bwd)
    return attn(q, k, v)


# ---------------------------------------------------------------------------
# Flash-kernel sliding-window path
# ---------------------------------------------------------------------------
def _ring_attention_flash_sw(q, k, v, axis_name: str, scale: float,
                             block_q: int, block_kv: int, window: int):
    """Sliding-window ring attention with Pallas-tiled chunk math.

    The ring loop is **statically unrolled over the rotation distance** i,
    which makes each chunk's band offset ``window - i*S_local`` a Python
    constant — so every chunk runs a tiled kernel with exact banded block
    sparsity instead of the O(S_local²) jnp fallback:

    - i == 0 (diagonal): canonical sliding_window kernel;
    - 0 < i, chunk fully inside the window: full (unmasked) kernel;
    - band edge: ``band`` kernel, valid iff row-col < window - i*S_local
      (the inter-chunk offset already guarantees causality);
    - i*S_local >= window + S_local - 1: statically skipped — AND the ring
      stops rotating, so a 1024-window over a 32k sequence on sp=8 does 1-2
      hops, not 8.

    Runtime gating on wraparound (src > my ⇒ future tokens) via lax.cond.
    """
    from . import masks as M
    from .flash_attention import flash_bwd_dkv, flash_bwd_dq, flash_fwd

    B, Sl, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    sp = _axis_size(axis_name)
    kw = dict(block_q=block_q, block_kv=block_kv, scale=scale)
    # distances with any visible element: i*Sl < window + Sl - 1
    n_live = ring_live_hops(sp, Sl, window)
    perm = _ring_perm(sp)

    def _chunk_kw(i: int) -> dict:
        shift = i * Sl
        if i == 0:
            return dict(mask_type="sliding_window", window=window,
                        mask_fn=M.sliding_window(window), canonical_mask=True)
        if shift + Sl - 1 < window:
            return dict(mask_type="full", mask_fn=None)
        t = window - shift  # may be <= 0: band clipped to the top-right corner
        return dict(mask_type="band", window=t, mask_fn=M.band(t),
                    canonical_mask=True)

    @jax.custom_vjp
    def attn(q, k, v):
        o, _ = _fwd(q, k, v)
        return o

    def _fwd(q, k, v):
        my = jax.lax.axis_index(axis_name)
        qt = q.transpose(0, 2, 1, 3)
        m = jnp.full((B, Hq, Sl), NEG_INF, jnp.float32)
        num = jnp.zeros((B, Hq, Sl, D), jnp.float32)
        den = jnp.zeros((B, Hq, Sl), jnp.float32)
        k_cur, v_cur = k, v
        for i in range(n_live):
            ckw = _chunk_kw(i)

            def live_case(ops, ckw=ckw):
                return flash_fwd(*ops, **ckw, **kw)

            def skip_case(ops):
                return (jnp.zeros_like(qt),
                        jnp.full((B, Hq, 1, Sl), NEG_INF, jnp.float32))

            o_c, lse_c = jax.lax.cond(
                my >= i, live_case, skip_case,
                (qt, k_cur.transpose(0, 2, 1, 3), v_cur.transpose(0, 2, 1, 3)))
            m, num, den = _merge_chunk(m, num, den, o_c, lse_c[:, :, 0])
            if i + 1 < n_live:  # no transfer for chunks that are never used
                k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
                v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        den_safe = jnp.maximum(den, 1e-30)
        ot = (num / den_safe[..., None]).astype(q.dtype)
        lse_g = (m + jnp.log(den_safe))[:, :, None, :]
        return ot.transpose(0, 2, 1, 3), (q, k, v, ot.transpose(0, 2, 1, 3), lse_g)

    def _bwd(res, g):
        q, k, v, o, lse_g = res
        my = jax.lax.axis_index(axis_name)
        qt = q.transpose(0, 2, 1, 3)
        gt = g.transpose(0, 2, 1, 3)
        delta = jnp.sum(gt.astype(jnp.float32) *
                        o.transpose(0, 2, 1, 3).astype(jnp.float32),
                        axis=-1)[:, :, None, :]

        dq = jnp.zeros((B, Hq, Sl, D), jnp.float32)
        dk_cur = jnp.zeros((B, Sl, Hkv, D), jnp.float32)
        dv_cur = jnp.zeros((B, Sl, Hkv, D), jnp.float32)
        k_cur, v_cur = k, v
        for i in range(n_live):
            ckw = _chunk_kw(i)

            def live_case(ops, ckw=ckw):
                kt, vt = ops
                dq_c = flash_bwd_dq(qt, kt, vt, gt, lse_g, delta, **ckw, **kw)
                dk_h, dv_h = flash_bwd_dkv(qt, kt, vt, gt, lse_g, delta, **ckw, **kw)
                return dq_c, dk_h, dv_h

            def skip_case(ops):
                return (jnp.zeros_like(qt),
                        jnp.zeros((B, Hq, Sl, D), k.dtype),
                        jnp.zeros((B, Hq, Sl, D), v.dtype))

            dq_c, dk_h, dv_h = jax.lax.cond(
                my >= i, live_case, skip_case,
                (k_cur.transpose(0, 2, 1, 3), v_cur.transpose(0, 2, 1, 3)))
            dq = dq + dq_c.astype(jnp.float32)
            dk_cur = dk_cur + _gqa_reduce(dk_h, B, Hkv, G, Sl, D).astype(jnp.float32)
            dv_cur = dv_cur + _gqa_reduce(dv_h, B, Hkv, G, Sl, D).astype(jnp.float32)
            if i + 1 < n_live:
                k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
                v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
                dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
                dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        # accumulators sit (n_live-1) hops ahead of their owner; one
        # corrective ppermute lands them home (identity when n_live == sp).
        home = (n_live - 1) % sp
        if home:
            back = [(j, (j + sp - home) % sp) for j in range(sp)]
            dk_cur = jax.lax.ppermute(dk_cur, axis_name, back)
            dv_cur = jax.lax.ppermute(dv_cur, axis_name, back)
        return (dq.transpose(0, 2, 1, 3).astype(q.dtype),
                dk_cur.astype(k.dtype), dv_cur.astype(v.dtype))

    attn.defvjp(_fwd, _bwd)
    return attn(q, k, v)


# ---------------------------------------------------------------------------
# Generic-mask jnp path (exact, O(S_local²) chunk scores)
# ---------------------------------------------------------------------------
def _chunk_scores(q, k, scale):
    """q [B, Sq, Hkv, G, D] x k [B, Skv, Hkv, D] -> [B, Hkv, G, Sq, Skv] f32."""
    return jnp.einsum("bqhgd,bkhd->bhgqk", q, k).astype(jnp.float32) * scale


def _ring_attention_jnp(q, k, v, axis_name, mask_mod, scale):
    B, Sl, Hq, D = q.shape
    _, _, Hkv, _ = k.shape
    G = Hq // Hkv
    sp = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    qg = q.reshape(B, Sl, Hkv, G, D)
    q_idx = my * Sl + jnp.arange(Sl, dtype=jnp.int32)

    def accumulate(m, l, acc, k_cur, v_cur, i):
        # chunk i holds the shard originally owned by device (my - i) % sp
        src = (my - i) % sp
        kv_idx = src * Sl + jnp.arange(Sl, dtype=jnp.int32)
        s = _chunk_scores(qg, k_cur, scale)  # [B, Hkv, G, Sl, Sl]
        mask = mask_mod(q_idx[:, None], kv_idx[None, :])
        s = jnp.where(mask[None, None, None], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_cur.astype(jnp.float32))
        return m_new, l_new, acc_new

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = accumulate(m, l, acc, k_cur, v_cur, i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, _ring_perm(sp))
        v_nxt = jax.lax.ppermute(v_cur, axis_name, _ring_perm(sp))
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((B, Hkv, G, Sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Sl), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, G, Sl, D), jnp.float32)
    # Only sp-1 rotations are needed: the last chunk's accumulation happens
    # outside the scan so its (otherwise discarded) ppermute is never issued.
    (k_last, v_last, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(sp - 1, dtype=jnp.int32))
    m, l, acc = accumulate(m, l, acc, k_last, v_last, sp - 1)

    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe[..., None]  # [B, Hkv, G, Sl, D]
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, Sl, Hq, D)
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    mask_mod: Optional[MaskMod] = None,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_kv: int = 512,
) -> jnp.ndarray:
    """Runs INSIDE shard_map. q/k/v: local shards [B, S_local, H, D] with the
    global sequence laid out contiguously across the axis. ``mask_mod``
    takes GLOBAL (q_idx, kv_idx). Default mask is causal (flash-kernel
    path); non-causal mods use the exact jnp chunk path."""
    from .flash_attention import fit_block

    Sl, D = q.shape[1], q.shape[-1]
    scale = (D ** -0.5) if scale is None else scale
    plan = getattr(mask_mod, "_plan", None) if mask_mod is not None else ("causal", 0, 0)
    bq = fit_block(block_q, Sl)
    bkv = fit_block(block_kv, Sl)
    if plan is not None and Sl % bq == 0 and Sl % bkv == 0:
        if plan[0] == "causal":
            return _ring_attention_flash(q, k, v, axis_name, scale, bq, bkv)
        if plan[0] == "sliding_window":
            return _ring_attention_flash_sw(q, k, v, axis_name, scale, bq, bkv,
                                            window=plan[1])
    from . import masks as M

    return _ring_attention_jnp(q, k, v, axis_name, mask_mod or M.causal(), scale)


def make_ring_attention(mesh, axis_name: str = "sp", mask_mod: Optional[MaskMod] = None,
                        batch_axes=("dp", "fsdp"), block_q: int = 256,
                        block_kv: int = 512):
    """shard_map wrapper: [B, S_global, H, D] (sharded batch over dp/fsdp,
    sequence over sp) -> same. Heads/D replicated across sp."""
    from jax.sharding import PartitionSpec as P

    data = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    data_spec = data if data else None
    spec = P(data_spec, axis_name, None, None)

    fn = partial(ring_attention, axis_name=axis_name, mask_mod=mask_mod,
                 block_q=block_q, block_kv=block_kv)
    # Current API straight off jax when present; the compat shim only
    # backfills the deprecated experimental path.
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from ..parallel.compat import shard_map as sm

    return sm(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
