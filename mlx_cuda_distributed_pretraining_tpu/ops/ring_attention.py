"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has no sequence/context parallelism at all (SURVEY.md §2.4 —
longest context 4096, sliding-window masks only). This implements
blockwise ring attention (Liu et al.): the sequence dim is sharded over the
``sp`` mesh axis; each device keeps its Q shard and rotates KV shards
around the ring with ``jax.lax.ppermute`` over ICI, accumulating an online
softmax. Attention memory per chip is O(S_local²) and the KV transfer
overlaps with compute under XLA's async collective scheduling.

Differentiable by construction (pure jnp inside a ``lax.scan``; wrap in
``jax.checkpoint`` upstream for long sequences). Exact — the chunk-level
mask uses global positions, so causality across shards is preserved.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .masks import NEG_INF, MaskMod


def _chunk_scores(q, k, scale):
    """q [B, Sq, Hkv, G, D] x k [B, Skv, Hkv, D] -> [B, Hkv, G, Sq, Skv] f32."""
    return jnp.einsum("bqhgd,bkhd->bhgqk", q, k).astype(jnp.float32) * scale


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    mask_mod: Optional[MaskMod] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Runs INSIDE shard_map. q/k/v: local shards [B, S_local, H, D] with the
    global sequence laid out contiguously across the axis. ``mask_mod``
    takes GLOBAL (q_idx, kv_idx). Default mask is causal."""
    B, Sl, Hq, D = q.shape
    _, _, Hkv, _ = k.shape
    G = Hq // Hkv
    scale = (D ** -0.5) if scale is None else scale
    sp = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    if mask_mod is None:
        from . import masks as M

        mask_mod = M.causal()

    qg = q.reshape(B, Sl, Hkv, G, D)
    q_idx = my * Sl + jnp.arange(Sl, dtype=jnp.int32)

    def accumulate(m, l, acc, k_cur, v_cur, i):
        # chunk i holds the shard originally owned by device (my - i) % sp
        src = (my - i) % sp
        kv_idx = src * Sl + jnp.arange(Sl, dtype=jnp.int32)
        s = _chunk_scores(qg, k_cur, scale)  # [B, Hkv, G, Sl, Sl]
        mask = mask_mod(q_idx[:, None], kv_idx[None, :])
        s = jnp.where(mask[None, None, None], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_cur.astype(jnp.float32))
        return m_new, l_new, acc_new

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = accumulate(m, l, acc, k_cur, v_cur, i)
        # rotate KV around the ring (device d sends to d+1)
        perm = [(j, (j + 1) % sp) for j in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((B, Hkv, G, Sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Sl), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, G, Sl, D), jnp.float32)
    # Only sp-1 rotations are needed: the last chunk's accumulation happens
    # outside the scan so its (otherwise discarded) ppermute is never issued.
    (k_last, v_last, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(sp - 1, dtype=jnp.int32))
    m, l, acc = accumulate(m, l, acc, k_last, v_last, sp - 1)

    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe[..., None]  # [B, Hkv, G, Sl, D]
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, Sl, Hq, D)
    return out.astype(q.dtype)


def make_ring_attention(mesh, axis_name: str = "sp", mask_mod: Optional[MaskMod] = None,
                        batch_axes=("dp", "fsdp")):
    """shard_map wrapper: [B, S_global, H, D] (sharded batch over dp/fsdp,
    sequence over sp) -> same. Heads/D replicated across sp."""
    from jax.sharding import PartitionSpec as P

    data = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    data_spec = data if data else None
    spec = P(data_spec, axis_name, None, None)

    fn = partial(ring_attention, axis_name=axis_name, mask_mod=mask_mod)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
