"""Backend-gated buffer donation.

``jax.jit`` donation is an accelerator feature: XLA:CPU has no
input-output aliasing, so a donated buffer there changes nothing and
emits a warning per compile. Hot-path jits route their donate_argnums
through :func:`donate_argnums`, which passes them through on
accelerators and returns ``()`` on CPU.

graftaudit (analysis/audit.py) lowers the same steps on CPU to check the
donation pattern the accelerator would see; it sets
``GRAFTAUDIT_FORCE_DONATE=1`` so the CPU lowering carries the real
donation intent (lowering is metadata-only — execution is what lacks
CPU aliasing, and the audit never executes).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax


def donation_enabled() -> bool:
    """Whether jit donation is in effect: real aliasing (accelerator) or
    audit lowering (GRAFTAUDIT_FORCE_DONATE=1). The fused optimizer path
    (optim/fused.py) is donation-shaped either way; this gate only
    controls whether the jits *declare* it, to keep XLA:CPU from warning
    on every hot-path compile."""
    if os.environ.get("GRAFTAUDIT_FORCE_DONATE") == "1":
        return True
    return jax.default_backend() != "cpu"


def donate_argnums(*argnums: int) -> Tuple[int, ...]:
    """``argnums`` when donation is real (non-CPU backend), else ``()``."""
    return argnums if donation_enabled() else ()
