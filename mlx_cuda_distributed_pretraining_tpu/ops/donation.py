"""Backend-gated buffer donation.

``jax.jit`` donation is an accelerator feature: XLA:CPU has no
input-output aliasing, so a donated buffer there changes nothing and
emits a warning per compile. Hot-path jits route their donate_argnums
through :func:`donate_argnums`, which passes them through on
accelerators and returns ``()`` on CPU.

graftaudit (analysis/audit.py) lowers the same steps on CPU to check the
donation pattern the accelerator would see; it sets
``GRAFTAUDIT_FORCE_DONATE=1`` so the CPU lowering carries the real
donation intent (lowering is metadata-only — execution is what lacks
CPU aliasing, and the audit never executes).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax


def donate_argnums(*argnums: int) -> Tuple[int, ...]:
    """``argnums`` when donation is real (non-CPU backend), else ``()``."""
    if os.environ.get("GRAFTAUDIT_FORCE_DONATE") == "1":
        return argnums
    if jax.default_backend() == "cpu":
        return ()
    return argnums
