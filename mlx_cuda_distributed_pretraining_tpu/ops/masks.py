"""Traceable mask/score modifiers ("flex attention" the TPU way).

The reference's FlexAttention applies ``mask_mod(b, h, q, kv)`` /
``score_mod(score, b, h, q, kv)`` via quadruple-nested Python loops
(reference: models/attention/flex_attention.py:220-275) — untraceable and
O(B·H·S²) Python calls. Here a mod is a **vectorized function of index
arrays**, evaluated (a) on full index lattices for the reference path,
(b) at block granularity to build block-sparsity maps for the Pallas kernel.

A ``MaskMod`` maps broadcastable int32 arrays ``(q_idx, kv_idx)`` → bool
(True = attend). A ``ScoreMod`` maps ``(score, q_idx, kv_idx)`` → score.
Builders below cover the reference's shipped patterns: causal, sliding
window, prefix-LM, document/padding masks, ALiBi and soft-capping.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

MaskMod = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]
ScoreMod = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]

NEG_INF = -1e30  # large-but-finite: keeps softmax well-defined on fully-masked rows

# Builders are lru_cached so identical arguments return the identical
# function object — kernel caches (flash_attention._cached_core, jit static
# args) key on function identity.


# -- mask mods --------------------------------------------------------------
# Each named builder tags its mod with ``_plan = (mask_type, window, prefix)``
# so the flash kernel can recover the exact block-sparsity plan.


@lru_cache(maxsize=None)
def causal() -> MaskMod:
    def mod(q, k):
        return q >= k

    mod._plan = ("causal", 0, 0)
    return mod


@lru_cache(maxsize=None)
def full() -> MaskMod:
    def mod(q, k):
        return jnp.ones(jnp.broadcast_shapes(jnp.shape(q), jnp.shape(k)), bool)

    mod._plan = ("full", 0, 0)
    return mod


@lru_cache(maxsize=None)
def sliding_window(window: int, causal_: bool = True) -> MaskMod:
    """Attend to the last ``window`` positions (reference flex tests use this:
    tests/test_flex_attention.py:64-80)."""

    def mod(q, k):
        near = (q - k) < window
        if causal_:
            return (q >= k) & near
        return jnp.abs(q - k) < window

    if causal_:
        mod._plan = ("sliding_window", window, 0)
    return mod


@lru_cache(maxsize=None)
def band(window: int) -> MaskMod:
    """Left band alone: valid iff ``q - k < window``, NO causal bound
    (window may be <= 0). The shape of an off-diagonal rotation chunk in
    sliding-window ring attention, where the inter-chunk offset already
    guarantees causality (ops/ring_attention.py)."""

    def mod(q, k):
        return (q - k) < window

    mod._plan = ("band", window, 0)
    return mod


@lru_cache(maxsize=None)
def prefix_lm(prefix_len: int) -> MaskMod:
    """Bidirectional over the first ``prefix_len`` tokens, causal after."""

    def mod(q, k):
        return (q >= k) | (k < prefix_len)

    mod._plan = ("prefix_lm", 0, prefix_len)
    return mod


def document_mask(doc_ids: jnp.ndarray) -> MaskMod:
    """Block attention across packed-document boundaries. ``doc_ids`` is a
    per-position int array [S]; same id ⇒ may attend."""

    def mod(q, k):
        return (q >= k) & (doc_ids[q] == doc_ids[k])

    return mod


def and_masks(*mods: MaskMod) -> MaskMod:
    def mod(q, k):
        out = mods[0](q, k)
        for m in mods[1:]:
            out = out & m(q, k)
        return out

    return mod


def or_masks(*mods: MaskMod) -> MaskMod:
    def mod(q, k):
        out = mods[0](q, k)
        for m in mods[1:]:
            out = out | m(q, k)
        return out

    return mod


# -- score mods -------------------------------------------------------------
def alibi(slope: float) -> ScoreMod:
    """ALiBi linear positional bias for one head."""
    return lambda s, q, k: s - slope * jnp.abs(q - k)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Standard geometric ALiBi slopes per head."""
    base = 2.0 ** (-8.0 / num_heads)
    return base ** np.arange(1, num_heads + 1)


def soft_cap(cap: float) -> ScoreMod:
    return lambda s, q, k: cap * jnp.tanh(s / cap)


def relative_bias(bias_table: jnp.ndarray, max_distance: int) -> ScoreMod:
    def mod(s, q, k):
        d = jnp.clip(q - k, -max_distance, max_distance) + max_distance
        return s + bias_table[d]

    return mod


# -- materialization --------------------------------------------------------
def materialize_mask(mod: Optional[MaskMod], q_len: int, kv_len: int, q_offset: int = 0) -> Optional[jnp.ndarray]:
    """Evaluate a mask mod on the full [q_len, kv_len] lattice. ``q_offset``
    shifts query positions (decode-time: query at absolute position
    offset+i)."""
    if mod is None:
        return None
    q = jnp.arange(q_len, dtype=jnp.int32)[:, None] + q_offset
    k = jnp.arange(kv_len, dtype=jnp.int32)[None, :]
    return mod(q, k)


def block_mask_map(mod: MaskMod, q_len: int, kv_len: int, block_q: int, block_kv: int) -> np.ndarray:
    """Classify each (q-block, kv-block) tile: 0 = skip, 1 = partial (apply
    mask inside kernel), 2 = dense (no masking needed). This is the traceable
    replacement for the reference's block-midpoint sampling heuristic
    (reference: flex_attention.py:90-138), computed exactly via corner/full
    evaluation on the block index lattice."""
    q = np.arange(q_len, dtype=np.int64)
    k = np.arange(kv_len, dtype=np.int64)
    m = np.asarray(materialize_mask(mod, q_len, kv_len))
    nq = (q_len + block_q - 1) // block_q
    nk = (kv_len + block_kv - 1) // block_kv
    out = np.zeros((nq, nk), np.int8)
    for i in range(nq):
        rows = m[i * block_q : (i + 1) * block_q]
        for j in range(nk):
            tile = rows[:, j * block_kv : (j + 1) * block_kv]
            if tile.all():
                out[i, j] = 2
            elif tile.any():
                out[i, j] = 1
    return out
