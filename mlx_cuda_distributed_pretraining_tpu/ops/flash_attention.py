"""Tiled flash attention — Pallas TPU kernels, forward + backward.

The reference's "FlashAttention" materializes the full [B,H,S,S] score
matrix ("Simple approach without tiling for now", reference:
models/attention/flash_attention.py:100,134-151). This is the real thing:

- forward: online-softmax accumulation with **KV streamed through the
  grid** — K/V enter VMEM one [block_kv, D] tile at a time via the Pallas
  pipeline (double-buffered HBM→VMEM DMA), so VMEM never holds the whole
  sequence and max context is bounded by HBM, not VMEM; fp32 accumulators
  live in VMEM scratch across the KV grid steps; MXU matmuls via
  ``dot_general(..., preferred_element_type=f32)``;
- block sparsity: per-mask-type KV tile ranges (causal skips the upper
  triangle, sliding-window skips everything outside the band) — skipped
  tiles are gated with ``pl.when`` AND their index maps are clamped into
  the live range, so the pipeline never fetches a tile it will not use;
- backward: recomputation-based (saves only O and the logsumexp), split
  into a dQ kernel (KV streamed, dQ in scratch) and a dK/dV kernel
  (Q/dO streamed, dK/dV in scratch), the flash-attention-2 decomposition;
- GQA: native — each query head reads its KV group's tile; dK/dV are
  accumulated per query head and group-reduced outside the kernel;
- masks/score mods are traceable index-lattice functions (ops/masks.py)
  traced INTO the kernel, which is what makes flex_attention.py a thin
  wrapper over the same machinery.

Runs in Pallas interpret mode off-TPU, so the same code path is exercised
by the CPU test suite.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .masks import NEG_INF, MaskMod, ScoreMod

try:  # pltpu only resolves on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Lane width of the TPU vector unit: scratch vectors are padded to a full
# register row so stores never touch partial lanes.
_LANES = 128

# Opt-in low-precision matmul modes (model.matmul_precision). None/"fp32"
# is the default fp path; "bf16" casts the attention operands; "int8"
# runs amax/scale-tracked symmetric int8 quantization of q/k/v (per-row
# over the head dim, the same grid as the int8 KV cache quartet).
MATMUL_PRECISIONS = (None, "fp32", "bf16", "int8")


def check_matmul_precision(precision: Optional[str]) -> Optional[str]:
    p = str(precision).lower() if precision is not None else None
    if p in ("", "none", "fp32", "fp"):
        p = None
    if p not in MATMUL_PRECISIONS:
        raise ValueError(f"unknown matmul_precision {precision!r} "
                         f"(expected one of {MATMUL_PRECISIONS})")
    return p


def quantize_operand_int8(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """amax/scale-tracked int8 matmul operand with a straight-through
    backward.

    Tracks the per-row amax over the contraction dim, scales onto the
    symmetric int8 grid and requantizes: the forward value is EXACTLY
    ``round(x/s) * s`` with ``|round(x/s)| <= 127`` — integer products
    under fp32 accumulation are exact up to 127²·D < 2²⁴ (D <= 1024), so
    the kernel's MXU dot is bit-equivalent to a native int8×int8→int32
    contraction of the tracked values. The backward passes gradients
    straight through to the fp operand (standard STE), keeping the
    recomputation-based flash backward in full precision."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    xq = (q * s).astype(x.dtype)
    return x + jax.lax.stop_gradient(xq - x)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vmem_spec(block_shape=None, index_map=None):
    kwargs = {}
    if _VMEM is not None and not _interpret():
        kwargs["memory_space"] = _VMEM
    if block_shape is None:
        return pl.BlockSpec(**kwargs)
    return pl.BlockSpec(block_shape, index_map, **kwargs)


def _scratch(shape, dtype=jnp.float32):
    if pltpu is None:  # pragma: no cover - this jaxlib has pltpu even on CPU
        raise RuntimeError(
            "flash_attention needs jax.experimental.pallas.tpu (for VMEM "
            "scratch shapes, also used by interpret mode); use "
            "attention_type='simple' on builds without it")
    return pltpu.VMEM(shape, dtype)


def _compiler_params(n_parallel: int, n_total: int):
    """Mark leading grid dims parallel, trailing (reduction) dims arbitrary
    so Mosaic knows scratch state only flows along the last dim."""
    if pltpu is None or _interpret():
        return None
    sem = ("parallel",) * n_parallel + ("arbitrary",) * (n_total - n_parallel)
    return pltpu.CompilerParams(dimension_semantics=sem)


# -- tile-range planners (block sparsity per mask type) ----------------------
def _kv_range(mask_type: str, window: int, prefix_len: int, block_q: int, block_kv: int,
              num_kv_blocks: int):
    """(qi -> lo, qi -> hi) KV-tile bounds for a given query tile.

    ``band`` is the sliding-window left edge alone — valid iff
    ``row - col < window`` with NO causal bound (window may be <= 0):
    the shape of an off-diagonal rotation chunk in sliding-window ring
    attention, where the inter-chunk offset already guarantees causality.
    """

    def lo(qi):
        if mask_type in ("sliding_window", "band"):
            # row_min = qi*bq; cols >= row_min - window + 1 can contribute,
            # but the prefix region [0, prefix) never applies here.
            return jnp.maximum((qi * block_q - window + 1) // block_kv, 0)
        return jnp.int32(0)

    def hi(qi):
        if mask_type in ("causal", "sliding_window"):
            return jnp.minimum(pl.cdiv(qi * block_q + block_q, block_kv), num_kv_blocks)
        if mask_type == "prefix_lm":
            causal_hi = pl.cdiv(qi * block_q + block_q, block_kv)
            return jnp.minimum(jnp.maximum(causal_hi, pl.cdiv(prefix_len, block_kv)), num_kv_blocks)
        return jnp.int32(num_kv_blocks)  # full / band

    return lo, hi


def _q_range(mask_type: str, window: int, prefix_len: int, block_q: int, block_kv: int,
             num_q_blocks: int):
    """(ki -> lo, ki -> hi) Q-tile bounds for a given KV tile (backward)."""

    def lo(ki):
        if mask_type in ("causal", "sliding_window"):
            # first q row that can see this kv tile is its own diagonal row
            return (ki * block_kv) // block_q
        # full / prefix_lm / band: every q tile can reach every kv tile
        # (band: rows below the edge are bounded by hi, not lo)
        return jnp.int32(0)

    def hi(ki):
        if mask_type in ("sliding_window", "band"):
            # rows < col_max + window
            return jnp.maximum(jnp.minimum(
                pl.cdiv(ki * block_kv + block_kv - 1 + window, block_q) + 1,
                num_q_blocks), 0)
        return jnp.int32(num_q_blocks)

    return lo, hi


def _full_tile_fn(mask_type: str, window: int, prefix_len: int,
                  block_q: int, block_kv: int):
    """(qi, j) -> traced bool: is the whole [block_q, block_kv] tile valid
    under the canonical mask? Interior tiles skip the iota/compare/select
    mask work on the VPU entirely (the exp/matmul path is identical), which
    matters because the kernel is VPU-bound between MXU calls — on a causal
    mask roughly half the live tiles are interior. Only canonical masks
    qualify; custom flex mask programs always evaluate in-tile."""
    if mask_type not in ("causal", "sliding_window", "prefix_lm", "band"):
        return None

    def full(qi, j):
        min_row = qi * block_q
        max_row = qi * block_q + block_q - 1
        max_col = j * block_kv + block_kv - 1
        causal_ok = max_col <= min_row
        if mask_type == "causal":
            return causal_ok
        if mask_type == "sliding_window":
            return causal_ok & (max_row - j * block_kv <= window - 1)
        if mask_type == "band":  # row - col < window, no causal bound
            return max_row - j * block_kv <= window - 1
        return causal_ok | (max_col < prefix_len)  # prefix_lm

    return full


def _tile_dispatch(live, full, compute, masked):
    """Shared live/interior/edge tile dispatch for all three kernels.

    ``compute(apply_mask)`` runs the tile body; ``full`` is the traced
    is-fully-valid predicate for THIS tile (None = no fast path) and
    ``masked`` whether a mask program exists at all. Interior tiles skip
    the in-tile mask work; edge tiles mask as usual."""
    if not masked or full is None:
        @pl.when(live)
        def _one_path():
            compute(apply_mask=masked)
    else:
        @pl.when(live & full)
        def _interior():
            compute(apply_mask=False)

        @pl.when(live & jnp.logical_not(full))
        def _edge():
            compute(apply_mask=True)


# -- forward kernel ----------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                scale, mask_fn, score_fn, kv_lo, kv_hi, nkv, full_tile=None):
    j = pl.program_id(3)
    qi = pl.program_id(2)
    h = pl.program_id(1)
    bq = q_ref.shape[2]
    bkv = k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute(apply_mask):
        # Matmul operands stay in their storage dtype (bf16 in training) so
        # the MXU runs at full rate; accumulation is fp32.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if score_fn is not None or apply_mask:
            row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            col = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            if score_fn is not None:
                s = score_fn(s, row, col, h)
            if apply_mask:
                s = jnp.where(mask_fn(row, col), s, NEG_INF)
        m = m_scr[:, 0:1]                                    # [bq, 1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    live = (j >= kv_lo(qi)) & (j < kv_hi(qi))
    _tile_dispatch(live, full_tile(qi, j) if full_tile else None,
                   _compute, mask_fn is not None)

    @pl.when(j == nkv - 1)
    def _finalize():
        m = m_scr[:, 0]
        l = l_scr[:, 0]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse is laid out [B, H, 1, Sq]: the singleton dim keeps the block's
        # second-to-last dim equal to the array dim, satisfying TPU (8, 128)
        # tiling without padding lse out to 128 lanes.
        lse_ref[0, 0, 0] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)


# -- backward kernels --------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
                   scale, mask_fn, score_fn, kv_lo, kv_hi, nkv, full_tile=None):
    j = pl.program_id(3)
    qi = pl.program_id(2)
    h = pl.program_id(1)
    bq = q_ref.shape[2]
    bkv = k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute(apply_mask):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, 0].astype(jnp.float32)
        delta = delta_ref[0, 0, 0].astype(jnp.float32)
        s_raw = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
        if score_fn is not None or apply_mask:
            row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            col = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
        s = score_fn(s_raw, row, col, h) if score_fn is not None else s_raw
        if apply_mask:
            s = jnp.where(mask_fn(row, col), s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        d_mod = getattr(score_fn, "_d_score", None) if score_fn is not None else None
        if d_mod is not None:  # non-additive score mod: chain through its Jacobian
            ds = ds * d_mod(s_raw, row, col, h)
        ds = ds * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = (j >= kv_lo(qi)) & (j < kv_hi(qi))
    _tile_dispatch(live, full_tile(qi, j) if full_tile else None,
                   _compute, mask_fn is not None)

    @pl.when(j == nkv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr, *, scale, mask_fn, score_fn, q_lo, q_hi, nq,
                    full_tile=None):
    j = pl.program_id(3)   # q tile (streamed)
    ki = pl.program_id(2)  # kv tile (resident)
    h = pl.program_id(1)
    bq = q_ref.shape[2]
    bkv = k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute(apply_mask):
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, 0].astype(jnp.float32)
        delta = delta_ref[0, 0, 0].astype(jnp.float32)
        s_raw = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
        if score_fn is not None or apply_mask:
            row = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            col = ki * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
        s = score_fn(s_raw, row, col, h) if score_fn is not None else s_raw
        if apply_mask:
            s = jnp.where(mask_fn(row, col), s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        d_mod = getattr(score_fn, "_d_score", None) if score_fn is not None else None
        if d_mod is not None:
            ds = ds * d_mod(s_raw, row, col, h)
        ds = ds * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = (j >= q_lo(ki)) & (j < q_hi(ki))
    # Tile geometry here is (q tile j, kv tile ki): full_tile takes
    # (query tile, kv tile) in that order.
    _tile_dispatch(live, full_tile(j, ki) if full_tile else None,
                   _compute, mask_fn is not None)

    @pl.when(j == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def fit_block(block: int, dim: int) -> int:
    """Largest power-of-two block <= requested that divides the sequence;
    128 is the TPU lane width / minimum tile. May still fail to divide for
    dims like 192 — callers must check ``dim % fit_block(...) == 0`` and
    fall back to a non-Pallas path."""
    while block > 128 and dim % block:
        block //= 2
    return min(block, dim)


def _check_divisible(Sq, bq, Skv, bkv):
    if Sq % bq or Skv % bkv:
        raise ValueError(
            f"flash kernels need block-divisible sequences: Sq={Sq} % bq={bq}"
            f" or Skv={Skv} % bkv={bkv} != 0 — pass fitted blocks "
            "(fit_block) or use the reference path")


# -- raw kernel entry points (reused by ring attention) ----------------------
def flash_fwd(q, k, v, *, mask_fn=None, score_fn=None, mask_type="causal",
              window=512, prefix_len=0, block_q=256, block_kv=512, scale=1.0,
              canonical_mask=False):
    """Raw tiled forward on [B, H, S, D] layout. Returns ``(o, lse)`` with
    lse laid out [B, Hq, 1, Sq]. Building block for the custom-vjp wrapper
    and for ring attention's per-chunk calls. ``canonical_mask`` asserts
    that ``mask_fn`` computes exactly the ``mask_type`` predicate, enabling
    the interior-tile fast path (skip in-tile masking where the tile is
    provably fully valid)."""
    B, Hq, Sq, D = q.shape
    _, Hkv, Skv, _ = k.shape
    G = Hq // Hkv
    bq = min(block_q, Sq)
    bkv = min(block_kv, Skv)
    _check_divisible(Sq, bq, Skv, bkv)
    nq = Sq // bq
    nkv = Skv // bkv
    kv_lo, kv_hi = _kv_range(mask_type, window, prefix_len, bq, bkv, nkv)
    full_tile = (_full_tile_fn(mask_type, window, prefix_len, bq, bkv)
                 if canonical_mask else None)

    def kv_index(b, h, i, j):
        # Clamp skipped tiles into the live range so the pipeline never
        # DMAs a tile the kernel will not touch (block sparsity saves
        # bandwidth, not just FLOPs). Empty ranges (possible for band
        # masks: lo can exceed nkv-1, hi-1 can go below lo) are clamped
        # into [0, nkv-1] from BOTH sides — jnp.clip resolves inverted
        # bounds toward the upper one, which is always in range.
        jc = jnp.clip(j, jnp.minimum(kv_lo(i), nkv - 1),
                      jnp.maximum(kv_hi(i) - 1, 0))
        return (b, h // G, jc, 0)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, mask_fn=mask_fn,
        score_fn=score_fn, kv_lo=kv_lo, kv_hi=kv_hi, nkv=nkv,
        full_tile=full_tile)
    return pl.pallas_call(
        kernel,
        grid=(B, Hq, nq, nkv),
        in_specs=[
            _vmem_spec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            _vmem_spec((1, 1, bkv, D), kv_index),
            _vmem_spec((1, 1, bkv, D), kv_index),
        ],
        out_specs=[
            _vmem_spec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            _vmem_spec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((bq, _LANES)),      # running max
            _scratch((bq, _LANES)),      # running denominator
            _scratch((bq, D)),           # fp32 output accumulator
        ],
        compiler_params=_compiler_params(3, 4),
        interpret=_interpret(),
    )(q, k, v)


def flash_bwd_dq(q, k, v, g, lse, delta, *, mask_fn=None, score_fn=None,
                 mask_type="causal", window=512, prefix_len=0,
                 block_q=256, block_kv=512, scale=1.0, canonical_mask=False):
    """Raw dQ kernel. ``lse``/``delta``: [B, Hq, 1, Sq] fp32."""
    B, Hq, Sq, D = q.shape
    _, Hkv, Skv, _ = k.shape
    G = Hq // Hkv
    bq = min(block_q, Sq)
    bkv = min(block_kv, Skv)
    _check_divisible(Sq, bq, Skv, bkv)
    nq = Sq // bq
    nkv = Skv // bkv
    kv_lo, kv_hi = _kv_range(mask_type, window, prefix_len, bq, bkv, nkv)
    full_tile = (_full_tile_fn(mask_type, window, prefix_len, bq, bkv)
                 if canonical_mask else None)

    def kv_index(b, h, i, j):
        jc = jnp.clip(j, jnp.minimum(kv_lo(i), nkv - 1),
                      jnp.maximum(kv_hi(i) - 1, 0))
        return (b, h // G, jc, 0)

    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale,
                          mask_fn=mask_fn, score_fn=score_fn,
                          kv_lo=kv_lo, kv_hi=kv_hi, nkv=nkv,
                          full_tile=full_tile),
        grid=(B, Hq, nq, nkv),
        in_specs=[
            _vmem_spec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            _vmem_spec((1, 1, bkv, D), kv_index),
            _vmem_spec((1, 1, bkv, D), kv_index),
            _vmem_spec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            _vmem_spec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
            _vmem_spec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_specs=_vmem_spec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Sq, D), q.dtype),
        scratch_shapes=[_scratch((bq, D))],
        compiler_params=_compiler_params(3, 4),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)


def flash_bwd_dkv(q, k, v, g, lse, delta, *, mask_fn=None, score_fn=None,
                  mask_type="causal", window=512, prefix_len=0,
                  block_q=256, block_kv=512, scale=1.0, canonical_mask=False):
    """Raw dK/dV kernel. Returns per-QUERY-head grads [B, Hq, Skv, D]
    (caller reduces GQA groups)."""
    B, Hq, Sq, D = q.shape
    _, Hkv, Skv, _ = k.shape
    G = Hq // Hkv
    bq = min(block_q, Sq)
    bkv = min(block_kv, Skv)
    _check_divisible(Sq, bq, Skv, bkv)
    nq = Sq // bq
    nkv = Skv // bkv
    q_lo, q_hi = _q_range(mask_type, window, prefix_len, bq, bkv, nq)
    full_tile = (_full_tile_fn(mask_type, window, prefix_len, bq, bkv)
                 if canonical_mask else None)

    def q_index(b, h, i, j):
        jc = jnp.clip(j, jnp.minimum(q_lo(i), nq - 1),
                      jnp.maximum(q_hi(i) - 1, 0))
        return (b, h, jc, 0)

    def stat_index(b, h, i, j):
        jc = jnp.clip(j, jnp.minimum(q_lo(i), nq - 1),
                      jnp.maximum(q_hi(i) - 1, 0))
        return (b, h, 0, jc)

    return pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale,
                          mask_fn=mask_fn, score_fn=score_fn,
                          q_lo=q_lo, q_hi=q_hi, nq=nq,
                          full_tile=full_tile),
        grid=(B, Hq, nkv, nq),
        in_specs=[
            _vmem_spec((1, 1, bq, D), q_index),
            _vmem_spec((1, 1, bkv, D), lambda b, h, i, j: (b, h // G, i, 0)),
            _vmem_spec((1, 1, bkv, D), lambda b, h, i, j: (b, h // G, i, 0)),
            _vmem_spec((1, 1, bq, D), q_index),
            _vmem_spec((1, 1, 1, bq), stat_index),
            _vmem_spec((1, 1, 1, bq), stat_index),
        ],
        out_specs=[
            _vmem_spec((1, 1, bkv, D), lambda b, h, i, j: (b, h, i, 0)),
            _vmem_spec((1, 1, bkv, D), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Skv, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hq, Skv, D), v.dtype),
        ],
        scratch_shapes=[_scratch((bkv, D)), _scratch((bkv, D))],
        compiler_params=_compiler_params(3, 4),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)


# -- host-side wrapper -------------------------------------------------------
def _attention_core(
    mask_fn, score_fn, mask_type: str, window: int, prefix_len: int,
    block_q: int, block_kv: int, scale: float, canonical_mask: bool = False,
):
    """Build the custom-vjp flash attention for a fixed mask/score program.

    Inputs (to the returned fn): q [B, Hq, Sq, D], k/v [B, Hkv, Skv, D].
    Output: o [B, Hq, Sq, D]. ``scale`` is baked in (nondiff).
    """
    kw = dict(mask_fn=mask_fn, score_fn=score_fn, mask_type=mask_type,
              window=window, prefix_len=prefix_len, block_q=block_q,
              block_kv=block_kv, scale=scale, canonical_mask=canonical_mask)

    @jax.custom_vjp
    def attn(q, k, v):
        o, _ = _fwd(q, k, v)
        return o

    def _fwd(q, k, v):
        o, lse = flash_fwd(q, k, v, **kw)
        return o, (q, k, v, o, lse)

    def _bwd(res, g):
        q, k, v, o, lse = res
        B, Hq, Sq, D = q.shape
        _, Hkv, Skv, _ = k.shape
        G = Hq // Hkv
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)[:, :, None, :]  # [B,Hq,1,Sq], lse layout
        dq = flash_bwd_dq(q, k, v, g, lse, delta, **kw)
        dk_h, dv_h = flash_bwd_dkv(q, k, v, g, lse, delta, **kw)
        # GQA: reduce per-query-head dK/dV over each group
        if G > 1:
            dk = dk_h.reshape(B, Hkv, G, Skv, D).sum(axis=2).astype(k.dtype)
            dv = dv_h.reshape(B, Hkv, G, Skv, D).sum(axis=2).astype(v.dtype)
        else:
            dk, dv = dk_h, dv_h
        return dq, dk, dv

    attn.defvjp(_fwd, _bwd)
    return attn


@functools.lru_cache(maxsize=64)
def _cached_core(mask_fn, score_fn, mask_type, window, prefix_len, block_q,
                 block_kv, scale, canonical_mask=False):
    return _attention_core(mask_fn, score_fn, mask_type, window, prefix_len,
                           block_q, block_kv, scale, canonical_mask)


# Defaults from an on-chip sweep (scripts/bench_attention.py) on TPU v5e:
# (256, 512) is within noise of the best (block_q, block_kv) across
# seq 1024-8192 for D in {64, 128}; override per-call or via env.
_DEF_BLOCK_Q = int(os.environ.get("FLASH_BLOCK_Q", 256))
_DEF_BLOCK_KV = int(os.environ.get("FLASH_BLOCK_KV", 512))


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask_type: str = "causal",
    window_size: int = 512,
    prefix_len: int = 0,
    scale: Optional[float] = None,
    block_q: int = _DEF_BLOCK_Q,
    block_kv: int = _DEF_BLOCK_KV,
    mask_fn: Optional[Callable] = None,
    score_fn: Optional[Callable] = None,
    precision: Optional[str] = None,
) -> jnp.ndarray:
    """Flash attention on [B, S, H, D] layout (framework convention).

    ``mask_type`` selects the block-sparsity plan (causal / sliding_window /
    prefix_lm / full); ``mask_fn``/``score_fn`` override the in-tile
    predicate (flex path): ``mask_fn(row, col) -> bool``,
    ``score_fn(scores, row, col, head) -> scores``.

    ``precision`` (model.matmul_precision): "bf16" casts q/k/v; "int8"
    quantizes them onto the symmetric int8 grid with per-row amax scales
    (:func:`quantize_operand_int8`) — loss-parity gated vs bf16 in the
    test suite; the backward stays full precision either way.
    """
    precision = check_matmul_precision(precision)
    if precision == "int8":
        q = quantize_operand_int8(q)
        k = quantize_operand_int8(k)
        v = quantize_operand_int8(v)
    elif precision == "bf16":
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    B, Sq, Hq, D = q.shape
    _, Skv, Hkv, _ = k.shape
    scale = (D ** -0.5) if scale is None else scale

    block_q = fit_block(block_q, Sq)
    block_kv = fit_block(block_kv, Skv)

    from . import masks as M

    # Canonical = the in-tile predicate provably equals the mask_type plan:
    # either we derive it here, or the caller (flex path) passes a
    # builder-tagged mod whose _plan matches (masks.py tags every named
    # builder) — then interior tiles may skip in-tile masking.
    plan = getattr(mask_fn, "_plan", None)
    canonical = mask_fn is None or (
        plan is not None
        and plan[0] == mask_type
        and (mask_type != "sliding_window" or plan[1] == window_size)
        and (mask_type != "prefix_lm" or plan[2] == prefix_len)
    )
    if mask_fn is None:
        mask_fn = {
            "causal": M.causal(),
            "sliding_window": M.sliding_window(window_size),
            "prefix_lm": M.prefix_lm(prefix_len),
            "full": None,
        }[mask_type]

    bq = min(block_q, Sq)
    bkv = min(block_kv, Skv)
    if Sq % bq or Skv % bkv or Hq % Hkv:
        # Odd sizes: reference path with the SAME mask and score program
        # (kernel-style score_fn adapted to the [B, Hkv, G, Sq, Skv] layout).
        from .attention import reference_attention

        ref_score = None
        if score_fn is not None:
            G = max(Hq // max(Hkv, 1), 1)
            head_grid = jnp.arange(Hkv * G).reshape(Hkv, G)

            def ref_score(s, q_idx, k_idx):
                return score_fn(s, q_idx[None, None, None],
                                k_idx[None, None, None],
                                head_grid[None, :, :, None, None])

        return reference_attention(q, k, v, mask_mod=mask_fn, score_mod=ref_score, scale=scale)

    core = _cached_core(mask_fn, score_fn, mask_type, window_size, prefix_len,
                        block_q, block_kv, float(scale), canonical)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = core(qt, kt, vt)
    return o.transpose(0, 2, 1, 3)
