"""Tiled flash attention — Pallas TPU kernels, forward + backward.

The reference's "FlashAttention" materializes the full [B,H,S,S] score
matrix ("Simple approach without tiling for now", reference:
models/attention/flash_attention.py:100,134-151). This is the real thing:

- forward: online-softmax accumulation over KV tiles in VMEM; scores never
  exist beyond one [block_q, block_kv] tile; fp32 accumulators; MXU matmuls
  via ``dot_general(..., preferred_element_type=f32)``;
- block sparsity: per-mask-type KV tile ranges (causal skips the upper
  triangle, sliding-window skips everything outside the band) — skipped
  tiles cost nothing;
- backward: recomputation-based (saves only O and the logsumexp), split
  into a dQ kernel (grid over Q tiles) and a dK/dV kernel (grid over KV
  tiles), the standard flash-attention-2 decomposition;
- GQA: native — each query head reads its KV group's tile; dK/dV are
  accumulated per query head and group-reduced outside the kernel;
- masks/score mods are traceable index-lattice functions (ops/masks.py)
  traced INTO the kernel, which is what makes flex_attention.py a thin
  wrapper over the same machinery.

Runs in Pallas interpret mode off-TPU, so the same code path is exercised
by the CPU test suite.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .masks import NEG_INF, MaskMod, ScoreMod

try:  # pltpu only resolves on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vmem_spec(block_shape=None, index_map=None):
    kwargs = {}
    if _VMEM is not None and not _interpret():
        kwargs["memory_space"] = _VMEM
    if block_shape is None:
        return pl.BlockSpec(**kwargs)
    return pl.BlockSpec(block_shape, index_map, **kwargs)


# -- tile-range planners (block sparsity per mask type) ----------------------
def _kv_range(mask_type: str, window: int, prefix_len: int, block_q: int, block_kv: int,
              num_kv_blocks: int):
    """(qi -> lo, qi -> hi) KV-tile bounds for a given query tile."""

    def lo(qi):
        if mask_type == "sliding_window":
            # row_min = qi*bq; cols >= row_min - window + 1 can contribute,
            # but the prefix region [0, prefix) never applies here.
            return jnp.maximum((qi * block_q - window + 1) // block_kv, 0)
        return jnp.int32(0)

    def hi(qi):
        if mask_type in ("causal", "sliding_window"):
            return jnp.minimum(pl.cdiv(qi * block_q + block_q, block_kv), num_kv_blocks)
        if mask_type == "prefix_lm":
            causal_hi = pl.cdiv(qi * block_q + block_q, block_kv)
            return jnp.minimum(jnp.maximum(causal_hi, pl.cdiv(prefix_len, block_kv)), num_kv_blocks)
        return jnp.int32(num_kv_blocks)

    return lo, hi


def _q_range(mask_type: str, window: int, prefix_len: int, block_q: int, block_kv: int,
             num_q_blocks: int):
    """(ki -> lo, ki -> hi) Q-tile bounds for a given KV tile (backward)."""

    def lo(ki):
        if mask_type in ("causal", "sliding_window"):
            # first q row that can see this kv tile is its own diagonal row
            return (ki * block_kv) // block_q
        # full / prefix_lm: every q tile can reach every kv tile
        return jnp.int32(0)

    def hi(ki):
        if mask_type == "sliding_window":
            # rows < col_max + window
            return jnp.minimum(pl.cdiv(ki * block_kv + block_kv - 1 + window, block_q) + 1,
                               num_q_blocks)
        return jnp.int32(num_q_blocks)

    return lo, hi


# -- forward kernel ----------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_kv,
                mask_fn, score_fn, kv_lo, kv_hi):
    qi = pl.program_id(2)
    h = pl.program_id(1)
    # Matmul operands stay in their storage dtype (bf16 in training) so the
    # MXU runs at full rate; accumulation is fp32 via preferred_element_type.
    q = q_ref[0, 0]
    bq, d = q.shape
    row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_kv), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        v = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, (bq, block_kv), 1)
        if score_fn is not None:
            s = score_fn(s, row, col, h)
        if mask_fn is not None:
            s = jnp.where(mask_fn(row, col), s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(kv_lo(qi), kv_hi(qi), body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse is laid out [B, H, 1, Sq]: the singleton dim keeps the block's
    # second-to-last dim equal to the array dim, satisfying TPU (8, 128)
    # tiling without padding lse out to 128 lanes.
    lse_ref[0, 0, 0] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)


# -- backward kernels --------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, block_kv, mask_fn, score_fn, kv_lo, kv_hi):
    qi = pl.program_id(2)
    h = pl.program_id(1)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, 0].astype(jnp.float32)
    delta = delta_ref[0, 0, 0].astype(jnp.float32)
    bq, d = q.shape
    row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_kv), 0)

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        v = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        s_raw = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, (bq, block_kv), 1)
        s = score_fn(s_raw, row, col, h) if score_fn is not None else s_raw
        if mask_fn is not None:
            s = jnp.where(mask_fn(row, col), s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        d_mod = getattr(score_fn, "_d_score", None) if score_fn is not None else None
        if d_mod is not None:  # non-additive score mod: chain through its Jacobian
            ds = ds * d_mod(s_raw, row, col, h)
        ds = ds * scale
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(kv_lo(qi), kv_hi(qi), body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
                    scale, block_q, mask_fn, score_fn, q_lo, q_hi):
    ki = pl.program_id(2)
    h = pl.program_id(1)
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    bkv, d = k.shape
    col = ki * bkv + jax.lax.broadcasted_iota(jnp.int32, (block_q, bkv), 1)

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(j * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(j * block_q, block_q), :]
        lse = lse_ref[0, 0, 0, pl.ds(j * block_q, block_q)].astype(jnp.float32)
        delta = delta_ref[0, 0, 0, pl.ds(j * block_q, block_q)].astype(jnp.float32)
        s_raw = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
        row = j * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bkv), 0)
        s = score_fn(s_raw, row, col, h) if score_fn is not None else s_raw
        if mask_fn is not None:
            s = jnp.where(mask_fn(row, col), s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        d_mod = getattr(score_fn, "_d_score", None) if score_fn is not None else None
        if d_mod is not None:
            ds = ds * d_mod(s_raw, row, col, h)
        ds = ds * scale
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bkv, d), jnp.float32)
    dv0 = jnp.zeros((bkv, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_lo(ki), q_hi(ki), body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# -- host-side wrapper -------------------------------------------------------
def _attention_core(
    mask_fn, score_fn, mask_type: str, window: int, prefix_len: int,
    block_q: int, block_kv: int, scale: float,
):
    """Build the custom-vjp flash attention for a fixed mask/score program.

    Inputs (to the returned fn): q [B, Hq, Sq, D], k/v [B, Hkv, Skv, D].
    Output: o [B, Hq, Sq, D]. ``scale`` is baked in (nondiff).
    """

    @jax.custom_vjp
    def attn(q, k, v):
        o, _ = _fwd(q, k, v)
        return o

    def _fwd(q, k, v):
        B, Hq, Sq, D = q.shape
        _, Hkv, Skv, _ = k.shape
        G = Hq // Hkv
        bq = min(block_q, Sq)
        bkv = min(block_kv, Skv)
        nq = Sq // bq
        nkv = Skv // bkv
        kv_lo, kv_hi = _kv_range(mask_type, window, prefix_len, bq, bkv, nkv)
        kernel = functools.partial(
            _fwd_kernel, scale=scale, block_kv=bkv, mask_fn=mask_fn,
            score_fn=score_fn, kv_lo=kv_lo, kv_hi=kv_hi)
        o, lse = pl.pallas_call(
            kernel,
            grid=(B, Hq, nq),
            in_specs=[
                _vmem_spec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
                _vmem_spec((1, 1, Skv, D), lambda b, h, i: (b, h // G, 0, 0)),
                _vmem_spec((1, 1, Skv, D), lambda b, h, i: (b, h // G, 0, 0)),
            ],
            out_specs=[
                _vmem_spec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
                _vmem_spec((1, 1, 1, bq), lambda b, h, i: (b, h, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, Hq, Sq, D), q.dtype),
                jax.ShapeDtypeStruct((B, Hq, 1, Sq), jnp.float32),
            ],
            interpret=_interpret(),
        )(q, k, v)
        return o, (q, k, v, o, lse)

    def _bwd(res, g):
        q, k, v, o, lse = res
        B, Hq, Sq, D = q.shape
        _, Hkv, Skv, _ = k.shape
        G = Hq // Hkv
        bq = min(block_q, Sq)
        bkv = min(block_kv, Skv)
        nq = Sq // bq
        nkv = Skv // bkv
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)[:, :, None, :]  # [B,Hq,1,Sq], lse layout

        kv_lo, kv_hi = _kv_range(mask_type, window, prefix_len, bq, bkv, nkv)
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, block_kv=bkv,
                              mask_fn=mask_fn, score_fn=score_fn,
                              kv_lo=kv_lo, kv_hi=kv_hi),
            grid=(B, Hq, nq),
            in_specs=[
                _vmem_spec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
                _vmem_spec((1, 1, Skv, D), lambda b, h, i: (b, h // G, 0, 0)),
                _vmem_spec((1, 1, Skv, D), lambda b, h, i: (b, h // G, 0, 0)),
                _vmem_spec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
                _vmem_spec((1, 1, 1, bq), lambda b, h, i: (b, h, 0, i)),
                _vmem_spec((1, 1, 1, bq), lambda b, h, i: (b, h, 0, i)),
            ],
            out_specs=_vmem_spec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            out_shape=jax.ShapeDtypeStruct((B, Hq, Sq, D), q.dtype),
            interpret=_interpret(),
        )(q, k, v, g, lse, delta)

        q_lo, q_hi = _q_range(mask_type, window, prefix_len, bq, bkv, nq)
        dk_h, dv_h = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, block_q=bq,
                              mask_fn=mask_fn, score_fn=score_fn,
                              q_lo=q_lo, q_hi=q_hi),
            grid=(B, Hq, nkv),
            in_specs=[
                _vmem_spec((1, 1, Sq, D), lambda b, h, i: (b, h, 0, 0)),
                _vmem_spec((1, 1, bkv, D), lambda b, h, i: (b, h // G, i, 0)),
                _vmem_spec((1, 1, bkv, D), lambda b, h, i: (b, h // G, i, 0)),
                _vmem_spec((1, 1, Sq, D), lambda b, h, i: (b, h, 0, 0)),
                _vmem_spec((1, 1, 1, Sq), lambda b, h, i: (b, h, 0, 0)),
                _vmem_spec((1, 1, 1, Sq), lambda b, h, i: (b, h, 0, 0)),
            ],
            out_specs=[
                _vmem_spec((1, 1, bkv, D), lambda b, h, i: (b, h, i, 0)),
                _vmem_spec((1, 1, bkv, D), lambda b, h, i: (b, h, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, Hq, Skv, D), k.dtype),
                jax.ShapeDtypeStruct((B, Hq, Skv, D), v.dtype),
            ],
            interpret=_interpret(),
        )(q, k, v, g, lse, delta)

        # GQA: reduce per-query-head dK/dV over each group
        if G > 1:
            dk = dk_h.reshape(B, Hkv, G, Skv, D).sum(axis=2).astype(k.dtype)
            dv = dv_h.reshape(B, Hkv, G, Skv, D).sum(axis=2).astype(v.dtype)
        else:
            dk, dv = dk_h, dv_h
        return dq, dk, dv

    attn.defvjp(_fwd, _bwd)
    return attn


@functools.lru_cache(maxsize=64)
def _cached_core(mask_fn, score_fn, mask_type, window, prefix_len, block_q, block_kv, scale):
    return _attention_core(mask_fn, score_fn, mask_type, window, prefix_len, block_q, block_kv, scale)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask_type: str = "causal",
    window_size: int = 512,
    prefix_len: int = 0,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_kv: int = 1024,
    mask_fn: Optional[Callable] = None,
    score_fn: Optional[Callable] = None,
) -> jnp.ndarray:
    """Flash attention on [B, S, H, D] layout (framework convention).

    ``mask_type`` selects the block-sparsity plan (causal / sliding_window /
    prefix_lm / full); ``mask_fn``/``score_fn`` override the in-tile
    predicate (flex path): ``mask_fn(row, col) -> bool``,
    ``score_fn(scores, row, col, head) -> scores``.
    """
    B, Sq, Hq, D = q.shape
    _, Skv, Hkv, _ = k.shape
    scale = (D ** -0.5) if scale is None else scale

    def _fit(block, dim):
        # Largest power-of-two block <= requested that divides the sequence,
        # so e.g. Sq=768 tiles at 256 instead of falling off to the O(S^2)
        # reference path. 128 is the TPU lane width / minimum tile.
        while block > 128 and dim % block:
            block //= 2
        return min(block, dim)

    block_q = _fit(block_q, Sq)
    block_kv = _fit(block_kv, Skv)

    from . import masks as M

    if mask_fn is None:
        mask_fn = {
            "causal": M.causal(),
            "sliding_window": M.sliding_window(window_size),
            "prefix_lm": M.prefix_lm(prefix_len),
            "full": None,
        }[mask_type]

    bq = min(block_q, Sq)
    bkv = min(block_kv, Skv)
    if Sq % bq or Skv % bkv or Hq % Hkv:
        # Odd sizes: reference path with the SAME mask and score program
        # (kernel-style score_fn adapted to the [B, Hkv, G, Sq, Skv] layout).
        from .attention import reference_attention

        ref_score = None
        if score_fn is not None:
            G = max(Hq // max(Hkv, 1), 1)
            head_grid = jnp.arange(Hkv * G).reshape(Hkv, G)

            def ref_score(s, q_idx, k_idx):
                return score_fn(s, q_idx[None, None, None],
                                k_idx[None, None, None],
                                head_grid[None, :, :, None, None])

        return reference_attention(q, k, v, mask_mod=mask_fn, score_mod=ref_score, scale=scale)

    core = _cached_core(mask_fn, score_fn, mask_type, window_size, prefix_len,
                        block_q, block_kv, float(scale))
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = core(qt, kt, vt)
    return o.transpose(0, 2, 1, 3)
