"""Weight-only quantization plane: per-channel int8 and packed int4.

Decode is HBM-bandwidth bound — every generated token re-reads the full
weight set — so serving throughput scales almost linearly with weight
bytes. This module is the single home for the weight-dtype transform the
rest of the stack composes:

- ``quantize_weights(params, "int8"|"int4")`` — tree walk producing the
  quantized leaf convention the forward paths consume (``models/llama.py
  _linear``, ``models/moe.py`` grouped/einsum experts):

  =========  ================================  =====================
  dtype      quantized leaf                    scale leaf
  =========  ================================  =====================
  int8       ``weight_q``  int8 [in, out]      ``weight_s`` f32 [out]
  int4       ``weight_q4`` int8 [in//2, out]   ``weight_s`` f32 [out]
  =========  ================================  =====================

  (MoE expert banks carry a leading ``E`` dim on both leaves.) Scales
  are symmetric per-OUTPUT-channel so they factor out of the
  contraction: dequant is a cheap multiply on the [.., out] matmul
  result, never a materialized fp weight copy.

- int4 packs TWO adjacent contraction-dim (``in``) rows per int8 byte:
  even row in the low nibble, odd row in the high nibble. Unpacking is
  two arithmetic shifts (``(p << 4) >> 4`` sign-extends the low nibble,
  ``p >> 4`` the high one) that XLA fuses into the consuming matmul.
  Packing along ``in`` (not ``out``) keeps ``weight_s`` [out] aligned
  with the unpacked result and halves the dim the fsdp/tp sharding
  rules already split evenly.

- ``*_np`` twins implement the same math in NumPy for the
  checkpoint-load path (``checkpoint/manager.py shard_arrays``), where
  each device's ``make_array_from_callback`` slice is quantized
  host-side WITHOUT ever materializing an fp replica on device.

Embeddings, the output head, norms, biases and MoE routers always stay
full precision — they set logit quality and are a small fraction of the
bytes. The existing int8 KV-cache quartet (``k_q/k_s/v_q/v_s``)
composes freely: weights and cache both cross HBM at <= 1 byte/elem.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]

WEIGHT_DTYPES = ("fp", "int8", "int4")

# Dotted-path pattern for the linear weights the quantize-on-load path
# transforms (checkpoint keys are flat dotted paths). Embeddings, output
# head, norms, biases and MoE routers never match.
QUANT_LEAF_RE = re.compile(
    r"(attention\.w[qkvo]|feed_forward\.w_(gate|up|down)|"
    r"experts\.w_(gate|up|down))\.weight$")

_INT8_MAX = 127.0
_INT4_MAX = 7.0  # symmetric: [-7, 7]; -8 stays unused


def check_weight_dtype(weight_dtype: str) -> str:
    wd = str(weight_dtype or "fp").lower()
    if wd in ("fp32", "bf16", "none", ""):
        wd = "fp"
    if wd not in WEIGHT_DTYPES:
        raise ValueError(f"unknown weight_dtype {weight_dtype!r} "
                         f"(expected one of {WEIGHT_DTYPES})")
    return wd


def quantizable_path(path: str) -> bool:
    """Whether a flat checkpoint key names a quantizable linear weight."""
    return QUANT_LEAF_RE.search(path) is not None


# -- per-channel scales ------------------------------------------------------
def channel_scales(w, bits: int = 8):
    """Symmetric per-output-channel scales over the contraction dim.

    ``w`` is [in, out] (axis 0 contracts) or [E, in, out] (axis 1
    contracts). Returns f32 scales shaped [out] / [E, out]."""
    xp = np if isinstance(w, np.ndarray) else jnp
    axis = 0 if w.ndim == 2 else 1
    qmax = _INT8_MAX if bits == 8 else _INT4_MAX
    s = xp.max(xp.abs(w.astype(xp.float32)), axis=axis) / qmax
    return xp.where(s == 0, 1.0, s).astype(xp.float32)


def _quantize_values(w, s, bits: int):
    """int8-stored quantized values for precomputed scales ``s``."""
    xp = np if isinstance(w, np.ndarray) else jnp
    qmax = _INT8_MAX if bits == 8 else _INT4_MAX
    se = s[None] if w.ndim == 2 else s[:, None, :]
    q = xp.clip(xp.round(w.astype(xp.float32) / se), -qmax, qmax)
    return q.astype(xp.int8)


# -- int4 packing ------------------------------------------------------------
def pack_int4(q):
    """Pack int8-stored int4 values ([-7, 7]) two-per-byte along the
    contraction dim: row 2i -> low nibble, row 2i+1 -> high nibble.
    [in, out] -> [in//2, out] (or [E, in, out] -> [E, in//2, out])."""
    xp = np if isinstance(q, np.ndarray) else jnp
    axis = q.ndim - 2
    if q.shape[axis] % 2:
        raise ValueError(f"int4 packing needs an even contraction dim, "
                         f"got shape {tuple(q.shape)}")
    if axis == 0:
        even, odd = q[0::2], q[1::2]
    else:
        even, odd = q[:, 0::2], q[:, 1::2]
    # Low nibble keeps only the value bits; the high nibble's shift wraps
    # mod 256 — both exact for values in [-8, 7].
    return ((odd.astype(xp.int8) << 4) | (even.astype(xp.int8) & 0x0F)) \
        .astype(xp.int8)


def unpack_int4(packed):
    """Inverse of :func:`pack_int4`: [in//2, out] -> int8 [in, out].

    Pure shifts — arithmetic ``>>`` on int8 sign-extends, so the low
    nibble round-trips through ``(p << 4) >> 4`` and the high nibble is
    just ``p >> 4``. XLA fuses both into the consuming matmul."""
    xp = np if isinstance(packed, np.ndarray) else jnp
    p = packed.astype(xp.int8)
    low = (p << 4) >> 4
    high = p >> 4
    axis = packed.ndim - 1  # stack position after the contraction dim
    out = xp.stack([low, high], axis=axis)
    shape = list(packed.shape)
    shape[-2] *= 2
    return out.reshape(shape)


# -- leaf + tree transforms --------------------------------------------------
def quantize_leaf(w, weight_dtype: str) -> Dict[str, Any]:
    """One linear weight -> its quantized leaf dict (see module doc)."""
    wd = check_weight_dtype(weight_dtype)
    if wd == "fp":
        return {"weight": w}
    bits = 8 if wd == "int8" else 4
    s = channel_scales(w, bits)
    q = _quantize_values(w, s, bits)
    if wd == "int8":
        return {"weight_q": q, "weight_s": s}
    return {"weight_q4": pack_int4(q), "weight_s": s}


def dequantize_leaf(p: Dict[str, Any], dtype=jnp.float32):
    """fp reference weight for a quantized leaf dict (tests/parity only —
    the forward paths never call this; they keep dequant in the matmul
    epilogue)."""
    xp = np if isinstance(p.get("weight_s"), np.ndarray) else jnp
    if "weight_q4" in p:
        q = unpack_int4(p["weight_q4"])
    elif "weight_q" in p:
        q = p["weight_q"]
    else:
        return p["weight"]
    s = p["weight_s"]
    se = s[None] if q.ndim == 2 else s[:, None, :]
    return (q.astype(xp.float32) * se).astype(dtype)


def _walk_linear(p: Params, weight_dtype: str) -> Params:
    if "weight" not in p or p["weight"].ndim not in (2, 3):
        return dict(p)
    out = {k: v for k, v in p.items() if k != "weight"}
    out.update(quantize_leaf(p["weight"], weight_dtype))
    return out


def quantize_weights(params: Params, weight_dtype: str) -> Params:
    """Weight-only quantization of a full param tree for serving.

    Quantizes every layer linear — attention wq/wk/wv/wo, the dense
    SwiGLU w_gate/w_up/w_down AND the stacked MoE expert banks (per
    (expert, out-channel) scales). Embeddings, the output head, norms,
    biases and MoE routers stay fp. ``"fp"`` is the identity."""
    wd = check_weight_dtype(weight_dtype)
    if wd == "fp":
        return params

    out = {k: v for k, v in params.items() if k != "layers"}
    new_layers = []
    for layer in params["layers"]:
        nl = dict(layer)
        nl["attention"] = {k: _walk_linear(v, wd) if isinstance(v, dict)
                           else v for k, v in layer["attention"].items()}
        ff = layer["feed_forward"]
        if "experts" in ff:  # MoE: quantize the banks, router stays fp
            nff = dict(ff)
            nff["experts"] = {k: _walk_linear(v, wd) if isinstance(v, dict)
                              else v for k, v in ff["experts"].items()}
            nl["feed_forward"] = nff
        elif "w_gate" in ff:
            nl["feed_forward"] = {k: _walk_linear(v, wd)
                                  if isinstance(v, dict) else v
                                  for k, v in ff.items()}
        new_layers.append(nl)
    out["layers"] = new_layers
    return out


def weight_dtype_of(params: Params) -> str:
    """Detect the weight dtype of a param tree ("fp" | "int8" | "int4")
    from its leaf naming convention — the hot-swap path uses this to
    quantize incoming fp checkpoints into a quantized ``like``."""
    found = "fp"
    for path in jax.tree_util.tree_flatten_with_path(params)[0]:
        keys = "".join(str(getattr(k, "key", "")) for k in path[0])
        if "weight_q4" in keys:
            return "int4"
        if "weight_q" in keys:
            found = "int8"
    return found


# -- NumPy twins for the checkpoint-load path --------------------------------
def quantized_key_shapes(path: str, shape: Tuple[int, ...],
                         weight_dtype: str
                         ) -> Optional[Dict[str, Tuple[int, ...]]]:
    """For a flat checkpoint key: the quantized keys + shapes it loads
    as under ``weight_dtype``, or None when it stays fp. Lets callers
    (shard_arrays, byte accounting) plan placement without touching
    data."""
    wd = check_weight_dtype(weight_dtype)
    if wd == "fp" or not quantizable_path(path) or len(shape) not in (2, 3):
        return None
    base = path[: -len(".weight")]
    contraction = shape[-2]
    s_shape = shape[:-2] + (shape[-1],)
    if wd == "int8":
        return {base + ".weight_q": tuple(shape), base + ".weight_s": s_shape}
    if contraction % 2:
        return None  # odd contraction dim: leave fp rather than pad
    q4 = shape[:-2] + (contraction // 2, shape[-1])
    return {base + ".weight_q4": q4, base + ".weight_s": s_shape}


def quantize_slice_np(arr: np.ndarray, scales: np.ndarray,
                      idx, weight_dtype: str) -> np.ndarray:
    """Quantize ONE device's slice of a host fp array.

    ``idx`` indexes the QUANTIZED shape (for int4 the contraction dim is
    packed, so the fp rows covered are ``2*start : 2*stop``); ``scales``
    are the full-array per-channel scales (a global reduction — computed
    once on host, sliced per device here). Only the slice's quantized
    bytes are ever handed to the device."""
    wd = check_weight_dtype(weight_dtype)
    bits = 8 if wd == "int8" else 4
    idx = tuple(idx) if isinstance(idx, tuple) else (idx,)
    # Normalize to one slice per dim.
    full = [slice(None)] * arr.ndim
    for i, sl in enumerate(idx):
        full[i] = sl
    caxis = arr.ndim - 2
    if wd == "int4":
        sl = full[caxis]
        start = 0 if sl.start is None else sl.start
        stop = arr.shape[caxis] // 2 if sl.stop is None else sl.stop
        fp_idx = list(full)
        fp_idx[caxis] = slice(2 * start, 2 * stop)
        w = arr[tuple(fp_idx)]
    else:
        w = arr[tuple(full)]
    # Scale index: leading expert dims + the out dim (last).
    s_idx = tuple(full[:caxis]) + (full[-1],)
    s = scales[s_idx]
    q = _quantize_values(w, s, bits)
    return pack_int4(q) if wd == "int4" else q


def dequantize_np(q: np.ndarray, s: np.ndarray,
                  packed: bool) -> np.ndarray:
    """Host-side reference dequant (tests)."""
    if packed:
        q = unpack_int4(q)
    se = s[None] if q.ndim == 2 else s[:, None, :]
    return q.astype(np.float32) * se


# -- byte accounting ---------------------------------------------------------
def weight_plane_bytes(params: Params) -> int:
    """Total bytes of every param leaf as stored (quantized trees count
    their int + scale bytes) — the ``serve_weight_bytes`` gauge."""
    return sum(int(np.dtype(l.dtype).itemsize) * int(l.size)
               for l in jax.tree_util.tree_leaves(params))
