"""Architecture registry.

The reference resolves ``model.architecture`` by importlib against
``models.<arch>`` with an mlx_lm fallback (reference:
core/training.py:1018-1091). Here it's an explicit registry: every
architecture provides ``(args_cls, init_params, forward, loss_fn)``.
"llama_standard" maps to llama with simple attention forced (reference keeps
a separate near-identical file models/llama_standard.py; one model +
config-selected attention is the same capability without the duplication).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple


class Architecture(NamedTuple):
    name: str
    args_cls: Any
    init_params: Callable
    forward: Callable
    loss_fn: Callable
    force_attention: str | None = None


_REGISTRY: Dict[str, Architecture] = {}


def register(arch: Architecture) -> None:
    _REGISTRY[arch.name] = arch


def resolve_architecture(name: str) -> Architecture:
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown architecture {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def _register_builtin() -> None:
    from . import llama

    base = Architecture("llama", llama.LlamaArgs, llama.init_params, llama.forward, llama.loss_fn)
    register(base)
    register(base._replace(name="llama_standard", force_attention="simple"))
    register(base._replace(name="llama_flash", force_attention="flash"))


_register_builtin()
