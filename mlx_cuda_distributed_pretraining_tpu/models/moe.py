"""Mixture-of-Experts feed-forward block (real, TPU-first).

The reference *declares* MoE fields (``num_local_experts`` /
``num_experts_per_tok``, reference: models/llama.py:40-41 and config plumbing
core/training.py:1055-1056) but never builds an MoE layer. Here they drive a
real block with two interchangeable dispatch implementations
(``moe.impl`` in the model config, ``LlamaArgs.moe_impl``):

- ``grouped`` (default) — MegaBlocks-style **dropless** routing: fp32 router
  → top-k → stable argsort by expert id → gather into a per-expert
  block-aligned buffer → grouped GEMM SwiGLU (ops/grouped_matmul.py) →
  scatter-add combine. Every shape is static (sort + gather, no
  data-dependent shapes) and **no token is ever dropped** — there is no
  expert capacity. On ``ep`` meshes the sorted dispatch drops below GSPMD
  via ``parallel/compat.shard_map``: each shard routes its local tokens,
  exchanges rows with the owning expert shard through a pair of
  ``all_to_all`` collectives with static per-destination send slots, and
  scatter-adds the returned rows (mirroring how
  ``ops/fused_ce.fused_cross_entropy_sp`` handles sp). Send capacity
  defaults to worst-case (``moe_ep_capacity_factor: 0``) so the exchange
  is dropless too; a positive factor trades all-to-all volume for
  (counted) overflow drops.
- ``einsum`` — the GShard/Switch dispatch/combine-tensor formulation kept
  as the parity oracle: top-k gating, per-group expert capacity ``C``,
  one-hot dispatch ``[B, S, E, C]``; tokens beyond capacity are dropped to
  the residual path. Expert parallelism happens implicitly under GSPMD via
  the ``ep``-sharded ``[E, ...]`` weight stacking.

Router math runs in fp32 regardless of compute dtype. The load-balancing
aux loss (Switch Transformer style) and optional router z-loss are computed
over **real tokens only** — ``moe_group_size`` padding rows are excluded —
and returned pre-scaled.

Routing observability rides a trace-time tap (:func:`routing_stats_tap`):
when a tap is active, ``transformer_block`` converts each layer's recorded
expert-load / dropped-token stats into return values (so they survive
``jax.checkpoint`` and ``lax.scan`` boundaries) and ``loss_fn`` surfaces
them to the train step.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import grouped_matmul as gm

Params = Dict[str, Any]


def init_moe_params(keys, args, dtype=jnp.float32) -> Params:
    """Stacked expert weights [E, ...] + router [D, E].

    ``keys`` is an iterator of PRNG keys (4 consumed).
    """
    D, I, E = args.hidden_size, args.intermediate_size, args.num_local_experts
    std = 0.02
    res_std = std / (2 * args.num_layers) ** 0.5

    def dense(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dtype)

    return {
        "router": {"weight": dense(next(keys), (D, E), std)},
        "experts": {
            "w_gate": {"weight": dense(next(keys), (E, D, I), std)},
            "w_up": {"weight": dense(next(keys), (E, D, I), std)},
            "w_down": {"weight": dense(next(keys), (E, I, D), res_std)},
        },
    }


# -- routing-stats tap -------------------------------------------------------
# Stats are traced values; a side list only works when producer and consumer
# sit in the SAME trace. transformer_block therefore re-emits tap entries as
# return values across jax.checkpoint / lax.scan boundaries, and loss_fn
# returns the merged stats through value_and_grad's aux.
_TAPS: List[list] = []

STAT_KEYS = ("moe_load", "moe_dropped")


@contextlib.contextmanager
def routing_stats_tap():
    """Collect per-layer routing stats dicts recorded while tracing."""
    tap: list = []
    _TAPS.append(tap)
    try:
        yield tap
    finally:
        _TAPS.pop()


def stats_tap_active() -> bool:
    return bool(_TAPS)


def record_stats(stats: Dict[str, jnp.ndarray]) -> None:
    if _TAPS:
        _TAPS[-1].append(stats)


def zero_stats(num_experts: int) -> Dict[str, jnp.ndarray]:
    return {
        "moe_load": jnp.zeros((num_experts,), jnp.float32),
        "moe_dropped": jnp.zeros((), jnp.float32),
    }


def merge_stats(entries, num_experts: int) -> Dict[str, jnp.ndarray]:
    """Sum a list of stats dicts (layers) into one."""
    total = zero_stats(num_experts)
    for e in entries:
        total = {k: total[k] + e[k] for k in total}
    return total


def expert_capacity(seq_len: int, num_experts: int, k: int, capacity_factor: float) -> int:
    """Per-sequence slots each expert can accept (static). Einsum impl only —
    the grouped impl is dropless and has no capacity."""
    c = int(capacity_factor * k * seq_len / num_experts + 0.5)
    return max(1, min(c, seq_len * k))


def _dispatch_combine(
    probs: jnp.ndarray, k: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Build dispatch/combine tensors from router probabilities.

    probs [B, S, E] fp32 → dispatch [B, S, E, C] in {0,1},
    combine [B, S, E, C] carrying renormalized top-k gate weights.
    Tokens beyond an expert's capacity are dropped (their combine weight is
    zero, so the residual path carries them — standard Switch behavior).
    """
    B, S, E = probs.shape
    gate_w, gate_idx = jax.lax.top_k(probs, k)  # [B, S, K]
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)

    # Slot-flatten [S, K] -> S*K in token-major order so earlier tokens win
    # capacity; one-hot over experts per selection.
    oh = jax.nn.one_hot(gate_idx, E, dtype=probs.dtype)  # [B, S, K, E]
    ohf = oh.reshape(B, S * k, E)
    # Position of each selection within its expert's queue.
    pos = jnp.cumsum(ohf, axis=1) - ohf  # [B, S*K, E]
    pos_in_expert = (pos * ohf).sum(-1)  # [B, S*K]
    keep = ((pos_in_expert < capacity) & (ohf.sum(-1) > 0)).astype(probs.dtype)
    pos_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity, dtype=probs.dtype)
    # [B, S*K, E, C]
    dispatch_f = ohf[..., None] * pos_oh[:, :, None, :] * keep[..., None, None]
    combine_f = dispatch_f * gate_w.reshape(B, S * k)[..., None, None]
    dispatch = dispatch_f.reshape(B, S, k, E, capacity).sum(2)
    combine = combine_f.reshape(B, S, k, E, capacity).sum(2)
    return dispatch, combine


def load_balancing_loss(probs: jnp.ndarray, gate_idx_top1: jnp.ndarray, num_experts: int) -> jnp.ndarray:
    """Switch Transformer aux loss: E * Σ_e f_e · P_e where f_e is the
    fraction of tokens whose top-1 choice is e and P_e the mean router prob."""
    f = jnp.mean(jax.nn.one_hot(gate_idx_top1, num_experts, dtype=jnp.float32), axis=(0, 1))
    p = jnp.mean(probs, axis=(0, 1))
    return num_experts * jnp.sum(f * p)


def router_z_loss(router_logits: jnp.ndarray) -> jnp.ndarray:
    """Mean squared logsumexp of router logits (stabilizes router scale)."""
    z = jax.nn.logsumexp(router_logits, axis=-1)
    return jnp.mean(z * z)


# -- quantized expert banks ---------------------------------------------------
def _expert_bank(experts: Params, name: str, dtype):
    """Resolve one expert bank leaf to ``(weights, scales | None)``.

    Quantized banks (models/quantize.py: ``weight_q`` int8 [E, in, out]
    or packed ``weight_q4`` [E, in//2, out], scales [E, out]) store at
    <= 1 byte/elem; the unpack/cast happens here at the dispatch site
    and the per-(expert, out-channel) scale is applied by the caller on
    the matmul RESULT — after the grouped GEMM / einsum, never as a
    scaled fp weight copy."""
    leaf = experts[name]
    if "weight_q4" in leaf:
        from .quantize import unpack_int4

        return unpack_int4(leaf["weight_q4"]).astype(dtype), leaf["weight_s"]
    if "weight_q" in leaf:
        return leaf["weight_q"].astype(dtype), leaf["weight_s"]
    return leaf["weight"], None


def _maybe_dequant_experts(p: Params) -> Params:
    """fp view of a (possibly quantized) expert subtree — only for paths
    that ship the banks through shard_map operands (expert-parallel),
    where threading separate scale operands isn't worth the wiring."""
    experts = p["experts"]
    if not any(("weight_q" in leaf or "weight_q4" in leaf)
               for leaf in experts.values() if isinstance(leaf, dict)):
        return p
    from .quantize import dequantize_leaf

    return {**p, "experts": {
        name: {"weight": dequantize_leaf(leaf)}
        for name, leaf in experts.items()}}


# -- einsum (GShard/Switch) implementation -----------------------------------
def _einsum_moe(
    p: Params, x: jnp.ndarray, probs: jnp.ndarray, args
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense dispatch/combine einsum pipeline → (out, dropped_selections).

    Tokens are routed in fixed-size groups of ``moe_group_size``
    (GShard-style) so capacity — and with it the [G, g*K, E, C] dispatch
    tensors — stays constant as sequence length grows: memory is O(S), not
    O(S²). Pad rows carry uniform router probs (softmax of a zero row),
    exactly as if zero-padded activations had been routed; their combine
    output is sliced off, though they can steal a little tail-group
    capacity, which is standard.
    """
    B, S, D = x.shape
    E, K = args.num_local_experts, args.num_experts_per_tok

    g = min(int(getattr(args, "moe_group_size", 256) or 256), S)
    S_pad = ((S + g - 1) // g) * g
    if S_pad != S:
        x_in = jnp.pad(x, ((0, 0), (0, S_pad - S), (0, 0)))
        probs_in = jnp.pad(probs, ((0, 0), (0, S_pad - S), (0, 0)),
                           constant_values=1.0 / E)
    else:
        x_in, probs_in = x, probs
    xg = x_in.reshape(B * (S_pad // g), g, D)
    probs_g = probs_in.reshape(B * (S_pad // g), g, E)
    C = expert_capacity(g, E, K, getattr(args, "moe_capacity_factor", 1.25))

    dispatch, combine = _dispatch_combine(probs_g, K, C)
    # Kept selections per token (0..K), real rows only → overflow drops.
    kept = dispatch.sum((2, 3)).reshape(B, S_pad)[:, :S]
    dropped = jax.lax.stop_gradient(K * B * S - kept.sum())
    dispatch = dispatch.astype(x.dtype)

    # [G,g,E,C] x [G,g,D] -> [E,G,C,D]: the all-to-all under ep sharding.
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, xg)
    wg_, sg = _expert_bank(p["experts"], "w_gate", expert_in.dtype)
    wu, su = _expert_bank(p["experts"], "w_up", expert_in.dtype)
    wd, sd = _expert_bank(p["experts"], "w_down", expert_in.dtype)

    def scaled(y, s):  # per-(expert, out-channel) dequant epilogue
        return y if s is None else y * s[:, None, None, :].astype(y.dtype)

    h = jax.nn.silu(scaled(jnp.einsum("ebcd,edi->ebci", expert_in, wg_), sg)) * scaled(
        jnp.einsum("ebcd,edi->ebci", expert_in, wu), su
    )
    expert_out = scaled(jnp.einsum("ebci,eid->ebcd", h, wd), sd)
    out = jnp.einsum("bsec,ebcd->bsd", combine.astype(x.dtype), expert_out)
    return out.reshape(B, S_pad, D)[:, :S], dropped


# -- grouped (sort-based dropless) implementation ----------------------------
def _grouped_ffn(
    experts: Params,
    x_flat: jnp.ndarray,
    gate_idx: jnp.ndarray,
    gate_w: jnp.ndarray,
    num_experts: int,
    block_t: int,
    precision=None,
) -> jnp.ndarray:
    """Sorted dropless expert FFN over local tokens.

    x_flat [T, D], gate_idx [T, K] int32, gate_w [T, K] → out [T, D].
    Selections are stably sorted by expert id and scattered into a
    per-expert ``block_t``-aligned buffer (static size: every expert's group
    rounds up to a full tile), the three expert matmuls run as grouped
    GEMMs, and the gate-weighted rows scatter-add back. No capacity, no
    drops.
    """
    T, D = x_flat.shape
    K = gate_idx.shape[-1]
    TK = T * K
    ids = gate_idx.reshape(TK)
    tok = jnp.arange(TK, dtype=jnp.int32) // K

    counts = jnp.bincount(ids, length=num_experts)  # [E]
    padded = ((counts + block_t - 1) // block_t) * block_t
    p_off = jnp.concatenate([jnp.zeros((1,), padded.dtype), jnp.cumsum(padded)])
    raw_off = jnp.cumsum(counts) - counts  # group starts in sorted order

    order = jnp.argsort(ids, stable=True)  # token-major within each expert
    ids_s = ids[order]
    rank = jnp.arange(TK, dtype=jnp.int32) - raw_off[ids_s].astype(jnp.int32)
    dest = (p_off[ids_s] + rank).astype(jnp.int32)

    T_buf = gm.round_up(TK + num_experts * (block_t - 1), block_t)
    x_buf = jnp.zeros((T_buf, D), x_flat.dtype).at[dest].set(x_flat[tok[order]])

    gs = padded
    wg_, sg = _expert_bank(experts, "w_gate", x_buf.dtype)
    wu, su = _expert_bank(experts, "w_up", x_buf.dtype)
    wd, sd = _expert_bank(experts, "w_down", x_buf.dtype)
    if sg is not None or su is not None or sd is not None:
        # Expert id of each buffer row (pad rows clamp to the last group —
        # they are all-zero, any scale is fine).
        row_e = jnp.minimum(
            jnp.searchsorted(jnp.cumsum(gs), jnp.arange(T_buf), side="right"),
            num_experts - 1)

        def scaled(y, s):  # per-row dequant epilogue
            return y if s is None else y * s[row_e].astype(y.dtype)
    else:
        def scaled(y, s):
            return y

    h = jax.nn.silu(
        scaled(gm.gmm(x_buf, wg_, gs, block_t=block_t, precision=precision), sg)
    ) * scaled(gm.gmm(x_buf, wu, gs, block_t=block_t, precision=precision), su)
    y_buf = scaled(gm.gmm(h, wd, gs, block_t=block_t, precision=precision), sd)

    w_s = gate_w.reshape(TK)[order].astype(y_buf.dtype)
    out = jnp.zeros((T, D), x_flat.dtype).at[tok[order]].add(
        y_buf[dest] * w_s[:, None])
    return out


def _usable_ep_mesh(args, num_experts: int):
    """The mesh to drop below GSPMD with, or None for the local path.

    Requires a multi-device mesh whose axes are not already bound manual
    (i.e. we are not inside another shard_map, e.g. the pipeline stage
    body), and an expert count divisible by the ep axis.
    """
    from ..parallel.context import current_mesh

    mesh = current_mesh()
    if mesh is None or mesh.size == 1:
        return None
    ep = mesh.shape.get("ep", 1)
    if num_experts % max(ep, 1):
        return None
    try:
        from jax._src import core as _core

        active = set(_core.unsafe_get_axis_names())
    except Exception:  # pragma: no cover - private-API drift
        active = set()
    if active & set(mesh.axis_names):
        return None
    return mesh


def _grouped_moe_ep(
    p: Params, x: jnp.ndarray, gate_idx: jnp.ndarray, gate_w: jnp.ndarray,
    args, mesh,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel sorted dispatch under shard_map → (out, dropped).

    Each shard routes its local tokens, posts rows into per-destination
    send slots ([ep, cap, D], expert id → owning shard = id // E_loc),
    exchanges them with one ``all_to_all``, runs the local grouped FFN over
    its E/ep experts, and returns rows with a second ``all_to_all``; gate
    weighting and the combine scatter-add stay on the source shard, so
    gradients flow through the exchange untouched.

    ``cap`` (send slots per source→dest pair) is static:
    ``moe_ep_capacity_factor <= 0`` means worst-case (= local selections,
    dropless); a positive factor shrinks the exchange to
    ``factor · TK / ep`` and overflow beyond it is dropped and counted.
    """
    from ..parallel.compat import shard_map
    from ..parallel.sharding_rules import moe_dispatch_specs

    B, S, D = x.shape
    E, K = args.num_local_experts, args.num_experts_per_tok
    ep = max(mesh.shape.get("ep", 1), 1)
    e_loc = E // ep

    specs = moe_dispatch_specs(mesh)
    # Static per-shard token geometry (shard_map divides batch evenly).
    b_shards = 1
    for a in specs["batch_axes"]:
        b_shards *= mesh.shape.get(a, 1)
    t_loc = (B // b_shards) * S
    tk = t_loc * K
    factor = float(getattr(args, "moe_ep_capacity_factor", 0.0) or 0.0)
    cap = tk if factor <= 0 else max(1, min(tk, int(factor * tk / ep + 0.5)))
    block_t = gm.pick_block_t(ep * cap, e_loc)

    def body(x_l, gi_l, gw_l, wg_l, wu_l, wd_l):
        b_l, s_l, _ = x_l.shape
        T_l = b_l * s_l
        TK = T_l * K
        xf = x_l.reshape(T_l, D)
        ids = gi_l.reshape(TK)
        gwf = gw_l.reshape(TK)
        tok = jnp.arange(TK, dtype=jnp.int32) // K

        dest_shard = ids // e_loc
        local_eid = ids % e_loc

        # Slot assignment: stable sort by destination shard (token-major
        # fairness within each destination, like einsum capacity).
        order = jnp.argsort(dest_shard, stable=True)
        ds_s = dest_shard[order]
        cnt = jnp.bincount(dest_shard, length=ep)
        start = jnp.cumsum(cnt) - cnt
        rank = jnp.arange(TK, dtype=jnp.int32) - start[ds_s].astype(jnp.int32)
        keep = rank < cap
        slot = ds_s * cap + rank
        slot_put = jnp.where(keep, slot, ep * cap)  # OOB scatter = drop

        send_x = jnp.zeros((ep * cap, D), xf.dtype).at[slot_put].set(xf[tok[order]])
        send_id = jnp.full((ep * cap,), e_loc, jnp.int32).at[slot_put].set(
            local_eid[order])

        recv_x = jax.lax.all_to_all(
            send_x.reshape(ep, cap, D), "ep", split_axis=0, concat_axis=0,
            tiled=True)
        recv_id = jax.lax.all_to_all(
            send_id.reshape(ep, cap), "ep", split_axis=0, concat_axis=0,
            tiled=True)

        # Local grouped FFN over the E/ep resident experts; sentinel id
        # e_loc marks empty slots and sorts past every real group.
        R = ep * cap
        rx = recv_x.reshape(R, D)
        rid = recv_id.reshape(R)
        counts = jnp.bincount(rid, length=e_loc)  # sentinels fall off
        padded = ((counts + block_t - 1) // block_t) * block_t
        p_off = jnp.concatenate([jnp.zeros((1,), padded.dtype), jnp.cumsum(padded)])
        raw_off = jnp.cumsum(counts) - counts
        order2 = jnp.argsort(rid, stable=True)
        rid_s = rid[order2]
        real2 = rid_s < e_loc
        rid_c = jnp.minimum(rid_s, e_loc - 1)
        rank2 = jnp.arange(R, dtype=jnp.int32) - raw_off[rid_c].astype(jnp.int32)
        T_buf = gm.round_up(R + e_loc * (block_t - 1), block_t)
        dest2 = jnp.where(real2, (p_off[rid_c] + rank2).astype(jnp.int32), T_buf)

        x_buf = jnp.zeros((T_buf, D), rx.dtype).at[dest2].set(rx[order2])
        prec = getattr(args, "matmul_precision", None)
        h = jax.nn.silu(
            gm.gmm(x_buf, wg_l, padded, block_t=block_t, precision=prec)
        ) * gm.gmm(x_buf, wu_l, padded, block_t=block_t, precision=prec)
        y_buf = gm.gmm(h, wd_l, padded, block_t=block_t, precision=prec)

        y_sorted = y_buf[jnp.minimum(dest2, T_buf - 1)] * real2[:, None]
        y_recv = jnp.zeros((R, D), y_buf.dtype).at[order2].set(y_sorted)

        y_back = jax.lax.all_to_all(
            y_recv.reshape(ep, cap, D), "ep", split_axis=0, concat_axis=0,
            tiled=True).reshape(R, D)

        y_sel = y_back[jnp.minimum(slot, R - 1)] * keep[:, None]
        out = jnp.zeros((T_l, D), x_l.dtype).at[tok[order]].add(
            y_sel * gwf[order][:, None].astype(y_sel.dtype))

        dropped = jax.lax.psum(
            (TK - keep.sum()).astype(jnp.float32), tuple(mesh.axis_names))
        return out.reshape(b_l, s_l, D), dropped

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(specs["activation"], specs["gate"], specs["gate"],
                  specs["expert_weight"], specs["expert_weight"],
                  specs["expert_weight"]),
        out_specs=(specs["activation"], specs["replicated"]),
        check_vma=False,
    )
    p = _maybe_dequant_experts(p)  # ep ships fp banks through shard_map
    out, dropped = fn(
        x, gate_idx, gate_w,
        p["experts"]["w_gate"]["weight"],
        p["experts"]["w_up"]["weight"],
        p["experts"]["w_down"]["weight"],
    )
    return out, jax.lax.stop_gradient(dropped)


# -- block entry point -------------------------------------------------------
def moe_block(p: Params, x: jnp.ndarray, args) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [B, S, D] → (out [B, S, D], aux_loss scalar fp32).

    Routes through the impl selected by ``args.moe_impl`` (see module
    docstring). The returned aux term is **fully pre-scaled**:
    ``moe_aux_weight * load_balance + router_z_weight * z_loss``; callers
    add it to the CE loss unweighted. Aux is computed from real tokens only
    and is identical across impls (it depends on the router, not the
    dispatch).
    """
    B, S, D = x.shape
    E, K = args.num_local_experts, args.num_experts_per_tok
    impl = getattr(args, "moe_impl", "grouped") or "grouped"

    # Project in the activation dtype, then route in fp32: only the tiny
    # [B, S, E] logits are upcast, not the [B, S, D] activations — under
    # bf16 compute the old fp32 projection paid an activation-sized
    # convert plus a 2x-wide matmul for logits that top_k/softmax need at
    # fp32 anyway (caught by graftaudit's dtype-upcast rule).
    router_logits = (x @ p["router"]["weight"].astype(x.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)  # [B, S, E] fp32

    if impl == "einsum":
        out, dropped = _einsum_moe(p, x, probs, args)
        gate_idx = jax.lax.top_k(probs, K)[1]  # stats only
    elif impl == "grouped":
        gate_w, gate_idx = jax.lax.top_k(probs, K)  # [B, S, K]
        gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)
        mesh = _usable_ep_mesh(args, E)
        if mesh is not None:
            out, dropped = _grouped_moe_ep(p, x, gate_idx, gate_w, args, mesh)
        else:
            out = _grouped_ffn(
                p["experts"], x.reshape(B * S, D), gate_idx.reshape(B * S, K),
                gate_w.reshape(B * S, K), E,
                gm.pick_block_t(B * S * K, E),
                precision=getattr(args, "matmul_precision", None),
            ).reshape(B, S, D)
            dropped = jnp.zeros((), jnp.float32)
    else:
        raise ValueError(f"unknown moe impl {impl!r} (grouped|einsum)")

    aw = float(getattr(args, "moe_aux_weight", 0.0) or 0.0)
    zw = float(getattr(args, "router_z_weight", 0.0) or 0.0)
    aux = jnp.zeros((), jnp.float32)
    if aw:
        aux = aux + aw * load_balancing_loss(probs, jnp.argmax(router_logits, axis=-1), E)
    if zw:
        aux = aux + zw * router_z_loss(router_logits)

    if stats_tap_active():
        record_stats({
            "moe_load": jax.lax.stop_gradient(
                jnp.bincount(gate_idx.reshape(-1), length=E).astype(jnp.float32)),
            "moe_dropped": jax.lax.stop_gradient(dropped.astype(jnp.float32)),
        })
    return out, aux
