"""Mixture-of-Experts feed-forward block (real, TPU-first).

The reference *declares* MoE fields (``num_local_experts`` /
``num_experts_per_tok``, reference: models/llama.py:40-41 and config plumbing
core/training.py:1055-1056) but never builds an MoE layer. Here they drive a
real block, designed for XLA/GSPMD rather than translated from any GPU code:

- **Static shapes everywhere.** Routing uses the GShard/Switch
  dispatch/combine-tensor formulation: top-k gating, per-sequence expert
  capacity ``C``, one-hot dispatch ``[B, S, E, C]``. No gather/scatter with
  data-dependent shapes — everything is einsum, so it tiles onto the MXU and
  shards cleanly.
- **Expert parallelism by sharding, not message passing.** Expert weight
  tensors are stacked ``[E, ...]`` and sharded over the ``ep`` mesh axis
  (parallel/sharding_rules.py); the dispatch/combine einsums then induce the
  all-to-alls under GSPMD. No hand-written collectives.
- **Load-balancing aux loss** (Switch Transformer style) and optional router
  z-loss, surfaced through ``loss_fn`` so training actually balances experts.

Router math runs in fp32 regardless of compute dtype.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_moe_params(keys, args, dtype=jnp.float32) -> Params:
    """Stacked expert weights [E, ...] + router [D, E].

    ``keys`` is an iterator of PRNG keys (4 consumed).
    """
    D, I, E = args.hidden_size, args.intermediate_size, args.num_local_experts
    std = 0.02
    res_std = std / (2 * args.num_layers) ** 0.5

    def dense(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dtype)

    return {
        "router": {"weight": dense(next(keys), (D, E), std)},
        "experts": {
            "w_gate": {"weight": dense(next(keys), (E, D, I), std)},
            "w_up": {"weight": dense(next(keys), (E, D, I), std)},
            "w_down": {"weight": dense(next(keys), (E, I, D), res_std)},
        },
    }


def expert_capacity(seq_len: int, num_experts: int, k: int, capacity_factor: float) -> int:
    """Per-sequence slots each expert can accept (static)."""
    c = int(capacity_factor * k * seq_len / num_experts + 0.5)
    return max(1, min(c, seq_len * k))


def _dispatch_combine(
    probs: jnp.ndarray, k: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Build dispatch/combine tensors from router probabilities.

    probs [B, S, E] fp32 → dispatch [B, S, E, C] in {0,1},
    combine [B, S, E, C] carrying renormalized top-k gate weights.
    Tokens beyond an expert's capacity are dropped (their combine weight is
    zero, so the residual path carries them — standard Switch behavior).
    """
    B, S, E = probs.shape
    gate_w, gate_idx = jax.lax.top_k(probs, k)  # [B, S, K]
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)

    # Slot-flatten [S, K] -> S*K in token-major order so earlier tokens win
    # capacity; one-hot over experts per selection.
    oh = jax.nn.one_hot(gate_idx, E, dtype=probs.dtype)  # [B, S, K, E]
    ohf = oh.reshape(B, S * k, E)
    # Position of each selection within its expert's queue.
    pos = jnp.cumsum(ohf, axis=1) - ohf  # [B, S*K, E]
    pos_in_expert = (pos * ohf).sum(-1)  # [B, S*K]
    keep = ((pos_in_expert < capacity) & (ohf.sum(-1) > 0)).astype(probs.dtype)
    pos_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity, dtype=probs.dtype)
    # [B, S*K, E, C]
    dispatch_f = ohf[..., None] * pos_oh[:, :, None, :] * keep[..., None, None]
    combine_f = dispatch_f * gate_w.reshape(B, S * k)[..., None, None]
    dispatch = dispatch_f.reshape(B, S, k, E, capacity).sum(2)
    combine = combine_f.reshape(B, S, k, E, capacity).sum(2)
    return dispatch, combine


def load_balancing_loss(probs: jnp.ndarray, gate_idx_top1: jnp.ndarray, num_experts: int) -> jnp.ndarray:
    """Switch Transformer aux loss: E * Σ_e f_e · P_e where f_e is the
    fraction of tokens whose top-1 choice is e and P_e the mean router prob."""
    f = jnp.mean(jax.nn.one_hot(gate_idx_top1, num_experts, dtype=jnp.float32), axis=(0, 1))
    p = jnp.mean(probs, axis=(0, 1))
    return num_experts * jnp.sum(f * p)


def router_z_loss(router_logits: jnp.ndarray) -> jnp.ndarray:
    """Mean squared logsumexp of router logits (stabilizes router scale)."""
    z = jax.nn.logsumexp(router_logits, axis=-1)
    return jnp.mean(z * z)


def moe_block(p: Params, x: jnp.ndarray, args) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [B, S, D] → (out [B, S, D], aux_loss scalar fp32).

    Dense einsum pipeline: dispatch → per-expert SwiGLU → combine. The expert
    dim E leads every expert tensor so sharding over ``ep`` partitions both
    weights and expert compute.

    Tokens are routed in fixed-size groups of ``moe_group_size`` (GShard-style)
    so capacity — and with it the [G, g*K, E, C] dispatch tensors — stays
    constant as sequence length grows: memory is O(S), not O(S²).

    The returned aux term is **fully pre-scaled**: ``moe_aux_weight *
    load_balance + router_z_weight * z_loss``; callers add it to the CE loss
    unweighted.
    """
    B, S, D = x.shape
    E, K = args.num_local_experts, args.num_experts_per_tok

    g = min(int(getattr(args, "moe_group_size", 256) or 256), S)
    # Pad S up to a multiple of g so capacity stays O(group), never O(S).
    # Pad tokens route like real ones but their combine output is sliced off;
    # they can steal a little tail-group capacity, which is standard.
    S_pad = ((S + g - 1) // g) * g
    if S_pad != S:
        x_in = jnp.pad(x, ((0, 0), (0, S_pad - S), (0, 0)))
    else:
        x_in = x
    xg = x_in.reshape(B * (S_pad // g), g, D)
    C = expert_capacity(g, E, K, getattr(args, "moe_capacity_factor", 1.25))

    router_logits = xg.astype(jnp.float32) @ p["router"]["weight"].astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)  # [G, g, E] fp32
    dispatch, combine = _dispatch_combine(probs, K, C)
    dispatch = dispatch.astype(x.dtype)

    # [G,g,E,C] x [G,g,D] -> [E,G,C,D]: the all-to-all under ep sharding.
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, xg)
    wg_ = p["experts"]["w_gate"]["weight"]
    wu = p["experts"]["w_up"]["weight"]
    wd = p["experts"]["w_down"]["weight"]
    h = jax.nn.silu(jnp.einsum("ebcd,edi->ebci", expert_in, wg_)) * jnp.einsum(
        "ebcd,edi->ebci", expert_in, wu
    )
    expert_out = jnp.einsum("ebci,eid->ebcd", h, wd)
    out = jnp.einsum("bsec,ebcd->bsd", combine.astype(x.dtype), expert_out)
    out = out.reshape(B, S_pad, D)[:, :S]

    aw = float(getattr(args, "moe_aux_weight", 0.0) or 0.0)
    zw = float(getattr(args, "router_z_weight", 0.0) or 0.0)
    aux = jnp.zeros((), jnp.float32)
    if aw:
        aux = aux + aw * load_balancing_loss(probs, jnp.argmax(router_logits, axis=-1), E)
    if zw:
        aux = aux + zw * router_z_loss(router_logits)
    return out, aux
