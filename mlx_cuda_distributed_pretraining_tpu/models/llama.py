"""Pure-pytree Llama decoder.

Capability parity with the reference model (reference: models/llama.py:
ModelArgs :17-41, RMSNorm :44-56, RoPE :59-139, MLP :141-160, attention
dispatch :181-209, TransformerBlock :298-319, Model :322-477) designed
TPU-first:

- params are a nested dict of ``jnp.ndarray`` (no module framework) so
  sharding rules, optimizer partitions and checkpoints address leaves by
  path;
- ``forward`` is a pure function — jit/grad/shard_map compose directly;
- attention dispatch simple/flash/flex selects the Pallas kernel at trace
  time; masks/score-mods are traceable index functions (ops/masks.py);
- canonical SwiGLU (``silu(gate) * up``) instead of the reference's
  nonstandard ``gate * sigmoid(up) * 2`` (models/llama.py:151) — documented
  behavioral divergence (SURVEY.md §7.3);
- RMSNorm computes in fp32 regardless of compute dtype; logits are fp32.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..ops import fused_ce
from ..ops import masks as masks_lib
from ..ops.attention import reference_attention

Params = Dict[str, Any]

# -- named remat policies ----------------------------------------------------
# Activation sites are tagged with jax.ad_checkpoint.checkpoint_name so a
# policy trades exactly the FLOPs we choose instead of blanket replay:
#   "qkv"      — q/k/v projections (pre-RoPE)
#   "attn_out" — the attention output (flash/flex/ring/reference), pre-wo
#   "ffn_up"   — silu(gate) * up, the SwiGLU elementwise product
#   "ffn_down" — the MLP down-projection output
# REMAT_POLICIES maps model.remat_policy names to what the backward pass
# may keep; anything unnamed is recomputed.
SAVE_ATTN_NAMES = ("qkv", "attn_out")
REMAT_POLICIES = ("none", "dots", "full", "save_attn")


def normalize_remat(remat: Optional[str]) -> Optional[str]:
    """"none"/"" → None; unknown names raise (a typo'd policy must not
    silently train without remat)."""
    if remat is None or remat == "":
        return None
    name = str(remat).lower()
    if name == "none":
        return None
    if name not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {remat!r} (expected one of "
            f"{REMAT_POLICIES})")
    return name


def remat_wrap(remat: Optional[str]):
    """Per-layer ``jax.checkpoint`` wrapper for a named policy, or None.

    - "full": replay everything (minimum memory, maximum recompute);
    - "dots": keep matmul outputs (checkpoint_dots_with_no_batch_dims);
    - "save_attn": keep only the tagged attention activations (qkv +
      attention output) — the backward never replays the O(S²) attention
      kernel, only the cheap FFN/elementwise work.
    """
    remat = normalize_remat(remat)
    if remat is None:
        return None
    if remat == "full":
        return partial(jax.checkpoint, static_argnums=(2, 5, 6))
    if remat == "dots":
        return partial(
            jax.checkpoint,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            static_argnums=(2, 5, 6))
    return partial(
        jax.checkpoint,
        policy=jax.checkpoint_policies.save_only_these_names(
            *SAVE_ATTN_NAMES),
        static_argnums=(2, 5, 6))


def remat_checkpoint_for_overlap(remat: Optional[str]):
    """``jax.checkpoint`` wrapper for the overlap path's per-layer
    ``(param_shards, x, *consts)`` function — same named policies as
    :func:`remat_wrap` but no static_argnums (the static config is closed
    over), so the checkpoint encloses the param gather and the backward
    re-gathers shards instead of keeping full per-layer params alive."""
    remat = normalize_remat(remat)
    if remat is None:
        return None
    if remat == "full":
        return jax.checkpoint
    if remat == "dots":
        return partial(
            jax.checkpoint,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)
    return partial(
        jax.checkpoint,
        policy=jax.checkpoint_policies.save_only_these_names(
            *SAVE_ATTN_NAMES))


@dataclass(frozen=True)
class LlamaArgs:
    vocab_size: int = 259
    hidden_size: int = 128
    intermediate_size: int = 256
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: int = 8
    head_dim: int = 16
    max_position_embeddings: int = 1024
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_traditional: bool = False
    rope_scaling_factor: Optional[float] = None
    attention_bias: bool = False
    mlp_bias: bool = False
    tie_word_embeddings: bool = True
    logit_scale: Optional[float] = None
    attention_type: str = "simple"  # simple | flash | flex
    # flex-attention mask program (traceable builders in ops/masks.py)
    mask_type: str = "causal"  # causal | sliding_window | prefix_lm
    window_size: int = 512
    prefix_len: int = 0
    score_mod_type: Optional[str] = None  # None | alibi | soft_cap
    soft_cap: float = 50.0
    # MoE (reference declares these fields but never uses them:
    # models/llama.py:40-41; here they drive a real block — models/moe.py).
    num_local_experts: int = 0
    num_experts_per_tok: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    router_z_weight: float = 0.0
    moe_group_size: int = 256
    # Dispatch implementation: "grouped" (sort-based dropless, grouped
    # GEMMs — ops/grouped_matmul.py) or "einsum" (GShard dispatch tensors,
    # capacity drops — kept as the parity oracle). models/moe.py.
    moe_impl: str = "grouped"
    # Static per-destination send slots for the ep all-to-all, as a
    # fraction of local selections: <= 0 means worst-case (dropless).
    moe_ep_capacity_factor: float = 0.0
    # Opt-in low-precision training matmuls (model.matmul_precision):
    # None/fp32 | bf16 | int8 — threaded into ops/flash_attention.py and
    # ops/grouped_matmul.py (amax/scale-tracked int8 forward, fp backward;
    # loss-parity gated vs bf16 in the test suite).
    matmul_precision: Optional[str] = None

    @property
    def is_moe(self) -> bool:
        return self.num_local_experts > 0 and self.num_experts_per_tok > 0

    @classmethod
    def from_config(cls, model_cfg: Any, vocab_size: int) -> "LlamaArgs":
        att = dict(getattr(model_cfg, "attention", None) or {})
        rope = dict(getattr(model_cfg, "rope", None) or {})
        misc = dict(getattr(model_cfg, "misc", None) or {})
        norm = dict(getattr(model_cfg, "normalization", None) or {})
        moe = dict(getattr(model_cfg, "moe", None) or {})
        scaling = rope.get("scaling") or {}
        scale_factor = scaling.get("factor") if isinstance(scaling, dict) else None
        return cls(
            vocab_size=vocab_size,
            hidden_size=model_cfg.hidden_size,
            intermediate_size=model_cfg.intermediate_size,
            num_layers=model_cfg.num_layers,
            num_heads=model_cfg.num_heads,
            num_kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_dim,
            max_position_embeddings=int(att.get("max_position_embeddings") or 0)
            or 4096,
            rms_norm_eps=float(norm.get("rms_norm_eps", 1e-5)),
            rope_theta=float(rope.get("theta", 10000.0)),
            rope_traditional=bool(rope.get("traditional", False)),
            rope_scaling_factor=float(scale_factor) if scale_factor else None,
            attention_bias=bool(misc.get("attention_bias", False)),
            mlp_bias=bool(misc.get("mlp_bias", False)),
            tie_word_embeddings=bool(misc.get("tie_word_embeddings", True)),
            logit_scale=misc.get("logit_scale"),
            attention_type=model_cfg.attention_type,
            mask_type=str(att.get("mask_type", "causal")),
            window_size=int(att.get("window_size", 512)),
            prefix_len=int(att.get("prefix_len", 0)),
            score_mod_type=att.get("score_mod"),
            soft_cap=float(att.get("soft_cap", 50.0)),
            num_local_experts=int(moe.get("num_local_experts", 0) or 0),
            num_experts_per_tok=int(moe.get("num_experts_per_tok", 0) or 0),
            moe_capacity_factor=float(moe.get("capacity_factor", 1.25) or 1.25),
            moe_aux_weight=float(moe.get("aux_loss_weight", 0.01) or 0.0),
            router_z_weight=float(moe.get("router_z_weight", 0.0) or 0.0),
            moe_group_size=int(moe.get("group_size", 256) or 256),
            moe_impl=str(moe.get("impl", "grouped") or "grouped"),
            moe_ep_capacity_factor=float(moe.get("ep_capacity_factor", 0.0) or 0.0),
            matmul_precision=getattr(model_cfg, "matmul_precision", None),
        )


# -- init -------------------------------------------------------------------
def init_params(rng: jax.Array, args: LlamaArgs, dtype=jnp.float32) -> Params:
    """Initialize parameters: normal(0.02) embeddings/projections, residual
    output projections scaled by 1/sqrt(2*num_layers) (GPT-2 style), ones for
    norms."""
    per_layer = 8 if args.is_moe else 7
    n_streams = per_layer * args.num_layers + 2
    keys = iter(jax.random.split(rng, n_streams))
    std = 0.02
    res_std = std / (2 * args.num_layers) ** 0.5
    D, Dh = args.hidden_size, args.head_dim
    Hq, Hkv, I = args.num_heads, args.num_kv_heads, args.intermediate_size

    def dense(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dtype)

    layers = []
    for _ in range(args.num_layers):
        layer = {
            "attention_norm": {"weight": jnp.ones((D,), dtype)},
            "attention": {
                "wq": {"weight": dense(next(keys), (D, Hq * Dh), std)},
                "wk": {"weight": dense(next(keys), (D, Hkv * Dh), std)},
                "wv": {"weight": dense(next(keys), (D, Hkv * Dh), std)},
                "wo": {"weight": dense(next(keys), (Hq * Dh, D), res_std)},
            },
            "ffn_norm": {"weight": jnp.ones((D,), dtype)},
        }
        if args.is_moe:
            from . import moe as moe_lib

            layer["feed_forward"] = moe_lib.init_moe_params(keys, args, dtype)
        else:
            layer["feed_forward"] = {
                "w_gate": {"weight": dense(next(keys), (D, I), std)},
                "w_up": {"weight": dense(next(keys), (D, I), std)},
                "w_down": {"weight": dense(next(keys), (I, D), res_std)},
            }
        if args.attention_bias:
            for name, fan_out in (("wq", Hq * Dh), ("wk", Hkv * Dh), ("wv", Hkv * Dh), ("wo", D)):
                layer["attention"][name]["bias"] = jnp.zeros((fan_out,), dtype)
        if args.mlp_bias:
            if args.is_moe:
                raise ValueError("mlp_bias is not supported with MoE (experts are bias-free)")
            for name, fan_out in (("w_gate", I), ("w_up", I), ("w_down", D)):
                layer["feed_forward"][name]["bias"] = jnp.zeros((fan_out,), dtype)
        layers.append(layer)

    params: Params = {
        "tok_embeddings": {"weight": dense(next(keys), (args.vocab_size, D), std)},
        "layers": layers,
        "norm": {"weight": jnp.ones((D,), dtype)},
    }
    if not args.tie_word_embeddings:
        params["output"] = {"weight": dense(next(keys), (D, args.vocab_size), std)}
    return params


def num_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


# -- building blocks --------------------------------------------------------
def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """fp32-internal RMSNorm (reference: models/llama.py:44-56)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * weight.astype(jnp.float32)).astype(dtype)


def _linear(x: jnp.ndarray, p: Params) -> jnp.ndarray:
    if "weight_q4" in p:
        # int4 weight-only quantization (models/quantize.py): two values
        # per byte along the contraction dim. The nibble unpack is two
        # arithmetic shifts XLA fuses into the matmul's operand read, and
        # the per-output-channel scale lands in the epilogue — the weight
        # crosses HBM at 0.5 byte/elem, no fp copy is materialized.
        from .quantize import unpack_int4

        w = unpack_int4(p["weight_q4"])
        y = (x @ w.astype(x.dtype)) * p["weight_s"].astype(x.dtype)
    elif "weight_q" in p:
        # int8 weight-only quantization (quantize_params_int8): the
        # per-output-channel scale factors OUT of the contraction, so
        # dequant happens after the matmul on the [.., out] result — the
        # weight crosses HBM at 1 byte/elem.
        y = (x @ p["weight_q"].astype(x.dtype)) * p["weight_s"].astype(x.dtype)
    else:
        y = x @ p["weight"]
    if "bias" in p:
        y = y + p["bias"]
    return y


def quantize_params_int8(params: Params) -> Params:
    """Weight-only int8 quantization for inference (per-output-channel
    symmetric scales on every layer linear: wq/wk/wv/wo, the dense MLP
    and MoE expert banks). Embeddings, the output head, norms, biases
    and MoE routers stay full precision (they set logit quality).
    Composes with the int8 KV cache: weights AND cache both cross HBM
    at 1 byte/elem. Thin wrapper over models/quantize.py, which also
    implements packed int4 and the quantize-on-load checkpoint path.

    The reference has no weight quantization (its only quant surface is
    the optional KV cache quant, core/generation_lite.py:75-89)."""
    from .quantize import quantize_weights

    return quantize_weights(params, "int8")


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, theta: float, scaling_factor: Optional[float] = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for given positions [S] -> [S, head_dim//2], fp32.

    Linear position scaling divides positions by the factor (reference:
    models/llama.py:59-139 supports the same "linear" scaling)."""
    pos = positions.astype(jnp.float32)
    if scaling_factor:
        pos = pos / scaling_factor
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = pos[:, None] * inv_freq[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, traditional: bool = False) -> jnp.ndarray:
    """Rotate [B, S, H, D]. ``traditional`` = interleaved pairs; default =
    half-split (llama) convention."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    if traditional:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        r1 = x1 * c - x2 * s
        r2 = x2 * c + x1 * s
        out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    else:
        half = x.shape[-1] // 2
        x1 = xf[..., :half]
        x2 = xf[..., half:]
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)


def build_mask_mod(args: LlamaArgs) -> masks_lib.MaskMod:
    if args.mask_type == "sliding_window":
        return masks_lib.sliding_window(args.window_size)
    if args.mask_type == "prefix_lm":
        return masks_lib.prefix_lm(args.prefix_len)
    return masks_lib.causal()


def build_score_mod(args: LlamaArgs, head: Optional[int] = None):
    """Score mod for the whole head dim (vectorized over heads where needed)."""
    if args.score_mod_type == "alibi":
        slopes = jnp.asarray(masks_lib.alibi_slopes(args.num_heads), jnp.float32)

        def mod(scores, q_idx, k_idx):
            # scores [B, Hkv, G, Sq, Skv]; recover absolute head index.
            B, Hkv, G = scores.shape[0], scores.shape[1], scores.shape[2]
            head_ids = jnp.arange(Hkv * G).reshape(Hkv, G)
            slope = slopes[head_ids][None, :, :, None, None]
            return scores - slope * jnp.abs(q_idx - k_idx)[None, None, None]

        return mod
    if args.score_mod_type == "soft_cap":
        return lambda s, q, k: args.soft_cap * jnp.tanh(s / args.soft_cap)
    return None


def attention_block(
    p: Params,
    x: jnp.ndarray,
    args: LlamaArgs,
    positions: jnp.ndarray,
    cache: Optional[Dict[str, jnp.ndarray]] = None,
    attn_impl: Optional[str] = None,
    attend_len: Optional[int] = None,
) -> Tuple[jnp.ndarray, Optional[Dict[str, jnp.ndarray]]]:
    """Self-attention with RoPE, GQA and optional KV cache.

    cache = {"k": [B, T, Hkv, Dh], "v": ..., "pos": scalar} with T =
    max_position_embeddings; decode writes at ``pos`` via dynamic slice and
    attends under a positional validity mask. ``attend_len`` (static)
    restricts attention to the first ``attend_len`` cache slots — the
    generation loop passes a power-of-two bucket >= pos+S, so decode cost
    is O(bucket), not O(T) (the reference's per-token decode is O(cache)
    from step 1: core/generation_lite.py:158-175)."""
    B, S, _ = x.shape
    Hq, Hkv, Dh = args.num_heads, args.num_kv_heads, args.head_dim

    q = checkpoint_name(_linear(x, p["wq"]), "qkv").reshape(B, S, Hq, Dh)
    k = checkpoint_name(_linear(x, p["wk"]), "qkv").reshape(B, S, Hkv, Dh)
    v = checkpoint_name(_linear(x, p["wv"]), "qkv").reshape(B, S, Hkv, Dh)

    cos, sin = rope_cos_sin(positions, Dh, args.rope_theta, args.rope_scaling_factor)
    q = apply_rope(q, cos, sin, args.rope_traditional)
    k = apply_rope(k, cos, sin, args.rope_traditional)

    new_cache = None
    if cache is not None and "k_q" in cache:
        # int8-quantized cache (reference: generation_lite.py:75-89 optional
        # KV quantization): per-(position, head) symmetric scales; int8
        # buffers cut decode's HBM cache reads ~4x, dequant fuses into the
        # attention matmul.
        pos = cache["pos"]
        kq, ks = _quantize_kv(k)
        vq, vs = _quantize_kv(v)
        ck_q = jax.lax.dynamic_update_slice(cache["k_q"], kq, (0, pos, 0, 0))
        ck_s = jax.lax.dynamic_update_slice(cache["k_s"], ks, (0, pos, 0, 0))
        cv_q = jax.lax.dynamic_update_slice(cache["v_q"], vq, (0, pos, 0, 0))
        cv_s = jax.lax.dynamic_update_slice(cache["v_s"], vs, (0, pos, 0, 0))
        new_cache = {"k_q": ck_q, "k_s": ck_s, "v_q": cv_q, "v_s": cv_s, "pos": pos + S}
        L = attend_len or ck_q.shape[1]
        k = ck_q[:, :L].astype(jnp.float32) * ck_s[:, :L]
        v = cv_q[:, :L].astype(jnp.float32) * cv_s[:, :L]
        out = _cached_attention(q, k, v, positions, pos, S)
    elif cache is not None:
        pos = cache["pos"]
        ck = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, pos, 0, 0))
        new_cache = {"k": ck, "v": cv, "pos": pos + S}
        L = attend_len or ck.shape[1]
        out = _cached_attention(q, ck[:, :L], cv[:, :L], positions, pos, S)
    else:
        mask_mod = build_mask_mod(args)
        impl = attn_impl or args.attention_type
        if impl == "flash" and args.score_mod_type is None:
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, mask_type=args.mask_type,
                                  window_size=args.window_size,
                                  prefix_len=args.prefix_len,
                                  precision=getattr(args, "matmul_precision",
                                                    None))
        elif impl == "ring":
            # Sequence/context parallelism: exact causal attention with KV
            # shards rotating over the sp mesh axis (ops/ring_attention.py).
            from ..ops.ring_attention import make_ring_attention
            from ..parallel.context import current_mesh

            mesh = current_mesh()
            if mesh is None or "sp" not in mesh.axis_names or mesh.shape["sp"] == 1:
                out = reference_attention(q, k, v, mask_mod=mask_mod)
            else:
                out = make_ring_attention(mesh, mask_mod=mask_mod)(q, k, v)
        elif impl == "flex":
            from ..ops.flex_attention import flex_attention, kernel_score_mod

            out = flex_attention(
                q, k, v, mask_mod=mask_mod,
                score_mod=kernel_score_mod(args.score_mod_type, args.num_heads, args.soft_cap),
            )
        else:
            out = reference_attention(q, k, v, mask_mod=mask_mod, score_mod=build_score_mod(args))

    out = checkpoint_name(out.reshape(B, S, Hq * Dh), "attn_out")
    return _linear(out, p["wo"]), new_cache


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 per-(batch, position, head) quantization of [B, S, H, D]
    → (int8 values, fp32 scales [B, S, H, 1])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _cached_attention(q, k, v, positions, pos, S):
    """Decode attention over a full static cache buffer under a positional
    validity mask (keys at or before each query, and already written)."""
    T = k.shape[1]
    k_idx = jnp.arange(T, dtype=jnp.int32)
    explicit = (k_idx[None, :] <= positions[:, None]) & (k_idx[None, :] < pos + S)
    return reference_attention(q, k, v, explicit_mask=explicit)


def mlp_block(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Canonical SwiGLU: ``down(silu(gate(x)) * up(x))``."""
    up = checkpoint_name(
        jax.nn.silu(_linear(x, p["w_gate"])) * _linear(x, p["w_up"]), "ffn_up")
    return checkpoint_name(_linear(up, p["w_down"]), "ffn_down")


def transformer_block(
    p: Params,
    x: jnp.ndarray,
    args: LlamaArgs,
    positions: jnp.ndarray,
    cache: Optional[Dict[str, jnp.ndarray]] = None,
    attn_impl: Optional[str] = None,
    attend_len: Optional[int] = None,
) -> Tuple[jnp.ndarray, Optional[Dict[str, jnp.ndarray]], jnp.ndarray]:
    """Pre-norm residual block (reference: models/llama.py:298-319).

    Returns ``(x, new_cache, aux_loss)`` — aux is the MoE load-balancing
    loss (0 for dense layers). When a routing-stats tap is active
    (models/moe.py — training with an MoE model), a fourth element carries
    this layer's routing stats: the stats are re-emitted as RETURN VALUES
    here, inside any ``jax.checkpoint`` wrapping this block, so they cross
    the remat/scan boundary instead of leaking out of its trace."""
    h, new_cache = attention_block(
        p["attention"], rms_norm(x, p["attention_norm"]["weight"], args.rms_norm_eps),
        args, positions, cache, attn_impl, attend_len,
    )
    x = x + h
    normed = rms_norm(x, p["ffn_norm"]["weight"], args.rms_norm_eps)
    if args.is_moe:
        from . import moe as moe_lib

        if moe_lib.stats_tap_active():
            with moe_lib.routing_stats_tap() as tap:
                ff, aux = moe_lib.moe_block(p["feed_forward"], normed, args)
            x = x + ff
            return x, new_cache, aux, moe_lib.merge_stats(
                tap, args.num_local_experts)
        ff, aux = moe_lib.moe_block(p["feed_forward"], normed, args)
    else:
        ff = mlp_block(p["feed_forward"], normed)
        aux = jnp.zeros((), jnp.float32)
    x = x + ff
    return x, new_cache, aux


# -- full model -------------------------------------------------------------
def forward(
    params: Params,
    tokens: jnp.ndarray,
    args: LlamaArgs,
    cache: Optional[list] = None,
    start_pos: Any = 0,
    compute_dtype: jnp.dtype = jnp.float32,
    remat: Optional[str] = None,
    remat_ratio: float = 1.0,
    return_aux: bool = False,
    attend_len: Optional[int] = None,
    return_hidden: bool = False,
    scan_layers: bool = False,
    overlap: bool = False,
) -> Tuple[jnp.ndarray, Optional[list]]:
    """tokens [B, S] int32 → (logits [B, S, V] fp32, new_cache | None).

    ``remat``: None | "none" | "full" | "dots" | "save_attn" — per-layer
    ``jax.checkpoint`` with the named policy (see :data:`REMAT_POLICIES`);
    ``remat_ratio`` checkpoints only the first fraction
    of layers (reference: system.gradient_checkpointing_ratio).
    ``return_aux=True`` appends the summed MoE aux loss:
    ``(logits, cache, aux)``. ``attend_len`` (static) bounds cached decode
    attention to a bucket of the cache — see :func:`attention_block`.
    ``return_hidden=True`` skips the output projection and returns the
    final normed hidden states [B, S, D] in compute dtype instead of
    logits (the fused-CE loss folds the projection into the loss —
    ops/fused_ce.py).
    ``scan_layers=True`` runs the (uniform) layer stack as one
    ``lax.scan`` body over in-jit-stacked params instead of a Python
    loop: XLA traces/compiles ONE layer instead of num_layers copies,
    cutting program size and (remote-)compile wall time ~num_layers x at
    the 400M-1B scales; the stack itself is one extra pass over the
    already-casted params, negligible next to a training step. Training
    path only (ignored under KV cache). ``remat_ratio < 1`` runs as TWO
    scans — the checkpointed prefix and the plain suffix.
    ``overlap=True`` routes the layer stack through the manual
    shard_map overlap schedule (parallel/overlap.py: per-layer bucketed
    fsdp param all-gather prefetched one layer ahead, gradient
    reduce-scatter draining per layer behind the backward) when the
    current mesh qualifies (pure dp×fsdp, dense, no int8); otherwise
    this flag is a no-op and GSPMD schedules the collectives.
    """
    B, S = tokens.shape
    x = params["tok_embeddings"]["weight"].astype(compute_dtype)[tokens]
    positions = jnp.arange(S, dtype=jnp.int32) + start_pos

    remat = normalize_remat(remat)
    wrap = remat_wrap(remat)
    block = wrap(transformer_block) if wrap is not None else transformer_block

    # int8 (quantized) leaves must stay int8 through the compute-dtype cast
    cast = partial(jax.tree_util.tree_map,
                   lambda a: a if a.dtype == jnp.int8 else a.astype(compute_dtype))
    new_cache = [] if cache is not None else None
    n_remat = int(round(args.num_layers * remat_ratio))
    aux_total = jnp.zeros((), jnp.float32)
    if args.is_moe:
        from . import moe as moe_lib
        collect_stats = moe_lib.stats_tap_active()
    else:
        collect_stats = False
    stats_total = moe_lib.zero_stats(args.num_local_experts) if collect_stats else None
    use_overlap = False
    if overlap and cache is None and not args.is_moe and not collect_stats:
        from ..parallel import overlap as overlap_lib
        from ..parallel.context import current_mesh

        overlap_mesh = current_mesh()
        layers_cast = [cast(l) for l in params["layers"]]
        use_overlap = overlap_lib.can_overlap(overlap_mesh, layers_cast, B)
    if use_overlap:
        # Manual overlap schedule (parallel/overlap.py): one bucketed
        # all-gather per layer over the fsdp axis, prefetched one layer
        # ahead on the non-checkpointed segment; the gather's transpose
        # drains the gradient reduce-scatter per layer in the backward.
        def overlap_body(layer, h, pos):
            h, _, aux = transformer_block(
                layer, h, args, pos, None, None, attend_len)
            return h, aux

        policy_wrap = None
        if wrap is not None:
            # Re-wrap WITHOUT static_argnums: overlap closes over the
            # static config and checkpoints (gather ∘ block) together so
            # the backward re-gathers shards instead of saving full
            # per-layer params as residuals.
            policy_wrap = remat_checkpoint_for_overlap(remat)
        x, aux = overlap_lib.overlapped_layer_scan(
            overlap_body, x, layers_cast, overlap_mesh,
            consts=(positions,), wrap=policy_wrap,
            n_wrapped=(n_remat if remat else 0),
        )
        aux_total = aux_total + aux
    elif scan_layers and cache is None:
        # Segmented scan: the checkpointed prefix (remat_ratio) and the
        # plain suffix each scan over their own stacked params — at most
        # two compiled layer bodies, any ratio.
        layers = [cast(l) for l in params["layers"]]
        segments = ([(layers[:n_remat], block),
                     (layers[n_remat:], transformer_block)]
                    if remat else [(layers, transformer_block)])
        for seg, blk in segments:
            if not seg:
                continue
            stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *seg)

            def body(h, layer, blk=blk):
                # transformer_block grows a stats element under an active
                # tap; routing it through the scan ys keeps the traced
                # stats inside the scan body's trace.
                out = blk(layer, h, args, positions, None, None, attend_len)
                if collect_stats:
                    h, _, aux, stats = out
                    return h, (aux, stats)
                h, _, aux = out
                return h, aux

            x, ys = jax.lax.scan(body, x, stacked)
            if collect_stats:
                auxs, stats = ys
                stats_total = {k: stats_total[k] + stats[k].sum(axis=0)
                               for k in stats_total}
            else:
                auxs = ys
            aux_total = aux_total + auxs.sum()
    else:
        for i, layer in enumerate(params["layers"]):
            blk = block if (remat and i < n_remat) else transformer_block
            layer_cache = cache[i] if cache is not None else None
            out = blk(cast(layer), x, args, positions, layer_cache, None,
                      attend_len)
            if collect_stats:
                x, c, aux, stats = out
                stats_total = {k: stats_total[k] + stats[k] for k in stats_total}
            else:
                x, c, aux = out
            aux_total = aux_total + aux
            if new_cache is not None:
                new_cache.append(c)
    if collect_stats:
        moe_lib.record_stats(stats_total)

    x = rms_norm(x, params["norm"]["weight"], args.rms_norm_eps)
    if return_hidden:
        if return_aux:
            return x, new_cache, aux_total
        return x, new_cache
    # Output projection accumulates in fp32 (preferred_element_type) so the
    # logits never round through bf16 — bit-identical to the fused-CE path
    # (ops/fused_ce.py) under any compute dtype.
    if args.tie_word_embeddings or "output" not in params:
        logits = jax.lax.dot_general(
            x, params["tok_embeddings"]["weight"].astype(compute_dtype),
            (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
    else:
        logits = jax.lax.dot_general(
            x, params["output"]["weight"].astype(compute_dtype),
            (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        if "bias" in params["output"]:
            # Raw fp32 bias (not rounded through compute_dtype) — keeps this
            # path bit-identical to fused_cross_entropy's bias handling.
            logits = logits + params["output"]["bias"].astype(jnp.float32)
    if args.logit_scale:
        logits = logits * args.logit_scale
    if return_aux:
        return logits, new_cache, aux_total
    return logits, new_cache


def init_cache(
    args: LlamaArgs,
    batch_size: int,
    max_len: Optional[int] = None,
    dtype=jnp.float32,
    quantize: bool = False,
) -> list:
    """KV cache buffers. ``quantize=True`` allocates int8 value buffers plus
    per-(position, head) fp32 scales (reference: generation_lite.py:75-89's
    optional KV-cache quantization, here int8-symmetric)."""
    T = max_len or args.max_position_embeddings
    B, H, D = batch_size, args.num_kv_heads, args.head_dim
    if quantize:
        return [
            {
                "k_q": jnp.zeros((B, T, H, D), jnp.int8),
                "k_s": jnp.zeros((B, T, H, 1), jnp.float32),
                "v_q": jnp.zeros((B, T, H, D), jnp.int8),
                "v_s": jnp.zeros((B, T, H, 1), jnp.float32),
                "pos": jnp.asarray(0, jnp.int32),
            }
            for _ in range(args.num_layers)
        ]
    return [
        {
            "k": jnp.zeros((B, T, H, D), dtype),
            "v": jnp.zeros((B, T, H, D), dtype),
            "pos": jnp.asarray(0, jnp.int32),
        }
        for _ in range(args.num_layers)
    ]


def init_paged_cache(
    args: LlamaArgs,
    num_blocks: int,
    block_size: int,
    dtype=jnp.float32,
    quantize: bool = False,
) -> list:
    """Paged KV arena (vLLM-style): per layer a global pool of fixed-size
    blocks ``[num_blocks, block_size, Hkv, Dh]`` addressed through per-
    sequence block tables instead of a per-sequence row. Same value layout
    as :func:`init_cache` (fp buffers, or the int8 quartet with per-
    (position, head) scales) — only the leading dims change, so the
    quantize/dequantize path is shared. No ``pos``: positions are
    per-sequence host state in the serving pool."""
    N, T, H, D = num_blocks, block_size, args.num_kv_heads, args.head_dim
    if quantize:
        return [
            {
                "k_q": jnp.zeros((N, T, H, D), jnp.int8),
                "k_s": jnp.zeros((N, T, H, 1), jnp.float32),
                "v_q": jnp.zeros((N, T, H, D), jnp.int8),
                "v_s": jnp.zeros((N, T, H, 1), jnp.float32),
            }
            for _ in range(args.num_layers)
        ]
    return [
        {
            "k": jnp.zeros((N, T, H, D), dtype),
            "v": jnp.zeros((N, T, H, D), dtype),
        }
        for _ in range(args.num_layers)
    ]


def loss_fn(
    params: Params,
    batch: Dict[str, jnp.ndarray],
    args: LlamaArgs,
    compute_dtype: jnp.dtype = jnp.float32,
    remat: Optional[str] = None,
    remat_ratio: float = 1.0,
    include_aux: bool = True,
    ce_chunk: int = -1,
    scan_layers: bool = False,
    z_loss_weight: float = 0.0,
    with_moe_stats: bool = False,
    overlap: bool = False,
) -> Tuple[jnp.ndarray, Any]:
    """Masked mean cross-entropy in fp32 (reference: core/training.py
    compute_loss :1195-1260). Returns (loss, token_count). MoE models add
    the pre-scaled router aux losses when ``include_aux`` (training); eval
    passes ``include_aux=False`` so val loss/ppl stay pure LM cross-entropy,
    comparable with dense baselines.

    ``ce_chunk``: rows per fused-CE chunk (ops/fused_ce.py — folds the
    output projection into a chunked loss, never materializing [B,S,V]
    logits). 0 disables; -1 (default) auto-enables when the logits tensor
    would be HBM-significant. Both paths run the projection with fp32
    accumulation and reduce in fp32, so toggling ce_chunk changes memory
    behavior only, not the computed loss.

    ``with_moe_stats=True`` (MoE training step) opens a routing-stats tap
    around the forward pass and returns ``(loss, (token_count, stats))``
    where stats is the layer-summed dict from models/moe.py — the shape
    ``value_and_grad(has_aux=True)`` needs to carry traced routing stats
    out of the differentiated region."""
    if with_moe_stats and args.is_moe:
        from . import moe as moe_lib

        with moe_lib.routing_stats_tap() as tap:
            loss, count = loss_fn(
                params, batch, args, compute_dtype=compute_dtype,
                remat=remat, remat_ratio=remat_ratio, include_aux=include_aux,
                ce_chunk=ce_chunk, scan_layers=scan_layers,
                z_loss_weight=z_loss_weight, overlap=overlap,
            )
        return loss, (count, moe_lib.merge_stats(tap, args.num_local_experts))
    targets = batch["targets"]
    mask = batch["mask"].astype(jnp.float32)
    count = jnp.maximum(mask.sum(), 1.0)

    B, S = batch["inputs"].shape
    if ce_chunk < 0:
        ce_chunk = fused_ce.auto_chunk(B, S, args.vocab_size)
    untied = not args.tie_word_embeddings and "output" in params
    if ce_chunk > 0:
        hidden, _, aux = forward(
            params, batch["inputs"], args, compute_dtype=compute_dtype,
            remat=remat, remat_ratio=remat_ratio, return_aux=True,
            return_hidden=True, scan_layers=scan_layers, overlap=overlap,
        )
        if untied:
            w_vd = params["output"]["weight"].astype(compute_dtype).T
            bias = params["output"].get("bias")
        else:
            w_vd = params["tok_embeddings"]["weight"].astype(compute_dtype)
            bias = None
        from ..parallel.context import current_mesh

        mesh = current_mesh()
        want_z = z_loss_weight > 0.0
        if (mesh is not None and mesh.shape.get("sp", 1) > 1
                and mesh.shape.get("tp", 1) == 1):
            # Sequence-sharded: shard_map keeps the chunked CE local to
            # each sp shard (ops/fused_ce.py::fused_cross_entropy_sp).
            out = fused_ce.fused_cross_entropy_sp(
                hidden, w_vd, targets, mask, mesh, bias_v=bias,
                logit_scale=args.logit_scale, chunk=ce_chunk, with_z=want_z,
            )
        else:
            out = fused_ce.fused_cross_entropy(
                hidden, w_vd, targets, mask, bias_v=bias,
                logit_scale=args.logit_scale, chunk=ce_chunk, with_z=want_z,
            )
        if want_z:
            nll_sum, z_sum = out
            loss = nll_sum / count + z_loss_weight * z_sum / count
        else:
            loss = out / count
    else:
        logits, _, aux = forward(
            params, batch["inputs"], args, compute_dtype=compute_dtype,
            remat=remat, remat_ratio=remat_ratio, return_aux=True,
            scan_layers=scan_layers, overlap=overlap,
        )
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        nll = (logz - gold) * mask
        loss = nll.sum() / count
        if z_loss_weight > 0.0:
            loss = loss + z_loss_weight * jnp.sum(jnp.square(logz) * mask) / count
    if args.is_moe and include_aux:
        loss = loss + aux  # pre-scaled inside moe_block
    return loss, mask.sum()
