from .llama import LlamaArgs, init_params, forward
from .registry import resolve_architecture

__all__ = ["LlamaArgs", "init_params", "forward", "resolve_architecture"]
