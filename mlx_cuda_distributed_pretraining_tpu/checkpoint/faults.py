"""Deterministic fault injection for checkpoint IO.

Every checkpoint artifact (safetensors files, JSON sidecars, manifests,
the metadata ledger) is written temp-file-then-rename, and every one of
those renames funnels through :func:`commit_write` below. That single
choke point lets tests — and manual chaos drills — make a *specific*
write fail in a *specific* way without monkeypatching internals:

    from mlx_cuda_distributed_pretraining_tpu.checkpoint import faults

    with faults.active("model", "enospc", match="step_20"):
        trainer.save_checkpoint(20)          # raises ENOSPC

Injection points are derived from the artifact filename, so callers
never thread point names through the IO layer:

    ``model``      step_<N>_model.safetensors
    ``optimizer``  step_<N>_optimizer.safetensors
    ``state``      step_<N>_state.json
    ``manifest``   step_<N>.manifest.json
    ``ledger``     metadata.json
    ``sidecar``    step_<N>_data_p<P>.json
    ``other``      anything else routed through the atomic writers

Modes:

    ``enospc``    remove the temp file and raise OSError(ENOSPC) — the
                  write never lands (a full disk / failed background write)
    ``truncate``  chop ``truncate_bytes`` off the temp file, then rename —
                  the final file is torn relative to what the writer (and
                  the step manifest) believe was written
    ``drop``      remove the temp file and report success — the artifact
                  silently never exists (lost page cache, vanished rename)
    ``block``     wait on ``event`` before committing — deterministic
                  back-pressure / in-flight-write tests

With no rules installed (production), :func:`commit_write` is a plain
``os.replace``.
"""

from __future__ import annotations

import contextlib
import errno
import os
import threading
from typing import List, Optional

MODES = ("enospc", "truncate", "drop", "block")


def point_for(path: str) -> str:
    """Derive the injection-point name from an artifact path."""
    name = os.path.basename(path)
    if name.endswith("_model.safetensors"):
        return "model"
    if name.endswith("_optimizer.safetensors"):
        return "optimizer"
    if name.endswith("_state.json"):
        return "state"
    if name.endswith(".manifest.json"):
        return "manifest"
    if name == "metadata.json":
        return "ledger"
    if "_data_p" in name and name.endswith(".json"):
        return "sidecar"
    return "other"


class Rule:
    """One armed fault: fires on writes whose point (and optional path
    substring) match, at most ``times`` times (None = unlimited)."""

    def __init__(
        self,
        point: str,
        mode: str,
        match: Optional[str] = None,
        times: Optional[int] = 1,
        truncate_bytes: int = 64,
        event: Optional[threading.Event] = None,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} (expected one of {MODES})")
        if mode == "block" and event is None:
            raise ValueError("mode='block' requires an event")
        self.point = point
        self.mode = mode
        self.match = match
        self.times = times
        self.truncate_bytes = truncate_bytes
        self.event = event
        self.hits = 0

    def _applies(self, point: str, path: str) -> bool:
        if self.point != point:
            return False
        if self.times is not None and self.hits >= self.times:
            return False
        return self.match is None or self.match in path

    def __repr__(self) -> str:  # shows up in test failures — keep it useful
        return (f"Rule({self.point!r}, {self.mode!r}, match={self.match!r}, "
                f"times={self.times}, hits={self.hits})")


_rules: List[Rule] = []
_lock = threading.Lock()


def inject(
    point: str,
    mode: str,
    *,
    match: Optional[str] = None,
    times: Optional[int] = 1,
    truncate_bytes: int = 64,
    event: Optional[threading.Event] = None,
) -> Rule:
    """Arm a fault rule. Returns the rule so tests can assert ``hits``."""
    rule = Rule(point, mode, match=match, times=times,
                truncate_bytes=truncate_bytes, event=event)
    with _lock:
        _rules.append(rule)
    return rule


def reset() -> None:
    """Disarm every rule (tests call this in teardown)."""
    with _lock:
        _rules.clear()


@contextlib.contextmanager
def active(point: str, mode: str, **kwargs):
    """Context-managed :func:`inject` that disarms only its own rule."""
    rule = inject(point, mode, **kwargs)
    try:
        yield rule
    finally:
        with _lock:
            if rule in _rules:
                _rules.remove(rule)


def _take(point: str, path: str) -> Optional[Rule]:
    with _lock:
        for rule in _rules:
            if rule._applies(point, path):
                rule.hits += 1
                return rule
    return None


def commit_write(tmp: str, path: str) -> None:
    """Commit ``tmp`` to ``path`` (atomic rename), honoring armed faults.

    This is the only way checkpoint artifacts reach their final name;
    both the safetensors writer and the atomic-JSON writer call it.
    """
    rule = _take(point_for(path), path)
    if rule is None:
        os.replace(tmp, path)
        return
    if rule.mode == "block":
        rule.event.wait()
        os.replace(tmp, path)
        return
    if rule.mode == "truncate":
        size = os.path.getsize(tmp)
        with open(tmp, "r+b") as f:
            f.truncate(max(0, size - rule.truncate_bytes))
        os.replace(tmp, path)
        return
    if rule.mode == "drop":
        os.unlink(tmp)
        return
    # enospc
    os.unlink(tmp)
    raise OSError(errno.ENOSPC, os.strerror(errno.ENOSPC), path)
