"""Pure-Python safetensors reader/writer.

The reference relies on ``mx.save_safetensors`` (reference:
core/training.py:1351); here the format is implemented directly so
checkpoints interoperate with the safetensors ecosystem (HF, mlx-lm) with no
native dependency. Format: ``u64le header_len | header JSON | raw tensor
bytes``; each header entry maps name -> {dtype, shape, data_offsets}.

bfloat16 is supported via ``ml_dtypes`` (ships with jaxlib).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, Optional, Tuple

import ml_dtypes
import numpy as np

from .faults import commit_write

_DTYPE_TO_ST = {
    np.dtype(np.float64): "F64",
    np.dtype(np.float32): "F32",
    np.dtype(np.float16): "F16",
    np.dtype(ml_dtypes.bfloat16): "BF16",
    np.dtype(np.int64): "I64",
    np.dtype(np.int32): "I32",
    np.dtype(np.int16): "I16",
    np.dtype(np.int8): "I8",
    np.dtype(np.uint8): "U8",
    np.dtype(np.uint16): "U16",
    np.dtype(np.uint32): "U32",
    np.dtype(np.uint64): "U64",
    np.dtype(np.bool_): "BOOL",
    np.dtype(ml_dtypes.float8_e4m3fn): "F8_E4M3",
    np.dtype(ml_dtypes.float8_e5m2): "F8_E5M2",
}
_ST_TO_DTYPE = {v: k for k, v in _DTYPE_TO_ST.items()}


def save_safetensors(
    path: str,
    tensors: Dict[str, np.ndarray],
    metadata: Optional[Dict[str, str]] = None,
) -> Tuple[int, int]:
    """Write ``tensors`` (flat dict of numpy arrays) to ``path``.

    Returns ``(nbytes, crc32)`` of the full file content, computed while
    the bytes stream out — the step manifest records what the writer
    *intended* to put on disk, so a torn/dropped write shows up as a
    mismatch on verify instead of being checksummed as-is."""
    header: Dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v) for k, v in metadata.items()}

    blobs = []
    offset = 0
    for name in sorted(tensors):
        # NOT ascontiguousarray: it silently promotes 0-d scalars to shape
        # (1,); ``tobytes()`` below C-orders non-contiguous views anyway.
        arr = np.asarray(tensors[name])
        st_dtype = _DTYPE_TO_ST.get(arr.dtype)
        if st_dtype is None:
            raise ValueError(f"unsupported dtype {arr.dtype} for tensor {name!r}")
        data = arr.tobytes()
        header[name] = {
            "dtype": st_dtype,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(data)],
        }
        blobs.append(data)
        offset += len(data)

    header_bytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    # Pad header to 8-byte alignment (spec allows trailing spaces).
    pad = (8 - len(header_bytes) % 8) % 8
    header_bytes += b" " * pad

    # Write-to-temp then atomic rename: an interrupted write (crash, killed
    # background checkpoint thread) must never shadow a good checkpoint
    # with a truncated file. The rename goes through the fault-injection
    # choke point (faults.commit_write — a plain os.replace in production).
    tmp = path + ".tmp"
    nbytes = 0
    crc = 0
    with open(tmp, "wb") as f:
        for chunk in (struct.pack("<Q", len(header_bytes)), header_bytes, *blobs):
            f.write(chunk)
            crc = zlib.crc32(chunk, crc)
            nbytes += len(chunk)
    commit_write(tmp, path)
    return nbytes, crc


def load_safetensors(path: str) -> Tuple[Dict[str, np.ndarray], Dict[str, str]]:
    """Read ``path`` → (tensors dict, metadata dict)."""
    with open(path, "rb") as f:
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len).decode("utf-8"))
        body = f.read()

    metadata = header.pop("__metadata__", {}) or {}
    tensors: Dict[str, np.ndarray] = {}
    for name, info in header.items():
        dtype = _ST_TO_DTYPE.get(info["dtype"])
        if dtype is None:
            raise ValueError(f"unsupported safetensors dtype {info['dtype']!r}")
        begin, end = info["data_offsets"]
        arr = np.frombuffer(body[begin:end], dtype=dtype)
        tensors[name] = arr.reshape(info["shape"]).copy()
    return tensors, metadata
