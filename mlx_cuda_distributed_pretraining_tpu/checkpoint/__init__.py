from .safetensors_io import load_safetensors, save_safetensors
from .manager import CheckpointManager

__all__ = ["load_safetensors", "save_safetensors", "CheckpointManager"]
