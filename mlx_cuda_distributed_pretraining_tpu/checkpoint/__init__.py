from .safetensors_io import load_safetensors, save_safetensors
from .manager import (
    CheckpointIntegrityError,
    CheckpointManager,
    StaleBackgroundWriteError,
)
from . import faults

__all__ = [
    "load_safetensors",
    "save_safetensors",
    "CheckpointManager",
    "CheckpointIntegrityError",
    "StaleBackgroundWriteError",
    "faults",
]
